#!/usr/bin/env python
"""Isolated microbench: host-seam fold vs device scatter fold, and
sequential vs batched window scoring, at serve shapes.

The end-to-end serve capture on this 2-core box carries a documented
±35% noise floor (docs/BENCHMARKS.md), so the device-resident-state
win (PR 8) is PROVEN here, where each leg isolates exactly the work
the residency change removes:

  - ``fold``: one retired lane dispatch's delta fold per lane bucket.
    The host seam applies L per-lane ``state + delta`` adds through the
    get_state/set_state seam — L interpreter iterations, 2L fresh
    array allocations; the pool path is ONE vectorized scatter-add
    into the tenant pool (the numpy engine on this CPU backend: an
    in-place ``+=`` over a zero-copy view of the deltas; the jax
    engine on accelerators: one donated-buffer device scatter) plus
    the block_until_ready barrier the scratch ring needs.  Same
    deltas, same f32 adds, same bits — the ratio prices the
    interpreter loop the pool deletes.
  - ``score``: T tenants' newly closed windows (serve-like density:
    one hot service on every 8th tenant), sequential per-tenant
    ``_score_through`` loop vs ONE ``score_closed_windows_batched``
    pass fed by the pool's fused column gather.  Identical alert
    streams (asserted per rep — a microbench that drifted from parity
    would be measuring a different computation).

Shapes follow the serve plane (``serve_plane_cfg``: 12 services, 32
windows) and the default lane-bucket grid.  Writes one bench_runs/
record (``fold_score_microbench``); runs on CPU — the point is this
box, where the serve capture itself cannot resolve the legs.
"""

import json
import os
import sys
import time


def _timed(fn, reps: int):
    """Median-of-reps wall (one untimed warmup call)."""
    fn()
    walls = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        walls.append(time.perf_counter() - t0)
    walls.sort()
    return walls[len(walls) // 2]


def main() -> int:
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import jax
    jax.config.update("jax_platforms", "cpu")
    import dataclasses

    import numpy as np

    from anomod.config import get_config
    from anomod.provenance import capture_record, write_capture
    from anomod.replay import (N_FEATS, ReplayState, TenantStatePool,
                               fold_delta)
    from anomod.serve.engine import serve_plane_cfg
    from anomod.stream import (OnlineDetector, StreamReplay,
                               score_closed_windows_batched)

    cfg = serve_plane_cfg()
    H = cfg.n_hist_buckets
    lane_buckets = get_config().serve_lane_buckets
    reps = int(os.environ.get("ANOMOD_FOLD_SWEEP_REPS", "30"))
    rng = np.random.default_rng(0)
    out = {"metric": "fold_score_microbench", "unit": "x",
           "mode": "micro", "reps": reps,
           "device": jax.devices()[0].device_kind,
           "plane": {"n_services": cfg.n_services,
                     "n_windows": cfg.n_windows},
           "lane_buckets": list(lane_buckets)}

    # -- fold: host readback+adds vs device scatter-add -------------------
    fold_rows = {}
    for L in lane_buckets:
        dagg = jax.device_put(
            rng.random((L, cfg.sw, N_FEATS)).astype(np.float32))
        dhist = jax.device_put(
            rng.random((L, cfg.sw, H)).astype(np.float32))
        states = [ReplayState(
            agg=rng.random((cfg.sw, N_FEATS)).astype(np.float32),
            hist=rng.random((cfg.sw, H)).astype(np.float32))
            for _ in range(L)]

        def host_fold():
            da, dh = np.asarray(dagg), np.asarray(dhist)
            return [fold_delta(st, da[i], dh[i])
                    for i, st in enumerate(states)]

        pool = TenantStatePool(cfg, capacity=L)
        slots = [pool.acquire() for _ in range(L)]
        for s, st in zip(slots, states):
            pool.put(s, st)
        pool.warm((L,))

        def pool_fold():
            pool.scatter_fold(slots, dagg, dhist)
            dagg.block_until_ready()

        t_host = _timed(host_fold, reps)
        t_pool = _timed(pool_fold, reps)
        fold_rows[str(L)] = {
            "host_seam_us": round(t_host * 1e6, 1),
            "pool_us": round(t_pool * 1e6, 1),
            "speedup": round(t_host / max(t_pool, 1e-9), 2)}
    out["fold"] = fold_rows
    out["pool_engine"] = pool.engine

    # -- score: sequential per-tenant loop vs one batched pass ------------
    svcs = tuple(f"s{i}" for i in range(cfg.n_services))
    w_us = cfg.window_us
    n_stream_w = 14

    def _stream(t, seed):
        """One tenant's seeded 14-window span stream, REAL data path:
        healthy traffic everywhere, a 25x latency fault on service 0
        from window 8 for every 8th tenant (serve-like alert density)."""
        from anomod.schemas import SpanBatch
        r = np.random.default_rng(seed + t)
        per_w = 24
        rows = n_stream_w * per_w
        start = np.sort(np.repeat(np.arange(n_stream_w, dtype=np.int64),
                                  per_w) * w_us
                        + r.integers(0, w_us, rows))
        dur = r.integers(900, 1100, rows).astype(np.int64)
        if t % 8 == 0:
            svc0_late = (start // w_us >= 8)
            dur = np.where(svc0_late, dur * 25, dur)
        return SpanBatch(
            trace=np.arange(rows, dtype=np.int32) % 9,
            parent=np.full(rows, -1, np.int32),
            service=r.integers(0, cfg.n_services, rows).astype(np.int32),
            endpoint=np.zeros(rows, np.int32), start_us=start,
            duration_us=dur, is_error=r.random(rows) < 0.02,
            status=np.full(rows, 200, np.int16),
            kind=np.zeros(rows, np.int8), services=svcs,
            endpoints=("ep",),
            trace_ids=tuple(f"t{i}" for i in range(9))).validate()

    def mk_dets(T, seed):
        dets = []
        for t in range(T):
            det = OnlineDetector(svcs, cfg, 0,
                                 replay=StreamReplay(cfg, 0),
                                 baseline_windows=4, z_threshold=4.0)
            w = det.replay.push(_stream(t, seed))
            det._max_seen = w
            dets.append(det)
        return dets

    def reset(det):
        det._scored_through = -1
        det._streak[:] = 0
        det._cusum[:] = 0.0
        det._cusum_k[:] = 0

    score_rows = {}
    for T in (8, 32, 128):
        seq = mk_dets(T, 100)
        bat = mk_dets(T, 100)
        pool = TenantStatePool(cfg, capacity=T)
        slots = [pool.acquire() for _ in range(T)]
        for s, d in zip(slots, bat):
            pool.put(s, d.replay.get_state())
        pool.warm()
        through = n_stream_w - 2

        def seq_score():
            for d in seq:
                reset(d)
                d.alerts.clear()
                d._score_through(through)

        def bat_score():
            work = []
            for d in bat:
                reset(d)
                d.alerts.clear()
                work.append((d, d.baseline_windows, through))

            def gather(items):
                return pool.gather_window(
                    [slots[i] for i, _ in items],
                    [c for _, c in items])

            score_closed_windows_batched(work, gather)

        t_seq = _timed(seq_score, reps)
        t_bat = _timed(bat_score, reps)
        seq_score()
        bat_score()
        a = [[dataclasses.asdict(x) for x in d.alerts] for d in seq]
        b = [[dataclasses.asdict(x) for x in d.alerts] for d in bat]
        assert a == b and any(a), \
            "batched scoring diverged from sequential — not a benchmark"
        score_rows[str(T)] = {
            "windows": through - seq[0].baseline_windows + 1,
            "seq_us": round(t_seq * 1e6, 1),
            "batched_us": round(t_bat * 1e6, 1),
            "speedup": round(t_seq / max(t_bat, 1e-9), 2),
            "alerts": sum(len(x) for x in a)}
    out["score"] = score_rows

    best_fold = max(r["speedup"] for r in fold_rows.values())
    best_score = max(r["speedup"] for r in score_rows.values())
    out["value"] = round(min(best_fold, best_score), 2)
    rec = capture_record(out["metric"], out["value"], out["unit"],
                         **{k: v for k, v in out.items()
                            if k not in ("metric", "value", "unit")})
    path = write_capture(rec)
    if path:
        out["capture_file"] = str(path)
    print(json.dumps(out, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
