#!/usr/bin/env python
"""Roofline ablation for the sorted replay kernel on the live TPU.

The headline kernel sits at ~1.6e9 spans/s — ~38 GB/s of 24-byte rows on a
part with ~800 GB/s HBM, so HBM is NOT the wall.  This probe measures what
is, by running ablations of the kernel's stages at kernel-dominated
replication (same corpus, same staging, same grid):

  - ``onehot_only``    — the [B, k] iota-compare one-hot plus a 1-row
                         matmul (counts): the irreducible scatter
                         densification.  One 128-lane compare per span is
                         the hardware's minimum for ANY one-hot
                         formulation (VPU lanes are 128 wide; a narrower
                         one-hot still burns a full lane register).
  - ``no_hist``        — full moment pipeline, histogram plane ablated
                         (ROWS 25 -> 9).
  - ``full``           — the shipping kernel.
  - ``full_bf16oh``    — the shipping kernel with the bf16 iota-compare
                         one-hot (16-bit lanes pack 2x on the VPU).

``full / onehot_only`` bounds how far the full kernel sits from the
formulation's hardware ceiling; the VERDICT's roofline criterion is met
when that ratio is within ~2x.  Writes one bench_runs/ record with every
ablation's rate.  Run when the tunnel is live (tpu_watch hooks it).
"""

import json
import os
import sys
import time


def main() -> int:
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from anomod.utils.platform import probe_device_platform

    plat, diag = probe_device_platform()
    if plat != "tpu":
        print(json.dumps({"error": f"no TPU backend ({diag})"}))
        return 2

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from anomod import labels, synth
    from anomod.ops.pallas_replay import (N_PLANES, _build_rhs_t,
                                          make_pallas_replay_sorted_fn,
                                          stage_sorted_planes)
    from anomod.provenance import capture_record, write_capture
    from anomod.replay import (ReplayConfig, stage_columns,
                               stage_pallas_planes)
    from anomod.schemas import concat_span_batches

    k, block, replicate, n_hist = 128, 4096, 4096, 16
    batch = concat_span_batches([
        synth.generate_spans(l, n_traces=2_000)
        for l in labels.labels_for_testbed("TT")])
    cfg = ReplayConfig(n_services=batch.n_services)
    chunks, n = stage_columns(batch, cfg)
    sid_np, planes_np = stage_pallas_planes(chunks)
    sid_l, planes_s, wids = stage_sorted_planes(sid_np, planes_np, cfg.sw,
                                                k=k, block=block)
    sid_d = jax.device_put(sid_l)
    planes_d = jax.device_put(planes_s)
    wids_d = jax.device_put(wids)
    t = sid_l.shape[0]
    nw = (cfg.sw + 1 + k - 1) // k

    def make_ablation(rows_mode: str):
        """Ablated sorted kernels sharing grid/staging with the real one.
        rows_mode: "counts" (1-row rhs) or "no_hist" (9-row rhs)."""
        ROWS = 1 if rows_mode == "counts" else 9
        NWK = nw * k

        def kernel(wids_ref, sid_ref, planes_ref, out_ref):
            @pl.when((pl.program_id(0) == 0) & (pl.program_id(1) == 0))
            def _init():
                out_ref[:] = jnp.zeros_like(out_ref)
            sid = sid_ref[:]
            planes = planes_ref[:]
            if rows_mode == "counts":
                rhs_t = planes[0:1].astype(jnp.bfloat16)
            else:
                moments = planes[3:6]
                hi = moments.astype(jnp.bfloat16)
                lo = (moments - hi.astype(jnp.float32)).astype(jnp.bfloat16)
                rhs_t = jnp.concatenate(
                    [planes[0:3].astype(jnp.bfloat16), hi, lo], axis=0)
            seg_iota = jax.lax.broadcasted_iota(jnp.int32, (block, k), 1)
            onehot = (seg_iota == sid[:, None]).astype(jnp.bfloat16)
            partial = jax.lax.dot_general(
                rhs_t, onehot, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            col = wids_ref[pl.program_id(1)] * k
            out_ref[:, pl.ds(col, k)] += partial

        @jax.jit
        def run(sid_local, planes, wids):
            return pl.pallas_call(
                kernel,
                grid_spec=pltpu.PrefetchScalarGridSpec(
                    num_scalar_prefetch=1,
                    grid=(replicate, t // block),
                    in_specs=[
                        pl.BlockSpec((block,), lambda r, i, w: (i,)),
                        pl.BlockSpec((N_PLANES, block),
                                     lambda r, i, w: (0, i)),
                    ],
                    out_specs=pl.BlockSpec((ROWS, NWK),
                                           lambda r, i, w: (0, 0)),
                ),
                out_shape=jax.ShapeDtypeStruct((ROWS, NWK), jnp.float32),
                compiler_params=pltpu.CompilerParams(
                    dimension_semantics=("arbitrary", "arbitrary")),
            )(wids, sid_local, planes)

        return run

    def timed(run, *args):
        out = np.asarray(run(*args))       # compile + warm
        times = []
        for _ in range(3):
            t0 = time.perf_counter()
            out = np.asarray(run(*args))
            times.append(time.perf_counter() - t0)
        wall = sorted(times)[1]
        return n * replicate / wall, wall, float(out[..., 0].sum())

    results = {}
    full = make_pallas_replay_sorted_fn(cfg.sw, n_hist, k=k, block=block,
                                        inner_repeats=replicate)
    results["full"], w, _ = timed(full, sid_d, planes_d, wids_d)
    fullb = make_pallas_replay_sorted_fn(cfg.sw, n_hist, k=k, block=block,
                                         inner_repeats=replicate,
                                         bf16_onehot=True)
    results["full_bf16oh"], _, _ = timed(fullb, sid_d, planes_d, wids_d)
    for mode, name in (("counts", "onehot_only"), ("no_hist", "no_hist")):
        results[name], _, _ = timed(make_ablation(mode), sid_d, planes_d,
                                    wids_d)

    ceiling = results["onehot_only"]
    best = max(results["full"], results["full_bf16oh"])
    verdict = {
        "metric": "replay_kernel_roofline",
        "value": round(best, 1),
        "unit": "spans/sec/chip",
        "rates": {m: round(v, 1) for m, v in results.items()},
        "onehot_ceiling_ratio": round(ceiling / max(best, 1.0), 3),
        "within_2x_of_formulation_ceiling": bool(ceiling / best <= 2.0),
        "params": dict(k=k, block=block, replicate=replicate,
                       n_spans=n, device=str(jax.devices()[0])),
    }
    # device must be TOP-LEVEL: write_capture names the file by the
    # record's "device" field (…_tpu.json), and tpu_watch.sh's retire
    # gate globs exactly that name
    rec = capture_record("replay_kernel_roofline", verdict["value"],
                         "spans/sec/chip",
                         device=str(jax.devices()[0]),
                         **{kk: vv for kk, vv in verdict.items()
                            if kk not in ("metric", "value", "unit")})
    path = write_capture(rec)
    verdict["capture_file"] = str(path)
    print(json.dumps(verdict))
    return 0


if __name__ == "__main__":
    sys.exit(main())
