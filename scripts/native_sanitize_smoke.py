#!/usr/bin/env python
"""Sanitizer smoke for the GIL-free native staging path.

The serve hot loop's staging (``anomod_stage_lanes`` /
``anomod_stage_lanes_mat`` + the shared ``Runtime`` pool, PR 7) runs
with the GIL released and multiple shard workers filling pinned scratch
concurrently — the repo's hardest-to-review code path.  This smoke
turns it into a CI-checkable artifact: it builds the whole native layer
with ``-fsanitize=thread`` (or ``address``) plus the staging hammer
driver (``native/sanitize_hammer.cpp`` — N worker threads, each owning
its own pipeline scratch slots, ALL sharing one Runtime pool: the
StagePlan fill pattern) and runs it.  The hammer also covers the
admission-plane columnar SFQ kernels (``anomod_sfq_drain`` /
``anomod_sfq_victim``): each worker drives them against an O(n^2)
repeated-scan reference oracle, so the serve drain/shed hot loop is
proven race-free and byte-identical the same way the staging layer is.

Why a native driver instead of the Python GIL-overlap hammer: a
TSan-instrumented shared library cannot be dlopen'd into an
uninstrumented CPython (the TSan runtime must own the process from
start), so the hammer drives the same ``extern "C"`` entry points with
the same concurrency shape and the same byte-parity oracle natively.

Verdicts (one JSON line on stdout):

- ``ok``   — built with the sanitizer, hammer ran clean; exit 0
- ``skip`` — toolchain cannot build sanitized binaries (no compiler,
  or ``-fsanitize`` probe failed); the REASON is recorded; exit 0
- ``fail`` — the sanitizer reported a race/error, or the hammer's
  byte-parity oracle failed; stderr carries the report; exit 1

``scripts/pre_bench_check.py --mode serve`` runs the tsan leg whenever
the native runtime is in play, mapping ``fail`` to its
``EXIT_NATIVE_UNUSABLE`` code (a racy staging runtime must not serve).
"""

import argparse
import json
import shutil
import subprocess
import sys
import tempfile
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
NATIVE = ROOT / "native"

_TARGETS = {"tsan": "anomod_hammer_tsan", "asan": "anomod_hammer_asan"}
_FLAGS = {"tsan": "thread", "asan": "address"}
_RUN_ENV = {"tsan": {"TSAN_OPTIONS": "halt_on_error=1 exitcode=66"},
            "asan": {"ASAN_OPTIONS": "halt_on_error=1"}}


def probe(sanitizer: str, cxx: str = None) -> dict:
    """Can this box build+link ``-fsanitize=<sanitizer>`` at all?
    Compiles a trivial threaded program with the SAME compiler command
    the Makefile will use (the full ``$CXX`` — e.g. ``ccache g++`` —
    default g++; probe and build must agree or a probe pass guarantees
    nothing); the reason string is what the SKIP verdict carries."""
    if cxx is None:
        import os
        cxx = (os.environ.get("CXX") or "").strip() or "g++"
    parts = cxx.split()
    if shutil.which(parts[0]) is None:
        return {"ok": False,
                "reason": f"no C++ compiler ({parts[0]}) on PATH"}
    if shutil.which("make") is None:
        return {"ok": False, "reason": "make not on PATH"}
    with tempfile.TemporaryDirectory() as td:
        src = Path(td) / "probe.cpp"
        src.write_text("#include <thread>\n"
                       "int main(){std::thread t([]{}); t.join();}\n")
        flag = _FLAGS.get(sanitizer, sanitizer)
        try:
            r = subprocess.run(
                [*parts, f"-fsanitize={flag}", "-pthread", str(src),
                 "-o", str(Path(td) / "probe")],
                capture_output=True, text=True, timeout=120)
        except subprocess.TimeoutExpired:
            return {"ok": False,
                    "reason": f"-fsanitize={flag} probe timed out"}
    if r.returncode != 0:
        tail = (r.stderr or r.stdout).strip().splitlines()[-1:]
        return {"ok": False,
                "reason": f"-fsanitize={flag} probe failed: "
                          f"{' '.join(tail) or 'compiler error'}"}
    return {"ok": True, "reason": ""}


def run(sanitizer: str = "tsan", workers: int = 4,
        iters: int = 40) -> dict:
    """Build + run the sanitized staging hammer; returns the verdict
    dict (never raises on the skip/fail paths — the caller maps
    status to its own exit policy)."""
    out = {"check": "native_sanitize_smoke", "sanitizer": sanitizer}
    if sanitizer not in _TARGETS:
        raise ValueError(f"unknown sanitizer {sanitizer!r}")
    p = probe(sanitizer)
    if not p["ok"]:
        out.update(status="skip", reason=p["reason"])
        return out
    target = _TARGETS[sanitizer]
    try:
        build = subprocess.run(["make", "-C", str(NATIVE), target],
                               capture_output=True, text=True,
                               timeout=300)
    except subprocess.TimeoutExpired:
        out.update(status="fail", reason="sanitized build timed out")
        return out
    if build.returncode != 0:
        # the probe proved the toolchain CAN build sanitized binaries,
        # so a failing hammer build is a real breakage (bad source /
        # Makefile), not a missing-sanitizer box — fail, don't skip
        out.update(status="fail",
                   reason="sanitized build failed (probe passed, so "
                          "this is a source/Makefile breakage, not a "
                          "toolchain gap)",
                   detail=build.stderr.strip()[-2000:])
        return out
    import os
    env = dict(os.environ)
    env.update(_RUN_ENV[sanitizer])
    try:
        r = subprocess.run([str(NATIVE / target), str(workers),
                            str(iters)], capture_output=True, text=True,
                           timeout=300, env=env)
    except subprocess.TimeoutExpired:
        # a deadlock is a typical sanitizer-era failure mode: the
        # verdict must still be a verdict (the gate prints ONE JSON
        # line and maps fail to its own exit code — never a traceback)
        out.update(status="fail",
                   reason="sanitized hammer timed out (possible "
                          "deadlock in the staging path)")
        return out
    out["exit_code"] = r.returncode
    if r.returncode == 0:
        out.update(status="ok", workers=workers, iters=iters)
    elif r.returncode == 2:
        out.update(status="fail", reason="byte-parity oracle failed "
                   "under the sanitized build")
    else:
        out.update(status="fail",
                   reason=f"{sanitizer} reported an error "
                          f"(exit {r.returncode})",
                   detail=r.stderr.strip()[-2000:])
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--sanitizer", choices=["tsan", "asan", "both"],
                    default="tsan")
    ap.add_argument("--workers", type=int, default=4,
                    help="concurrent staging worker threads")
    ap.add_argument("--iters", type=int, default=40,
                    help="staging calls per worker (small-slot pass; "
                         "the pool fan-out pass runs iters/8)")
    args = ap.parse_args(argv)
    legs = ["tsan", "asan"] if args.sanitizer == "both" \
        else [args.sanitizer]
    rc = 0
    for leg in legs:
        out = run(leg, workers=args.workers, iters=args.iters)
        print(json.dumps(out))
        if out["status"] == "fail":
            print(f"native_sanitize_smoke: {leg} FAILED — "
                  f"{out.get('reason')}", file=sys.stderr)
            if out.get("detail"):
                print(out["detail"], file=sys.stderr)
            rc = 1
        elif out["status"] == "skip":
            print(f"native_sanitize_smoke: {leg} SKIP — "
                  f"{out.get('reason')}", file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
