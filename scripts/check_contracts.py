#!/usr/bin/env python
"""CI gate: the contract linter + parity-surface audit.

Runs ``anomod.analysis`` over the repo — the AST rule families
(determinism, env contract, seam discipline, lock discipline) plus the
static parity-surface audit (ServeReport fields / flight-record keys
vs their declared variant lists) — and fails on any finding that is
neither inline-suppressed (with a reason) nor in the baseline
(``scripts/lint_baseline.json``, which may only shrink).

The catalog of enforced contracts lives in docs/CONTRACTS.md; the same
run is available as ``anomod lint``.  ``scripts/pre_bench_check.py``
runs this gate in BOTH modes before every capture (its own
``EXIT_LINT`` code): a capture of a tree with a violated determinism
or parity contract is not reproducible from its record.

Exit codes: 0 = clean (baselined findings ride, shrinkage reported),
1 = new contract violations (listed on stderr).
"""

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def run(root=None) -> dict:
    """The gate body (importable by pre_bench_check): the ONE shared
    composition ``anomod.analysis.lint.run_gate`` as a summary doc."""
    from anomod.analysis.lint import run_gate
    doc, _ = run_gate(root)
    return doc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--root", default=None,
                    help="repo root to scan (tests use a fixture tree)")
    args = ap.parse_args(argv)
    doc = run(args.root)
    print(json.dumps(doc))
    if doc["status"] != "ok":
        for line in doc["new"]:
            print(f"check_contracts: {line}", file=sys.stderr)
        print("check_contracts: run `anomod lint` locally; fix the "
              "finding, add a reasoned inline suppression "
              "(# anomod-" "lint: disable=RULE — why), or baseline it "
              "deliberately", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
