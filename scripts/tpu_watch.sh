#!/bin/bash
# TPU tunnel watcher: probe until the device backend comes up, then capture
# the headline bench on-chip IMMEDIATELY (both kernels) and COMMIT the
# bench_runs/ provenance records (the round-2 verdict's evidence gap: the
# tunnel drops mid-round, so captures must happen — and be committed — the
# moment it is up).  A failed/timed-out capture does NOT consume the
# watcher: it keeps probing so the next live window is retried.
cd "$(dirname "$0")/.." || exit 1
PROBE='import jax; d=jax.devices()[0]; print(d.platform, getattr(d,"device_kind","?"))'
for i in $(seq 1 200); do
  out=$(timeout 90 python -c "$PROBE" 2>/dev/null | tail -1)
  echo "$(date -u +%H:%M:%S) probe $i: ${out:-timeout/dead}"
  if [[ "$out" == tpu* ]]; then
    echo "=== TUNNEL LIVE: $out — capturing now ==="
    # The driver appends to the tracked PROGRESS.jsonl continuously, which
    # alone makes provenance stamp every capture "<sha>-dirty".  Commit it
    # (pathspec-scoped — must not sweep up in-progress source edits) so a
    # clean code tree yields a clean-SHA record; genuinely dirty source
    # still stamps -dirty, as it should.  Diffed against HEAD (not just
    # the worktree-vs-index diff): a staged-but-uncommitted append from a
    # failed prior pass still dirties provenance's `status -uno` check.
    # Called again before each capture group below — the driver keeps
    # appending during the multi-hour sequence, so a single up-front sync
    # would protect only the first few records.
    sync_progress() {
      if ! git diff --quiet HEAD -- PROGRESS.jsonl; then
        git add PROGRESS.jsonl && \
          git commit -q -m "progress log sync (tpu_watch pre-capture)" \
            -- PROGRESS.jsonl
      fi
    }
    sync_progress
    before=$(ls bench_runs/*_tpu.json 2>/dev/null | wc -l)
    # pin kernel AND replicate explicitly on every run: an inherited
    # ANOMOD_BENCH_KERNEL / ANOMOD_BENCH_REPLICATE from the operator's
    # shell must not silently change what each rc label measures.
    # 4096-replicate runs use the driver's 2000-trace corpus: its max
    # per-segment count x4096 (11.3M) stays under f32's exact-integer
    # 2^24, so the bench count-assert is exact; at 20000 traces the
    # biggest counter would reach 1.13e8 and accumulate rounding drift.
    ANOMOD_BENCH_PLATFORM=tpu ANOMOD_BENCH_KERNEL=pallas-sorted \
      ANOMOD_BENCH_REPLICATE=4096 timeout 600 python bench.py
    rc1=$?   # the headline path
    sync_progress
    ANOMOD_BENCH_PLATFORM=tpu ANOMOD_BENCH_KERNEL=pallas \
      ANOMOD_BENCH_REPLICATE=64 timeout 600 python bench.py 20000
    rc2=$?   # dense pallas keeps a recurring on-chip capture
    sync_progress
    ANOMOD_BENCH_PLATFORM=tpu ANOMOD_BENCH_KERNEL=xla \
      ANOMOD_BENCH_REPLICATE=64 timeout 600 python bench.py 20000
    rc3=$?
    # like-for-like 4096-replicate captures for the kernel-vs-kernel
    # ratios (BENCHMARKS.md overhead-correction note): one-off PER KERNEL
    # until a record exists at this replicate for that kernel, and the
    # rcs join the success gate so a failed capture is retried next pass
    rc4=0; rc5=0
    has_4096() {  # $1 = exact "kernel" value to look for
      local f
      f=$(grep -l "\"kernel\": \"$1\"" \
          bench_runs/*_tt_replay_throughput_tpu.json 2>/dev/null)
      [[ -n "$f" ]] && grep -l '"replicate_used": 4096' $f >/dev/null 2>&1
    }
    if ! has_4096 pallas; then
      sync_progress
      ANOMOD_BENCH_PLATFORM=tpu ANOMOD_BENCH_KERNEL=pallas \
        ANOMOD_BENCH_REPLICATE=4096 timeout 600 python bench.py
      rc4=$?
    fi
    if ! has_4096 xla; then
      sync_progress
      ANOMOD_BENCH_PLATFORM=tpu ANOMOD_BENCH_KERNEL=xla \
        ANOMOD_BENCH_REPLICATE=4096 timeout 600 python bench.py
      rc5=$?
    fi
    # Mosaic-compiled kernel parity at the current tree (writes its own
    # bench_runs/ record via the tpu_tests conftest)
    sync_progress
    timeout 600 python -m pytest tpu_tests/ -q
    # On-chip quality shift sweeps, PER TESTBED (the record filename is not
    # testbed-tagged, so grep the record bodies): the round-3 tunnel deaths
    # killed these exact captures; ~6 min each when the tunnel holds.
    # ANOMOD_SKIP_PROBE: the watcher just proved the backend live, and the
    # CLI's own probe would burn another subprocess init.
    sync_progress
    for tb in TT SN; do
      if ! grep -l "\"testbed\": \"$tb\"" \
          bench_runs/*_quality_shift_sweep_tpu.json >/dev/null 2>&1; then
        ANOMOD_SKIP_PROBE=1 timeout 2400 \
          python -m anomod.cli quality --testbed "$tb" --sweep shift --json \
          > "/tmp/tpu_watch_shift_$tb.log" 2>&1
        echo "=== $tb shift sweep rc: $? ==="
      fi
    done
    # Kernel-dominated block sweep (sorted kernel ranked at replicate 512
    # where dispatch overhead no longer masks block preferences): once,
    # keyed on the record field that only the extended sweep writes
    sync_progress
    if ! grep -l '"sorted_best_r512"' \
        bench_runs/*_pallas_block_sweep_tpu.json /dev/null >/dev/null 2>&1
    then
      timeout 1200 python scripts/bench_block_sweep.py \
        > /tmp/tpu_watch_blocksweep.log 2>&1
      echo "=== block sweep rc: $? ==="
    fi
    # Roofline ablation of the sorted kernel (the round-3 verdict's #1
    # evidence criterion): once, keyed on the record file the script's
    # provenance capture writes (metric name replay_kernel_roofline)
    sync_progress
    if ! ls bench_runs/*_replay_kernel_roofline_tpu.json >/dev/null 2>&1
    then
      timeout 1200 python scripts/bench_kernel_roofline.py \
        > /tmp/tpu_watch_roofline.log 2>&1
      echo "=== roofline rc: $? ==="
    fi
    # On-chip streaming-quality records (multimodal, both testbeds): cheap
    # (~2-4 min each).  Code-tree-gated, not existence-gated: the streaming
    # detector evolves (edge attribution landed after the last on-chip
    # captures), so agreement evidence must track the current detector —
    # but gating on the HEAD commit would be self-defeating: the watcher's
    # own bench_runs/ auto-commit advances HEAD and would re-stage every
    # stream capture on the next pass with zero code change.  So the gate
    # resolves each record's stamped commit to its anomod/ TREE hash and
    # accepts the record iff that tree matches HEAD's.  A "<sha>-dirty"
    # stamp resolves through its commit prefix — if the dirt was outside
    # anomod/ the record still counts; dirt inside anomod/ is invisible to
    # git, which errs toward accepting, same as the old prefix match.  The
    # plain and edge-locus captures gate independently (a landed plain
    # record must not retire a failed edge-locus one).
    sync_progress
    code_tree=$(git rev-parse HEAD:anomod 2>/dev/null)
    has_stream_rec() {  # $1 = testbed, $2 = shift value ("in-dist"/"edge-locus")
      # each narrowing step checks its own emptiness: a tail command fed an
      # empty list (xargs -r, grep with no files) exits 0 and would misread
      # "no record at all" as "record present"
      local by_tb by_shift f rsha rtree
      by_tb=$(grep -l "\"testbed\": \"$1\"" \
              bench_runs/*_stream_quality_tpu.json 2>/dev/null)
      [[ -n "$by_tb" ]] || return 1
      by_shift=$(grep -l "\"shift\": \"$2\"" $by_tb 2>/dev/null)
      [[ -n "$by_shift" ]] || return 1
      for f in $by_shift; do
        rsha=$(grep -o '"git_sha": "[0-9a-f]*' "$f" | head -1 | cut -d'"' -f4)
        [[ -n "$rsha" ]] || continue
        rtree=$(git rev-parse "$rsha:anomod" 2>/dev/null) || continue
        [[ "$rtree" == "$code_tree" ]] && return 0
      done
      return 1
    }
    for tb in TT SN; do
      if ! has_stream_rec "$tb" in-dist; then
        ANOMOD_SKIP_PROBE=1 timeout 900 \
          python -m anomod.cli stream --all --testbed "$tb" --multimodal \
          > "/tmp/tpu_watch_stream_$tb.log" 2>&1
        echo "=== $tb stream rc: $? ==="
      fi
      if ! has_stream_rec "$tb" edge-locus; then
        ANOMOD_SKIP_PROBE=1 timeout 900 \
          python -m anomod.cli stream --all --testbed "$tb" --multimodal \
          --severity 0.3 --noise 0.5 --confounders 2 --shift edge-locus \
          > "/tmp/tpu_watch_stream_edge_$tb.log" 2>&1
        echo "=== $tb stream edge-locus rc: $? ==="
      fi
    done
    after=$(ls bench_runs/*_tpu.json 2>/dev/null | wc -l)
    new=$((after - before))
    echo "=== capture rc: sorted=$rc1 pallas=$rc2 xla=$rc3 pallas4096=$rc4 xla4096=$rc5; new TPU records: $new ==="
    if [[ "$new" -gt 0 ]]; then
      # pathspec-scoped commit: must not sweep up unrelated staged work
      git add bench_runs/ && \
        git commit -m "Record on-chip bench captures (tpu_watch auto-commit)" \
          -- bench_runs/ \
        && echo "=== provenance committed ==="
      if [[ "$rc1" -eq 0 && "$rc2" -eq 0 && "$rc3" -eq 0 \
            && "$rc4" -eq 0 && "$rc5" -eq 0 ]]; then
        exit 0
      fi
    fi
    echo "=== capture incomplete; continuing to probe ==="
  fi
  sleep 240
done
echo "=== watcher exhausted retries; tunnel never came up ==="
exit 2
