#!/usr/bin/env python
"""Pallas replay-kernel block-size sweep on the live TPU.

Evidences the docs claim that throughput is flat (within a few %) across
block sizes 1024-8192 with a committed bench_runs/ record per sweep —
docs/BENCHMARKS.md cites the record instead of prose.  Also captures the
XLA scan path on the same staged corpus for the kernel-vs-XLA ratio.

Run manually when the tunnel is up: ``python scripts/bench_block_sweep.py``.
Exits non-zero without touching the backend if no TPU is reachable (probe
with a hard deadline, same recipe as bench.py).
"""

import json
import os
import sys
import time


def main() -> int:
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from anomod.utils.platform import probe_device_platform

    plat, diag = probe_device_platform()
    if plat != "tpu":
        print(json.dumps({"error": f"no TPU backend ({diag})"}))
        return 2

    import jax
    import numpy as np

    from anomod import labels, synth
    from anomod.ops.pallas_replay import make_pallas_replay_fn
    from anomod.provenance import capture_record, write_capture
    from anomod.replay import (ReplayConfig, measure_throughput,
                               stage_columns, stage_pallas_planes)
    from anomod.schemas import concat_span_batches

    batch = concat_span_batches([
        synth.generate_spans(l, n_traces=2_000)
        for l in labels.labels_for_testbed("TT")])
    cfg = ReplayConfig(n_services=batch.n_services)
    chunks, n = stage_columns(batch, cfg)
    sid_np, planes_np = stage_pallas_planes(chunks)
    replicate = 64
    sid = jax.device_put(np.asarray(sid_np))
    planes = jax.device_put(np.asarray(planes_np))

    def time_fn(run):
        """Shared measurement policy for every sweep point: warm/compile,
        then 3 timed runs with the out[:1] host read-back barrier, median
        wall.  Returns (wall, raw_walls)."""
        jax.block_until_ready(run())
        walls = []
        for _ in range(3):
            t0 = time.perf_counter()
            np.asarray(run()[:1])           # host read-back barrier
            walls.append(time.perf_counter() - t0)
        return sorted(walls)[1], walls

    points = []
    for block in (1024, 2048, 4096, 8192):
        fn = make_pallas_replay_fn(cfg.sw, cfg.n_hist_buckets, block=block,
                                   inner_repeats=replicate)
        wall, walls = time_fn(lambda: fn(sid, planes))
        points.append({"block": block,
                       "spans_per_sec": round(n * replicate / wall, 1),
                       "wall_s": round(wall, 4),
                       "raw_wall_s": [round(w, 4) for w in walls]})
        print(json.dumps(points[-1]))

    # sorted-window variant: sweep (block, k) over the same corpus — its
    # one-hot is k lanes wide, so block can grow without VMEM pressure.
    # At replicate 64 the ~70 ms fixed tunnel dispatch/read-back overhead
    # masks block preferences (every point lands ~0.09-0.10 s), so the
    # sweep also runs each point at replicate 512 (~0.2 s/dispatch,
    # kernel-dominated) — that column is the one that ranks configs.
    from anomod.ops.pallas_replay import (make_pallas_replay_sorted_fn,
                                          stage_sorted_planes)
    sorted_points = []
    for block in (1024, 2048, 4096, 8192, 16384):
        for k in (128, 256):
            sid_l, planes_s, wids = stage_sorted_planes(
                sid_np, planes_np, cfg.sw, k=k, block=block)
            sid_d = jax.device_put(sid_l)
            planes_d = jax.device_put(planes_s)
            wids_d = jax.device_put(wids)
            point = {"block": block, "k": k,
                     "staged_rows": int(sid_l.shape[0])}
            for rep in (replicate, 512):
                fn = make_pallas_replay_sorted_fn(cfg.sw,
                                                  cfg.n_hist_buckets,
                                                  k=k, block=block,
                                                  inner_repeats=rep)
                wall, walls = time_fn(
                    lambda: fn(sid_d, planes_d, wids_d))
                tag = "" if rep == replicate else f"_r{rep}"
                point[f"spans_per_sec{tag}"] = round(n * rep / wall, 1)
                point[f"wall_s{tag}"] = round(wall, 4)
                point[f"raw_wall_s{tag}"] = [round(w, 4) for w in walls]
            sorted_points.append(point)
            print(json.dumps(point))

    # replicate scaling at the default sorted config: if spans/sec keeps
    # rising with on-device replication, the fixed dispatch/read-back
    # overhead (tunnel RPC) still dominates the wall and the kernel's true
    # rate is higher than the headline
    replicate_points = []
    sid_l, planes_s, wids = stage_sorted_planes(sid_np, planes_np, cfg.sw)
    sid_d, planes_d, wids_d = (jax.device_put(sid_l),
                               jax.device_put(planes_s),
                               jax.device_put(wids))
    for rep in (64, 256, 1024):
        fn = make_pallas_replay_sorted_fn(cfg.sw, cfg.n_hist_buckets,
                                          inner_repeats=rep)
        wall, walls = time_fn(lambda: fn(sid_d, planes_d, wids_d))
        replicate_points.append({
            "replicate": rep, "spans_per_sec": round(n * rep / wall, 1),
            "wall_s": round(wall, 4),
            "raw_wall_s": [round(w, 4) for w in walls]})
        print(json.dumps(replicate_points[-1]))

    xla = measure_throughput(batch, cfg, repeats=3, replicate=replicate,
                             kernel="xla")
    best = max(p["spans_per_sec"] for p in points)
    worst = min(p["spans_per_sec"] for p in points)
    rec = capture_record(
        "pallas_block_sweep", best, "spans/sec/chip",
        device=str(jax.devices()[0]), n_spans=n * replicate,
        points=points, flatness=round(worst / best, 4),
        sorted_points=sorted_points,
        sorted_best=max(p["spans_per_sec"] for p in sorted_points),
        sorted_best_r512=max(p["spans_per_sec_r512"]
                             for p in sorted_points),
        replicate_points=replicate_points,
        xla_spans_per_sec=round(xla.spans_per_sec, 1),
        xla_raw_wall_s=[round(w, 4) for w in xla.raw_wall_s])
    path = write_capture(rec)
    print(json.dumps({"capture_file": path, "best": best,
                      "flatness": rec["flatness"],
                      "vs_xla": round(best / xla.spans_per_sec, 3)}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
