#!/usr/bin/env python
"""CI gate: every ``ANOMOD_*`` env var the code reads must be covered.

"Covered" means at least one of:

- it appears in the validated ``Config`` env contract
  (``anomod/config.py`` — the typed, fail-loud home for knobs that shape
  framework behavior), or
- it is documented (``README.md`` or any ``docs/*.md`` — the contract
  for operational/driver knobs that deliberately stay out of Config,
  e.g. the bench platform overrides).

An env read that is neither is exactly how a knob rots: it works on the
author's machine, nobody else can discover it, and a typo'd value fails
silently.  This gate greps the whole package (plus ``bench.py`` and
``scripts/``) for ``ANOMOD_[A-Z0-9_]+`` tokens and fails listing every
uncovered name — including any new ``ANOMOD_OBS_*`` knob someone adds
without teaching the Config/doc contract about it.

Since PR 11 the token grep is backed by the AST scanner in
``anomod.analysis.envscan`` (the E2xx lint rules' engine), which closes
this script's documented false negative: a DYNAMIC key —
``os.environ[f"ANOMOD_{name}"]``, ``os.getenv("ANOMOD_" + name)`` —
contains no complete token for the regex to match but is statically
provable to read an ``ANOMOD_*`` var.  Dynamic reads are reported as
violations in their own ``dynamic`` key (they cannot be checked against
the contract at all; route them through anomod.config).

Exit codes: 0 = every referenced var is covered and no dynamic reads,
1 = violations (listed in the JSON line and on stderr) — the exit
contract is unchanged from PR 3.  ``scripts/pre_bench_check.py`` runs
this before every bench gate.
"""

import argparse
import json
import re
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent

# the AST scanner lives in the package (shared with `anomod lint`)
sys.path.insert(0, str(ROOT))

_VAR = re.compile(r"ANOMOD_[A-Z0-9_]+")


def referenced_vars(root: Path) -> dict:
    """Every ANOMOD_* token in the scanned sources -> the files naming it.

    Tokens ending in ``_`` are glob-style prefixes in prose (e.g.
    ``ANOMOD_SERVE_BENCH_*`` rendered without the star) — not reads."""
    out: dict = {}
    files = [root / "bench.py"]
    files += sorted((root / "anomod").rglob("*.py"))
    files += sorted((root / "scripts").glob("*.py"))
    for p in files:
        if not p.is_file():
            continue
        for m in _VAR.finditer(p.read_text(errors="replace")):
            name = m.group(0)
            if name.endswith("_"):
                continue
            out.setdefault(name, set()).add(
                str(p.relative_to(root)))
    return out


def dynamic_reads(root: Path) -> dict:
    """AST pass over the same scan set: dynamic ``ANOMOD_*`` env reads
    (f-string/concat keys) the token grep cannot see — file ->
    [(line, static_prefix)].  ``anomod/config.py`` is exempt: it is the
    contract's one legitimate home for parameterized reads.  The scan
    set is ``anomod.analysis.lint.scan_files`` — ONE definition shared
    with the linter, so the two passes can never cover different
    trees."""
    from anomod.analysis.envscan import dynamic_anomod_reads
    from anomod.analysis.lint import ModuleContext, scan_files
    out: dict = {}
    # exactly anomod/config.py — the same exemption the E2xx lint rule
    # applies; a basename match would also exempt some future
    # anomod/serve/config.py and let the two gates diverge
    exempt = (root / "anomod" / "config.py").resolve()
    for p in scan_files(root):
        if p.resolve() == exempt:
            continue
        rel = str(p.relative_to(root))
        try:
            # a full ModuleContext (not a bare ast.parse): its import
            # table is what resolves `import os as _os` aliased reads
            ctx = ModuleContext(p.read_text(errors="replace"), rel)
        except SyntaxError:
            continue
        got = dynamic_anomod_reads(ctx.tree, ctx)
        if got:
            out[rel] = [[r.line, r.prefix] for r in got]
    return out


def covered_vars(root: Path) -> str:
    """The coverage corpus: the Config module + every markdown doc."""
    parts = []
    for p in [root / "anomod" / "config.py", root / "README.md",
              *sorted((root / "docs").glob("*.md"))]:
        if p.is_file():
            parts.append(p.read_text(errors="replace"))
    return "\n".join(parts)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--root", default=str(ROOT),
                    help="repo root to scan (tests use a fixture tree)")
    args = ap.parse_args(argv)
    root = Path(args.root)
    refs = referenced_vars(root)
    corpus = covered_vars(root)
    missing = {name: sorted(files) for name, files in sorted(refs.items())
               if name not in corpus}
    dynamic = dynamic_reads(root)
    bad = bool(missing or dynamic)
    out = {"check": "env_contract", "n_vars": len(refs),
           "n_missing": len(missing), "n_dynamic": len(dynamic),
           "status": "ok" if not bad else "uncovered-env-vars"}
    if missing:
        out["missing"] = missing
    if dynamic:
        out["dynamic"] = dynamic
    print(json.dumps(out))
    if bad:
        for name, files in missing.items():
            print(f"check_env_contract: {name} (read in "
                  f"{', '.join(files)}) is neither in the Config env "
                  "contract (anomod/config.py) nor documented "
                  "(README.md / docs/*.md)", file=sys.stderr)
        for fname, sites in dynamic.items():
            for line, prefix in sites:
                print(f"check_env_contract: {fname}:{line} reads a "
                      f"DYNAMIC ANOMOD_* env var (key built from "
                      f"{prefix!r}...) — statically uncheckable; route "
                      "it through anomod.config", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
