#!/usr/bin/env python
"""CI gate: every ``ANOMOD_*`` env var the code reads must be covered.

"Covered" means at least one of:

- it appears in the validated ``Config`` env contract
  (``anomod/config.py`` — the typed, fail-loud home for knobs that shape
  framework behavior), or
- it is documented (``README.md`` or any ``docs/*.md`` — the contract
  for operational/driver knobs that deliberately stay out of Config,
  e.g. the bench platform overrides).

An env read that is neither is exactly how a knob rots: it works on the
author's machine, nobody else can discover it, and a typo'd value fails
silently.  This gate greps the whole package (plus ``bench.py`` and
``scripts/``) for ``ANOMOD_[A-Z0-9_]+`` tokens and fails listing every
uncovered name — including any new ``ANOMOD_OBS_*`` knob someone adds
without teaching the Config/doc contract about it.

Exit codes: 0 = every referenced var is covered, 1 = violations (listed
in the JSON line and on stderr).  ``scripts/pre_bench_check.py`` runs
this before every bench gate.
"""

import argparse
import json
import re
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent

_VAR = re.compile(r"ANOMOD_[A-Z0-9_]+")


def referenced_vars(root: Path) -> dict:
    """Every ANOMOD_* token in the scanned sources -> the files naming it.

    Tokens ending in ``_`` are glob-style prefixes in prose (e.g.
    ``ANOMOD_SERVE_BENCH_*`` rendered without the star) — not reads."""
    out: dict = {}
    files = [root / "bench.py"]
    files += sorted((root / "anomod").rglob("*.py"))
    files += sorted((root / "scripts").glob("*.py"))
    for p in files:
        if not p.is_file():
            continue
        for m in _VAR.finditer(p.read_text(errors="replace")):
            name = m.group(0)
            if name.endswith("_"):
                continue
            out.setdefault(name, set()).add(
                str(p.relative_to(root)))
    return out


def covered_vars(root: Path) -> str:
    """The coverage corpus: the Config module + every markdown doc."""
    parts = []
    for p in [root / "anomod" / "config.py", root / "README.md",
              *sorted((root / "docs").glob("*.md"))]:
        if p.is_file():
            parts.append(p.read_text(errors="replace"))
    return "\n".join(parts)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--root", default=str(ROOT),
                    help="repo root to scan (tests use a fixture tree)")
    args = ap.parse_args(argv)
    root = Path(args.root)
    refs = referenced_vars(root)
    corpus = covered_vars(root)
    missing = {name: sorted(files) for name, files in sorted(refs.items())
               if name not in corpus}
    out = {"check": "env_contract", "n_vars": len(refs),
           "n_missing": len(missing),
           "status": "ok" if not missing else "uncovered-env-vars"}
    if missing:
        out["missing"] = missing
    print(json.dumps(out))
    if missing:
        for name, files in missing.items():
            print(f"check_env_contract: {name} (read in "
                  f"{', '.join(files)}) is neither in the Config env "
                  "contract (anomod/config.py) nor documented "
                  "(README.md / docs/*.md)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
