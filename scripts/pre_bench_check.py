#!/usr/bin/env python
"""Pre-bench gate: ingest-cache warmth (replay mode) / bucket-set
compilability (serve mode).

A throughput capture taken against a cold ingest cache silently folds host
synth/parse time into the session (and, before the cache, re-measured it on
every invocation) — the recorded kernel numbers stop being comparable.
This gate is the scripts/ hook a driver runs before ``python bench.py``:

    python scripts/pre_bench_check.py            # exit 0 iff cache is warm
    python scripts/pre_bench_check.py --cold     # cold capture, on purpose
    python scripts/pre_bench_check.py --mode serve   # serve preconditions

Serve mode validates the serve bench's preconditions instead: the
``ANOMOD_SERVE_BUCKETS`` / ``ANOMOD_SERVE_MAX_BACKLOG`` env contract must
parse, and the bucket set must COMPILE (every bucket width traced through
the shared chunk step on the pinned-CPU backend — a bucket set that can't
compile would burn the capture window mid-serve).  The online-RCA
``ANOMOD_SERVE_RCA_BUCKETS`` (nodes, neighbors) grid gets the same
treatment: every bucket AOT-compiles or the gate fails on the shape
miss.  Exit 3 = serve preconditions failed.

Both modes FIRST run the env-contract gate
(``scripts/check_env_contract.py``): every ``ANOMOD_*`` env var read in
the package must be in the validated Config contract or documented —
a capture driven by an undocumented knob is not reproducible from the
record.  Exit 4 = env contract violation.

Serve mode also builds/validates the NATIVE runtime when the validated
``ANOMOD_NATIVE`` knob requests it: the .so is (re)built on first touch,
a tiny ``stage_lanes`` round-trip must reproduce the interpreter fill
byte-for-byte, and a requested-but-unusable runtime (``ANOMOD_NATIVE=1``
on a box without a toolchain) fails with the recorded build reason —
exit 5, distinct from the generic serve failure, so a driver can tell
"install g++ or unset ANOMOD_NATIVE" from "the bucket grid is broken".
When staging is in play the gate also runs the ThreadSanitizer staging
smoke (``scripts/native_sanitize_smoke.py``: the whole native layer
rebuilt ``-fsanitize=thread`` + the concurrent StagePlan-pattern fill
hammer); a detected race is exit 5 too (a racy staging runtime must
not serve), and a toolchain without sanitizer support SKIPs with its
reason recorded in the JSON line.

Serve mode also runs a <5 s tenant-state RESIDENCY parity smoke: the
same tiny seeded multi-tick run on the device pool
(``ANOMOD_SERVE_STATE=device``) and on the host seam must be
byte-equal — per-tenant alert streams, replay states, SLO quantiles
and shed.  A divergence is a generic serve failure (exit 3: the pool
broke the bit-parity contract); ``ANOMOD_SERVE_STATE=device`` forced
on a box whose pool cannot even construct/operate is its own failure
mode — exit 6, distinct, so a driver can tell "unset
ANOMOD_SERVE_STATE" from "the fold math is broken".

Serve mode also runs a <5 s flight-recorder record→replay→diff smoke
(anomod.obs.flight): the same tiny seeded run journaled twice — once at
1 shard, once at 2 — must produce byte-identical canonical journals;
``diff_journals`` bisecting a divergence fails the gate with its own
exit code (7), distinct from the generic serve failure, so a driver can
tell "the tick journal broke determinism" from "the grid is broken".

Exit codes (the ``EXIT_*`` constants below are the one definition — the
uniqueness test in tests/test_bench_contract.py collects them by prefix
and the table in docs/BENCHMARKS.md mirrors them):

- ``EXIT_READY`` (0): ready — warm cache, or --cold / caching disabled
  is explicit, or serve preconditions hold
- ``EXIT_COLD_CACHE`` (1): cold ingest cache without --cold
- ``EXIT_CACHE_DISABLED`` (2): caching disabled without --cold
- ``EXIT_SERVE_PRECONDITION`` (3): serve precondition failure (env
  knobs, bucket-grid compile, shard fan-out / state-residency parity)
- ``EXIT_ENV_CONTRACT`` (4): undocumented ``ANOMOD_*`` env read
- ``EXIT_NATIVE_UNUSABLE`` (5): ANOMOD_NATIVE requested but the native
  runtime is unusable (compiler missing / build failed)
- ``EXIT_STATE_POOL_UNUSABLE`` (6): ANOMOD_SERVE_STATE=device forced
  but the device state pool is unusable
- ``EXIT_FLIGHT_DIVERGENCE`` (7): the flight-journal record→replay→diff
  smoke found a divergent tick/plane
- ``EXIT_RECOVERY_DIVERGENCE`` (8): the crash→respawn→audit-diff smoke
  found a score gap — a recovered run's canonical journal diverged
  from the fault-free run of the same seed
- ``EXIT_LINT`` (9): the contract linter / parity-surface audit
  (``scripts/check_contracts.py``, docs/CONTRACTS.md) found a new
  unsuppressed, unbaselined violation — a capture of a tree with a
  broken determinism or parity contract is not reproducible from its
  record.  Both modes run this gate right after the env contract.
- ``EXIT_POLICY_DIVERGENCE`` (10): the elastic smoke (scale 1→2→1
  under a scripted load surge, ``anomod audit diff`` vs the static run
  of the same seed) found a score gap or failed to produce both a
  scale-up and a scale-down episode — the elastic policy either moved
  a scored byte or never scaled at all.
- ``EXIT_PERF_DIVERGENCE`` (11): the performance-observatory smoke
  (record → report → self-diff, anomod.obs.perf) failed — the
  dispatch-lifecycle recorder moved a decision byte, the timeline no
  longer reconciles with the five-leg walls, or ``anomod perf diff``
  semantics broke (a same-capture self-diff flagged something, or a
  doctored 2× slowdown went unflagged) — a capture's perf block /
  regression verdicts could not be trusted.
- ``EXIT_CENSUS_DIVERGENCE`` (12): the fleet-census smoke (record →
  report → on/off byte-parity → pool-bytes reconciliation,
  anomod.obs.census) failed — the census recorder moved a decision
  byte, recorded no census, or a state pool's array bytes stopped
  reconciling with ``(capacity + 1) × per-slot nbytes`` — a capture's
  census block (the tiering baseline) could not be trusted.
- ``EXIT_ASYNC_DIVERGENCE`` (13): the deferred-commit smoke (the same
  tiny seeded run served synchronous and with
  ``ANOMOD_SERVE_ASYNC_COMMIT`` on) diverged on states, alerts, SLO,
  shed or the canonical flight journal, or never actually deferred a
  tick — the async engine broke the byte-parity contract and an
  async capture's decision planes could not be trusted.
- ``EXIT_TIERING_DIVERGENCE`` (15): the state-tiering smoke (a small
  sub-capacity fleet with an idle tail, tiered hot→warm→cold vs the
  same seed never-evicted) found a demotion that never fired, a parity
  break (alerts/SLO/shed/final state digest), or a tenant left
  stranded in the tier at run end — do not capture fleet blocks with
  ``ANOMOD_SERVE_TIER_HOT`` set
- ``EXIT_FEED_DIVERGENCE`` (14): the live-feed loop smoke (an
  in-process ``/metrics`` endpoint scraped by ``LiveFeed``, the wire
  journal replayed through ``ReplayTransport``, live vs replay
  compared on states, alerts, SLO, shed and the canonical flight
  journal) diverged, or the live leg consumed nothing — a
  ``--from-live`` capture could not be reproduced from its wire
  journal.
- ``EXIT_PROCSHARD_DIVERGENCE`` (16): the process-worker smoke (the
  same tiny seeded run served on 2 shard threads, 2 shard processes
  and 1 shard process, sparse barrier fold) diverged on states,
  alerts, SLO, shed or the canonical flight journal, the process legs
  silently degraded to threads, or the sparse fold failed to shrink
  the barrier payload — the GIL-free engine broke the byte-parity
  contract and an ``ANOMOD_SERVE_WORKER=process`` capture's decision
  planes could not be trusted.

Always prints one JSON line describing the decision (plus the contract
gate's line).  ``--traces`` must match the bench invocation's span
count (the cache key includes it).
"""

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

#: the gate's exit-code contract, accreted one failure mode per PR —
#: named in ONE place so drivers, docs/BENCHMARKS.md and the uniqueness
#: test cannot drift apart
EXIT_READY = 0
EXIT_COLD_CACHE = 1
EXIT_CACHE_DISABLED = 2
EXIT_SERVE_PRECONDITION = 3
EXIT_ENV_CONTRACT = 4
EXIT_NATIVE_UNUSABLE = 5
EXIT_STATE_POOL_UNUSABLE = 6
EXIT_FLIGHT_DIVERGENCE = 7
EXIT_RECOVERY_DIVERGENCE = 8
EXIT_LINT = 9
EXIT_POLICY_DIVERGENCE = 10
EXIT_PERF_DIVERGENCE = 11
EXIT_CENSUS_DIVERGENCE = 12
EXIT_ASYNC_DIVERGENCE = 13
EXIT_FEED_DIVERGENCE = 14
EXIT_TIERING_DIVERGENCE = 15
EXIT_PROCSHARD_DIVERGENCE = 16


def _shard_fanout_smoke() -> dict:
    """The 2-shard fan-out smoke (<5 s): a tiny seeded fused run on 2
    engine workers must produce the EXACT decision output of the same
    run on 1 shard — per-tenant alerts, replay states (bitwise), and
    every report field that is not wall-clock or shard topology.  A
    divergence here means the sharded score path broke determinism and
    a shard-scaling capture would compare different computations."""
    import dataclasses

    import numpy as np

    from anomod.serve.engine import (SHARD_VARIANT_REPORT_FIELDS,
                                     run_power_law)

    def go(n_shards):
        return run_power_law(
            n_tenants=6, n_services=4, capacity_spans_per_s=1000,
            overload=2.0, duration_s=20, tick_s=1.0, seed=5,
            window_s=5.0, baseline_windows=4, fault_tenants=0,
            buckets=(64, 256), lane_buckets=(1, 2, 4), max_backlog=1500,
            n_windows=16, shards=n_shards, pipeline=2)

    e1, r1 = go(1)
    e2, r2 = go(2)
    skip = SHARD_VARIANT_REPORT_FIELDS
    a = {k: v for k, v in r1.to_dict().items() if k not in skip}
    b = {k: v for k, v in r2.to_dict().items() if k not in skip}
    if a != b:
        diff = sorted(k for k in a if a[k] != b[k])
        raise RuntimeError(f"shard fan-out smoke: 2-shard report "
                           f"diverges from 1-shard on {diff}")
    for tid in e1._tenant_det:
        if [dataclasses.asdict(x) for x in e1.alerts_for(tid)] != \
                [dataclasses.asdict(x) for x in e2.alerts_for(tid)]:
            raise RuntimeError(f"shard fan-out smoke: tenant {tid} "
                               "alert stream diverges")
        s1 = e1._tenant_replay[tid].state
        s2 = e2._tenant_replay[tid].state
        if not (np.array_equal(np.asarray(s1.agg), np.asarray(s2.agg))
                and np.array_equal(np.asarray(s1.hist),
                                   np.asarray(s2.hist))):
            raise RuntimeError(f"shard fan-out smoke: tenant {tid} "
                               "replay state diverges")
    return {"tenants": len(e1._tenant_det),
            "served_spans": r1.served_spans}


def _state_parity_smoke() -> dict:
    """The device-vs-host residency smoke (<5 s): a tiny seeded fused
    multi-tick run with the device state pool must produce the EXACT
    decision output of the same run on the host seam — per-tenant alert
    streams, replay states (bitwise), SLO quantiles and shed fraction.
    A divergence means the pool's scatter/roll/gather broke the
    bit-parity contract and a serve capture would compare different
    computations."""
    import dataclasses

    import numpy as np

    from anomod.serve.engine import run_power_law

    def go(state):
        return run_power_law(
            n_tenants=5, n_services=4, capacity_spans_per_s=1000,
            overload=2.0, duration_s=16, tick_s=1.0, seed=9,
            window_s=2.0, baseline_windows=4, fault_tenants=1,
            buckets=(64, 256), lane_buckets=(1, 2, 4), max_backlog=1500,
            n_windows=16, shards=1, pipeline=2, state=state)

    eh, rh = go("host")
    ed, rd = go("device")
    for tid in eh._tenant_det:
        if [dataclasses.asdict(a) for a in eh.alerts_for(tid)] != \
                [dataclasses.asdict(a) for a in ed.alerts_for(tid)]:
            raise RuntimeError(f"state parity smoke: tenant {tid} alert "
                               "stream diverges device vs host")
        s1 = eh._tenant_replay[tid].state
        s2 = ed._tenant_replay[tid].state
        if not (np.array_equal(np.asarray(s1.agg), np.asarray(s2.agg))
                and np.array_equal(np.asarray(s1.hist),
                                   np.asarray(s2.hist))):
            raise RuntimeError(f"state parity smoke: tenant {tid} "
                               "replay state diverges device vs host")
    if rh.latency != rd.latency or rh.shed_fraction != rd.shed_fraction:
        raise RuntimeError("state parity smoke: SLO/shed diverge "
                           "device vs host")
    return {"tenants": len(eh._tenant_det),
            "pool_engine": ed.runner.pool.engine,
            "alerts": sum(len(ed.alerts_for(t))
                          for t in ed._tenant_det)}


def _native_smoke() -> dict:
    """One stage_lanes round-trip vs the interpreter fill, byte-for-byte
    — proves the freshly-(re)built ABI before a capture trusts it."""
    import numpy as np

    from anomod.io import native
    scratch = {"sid": native.aligned_empty((4, 32), np.int32),
               "dur": native.aligned_empty((4, 32), np.float32)}
    rng = np.random.default_rng(0)
    group = [{"sid": rng.integers(0, 9, 20).astype(np.int32),
              "dur": rng.random(20).astype(np.float32)},
             {"sid": rng.integers(0, 9, 32).astype(np.int32),
              "dur": rng.random(32).astype(np.float32)}]
    fills = {"sid": 9, "dur": 0}
    if not native.stage_lanes(scratch, group, lambda k: fills[k]):
        raise RuntimeError("stage_lanes refused a well-formed slot")
    for k, buf in scratch.items():
        want = np.empty((4, 32), buf.dtype)
        for i, cols in enumerate(group):
            m = cols[k].shape[0]
            want[i, :m] = cols[k]
            want[i, m:] = fills[k]
        want[2:] = fills[k]
        if buf.tobytes() != want.tobytes():
            raise RuntimeError(f"stage_lanes byte mismatch on {k!r}")
    return {"status": "ok", "cols": len(scratch)}


def _flight_smoke():
    """The flight-recorder record→replay→diff smoke (<5 s): the same
    tiny seeded run journaled at 1 shard (record) and re-executed at 2
    shards (the forensic replay) must produce canonical journals
    ``diff_journals`` finds identical — every plane, every tick.  A
    divergence means the tick journal broke the determinism contract
    and every audit trail a capture leaves would be unusable.  Returns
    ``(info, divergence_or_None)``."""
    from anomod.obs.flight import diff_journals
    from anomod.serve.engine import run_power_law

    def go(n_shards):
        eng, _ = run_power_law(
            n_tenants=6, n_services=4, capacity_spans_per_s=1000,
            overload=2.0, duration_s=20, tick_s=1.0, seed=5,
            window_s=5.0, baseline_windows=4, fault_tenants=0,
            buckets=(64, 256), lane_buckets=(1, 2, 4), max_backlog=1500,
            n_windows=16, shards=n_shards, pipeline=2, flight=True,
            flight_digest_every=4)
        return eng.flight_recorder

    rec = go(1)
    rep = go(2)
    info = {"ticks": rec.n_recorded, "dropped": rec.n_dropped,
            "digest_every": rec.digest_every}
    return info, diff_journals(rec.journal(), rep.journal())


def _recovery_smoke():
    """The crash→respawn→audit-diff smoke (<5 s): the same tiny seeded
    run executed fault-free and again with scripted mid-tick shard
    crashes (a worker kill + a score-path exception) under supervision
    (anomod.serve.supervise) must produce canonical flight journals
    ``diff_journals`` finds identical — the no-score-gap recovery
    contract.  A divergence means recovery re-execution broke
    determinism and a chaos campaign's results could not be trusted.
    Returns ``(info, divergence_or_None)``."""
    from anomod.obs.flight import diff_journals
    from anomod.serve.engine import run_power_law

    kw = dict(n_tenants=6, n_services=4, capacity_spans_per_s=1000,
              overload=2.0, duration_s=20, tick_s=1.0, seed=5,
              window_s=5.0, baseline_windows=4, fault_tenants=0,
              buckets=(64, 256), lane_buckets=(1, 2, 4),
              max_backlog=1500, n_windows=16, shards=2, pipeline=2,
              flight=True, flight_digest_every=4, ckpt_every=4)
    eng_ref, _ = run_power_law(**kw)
    eng_chaos, rep = run_power_law(
        chaos="crash@6:shard=0:phase=dispatch;"
              "except@11:shard=1:phase=score", **kw)
    info = {"crashes": rep.n_shard_crashes, "respawns": rep.n_respawns,
            "restored_ticks": rep.n_restored_ticks,
            "quarantined": rep.n_quarantined,
            "checkpoints": rep.n_checkpoints}
    if rep.n_shard_crashes < 2 or rep.n_respawns < 1:
        raise RuntimeError(
            f"recovery smoke injected faults did not fire: {info}")
    return info, diff_journals(eng_ref.flight_recorder.journal(),
                               eng_chaos.flight_recorder.journal())


def _elastic_smoke():
    """The elastic-policy smoke (<5 s): the same tiny seeded
    sub-capacity run hit by a scripted load surge (the chaos ``surge``
    kind), served static and again under ``ANOMOD_SERVE_POLICY=auto``
    with a 1→2 shard envelope.  The policy leg must produce at least
    one scale-up AND one scale-down episode (a policy that never
    scales is a silent no-op — raised as a precondition failure), and
    its canonical flight journal must equal the static leg's (the
    elastic no-score-gap contract: scaling moves wall capacity, never
    a scored byte).  Returns ``(info, divergence_or_None)``."""
    from anomod.obs.flight import diff_journals
    from anomod.serve.engine import run_power_law

    kw = dict(n_tenants=6, n_services=4, capacity_spans_per_s=1000,
              overload=0.6, duration_s=24, tick_s=1.0, seed=5,
              window_s=5.0, baseline_windows=4, fault_tenants=0,
              buckets=(64, 256), lane_buckets=(1, 2, 4),
              max_backlog=1500, n_windows=16, flight_digest_every=4,
              chaos="surge@6:factor=6:ticks=6")
    eng_static, _ = run_power_law(shards=1, **kw)
    eng_elastic, rep = run_power_law(
        shards=1, policy="auto", min_shards=1, max_shards=2,
        cooldown_ticks=3, **kw)
    info = {"scale_ups": rep.n_scale_ups,
            "scale_downs": rep.n_scale_downs,
            "migrated_tenants": rep.n_policy_migrations,
            "peak_shards": rep.peak_shards}
    if rep.n_scale_ups < 1 or rep.n_scale_downs < 1:
        raise RuntimeError(
            f"elastic smoke produced no full scaling episode: {info}")
    return info, diff_journals(eng_static.flight_recorder.journal(),
                               eng_elastic.flight_recorder.journal())


def _async_commit_smoke():
    """The deferred-commit byte-parity smoke (<5 s): the same tiny
    seeded run served synchronous (the parity oracle) and again with
    the deferred-commit tick on (``ANOMOD_SERVE_ASYNC_COMMIT``).  The
    async leg must actually defer (``async_ticks > 0`` — a silently
    synchronous "async" run would pass parity vacuously, raised as a
    precondition failure) and must match the oracle on tenant states,
    alerts, SLO, shed and the canonical flight journal — the deferred
    barrier moves wall-clock attribution, never a scored byte.
    Returns ``(info, divergence_or_None)``."""
    from anomod.obs.flight import diff_journals
    from anomod.serve.engine import run_power_law

    kw = dict(n_tenants=6, n_services=4, capacity_spans_per_s=1000,
              overload=2.0, duration_s=20, tick_s=1.0, seed=5,
              window_s=5.0, baseline_windows=4, fault_tenants=0,
              buckets=(64, 256), lane_buckets=(1, 2, 4),
              max_backlog=1500, n_windows=16, shards=2, pipeline=2,
              flight=True, flight_digest_every=4, ckpt_every=4)
    eng_sync, rep_sync = run_power_law(async_commit=False, **kw)
    eng_async, rep_async = run_power_law(async_commit=True, **kw)
    info = {"async_ticks": rep_async.async_ticks,
            "commit_defer_wall_s": rep_async.commit_defer_wall_s,
            "p99_identical": rep_async.latency.get("p99_latency_s")
            == rep_sync.latency.get("p99_latency_s"),
            "shed_identical":
                rep_async.shed_fraction == rep_sync.shed_fraction}
    if rep_async.async_ticks < 1:
        raise RuntimeError(
            f"async-commit smoke never deferred a tick: {info}")
    if not (info["p99_identical"] and info["shed_identical"]):
        return info, {"tick": -1, "plane": "slo/shed"}
    return info, diff_journals(eng_sync.flight_recorder.journal(),
                               eng_async.flight_recorder.journal())


def _feed_smoke():
    """The live-feed loop smoke (<5 s): the serve tick fed from a REAL
    socket.  An in-process ``/metrics`` endpoint (anomod.obs.http)
    serves this process's own registry; a :class:`LiveFeed` scrapes it
    through the recording transport while the engine runs; the wire
    journal is then replayed through :class:`ReplayTransport` and the
    two runs must be byte-identical on tenant states, alerts, SLO,
    shed and the canonical flight journal — the ``--from-live``
    reproducibility contract.  A live leg that consumed nothing is a
    precondition failure (parity would pass vacuously).  Returns
    ``(info, divergence_or_None)``."""
    import tempfile

    import numpy as np

    from anomod.obs.flight import diff_journals
    from anomod.obs.http import ObsHttpServer
    from anomod.obs.registry import Registry, set_registry
    from anomod.serve.feed import run_live_feed

    kw = dict(n_tenants=4, n_services=4, capacity_spans_per_s=2000.0,
              duration_s=8.0, tick_s=1.0, window_s=2.0,
              baseline_windows=2, buckets=(64,), n_windows=16,
              flight=True, flight_digest_every=2)
    prev = set_registry(Registry(enabled=True))
    try:
        with tempfile.TemporaryDirectory() as tmp, \
                ObsHttpServer(port=0) as srv:
            jpath = Path(tmp) / "feed_wire.json"
            eng_live, rep_live, feed = run_live_feed(
                scrape_url=f"{srv.url}/metrics", journal=jpath, **kw)
            srv.stop()
            eng_rep, rep_rep, _ = run_live_feed(
                replay=jpath,
                **{k: v for k, v in kw.items()
                   if k not in ("n_tenants", "n_services")})
    finally:
        set_registry(prev)
    info = {"polls": feed.n_polls, "samples": feed.n_samples,
            "spans": feed.n_spans, "gaps": feed.n_gaps,
            "served_spans": rep_live.served_spans,
            "p99_identical": rep_rep.latency.get("p99_latency_s")
            == rep_live.latency.get("p99_latency_s"),
            "shed_identical":
                rep_rep.shed_fraction == rep_live.shed_fraction}
    if feed.n_polls < 1 or feed.n_samples < 1 \
            or rep_live.served_spans < 1:
        raise RuntimeError(
            f"live-feed smoke consumed nothing: {info}")
    tids = sorted(set(eng_live._tenant_replay)
                  | set(eng_rep._tenant_replay))
    states_same = all(
        t in eng_live._tenant_replay and t in eng_rep._tenant_replay
        and np.array_equal(
            np.asarray(eng_live._tenant_replay[t].state.agg),
            np.asarray(eng_rep._tenant_replay[t].state.agg))
        and np.array_equal(
            np.asarray(eng_live._tenant_replay[t].state.hist),
            np.asarray(eng_rep._tenant_replay[t].state.hist))
        for t in tids)
    alerts_same = all(eng_live.alerts_for(t) == eng_rep.alerts_for(t)
                      for t in sorted(set(eng_live._tenant_det)
                                      | set(eng_rep._tenant_det)))
    if not (states_same and alerts_same
            and info["p99_identical"] and info["shed_identical"]):
        return info, {"tick": -1, "plane": "states/alerts/slo/shed"}
    return info, diff_journals(eng_live.flight_recorder.journal(),
                               eng_rep.flight_recorder.journal())


def _procshard_smoke():
    """The process-worker byte-parity smoke: the same tiny seeded run
    served on 2 shard THREADS (the parity oracle), 2 shard PROCESSES
    and 1 shard process, sparse barrier fold throughout.  The process
    legs must actually run process workers (``ServeReport.worker`` —
    an env-degraded thread run would pass parity vacuously), and all
    three legs must agree on states, alerts, SLO, shed and the
    canonical flight journal — the GIL escape moves wall-clock, never
    a scored byte.  The sparse fold's payload bytes ride the info
    line; the sparse-vs-dense payload bound and real worker RESPAWN
    through a process crash are pinned by
    tests/test_serve_procshard.py, not re-run here.  Returns
    ``(info, divergence_or_None)``."""
    from anomod.obs.flight import diff_journals
    from anomod.serve.engine import run_power_law

    kw = dict(n_tenants=6, n_services=4, capacity_spans_per_s=1000,
              overload=2.0, duration_s=12, tick_s=1.0, seed=5,
              window_s=5.0, baseline_windows=4, fault_tenants=0,
              buckets=(64, 256), lane_buckets=(1, 2, 4),
              max_backlog=1500, n_windows=16, pipeline=2,
              flight=True, flight_digest_every=4)
    eng_thr, rep_thr = run_power_law(shards=2, worker="thread",
                                     fold="sparse", **kw)
    eng_prc, rep_prc = run_power_law(shards=2, worker="process",
                                     fold="sparse", **kw)
    eng_one, rep_one = run_power_law(shards=1, worker="process",
                                     fold="sparse", **kw)
    info = {"worker_thread_leg": rep_thr.worker,
            "worker_process_leg": rep_prc.worker,
            "fold": rep_prc.fold,
            "fold_payload_bytes_thread": rep_thr.fold_payload_bytes,
            "fold_payload_bytes_process": rep_prc.fold_payload_bytes,
            "p99_identical": rep_prc.latency.get("p99_latency_s")
            == rep_thr.latency.get("p99_latency_s"),
            "shed_identical":
                rep_prc.shed_fraction == rep_thr.shed_fraction}
    if rep_prc.worker != "process" or rep_one.worker != "process":
        raise RuntimeError(
            "process legs silently degraded to the thread engine: "
            f"{info}")
    alerts_same = all(
        eng_thr.alerts_for(t) == eng_prc.alerts_for(t)
        == eng_one.alerts_for(t)
        for t in sorted(set(eng_thr._tenant_det)
                        | set(eng_prc._tenant_det)
                        | set(eng_one._tenant_det)))
    if not (alerts_same and info["p99_identical"]
            and info["shed_identical"]):
        return info, {"tick": -1, "plane": "alerts/slo/shed"}
    for pair, (a, b) in (("thread_vs_process", (eng_thr, eng_prc)),
                         ("2_vs_1_process", (eng_prc, eng_one))):
        div = diff_journals(a.flight_recorder.journal(),
                            b.flight_recorder.journal())
        if div is not None:
            div["pair"] = pair
            return info, div
    return info, None


def _perf_smoke():
    """The performance-observatory smoke (<5 s): record → report →
    self-diff.  RECORD: a tiny seeded run with the dispatch-lifecycle
    timeline ON must record events and leave every decision
    byte-identical to the same run with it OFF (alert streams, SLO
    quantiles, shed, canonical flight journal — the read-side
    contract).  REPORT: the event-timeline durations must reconcile
    with the five-leg ServeReport walls within tolerance (the events
    reuse the wall-leg clock reads, so drift means a hook moved).
    SELF-DIFF: ``diff_captures`` of a capture-shaped doc against
    itself must be clean, and against a doctored 2× wall slowdown must
    flag a regression — the noise-aware verdict machinery proves both
    directions before a driver trusts it.  Returns
    ``(info, problem_or_None)``."""
    import copy
    import dataclasses
    import gc

    from anomod.obs.perf import diff_captures
    from anomod.serve.engine import run_power_law

    kw = dict(n_tenants=6, n_services=4, capacity_spans_per_s=1000,
              overload=2.0, duration_s=16, tick_s=1.0, seed=5,
              window_s=5.0, baseline_windows=4, fault_tenants=0,
              buckets=(64, 256), lane_buckets=(1, 2, 4),
              max_backlog=1500, n_windows=16, shards=1, pipeline=2)
    eng_off, rep_off = run_power_law(**kw)
    # The doctored-2x check below proves the VERDICT MACHINERY, and a
    # gen-2 stop-the-world GC pause (~0.25 s against ~2 ms ticks, landing
    # wherever the gate's prior smokes left the allocator thresholds) is
    # the one wall outlier that can blind a 16-sample mean-ratio
    # bootstrap — collect up front and hold GC off for the measured run
    # so raw_wall_s prices the serve tick, not the gate's garbage.
    gc.collect()
    gc.disable()
    try:
        eng_on, rep_on = run_power_law(perf=True, **kw)
    finally:
        gc.enable()
    info = {"events": rep_on.perf_events_recorded,
            "overlap_headroom_s": rep_on.overlap_headroom_s,
            "fold_wait_s": rep_on.fold_wait_s}

    def problem(what, detail):
        return info, {"what": what, "detail": detail}

    if rep_on.perf_events_recorded < 1:
        return problem("no-events", "the perf run recorded no dispatch "
                       "lifecycle events")
    for tid in eng_off._tenant_det:
        if [dataclasses.asdict(a) for a in eng_off.alerts_for(tid)] != \
                [dataclasses.asdict(a) for a in eng_on.alerts_for(tid)]:
            return problem("decision-divergence",
                           f"tenant {tid} alert stream diverges with "
                           "perf recording on")
    if rep_off.latency != rep_on.latency \
            or rep_off.shed_fraction != rep_on.shed_fraction:
        return problem("decision-divergence",
                       "SLO/shed diverge with perf recording on")
    if eng_off.flight_recorder is not None \
            and eng_on.flight_recorder is not None \
            and eng_off.flight_recorder.canonical_bytes() \
            != eng_on.flight_recorder.canonical_bytes():
        return problem("decision-divergence",
                       "canonical flight journal diverges with perf "
                       "recording on")
    evs = eng_on.perf_events
    disp = sum(e["submitted"] - e["submitted_t0"] for e in evs)
    fold = sum(e["folded"] - e["retire_t0"] for e in evs)
    stage = sum(e["staged"] - e["staged_t0"] for e in evs)
    for name, got, wall in (("dispatch", disp, rep_on.dispatch_wall_s),
                            ("fold", fold, rep_on.fold_wall_s)):
        if abs(got - wall) > 1e-3 + 0.02 * wall:
            return problem("reconciliation",
                           f"timeline {name} {got:.6f}s vs report "
                           f"wall {wall:.6f}s")
    if stage > rep_on.stage_wall_s + 1e-3:
        return problem("reconciliation",
                       f"timeline stage {stage:.6f}s exceeds report "
                       f"wall {rep_on.stage_wall_s:.6f}s")
    cap = {"metric": "perf_smoke",
           "shed_fraction": rep_on.shed_fraction,
           "p99_admission_to_scored_latency_s":
               rep_on.latency.get("p99_latency_s"),
           "perf": {"raw_wall_s": [round(t, 6)
                                   for t in eng_on.tick_walls]}}
    if diff_captures(cap, copy.deepcopy(cap))["status"] != "ok":
        return problem("self-diff", "a capture self-diff was not clean")
    doctored = copy.deepcopy(cap)
    doctored["perf"]["raw_wall_s"] = [
        2.0 * t for t in doctored["perf"]["raw_wall_s"]]
    if not diff_captures(cap, doctored)["regressions"]:
        return problem("self-diff",
                       "a doctored 2x wall slowdown went unflagged")
    return info, None


def _census_smoke():
    """The fleet-census smoke (<5 s): record → report → on/off
    byte-parity → pool-bytes reconciliation (anomod.obs.census).  A
    tiny seeded run with the census ON must take censuses, reconcile
    every state pool's bytes exactly with ``(capacity + 1) × per-slot
    nbytes``, and leave every decision byte-identical to the same run
    with it OFF (alert streams, SLO quantiles, shed, the canonical
    flight journal — the read-side contract).  A failure means the
    census block a capture commits (the million-tenant tiering
    baseline) could not be trusted.  Returns
    ``(info, problem_or_None)``."""
    import dataclasses

    from anomod.serve.engine import run_power_law

    kw = dict(n_tenants=6, n_services=4, capacity_spans_per_s=1000,
              overload=2.0, duration_s=16, tick_s=1.0, seed=5,
              window_s=5.0, baseline_windows=4, fault_tenants=0,
              buckets=(64, 256), lane_buckets=(1, 2, 4),
              max_backlog=1500, n_windows=16, shards=1, pipeline=2)
    eng_off, rep_off = run_power_law(**kw)
    eng_on, rep_on = run_power_law(census=True, census_every=4, **kw)
    resident = rep_on.census_resident_bytes
    info = {"census_ticks": rep_on.census_ticks,
            "resident_bytes": resident.get("total"),
            "pool_reconciled": resident.get("pool_reconciled"),
            "hot_tenants": (rep_on.census_hot_set.get("hot_by_decay")
                            or {}).get("4")}

    def problem(what, detail):
        return info, {"what": what, "detail": detail}

    if rep_on.census_ticks < 1 or not resident.get("total"):
        return problem("no-census", "the census run recorded no "
                       "resident-bytes census")
    if resident.get("pool_reconciled") is not True:
        return problem("pool-reconciliation",
                       "state-pool bytes do not reconcile with "
                       "(capacity + 1) x per-slot nbytes")
    for tid in eng_off._tenant_det:
        if [dataclasses.asdict(a) for a in eng_off.alerts_for(tid)] != \
                [dataclasses.asdict(a) for a in eng_on.alerts_for(tid)]:
            return problem("decision-divergence",
                           f"tenant {tid} alert stream diverges with "
                           "the census on")
    if rep_off.latency != rep_on.latency \
            or rep_off.shed_fraction != rep_on.shed_fraction:
        return problem("decision-divergence",
                       "SLO/shed diverge with the census on")
    if eng_off.flight_recorder is not None \
            and eng_on.flight_recorder is not None \
            and eng_off.flight_recorder.canonical_bytes() \
            != eng_on.flight_recorder.canonical_bytes():
        return problem("decision-divergence",
                       "canonical flight journal diverges with the "
                       "census on")
    return info, None


def _tiering_smoke():
    """The state-tiering smoke (<5 s): a small SUB-capacity fleet whose
    power-law tail goes idle (so the decay plane actually demotes),
    run tiered (device hot pool → host warm tier → content-addressed
    disk cold tier) and never-evicted on the same seed.  The tiered
    run must demote AND spill AND promote at least once, and leave
    every decision byte-identical: alert streams, SLO quantiles, shed,
    the final tenant-state digest — with the tier EMPTY at run end
    (the run-end promote-all settlement).  A failure means a fleet
    capture under ``ANOMOD_SERVE_TIER_HOT`` could not be trusted.
    Returns ``(info, problem_or_None)``."""
    import dataclasses
    import tempfile

    from anomod.obs.flight import state_digest
    from anomod.serve.engine import run_power_law

    kw = dict(n_tenants=24, n_services=4, capacity_spans_per_s=400,
              overload=0.5, duration_s=14, tick_s=1.0, seed=7,
              window_s=5.0, baseline_windows=2, fault_tenants=0,
              buckets=(64, 256), lane_buckets=(1, 2, 4),
              max_backlog=1500, n_windows=16, shards=1, pipeline=2)
    eng_off, rep_off = run_power_law(**kw)
    with tempfile.TemporaryDirectory() as cold_dir:
        eng_on, rep_on = run_power_law(
            tier_hot=6, tier_demote_after=2, tier_warm_bytes=4096,
            tier_cold_dir=cold_dir, tier_prefetch=2, **kw)
        info = {"demotions_warm": rep_on.n_tier_demotions_warm,
                "demotions_cold": rep_on.n_tier_demotions_cold,
                "promotions": rep_on.n_tier_promotions,
                "misses": rep_on.n_tier_misses,
                "prefetch_hidden": rep_on.tier_prefetch_hidden}

        def problem(what, detail):
            return info, {"what": what, "detail": detail}

        if not (rep_on.n_tier_demotions_warm
                and rep_on.n_tier_demotions_cold
                and rep_on.n_tier_promotions):
            return problem("no-tiering", "the tiered run never "
                           "demoted/spilled/promoted — the smoke "
                           "exercised nothing")
        if len(eng_on._tier):
            return problem("stranded-tenants",
                           f"{len(eng_on._tier)} tenants left in the "
                           "tier at run end (promote-all settlement "
                           "broke)")
        for tid in eng_off._tenant_det:
            if [dataclasses.asdict(a) for a in eng_off.alerts_for(tid)] \
                    != [dataclasses.asdict(a)
                        for a in eng_on.alerts_for(tid)]:
                return problem("decision-divergence",
                               f"tenant {tid} alert stream diverges "
                               "under tiering")
        if rep_off.latency != rep_on.latency \
                or rep_off.shed_fraction != rep_on.shed_fraction \
                or rep_off.served_spans != rep_on.served_spans:
            return problem("decision-divergence",
                           "SLO/shed/served diverge under tiering")
        if state_digest(eng_off._tenant_replay) \
                != state_digest(eng_on._tenant_replay):
            return problem("decision-divergence",
                           "final tenant-state digest diverges under "
                           "tiering")
    return info, None


def check_serve() -> int:
    """Serve-bench preconditions: env contract parses, bucket set
    compiles, the shard fan-out reproduces the 1-shard output, and the
    native runtime is healthy when ANOMOD_NATIVE requests it.  Runs on
    the pinned-CPU backend (the gate must never hang on a dead device
    tunnel — compilability is backend-independent)."""
    out = {"check": "pre_bench_serve", "mode": "serve"}
    try:
        from anomod.utils.platform import enable_jit_cache, pin_cpu
        pin_cpu(1)
        from anomod.config import Config
        cfg = Config()                    # validates the serve env knobs
        out["buckets"] = list(cfg.serve_buckets)
        out["max_backlog"] = cfg.serve_max_backlog
        out["shards"] = cfg.serve_shards
        out["pipeline"] = cfg.serve_pipeline
        out["jit_cache"] = enable_jit_cache()
        # native runtime: status() triggers the build when the .so is
        # stale/missing; a requested-but-unusable runtime is its OWN
        # failure mode (exit 5) — "install a toolchain or unset
        # ANOMOD_NATIVE", not a bucket-grid problem
        from anomod.io import native
        out["native"] = native.status()
        if cfg.native == "on" and not native.available():
            out["status"] = "native-unusable"
            print(json.dumps(out))
            print("pre_bench_check: ANOMOD_NATIVE=on but the native "
                  f"runtime is unusable: {native.build_error()} — "
                  "install g++ and `make -C native smoke`, or unset "
                  "ANOMOD_NATIVE to serve the pure-Python path",
                  file=sys.stderr)
            return EXIT_NATIVE_UNUSABLE
        if out["native"]["staging"]:
            out["native"]["smoke"] = _native_smoke()
            # TSan leg: rebuild the staging layer -fsanitize=thread and
            # run the concurrent-fill hammer (native/sanitize_hammer.
            # cpp).  A detected race means the GIL-free staging runtime
            # must not serve (same exit as unusable); a box whose
            # toolchain can't build sanitized binaries SKIPs with the
            # recorded reason — never silently.
            import native_sanitize_smoke as nss
            tsan = nss.run("tsan", workers=4, iters=20)
            out["native"]["tsan"] = tsan
            if tsan["status"] == "fail":
                out["status"] = "native-sanitize-failed"
                print(json.dumps(out))
                print("pre_bench_check: the native staging sanitize "
                      f"smoke failed — {tsan.get('reason')} — run "
                      "`make -C native tsan` for the full report; do "
                      "not serve this runtime", file=sys.stderr)
                return EXIT_NATIVE_UNUSABLE
        from anomod.serve.batcher import BucketRunner
        from anomod.serve.engine import serve_plane_cfg
        # tenant-state residency: a FORCED device pool that cannot even
        # construct/operate on this box is its own failure mode (exit
        # 6 — "unset ANOMOD_SERVE_STATE", not "the grid is broken");
        # auto silently serves whatever engine the backend supports
        out["serve_state"] = cfg.serve_state
        if cfg.serve_state == "device":
            try:
                from anomod.replay import TenantStatePool
                probe = TenantStatePool(serve_plane_cfg(), capacity=1)
                slot = probe.acquire()
                probe.put(slot, probe.zero_state())
                probe.gather(slot)
            except Exception as e:
                out["status"] = "serve-state-unusable"
                print(json.dumps(out))
                print("pre_bench_check: ANOMOD_SERVE_STATE=device but "
                      f"the device state pool is unusable: "
                      f"{type(e).__name__}: {e} — unset "
                      "ANOMOD_SERVE_STATE (auto picks the backend's "
                      "engine) or serve the host seam",
                      file=sys.stderr)
                return EXIT_STATE_POOL_UNUSABLE
        # the serve bench's plane shape (ONE definition with bench.py's
        # serve path): compile every bucket width once so the capture's
        # compile_s is warm-path bookkeeping, not a mid-capture stall.
        # The bench's shard legs each compile this same grid per shard
        # runner — with ANOMOD_JIT_CACHE on they read it back from the
        # persistent cache this warm just populated.
        runner = BucketRunner(serve_plane_cfg(), cfg.serve_buckets,
                              lane_buckets=cfg.serve_lane_buckets)
        compile_s = runner.warm()
        out.update(status="ready", widths=list(runner.widths),
                   compile_s=round(compile_s, 3))
        if cfg.serve_fuse:
            # the fused path additionally needs the full
            # (width x lane-bucket) grid compiled — a shape miss here
            # would stall (or crash) the capture mid-serve
            out["lane_buckets"] = list(runner.lane_buckets)
            lane_compile_s = runner.warm_lanes()
            expected = {(w, l) for w in runner.widths
                        for l in runner.lane_buckets}
            missing = sorted(expected - runner.lane_shapes)
            if missing:
                raise RuntimeError(
                    f"fused lane grid shape miss: {missing} did not "
                    "compile")
            out.update(lane_shapes=len(runner.lane_shapes),
                       lane_compile_s=round(lane_compile_s, 3))
            # determinism gate for the bench's shard-scaling legs
            out["shard_smoke"] = _shard_fanout_smoke()
        # determinism gate for the bench's serve_state legs: device-vs-
        # host residency byte-parity over a multi-tick seeded run
        out["state_smoke"] = _state_parity_smoke()
        # the online-RCA bucket grid (the bench's --rca legs): every
        # (nodes, neighbors) bucket must AOT-compile — a shape miss here
        # would stall the capture's alert→culprit path mid-serve
        from anomod.serve.rca import RcaRunner
        rca_runner = RcaRunner(cfg.serve_rca_buckets)
        # warm() compiles every bucket or raises — a shape that cannot
        # compile fails the gate here, never mid-capture
        rca_compile_s = rca_runner.warm()
        out.update(rca_buckets=[list(b) for b in rca_runner.buckets],
                   rca_compile_s=round(rca_compile_s, 3))
        # the flight-recorder record→replay→diff smoke: a capture whose
        # tick journal cannot replay clean leaves no usable audit trail
        # — its own exit code, distinct from the generic serve failure
        flight_info, divergence = _flight_smoke()
        out["flight_smoke"] = flight_info
        if divergence is not None:
            out["status"] = "flight-divergence"
            out["divergence"] = divergence
            print(json.dumps(out))
            print(f"pre_bench_check: flight-journal smoke diverged at "
                  f"tick {divergence['tick']} in the "
                  f"{divergence['plane']} plane — the tick journal broke "
                  "the determinism contract and a capture's audit trail "
                  "would be unusable", file=sys.stderr)
            return EXIT_FLIGHT_DIVERGENCE
        # the crash→respawn→audit-diff smoke: supervised recovery must
        # leave NO score gap (canonical journal equal to fault-free) —
        # its own exit code, distinct from a replay-path divergence
        recovery_info, recovery_div = _recovery_smoke()
        out["recovery_smoke"] = recovery_info
        if recovery_div is not None:
            out["status"] = "recovery-divergence"
            out["divergence"] = recovery_div
            print(json.dumps(out))
            print(f"pre_bench_check: recovery smoke diverged at tick "
                  f"{recovery_div['tick']} in the "
                  f"{recovery_div['plane']} plane — a recovered run "
                  "left a score gap vs the fault-free run of the same "
                  "seed", file=sys.stderr)
            return EXIT_RECOVERY_DIVERGENCE
        # the elastic smoke: scale 1→2→1 under a scripted surge must
        # leave the canonical journal equal to the static run — its own
        # exit code, distinct from a recovery or replay divergence
        elastic_info, elastic_div = _elastic_smoke()
        out["elastic_smoke"] = elastic_info
        if elastic_div is not None:
            out["status"] = "policy-divergence"
            out["divergence"] = elastic_div
            print(json.dumps(out))
            print(f"pre_bench_check: elastic smoke diverged at tick "
                  f"{elastic_div['tick']} in the "
                  f"{elastic_div['plane']} plane — a policy-scaled run "
                  "left a score gap vs the static run of the same "
                  "seed", file=sys.stderr)
            return EXIT_POLICY_DIVERGENCE
        # the performance-observatory smoke: record → report →
        # self-diff — a perf-block capture or an `anomod perf diff`
        # verdict from a broken observatory would be worse than none
        perf_info, perf_problem = _perf_smoke()
        out["perf_smoke"] = perf_info
        if perf_problem is not None:
            out["status"] = "perf-divergence"
            out["problem"] = perf_problem
            print(json.dumps(out))
            print(f"pre_bench_check: perf-observatory smoke failed "
                  f"({perf_problem['what']}): {perf_problem['detail']}"
                  " — the dispatch-lifecycle recorder or the "
                  "noise-aware diff broke its contract; do not trust "
                  "perf blocks or regression verdicts",
                  file=sys.stderr)
            return EXIT_PERF_DIVERGENCE
        # the fleet-census smoke: record → report → on/off byte-parity
        # → pool-bytes reconciliation — a census block (the tiering
        # baseline curve) from a broken census would anchor the
        # tiering refactor against fiction
        census_info, census_problem = _census_smoke()
        out["census_smoke"] = census_info
        if census_problem is not None:
            out["status"] = "census-divergence"
            out["problem"] = census_problem
            print(json.dumps(out))
            print(f"pre_bench_check: fleet-census smoke failed "
                  f"({census_problem['what']}): "
                  f"{census_problem['detail']} — the census recorder "
                  "broke its read-side or reconciliation contract; do "
                  "not trust census blocks or `anomod census diff` "
                  "verdicts", file=sys.stderr)
            return EXIT_CENSUS_DIVERGENCE
        # the state-tiering smoke: demote → spill → re-admit must be a
        # pure residency move — byte parity with the never-evicted run
        # on every decision plane, its own exit code so a driver can
        # tell "tiering moved a scored byte" from a census-recorder or
        # replay-path break
        tier_info, tier_problem = _tiering_smoke()
        out["tiering_smoke"] = tier_info
        if tier_problem is not None:
            out["status"] = "tiering-divergence"
            out["problem"] = tier_problem
            print(json.dumps(out))
            print(f"pre_bench_check: state-tiering smoke failed "
                  f"({tier_problem['what']}): {tier_problem['detail']}"
                  " — demotion/promotion through the snapshot seams "
                  "broke byte parity; do not capture with "
                  "ANOMOD_SERVE_TIER_HOT set", file=sys.stderr)
            return EXIT_TIERING_DIVERGENCE
        # the deferred-commit smoke: the async engine must be a pure
        # wall-clock move — byte parity with the synchronous oracle on
        # every decision plane, its own exit code so a driver can tell
        # "async broke parity" from every other divergence
        async_info, async_div = _async_commit_smoke()
        out["async_commit_smoke"] = async_info
        if async_div is not None:
            out["status"] = "async-divergence"
            out["divergence"] = async_div
            print(json.dumps(out))
            print(f"pre_bench_check: deferred-commit smoke diverged at "
                  f"tick {async_div['tick']} in the "
                  f"{async_div['plane']} plane — the async tick moved "
                  "a scored byte; do not capture with "
                  "ANOMOD_SERVE_ASYNC_COMMIT on", file=sys.stderr)
            return EXIT_ASYNC_DIVERGENCE
        # the live-feed loop smoke: endpoint → LiveFeed → wire-journal
        # replay must be a closed deterministic loop — its own exit
        # code so a driver can tell "the live adapter broke replay"
        # from every other divergence
        feed_info, feed_div = _feed_smoke()
        out["feed_smoke"] = feed_info
        if feed_div is not None:
            out["status"] = "feed-divergence"
            out["divergence"] = feed_div
            print(json.dumps(out))
            print(f"pre_bench_check: live-feed smoke diverged at tick "
                  f"{feed_div['tick']} in the {feed_div['plane']} "
                  "plane — a live run and its wire-journal replay "
                  "disagree; do not trust --from-live captures",
                  file=sys.stderr)
            return EXIT_FEED_DIVERGENCE
        # the process-worker smoke: the GIL-free engine must be a pure
        # wall-clock move — byte parity with the thread oracle and the
        # 1-process run on every decision plane, its own exit code so
        # a driver can tell "the process seam broke parity" from every
        # other divergence
        proc_info, proc_div = _procshard_smoke()
        out["procshard_smoke"] = proc_info
        if proc_div is not None:
            out["status"] = "procshard-divergence"
            out["divergence"] = proc_div
            print(json.dumps(out))
            print(f"pre_bench_check: process-worker smoke diverged at "
                  f"tick {proc_div['tick']} in the "
                  f"{proc_div['plane']} plane "
                  f"({proc_div.get('pair', 'decision planes')}) — the "
                  "process seam moved a scored byte; do not capture "
                  "with ANOMOD_SERVE_WORKER=process", file=sys.stderr)
            return EXIT_PROCSHARD_DIVERGENCE
        print(json.dumps(out))
        return EXIT_READY
    except Exception as e:
        out.update(status="serve-precondition-failed",
                   error=f"{type(e).__name__}: {e}")
        print(json.dumps(out))
        print(f"pre_bench_check: serve preconditions failed: {e}",
              file=sys.stderr)
        return EXIT_SERVE_PRECONDITION


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--mode", choices=["replay", "serve"], default="replay",
                    help="replay: ingest-cache warmth gate (default); "
                         "serve: serve-bench precondition gate")
    ap.add_argument("--testbed", choices=["SN", "TT"], default="TT")
    ap.add_argument("--traces", type=int, default=2_000,
                    help="bench.py span corpus size (default matches "
                         "bench.py's argv default)")
    ap.add_argument("--cold", action="store_true",
                    help="allow the capture anyway; the bench line still "
                         "records cache_hit=false for honesty")
    args = ap.parse_args(argv)

    # env-contract gate first (quiet on success: the drivers parse this
    # script's stdout as ONE JSON line)
    import check_env_contract as cec
    root = Path(cec.ROOT)
    corpus = cec.covered_vars(root)
    missing = {name: sorted(files)
               for name, files in sorted(cec.referenced_vars(root).items())
               if name not in corpus}
    if missing:
        print(json.dumps({"check": "pre_bench_env_contract",
                          "status": "uncovered-env-vars",
                          "missing": missing}))
        print("pre_bench_check: env contract violated — run "
              "scripts/check_env_contract.py and fix the listed ANOMOD_* "
              "vars (Config or docs) before capturing", file=sys.stderr)
        return EXIT_ENV_CONTRACT

    # contract lint + parity-surface audit (static AST — milliseconds,
    # never touches the backend): a capture of a tree violating a
    # determinism/seam/parity contract is not reproducible from its
    # record, so both modes gate on it
    import check_contracts
    lint_doc = check_contracts.run()
    if lint_doc["status"] != "ok":
        print(json.dumps({"check": "pre_bench_contracts", **lint_doc}))
        print("pre_bench_check: contract lint failed — run `anomod "
              "lint`, then fix each finding in place, add a reasoned "
              "inline suppression, or baseline it deliberately "
              "(docs/CONTRACTS.md)", file=sys.stderr)
        return EXIT_LINT

    if args.mode == "serve":
        return check_serve()

    from anomod.io import cache
    from anomod.io.dataset import bench_cache_status

    root = cache.cache_root()
    out = {"check": "pre_bench_ingest", "testbed": args.testbed,
           "traces": args.traces,
           "cache_dir": str(root) if root else None,
           "cold_ok": bool(args.cold)}
    if root is None:
        out["status"] = "caching-disabled"
        print(json.dumps(out))
        if args.cold:
            return EXIT_READY
        print("pre_bench_check: ANOMOD_CACHE_DIR is disabled — captures "
              "would re-synthesize the corpus every run; pass --cold to "
              "record one anyway", file=sys.stderr)
        return EXIT_CACHE_DISABLED
    present, total = bench_cache_status(args.testbed, args.traces)
    out.update(entries_present=present, entries_total=total,
               status="warm" if present == total else "cold")
    print(json.dumps(out))
    if present == total or args.cold:
        return EXIT_READY
    print(f"pre_bench_check: ingest cache at {root} is cold for the "
          f"{args.testbed}/{args.traces}-trace bench corpus — run "
          f"`anomod ingest --warm-cache --bench-traces {args.traces}` "
          "first, or pass --cold to capture an ingest-bound number on "
          "purpose", file=sys.stderr)
    return EXIT_COLD_CACHE


if __name__ == "__main__":
    sys.exit(main())
