#!/usr/bin/env python
"""Pre-bench ingest gate: refuse a capture on a cold cache unless --cold.

A throughput capture taken against a cold ingest cache silently folds host
synth/parse time into the session (and, before the cache, re-measured it on
every invocation) — the recorded kernel numbers stop being comparable.
This gate is the scripts/ hook a driver runs before ``python bench.py``:

    python scripts/pre_bench_check.py            # exit 0 iff cache is warm
    python scripts/pre_bench_check.py --cold     # cold capture, on purpose

Exit codes: 0 = warm (or --cold / caching disabled is explicit), 1 = cold
cache without --cold, 2 = caching disabled without --cold.  Always prints
one JSON line describing the decision.  ``--traces`` must match the bench
invocation's span count (the cache key includes it).
"""

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--testbed", choices=["SN", "TT"], default="TT")
    ap.add_argument("--traces", type=int, default=2_000,
                    help="bench.py span corpus size (default matches "
                         "bench.py's argv default)")
    ap.add_argument("--cold", action="store_true",
                    help="allow the capture anyway; the bench line still "
                         "records cache_hit=false for honesty")
    args = ap.parse_args(argv)

    from anomod.io import cache
    from anomod.io.dataset import bench_cache_status

    root = cache.cache_root()
    out = {"check": "pre_bench_ingest", "testbed": args.testbed,
           "traces": args.traces,
           "cache_dir": str(root) if root else None,
           "cold_ok": bool(args.cold)}
    if root is None:
        out["status"] = "caching-disabled"
        print(json.dumps(out))
        if args.cold:
            return 0
        print("pre_bench_check: ANOMOD_CACHE_DIR is disabled — captures "
              "would re-synthesize the corpus every run; pass --cold to "
              "record one anyway", file=sys.stderr)
        return 2
    present, total = bench_cache_status(args.testbed, args.traces)
    out.update(entries_present=present, entries_total=total,
               status="warm" if present == total else "cold")
    print(json.dumps(out))
    if present == total or args.cold:
        return 0
    print(f"pre_bench_check: ingest cache at {root} is cold for the "
          f"{args.testbed}/{args.traces}-trace bench corpus — run "
          f"`anomod ingest --warm-cache --bench-traces {args.traces}` "
          "first, or pass --cold to capture an ingest-bound number on "
          "purpose", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
