"""Generated suites: calibration, execution, run-id join."""

import numpy as np
import pytest

from anomod import chaos, suite


def test_budget_calibration_matches_reference_points():
    # SN: 2 minutes → 13 tests / 72 targets; TT: 10 minutes → 256 / 825
    sn = suite.generate_suite("SN")
    assert sn.n_tests == 13 and sn.covered_targets == 72
    tt = suite.generate_suite("TT")
    assert tt.n_tests == 256 and tt.covered_targets == 825
    # scaling: half budget → about half the tests; targets saturate
    half = suite.generate_suite("TT", budget_s=300)
    assert 120 <= half.n_tests <= 136
    assert suite.generate_suite("TT", n_tests=5000).covered_targets == 825


def test_suite_deterministic_and_pool_coverage():
    a = suite.generate_suite("TT", seed=4)
    b = suite.generate_suite("TT", seed=4)
    assert a.run_id == b.run_id
    assert [t.spec.endpoint for t in a.tests] == \
        [t.spec.endpoint for t in b.tests]
    # first len(pool) tests round-robin the whole endpoint catalog
    sn = suite.generate_suite("SN")
    eps = {t.spec.template for t in sn.tests[:12]}
    assert len(eps) == 12


def test_run_suite_emits_api_and_joined_traces():
    s = suite.generate_suite("TT", n_tests=20)
    run = suite.run_suite(s, iterations=3, seed=2)
    assert run.api.n_records == 60
    assert len(run.spans.trace_ids) == 60
    assert run.pass_rate > 0.9
    # every request joins to exactly one trace, stamped with the run id
    assert len(np.unique(run.trace_of_request)) == 60
    assert all(t.startswith(s.run_id + "-") for t in run.spans.trace_ids)
    got = suite.traces_for_run(run.spans, s.run_id)
    assert len(got) == 60
    assert len(suite.traces_for_run(run.spans, "em-nope")) == 0


def test_run_suite_trace_structure():
    s = suite.generate_suite("SN", n_tests=12)
    run = suite.run_suite(s, iterations=1, seed=0)
    spans = run.spans
    # parents resolve to a forest: exactly one root per trace
    roots = np.flatnonzero(spans.parent == -1)
    assert len(roots) == len(spans.trace_ids)
    # root is the gateway
    assert all(spans.services[spans.service[r]] == "nginx-web-server"
               for r in roots)
    # home-timeline test's entry span lands on home-timeline-service
    tl = [i for i, e in enumerate(spans.endpoints) if "home-timeline" in e]
    rows = np.flatnonzero(np.isin(spans.endpoint, tl) &
                          (spans.kind == 1) & (spans.parent >= 0))
    svcs = {spans.services[spans.service[r]] for r in rows}
    assert "home-timeline-service" in svcs


def test_run_suite_under_chaos_fails_assertions():
    ctl = chaos.ChaosController()
    s = suite.generate_suite("TT", n_tests=40)
    with ctl.inject("Lv_S_HTTPABORT_preserve"):
        run = suite.run_suite(s, iterations=2, seed=5, controller=ctl)
    # preserve tests fail often; suite tolerates (records) failures
    assert 0.5 < run.pass_rate < 1.0
    errs = run.spans.is_error
    assert errs.any()
    # error spans on the faulted endpoint carry the abort status 503
    pres_eps = [i for i, e in enumerate(run.spans.endpoints)
                if "preserveservice" in e]
    pres_err = errs & np.isin(run.spans.endpoint, pres_eps)
    assert pres_err.any()
    assert (run.spans.status[pres_err] == 503).all()


def test_generate_suite_rejects_unknown_testbed():
    with pytest.raises(ValueError):
        suite.generate_suite("XX")
