"""Native C++ scanner: parity with the Python oracle + throughput sanity."""

import numpy as np
import pytest

from anomod.io import native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native lib not built")

SAMPLE_LOG = """2025-11-03 22:02:28 INFO Starting service
2025-11-03 22:02:29 WARN slow response detected
2025-11-03T22:02:30 ERROR connection refused
plain line without level or time
2025-11-03 22:02:31 info lowercase info
NullPointerException at line 42
"""


def _python_oracle(text):
    # bypass the native dispatch inside parse_log_lines
    import importlib
    from anomod.io import logs as logs_io
    orig = native.available
    native.available = lambda: False
    try:
        svc, t, lvl = logs_io.parse_log_lines(text, 0)
    finally:
        native.available = orig
    return t, lvl


def test_scan_log_matches_python():
    levels, ts = native.scan_log(SAMPLE_LOG.encode())
    t_ref, lvl_ref = _python_oracle(SAMPLE_LOG)
    assert levels.shape[0] == lvl_ref.shape[0]
    np.testing.assert_array_equal(levels, lvl_ref)
    np.testing.assert_allclose(np.where(ts == 0, 0, ts), t_ref)


def test_scan_log_levels():
    levels, ts = native.scan_log(SAMPLE_LOG.encode())
    from anomod.schemas import LOG_ERROR, LOG_INFO, LOG_OTHER, LOG_WARN
    assert list(levels) == [LOG_INFO, LOG_WARN, LOG_ERROR, LOG_OTHER,
                            LOG_INFO, LOG_ERROR]
    assert ts[0] > 1.7e9
    assert ts[3] == 0.0


def test_scan_log_multithreaded_large():
    big = (SAMPLE_LOG * 50_000).encode()  # ~18 MB, crosses the MT threshold
    levels, ts = native.scan_log(big, n_threads=4)
    assert levels.shape[0] == 6 * 50_000
    # pattern repeats
    np.testing.assert_array_equal(levels[:6], levels[6:12])


def test_scan_api_jsonl():
    text = b"""{"timestamp": "2025-11-03T22:02:28", "endpoint": "/x", "status_code": 200, "latency_ms": 12.5, "content_length": 512}
{"timestamp": "2025-11-03T22:02:29", "endpoint": "/y", "status_code": 500, "latency_ms": 3001.75, "content_length": 0}
"""
    status, lat, clen = native.scan_api_jsonl(text)
    assert list(status) == [200, 500]
    np.testing.assert_allclose(lat, [12.5, 3001.75])
    assert list(clen) == [512, 0]


def test_runtime_create_destroy():
    with native.Runtime(3) as rt:
        assert rt.n_threads == 3


def test_summarize_logs_matches_python(tmp_path):
    from anomod.io.logs import summarize_log_files
    for i in range(6):
        (tmp_path / f"Service{i}_x.log").write_text(SAMPLE_LOG * (i + 1))
    paths = sorted(tmp_path.glob("*.log"))
    got = summarize_log_files(paths)
    # python oracle
    orig = native.available
    native.available = lambda: False
    try:
        want = summarize_log_files(paths)
    finally:
        native.available = orig
    assert [s.__dict__ for s in got] == [s.__dict__ for s in want]
    assert got[0].service == "Service0"
    assert got[0].n_lines == 6 and got[0].n_error == 2
    assert got[5].n_lines == 36


def test_summarize_logs_unreadable_file(tmp_path):
    (tmp_path / "a.log").write_text(SAMPLE_LOG)
    counts, ts = native.summarize_log_files(
        [tmp_path / "a.log", tmp_path / "missing.log"])
    assert counts[0, 0] == 6
    assert counts[1].sum() == 0 and ts[1].sum() == 0


def test_summarize_logs_timestamps(tmp_path):
    (tmp_path / "a.log").write_text(SAMPLE_LOG)
    counts, ts = native.summarize_log_files([tmp_path / "a.log"])
    assert ts[0, 0] > 1.7e9 and ts[0, 1] >= ts[0, 0]


def test_scan_csv_columns():
    text = b"""timestamp,value,metric,service
1730671348,0.52,cpu,"compose-post"
1730671363,0.61,cpu,unique-id
1730671378,not_a_number,cpu,"a,b quoted comma"
"""
    out = native.scan_csv_columns(text, [0, 1])
    assert out.shape == (2, 3)
    np.testing.assert_allclose(out[0], [1730671348, 1730671363, 1730671378])
    np.testing.assert_allclose(out[1][:2], [0.52, 0.61])
    assert np.isnan(out[1][2])


def test_scan_csv_columns_no_header():
    out = native.scan_csv_columns(b"1,2\n3,4\n", [1], skip_header=False)
    np.testing.assert_allclose(out[0], [2, 4])


def test_logscan_cli(tmp_path, capsys):
    import json
    from anomod.cli import main
    (tmp_path / "Svc_a.log").write_text(SAMPLE_LOG)
    assert main(["logscan", str(tmp_path)]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["n_files"] == 1
    assert doc["totals"]["lines"] == 6
    assert doc["totals"]["errors"] == 2
    assert doc["files"][0]["service"] == "Svc"


def test_sn_loader_generates_summaries_without_summary_txt(tmp_path):
    from anomod.io.logs import load_sn_log_dir
    (tmp_path / "ComposePost_x.log").write_text(SAMPLE_LOG)
    (tmp_path / "UniqueId_x.log").write_text(SAMPLE_LOG * 2)
    batch, summaries = load_sn_log_dir(tmp_path)
    assert batch is not None and batch.n_lines == 18
    assert summaries is not None and len(summaries) == 2
    by_svc = {s.service: s for s in summaries}
    assert by_svc["ComposePost"].n_lines == 6
    assert by_svc["UniqueId"].n_error == 4


def test_tt_metric_csv_native_fast_path(tmp_path):
    """Native numeric-column parse must agree with the pure-Python path."""
    from anomod.io.metrics import load_tt_metric_csv
    csv_text = (
        "metric_name,timestamp,datetime,value,labels\n"
        "cpu,1730671348,2024-11-03T22:02:28,0.52,pod=ts-order-service-abc\n"
        "cpu,1730671363,2024-11-03T22:02:43,0.61,pod=ts-order-service-abc\n"
        "mem,1730671348,2024-11-03T22:02:28,,pod=ts-travel-service-xyz\n"
    )
    p = tmp_path / "Lv_X_metrics_1.csv"
    p.write_text(csv_text)
    got = load_tt_metric_csv(p)
    orig = native.available
    native.available = lambda: False
    try:
        want = load_tt_metric_csv(p)
    finally:
        native.available = orig
    np.testing.assert_allclose(got.t_s, want.t_s)
    np.testing.assert_allclose(got.value, want.value)
    np.testing.assert_array_equal(got.metric, want.metric)
    assert got.metric_names == want.metric_names


def test_logscan_cli_skips_lfs_stubs(tmp_path, capsys):
    import json
    from anomod.cli import main
    (tmp_path / "Svc_a.log").write_text(SAMPLE_LOG)
    (tmp_path / "Stub_b.log").write_text(
        "version https://git-lfs.github.com/spec/v1\n"
        "oid sha256:abcd\nsize 12345\n")
    assert main(["logscan", str(tmp_path)]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["n_files"] == 1
    assert doc["n_lfs_stubs"] == 1
    assert doc["totals"]["lines"] == 6
