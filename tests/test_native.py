"""Native C++ scanner: parity with the Python oracle + throughput sanity."""

import numpy as np
import pytest

from anomod.io import native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native lib not built")

SAMPLE_LOG = """2025-11-03 22:02:28 INFO Starting service
2025-11-03 22:02:29 WARN slow response detected
2025-11-03T22:02:30 ERROR connection refused
plain line without level or time
2025-11-03 22:02:31 info lowercase info
NullPointerException at line 42
"""


def _python_oracle(text):
    # bypass the native dispatch inside parse_log_lines
    import importlib
    from anomod.io import logs as logs_io
    orig = native.available
    native.available = lambda: False
    try:
        svc, t, lvl = logs_io.parse_log_lines(text, 0)
    finally:
        native.available = orig
    return t, lvl


def test_scan_log_matches_python():
    levels, ts = native.scan_log(SAMPLE_LOG.encode())
    t_ref, lvl_ref = _python_oracle(SAMPLE_LOG)
    assert levels.shape[0] == lvl_ref.shape[0]
    np.testing.assert_array_equal(levels, lvl_ref)
    np.testing.assert_allclose(np.where(ts == 0, 0, ts), t_ref)


def test_scan_log_levels():
    levels, ts = native.scan_log(SAMPLE_LOG.encode())
    from anomod.schemas import LOG_ERROR, LOG_INFO, LOG_OTHER, LOG_WARN
    assert list(levels) == [LOG_INFO, LOG_WARN, LOG_ERROR, LOG_OTHER,
                            LOG_INFO, LOG_ERROR]
    assert ts[0] > 1.7e9
    assert ts[3] == 0.0


def test_scan_log_multithreaded_large():
    big = (SAMPLE_LOG * 50_000).encode()  # ~18 MB, crosses the MT threshold
    levels, ts = native.scan_log(big, n_threads=4)
    assert levels.shape[0] == 6 * 50_000
    # pattern repeats
    np.testing.assert_array_equal(levels[:6], levels[6:12])


def test_scan_api_jsonl():
    text = b"""{"timestamp": "2025-11-03T22:02:28", "endpoint": "/x", "status_code": 200, "latency_ms": 12.5, "content_length": 512}
{"timestamp": "2025-11-03T22:02:29", "endpoint": "/y", "status_code": 500, "latency_ms": 3001.75, "content_length": 0}
"""
    status, lat, clen = native.scan_api_jsonl(text)
    assert list(status) == [200, 500]
    np.testing.assert_allclose(lat, [12.5, 3001.75])
    assert list(clen) == [512, 0]


def test_runtime_create_destroy():
    with native.Runtime(3) as rt:
        assert rt.n_threads == 3


def test_summarize_logs_matches_python(tmp_path):
    from anomod.io.logs import summarize_log_files
    for i in range(6):
        (tmp_path / f"Service{i}_x.log").write_text(SAMPLE_LOG * (i + 1))
    paths = sorted(tmp_path.glob("*.log"))
    got = summarize_log_files(paths)
    # python oracle
    orig = native.available
    native.available = lambda: False
    try:
        want = summarize_log_files(paths)
    finally:
        native.available = orig
    assert [s.__dict__ for s in got] == [s.__dict__ for s in want]
    assert got[0].service == "Service0"
    assert got[0].n_lines == 6 and got[0].n_error == 2
    assert got[5].n_lines == 36


def test_summarize_logs_unreadable_file(tmp_path):
    (tmp_path / "a.log").write_text(SAMPLE_LOG)
    counts, ts = native.summarize_log_files(
        [tmp_path / "a.log", tmp_path / "missing.log"])
    assert counts[0, 0] == 6
    assert counts[1].sum() == 0 and ts[1].sum() == 0


def test_summarize_logs_timestamps(tmp_path):
    (tmp_path / "a.log").write_text(SAMPLE_LOG)
    counts, ts = native.summarize_log_files([tmp_path / "a.log"])
    assert ts[0, 0] > 1.7e9 and ts[0, 1] >= ts[0, 0]


def test_scan_csv_columns():
    text = b"""timestamp,value,metric,service
1730671348,0.52,cpu,"compose-post"
1730671363,0.61,cpu,unique-id
1730671378,not_a_number,cpu,"a,b quoted comma"
"""
    out = native.scan_csv_columns(text, [0, 1])
    assert out.shape == (2, 3)
    np.testing.assert_allclose(out[0], [1730671348, 1730671363, 1730671378])
    np.testing.assert_allclose(out[1][:2], [0.52, 0.61])
    assert np.isnan(out[1][2])


def test_scan_csv_columns_no_header():
    out = native.scan_csv_columns(b"1,2\n3,4\n", [1], skip_header=False)
    np.testing.assert_allclose(out[0], [2, 4])


def test_logscan_cli(tmp_path, capsys):
    import json
    from anomod.cli import main
    (tmp_path / "Svc_a.log").write_text(SAMPLE_LOG)
    assert main(["logscan", str(tmp_path)]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["n_files"] == 1
    assert doc["totals"]["lines"] == 6
    assert doc["totals"]["errors"] == 2
    assert doc["files"][0]["service"] == "Svc"


def test_sn_loader_generates_summaries_without_summary_txt(tmp_path):
    from anomod.io.logs import load_sn_log_dir
    (tmp_path / "ComposePost_x.log").write_text(SAMPLE_LOG)
    (tmp_path / "UniqueId_x.log").write_text(SAMPLE_LOG * 2)
    batch, summaries = load_sn_log_dir(tmp_path)
    assert batch is not None and batch.n_lines == 18
    assert summaries is not None and len(summaries) == 2
    by_svc = {s.service: s for s in summaries}
    assert by_svc["ComposePost"].n_lines == 6
    assert by_svc["UniqueId"].n_error == 4


def test_tt_metric_csv_native_fast_path(tmp_path):
    """Native numeric-column parse must agree with the pure-Python path."""
    from anomod.io.metrics import load_tt_metric_csv
    csv_text = (
        "metric_name,timestamp,datetime,value,labels\n"
        "cpu,1730671348,2024-11-03T22:02:28,0.52,pod=ts-order-service-abc\n"
        "cpu,1730671363,2024-11-03T22:02:43,0.61,pod=ts-order-service-abc\n"
        "mem,1730671348,2024-11-03T22:02:28,,pod=ts-travel-service-xyz\n"
    )
    p = tmp_path / "Lv_X_metrics_1.csv"
    p.write_text(csv_text)
    got = load_tt_metric_csv(p)
    orig = native.available
    native.available = lambda: False
    try:
        want = load_tt_metric_csv(p)
    finally:
        native.available = orig
    np.testing.assert_allclose(got.t_s, want.t_s)
    np.testing.assert_allclose(got.value, want.value)
    np.testing.assert_array_equal(got.metric, want.metric)
    assert got.metric_names == want.metric_names


# ---------------------------------------------------------------------------
# GIL-free serve staging (anomod_stage_lanes): the serving plane's native
# scratch packing — byte parity with the interpreter fill + the GIL-overlap
# contract the pipelined dispatch leans on
# ---------------------------------------------------------------------------

def _rand_span_batch(n, n_services, seed):
    from anomod.schemas import SpanBatch
    rng = np.random.default_rng(seed)
    err = rng.random(n) < 0.05
    return SpanBatch(
        trace=rng.integers(0, 16, n).astype(np.int32),
        parent=np.full(n, -1, np.int32),
        service=rng.integers(0, n_services, n).astype(np.int32),
        endpoint=np.zeros(n, np.int32),
        start_us=np.sort(rng.integers(0, int(60e6), n)).astype(np.int64),
        duration_us=rng.integers(1, 1_000_000, n).astype(np.int64),
        is_error=err.astype(np.bool_),
        status=np.where(err, 500, 200).astype(np.int16),
        kind=np.zeros(n, np.int8),
        services=tuple(f"s{i}" for i in range(n_services)),
        endpoints=("e",),
        trace_ids=tuple(f"t{i:02d}" for i in range(16))).validate()


def _py_fill(scratch, group_cols, fills):
    lanes, width = next(iter(scratch.values())).shape
    for k, buf in scratch.items():
        for i, cols in enumerate(group_cols):
            c = cols[k]
            m = c.shape[0]
            buf[i, :m] = c
            if m < width:
                buf[i, m:] = fills[k]
        buf[len(group_cols):] = fills[k]


def _rand_group(rng, keys, dtypes, n_live, width, allow_empty=True):
    group = []
    for _ in range(n_live):
        lo = 0 if allow_empty else 1
        m = int(rng.integers(lo, width + 1))
        group.append({
            k: (rng.integers(0, 1000, m).astype(dtypes[k])
                if np.issubdtype(dtypes[k], np.integer)
                else rng.random(m).astype(dtypes[k]))
            for k in keys})
    return group


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("lanes,width", [(2, 64), (4, 256), (8, 1024)])
def test_stage_lanes_byte_identical_to_python_fill(seed, lanes, width):
    """The staging-parity contract across dtypes/widths/seeds: the native
    pack must reproduce stage_columns_raw + dead-fill (the interpreter
    fill) byte-for-byte — int32 and float32 columns, empty-to-full live
    rows, dead lanes included."""
    keys = ["sid", "dur", "dur_raw", "err", "s5", "valid", "tid"]
    dtypes = {"sid": np.int32, "tid": np.int32}
    dtypes.update({k: np.float32 for k in keys if k not in dtypes})
    fills = {k: (37 if k == "sid" else 0) for k in keys}
    rng = np.random.default_rng(seed)
    n_live = int(rng.integers(1, lanes + 1))
    group = _rand_group(rng, keys, dtypes, n_live, width)
    nat = {k: native.aligned_empty((lanes, width), dtypes[k]) for k in keys}
    ref = {k: np.empty((lanes, width), dtypes[k]) for k in keys}
    assert native.stage_lanes(nat, group, lambda k: fills[k])
    _py_fill(ref, group, fills)
    for k in keys:
        assert nat[k].tobytes() == ref[k].tobytes(), k


def test_stage_lanes_through_the_real_runner_schema():
    """The actual serve column schema end to end: BucketRunner._fill_slot
    with native staging on vs off packs byte-identical scratch from the
    same staged plan (the ONE staging definition)."""
    from anomod.replay import ReplayConfig
    from anomod.serve.batcher import BucketRunner
    cfg = ReplayConfig(n_services=6, n_windows=8, window_us=5_000_000,
                       chunk_size=512)
    r_nat = BucketRunner(cfg, (128, 512), lane_buckets=(1, 2, 4),
                         native_stage=True)
    r_py = BucketRunner(cfg, (128, 512), lane_buckets=(1, 2, 4),
                        native_stage=False)
    group = [r_nat.stage_plan(
        _rand_span_batch(40 + 17 * i, 6, seed=i), 0)[0][1]
        for i in range(3)]
    s_nat, _ = r_nat._fill_slot(128, 4, group)
    s_py, _ = r_py._fill_slot(128, 4, group)
    assert r_nat.native_staged == 1 and r_py.native_staged == 0
    assert set(s_nat) == set(s_py)
    for k in s_nat:
        assert s_nat[k].tobytes() == s_py[k].tobytes(), k
        # the pinned slots are zero-copy-eligible: 64-byte aligned
        assert s_nat[k].ctypes.data % 64 == 0


def test_stage_plan_matrix_fast_path_byte_identical_with_offsets():
    """The matrix-carrier fast path (StagedChunk ptr/stride/m through a
    cached StagePlan) vs the interpreter fill, byte-for-byte — with a
    batch big enough to split into MULTIPLE chunks, so lanes stage from
    non-zero matrix offsets (the ``ptr = mat + 4*lo`` arithmetic) and
    the same plan is reused across calls."""
    from anomod.replay import ReplayConfig
    from anomod.serve.batcher import BucketRunner
    cfg = ReplayConfig(n_services=6, n_windows=8, window_us=5_000_000,
                       chunk_size=256)
    r = BucketRunner(cfg, (64, 256), lane_buckets=(1, 2, 4),
                     native_stage=True)
    # 300 spans -> a 256-chunk plus a 64-chunk (lo=256): both carriers
    plan = r.stage_plan(_rand_span_batch(300, 6, seed=3), 0)
    assert len(plan) == 2 and plan[1][1].ptr != plan[1][1].mat.ctypes.data
    for width, cols in plan:
        group = [cols, cols]
        s_nat, key = r._fill_slot(width, 2, group)
        ref = {k: np.empty((2, width), v.dtype) for k, v in s_nat.items()}
        _py_fill(ref, group, {k: r._pad_fill(k) for k in ref})
        for k in ref:
            assert s_nat[k].tobytes() == ref[k].tobytes(), (k, width)
        assert r._stage_plans[key] is not None    # plan cached + reused
    assert r.native_staged == 2


def test_stage_columns_raw_matches_legacy_per_column_transforms():
    """The fused [7, n] matrix staging must reproduce the original
    independent per-column transforms bit-for-bit (the copyto casts are
    the same C casts astype performed) — the byte-parity bedrock every
    staging path above sits on."""
    from anomod.replay import ReplayConfig, segment_ids, stage_columns_raw
    cfg = ReplayConfig(n_services=6, n_windows=8, window_us=5_000_000,
                       chunk_size=256)
    batch = _rand_span_batch(777, 6, seed=11)
    got = stage_columns_raw(batch, cfg, t0_us=0)
    dur_raw = batch.duration_us.astype(np.float32)
    want = dict(sid=segment_ids(batch, cfg, 0), dur=np.log1p(dur_raw),
                dur_raw=dur_raw, err=batch.is_error.astype(np.float32),
                s5=(batch.status >= 500).astype(np.float32),
                valid=np.ones(batch.n_spans, np.float32),
                tid=batch.trace.astype(np.int32))
    assert list(got) == list(want)
    for k in want:
        assert got[k].dtype == want[k].dtype, k
        assert got[k].tobytes() == want[k].tobytes(), k


def test_stage_lanes_rejects_contract_breakers():
    """Anything off the 4-byte / contiguous / dtype-match contract must
    return False (caller falls back to the interpreter fill) — never
    stage garbage bytes."""
    scratch = {"x": native.aligned_empty((2, 8), np.float64)}
    assert not native.stage_lanes(
        scratch, [{"x": np.zeros(3, np.float64)}], lambda k: 0)
    scratch = {"x": native.aligned_empty((2, 8), np.float32)}
    # dtype mismatch between source and slot
    assert not native.stage_lanes(
        scratch, [{"x": np.zeros(3, np.float64)}], lambda k: 0)
    # live rows wider than the slot
    assert not native.stage_lanes(
        scratch, [{"x": np.zeros(9, np.float32)}], lambda k: 0)


def test_aligned_empty_contract():
    a = native.aligned_empty((3, 5), np.float32)
    assert a.shape == (3, 5) and a.dtype == np.float32
    assert a.flags.c_contiguous and a.ctypes.data % 64 == 0
    b = native.aligned_empty(7, np.int32)
    assert b.shape == (7,) and b.ctypes.data % 64 == 0


def test_stage_lanes_releases_the_gil():
    """The GIL-overlap smoke the pipelined dispatch leans on: a thread
    inside the native staging call must NOT hold the GIL, so another
    Python thread makes progress during it (= staging slot k+1 can
    overlap a dispatch whose python-side bookkeeping is busy, and shard
    workers stage concurrently).

    Protocol: with a long interpreter switch interval, a pure-Python
    main loop can only run during a background stage_lanes call if that
    call released the GIL — so a main-loop timestamp strictly inside a
    call window (with a 25% guard band against pre-entry switches)
    proves the release.  A GIL-holding call makes the window unreachable
    by construction."""
    import sys
    import threading
    import time

    keys = ["sid", "dur", "dur_raw", "err", "s5", "valid", "tid"]
    lanes, width = 8, 1 << 18
    scratch = {k: native.aligned_empty(
        (lanes, width), np.int32 if k in ("sid", "tid") else np.float32)
        for k in keys}
    group = [{k: np.zeros(width, scratch[k].dtype) for k in keys}
             for _ in range(lanes)]
    windows = []

    def stage_loop():
        for _ in range(8):
            t0 = time.perf_counter()
            assert native.stage_lanes(scratch, group, lambda k: 0)
            windows.append((t0, time.perf_counter()))

    old = sys.getswitchinterval()
    sys.setswitchinterval(0.2)
    try:
        bg = threading.Thread(target=stage_loop)
        bg.start()
        # spin at full speed but RECORD at 100us granularity: the
        # guard-banded window interiors are >= 1 ms, so sampling keeps
        # the proof while bounding the list (an unsampled busy-append
        # allocates tens of millions of floats over the bg thread's
        # GIL-stretched lifetime on this 2-core box)
        stamps = []
        last = 0.0
        while bg.is_alive():
            s = time.perf_counter()
            if s - last >= 1e-4:
                stamps.append(s)
                last = s
        bg.join()
    finally:
        sys.setswitchinterval(old)
    overlapped = any(
        any(t0 + 0.25 * (t1 - t0) < s < t1 - 0.25 * (t1 - t0)
            for s in stamps)
        for t0, t1 in windows if t1 - t0 > 0.002)
    assert overlapped, (
        "no main-thread progress inside any native staging window — "
        "stage_lanes appears to hold the GIL")


def test_native_status_reports_health():
    st = native.status()
    assert st["available"] is True
    assert st["build_error"] is None
    assert st["mode"] in ("auto", "on", "off")
    assert st["so_path"] is not None


def test_logscan_cli_skips_lfs_stubs(tmp_path, capsys):
    import json
    from anomod.cli import main
    (tmp_path / "Svc_a.log").write_text(SAMPLE_LOG)
    (tmp_path / "Stub_b.log").write_text(
        "version https://git-lfs.github.com/spec/v1\n"
        "oid sha256:abcd\nsize 12345\n")
    assert main(["logscan", str(tmp_path)]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["n_files"] == 1
    assert doc["n_lfs_stubs"] == 1
    assert doc["totals"]["lines"] == 6
