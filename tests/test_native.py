"""Native C++ scanner: parity with the Python oracle + throughput sanity."""

import numpy as np
import pytest

from anomod.io import native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native lib not built")

SAMPLE_LOG = """2025-11-03 22:02:28 INFO Starting service
2025-11-03 22:02:29 WARN slow response detected
2025-11-03T22:02:30 ERROR connection refused
plain line without level or time
2025-11-03 22:02:31 info lowercase info
NullPointerException at line 42
"""


def _python_oracle(text):
    # bypass the native dispatch inside parse_log_lines
    import importlib
    from anomod.io import logs as logs_io
    orig = native.available
    native.available = lambda: False
    try:
        svc, t, lvl = logs_io.parse_log_lines(text, 0)
    finally:
        native.available = orig
    return t, lvl


def test_scan_log_matches_python():
    levels, ts = native.scan_log(SAMPLE_LOG.encode())
    t_ref, lvl_ref = _python_oracle(SAMPLE_LOG)
    assert levels.shape[0] == lvl_ref.shape[0]
    np.testing.assert_array_equal(levels, lvl_ref)
    np.testing.assert_allclose(np.where(ts == 0, 0, ts), t_ref)


def test_scan_log_levels():
    levels, ts = native.scan_log(SAMPLE_LOG.encode())
    from anomod.schemas import LOG_ERROR, LOG_INFO, LOG_OTHER, LOG_WARN
    assert list(levels) == [LOG_INFO, LOG_WARN, LOG_ERROR, LOG_OTHER,
                            LOG_INFO, LOG_ERROR]
    assert ts[0] > 1.7e9
    assert ts[3] == 0.0


def test_scan_log_multithreaded_large():
    big = (SAMPLE_LOG * 50_000).encode()  # ~18 MB, crosses the MT threshold
    levels, ts = native.scan_log(big, n_threads=4)
    assert levels.shape[0] == 6 * 50_000
    # pattern repeats
    np.testing.assert_array_equal(levels[:6], levels[6:12])


def test_scan_api_jsonl():
    text = b"""{"timestamp": "2025-11-03T22:02:28", "endpoint": "/x", "status_code": 200, "latency_ms": 12.5, "content_length": 512}
{"timestamp": "2025-11-03T22:02:29", "endpoint": "/y", "status_code": 500, "latency_ms": 3001.75, "content_length": 0}
"""
    status, lat, clen = native.scan_api_jsonl(text)
    assert list(status) == [200, 500]
    np.testing.assert_allclose(lat, [12.5, 3001.75])
    assert list(clen) == [512, 0]
