"""LineGraphRCA model contract: shapes, mask/padding invariance, and the
quality-harness wiring (init/apply dispatch, edge_x requirement).

The model's promise is edge-native scoring over STATIC padded shapes:
adding pad rows (mask=False) must not change any service's score, and the
scorer must consume the per-edge feature plane (edge_x) — the quality
sweep's edge_aware path feeds it via rca._apply_model.
"""

import numpy as np
import pytest


def _tiny_inputs(S=5, W=4, Fs=3, Fn=6, E=8, n_real=6, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(S, Fs)).astype(np.float32)
    x_t = rng.normal(size=(S, W, Fn)).astype(np.float32)
    edge_x = rng.normal(size=(E, W, 4)).astype(np.float32)
    src = rng.integers(0, S, E).astype(np.int32)
    dst = ((src + 1 + rng.integers(0, S - 1, E)) % S).astype(np.int32)
    mask = np.arange(E) < n_real
    edge_x[~mask] = 0.0
    return x, x_t, edge_x, src, dst, mask


def _init_and_apply(inputs):
    import jax
    from anomod.models.linegraph import LineGraphRCA
    model = LineGraphRCA()
    params = model.init(jax.random.PRNGKey(0), *inputs)
    return model, params, np.asarray(model.apply(params, *inputs))


def test_scores_shape_and_finite():
    inputs = _tiny_inputs()
    _, _, scores = _init_and_apply(inputs)
    assert scores.shape == (5,)
    assert np.isfinite(scores).all()


def test_pad_rows_do_not_change_scores():
    """Appending masked pad edges (the static-E_max discipline) must leave
    every service score bit-unchanged up to float assoc tolerance."""
    import jax
    from anomod.models.linegraph import LineGraphRCA
    x, x_t, edge_x, src, dst, mask = _tiny_inputs()
    model = LineGraphRCA()
    params = model.init(jax.random.PRNGKey(0), x, x_t, edge_x, src, dst,
                        mask)
    base = np.asarray(model.apply(params, x, x_t, edge_x, src, dst, mask))
    pad = 5
    edge_x2 = np.concatenate(
        [edge_x, np.ones((pad,) + edge_x.shape[1:], np.float32)])
    src2 = np.concatenate([src, np.zeros(pad, np.int32)])
    dst2 = np.concatenate([dst, np.ones(pad, np.int32)])
    mask2 = np.concatenate([mask, np.zeros(pad, bool)])
    padded = np.asarray(model.apply(params, x, x_t, edge_x2, src2, dst2,
                                    mask2))
    np.testing.assert_allclose(padded, base, rtol=1e-5, atol=1e-5)


def test_edge_evidence_reaches_caller_score():
    """Heating ONE out-edge's features must move its caller's score: the
    edge->service evidence path (the model's reason to exist) is live."""
    import jax
    from anomod.models.linegraph import LineGraphRCA
    x, x_t, edge_x, src, dst, mask = _tiny_inputs()
    model = LineGraphRCA()
    params = model.init(jax.random.PRNGKey(0), x, x_t, edge_x, src, dst,
                        mask)
    base = np.asarray(model.apply(params, x, x_t, edge_x, src, dst, mask))
    hot = edge_x.copy()
    hot[0] += 5.0            # edge 0 is real (mask True) with caller src[0]
    moved = np.asarray(model.apply(params, x, x_t, hot, src, dst, mask))
    assert abs(moved[src[0]] - base[src[0]]) > 1e-6


def test_quality_harness_dispatch_requires_edge_x():
    """rca._apply_model('linegraph', ...) without edge_x must raise the
    actionable error, not an obscure KeyError downstream."""
    from anomod.rca import _apply_model
    with pytest.raises(ValueError, match="edge"):
        _apply_model("linegraph", None, None, {"x": np.zeros((1, 2, 3))})


def test_trains_and_discriminates_on_synthetic_link_fault():
    """Micro end-to-end: on a toy corpus where the label is always the
    caller of the one hot edge, a few training steps must rank the culprit
    first for a held-out hot edge — the edge channel LEARNS, not just
    reacts."""
    import jax
    import jax.numpy as jnp
    import optax

    from anomod.models.linegraph import LineGraphRCA
    from anomod.rca import rca_loss

    S, W, E = 5, 4, 10
    rng = np.random.default_rng(1)
    src = np.repeat(np.arange(5, dtype=np.int32), 2)
    dst = ((src + 1) % S).astype(np.int32)
    dst[1::2] = (src[1::2] + 2) % S
    mask = np.ones(E, bool)

    def sample(culprit, seed):
        r = np.random.default_rng(seed)
        x = r.normal(scale=0.1, size=(S, 3)).astype(np.float32)
        x_t = r.normal(scale=0.1, size=(S, W, 6)).astype(np.float32)
        ex = r.normal(scale=0.1, size=(E, W, 4)).astype(np.float32)
        hot = np.where(src == culprit)[0]
        ex[hot, W // 2:, 1:3] += 3.0       # err+lat heat on out-edges
        return x, x_t, ex

    model = LineGraphRCA()
    batches = []
    for i in range(40):
        culprit = i % S
        x, x_t, ex = sample(culprit, seed=i)
        batches.append((x, x_t, ex, culprit))
    stack = {
        "x": jnp.asarray(np.stack([b[0] for b in batches])),
        "x_t": jnp.asarray(np.stack([b[1] for b in batches])),
        "edge_x": jnp.asarray(np.stack([b[2] for b in batches])),
        "edge_src": jnp.asarray(np.tile(src, (40, 1))),
        "edge_dst": jnp.asarray(np.tile(dst, (40, 1))),
        "edge_mask": jnp.asarray(np.tile(mask, (40, 1))),
        "target": jnp.asarray([b[3] for b in batches], jnp.int32),
        "is_anomaly": jnp.ones(40, jnp.float32),
    }
    params = model.init(jax.random.PRNGKey(0), *(
        np.asarray(stack[k][0]) for k in
        ("x", "x_t", "edge_x", "edge_src", "edge_dst", "edge_mask")))

    def apply_batch(p, b):
        return jax.vmap(lambda x, xt, ex, s, d, m:
                        model.apply(p, x, xt, ex, s, d, m))(
            b["x"], b["x_t"], b["edge_x"], b["edge_src"],
            b["edge_dst"], b["edge_mask"])

    tx = optax.adam(3e-3)
    opt_state = tx.init(params)

    @jax.jit
    def step(p, o, b):
        loss, g = jax.value_and_grad(
            lambda pp: rca_loss(apply_batch(pp, b), b))(p)
        up, o = tx.update(g, o, p)
        return optax.apply_updates(p, up), o, loss

    for _ in range(60):
        params, opt_state, _ = step(params, opt_state, stack)
    # held-out sample, unseen seed
    x, x_t, ex = sample(culprit=3, seed=999)
    scores = np.asarray(model.apply(params, x, x_t, ex, src, dst, mask))
    assert int(np.argmax(scores)) == 3, scores


@pytest.mark.slow
def test_train_rca_linegraph_smoke():
    """The CLI training entry accepts the edge-native model: train_rca
    builds the per-edge feature plane (edge_features on, pads edge_x with
    the other edge arrays) and reaches a sane held-out score at easy
    full-severity settings."""
    from anomod.rca import train_rca
    r = train_rca("TT", "linegraph", train_seeds=[0, 1], eval_seeds=[100],
                  epochs=30, n_traces=20)
    assert r.top1 >= 0.7, (r.top1, r.top3)
