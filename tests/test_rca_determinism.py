"""build_dataset must be byte-identical across interpreter processes.

Python string hashes are salted per process (PYTHONHASHSEED), so any
hash()-derived seed breaks the "same dataset bytes" reproducibility claim —
the per-experiment stream must come from anomod.synth._seed_for (sha256).
"""

import hashlib
import subprocess
import sys

_SNIPPET = """
import hashlib
from anomod.rca import _stack, build_dataset
samples, _ = build_dataset("SN", seeds=[0], n_traces=8, n_windows=2)
d = _stack(samples)
h = hashlib.sha256()
for k in sorted(d):
    h.update(k.encode())
    h.update(d[k].tobytes())
print(h.hexdigest())
"""


def _run_fresh_process() -> str:
    r = subprocess.run([sys.executable, "-c", _SNIPPET], timeout=240,
                       capture_output=True, text=True, check=True)
    return r.stdout.strip().splitlines()[-1]


def test_build_dataset_cross_process_determinism():
    a = _run_fresh_process()
    b = _run_fresh_process()
    assert a == b, "build_dataset bytes differ across processes"
    assert len(a) == 64
