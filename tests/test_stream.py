"""Online detection: incremental state parity + alert quality.

The streaming layer's contract is that it is the SAME replay plane fed
incrementally (anomod.replay.make_chunk_step), so parity with the batch
path is exact for order-independent planes (0/1 counts, histogram, HLL
max-merge) and allclose for the f32 moment sums (different chunk
boundaries reorder the additions).
"""

import numpy as np

from anomod import labels, synth
from anomod.replay import ReplayConfig, replay_numpy, stage_columns
from anomod.schemas import SpanBatch, concat_span_batches, take_spans
from anomod.stream import OnlineDetector, StreamReplay, stream_experiment


def _tt_batch(n_traces=40):
    return concat_span_batches([
        synth.generate_spans(l, n_traces=n_traces)
        for l in labels.labels_for_testbed("TT")[:4]])


def test_take_spans_subsets_rows():
    b = _tt_batch(10)
    idx = np.arange(0, b.n_spans, 3)
    sub = take_spans(b, idx)
    assert sub.n_spans == len(idx)
    np.testing.assert_array_equal(sub.service, b.service[idx])
    np.testing.assert_array_equal(sub.start_us, b.start_us[idx])
    assert sub.services == b.services       # side tables kept whole


def test_stream_state_matches_batch_replay():
    batch = _tt_batch()
    cfg = ReplayConfig(n_services=batch.n_services, chunk_size=2048)
    chunks, n = stage_columns(batch, cfg)
    ref = replay_numpy(chunks, cfg)

    t0 = int(batch.start_us.min())
    sr = StreamReplay(cfg, t0, with_hll=True)
    order = np.argsort(batch.start_us, kind="stable")
    batch = take_spans(batch, order)
    # uneven micro-batches: chunk boundaries differ from the batch staging
    cuts = [0, 1000, 1001, 5000, batch.n_spans]
    for lo, hi in zip(cuts, cuts[1:]):
        sr.push(take_spans(batch, slice(lo, hi)))
    assert sr.n_spans == n
    got = np.asarray(sr.state.agg)
    # 0/1 planes + histogram: small-integer f32 sums, order-independent
    np.testing.assert_array_equal(got[:, :3], ref.agg[:, :3])
    np.testing.assert_array_equal(np.asarray(sr.state.hist), ref.hist)
    # moment planes: f32 accumulation order differs -> allclose
    np.testing.assert_allclose(got[:, 3:], ref.agg[:, 3:], rtol=1e-5,
                               atol=1e-3)
    # HLL registers max-merge, exactly order-independent: compare against
    # a second stream fed as ONE batch
    one = StreamReplay(cfg, t0, with_hll=True)
    one.push(batch)
    np.testing.assert_array_equal(np.asarray(sr.state.hll),
                                  np.asarray(one.state.hll))


def test_streaming_detects_and_localizes_kill_fault():
    label = labels.label_for("Svc_Kill_UserTimeline")
    exp = synth.generate_experiment(label, n_traces=300, seed=0)
    det = stream_experiment(exp.spans)
    ranked = det.ranked_services()
    assert ranked and ranked[0] == label.target_service
    onset = 10                               # fault onset 600 s, 60 s windows
    fw = det.first_alert_window(label.target_service)
    assert fw is not None and onset <= fw <= onset + 6


def test_streaming_detects_latency_fault_tt():
    label = labels.label_for("Lv_P_CPU_preserve")
    exp = synth.generate_experiment(label, n_traces=300, seed=0)
    det = stream_experiment(exp.spans)
    ranked = det.ranked_services()
    assert ranked and ranked[0] == label.target_service
    fw = det.first_alert_window(label.target_service)
    assert fw is not None and 10 <= fw <= 16


def test_streaming_quiet_on_normal_baseline():
    exp = synth.generate_experiment(labels.label_for("Normal_Baseline"),
                                    n_traces=300, seed=0)
    det = stream_experiment(exp.spans)
    assert len(det.alerts) <= 2              # no alert storm without a fault


def test_stream_quality_rows():
    from anomod.stream import stream_quality
    rows = stream_quality("SN", n_traces=300,
                          experiments=["Normal_Baseline",
                                       "Svc_Kill_UserTimeline"])
    assert len(rows) == 2
    normal, kill = rows
    assert "top1_hit" not in normal          # no RCA row for the baseline
    assert kill["top1_hit"] and kill["top3_hit"]
    # signed latency: a marginal pre-onset noise alert on the culprit
    # (window 9, onset 10) legitimately reads as -1
    assert -1 <= kill["detection_latency_windows"] <= 6


def _uniform_batch(n_per_window, n_windows, n_services=2, window_us=60_000_000):
    """Healthy constant-rate, constant-latency synthetic stream."""
    rng = np.random.default_rng(0)
    rows = n_per_window * n_windows * n_services
    start = np.repeat(np.arange(n_windows, dtype=np.int64),
                      n_per_window * n_services) * window_us
    start = start + rng.integers(0, window_us, rows)
    svc = np.tile(np.arange(n_services, dtype=np.int32),
                  rows // n_services)
    return SpanBatch(
        trace=np.arange(rows, dtype=np.int32) % 100,
        parent=np.full(rows, -1, np.int32),
        service=svc, endpoint=np.zeros(rows, np.int32),
        start_us=np.sort(start),
        duration_us=rng.integers(900, 1100, rows).astype(np.int64),
        is_error=np.zeros(rows, np.bool_),
        status=np.full(rows, 200, np.int16),
        kind=np.zeros(rows, np.int8),
        services=tuple(f"svc{i}" for i in range(n_services)),
        endpoints=("ep",), trace_ids=tuple(f"t{i}" for i in range(100)),
    ).validate()


def test_finish_does_not_score_empty_trailing_windows():
    """A stream that ends at window 11 of a 32-window grid must not fire
    the drop signal for windows 12..31 (stream end != fleet outage)."""
    batch = _uniform_batch(n_per_window=20, n_windows=12)
    cfg = ReplayConfig(n_services=2, n_windows=32, chunk_size=512)
    det = OnlineDetector(batch.services, cfg, t0_us=0)
    det.push(batch)
    det.finish()
    assert det.alerts == []


def test_ring_rolls_past_grid_and_keeps_detecting():
    """A live stream longer than the window grid keeps scoring: the ring
    evicts old windows, alert indices stay absolute, and a fault at
    window 30 of a 16-window grid is caught."""
    batch = _uniform_batch(n_per_window=20, n_windows=40)
    kill_us = 30 * 60_000_000
    keep = ~((batch.service == 1) & (batch.start_us >= kill_us))
    batch = take_spans(batch, keep)
    cfg = ReplayConfig(n_services=2, n_windows=16, chunk_size=512)
    det = OnlineDetector(batch.services, cfg, t0_us=0)
    # window-sized micro-batches, as a live feed would deliver them
    for w in range(40):
        lo, hi = w * 60_000_000, (w + 1) * 60_000_000
        m = (batch.start_us >= lo) & (batch.start_us < hi)
        det.push(take_spans(batch, m))
    det.finish()
    assert det.replay.window_offset > 0          # the ring really rolled
    dead = [a for a in det.alerts if a.service_name == "svc1"]
    assert dead and dead[0].window in (30, 31)   # absolute indices
    assert not [a for a in det.alerts if a.service_name == "svc0"]


def test_feed_gap_wider_than_grid_no_alert_storm():
    """A collector outage longer than the whole window grid: the anchor
    advances by the FULL gap (spans after the gap bin into their true
    absolute window) and the empty gap windows are skipped as feed
    silence — not scored as a fleet-wide outage."""
    cfg = ReplayConfig(n_services=2, n_windows=16, chunk_size=512)
    healthy = _uniform_batch(n_per_window=20, n_windows=10)
    det = OnlineDetector(healthy.services, cfg, t0_us=0)
    det.push(healthy)
    # 35-window silence, then healthy traffic resumes at window 45
    resumed = _uniform_batch(n_per_window=20, n_windows=2)
    resumed = resumed._replace(start_us=resumed.start_us + 45 * 60_000_000)
    det.push(resumed)
    det.finish()
    assert det.alerts == []                      # no storm from the gap
    # the resumed data landed at its true absolute windows (45, 46)
    assert det.replay.window_offset == 46 - (cfg.n_windows - 1)
    plane = det.replay.agg_plane()
    nonzero_cols = np.nonzero(plane[..., 0].sum(axis=0))[0]
    got_abs = set(int(c) + det.replay.window_offset for c in nonzero_cols)
    assert got_abs == {45, 46}


def test_dependency_aware_ranking_prefers_deepest_anomalous():
    """A gateway whose error spike is explained by its dying callee must
    rank BELOW the callee, even with a louder peak score."""
    label = labels.label_for("Svc_Kill_UserTimeline")
    exp = synth.generate_experiment(label, n_traces=300, seed=0)
    det = stream_experiment(exp.spans)
    ranked = det.ranked_services()
    assert ranked[0] == "user-timeline-service"
    # the gateway still alerted (detection kept its sensitivity)...
    alerted = {a.service_name for a in det.alerts}
    assert "nginx-web-server" in alerted
    # ...but ranks behind the dependency that explains it (structural
    # property of the attribution: anomalous-callee services sort last)
    assert ranked.index("nginx-web-server") > \
        ranked.index("user-timeline-service")
    from anomod.stream import _explained_by_downstream
    anomalous = {a.service for a in det.alerts}
    explained = _explained_by_downstream(det.call_edges, anomalous)
    clean = [det.services.index(n) not in explained for n in ranked]
    assert clean == sorted(clean, reverse=True)   # unexplained first


def test_explained_by_downstream_graph_cases():
    from anomod.stream import _explained_by_downstream as ex
    # direct edge: caller explained by anomalous callee
    assert ex({(0, 1)}, {0, 1}) == {0}
    # chain through a HEALTHY middle hop still explains the caller
    assert ex({(0, 1), (1, 2)}, {0, 2}) == {0}
    # mutual cycle: same SCC -> neither explained (peak order decides)
    assert ex({(0, 1), (1, 0)}, {0, 1}) == set()
    # cycle with a genuinely downstream anomaly: both cycle members explained
    assert ex({(0, 1), (1, 0), (1, 2)}, {0, 1, 2}) == {0, 1}
    # no edges -> nothing explained
    assert ex(set(), {0, 1}) == set()
    # cross-edge DAG: u->v visited via another branch first — u must
    # still see v's transitive anomaly w (memo must be topo-ordered)
    D, u, v, w = 0, 1, 2, 3
    assert ex({(D, u), (D, v), (u, v), (v, w)}, {u, w}) == {u}
    # deep chain (iterative closure, no recursion limit)
    chain = {(i, i + 1) for i in range(3000)}
    assert ex(chain, {0, 3000}) == {0}


def test_multimodal_catches_sparse_kill():
    """The spans-only information floor (a sub-1-span/window service
    killed) is closed by the metric plane: request-rate collapse and
    error-rate series localize media-service directly."""
    from anomod.stream import stream_experiment_multimodal
    label = labels.label_for("Svc_Kill_Media")
    exp = synth.generate_experiment(label, n_traces=300, seed=0)
    span_only = stream_experiment(exp.spans)
    assert span_only.first_alert_window("media-service") is None  # the floor
    det = stream_experiment_multimodal(exp)
    assert det.ranked_services()[0] == "media-service"
    fw = det.first_alert_window("media-service")
    assert fw is not None and 10 <= fw <= 13
    culprit = [a for a in det.alerts if a.service_name == "media-service"]
    assert any(a.evidence in ("metric", "log", "api") for a in culprit)


def test_multimodal_quiet_on_normal():
    from anomod.stream import stream_experiment_multimodal
    exp = synth.generate_experiment(labels.label_for("Normal_Baseline"),
                                    n_traces=300, seed=0)
    det = stream_experiment_multimodal(exp)
    assert len(det.alerts) <= 2


def test_multimodal_state_stays_bounded():
    """The per-window modality planes are pruned as scoring advances —
    a long stream must not accumulate host state without bound."""
    from anomod.schemas import LogBatch
    from anomod.stream import MultimodalDetector
    cfg = ReplayConfig(n_services=2, n_windows=16, chunk_size=512)
    det = MultimodalDetector(("svc0", "svc1"), cfg, t0_us=0, testbed="TT")
    for w in range(40):
        spans = _uniform_batch(n_per_window=20, n_windows=1)
        spans = spans._replace(start_us=spans.start_us + w * 60_000_000)
        t = np.full(10, w * 60.0 + 5.0)
        det.push_logs(LogBatch(service=np.zeros(10, np.int32), t_s=t,
                               level=np.zeros(10, np.int8),
                               services=("svc0", "svc1")))
        det.push(spans)
    det.finish()
    assert len(det._log_tot) <= 4        # pruned, not 40


def test_metric_counter_rateification():
    """A healthy monotone counter (http_requests_total-style) must not
    drift into a false alert: baseline-detected counters are scored on
    window DIFFS."""
    from anomod.stream import MultimodalDetector
    from anomod.schemas import MetricBatch
    cfg = ReplayConfig(n_services=2, n_windows=32, chunk_size=512)
    spans = _uniform_batch(n_per_window=20, n_windows=20)
    det = MultimodalDetector(spans.services, cfg, t0_us=0, testbed="TT")
    # counter series for svc0: +240 per window, forever (healthy rate)
    t = np.arange(0, 20 * 60, 15, dtype=np.float64)
    mb = MetricBatch(
        metric=np.zeros(t.shape[0], np.int32),
        series=np.zeros(t.shape[0], np.int32),
        t_s=t, value=np.cumsum(np.full(t.shape[0], 60.0)),
        metric_names=("http_requests_total",), series_keys=('svc="svc0"',),
        series_service=np.array([0], np.int32), services=spans.services)
    det.push_metrics(mb)
    det.push(spans)
    det.finish()
    assert det.alerts == []
    base = det._mm_base["met"]['http_requests_total|svc="svc0"']
    assert base["counter"]          # detected as a counter


def test_consecutive_zero_rejected():
    import pytest
    cfg = ReplayConfig(n_services=2, n_windows=32)
    with pytest.raises(ValueError, match="consecutive"):
        OnlineDetector(("a", "b"), cfg, t0_us=0, consecutive=0)


def test_gap_breaks_hysteresis_streak():
    """With consecutive=2, hot windows on either side of a feed-silence
    gap are NOT a consecutive run."""
    cfg = ReplayConfig(n_services=2, n_windows=32, chunk_size=512)
    base = _uniform_batch(n_per_window=20, n_windows=9)
    det = OnlineDetector(base.services, cfg, t0_us=0, consecutive=2)
    det.push(base)
    # window 9 hot for svc1 (all errors), window 10 silent, window 11 hot
    hot = _uniform_batch(n_per_window=20, n_windows=1)

    def at(b, w):
        return b._replace(start_us=b.start_us + w * 60_000_000,
                          is_error=(b.service == 1),
                          status=np.where(b.service == 1, 500,
                                          b.status).astype(np.int16))
    det.push(at(hot, 9))
    det.push(at(hot, 11))
    det.finish()
    assert det.alerts == []          # 9 and 11 are separated by silence


def test_uncalibrated_service_does_not_false_alert():
    """A service with no baseline traffic must not alert on its first busy
    window (its mu/var would be fabricated) — but its drop signal stays
    off too (nothing to drop from)."""
    batch = _uniform_batch(n_per_window=20, n_windows=14)
    late = (batch.service == 1) & (batch.start_us < 10 * 60_000_000)
    batch = take_spans(batch, ~late)             # svc1 exists only from w10
    cfg = ReplayConfig(n_services=2, n_windows=32, chunk_size=512)
    det = OnlineDetector(batch.services, cfg, t0_us=0)
    det.push(batch)
    det.finish()
    assert not [a for a in det.alerts if a.service_name == "svc1"]


def test_sharded_stream_replay_matches_single_chip():
    """The mesh-sharded streaming plane (psum-merged per-push deltas over
    the 8-device CPU mesh) is numerically interchangeable with the
    single-chip StreamReplay, and the detector runs on it unchanged."""
    from anomod.parallel import make_mesh
    from anomod.parallel.stream import ShardedStreamReplay

    label = labels.label_for("Svc_Kill_UserTimeline")
    exp = synth.generate_experiment(label, n_traces=200, seed=0)
    batch = exp.spans
    cfg = ReplayConfig(n_services=batch.n_services, chunk_size=1024)
    order = np.argsort(batch.start_us, kind="stable")
    batch = take_spans(batch, order)
    t0 = int(batch.start_us.min())

    single = StreamReplay(cfg, t0)
    mesh = make_mesh()
    sharded = ShardedStreamReplay(cfg, t0, mesh)
    cuts = [0, 3000, 3001, 9000, batch.n_spans]
    for lo, hi in zip(cuts, cuts[1:]):
        mb = take_spans(batch, slice(lo, hi))
        assert single.push(mb) == sharded.push(mb)
    assert sharded.n_spans == single.n_spans
    np.testing.assert_array_equal(np.asarray(sharded.state.hist),
                                  np.asarray(single.state.hist))
    np.testing.assert_allclose(np.asarray(sharded.state.agg),
                               np.asarray(single.state.agg),
                               rtol=1e-5, atol=1e-3)

    # the full detector stack over the mesh: same culprit
    det = OnlineDetector(batch.services, cfg, t0,
                         replay=ShardedStreamReplay(cfg, t0, mesh))
    for lo, hi in zip(cuts, cuts[1:]):
        det.push(take_spans(batch, slice(lo, hi)))
    det.finish()
    assert det.first_alert_window(label.target_service) is not None


def test_ring_random_jumps_match_absolute_accumulator():
    """Property test for the ring math: arbitrary monotone window jumps
    (including gaps wider than the grid) must leave every retained ring
    column equal to a naive absolute-window accumulator."""
    rng = np.random.default_rng(7)
    W, S = 8, 2
    cfg = ReplayConfig(n_services=S, n_windows=W, chunk_size=256)
    sr = StreamReplay(cfg, t0_us=0)
    truth = {}                      # abs window -> [S] span counts
    w_abs = 0
    for _ in range(25):
        w_abs += int(rng.integers(0, 14))      # jumps 0..13 (> grid ok)
        n = int(rng.integers(1, 30))
        svc = rng.integers(0, S, n).astype(np.int32)
        start = (np.full(n, w_abs, np.int64) * cfg.window_us
                 + rng.integers(0, cfg.window_us, n))
        batch = SpanBatch(
            trace=np.zeros(n, np.int32), parent=np.full(n, -1, np.int32),
            service=svc, endpoint=np.zeros(n, np.int32),
            start_us=np.sort(start),
            duration_us=np.full(n, 1000, np.int64),
            is_error=np.zeros(n, np.bool_),
            status=np.full(n, 200, np.int16), kind=np.zeros(n, np.int8),
            services=("a", "b"), endpoints=("e",), trace_ids=("t",),
        )
        got_w = sr.push(batch)
        assert got_w == w_abs       # true absolute window, post-roll
        t = truth.setdefault(w_abs, np.zeros(S))
        np.add.at(t, svc, 1.0)
    plane = sr.agg_plane()          # [S, W, F]
    for col in range(W):
        w = sr.window_offset + col
        expect = truth.get(w, np.zeros(S))
        np.testing.assert_array_equal(plane[:, col, 0], expect)


def test_cusum_resets_on_recovery():
    """No lingering 'still down' alerts once traffic returns: the CUSUM
    run resets at the first window back at the baseline rate."""
    batch = _uniform_batch(n_per_window=20, n_windows=24)
    outage = ((batch.service == 1)
              & (batch.start_us >= 10 * 60_000_000)
              & (batch.start_us < 14 * 60_000_000))
    cfg = ReplayConfig(n_services=2, n_windows=32, chunk_size=512)
    det = OnlineDetector(batch.services, cfg, t0_us=0)
    det.push(take_spans(batch, ~outage))
    det.finish()
    dead = [a.window for a in det.alerts if a.service_name == "svc1"]
    assert dead and min(dead) in (10, 11)        # outage caught
    assert max(dead) <= 14                       # nothing after recovery


def test_detector_flags_throughput_drop():
    """A service that stops emitting after window 9 alerts via z_drop."""
    batch = _uniform_batch(n_per_window=20, n_windows=12)
    keep = ~((batch.service == 1) & (batch.start_us >= 10 * 60_000_000))
    cfg = ReplayConfig(n_services=2, n_windows=32, chunk_size=512)
    det = OnlineDetector(batch.services, cfg, t0_us=0)
    det.push(take_spans(batch, keep))
    det.finish()
    dead = [a for a in det.alerts if a.service_name == "svc1"]
    assert dead and dead[0].window in (10, 11)
    assert dead[0].z_drop >= det.z_threshold
    assert not [a for a in det.alerts if a.service_name == "svc0"]


# -- edge-locus attribution (the out-edge plane) ---------------------------


def test_edge_ids_self_vs_cross_vs_missing():
    """Slot mapping: cross spans key to the CALLER's out-edge slot 2S+p;
    roots / own-parented spans (and every span when parent info is
    absent) key to their service's self-edge slot S+c."""
    cfg = ReplayConfig(n_services=3, n_windows=16, chunk_size=256)
    det = OnlineDetector(("a", "b", "c"), cfg, t0_us=0)
    S = 3
    svc = np.array([0, 1, 2, 1], np.int32)
    psvc = np.array([-1, 0, 1, 1], np.int32)   # root, a->b, b->c, self b
    got = det._edge_ids(svc, psvc)
    assert got.tolist() == [S + 0, 2 * S + 0, 2 * S + 1, S + 1]
    assert det._edge_ids(svc, None).tolist() == [S + 0, S + 1, S + 2, S + 1]


def test_edge_mode_node_alerts_match_node_only_detector():
    """The combined id space must not change NODE behavior: the node rows
    see the same spans with the same binning, so the non-edge alert
    stream is identical to an edge_attribution=False detector's."""
    label = labels.label_for("Lv_P_CPU_preserve")
    exp = synth.generate_experiment(label, n_traces=200, seed=3)
    det_on = stream_experiment(exp.spans)
    det_off = stream_experiment(exp.spans, edge_attribution=False)
    node_on = [a for a in det_on.alerts if a.evidence != "edge"]
    assert [(a.window, a.service, a.evidence, round(a.score, 6))
            for a in node_on] == \
           [(a.window, a.service, a.evidence, round(a.score, 6))
            for a in det_off.alerts]


def test_edge_locus_fault_attributed_to_caller():
    """A link fault (callee-side degradation of the culprit's outgoing
    calls, anomod/synth.py fault_locus='edge') leaves every node-scoped
    statistic of the culprit healthy — only the out-edge plane names it.
    The detector must rank the CALLER first with evidence='edge'."""
    label = labels.label_for("Lv_C_travel_detail_failure")
    hard = synth.HardMode(severity=1.0, noise=0.0, fault_locus="edge")
    exp = synth.generate_experiment(label, n_traces=400, seed=0, hard=hard)
    det = stream_experiment(exp.spans)
    ranked = det.ranked_services()
    assert ranked and ranked[0] == label.target_service
    edge_alerts = [a for a in det.alerts if a.evidence == "edge"]
    assert any(a.service_name == label.target_service for a in edge_alerts)
    # propagated errors legitimately heat ancestor out-slots too (failed
    # callee spans error their parents' entry spans, which ride the
    # grandparent's out-edge slot) — the CULPRIT must carry the max
    tgt = list(det.services).index(label.target_service)
    assert det._edge_hot[tgt] == max(det._edge_hot.values())
    # detection latency through the edge plane stays bounded (pooled
    # windows add a few windows over the node path's 0-4)
    fw = det.first_alert_window(label.target_service)
    assert fw is not None and 10 <= fw <= 10 + det.edge_pool


def test_edge_locus_attribution_survives_sparse_density():
    """The sparse-density fix (mass-based two-scale pooling + shrunk
    empirical-Bayes edge baselines + exact-binomial error tail): at the
    offline sweep's knobs (60 traces, severity 0.3, noise 0.5) an
    edge-locus fault whose out-edge baseline holds only a handful of
    spans must still be attributed to the caller — the old fixed-width
    pool with the hard C0 gate scored these rows 0 (docs/BENCHMARKS.md's
    0.17 collapse)."""
    label = labels.label_for("Lv_C_travel_detail_failure")
    hard = synth.HardMode(severity=0.3, noise=0.5, fault_locus="edge")
    exp = synth.generate_spans(label, n_traces=60, seed=0, hard=hard)
    det = stream_experiment(exp)
    edge_alerts = [a for a in det.alerts if a.evidence == "edge"]
    assert any(a.service_name == label.target_service
               for a in edge_alerts), \
        [(a.service_name, a.evidence) for a in det.alerts]
    assert det.ranked_services()[0] == label.target_service


def test_sparse_normal_has_no_edge_alerts():
    """The liberalized sparse-edge path (borrowed baselines, dominance
    tier) must not buy its sensitivity with normal-baseline false
    alerts: a healthy sparse stream produces ZERO edge-evidence
    alerts."""
    label = labels.label_for("Normal_case")
    hard = synth.HardMode(severity=0.3, noise=0.5)
    exp = synth.generate_spans(label, n_traces=60, seed=0, hard=hard)
    det = stream_experiment(exp)
    assert not [a for a in det.alerts if a.evidence == "edge"]


def test_node_fault_not_misattributed_to_caller():
    """Under a NODE fault the culprit's self-edge goes hot, so the
    callee-self-hot guard must suppress out-edge blame on its callers:
    the culprit still ranks first and no caller outranks it via edge
    evidence."""
    label = labels.label_for("Lv_P_CPU_preserve")
    exp = synth.generate_experiment(label, n_traces=300, seed=0)
    det = stream_experiment(exp.spans)
    ranked = det.ranked_services()
    assert ranked and ranked[0] == label.target_service
    tgt = list(det.services).index(label.target_service)
    assert det._self_hot[tgt]                 # locus discriminator fired


def test_sharded_edge_attribution_matches_single_chip():
    """Edge attribution over the mesh: an injected ShardedStreamReplay
    built on the COMBINED id space (edge_combined_cfg) runs the full
    edge-alerting stack, and the alert stream matches the single-chip
    edge detector's on an edge-locus corpus."""
    from anomod.parallel import make_mesh
    from anomod.parallel.stream import ShardedStreamReplay
    from anomod.stream import (edge_combined_cfg, resolve_parent_services,
                               stream_experiment)

    label = labels.label_for("Lv_C_travel_detail_failure")
    hard = synth.HardMode(severity=1.0, noise=0.0, fault_locus="edge")
    exp = synth.generate_spans(label, n_traces=300, seed=0, hard=hard)
    cfg = ReplayConfig(n_services=exp.n_services, chunk_size=1024)
    psvc = resolve_parent_services(exp)
    order = np.argsort(exp.start_us, kind="stable")
    batch, psvc = take_spans(exp, order), psvc[order]
    t0 = int(batch.start_us.min())
    edges = set(zip(batch.service[batch.parent[batch.parent >= 0]].tolist(),
                    batch.service[batch.parent >= 0].tolist()))

    mesh = make_mesh()
    combined = edge_combined_cfg(cfg, batch.n_services)
    det_mesh = OnlineDetector(
        batch.services, cfg, t0, call_edges=edges,
        replay=ShardedStreamReplay(combined, t0, mesh),
        edge_attribution=True)
    det_one = OnlineDetector(batch.services, cfg, t0, call_edges=edges)
    cuts = [0, 4000, 11000, batch.n_spans]
    for lo, hi in zip(cuts, cuts[1:]):
        sl = slice(lo, hi)
        det_mesh.push(take_spans(batch, sl), parent_service=psvc[sl])
        det_one.push(take_spans(batch, sl), parent_service=psvc[sl])
    det_mesh.finish(); det_one.finish()
    key = [(a.window, a.service, a.evidence) for a in det_one.alerts]
    assert [(a.window, a.service, a.evidence)
            for a in det_mesh.alerts] == key
    assert any(a.evidence == "edge" for a in det_mesh.alerts)
    assert det_mesh.ranked_services()[0] == label.target_service
    # a node-keyed injected replay with edge_attribution=True is rejected
    # with the combined-cfg hint
    import pytest
    with pytest.raises(ValueError, match="3\\*S"):
        OnlineDetector(batch.services, cfg, t0,
                       replay=ShardedStreamReplay(cfg, t0, mesh),
                       edge_attribution=True)


def test_rank_tier_demotes_isolated_single_plane_decoy(monkeypatch):
    """Plane-corroboration reorder (round 5): an edge-dominant caller
    bubbles above services whose entire evidence is a single non-span
    plane — UNLESS the per-pair concentration discriminator says the
    caller's heat is blast pointing at one callee, in which case that
    callee keeps its rank (the node-culprit reading)."""
    import numpy as np

    from anomod.replay import ReplayConfig
    from anomod.stream import Alert, MultimodalDetector

    services = ("caller", "decoy", "victim", "other")
    cfg = ReplayConfig(n_services=4, n_windows=16)

    def make_det():
        det = MultimodalDetector(services, cfg, t0_us=0,
                                 call_edges={(0, 2), (0, 3)})
        det.edge_attribution = True
        det._self_hot = np.zeros(4, bool)
        det._edge_hot = {0: 6.0}          # caller is edge-dominant
        det.alerts.extend([
            alert(0, 10, 3.0, "edge"), alert(0, 11, 3.0, "edge"),
            # single-plane log evidence, louder than the edge z
            alert(1, 10, 8.0, "log"),
            alert(2, 10, 9.0, "log"), alert(2, 11, 9.0, "log"),
        ])
        return det

    def alert(svc, w, score, evidence):
        return Alert(window=w, service=svc, service_name=services[svc],
                     score=score, z_latency=0.0, z_error=0.0, z_drop=0.0,
                     evidence=evidence)

    monkeypatch.delenv("ANOMOD_RANK_TIER", raising=False)

    # SPREAD heat across the caller's pairs (the link-fault signature):
    # every single-plane service is demoted below the caller, sustained
    # or not — a sustained decoy is observationally identical
    det = make_det()
    S = 4
    det._pair_base = {0 * S + 2: [20.0, 100.0, 0.0],
                      0 * S + 3: [20.0, 100.0, 0.0]}
    det._pair_anom = {0 * S + 2: [20.0, 140.0, 2.0],
                      0 * S + 3: [20.0, 138.0, 2.0]}
    ranked = det.ranked_services()
    assert ranked[0] == "caller", ranked

    # CONCENTRATED heat on one callee (blast pointing at a node
    # culprit): that callee is exempt and keeps its magnitude rank;
    # the unrelated decoy is still demoted
    det = make_det()
    det._pair_base = {0 * S + 2: [20.0, 100.0, 0.0],
                      0 * S + 3: [20.0, 100.0, 0.0]}
    det._pair_anom = {0 * S + 2: [20.0, 170.0, 4.0],
                      0 * S + 3: [20.0, 101.0, 0.0]}
    ranked = det.ranked_services()
    assert ranked[0] == "victim", ranked
    # the caller yields (explained by the node-borne victim downstream);
    # explained services rank last by the standing convention, so the
    # decoy's relative spot vs the caller is not asserted here

    # tier disabled: raw magnitudes win back their spots
    monkeypatch.setenv("ANOMOD_RANK_TIER", "0")
    ranked0 = det.ranked_services()
    assert ranked0.index("decoy") < ranked0.index("caller")


def test_pair_accumulators_via_push_drive_verdict():
    """End-to-end pair plumbing: spans pushed with parent_service land in
    the right (caller*S+callee) keys with the baseline/anomalous phase
    split on the frozen t0 grid, and _pair_verdict reads concentration
    out of them."""
    import numpy as np

    from anomod.replay import ReplayConfig
    from anomod.schemas import SpanBatch
    from anomod.stream import OnlineDetector

    services = ("caller", "c1", "c2")
    S = 3
    w_us = 1_000_000
    cfg = ReplayConfig(n_services=S, n_windows=16, window_us=w_us)
    det = OnlineDetector(services, cfg, t0_us=0, baseline_windows=4)

    def batch(windows, svc, dur_us):
        n = len(windows)
        start = np.asarray(windows, np.int64) * w_us + 1000
        return SpanBatch(
            trace=np.zeros(n, np.int32), parent=np.zeros(n, np.int32) - 1,
            service=np.full(n, svc, np.int32),
            endpoint=np.zeros(n, np.int32),
            start_us=start,
            duration_us=np.full(n, dur_us, np.int64),
            is_error=np.zeros(n, bool),
            status=np.full(n, 200, np.int16),
            kind=np.zeros(n, np.int8),
            services=services, endpoints=("e",),
            trace_ids=("t",))

    # baseline phase (windows 0-3): both pairs healthy at 10ms
    for c in (1, 2):
        b = batch([0, 0, 0, 1, 1, 2, 2, 3], c, 10_000)
        det.push(b, parent_service=np.zeros(b.n_spans, np.int32))
    # anomalous phase: c1's pair heats 20x, c2 stays flat
    b = batch([8, 8, 8, 9, 9, 10], 1, 200_000)
    det.push(b, parent_service=np.zeros(b.n_spans, np.int32))
    b = batch([8, 8, 9, 9, 10, 10], 2, 10_000)
    det.push(b, parent_service=np.zeros(b.n_spans, np.int32))

    assert set(det._pair_base) == {0 * S + 1, 0 * S + 2}
    assert det._pair_base[1][0] == 8.0          # n spans in baseline
    assert det._pair_anom[1][0] == 6.0
    assert det._pair_verdict(0) == ("concentrated", 1)
    # heat c2's pair too (strongly enough to overcome its earlier
    # healthy anomalous-phase spans) -> spread
    for ws in ([11, 11, 11, 12, 12, 12], [13, 13, 13, 13, 13, 13],
               [14, 14, 14, 14, 14, 14]):
        b = batch(ws, 2, 200_000)
        det.push(b, parent_service=np.zeros(b.n_spans, np.int32))
    assert det._pair_verdict(0) == ("spread", -1)
