"""SN API monitor subsystem: endpoint surface, body synthesis, chaos
conditioning, artifact family."""

import json

import numpy as np
import pytest

from anomod.io.api import load_api_jsonl
from anomod.monitor import (SN_ENDPOINTS, ActiveMonitor, PassiveMonitor,
                            capture_openapi_responses, synthesize_body)


def test_endpoint_surface():
    # the reference's 12 wrk2-api endpoints, POST iff
    # register/login/compose/upload/follow/unfollow
    assert len(SN_ENDPOINTS) == 12
    for method, path, owner in SN_ENDPOINTS:
        # the reference's method rule (enhanced_openapi_monitor.py:104)
        is_post = any(k in path for k in ("register", "login", "compose",
                                          "upload", "follow", "unfollow"))
        assert (method == "POST") == is_post, path
        assert owner.endswith("-service") or owner.endswith("-server")


def test_body_synthesis_contract():
    reg = synthesize_body("/wrk2-api/user/register", 7)
    assert reg["username"] == "testuser_7" and reg["user_id"] == 7
    login = synthesize_body("/wrk2-api/user/login", 1)
    assert set(login) == {"username", "password"}
    comp = synthesize_body("/wrk2-api/post/compose", 2)
    assert comp["post_type"] == 0 and comp["media_ids"] == []
    assert synthesize_body("/wrk2-api/media/upload", 3) == {}
    assert synthesize_body("/wrk2-api/home-timeline/read", 4) is None


def test_active_monitor_covers_all_endpoints():
    report = ActiveMonitor(seed=0).run(cycles=5)
    assert report.mode == "active"
    # connectivity pre-check probes + 5 cycles x 12 endpoints
    assert report.batch.n_records == 12 + 5 * 12
    paths = {e.split(" ", 1)[1] for e in report.batch.endpoints}
    assert paths == {p for _, p, _ in SN_ENDPOINTS}
    assert all(report.connectivity.values())


def test_passive_monitor_limits_to_three_gets():
    report = PassiveMonitor(seed=0).run(cycles=4)
    assert report.mode == "passive"
    # pre-check covers all 12; cycles only the first 3 endpoints, GET-only
    assert report.batch.n_records == 12 + 4 * 3
    assert not any(e.startswith("POST ") for e in report.batch.endpoints)
    # only the first 3 endpoints accumulate cycle traffic (the other 9 see
    # exactly their single pre-check probe)
    counts = np.bincount(report.batch.endpoint,
                         minlength=len(report.batch.endpoints))
    assert sorted(counts.tolist(), reverse=True)[:3] == [5, 5, 5]
    assert sorted(counts.tolist(), reverse=True)[3:] == [1] * 9


def test_monitor_determinism():
    a = ActiveMonitor(seed=3).run(cycles=3).batch
    b = ActiveMonitor(seed=3).run(cycles=3).batch
    np.testing.assert_array_equal(a.status, b.status)
    np.testing.assert_allclose(a.latency_ms, b.latency_ms)


def test_chaos_conditions_monitor_traffic():
    from anomod.chaos import ChaosController
    ctl = ChaosController()
    ctl.create("Svc_Kill_UserTimeline")  # service-level fault, SN testbed
    try:
        faulted = ActiveMonitor(seed=1, controller=ctl).run(cycles=30).batch
    finally:
        ctl.destroy_all()
    clean = ActiveMonitor(seed=1).run(cycles=30).batch
    assert (faulted.status >= 500).mean() > (clean.status >= 500).mean()


def test_capture_orchestrator_artifacts(tmp_path):
    report = capture_openapi_responses(tmp_path, mode="active", cycles=4,
                                       seed=0, chaos=None)
    for name in ("openapi_responses.jsonl", "response_summary.json",
                 "endpoint_performance.json", "status_code_distribution.csv",
                 "traffic_analysis.json", "collection_report.json"):
        assert (tmp_path / name).exists(), name
    batch = load_api_jsonl(tmp_path / "openapi_responses.jsonl")
    assert batch.n_records == report.batch.n_records
    doc = json.loads((tmp_path / "collection_report.json").read_text())
    assert doc["mode"] == "active" and len(doc["endpoints_monitored"]) == 12
    analysis = json.loads((tmp_path / "traffic_analysis.json").read_text())
    assert "POST" in analysis["method_distribution"]
    assert analysis["total_requests"] == report.batch.n_records


def test_monitor_cli(capsys):
    from anomod.cli import main
    assert main(["monitor", "--mode", "passive", "--cycles", "2"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["mode"] == "passive"
    assert doc["requests"] == 12 + 2 * 3
