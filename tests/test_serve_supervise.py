"""Chaos-hardened serving: shard supervision, deterministic
checkpoint/restore, and the no-score-gap recovery contract
(anomod.serve.supervise + anomod.serve.chaos, ISSUE-10).

The central pin: a seeded run with scripted mid-tick shard faults —
worker crashes, score-path exceptions at every phase, state-pool
failures — recovers through checkpoint restore + deterministic
re-execution to states, alerts, SLO and shed BYTE-identical to the
fault-free run of the same seed, with equal canonical flight journals
(`anomod audit diff` semantics).  Tier-1 covers every phase and the
degradation paths on compact configs; the exhaustive
phase × shards × pipeline × residency cross runs under ``-m slow``.
"""

import dataclasses
import threading
import warnings

import numpy as np
import pytest

from anomod.obs.flight import diff_journals
from anomod.serve.engine import (SHARD_VARIANT_REPORT_FIELDS, ServeEngine,
                                 run_power_law)

#: the compact seeded scenario every test in this module compares on —
#: small enough for tier-1, long enough that alerts fire (window 2 s,
#: fault onset 12 s) and several checkpoints land (cadence 4 over 20
#: ticks), so every canonical plane is LIVE when recovery re-executes
KW = dict(n_tenants=6, n_services=4, capacity_spans_per_s=1000,
          overload=2.0, duration_s=20, tick_s=1.0, seed=5,
          window_s=2.0, baseline_windows=4, fault_tenants=1,
          buckets=(64, 256), lane_buckets=(1, 2, 4), max_backlog=1500,
          n_windows=16, flight_digest_every=4, ckpt_every=4)

#: a script that exercises EVERY score-path phase across both shards of
#: a 2-shard engine (shard ids clamp to 0 on the inline engine), plus a
#: stall (output-neutral) — one run, five recoveries
ALL_PHASE_SCRIPT = ("crash@6:shard=0:phase=dispatch;"
                    "except@9:shard=1:phase=score;"
                    "poolput@12:shard=0;"
                    "except@15:shard=1:phase=commit;"
                    "crash@17:shard=0:phase=stage;"
                    "stall@10:shard=0:ms=1")

#: report fields that legitimately differ between a fault-free and a
#: recovered run (the recovery counters + the wall legs already in the
#: shard-variant list)
RECOVERY_REPORT_FIELDS = ("n_shard_crashes", "n_respawns",
                          "n_restored_ticks", "n_quarantined",
                          "n_migrated_tenants")


@pytest.fixture(scope="module")
def reference():
    """ONE fault-free 2-shard reference run: tenant bits, SLO, shed and
    the canonical journal are pinned shard/pipeline/residency-invariant
    by PRs 5/8/9, so this single run is the valid reference for every
    configuration in the module."""
    eng, rep = run_power_law(shards=2, pipeline=2, **KW)
    return eng, rep, eng.flight_recorder.journal()


def assert_no_score_gap(reference, eng, rep, journal=True,
                        extra_skip=()):
    """The no-score-gap contract: byte-identical tenant states + alert
    streams, identical SLO/shed and report decision fields, equal
    canonical flight journals.  ``extra_skip`` names report fields the
    comparison legitimately ignores (e.g. ``serve_state`` when the two
    legs run different residencies — the decisions are pinned identical
    anyway)."""
    ref_eng, ref_rep, ref_journal = reference
    tids = sorted(set(ref_eng._tenant_det) | set(eng._tenant_det))
    assert tids == sorted(ref_eng._tenant_det)
    for tid in tids:
        assert [dataclasses.asdict(a) for a in ref_eng.alerts_for(tid)] \
            == [dataclasses.asdict(a) for a in eng.alerts_for(tid)], \
            f"tenant {tid} alert stream diverges"
        s1 = ref_eng._tenant_replay[tid].state
        s2 = eng._tenant_replay[tid].state
        assert np.array_equal(np.asarray(s1.agg), np.asarray(s2.agg)), \
            f"tenant {tid} agg plane diverges"
        assert np.array_equal(np.asarray(s1.hist), np.asarray(s2.hist)), \
            f"tenant {tid} hist plane diverges"
    skip = set(SHARD_VARIANT_REPORT_FIELDS) \
        | set(RECOVERY_REPORT_FIELDS) | set(extra_skip)
    a = {k: v for k, v in ref_rep.to_dict().items() if k not in skip}
    b = {k: v for k, v in rep.to_dict().items() if k not in skip}
    assert a == b, sorted(k for k in a if a[k] != b[k])
    if journal:
        d = diff_journals(ref_journal, eng.flight_recorder.journal())
        assert d is None, d


def test_recovery_every_phase_sharded(reference):
    """Crashes at every phase (stage/dispatch/fold/score/commit — the
    dispatch one a worker KILL with live in-flight dispatches, the fold
    one a pool-put failure) spread over both shards of a 2-shard
    pipelined engine recover with no score gap — and the whole recovery
    surface lands in the metrics registry (the OBSERVABILITY.md
    catalog rows)."""
    from anomod import obs
    from anomod.obs.registry import Registry, set_registry
    reg = Registry(enabled=True)
    prev = set_registry(reg)
    try:
        eng, rep = run_power_law(shards=2, pipeline=2,
                                 chaos=ALL_PHASE_SCRIPT, **KW)
        for name, want in (
                ("anomod_serve_chaos_injected_total", 6),  # + the stall
                ("anomod_serve_chaos_stalls_total", 1),
                ("anomod_serve_shard_crashes_total", 5),
                ("anomod_serve_shard_respawns_total", 2),
                ("anomod_serve_ckpt_total", rep.n_checkpoints),
                ("anomod_serve_restored_ticks_total",
                 rep.n_restored_ticks)):
            assert obs.counter(name).value == want, name
        assert obs.counter(
            "anomod_serve_recovery_seconds_total").value > 0
    finally:
        set_registry(prev)
    assert rep.n_shard_crashes == 5          # the stall never crashes
    assert rep.n_respawns == 2               # exactly the two kills
    assert rep.n_restored_ticks >= 5
    assert rep.n_quarantined == 0 and rep.n_migrated_tenants == 0
    assert_no_score_gap(reference, eng, rep)


def test_recovery_every_phase_inline_host_depth1(reference):
    """The same five-phase campaign on the INLINE 1-shard engine (no
    worker threads: crashes surface as plain exceptions, recovery
    restores + re-executes on the coordinator) — run on the HOST state
    seam at pipeline depth 1, so restore goes through host set_state
    instead of the pool scatter and re-execution has no in-flight
    window.  With the sharded/device/depth-2 test above, every
    matrix axis is covered in tier-1; the full cross runs under
    ``-m slow``."""
    eng, rep = run_power_law(shards=1, pipeline=1, state="host",
                             chaos=ALL_PHASE_SCRIPT.replace("shard=1",
                                                            "shard=0"),
                             **KW)
    assert rep.n_shard_crashes == 5
    assert rep.n_respawns == 0               # nothing to respawn inline
    assert_no_score_gap(reference, eng, rep,
                        extra_skip={"serve_state"})


@pytest.mark.slow
@pytest.mark.parametrize("phase", ["stage", "dispatch", "fold", "score",
                                   "commit"])
@pytest.mark.parametrize("shards", [1, 2])
@pytest.mark.parametrize("pipeline", [1, 2, 3])
@pytest.mark.parametrize("state", ["host", "device"])
def test_recovery_matrix(reference, phase, shards, pipeline, state):
    """The exhaustive recovery matrix: a worker kill at every score
    phase × 1-vs-2 shards × pipeline depths 1–3 × host-vs-device
    residency ⇒ byte-identical to fault-free (the compact tier-1 tests
    above cover every axis; this cross pins every combination)."""
    eng, rep = run_power_law(
        shards=shards, pipeline=pipeline, state=state,
        chaos=f"crash@6:shard=0:phase={phase};"
              f"except@13:shard={shards - 1}:phase={phase}", **KW)
    assert rep.n_shard_crashes == 2
    assert_no_score_gap(reference, eng, rep,
                        extra_skip={"serve_state"} if state == "host"
                        else ())


def test_unfused_engine_fires_and_recovers_every_phase_kind():
    """The unfused path has no phase structure, but a scripted fault at
    ANY phase must still fire (collapsed onto the slice boundaries) —
    a silently never-injected fault would read as 'survived'."""
    kw = {**KW, "duration_s": 12, "fault_tenants": 0}
    e0, r0 = run_power_law(shards=1, fuse=False, **kw)
    eng, rep = run_power_law(
        shards=1, fuse=False,
        chaos="crash@4;except@6:phase=fold;poolput@8;"
              "except@9:phase=commit;stall@5:ms=1", **kw)
    assert eng._chaos.n_injected == 5
    assert rep.n_shard_crashes == 4          # all but the stall
    for tid in e0._tenant_replay:
        s1 = e0._tenant_replay[tid].state
        s2 = eng._tenant_replay[tid].state
        assert np.array_equal(np.asarray(s1.agg), np.asarray(s2.agg))
    assert diff_journals(e0.flight_recorder.journal(),
                         eng.flight_recorder.journal()) is None


def test_chaos_off_supervised_byte_identical_to_unsupervised(reference):
    """Supervision is pure reads on the happy path: a chaos-off
    SUPERVISED run (the new default) is byte-identical to the same run
    with supervision off (the exact PR-9 engine) — decisions, report
    and canonical journal."""
    eng, rep = run_power_law(shards=2, pipeline=2, ckpt_every=0,
                             **{k: v for k, v in KW.items()
                                if k != "ckpt_every"})
    assert rep.supervised is False and rep.n_checkpoints == 0
    ref_eng, ref_rep, _ = reference
    assert ref_rep.supervised is True and ref_rep.n_checkpoints > 0
    assert_no_score_gap(reference, eng, rep,
                        extra_skip={"supervised", "ckpt_every",
                                    "n_checkpoints"})


def test_unsupervised_chaos_propagates():
    """ckpt_every=0 disables recovery: the first injected fault fails
    the tick exactly like any shard error before supervision existed."""
    from anomod.serve.chaos import ChaosFault
    with pytest.raises(ChaosFault):
        run_power_law(shards=1, chaos="except@6:shard=0",
                      **{**KW, "ckpt_every": 0})


def test_quarantine_after_k_consecutive_failures():
    """A slice that kills its shard ``retries`` consecutive times is
    QUARANTINED (dropped, counted, journaled in the variant tier) and
    the shard recovers without it — never retried forever.  The
    quarantined spans are a real score gap, so the canonical journal
    must NOT be claimed equal; everything else keeps serving."""
    eng, rep = run_power_law(
        shards=2, chaos="except@8:shard=1:phase=dispatch:repeat=-1",
        retries=2, **KW)
    assert rep.n_shard_crashes == 1
    assert rep.n_quarantined > 0
    assert rep.n_migrated_tenants == 0
    assert rep.ticks == 20                   # the run completed
    # the quarantine event rides the flight journal's VARIANT tier
    evs = [ev for t in eng.flight_recorder.records()
           for ev in t.get("recovery", ()) if ev["kind"] == "quarantine"]
    assert evs and evs[0]["batches"] == rep.n_quarantined


def test_migration_parity_after_shard_death(reference):
    """A shard whose worker dies past the respawn budget has its
    tenants MIGRATED to the survivor through the set_state seam and the
    retained slices re-executed there — and because tenant bits are
    placement-invariant, even this degraded path keeps the
    no-score-gap parity when the fault followed the shard."""
    eng, rep = run_power_law(
        shards=2,
        chaos=";".join(f"crash@{t}:shard=0:phase=stage:repeat=-1"
                       for t in range(4, 20)),
        retries=3, max_respawns=2, **KW)
    assert rep.n_migrated_tenants > 0
    assert rep.n_respawns == 2
    assert rep.n_quarantined == 0
    assert_no_score_gap(reference, eng, rep)
    evs = [ev for t in eng.flight_recorder.records()
           for ev in t.get("recovery", ()) if ev["kind"] == "migrate"]
    assert len(evs) == 1 and evs[0]["tenants"] == rep.n_migrated_tenants


@pytest.mark.slow
def test_batch_bound_fault_during_migration_quarantines_not_doubles():
    """A poison batch that follows its tenant onto the migration target
    quarantines THERE — and the nested recovery replaying the target's
    whole log must not let the outer migration walk re-execute the
    later slices a second time (a double fold would silently corrupt
    states).  The span-conservation invariant is the oracle: every
    served span folds into exactly one replay, minus the quarantined
    ones."""
    eng, rep = run_power_law(
        shards=2,
        chaos="crash@12:shard=0:phase=stage:repeat=-1;"
              "except@12:shard=1:phase=dispatch:repeat=-1",
        retries=2, max_respawns=1, **KW)
    assert rep.ticks == 20                    # the run completed
    assert rep.n_migrated_tenants > 0
    assert rep.n_quarantined > 0
    sup = eng._supervisor
    folded = sum(r.n_spans for r in eng._tenant_replay.values())
    assert folded == rep.served_spans - sup.quarantined_spans


@pytest.mark.slow
def test_migration_with_no_survivor_propagates():
    """The 1-shard engine has nowhere to migrate: a worker... there is
    no worker inline, so exhaust the retry path on a 2-shard engine
    with BOTH shards dead — the original error propagates loudly."""
    from anomod.serve.chaos import ChaosFault
    script = ";".join(f"crash@{t}:shard={s}:phase=stage:repeat=-1"
                      for t in range(4, 8) for s in (0, 1))
    with pytest.raises(ChaosFault):
        run_power_law(shards=2, chaos=script, retries=2,
                      max_respawns=1, **KW)


def test_chaos_script_validation():
    """The ANOMOD_SERVE_CHAOS grammar fails loud on every malformed
    shape, and round-trips through the Config contract."""
    from anomod.config import validate_chaos_script
    good = validate_chaos_script(
        "crash@5;except@6:shard=1:phase=score;stall@7:ms=2.5;"
        "poolput@8:repeat=-1")
    assert [f["kind"] for f in good] == ["crash", "except", "stall",
                                        "poolput"]
    assert good[0]["phase"] == "dispatch"     # per-kind default
    assert good[2]["ms"] == 2.5
    assert good[3]["phase"] == "fold" and good[3]["repeat"] == -1
    for bad in ("boom@5", "crash", "crash@x", "crash@-1",
                "crash@5:phase=nope", "crash@5:repeat=0",
                "crash@5:shard=-2", "crash@5:frobnicate=1",
                "stall@5:ms=99999"):
        with pytest.raises(ValueError):
            validate_chaos_script(bad)


def test_supervision_knobs_validated(monkeypatch):
    """Every new knob is Config-validated (fail-loud), and the engine
    refuses nonsense values."""
    from anomod.config import Config
    for var, bad in (("ANOMOD_SERVE_CHAOS", "boom@5"),
                     ("ANOMOD_SERVE_CKPT_EVERY", "-1"),
                     ("ANOMOD_SERVE_RETRIES", "0"),
                     ("ANOMOD_SERVE_RETRY_BACKOFF_S", "-0.5"),
                     ("ANOMOD_SERVE_MAX_RESPAWNS", "-1")):
        monkeypatch.setenv(var, bad)
        with pytest.raises(ValueError):
            Config()
        monkeypatch.delenv(var)
    cfg = Config()
    assert cfg.serve_chaos == "" and cfg.serve_ckpt_every == 32
    assert cfg.serve_retries == 3 and cfg.serve_retry_backoff_s == 0.0
    assert cfg.serve_max_respawns == 8
    from anomod.replay import ReplayConfig
    with pytest.raises(ValueError):
        ServeEngine([], ["a"], ReplayConfig(n_services=1), ckpt_every=-1)
    # a fault aimed at a shard the engine doesn't have can never fire:
    # WARNED loud at construction (not refused — `audit replay
    # --shards 1` legitimately re-executes a 2-shard chaos journal
    # with the extra faults inert); the CLI's --chaos path refuses
    # the same mistake hard via parser.error
    with pytest.warns(RuntimeWarning, match="targets shard"):
        eng = ServeEngine([], ["a"], ReplayConfig(n_services=1),
                          chaos="crash@5:shard=1", shards=1)
    eng.close()


def test_supervision_refused_with_multimodal_and_mesh():
    """Supervision cannot checkpoint the multimodal sidecar planes or
    the mesh plane's sharded state: an explicit request is refused, the
    env default silently degrades to unsupervised."""
    from anomod.replay import ReplayConfig
    from anomod.serve.queues import TenantSpec
    specs = [TenantSpec(0, "t0", rate_spans_per_s=10.0)]
    cfg = ReplayConfig(n_services=2, n_windows=8, window_us=1_000_000,
                      chunk_size=64)
    with pytest.raises(ValueError, match="multimodal"):
        ServeEngine(specs, ["a", "b"], cfg, multimodal=True,
                    ckpt_every=8)
    eng = ServeEngine(specs, ["a", "b"], cfg, multimodal=True)
    assert eng._supervisor is None            # env default degrades
    eng.close()


def test_shard_worker_close_timeout_counted_and_error_reraised():
    """ShardWorker.close() satellites: (1) a worker parked past the
    join timeout is counted + warned instead of silently abandoned;
    (2) a deferred task error nobody joined re-raises at close instead
    of vanishing with the thread."""
    from anomod import obs
    from anomod.serve.shard import ShardWorker

    # (2) deferred error: submitted, never joined, must surface at close
    w = ShardWorker(0)
    w.submit(lambda: (_ for _ in ()).throw(RuntimeError("unjoined")))
    w._done.wait()
    with pytest.raises(RuntimeError, match="unjoined"):
        w.close()
    assert not w.alive                        # thread still shut down

    # (1) hung worker: a task that outlives the close timeout
    release = threading.Event()
    w2 = ShardWorker(1)
    before = obs.counter(
        "anomod_serve_shard_close_timeout_total").value
    w2.submit(release.wait)
    # shrink the timeout via a monkey-joined thread? close() uses 5 s —
    # patch the thread's join so the test never waits that long
    orig_join = w2._thread.join
    w2._thread.join = lambda timeout=None: orig_join(timeout=0.05)
    try:
        with warnings.catch_warnings(record=True) as got:
            warnings.simplefilter("always")
            w2.close()
        assert any("still running" in str(x.message) for x in got)
        after = obs.counter(
            "anomod_serve_shard_close_timeout_total").value
        assert after == before + 1
    finally:
        release.set()
        orig_join(timeout=5.0)


def test_worker_crash_kills_thread_and_reports_at_join():
    """A kills_worker exception (the chaos crash taxonomy) reports its
    error at the barrier AND exits the worker thread — the supervisor's
    respawn trigger."""
    from anomod.serve.chaos import ChaosWorkerCrash
    from anomod.serve.shard import ShardWorker
    w = ShardWorker(0)
    w.submit(lambda: (_ for _ in ()).throw(ChaosWorkerCrash("boom")))
    with pytest.raises(ChaosWorkerCrash):
        w.join()
    w._thread.join(timeout=5.0)
    assert not w.alive


