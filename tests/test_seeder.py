"""Social-graph seeder: scale, determinism, program shape."""

import numpy as np

from anomod import seeder


def test_graph_scale_and_determinism():
    g = seeder.generate_graph()
    assert g.n_users == seeder.REED98_USERS
    assert g.n_edges == seeder.REED98_EDGES
    # no self loops, no duplicates, u < v canonical form
    assert (g.edges[:, 0] < g.edges[:, 1]).all()
    assert len({(int(a), int(b)) for a, b in g.edges}) == g.n_edges

    g2 = seeder.generate_graph()
    assert np.array_equal(g.edges, g2.edges)
    assert np.array_equal(g.posts_per_user, g2.posts_per_user)
    g3 = seeder.generate_graph(seed=2)
    assert not np.array_equal(g.edges, g3.edges)


def test_heavy_tail_degrees():
    g = seeder.generate_graph()
    deg = g.follower_counts()
    # heavy tail: the top user has far more followers than the median
    assert deg.max() > 8 * max(np.median(deg), 1)
    assert deg.sum() == 2 * g.n_edges


def test_seeding_program_shape():
    g = seeder.generate_graph(n_users=50, n_edges=120)
    ops = seeder.seeding_program(g, compose=True)
    n_reg = sum(1 for o in ops if o.path.endswith("register"))
    n_fol = sum(1 for o in ops if o.path.endswith("follow"))
    n_cmp = sum(1 for o in ops if o.path.endswith("compose"))
    assert n_reg == 50
    assert n_fol == 2 * 120            # both directions per edge
    assert n_cmp == int(g.posts_per_user.sum())
    # registers precede follows precede composes
    kinds = [o.path.rsplit("/", 1)[1] for o in ops]
    assert kinds.index("follow") == 50
    assert "register" not in kinds[50:]


def test_waves_batching():
    g = seeder.generate_graph(n_users=30, n_edges=40)
    ops = seeder.seeding_program(g)
    batches = list(seeder.waves(ops, limit=32))
    assert all(len(b) <= 32 for b in batches)
    assert sum(len(b) for b in batches) == len(ops)


def test_timeline_weights():
    g = seeder.generate_graph(n_users=100, n_edges=300)
    w = seeder.timeline_weights(g)
    assert np.isclose(w.sum(), 1.0)
    assert (w >= 0).all() and len(w) == 100
    # hottest user gets the biggest weight
    assert w.argmax() == g.follower_counts().argmax()


def test_posts_average_about_ten():
    g = seeder.generate_graph()
    assert 8.0 < g.posts_per_user.mean() < 12.0
