"""Million-tenant state tiering: device hot pool → host warm tier →
content-addressed disk cold tier (anomod.serve.tiering, ISSUE-19).

The central pin: a tiered run — tenants demoting out of the device
pool on idle decay, spilling to disk past the warm budget, re-admitting
transparently on their next span — produces states, alerts, SLO and
shed BYTE-identical to the never-evicted run of the same seed, with
the tier empty at run end (the promote-all settlement).  Cold
promotion defers exactly one tick as a counted, journaled
``tier_miss`` (never a blocking read in the hot loop), so every tier
decision is a function of seed+config alone: same-config reruns are
pinned byte-equal on the canonical journal AND the tiering event
stream.  The cold tier's publish-before-drop protocol is pinned
crash-safe, and tiering composes with the PR-13 migration seam.
"""

import dataclasses
from pathlib import Path

import numpy as np
import pytest

from anomod.obs.flight import canonical_ticks, state_digest
from anomod.serve.engine import (SHARD_VARIANT_REPORT_FIELDS, ServeEngine,
                                 run_power_law)

#: the compact seeded scenario: SUB-capacity (overload 0.5), because
#: the power-law tail must go idle whole ticks for the decay plane to
#: demote at all — an overloaded fleet keeps every tenant backlogged
#: and the anti-thrash exclusion never fires
KW = dict(n_tenants=24, n_services=4, capacity_spans_per_s=400,
          overload=0.4, duration_s=24, tick_s=1.0, seed=7,
          window_s=5.0, baseline_windows=2, fault_tenants=0,
          buckets=(64, 256), lane_buckets=(1, 2, 4), max_backlog=1500,
          n_windows=16, flight_digest_every=4)

#: a hot capacity well under the fleet and a warm budget under one
#: state slot: every demotion spills cold, so all four event legs
#: (warm demote, cold spill, promote, miss) fire several times per
#: run (9 each on this seed — enough that the crash test still has
#: spills left AFTER its killed one)
TIER_KW = dict(tier_hot=4, tier_demote_after=2, tier_warm_bytes=4096,
               tier_prefetch=2)

#: report fields that legitimately differ between a tiered and a
#: never-evicted leg: the tiering config + its canonical counters
#: (everything else in the report must match byte-for-byte)
TIERING_REPORT_FIELDS = ("tier_hot", "n_tier_demotions_warm",
                         "n_tier_demotions_cold", "n_tier_promotions",
                         "n_tier_misses")


@pytest.fixture(scope="module")
def oracle():
    """ONE never-evicted reference run of the module scenario."""
    return run_power_law(**KW)


def run_tiered(cold_dir, **overrides):
    kw = dict(KW, **TIER_KW, tier_cold_dir=str(cold_dir))
    kw.update(overrides)
    return run_power_law(**kw)


def assert_tier_parity(oracle, eng, rep, extra_skip=()):
    """Byte-identical tenant states + alert streams, identical
    SLO/shed/served, report equal outside the declared tiering and
    shard-variant fields."""
    ref_eng, ref_rep = oracle
    assert sorted(eng._tenant_det) == sorted(ref_eng._tenant_det)
    for tid in sorted(ref_eng._tenant_det):
        assert [dataclasses.asdict(a) for a in ref_eng.alerts_for(tid)] \
            == [dataclasses.asdict(a) for a in eng.alerts_for(tid)], \
            f"tenant {tid} alert stream diverges under tiering"
    assert state_digest(ref_eng._tenant_replay) \
        == state_digest(eng._tenant_replay)
    skip = set(SHARD_VARIANT_REPORT_FIELDS) \
        | set(TIERING_REPORT_FIELDS) | set(extra_skip)
    # the one-tick deferral moves WHICH tick a parked batch scores in,
    # so the fused lane packing regroups around it: per-width dispatch
    # counts are a dispatch-topology artifact (content conserved — the
    # state/alert/SLO planes above are byte-checked; same-config reruns
    # pin it deterministic in the journal test)
    skip.add("dispatches_by_width")
    a = {k: v for k, v in ref_rep.to_dict().items() if k not in skip}
    b = {k: v for k, v in rep.to_dict().items() if k not in skip}
    assert a == b, sorted(k for k in a if a[k] != b[k])


def test_tiered_run_byte_identical_to_never_evicted(oracle, tmp_path):
    """The headline parity pin — and the tier actually worked for it:
    warm demotions, cold spills, promotions and misses all fired, the
    prefetch lane carried every cold fetch, and the run-end settlement
    left the tier empty."""
    eng, rep = run_tiered(tmp_path / "cold")
    assert rep.tier_hot == TIER_KW["tier_hot"]
    assert rep.n_tier_demotions_warm > 0
    assert rep.n_tier_demotions_cold > 0
    assert rep.n_tier_promotions > 0
    assert rep.n_tier_misses > 0
    assert len(eng._tier) == 0, "promote-all settlement left tenants tiered"
    # the cold tier is real: content-addressed payloads were published
    assert list((tmp_path / "cold").rglob("*.npc"))
    assert_tier_parity(oracle, eng, rep)


def test_tier_events_journaled_and_rerun_deterministic(tmp_path):
    """Every demote/spill/promote/miss is flight-journaled (the
    ``tiering`` variant key `anomod audit replay` reconstructs from),
    the journaled stream reconciles exactly with the report counters,
    a deferred promote lands one tick after its miss — and because the
    deferral is deterministic (never wall-clock), a same-config rerun
    reproduces the canonical journal AND the event stream byte-equal,
    prefetch timing notwithstanding."""
    eng_a, rep_a = run_tiered(tmp_path / "cold_a")
    eng_b, rep_b = run_tiered(tmp_path / "cold_b")
    recs = eng_a.flight_recorder.records()
    events = [ev for rec in recs for ev in rec["tiering"]]
    by_kind = {}
    for ev in events:
        by_kind.setdefault((ev["kind"], ev.get("tier")), []).append(ev)

    def of(kind, tier=None):
        return by_kind.get((kind, tier), [])

    assert len(of("demote", "warm")) == rep_a.n_tier_demotions_warm
    assert len(of("demote", "cold")) == rep_a.n_tier_demotions_cold
    assert len(of("promote", "warm")) + len(of("promote", "cold")) \
        == rep_a.n_tier_promotions
    assert len(of("miss")) == rep_a.n_tier_misses
    # one miss ↔ one deferred promote, exactly one tick later (the
    # run-end settlement's promotes are the non-deferred remainder)
    deferred = [ev for tier in ("warm", "cold")
                for ev in of("promote", tier) if ev["deferred"]]
    missed = {(ev["tenant"], ev["tick"]) for ev in of("miss")}
    assert len(deferred) == len(missed)
    for ev in deferred:
        assert (ev["tenant"], ev["tick"] - 1) in missed
    # the replay-determinism pin: same config, byte-equal journal and
    # byte-equal event stream (misses included — the deferral never
    # consults wall clock)
    assert canonical_ticks(recs) \
        == canonical_ticks(eng_b.flight_recorder.records())
    assert events == [ev for rec in eng_b.flight_recorder.records()
                      for ev in rec["tiering"]]
    assert (rep_a.n_tier_demotions_warm, rep_a.n_tier_demotions_cold,
            rep_a.n_tier_promotions, rep_a.n_tier_misses) \
        == (rep_b.n_tier_demotions_warm, rep_b.n_tier_demotions_cold,
            rep_b.n_tier_promotions, rep_b.n_tier_misses)


def test_cold_tier_crash_between_tmp_write_and_rename(
        oracle, tmp_path, monkeypatch):
    """A kill between the cold entry's tmp write and its rename leaves
    NO torn published file: the publish-before-drop protocol keeps the
    victim warm (its host copy is only dropped after the rename lands),
    the orphaned ``.tmp`` is never read, the next demotion re-derives
    the spill cleanly, and the run's decisions stay byte-identical."""
    import anomod.io.cache as io_cache
    real_replace = io_cache.os.replace
    killed = {"n": 0}

    def killing_replace(src, dst):
        if str(dst).endswith(".npc") and killed["n"] == 0:
            killed["n"] += 1
            raise OSError("simulated kill between tmp write and rename")
        return real_replace(src, dst)

    monkeypatch.setattr(io_cache.os, "replace", killing_replace)
    cold = tmp_path / "cold"
    eng, rep = run_tiered(cold)
    assert killed["n"] == 1                  # the kill actually fired
    # the torn tmp is still on disk — and every PUBLISHED payload is
    # whole (the reader never opens tmp paths)
    assert list(cold.rglob("*.tmp"))
    from anomod.io.cache import _read_payload
    published = list(cold.rglob("*.npc"))
    assert published                          # later spills re-derived
    for p in published:
        _read_payload(p.read_bytes())         # raises on any torn file
    assert rep.n_tier_demotions_cold > 0
    assert len(eng._tier) == 0
    assert_tier_parity(oracle, eng, rep)


def test_tiering_composed_with_migration_seam(tmp_path):
    """Tiering × the PR-13 migration seam: a 2-shard supervised tiered
    run whose shard 0 dies past the respawn budget migrates its tenants
    (demoted ones included — the checkpoint covers tier entries, warm
    by reference and cold by content address) to the survivor, where
    they re-admit and keep scoring — byte-identical to the fault-free
    never-evicted 1-shard run of the same seed (placement invariance ×
    tiering parity × recovery, one oracle)."""
    ref_eng, ref_rep = run_power_law(**KW)
    eng, rep = run_tiered(
        tmp_path / "cold", shards=2, pipeline=2, ckpt_every=4,
        retries=2, max_respawns=1,
        chaos=";".join(f"crash@{t}:shard=0:phase=stage:repeat=-1"
                       for t in range(10, 24)))
    assert rep.n_migrated_tenants > 0
    assert rep.n_tier_demotions_warm > 0
    assert rep.n_tier_demotions_cold > 0
    assert rep.n_tier_promotions > 0
    assert len(eng._tier) == 0
    for tid in sorted(ref_eng._tenant_det):
        assert [dataclasses.asdict(a) for a in ref_eng.alerts_for(tid)] \
            == [dataclasses.asdict(a) for a in eng.alerts_for(tid)], \
            f"tenant {tid} alert stream diverges (tiering × migration)"
    assert state_digest(ref_eng._tenant_replay) \
        == state_digest(eng._tenant_replay)
    assert rep.latency == ref_rep.latency
    assert rep.shed_fraction == ref_rep.shed_fraction
    assert rep.served_spans == ref_rep.served_spans


def test_tier_knobs_validated(monkeypatch):
    """Every ANOMOD_SERVE_TIER_* knob fails loud on garbage with the
    pinned message, and the engine refuses nonsense kwargs."""
    from anomod.config import Config
    for var, bad, msg in (
            ("ANOMOD_SERVE_TIER_HOT", "-1", r"must be >= 0, got -1"),
            ("ANOMOD_SERVE_TIER_HOT", "lots",
             r"non-negative integer.*'lots'"),
            ("ANOMOD_SERVE_TIER_DEMOTE_AFTER", "0", r"must be >= 1"),
            ("ANOMOD_SERVE_TIER_DEMOTE_AFTER", "soon",
             r"positive.*'soon'"),
            ("ANOMOD_SERVE_TIER_WARM_BYTES", "-4096",
             r"must be >= 0, got -4096"),
            ("ANOMOD_SERVE_TIER_PREFETCH", "0", r"in \[1, 256\], got 0"),
            ("ANOMOD_SERVE_TIER_PREFETCH", "many",
             r"positive integer.*'many'")):
        monkeypatch.setenv(var, bad)
        with pytest.raises(ValueError, match=msg):
            Config()
        monkeypatch.delenv(var)
    cfg = Config()
    assert cfg.serve_tier_hot == 0            # tiering off by default
    assert cfg.serve_tier_demote_after == 8
    assert cfg.serve_tier_warm_bytes == 64 * 1024 * 1024
    assert cfg.serve_tier_cold_dir is None
    assert cfg.serve_tier_prefetch == 4
    from anomod.replay import ReplayConfig
    rcfg = ReplayConfig(n_services=1)
    with pytest.raises(ValueError, match="tier_hot"):
        ServeEngine([], ["a"], rcfg, tier_hot=-1)
    with pytest.raises(ValueError, match="tier_demote_after"):
        ServeEngine([], ["a"], rcfg, tier_hot=4, tier_demote_after=0)
    with pytest.raises(ValueError, match="tier_prefetch"):
        ServeEngine([], ["a"], rcfg, tier_hot=4, tier_prefetch=0)


def test_tiering_refused_on_uncovered_planes(monkeypatch):
    """The policy idiom: an EXPLICIT tiering request on a plane the
    demotion copier cannot cover (the deferred-commit tick) is refused
    with the reason; the env-derived default silently degrades to
    untiered instead."""
    from anomod.replay import ReplayConfig
    rcfg = ReplayConfig(n_services=1)
    with pytest.raises(ValueError, match="deferred-commit"):
        ServeEngine([], ["a"], rcfg, tier_hot=4, async_commit=True)
    monkeypatch.setenv("ANOMOD_SERVE_TIER_HOT", "4")
    eng = ServeEngine([], ["a"], rcfg, async_commit=True)
    assert eng.tier_hot == 0 and eng._tier is None
    eng.close()


def test_tier_misses_deferred_exactly_one_tick_and_counted(tmp_path):
    """The stall-free contract, mechanized: every cold promotion rides
    the prefetch lane and defers exactly one tick (asserted per-event
    in the journal test above); here the miss COUNT is pinned equal to
    the number of deferred promotes and bounded by cold demotions —
    a tenant never parks longer than one tick."""
    eng, rep = run_tiered(tmp_path / "cold")
    events = [ev for rec in eng.flight_recorder.records()
              for ev in rec["tiering"]]
    deferred = [ev for ev in events
                if ev["kind"] == "promote" and ev["deferred"]]
    assert rep.n_tier_misses == len(deferred)
    assert rep.n_tier_misses > 0
    # nothing parks at run end, and nothing ever parked twice: each
    # miss's tenant promoted at the very next tick
    assert not eng._tier_parked
    misses = {(ev["tenant"], ev["tick"]) for ev in events
              if ev["kind"] == "miss"}
    assert {(ev["tenant"], ev["tick"] - 1) for ev in deferred} == misses
