"""Contract-checking static analysis plane (anomod.analysis, PR 11).

Covers: the fixture corpus (one must-trip and one must-pass file per
rule family under tests/lint_fixtures/), the suppression-syntax round
trip, baseline-regression semantics (new finding fails, baselined
finding passes, stale entries ratchet out), the parity-surface audit
(incl. the synthetic un-listed ServeReport field the acceptance
criteria name), the CANONICAL ServeReport field inventory (the
forcing function: a new field must either join the variant list or be
named by a test — this literal is that naming), the repo-runs-clean
pin, the env-contract delegation (dynamic-read false negative closed),
the pre-bench EXIT_LINT wiring and the sanitize-smoke verdict shapes.
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

from anomod.analysis import (RULES, lint_repo, lint_source, run_parity_audit,
                             status_block)
from anomod.analysis.lint import (Finding, load_baseline, save_baseline,
                                  summarize)
from anomod.analysis.parity import (FLIGHT_SPINE, audit_flight_record,
                                    audit_serve_report, flight_contract,
                                    flight_record_keys, serve_report_fields,
                                    shard_variant_fields)

REPO = Path(__file__).resolve().parent.parent
FIXTURES = Path(__file__).parent / "lint_fixtures"
SCRIPTS = REPO / "scripts"


def _lint_fixture(name, pretend, corpus=""):
    src = (FIXTURES / name).read_text()
    return lint_source(src, pretend, corpus)


def _active_rules(findings):
    return sorted({f.rule for f in findings if not f.suppressed})


# ---------------------------------------------------------------------------
# fixture corpus: each family demonstrably trips and passes
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("trip,passes,pretend,corpus,rules", [
    ("determinism_trip.py", "determinism_pass.py",
     "anomod/serve/fixture.py", "",
     ["D101", "D102", "D103", "D104", "D105"]),
    ("env_trip.py", "env_pass.py", "anomod/fixture.py",
     "ANOMOD_KNOWN_KNOB is documented here", ["E201", "E202"]),
    ("seam_trip.py", "seam_pass.py", "anomod/serve/fixture.py", "",
     ["S301"]),
    ("seam_gather_trip.py", "seam_gather_pass.py", "anomod/replay.py",
     "", ["S302"]),
    ("lock_trip.py", "lock_pass.py", "anomod/obs/registry.py", "",
     ["L501"]),
    ("commit_barrier_trip.py", "commit_barrier_pass.py",
     "anomod/serve/fixture.py", "", ["C601"]),
])
def test_fixture_family(trip, passes, pretend, corpus, rules):
    assert _active_rules(_lint_fixture(trip, pretend, corpus)) == rules
    assert _active_rules(_lint_fixture(passes, pretend, corpus)) == []


def test_scoping_is_path_based():
    """The same determinism-trip source is CLEAN outside the canonical
    modules, and the seam-trip source is clean inside a seam module —
    the contracts bind where they are declared, nowhere else."""
    src = (FIXTURES / "determinism_trip.py").read_text()
    assert _active_rules(lint_source(src, "anomod/io/fixture.py")) == []
    seam = (FIXTURES / "seam_trip.py").read_text()
    assert _active_rules(
        lint_source(seam, "anomod/serve/batcher.py")) == []


# ---------------------------------------------------------------------------
# suppression syntax
# ---------------------------------------------------------------------------

_VIOLATION = ("import time\n"
              "def f():\n"
              "    return time.time(){directive}\n")


def test_suppression_roundtrip():
    clean = _VIOLATION.format(
        directive="  # anomod-" "lint: disable=D101 — forensic stamp")
    got = lint_source(clean, "anomod/serve/x.py")
    assert _active_rules(got) == []
    sup = [f for f in got if f.suppressed]
    assert len(sup) == 1 and sup[0].rule == "D101"
    assert sup[0].reason == "forensic stamp"
    # -- and the "--" separator spelling
    clean2 = _VIOLATION.format(
        directive="  # anomod-" "lint: disable=D101 -- forensic stamp")
    assert _active_rules(lint_source(clean2, "anomod/serve/x.py")) == []


def test_suppression_requires_reason():
    bare = _VIOLATION.format(
        directive="  # anomod-" "lint: disable=D101")
    rules = _active_rules(lint_source(bare, "anomod/serve/x.py"))
    # the reasonless directive is a finding AND grants no suppression:
    # the tree cannot go green on a bare disable
    assert rules == ["D101", "LINT000"]


def test_suppression_unknown_rule_is_finding():
    bad = _VIOLATION.format(
        directive="  # anomod-" "lint: disable=NOPE — because")
    rules = _active_rules(lint_source(bad, "anomod/serve/x.py"))
    assert "LINT000" in rules and "D101" in rules


def test_suppression_statement_scope():
    """A directive-only line blesses the whole statement below it —
    including a compound statement's body (the engine's fused-gather
    branch is the real instance)."""
    src = ("import time\n"
           "def f(x):\n"
           "    # anomod-" "lint: disable=D101 — blessed block\n"
           "    if x:\n"
           "        a = time.time()\n"
           "        b = time.time()\n"
           "        return a, b\n"
           "    return time.time()\n")
    got = lint_source(src, "anomod/serve/x.py")
    active = [f for f in got if not f.suppressed]
    # lines 5 and 6 are inside the blessed if-statement; line 8 is NOT
    assert len(active) == 1 and active[0].line == 8
    assert sum(1 for f in got if f.suppressed) == 2


def test_suppression_file_wide():
    src = ("# anomod-" "lint: disable-file=D101 — fixture-wide waiver\n"
           "import time\n"
           "a = time.time()\n"
           "b = time.time()\n")
    assert _active_rules(lint_source(src, "anomod/serve/x.py")) == []


# ---------------------------------------------------------------------------
# baseline semantics
# ---------------------------------------------------------------------------

def test_baseline_semantics(tmp_path):
    f1 = Finding("D101", "anomod/serve/x.py", 3, "wall clock")
    f2 = Finding("L501", "anomod/obs/registry.py", 9, "unlocked")
    # new finding fails
    doc = summarize([f1, f2], [])
    assert doc["status"] == "contract-violations" \
        and doc["findings"] == 2
    # baselined finding passes; the other still fails
    doc = summarize([f1, f2], [f1.key])
    assert doc["findings"] == 1 and doc["baselined"] == 1
    # fully baselined tree is green, suppressed findings never fail
    doc = summarize([f1, Finding("D101", "a.py", 1, "x",
                                 suppressed=True, reason="why")],
                    [f1.key])
    assert doc["status"] == "ok" and doc["suppressed"] == 1
    # stale entries are reported (the shrink ratchet)
    doc = summarize([], [f1.key])
    assert doc["status"] == "ok" and doc["stale_baseline"] == [f1.key]
    # file round-trip
    p = tmp_path / "baseline.json"
    save_baseline(p, [f1.key, f2.key])
    assert load_baseline(p) == sorted([f1.key, f2.key])
    assert load_baseline(tmp_path / "absent.json") == []


def test_lint000_cannot_be_baselined(tmp_path):
    """A reasonless/malformed suppression (LINT000) can never ride the
    baseline: --update-baseline must not write its key, and even a
    hand-edited baseline entry must not silence it — otherwise the
    ratchet would launder the exact silent-disable hole the rule
    closes."""
    bad = Finding("LINT000", "anomod/serve/x.py", 3, "bare disable")
    p = tmp_path / "baseline.json"
    save_baseline(p, [bad.key, "D101|a.py|1"])
    assert load_baseline(p) == ["D101|a.py|1"]     # key dropped on save
    doc = summarize([bad], [bad.key])              # hand-edited entry
    assert doc["status"] == "contract-violations" \
        and doc["findings"] == 1


# ---------------------------------------------------------------------------
# parity-surface audit
# ---------------------------------------------------------------------------

#: THE canonical ServeReport inventory — every field that is pinned
#: byte-identical across shard counts / pipeline depths / residencies /
#: recoveries (i.e. NOT on SHARD_VARIANT_REPORT_FIELDS).  Adding a
#: ServeReport field breaks this equality until the author either adds
#: it here (naming it in a test — the parity audit's requirement) or
#: declares it variant, consciously widening the variant surface.
CANONICAL_REPORT_FIELDS = (
    "n_tenants", "duration_s", "ticks", "capacity_spans_per_s",
    "offered_spans", "admitted_spans", "served_spans", "shed_spans",
    "shed_fraction", "served_batches", "peak_backlog_spans",
    "max_backlog", "buckets", "dispatches_by_width", "fused",
    "lane_buckets", "native_staging", "serve_state", "latency",
    "per_priority", "modality_events", "n_alerts",
    "n_tenants_alerted", "fault_detection", "rca_enabled",
    "n_rca_runs", "rca_topk_hits", "rca_eligible",
    "rca_alert_to_culprit_s", "supervised", "ckpt_every",
    "n_checkpoints", "n_shard_crashes", "n_respawns",
    "n_restored_ticks", "n_quarantined", "n_migrated_tenants",
    "flight_enabled", "flight_recorded_ticks", "flight_dropped_ticks",
    # elastic policy (ISSUE-13): the policy mode and its executed
    # decision counts are seed-deterministic (and zero with the policy
    # off, so the shard fan-out parity holds trivially); peak_shards /
    # policy_wall_s are the variant topology/wall halves
    "policy", "n_scale_ups", "n_scale_downs", "n_rebalances",
    "n_policy_migrations", "brownout_ticks",
    # the performance observatory (ISSUE-14): whether the dispatch-
    # lifecycle timeline ran is config, identical at every shard
    # count; its event counts / headroom / wait / bubble numbers are
    # wall-clock+topology and live on SHARD_VARIANT_REPORT_FIELDS
    "perf_enabled",
    # the fleet census (ISSUE-15): the enable bit is config, the
    # census tick count is a pure function of cadence × run length,
    # and the hot-set/Zipf census derives from coordinator admission
    # decisions alone — all three shard-invariant (pinned in
    # tests/test_census.py); the resident-bytes dict follows the
    # pool/scratch topology and lives on SHARD_VARIANT_REPORT_FIELDS
    "census_enabled", "census_ticks", "census_hot_set",
    # the deferred-commit seam (ISSUE-16): the mode bit is config and
    # the async tick count is a pure function of config × run length
    # (every served tick defers except the forced-sync checkpoint
    # cadence), so both are parity-checked; the hidden-wait wall
    # (commit_defer_wall_s) lives on SHARD_VARIANT_REPORT_FIELDS
    "async_commit", "async_ticks",
    # state tiering (ISSUE-19): the hot capacity is config and every
    # demote/spill/promote/miss count is a pure function of
    # seed+config (the deferral is deterministic, never wall-clock —
    # pinned in tests/test_serve_tiering.py); the prefetch-hidden
    # count and the tier wall are wall-clock telemetry and live on
    # SHARD_VARIANT_REPORT_FIELDS
    "tier_hot", "n_tier_demotions_warm", "n_tier_demotions_cold",
    "n_tier_promotions", "n_tier_misses")


def test_canonical_report_inventory_pinned():
    fields = serve_report_fields(REPO)
    variant = set(shard_variant_fields(REPO))
    assert set(CANONICAL_REPORT_FIELDS) == set(fields) - variant, \
        "ServeReport changed: update CANONICAL_REPORT_FIELDS (naming " \
        "the field pins it canonical) or SHARD_VARIANT_REPORT_FIELDS " \
        "(declaring it variant) — never neither"
    assert not variant - set(fields)       # no stale variant entries


def test_parity_audit_fails_on_unlisted_synthetic_field():
    fields = list(serve_report_fields(REPO)) + ["sneaky_new_field"]
    got = audit_serve_report(fields, shard_variant_fields(REPO),
                             test_corpus="nothing names it")
    assert any(f.rule == "P401" and "sneaky_new_field" in f.message
               for f in got)
    # ...and is satisfied by EITHER coverage route
    ok_by_test = audit_serve_report(
        ["sneaky_new_field"], (), test_corpus="sneaky_new_field pinned")
    assert ok_by_test == []
    ok_by_variant = audit_serve_report(
        ["sneaky_new_field"], ("sneaky_new_field",), test_corpus="")
    assert ok_by_variant == []


def test_parity_audit_stale_variant_entry():
    got = audit_serve_report(["real_field"],
                             ("real_field", "ghost_field"),
                             test_corpus="")
    assert [f.rule for f in got] == ["P402"]


def test_flight_record_audit():
    planes, variant = flight_contract(REPO)
    keys = flight_record_keys(REPO)
    # the real record is exactly spine + planes + variant
    assert audit_flight_record(keys, planes, variant) == []
    assert set(planes) <= set(keys) and set(variant) <= set(keys)
    # an undeclared key fails (P403); a missing declared key fails
    # (P404) — the every-record-carries-every-tier contract
    got = audit_flight_record(list(keys) + ["stowaway"], planes, variant)
    assert [f.rule for f in got] == ["P403"]
    got = audit_flight_record([k for k in keys if k != "fold"],
                              planes, variant)
    assert [f.rule for f in got] == ["P404"]
    assert set(FLIGHT_SPINE) == {"tick", "now_s", "final"}


# ---------------------------------------------------------------------------
# the repo itself holds its contracts
# ---------------------------------------------------------------------------

def test_repo_lint_clean():
    """`anomod lint` runs clean on the repo: zero unsuppressed findings
    (every deliberate exception carries a reasoned inline suppression)
    and the shipped baseline is EMPTY — the acceptance pin."""
    findings = lint_repo(REPO) + run_parity_audit(REPO)
    active = [f.render() for f in findings if not f.suppressed]
    assert active == [], "\n".join(active)
    assert load_baseline(SCRIPTS / "lint_baseline.json") == []
    # the deliberate exceptions exist and carry reasons
    sup = [f for f in findings if f.suppressed]
    assert sup and all(f.reason for f in sup)


def test_rule_catalog_documented():
    """Every rule id is cataloged in docs/CONTRACTS.md with its
    motivation — the operator-facing contract list cannot drift from
    the code."""
    doc = (REPO / "docs" / "CONTRACTS.md").read_text()
    for rid, rule in RULES.items():
        assert rid in doc, f"{rid} missing from docs/CONTRACTS.md"
        assert rule.family and rule.synopsis and rule.motivation


def test_status_block_shape():
    blk = status_block(REPO)
    assert blk["status"] == "ok" and blk["findings"] == 0
    assert blk["rules"] == len(RULES)
    assert blk["baseline_size"] == 0 and blk["suppressed"] >= 4


def test_lint_cli_json():
    from anomod.cli import main
    import io
    from contextlib import redirect_stdout
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = main(["lint", "--json", "--show-suppressed"])
    assert rc == 0
    doc = json.loads(buf.getvalue())
    assert doc["status"] == "ok" and doc["findings"] == 0
    assert all(s["reason"] for s in doc["suppressed_findings"])


# ---------------------------------------------------------------------------
# env-contract delegation: the dynamic-read false negative is closed
# ---------------------------------------------------------------------------

def test_env_contract_catches_dynamic_read(tmp_path):
    """os.environ[f"ANOMOD_{name}"] — invisible to the PR-3 token grep
    — now fails the delegating script with its exit code unchanged."""
    (tmp_path / "anomod").mkdir()
    (tmp_path / "scripts").mkdir()
    (tmp_path / "docs").mkdir()
    (tmp_path / "anomod" / "config.py").write_text(
        'X = _env("ANOMOD_KNOWN_KNOB", "1")\n')
    (tmp_path / "anomod" / "dyn.py").write_text(
        'import os\nname = "SHARDS"\n'
        'Y = os.environ[f"ANOMOD_{name}"]\n')
    (tmp_path / "README.md").write_text("docs\n")
    r = subprocess.run(
        [sys.executable, str(SCRIPTS / "check_env_contract.py"),
         "--root", str(tmp_path)],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 1, r.stdout + r.stderr
    out = json.loads(r.stdout)
    assert out["n_dynamic"] == 1 and "anomod/dyn.py" in out["dynamic"]
    assert "DYNAMIC" in r.stderr


def test_env_rule_alias_and_concat_forms():
    """The AST scanner sees through the alias/concat spellings the grep
    inferred only by accident of the token appearing somewhere."""
    src = ("from os import environ, getenv\n"
           "name = 'X'\n"
           "a = environ['ANOMOD_ALIAS_ROGUE']\n"
           "b = getenv('ANOMOD_' + name)\n")
    rules = _active_rules(lint_source(src, "anomod/x.py"))
    assert rules == ["E201", "E202"]


# ---------------------------------------------------------------------------
# gate wiring
# ---------------------------------------------------------------------------

def test_check_contracts_gate_green_on_repo():
    sys.path.insert(0, str(SCRIPTS))
    try:
        import check_contracts
    finally:
        sys.path.pop(0)
    out = check_contracts.run()
    assert out["status"] == "ok" and out["findings"] == 0
    assert out["stale_baseline"] == []


def test_sanitize_smoke_verdict_shapes():
    """The probe returns a reasoned verdict either way; the smoke's
    skip path carries its reason (never a silent skip).  The full
    build+hammer run is exercised by `pre_bench_check --mode serve`
    and `make -C native tsan` (slow path)."""
    sys.path.insert(0, str(SCRIPTS))
    try:
        import native_sanitize_smoke as nss
    finally:
        sys.path.pop(0)
    p = nss.probe("tsan")
    assert set(p) == {"ok", "reason"}
    assert p["ok"] is True or p["reason"]
    with pytest.raises(ValueError):
        nss.run("nope")
    # a box with no compiler must SKIP with the reason recorded
    missing = nss.probe("tsan", cxx="definitely-not-a-compiler")
    assert missing["ok"] is False and "compiler" in missing["reason"]


@pytest.mark.slow
def test_sanitize_smoke_full_run():
    sys.path.insert(0, str(SCRIPTS))
    try:
        import native_sanitize_smoke as nss
    finally:
        sys.path.pop(0)
    out = nss.run("tsan", workers=2, iters=8)
    assert out["status"] in ("ok", "skip")
    if out["status"] == "skip":
        assert out["reason"]
