"""Replay engine: jax-vs-numpy parity and staging correctness."""

import numpy as np
import pytest

from anomod import labels, synth
from anomod.replay import (ReplayConfig, make_replay_fn, measure_throughput,
                           percentile_from_hist, replay_numpy, stage_columns,
                           F_COUNT, F_ERR)
from anomod.schemas import concat_span_batches


@pytest.fixture(scope="module")
def tt_batch():
    batches = [synth.generate_spans(l, n_traces=40)
               for l in labels.labels_for_testbed("TT")]
    return concat_span_batches(batches)


def test_stage_columns_shapes(tt_batch):
    cfg = ReplayConfig(n_services=tt_batch.n_services, chunk_size=1024)
    chunks, n = stage_columns(tt_batch, cfg)
    assert n == tt_batch.n_spans
    for v in chunks.values():
        assert v.shape[1] == 1024
    # padding rows carry the dead segment id
    total_valid = chunks["valid"].sum()
    assert int(total_valid) == n


def test_replay_jax_matches_numpy(tt_batch):
    cfg = ReplayConfig(n_services=tt_batch.n_services, chunk_size=2048)
    chunks, _ = stage_columns(tt_batch, cfg)
    ref = replay_numpy(chunks, cfg)
    fn = make_replay_fn(cfg)
    out = fn(chunks)
    agg = np.asarray(out.agg)
    hist = np.asarray(out.hist)
    np.testing.assert_allclose(agg[:, F_COUNT], ref.agg[:, F_COUNT], rtol=1e-6)
    np.testing.assert_allclose(agg[:, F_ERR], ref.agg[:, F_ERR], rtol=1e-6)
    np.testing.assert_allclose(agg, ref.agg, rtol=1e-3)
    np.testing.assert_allclose(hist, ref.hist, rtol=1e-6)
    # total span count conserved
    assert int(agg[:, F_COUNT].sum()) == tt_batch.n_spans


def test_replay_aggregates_match_direct_stats(tt_batch):
    cfg = ReplayConfig(n_services=tt_batch.n_services, chunk_size=2048)
    chunks, _ = stage_columns(tt_batch, cfg)
    st = replay_numpy(chunks, cfg)
    # per-service totals (sum over windows) match direct numpy groupby
    agg = st.agg.reshape(cfg.n_services, cfg.n_windows, -1)
    per_svc_count = agg[..., F_COUNT].sum(axis=1)
    direct = np.bincount(tt_batch.service, minlength=cfg.n_services)
    np.testing.assert_array_equal(per_svc_count.astype(int), direct)
    per_svc_err = agg[..., F_ERR].sum(axis=1)
    direct_err = np.bincount(tt_batch.service,
                             weights=tt_batch.is_error.astype(float),
                             minlength=cfg.n_services)
    np.testing.assert_allclose(per_svc_err, direct_err, rtol=1e-6)


def test_percentile_from_hist_monotone(tt_batch):
    cfg = ReplayConfig(n_services=tt_batch.n_services, chunk_size=2048)
    chunks, _ = stage_columns(tt_batch, cfg)
    st = replay_numpy(chunks, cfg)
    p50 = percentile_from_hist(st.hist, 0.5)
    p99 = percentile_from_hist(st.hist, 0.99)
    assert (p99 >= p50).all()


def test_measure_throughput_smoke(tt_batch):
    cfg = ReplayConfig(n_services=tt_batch.n_services, chunk_size=4096)
    r = measure_throughput(tt_batch, cfg, repeats=1)
    assert r.n_spans == tt_batch.n_spans
    assert r.spans_per_sec > 0


def test_replay_hll_distinct_traces(tt_batch):
    """HLL plane counts distinct traces per service within sketch error."""
    import numpy as np
    from anomod.ops.hll import hll_estimate
    cfg = ReplayConfig(n_services=tt_batch.n_services, chunk_size=2048)
    chunks, _ = stage_columns(tt_batch, cfg)
    fn = make_replay_fn(cfg, with_hll=True)
    out = fn(chunks)
    regs = np.asarray(out.hll)
    assert regs.shape == (cfg.n_services, cfg.hll_m)
    est = hll_estimate(regs)
    for s in range(cfg.n_services):
        true = len(np.unique(tt_batch.trace[tt_batch.service == s]))
        if true >= 50:
            assert abs(est[s] - true) / true < 0.25, (s, true, est[s])


def test_replay_inner_repeats_scales_state(tt_batch):
    """Device-side replication (bench replicate) = exactly R x one pass."""
    cfg = ReplayConfig(n_services=tt_batch.n_services, chunk_size=2048)
    chunks, _ = stage_columns(tt_batch, cfg)
    one = make_replay_fn(cfg)(chunks)
    three = make_replay_fn(cfg, inner_repeats=3)(chunks)
    np.testing.assert_allclose(np.asarray(three.agg),
                               3.0 * np.asarray(one.agg), rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(three.hist),
                                  3.0 * np.asarray(one.hist))


def test_measure_throughput_replicate_counts(tt_batch):
    cfg = ReplayConfig(n_services=tt_batch.n_services, chunk_size=4096)
    r = measure_throughput(tt_batch, cfg, repeats=1, replicate=3)
    assert r.n_spans == 3 * tt_batch.n_spans


def test_replay_variance_reconstruction_low_variance():
    """Variance from the bf16 hi/lo moment planes on a LOW-variance latency
    distribution: pins the accepted error bound documented in chunk_step
    (~1.5e-5 * E[x^2] / Var(x) relative after the E[x^2]-E[x]^2 cancellation).
    """
    from anomod import labels, synth
    rng = np.random.default_rng(0)
    base = synth.generate_spans(labels.label_for("Normal_case"), n_traces=400)
    # low-variance log-latency: sigma=0.1 around ~50ms (vs synth's 0.4)
    dur_us = np.exp(rng.normal(np.log(50_000.0), 0.1,
                               base.n_spans)).astype(np.int64)
    batch = base._replace(duration_us=dur_us)
    cfg = ReplayConfig(n_services=batch.n_services, n_windows=1,
                       chunk_size=2048, window_us=10**12)
    chunks, _ = stage_columns(batch, cfg)
    out = make_replay_fn(cfg)(chunks)
    agg = np.asarray(out.agg)
    from anomod.replay import F_LOGLAT, F_LOGLAT2
    x = np.log1p(dur_us.astype(np.float64))
    for s in range(batch.n_services):
        m = batch.service == s
        n = int(m.sum())
        if n < 500:
            continue
        mean = agg[s, F_LOGLAT] / n
        var = agg[s, F_LOGLAT2] / n - mean**2
        true_var = x[m].var()
        # documented bound: rel err ~ 1.5e-5 * E[x^2]/Var ~ 0.2 at sigma=0.1;
        # assert a 30% envelope (and that var stays positive / same scale)
        assert var > 0, (s, var)
        assert abs(var - true_var) / true_var < 0.30, (s, var, true_var)
