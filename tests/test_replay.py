"""Replay engine: jax-vs-numpy parity and staging correctness."""

import numpy as np
import pytest

from anomod import labels, synth
from anomod.replay import (ReplayConfig, make_replay_fn, measure_throughput,
                           percentile_from_hist, replay_numpy, stage_columns,
                           F_COUNT, F_ERR)
from anomod.schemas import concat_span_batches


@pytest.fixture(scope="module")
def tt_batch():
    batches = [synth.generate_spans(l, n_traces=40)
               for l in labels.labels_for_testbed("TT")]
    return concat_span_batches(batches)


def test_stage_columns_shapes(tt_batch):
    cfg = ReplayConfig(n_services=tt_batch.n_services, chunk_size=1024)
    chunks, n = stage_columns(tt_batch, cfg)
    assert n == tt_batch.n_spans
    for v in chunks.values():
        assert v.shape[1] == 1024
    # padding rows carry the dead segment id
    total_valid = chunks["valid"].sum()
    assert int(total_valid) == n


def test_replay_jax_matches_numpy(tt_batch):
    cfg = ReplayConfig(n_services=tt_batch.n_services, chunk_size=2048)
    chunks, _ = stage_columns(tt_batch, cfg)
    ref = replay_numpy(chunks, cfg)
    fn = make_replay_fn(cfg)
    out = fn(chunks)
    agg = np.asarray(out.agg)
    hist = np.asarray(out.hist)
    np.testing.assert_allclose(agg[:, F_COUNT], ref.agg[:, F_COUNT], rtol=1e-6)
    np.testing.assert_allclose(agg[:, F_ERR], ref.agg[:, F_ERR], rtol=1e-6)
    np.testing.assert_allclose(agg, ref.agg, rtol=1e-3)
    np.testing.assert_allclose(hist, ref.hist, rtol=1e-6)
    # total span count conserved
    assert int(agg[:, F_COUNT].sum()) == tt_batch.n_spans


def test_replay_aggregates_match_direct_stats(tt_batch):
    cfg = ReplayConfig(n_services=tt_batch.n_services, chunk_size=2048)
    chunks, _ = stage_columns(tt_batch, cfg)
    st = replay_numpy(chunks, cfg)
    # per-service totals (sum over windows) match direct numpy groupby
    agg = st.agg.reshape(cfg.n_services, cfg.n_windows, -1)
    per_svc_count = agg[..., F_COUNT].sum(axis=1)
    direct = np.bincount(tt_batch.service, minlength=cfg.n_services)
    np.testing.assert_array_equal(per_svc_count.astype(int), direct)
    per_svc_err = agg[..., F_ERR].sum(axis=1)
    direct_err = np.bincount(tt_batch.service,
                             weights=tt_batch.is_error.astype(float),
                             minlength=cfg.n_services)
    np.testing.assert_allclose(per_svc_err, direct_err, rtol=1e-6)


def test_percentile_from_hist_monotone(tt_batch):
    cfg = ReplayConfig(n_services=tt_batch.n_services, chunk_size=2048)
    chunks, _ = stage_columns(tt_batch, cfg)
    st = replay_numpy(chunks, cfg)
    p50 = percentile_from_hist(st.hist, 0.5)
    p99 = percentile_from_hist(st.hist, 0.99)
    assert (p99 >= p50).all()
    # interpolated values are continuous, not bare bucket indices: occupied
    # rows should mostly land strictly inside buckets
    occupied = st.hist.sum(axis=-1) > 4
    frac = p50[occupied] - np.floor(p50[occupied])
    assert (frac > 0).mean() > 0.5


def test_percentile_interpolation_accuracy():
    """Interpolated histogram percentile approaches the exact log-latency
    percentile much closer than the ±1-bucket quantization of the old
    bucket-index form."""
    rng = np.random.default_rng(0)
    dur_log = np.clip(rng.lognormal(1.6, 0.35, 20_000), 0, 15.999)
    hist = np.bincount(dur_log.astype(np.int64), minlength=16).astype(
        np.float32)[None, :]
    for q in (0.5, 0.9, 0.99):
        exact = np.quantile(dur_log, q)
        interp = float(percentile_from_hist(hist, q)[0])
        assert abs(interp - exact) < 0.35, (q, interp, exact)
    us = percentile_from_hist(hist, 0.99, as_us=True)
    assert np.allclose(us, np.expm1(percentile_from_hist(hist, 0.99)))
    # empty histogram rows report 0, not the max bucket
    empty = np.zeros((3, 16), np.float32)
    assert (percentile_from_hist(empty, 0.99) == 0).all()
    assert (percentile_from_hist(empty, 0.99, as_us=True) == 0).all()


def test_pallas_kernel_block_follows_chunk_size():
    """The throughput harness must pick a block that divides the staged
    span count for any power-of-2-factor chunk_size, and reject chunk
    sizes with no usable factor."""
    from anomod.replay import measure_throughput
    from anomod import labels, synth
    label = labels.labels_for_testbed("TT")[0]
    batch = synth.generate_spans(label, n_traces=10)
    cfg = ReplayConfig(n_services=batch.n_services, chunk_size=1536)  # 3*512
    res = measure_throughput(batch, cfg, repeats=1, kernel="pallas")
    assert res.n_spans == batch.n_spans
    bad = ReplayConfig(n_services=batch.n_services, chunk_size=1000)
    with pytest.raises(ValueError, match="power-of-2"):
        measure_throughput(batch, bad, repeats=1, kernel="pallas")


def test_sorted_staging_reconstructs_segments():
    """stage_sorted_planes invariants: every row of a block belongs to the
    block's window, global segment ids reconstruct from (wid, local), and
    the staged aggregate equals the unsorted one (padding rows are inert)."""
    from anomod.ops.pallas_replay import (pallas_replay_numpy,
                                          stage_sorted_planes)
    rng = np.random.default_rng(3)
    SW, K, BLOCK, H = 600, 128, 256, 16
    n = 5000
    sid = rng.integers(0, SW + 1, n).astype(np.int32)
    planes = np.abs(rng.normal(size=(6, n))).astype(np.float32)
    sid_l, planes_s, wids = stage_sorted_planes(sid, planes, SW,
                                                k=K, block=BLOCK)
    assert sid_l.shape[0] % BLOCK == 0
    assert wids.shape[0] == sid_l.shape[0] // BLOCK
    assert (np.diff(wids) >= 0).all()          # windows in order
    assert sid_l.min() >= 0 and sid_l.max() < K
    gsid = sid_l + np.repeat(wids, BLOCK).astype(np.int32) * K
    got = pallas_replay_numpy(gsid, planes_s, SW, H)
    want = pallas_replay_numpy(sid, planes, SW, H)
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_pallas_sorted_kernel_matches_oracle():
    """The sorted-window kernel (interpret path) reproduces the unsorted
    oracle: 0/1 planes + histogram exactly, moments within the hi/lo
    bound — including device-side replication via inner_repeats."""
    from anomod.ops.pallas_replay import (make_pallas_replay_sorted_fn,
                                          pallas_replay_numpy,
                                          stage_sorted_planes)
    rng = np.random.default_rng(7)
    SW, H, K, BLOCK = 600, 16, 128, 256
    n = 5000
    sid = rng.integers(0, SW + 1, n).astype(np.int32)
    valid = (rng.random(n) < 0.9).astype(np.float32)
    dur_us = rng.lognormal(8.0, 1.0, n).astype(np.float32) * valid
    dur = np.log1p(dur_us)
    planes = np.stack([
        valid,
        ((rng.random(n) < 0.2) * valid).astype(np.float32),   # err: 0/1
        ((rng.random(n) < 0.1) * valid).astype(np.float32),   # 5xx: 0/1
        dur_us, dur, dur * dur,
    ])
    sid_l, planes_s, wids = stage_sorted_planes(sid, planes, SW,
                                                k=K, block=BLOCK)
    fn = make_pallas_replay_sorted_fn(SW, H, k=K, block=BLOCK,
                                      interpret=True, inner_repeats=2)
    got = np.asarray(fn(sid_l, planes_s, wids))
    want = pallas_replay_numpy(sid, planes, SW, H) * 2
    np.testing.assert_array_equal(got[:, :3], want[:, :3])    # exact planes
    np.testing.assert_array_equal(got[:, 6:], want[:, 6:])    # histogram
    np.testing.assert_allclose(got[:, 3:6], want[:, 3:6],     # hi/lo bound
                               rtol=2e-3, atol=1e-2)


def test_pallas_sorted_kernel_sparse_windows():
    """Corpora that leave whole segment windows empty (clustered service
    traffic) must still aggregate correctly: windows with no spans get no
    blocks, and their accumulator columns stay zero.  Also pins the
    zero-span guard."""
    from anomod.ops.pallas_replay import (make_pallas_replay_sorted_fn,
                                          pallas_replay_numpy,
                                          stage_sorted_planes)
    rng = np.random.default_rng(11)
    SW, H, K, BLOCK = 600, 16, 128, 256
    n = 1500
    sid = rng.integers(260, 380, n).astype(np.int32)   # one window only
    planes = np.abs(rng.normal(size=(6, n))).astype(np.float32)
    planes[0] = 1.0
    planes[1] = (rng.random(n) < 0.2).astype(np.float32)
    planes[2] = 0.0
    planes[4] = rng.uniform(0, 15, n).astype(np.float32)
    sid_l, planes_s, wids = stage_sorted_planes(sid, planes, SW,
                                                k=K, block=BLOCK)
    assert set(wids.tolist()) == {2}                   # only window 2 staged
    fn = make_pallas_replay_sorted_fn(SW, H, k=K, block=BLOCK,
                                      interpret=True)
    got = np.asarray(fn(sid_l, planes_s, wids))
    want = pallas_replay_numpy(sid, planes, SW, H)
    np.testing.assert_array_equal(got[:, :3], want[:, :3])
    assert (got[:256] == 0).all() and (got[384:] == 0).all()
    # zero-span corpus: defined all-zero output, not uninitialized memory
    # (both kernels share the guard)
    empty = fn(np.zeros(0, np.int32), np.zeros((6, 0), np.float32),
               np.zeros(0, np.int32))
    assert np.asarray(empty).shape == (SW, 6 + H)
    assert (np.asarray(empty) == 0).all()
    from anomod.ops.pallas_replay import make_pallas_replay_fn
    fn_full = make_pallas_replay_fn(SW, H, block=BLOCK, interpret=True)
    empty_full = fn_full(np.zeros(0, np.int32), np.zeros((6, 0), np.float32))
    assert (np.asarray(empty_full) == 0).all()


def test_measure_throughput_pallas_sorted_kernel(tt_batch):
    """End-to-end: the pallas-sorted path stages, runs (interpret on the
    CPU mesh), and passes the span-count audit."""
    cfg = ReplayConfig(n_services=tt_batch.n_services, chunk_size=2048)
    res = measure_throughput(tt_batch, cfg, repeats=1, kernel="pallas-sorted")
    assert res.kernel == "pallas-sorted"
    assert res.n_spans == tt_batch.n_spans


def test_replay_percentiles_tdigest_plane(tt_batch):
    """replay_percentiles (t-digest over the replay segments) tracks exact
    per-segment quantiles within the sketch's error bound."""
    from anomod.replay import replay_percentiles
    cfg = ReplayConfig(n_services=tt_batch.n_services, chunk_size=2048)
    out = replay_percentiles(tt_batch, cfg, qs=(0.5, 0.99))
    assert out.shape == (cfg.sw, 2)
    chunks, _ = stage_columns(tt_batch, cfg)
    sid = chunks["sid"].reshape(-1)
    dur = chunks["dur_raw"].reshape(-1)
    real = sid < cfg.sw
    sid, dur = sid[real], dur[real]
    # exact quantiles on the five most-populated segments; the p99 of a
    # ~70-sample segment rides the top order statistics, so its µs-domain
    # tolerance is wider than the median's
    counts = np.bincount(sid, minlength=cfg.sw)
    for seg in np.argsort(counts)[-5:]:
        vals = dur[sid == seg]
        assert abs(out[seg, 0] - np.quantile(vals, 0.5)) \
            <= 0.08 * max(np.quantile(vals, 0.5), 1.0)
        assert abs(out[seg, 1] - np.quantile(vals, 0.99)) \
            <= 0.20 * max(np.quantile(vals, 0.99), 1.0)
        # and the tail must actually be a tail (the pre-fix empty-centroid
        # bug returned p99 below p50)
        assert out[seg, 1] > out[seg, 0]


def test_replay_percentiles_pallas_engine_matches_host(tt_batch):
    """Engine parity across the digest builds: the TPU auto default
    (engine='xla') and the opt-in Mosaic kernel (engine='pallas',
    interpret path on the CPU mesh) must both reproduce the host digest
    plane, and engine='auto' must resolve to host off-TPU."""
    import pytest
    from anomod.replay import replay_percentiles
    cfg = ReplayConfig(n_services=tt_batch.n_services, chunk_size=2048)
    host = replay_percentiles(tt_batch, cfg, qs=(0.5, 0.99), engine="host")
    auto = replay_percentiles(tt_batch, cfg, qs=(0.5, 0.99), engine="auto")
    np.testing.assert_array_equal(auto, host)
    # the TPU auto default (jitted XLA one-hot build) must reproduce the
    # host plane from the identical staged lanes
    xla = replay_percentiles(tt_batch, cfg, qs=(0.5, 0.99), engine="xla")
    np.testing.assert_allclose(xla, host, rtol=2e-3, atol=1e-2)
    pal = replay_percentiles(tt_batch, cfg, qs=(0.5, 0.99), engine="pallas")
    # identical staging + identical bucket math; only kernel-vs-numpy float
    # ordering differs (lane padding slots carry weight 0)
    np.testing.assert_allclose(pal, host, rtol=2e-3, atol=1e-2)
    with pytest.raises(ValueError, match="engine"):
        replay_percentiles(tt_batch, cfg, engine="exact")
    # env override is normalized: "AUTO" restores auto-selection instead of
    # crashing, "HOST" selects the host build
    import os
    for val in ("AUTO", "HOST"):
        os.environ["ANOMOD_TDIGEST_ENGINE"] = val
        try:
            np.testing.assert_array_equal(
                replay_percentiles(tt_batch, cfg, qs=(0.5, 0.99)), host)
        finally:
            del os.environ["ANOMOD_TDIGEST_ENGINE"]


def test_measure_throughput_smoke(tt_batch):
    cfg = ReplayConfig(n_services=tt_batch.n_services, chunk_size=4096)
    r = measure_throughput(tt_batch, cfg, repeats=1)
    assert r.n_spans == tt_batch.n_spans
    assert r.spans_per_sec > 0


def test_measure_throughput_numpy_kernel(tt_batch):
    """The cpu-backend engine rides the same harness: replicate scaling,
    count integrity (asserted inside), median-of-N walls."""
    cfg = ReplayConfig(n_services=tt_batch.n_services, chunk_size=4096)
    r = measure_throughput(tt_batch, cfg, repeats=3, replicate=2,
                           kernel="numpy")
    assert r.kernel == "numpy"
    assert r.n_spans == 2 * tt_batch.n_spans
    assert r.spans_per_sec > 0
    assert len(r.raw_wall_s) == 3


def test_replay_hll_distinct_traces(tt_batch):
    """HLL plane counts distinct traces per service within sketch error."""
    import numpy as np
    from anomod.ops.hll import hll_estimate
    cfg = ReplayConfig(n_services=tt_batch.n_services, chunk_size=2048)
    chunks, _ = stage_columns(tt_batch, cfg)
    fn = make_replay_fn(cfg, with_hll=True)
    out = fn(chunks)
    regs = np.asarray(out.hll)
    assert regs.shape == (cfg.n_services, cfg.hll_m)
    est = hll_estimate(regs)
    for s in range(cfg.n_services):
        true = len(np.unique(tt_batch.trace[tt_batch.service == s]))
        if true >= 50:
            assert abs(est[s] - true) / true < 0.25, (s, true, est[s])


def test_replay_inner_repeats_scales_state(tt_batch):
    """Device-side replication (bench replicate) = exactly R x one pass."""
    cfg = ReplayConfig(n_services=tt_batch.n_services, chunk_size=2048)
    chunks, _ = stage_columns(tt_batch, cfg)
    one = make_replay_fn(cfg)(chunks)
    three = make_replay_fn(cfg, inner_repeats=3)(chunks)
    np.testing.assert_allclose(np.asarray(three.agg),
                               3.0 * np.asarray(one.agg), rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(three.hist),
                                  3.0 * np.asarray(one.hist))


def test_measure_throughput_replicate_counts(tt_batch):
    cfg = ReplayConfig(n_services=tt_batch.n_services, chunk_size=4096)
    r = measure_throughput(tt_batch, cfg, repeats=1, replicate=3)
    assert r.n_spans == 3 * tt_batch.n_spans


def test_replay_variance_reconstruction_low_variance():
    """Variance from the bf16 hi/lo moment planes on a LOW-variance latency
    distribution: pins the accepted error bound documented in chunk_step
    (~1.5e-5 * E[x^2] / Var(x) relative after the E[x^2]-E[x]^2 cancellation).
    """
    from anomod import labels, synth
    rng = np.random.default_rng(0)
    base = synth.generate_spans(labels.label_for("Normal_case"), n_traces=400)
    # low-variance log-latency: sigma=0.1 around ~50ms (vs synth's 0.4)
    dur_us = np.exp(rng.normal(np.log(50_000.0), 0.1,
                               base.n_spans)).astype(np.int64)
    batch = base._replace(duration_us=dur_us)
    cfg = ReplayConfig(n_services=batch.n_services, n_windows=1,
                       chunk_size=2048, window_us=10**12)
    chunks, _ = stage_columns(batch, cfg)
    out = make_replay_fn(cfg)(chunks)
    agg = np.asarray(out.agg)
    from anomod.replay import F_LOGLAT, F_LOGLAT2
    x = np.log1p(dur_us.astype(np.float64))
    for s in range(batch.n_services):
        m = batch.service == s
        n = int(m.sum())
        if n < 500:
            continue
        mean = agg[s, F_LOGLAT] / n
        var = agg[s, F_LOGLAT2] / n - mean**2
        true_var = x[m].var()
        # documented bound: rel err ~ 1.5e-5 * E[x^2]/Var ~ 0.2 at sigma=0.1;
        # assert a 30% envelope (and that var stays positive / same scale)
        assert var > 0, (s, var)
        assert abs(var - true_var) / true_var < 0.30, (s, var, true_var)


def test_replay_cli_kernel_flag(capsys):
    """`anomod replay --kernel pallas` runs the fused kernel end to end
    (interpret path on the CPU mesh) and reports which kernel ran."""
    import json

    from anomod.cli import main

    assert main(["replay", "--traces", "10", "--kernel", "pallas"]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["kernel"] == "pallas" and out["n_spans"] > 0


def test_replay_cli_sharded(capsys):
    """`anomod replay --devices N` runs the pod-sharded replay (shard_map +
    psum merge) over the virtual mesh from the CLI."""
    import json

    from anomod.cli import main

    assert main(["replay", "--traces", "10", "--devices", "8"]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["devices"] == 8 and out["n_spans"] > 0
    assert out["spans_per_sec"] > 0
    # over-asking must fail loudly, not silently shrink the mesh (the
    # reported device count is benchmark provenance)
    from anomod.parallel import make_mesh
    with pytest.raises(ValueError, match="attached"):
        make_mesh(99)
    with pytest.raises(ValueError, match="attached"):
        make_mesh(-1)
    # --replicate is a single-chip knob; combining it with --devices is
    # rejected rather than silently dropped
    with pytest.raises(SystemExit):
        main(["replay", "--traces", "10", "--devices", "8",
              "--replicate", "4"])
    capsys.readouterr()


def test_edge_percentiles_match_numpy_oracle():
    """Per-edge t-digest percentiles: each (caller->callee, window)
    segment's p50/p99 tracks the exact numpy percentile of that edge's
    spans, and a link fault surfaces as the culprit's out-edge p99."""
    from anomod import labels, synth
    from anomod.replay import (ReplayConfig, edge_keyed_batch,
                               replay_edge_percentiles)

    lab = labels.label_for("Lv_D_TRANSACTION_timeout")   # 20x latency fault
    hard = synth.HardMode(severity=1.0, fault_locus="edge")
    batch = synth.generate_spans(lab, n_traces=200, seed=5, hard=hard)
    cfg = ReplayConfig(n_services=batch.n_services, n_windows=8,
                       window_us=300_000_000)
    pct, table = replay_edge_percentiles(batch, cfg)
    eb, table2 = edge_keyed_batch(batch)
    assert table == table2
    pct = pct.reshape(len(table), cfg.n_windows, 3)
    # oracle: exact percentiles of one busy cross edge's spans per window
    t0 = int(batch.start_us.min())
    w = np.minimum((batch.start_us - t0) // cfg.window_us,
                   cfg.n_windows - 1).astype(int)
    counts = np.bincount(eb.service, minlength=len(table))
    cross = [i for i, (a, b) in enumerate(table) if a != b]
    busiest = max(cross, key=lambda i: counts[i])
    for wi in range(cfg.n_windows):
        sel = (eb.service == busiest) & (w == wi)
        if sel.sum() < 30:
            continue
        exact = np.percentile(batch.duration_us[sel], [50, 99])
        got = pct[busiest, wi, [0, 2]]
        np.testing.assert_allclose(got, exact, rtol=0.15)
    # the culprit's out-edges carry the inflated tail in the fault
    # windows vs the SAME edges' healthy windows (same traffic mix —
    # cross-service base-latency differences don't confound the ratio)
    ti = list(batch.services).index(lab.target_service)
    out_edges = [i for i, (a, b) in enumerate(table)
                 if a == ti and b != ti and counts[i] >= 20]
    assert out_edges
    hot = np.nanmax([np.nanmax(pct[i, 2:4, 2]) for i in out_edges])
    cool = np.nanmax([np.nanmax(pct[i, [0, 1, 5, 6], 2])
                      for i in out_edges])
    assert hot > 3 * cool


def test_edge_features_single_pass_matches_single_plane_entries():
    """The combined reporting entry (one re-key + staging pass) returns
    bit-identical planes to the two single-plane entries run separately —
    the CLI's --edge-percentiles view must not drift from them."""
    from anomod import labels, synth
    from anomod.replay import (replay_edge_distinct, replay_edge_features,
                               replay_edge_percentiles)

    batch = synth.generate_spans(labels.label_for("Normal_case"),
                                 n_traces=120, seed=3)
    pct, counts, table = replay_edge_features(batch)
    pct1, table1 = replay_edge_percentiles(batch)
    counts1, table2 = replay_edge_distinct(batch)
    assert table == table1 == table2
    np.testing.assert_array_equal(pct, pct1)
    np.testing.assert_array_equal(counts, counts1)


def test_edge_distinct_traces_match_exact():
    """Per-edge HLL distinct-trace counts track the exact per-edge trace
    cardinality within sketch error (p=8: exact-ish at small counts via
    linear counting, ~7% at thousands)."""
    from anomod import labels, synth
    from anomod.replay import (ReplayConfig, edge_keyed_batch,
                               replay_edge_distinct)

    batch = synth.generate_spans(labels.label_for("Normal_case"),
                                 n_traces=300, seed=1)
    counts, table = replay_edge_distinct(batch)
    eb, _ = edge_keyed_batch(batch)
    for i in range(len(table)):
        sel = eb.service == i
        exact = len(set(batch.trace[sel].tolist()))
        assert abs(counts[i] - exact) <= max(3.0, 0.1 * exact), \
            (table[i], counts[i], exact)


def test_pallas_lane_delta_interpret_matches_scatter_twin():
    """The fused TPU lane kernel's tier-1 twin: make_lane_delta(engine=
    "pallas") runs the single Mosaic kernel in INTERPRET mode on CPU
    (the TPU tunnel being down must not stop the kernel logic from
    being exercised) against the XLA:CPU scatter formulation — 0/1 and
    histogram planes exact, latency moments within the bf16 hi/lo
    envelope (the compiled-replay tolerance contract), and a dead pad
    lane's delta exactly zero."""
    import jax

    from anomod.replay import (dead_chunk, default_lane_engine,
                               make_lane_delta, stage_columns)

    assert default_lane_engine() == "scatter"     # CPU backend default
    cfg = ReplayConfig(n_services=5, n_windows=6, window_us=5_000_000,
                       chunk_size=256)
    chunks = []
    for i in range(3):
        batch = synth.generate_spans(labels.label_for("Normal_case"),
                                     n_traces=40, seed=i)
        batch = batch._replace(
            service=(batch.service % cfg.n_services).astype(np.int32),
            services=batch.services[:cfg.n_services])
        staged, _ = stage_columns(batch, cfg, t0_us=0)
        chunks.append({k: v[0] for k, v in staged.items()})
    chunks.append(dead_chunk(cfg, 256, xp=np))    # dead pad lane
    stack = {k: np.stack([c[k] for c in chunks]) for k in chunks[0]}
    sca = jax.jit(make_lane_delta(cfg, engine="scatter"))
    pal = jax.jit(make_lane_delta(cfg, engine="pallas"))
    da, dh = map(np.asarray, sca(stack))
    pa, ph = map(np.asarray, pal(stack))
    np.testing.assert_array_equal(pa[..., :3], da[..., :3])
    np.testing.assert_array_equal(ph, dh)
    np.testing.assert_allclose(pa[..., 3:6], da[..., 3:6], rtol=2e-3,
                               atol=1e-2)
    assert (pa[-1] == 0).all() and (ph[-1] == 0).all()
