"""Dry-run of scripts/tpu_watch.sh's capture-staging logic (no TPU, no JAX).

The watcher is the only thing standing between a short tunnel-revival
window and the on-chip evidence the verdicts keep asking for, so its
gating must be provably correct *before* the tunnel comes back: a fresh
tree must stage EVERY pending capture (headline triple, 4096-replicate
one-offs, roofline ablation, block sweep, per-testbed quality sweeps,
SHA-gated stream records), and a tree that already has them must re-run
only the always-on headline triple.  These tests run the real script in a
sandbox git repo with a stub ``python`` on PATH that records each
invocation and fabricates the record file the real command would write —
the shell gating (ls/grep existence checks, SHA prefix matches, the
pre-capture PROGRESS.jsonl commit) is exercised verbatim.
"""

import os
import pathlib
import stat
import subprocess

REPO = pathlib.Path(__file__).resolve().parent.parent
WATCH = REPO / "scripts" / "tpu_watch.sh"

# Stub interpreter: logs every invocation (argv + the bench env knobs),
# then fabricates the bench_runs/ record the real command would produce.
# Written in bash so the sandbox needs nothing beyond coreutils+git.
_STUB = r"""#!/bin/bash
root="$STUB_ROOT"
ts=$(date -u +%Y%m%dT%H%M%S)N$RANDOM$RANDOM
echo "ARGS=$* KERNEL=${ANOMOD_BENCH_KERNEL:-} REPL=${ANOMOD_BENCH_REPLICATE:-}" \
  >> "$root/invocations.log"
case "$*" in
  *"jax.devices()"*)         echo "tpu v5e-stub" ;;
  *bench.py*)
    cat > "$root/bench_runs/${ts}_tt_replay_throughput_tpu.json" <<EOF
{"metric": "tt_replay_throughput", "kernel": "${ANOMOD_BENCH_KERNEL}",
 "replicate_used": ${ANOMOD_BENCH_REPLICATE}}
EOF
    ;;
  *bench_kernel_roofline.py*)
    echo '{"metric": "replay_kernel_roofline"}' \
      > "$root/bench_runs/${ts}_replay_kernel_roofline_tpu.json" ;;
  *bench_block_sweep.py*)
    echo '{"metric": "pallas_block_sweep", "sorted_best_r512": [8, 4096]}' \
      > "$root/bench_runs/${ts}_pallas_block_sweep_tpu.json" ;;
  *"pytest tpu_tests"*)      : ;;
  *"anomod.cli quality"*)
    tb=$(echo "$*" | grep -o -- '--testbed [A-Z]*' | cut -d' ' -f2)
    echo "{\"metric\": \"quality_shift_sweep\", \"testbed\": \"$tb\"}" \
      > "$root/bench_runs/${ts}_quality_shift_sweep_tpu.json" ;;
  *"anomod.cli stream"*)
    tb=$(echo "$*" | grep -o -- '--testbed [A-Z]*' | cut -d' ' -f2)
    case "$*" in *edge-locus*) shift=edge-locus ;; *) shift=in-dist ;; esac
    sha=$(cd "$root" && git rev-parse HEAD)
    printf '{"metric": "stream_quality", "testbed": "%s", "shift": "%s", "git_sha": "%s"}\n' \
      "$tb" "$shift" "$sha" \
      > "$root/bench_runs/${ts}_stream_quality_tpu.json" ;;
  *) echo "unexpected stub python call: $*" >&2; exit 9 ;;
esac
exit 0
"""


def _sandbox(tmp_path):
    """Sandbox repo with the real watcher script and a stub python."""
    root = tmp_path / "repo"
    (root / "scripts").mkdir(parents=True)
    (root / "bench_runs").mkdir()
    (root / "tpu_tests").mkdir()
    (root / "scripts" / "tpu_watch.sh").write_text(WATCH.read_text())
    (root / "anomod").mkdir()   # the stream gate keys on this dir's tree hash
    (root / "anomod" / "detect.py").write_text("# detector v1\n")
    (root / "PROGRESS.jsonl").write_text('{"turn": 1}\n')
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("ANOMOD_")}
    git_env = dict(env, GIT_AUTHOR_NAME="t", GIT_AUTHOR_EMAIL="t@t",
                   GIT_COMMITTER_NAME="t", GIT_COMMITTER_EMAIL="t@t")

    def git(*args):
        return subprocess.run(["git", *args], cwd=root, env=git_env,
                              capture_output=True, text=True, check=True)

    git("init", "-q")
    git("config", "user.name", "t")
    git("config", "user.email", "t@t")
    git("add", "-A")
    git("commit", "-qm", "seed")
    bindir = tmp_path / "bin"
    bindir.mkdir()
    stub = bindir / "python"
    stub.write_text(_STUB)
    stub.chmod(stub.stat().st_mode | stat.S_IXUSR)
    env["PATH"] = f"{bindir}:{env['PATH']}"
    env["STUB_ROOT"] = str(root)
    return root, env, git


def _run_watcher(root, env):
    return subprocess.run(
        ["bash", str(root / "scripts" / "tpu_watch.sh")],
        cwd=root, env=env, capture_output=True, text=True, timeout=120)


def _invocations(root):
    return (root / "invocations.log").read_text().splitlines()


def test_fresh_tree_stages_every_pending_capture(tmp_path):
    root, env, git = _sandbox(tmp_path)
    # dirty the driver-owned progress log: the pre-capture commit must
    # scrub it so the captures carry a clean SHA
    (root / "PROGRESS.jsonl").write_text('{"turn": 2}\n')
    r = _run_watcher(root, env)
    assert r.returncode == 0, r.stdout + r.stderr
    inv = "\n".join(_invocations(root))
    # headline triple (always-on)
    assert "KERNEL=pallas-sorted REPL=4096" in inv
    assert "bench.py 20000 KERNEL=pallas REPL=64" in inv
    assert "bench.py 20000 KERNEL=xla REPL=64" in inv
    # 4096-replicate one-offs for the like-for-like ratios
    assert "bench.py KERNEL=pallas REPL=4096" in inv
    assert "bench.py KERNEL=xla REPL=4096" in inv
    # roofline ablation (the round-4 verdict's missing hook)
    assert "bench_kernel_roofline.py" in inv
    # block sweep, Mosaic parity suite, per-testbed sweeps, stream records
    assert "bench_block_sweep.py" in inv
    assert "pytest tpu_tests" in inv
    for tb in ("TT", "SN"):
        assert f"quality --testbed {tb}" in inv
    assert inv.count("anomod.cli stream") == 4  # 2 testbeds x 2 shifts
    # pre-capture hygiene commit: progress log committed separately, so
    # the capture SHA is clean and the record commit is pathspec-scoped
    log = git("log", "--format=%s").stdout
    assert "progress log sync (tpu_watch pre-capture)" in log
    assert "Record on-chip bench captures" in log
    status = git("status", "--porcelain", "-uno").stdout.strip()
    assert status == "", status


def test_satisfied_tree_reruns_only_headline_triple(tmp_path):
    root, env, git = _sandbox(tmp_path)
    first = _run_watcher(root, env)
    assert first.returncode == 0, first.stdout + first.stderr
    (root / "invocations.log").unlink()
    second = _run_watcher(root, env)
    assert second.returncode == 0, second.stdout + second.stderr
    inv = _invocations(root)
    bench_calls = [l for l in inv if "bench.py" in l]
    # the always-on headline triple reruns; every one-off is gated out
    assert len(bench_calls) == 3, bench_calls
    assert not any("roofline" in l for l in inv)
    assert not any("block_sweep" in l for l in inv)
    assert not any("anomod.cli" in l for l in inv)


def test_stream_gate_reopens_on_detector_change_only(tmp_path):
    """The stream captures are gated on the anomod/ code-tree hash: a
    commit outside anomod/ (e.g. the watcher's own bench_runs/ record
    commit, or docs) must NOT re-stage them, while a detector change must
    re-stage all four — with the existence-gated one-offs staying retired
    either way."""
    root, env, git = _sandbox(tmp_path)
    assert _run_watcher(root, env).returncode == 0
    # non-detector commit: gate stays closed
    (root / "newfile.txt").write_text("x\n")
    git("add", "newfile.txt")
    git("commit", "-qm", "docs-only change")
    (root / "invocations.log").unlink()
    assert _run_watcher(root, env).returncode == 0
    inv = _invocations(root)
    assert sum("anomod.cli stream" in l for l in inv) == 0, inv
    # detector commit: all four stream captures re-stage
    (root / "anomod" / "detect.py").write_text("# detector v2\n")
    git("add", "anomod/detect.py")
    git("commit", "-qm", "detector evolved")
    (root / "invocations.log").unlink()
    assert _run_watcher(root, env).returncode == 0
    inv = _invocations(root)
    assert sum("anomod.cli stream" in l for l in inv) == 4, inv
    assert not any("roofline" in l for l in inv)
