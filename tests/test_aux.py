"""Aux subsystems: checkpoint/resume, tracing, explicit collectives."""

import numpy as np
import pytest

from anomod.utils.checkpoint import restore_train_state, save_train_state
from anomod.utils.tracing import Tracer


def test_checkpoint_roundtrip(tmp_path):
    import jax.numpy as jnp
    import optax
    params = {"dense": {"kernel": jnp.arange(12.0).reshape(3, 4),
                        "bias": jnp.zeros(4)}}
    tx = optax.adam(1e-3)
    opt_state = tx.init(params)
    backend = save_train_state(tmp_path / "ck", params, opt_state, step=42,
                               meta={"model": "gcn"})
    assert backend in ("orbax", "pickle")
    p2, o2, step, meta = restore_train_state(tmp_path / "ck")
    assert step == 42
    assert meta["model"] == "gcn"
    np.testing.assert_array_equal(np.asarray(p2["dense"]["kernel"]),
                                  np.arange(12.0).reshape(3, 4))
    # structure must survive (optax namedtuples), not just leaf values:
    # a resumed tx.update must work on the restored state
    import jax
    import jax.numpy as jnp2
    assert (jax.tree_util.tree_structure(o2)
            == jax.tree_util.tree_structure(opt_state))
    grads = jax.tree_util.tree_map(jnp2.ones_like, p2)
    updates, _ = tx.update(grads, o2, p2)
    assert jax.tree_util.tree_leaves(updates)


def test_checkpoint_meta_cannot_clobber_step(tmp_path):
    import jax.numpy as jnp
    save_train_state(tmp_path / "ck", {"w": jnp.ones(2)}, (), step=42,
                     meta={"step": 99})
    _, _, step, _ = restore_train_state(tmp_path / "ck")
    assert step == 42


def test_tracer_jaeger_roundtrip(tmp_path):
    from anomod.io.sn_traces import load_jaeger_json
    tr = Tracer("anomod-test")
    with tr.span("pipeline"):
        with tr.span("load"):
            pass
        with tr.span("detect"):
            pass
    path = tmp_path / "trace.json"
    tr.dump(path)
    batch = load_jaeger_json(path)
    assert batch.n_spans == 3
    assert batch.services == ("anomod-test",)
    # parent structure: load/detect are children of pipeline
    assert (batch.parent == -1).sum() == 1


def test_ring_allreduce_matches_psum():
    import jax
    import jax.numpy as jnp
    from anomod.parallel.mesh import shard_map_compat as shard_map
    from jax.sharding import PartitionSpec as P
    from anomod.parallel import make_mesh
    from anomod.parallel.collectives import ring_allreduce

    mesh = make_mesh(8)
    x = np.arange(8 * 4, dtype=np.float32).reshape(8, 4)

    def body(xs):
        local = xs[0]
        return ring_allreduce(local, "data")[None]

    fn = shard_map(body, mesh=mesh, in_specs=(P("data"),), out_specs=P("data"))
    out = np.asarray(jax.jit(fn)(x))
    expect = x.sum(axis=0)
    for d in range(8):
        np.testing.assert_allclose(out[d], expect, rtol=1e-6)


def test_hll_pmax_merge_across_shards():
    import jax
    import jax.numpy as jnp
    from anomod.parallel.mesh import shard_map_compat as shard_map
    from jax.sharding import PartitionSpec as P
    from anomod.ops import hll_add, hll_estimate, hll_init
    from anomod.parallel import make_mesh
    from anomod.parallel.collectives import pmax_merge_hll

    p = 10
    items = (np.arange(64_000, dtype=np.int64) * 2654435761 % (2**31)
             ).astype(np.int32).reshape(8, -1)
    mesh = make_mesh(8)

    def body(shard_items):
        regs = hll_add(hll_init(p, xp=jnp), shard_items[0], p=p, xp=jnp)
        return pmax_merge_hll(regs, "data")[None]

    fn = shard_map(body, mesh=mesh, in_specs=(P("data"),), out_specs=P("data"))
    out = np.asarray(jax.jit(fn)(items))
    est = hll_estimate(out[0])
    assert abs(est - 64_000) / 64_000 < 0.08
    # all shards hold the identical merged state
    for d in range(1, 8):
        np.testing.assert_array_equal(out[d], out[0])


def test_tdigest_allgather_merge_across_shards():
    import jax
    import jax.numpy as jnp
    from anomod.parallel.mesh import shard_map_compat as shard_map
    from jax.sharding import PartitionSpec as P
    from anomod.ops import tdigest_build, tdigest_quantile
    from anomod.parallel import make_mesh
    from anomod.parallel.collectives import allgather_merge_tdigests

    rng = np.random.default_rng(0)
    vals = rng.lognormal(3.0, 1.0, (8, 4000)).astype(np.float32)
    mesh = make_mesh(8)

    def body(shard_vals):
        d = tdigest_build(shard_vals[0], k=64, xp=jnp)
        m, w = allgather_merge_tdigests(d.mean, d.weight, "data", k=64)
        return m[None], w[None]

    fn = shard_map(body, mesh=mesh, in_specs=(P("data"),),
                   out_specs=(P("data"), P("data")))
    mean, weight = jax.jit(fn)(vals)
    from anomod.ops.tdigest import TDigest
    d = TDigest(mean=np.asarray(mean)[0], weight=np.asarray(weight)[0])
    for q in (0.5, 0.99):
        exact = np.quantile(vals.reshape(-1), q)
        assert abs(tdigest_quantile(d, q) - exact) / exact < 0.05


def test_train_rca_checkpoint_resume(tmp_path):
    """An interrupted training run resumes from its checkpoint: train N
    epochs with a checkpoint dir, then 'resume' a fresh call which must
    (a) load the saved epoch instead of restarting, (b) produce a valid
    eval, and (c) refuse a checkpoint from a different model."""
    import pytest

    from anomod.rca import train_rca

    ck = tmp_path / "ck"
    kwargs = dict(testbed="TT", model_name="gcn", train_seeds=range(2),
                  eval_seeds=range(100, 101), n_traces=12, save_every=10)
    train_rca(epochs=12, checkpoint_dir=ck, **kwargs)
    # saved at epoch 10 (periodic) and 12 (final); final wins
    import json
    assert json.loads((ck / "meta.json").read_text())["step"] == 12
    r = train_rca(epochs=16, checkpoint_dir=ck, resume=True, **kwargs)
    assert json.loads((ck / "meta.json").read_text())["step"] == 16
    assert 0.0 <= r.top1 <= 1.0
    # a no-op resume (target epochs already reached) must not rewind the
    # completed-epoch counter
    train_rca(epochs=12, checkpoint_dir=ck, resume=True, **kwargs)
    assert json.loads((ck / "meta.json").read_text())["step"] == 16
    # model / testbed mismatches are rejected
    with pytest.raises(ValueError, match="model"):
        train_rca(epochs=16, model_name="gat", testbed="TT",
                  train_seeds=range(2), eval_seeds=range(100, 101),
                  n_traces=12, checkpoint_dir=ck, resume=True)
    with pytest.raises(ValueError, match="testbed"):
        train_rca(epochs=16, model_name="gcn", testbed="SN",
                  train_seeds=range(2), eval_seeds=range(100, 101),
                  n_traces=12, checkpoint_dir=ck, resume=True)
    # resume with no checkpoint yet starts fresh instead of crashing
    # (always-pass-resume job scripts)
    fresh = tmp_path / "fresh"
    train_rca(epochs=2, checkpoint_dir=fresh, resume=True, **kwargs)
    assert json.loads((fresh / "meta.json").read_text())["step"] == 2


def test_checkpoint_versioned_publish(tmp_path):
    """Crash-safety layout: state lives in a v<step> dir named by meta.json
    (written last, atomically); superseded versions are GC'd; the legacy
    flat layout still restores."""
    import json
    import pickle

    import numpy as np

    from anomod.utils.checkpoint import (has_checkpoint, restore_train_state,
                                         save_train_state)

    ck = tmp_path / "ck"
    assert not has_checkpoint(ck)
    params = {"w": np.arange(4, dtype=np.float32)}
    save_train_state(ck, params, {"m": np.zeros(4, np.float32)}, step=10)
    assert has_checkpoint(ck)
    meta = json.loads((ck / "meta.json").read_text())
    assert meta["version"] == "v10" and (ck / "v10").is_dir()
    save_train_state(ck, params, {"m": np.ones(4, np.float32)}, step=20)
    assert not (ck / "v10").exists()        # GC'd after publish
    p, o, step, _ = restore_train_state(ck)
    assert step == 20 and float(o["m"][0]) == 1.0
    # legacy flat layout (pre-versioning checkpoints) still restores
    legacy = tmp_path / "legacy"
    legacy.mkdir()
    with open(legacy / "state.pkl", "wb") as f:
        pickle.dump((params, {"m": np.full(4, 7.0, np.float32)}), f)
    (legacy / "meta.json").write_text(json.dumps({"step": 5}))
    p, o, step, _ = restore_train_state(legacy)
    assert step == 5 and float(o["m"][0]) == 7.0
    assert has_checkpoint(legacy)
    # a torn legacy checkpoint (meta written, state never landed) is NOT
    # restorable and must read as no-checkpoint so resume starts fresh
    torn = tmp_path / "torn"
    torn.mkdir()
    (torn / "meta.json").write_text(json.dumps({"step": 50}))
    assert not has_checkpoint(torn)
    # orbax state without its treedef companion is equally unrestorable
    torn2 = tmp_path / "torn2"
    (torn2 / "v9" / "state.orbax").mkdir(parents=True)
    (torn2 / "meta.json").write_text(json.dumps({"step": 9,
                                                 "version": "v9"}))
    assert not has_checkpoint(torn2)
