"""Ingest fast-path tests: content-addressed cache correctness (warm ==
cold bit-identical, invalidation on source change and loader-version bump,
corrupt-entry fallback), parallel-loader parity, the double-buffered
prefetcher, the env contract, and the pre-bench cold-cache gate."""

import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from anomod import labels, synth
from anomod.config import Config
from anomod.io import cache, dataset
from anomod.io import metrics as met_io

SCRIPTS = Path(__file__).parent.parent / "scripts"


def _cfg(tmp_path, **kw):
    kw.setdefault("data_root", tmp_path / "data")
    kw.setdefault("cache_dir", tmp_path / "cache")
    return Config(**kw)


def _assert_batches_equal(a, b, ctx=""):
    if a is None or b is None:
        assert a is b, ctx
        return
    for f in a._fields:
        x, y = getattr(a, f), getattr(b, f)
        if isinstance(x, np.ndarray):
            assert x.dtype == y.dtype, (ctx, f)
            np.testing.assert_array_equal(x, y, err_msg=f"{ctx}.{f}")
        else:
            assert x == y, (ctx, f)


def _assert_experiments_equal(e1, e2):
    assert e1.name == e2.name and e1.testbed == e2.testbed
    assert e1.synthetic == e2.synthetic
    _assert_batches_equal(e1.spans, e2.spans, "spans")
    _assert_batches_equal(e1.metrics, e2.metrics, "metrics")
    _assert_batches_equal(e1.logs, e2.logs, "logs")
    _assert_batches_equal(e1.api, e2.api, "api")
    _assert_batches_equal(e1.coverage, e2.coverage, "coverage")
    assert e1.log_summaries == e2.log_summaries


def test_warm_load_bit_identical_all_modalities(tmp_path):
    """Warm load == cold load, bit for bit, for all five modalities
    (synth-fallback corpus: the shipped checkout's situation)."""
    cfg = _cfg(tmp_path)
    cold = dataset.load_experiment("Lv_P_CPU_preserve", cfg=cfg,
                                   n_synth_traces=20)
    cache.reset_stats()
    warm = dataset.load_experiment("Lv_P_CPU_preserve", cfg=cfg,
                                   n_synth_traces=20)
    assert cache.stats().hits == 5 and cache.stats().misses == 0
    _assert_experiments_equal(cold, warm)
    assert warm.synthetic


def _write_tt_metric_tree(cfg, label, value_shift=0.0):
    d = (cfg.tt_data / "metric_data"
         / f"{label.experiment}_20251103T185917Z_em")
    d.mkdir(parents=True, exist_ok=True)
    m = synth.generate_metrics(label, duration_s=120)
    if value_shift:
        m = m._replace(value=m.value + value_shift)
    met_io.write_metric_batch_tt_csv(m, d / "exp_metrics_1.csv")
    return d / "exp_metrics_1.csv"


def test_invalidation_on_source_file_change(tmp_path):
    """Rewriting a source artifact (new size/mtime) must invalidate the
    entry: the reload parses the NEW content instead of serving stale."""
    cfg = _cfg(tmp_path)
    label = labels.label_for("Lv_D_cachelimit")
    art = _write_tt_metric_tree(cfg, label)
    m1 = dataset.load_experiment(label.experiment, cfg=cfg,
                                 modalities=["metrics"]).metrics
    cache.reset_stats()
    m1b = dataset.load_experiment(label.experiment, cfg=cfg,
                                  modalities=["metrics"]).metrics
    assert cache.stats().hits == 1
    _assert_batches_equal(m1, m1b, "metrics")

    _write_tt_metric_tree(cfg, label, value_shift=100.0)
    os.utime(art, ns=(1, 1))     # force a distinct mtime_ns fingerprint
    cache.reset_stats()
    m2 = dataset.load_experiment(label.experiment, cfg=cfg,
                                 modalities=["metrics"]).metrics
    assert cache.stats().misses >= 1
    assert float(np.nanmean(m2.value)) > float(np.nanmean(m1.value)) + 50


def test_invalidation_on_loader_version_bump(tmp_path, monkeypatch):
    cfg = _cfg(tmp_path)
    label = labels.label_for("Lv_D_cachelimit")
    _write_tt_metric_tree(cfg, label)
    dataset.load_experiment(label.experiment, cfg=cfg,
                            modalities=["metrics"])
    monkeypatch.setattr(met_io, "LOADER_VERSION",
                        met_io.LOADER_VERSION + 1)
    cache.reset_stats()
    dataset.load_experiment(label.experiment, cfg=cfg,
                            modalities=["metrics"])
    assert cache.stats().misses >= 1, \
        "a loader-version bump must invalidate that modality's entries"


def test_synth_version_bump_invalidates_synth_entries(tmp_path, monkeypatch):
    cfg = _cfg(tmp_path)
    dataset.load_experiment("Lv_P_CPU_preserve", cfg=cfg,
                            modalities=["traces"], n_synth_traces=10)
    monkeypatch.setattr(synth, "SYNTH_VERSION", synth.SYNTH_VERSION + 1)
    cache.reset_stats()
    dataset.load_experiment("Lv_P_CPU_preserve", cfg=cfg,
                            modalities=["traces"], n_synth_traces=10)
    assert cache.stats().misses >= 1


def test_corrupt_cache_entry_falls_back_to_reparse(tmp_path):
    """A truncated/garbage payload is a miss, not a crash — and the reload
    re-publishes a good entry."""
    cfg = _cfg(tmp_path)
    cold = dataset.load_experiment("Lv_S_KILLPOD_preserve", cfg=cfg,
                                   n_synth_traces=15)
    payloads = sorted((tmp_path / "cache").glob("*/*.npc"))
    assert payloads
    for p in payloads:
        p.write_bytes(p.read_bytes()[: max(8, p.stat().st_size // 3)])
    cache.reset_stats()
    again = dataset.load_experiment("Lv_S_KILLPOD_preserve", cfg=cfg,
                                    n_synth_traces=15)
    assert cache.stats().errors >= 1 and cache.stats().hits == 0
    _assert_experiments_equal(cold, again)
    cache.reset_stats()
    dataset.load_experiment("Lv_S_KILLPOD_preserve", cfg=cfg,
                            n_synth_traces=15)
    assert cache.stats().hits == 5, "re-parse must re-publish the entries"


def test_cache_disabled_still_loads(tmp_path):
    cfg = _cfg(tmp_path, cache_dir=None)
    exp = dataset.load_experiment("Lv_P_CPU_preserve", cfg=cfg,
                                  n_synth_traces=10)
    assert exp.spans is not None and exp.spans.n_spans > 0
    assert cache.entry_count(tmp_path / "cache") == 0


def test_parallel_loader_matches_serial(tmp_path):
    """Pool-loaded corpus == serial corpus (same Experiment fields, same
    synthetic flags), including the LFS-stub + synth-fallback path."""
    cfg = _cfg(tmp_path)
    # one experiment gets an LFS-pointer trace artifact: the loader must
    # see the stub, fall back to synth, and still match across pool/serial
    label = labels.label_for("Lv_P_CPU_preserve")
    d = (cfg.tt_data / "trace_data"
         / f"{label.experiment}_20251103T185917Z_em")
    d.mkdir(parents=True)
    (d / f"{label.experiment}_skywalking_traces_x.json").write_text(
        "version https://git-lfs.github.com/spec/v1\n"
        "oid sha256:deadbeef\nsize 12345\n")
    serial = dataset.load_corpus("TT", cfg=cfg, n_synth_traces=10,
                                 workers=0)
    cache.reset_stats()
    pooled = dataset.load_corpus("TT", cfg=cfg, n_synth_traces=10,
                                 workers=2)
    assert len(serial) == len(pooled) == 13
    for e1, e2 in zip(serial, pooled):
        _assert_experiments_equal(e1, e2)
    assert any(e.synthetic for e in pooled)
    # worker-process cache counters must merge back into this process
    assert cache.stats().hits >= 65


def test_prefetch_pipeline_preserves_order_and_values():
    from anomod.io.prefetch import Pipeline, iter_chunk_dicts
    chunks = {"a": np.arange(12).reshape(3, 4),
              "b": np.arange(12, 24).reshape(3, 4)}
    staged = list(Pipeline(iter_chunk_dicts(chunks), fn=lambda d: d))
    assert len(staged) == 3
    for i, d in enumerate(staged):
        np.testing.assert_array_equal(d["a"], chunks["a"][i])
        np.testing.assert_array_equal(d["b"], chunks["b"][i])


def test_prefetch_pipeline_propagates_worker_errors():
    from anomod.io.prefetch import Pipeline

    def bad():
        yield 1
        raise RuntimeError("boom")

    it = Pipeline(bad(), fn=lambda x: x * 2)
    assert next(it) == 2
    with pytest.raises(RuntimeError, match="boom"):
        list(it)


def test_device_put_columns_matches_direct_put():
    from anomod.io.prefetch import device_put_columns
    cols = {"x": np.arange(100, dtype=np.int32),
            "y": np.linspace(0, 1, 50, dtype=np.float32)}
    staged = device_put_columns(cols)
    assert set(staged) == {"x", "y"}
    for k in cols:
        np.testing.assert_array_equal(np.asarray(staged[k]), cols[k])


def test_env_contract(monkeypatch):
    monkeypatch.setenv("ANOMOD_CACHE_DIR", "off")
    assert Config().cache_dir is None
    monkeypatch.setenv("ANOMOD_CACHE_DIR", "/tmp/somewhere")
    assert Config().cache_dir == Path("/tmp/somewhere")
    monkeypatch.setenv("ANOMOD_INGEST_WORKERS", "4")
    assert Config().ingest_workers == 4
    monkeypatch.setenv("ANOMOD_INGEST_WORKERS", "many")
    with pytest.raises(ValueError, match="ANOMOD_INGEST_WORKERS"):
        Config()
    monkeypatch.setenv("ANOMOD_INGEST_WORKERS", "-2")
    with pytest.raises(ValueError, match="ANOMOD_INGEST_WORKERS"):
        Config()


def test_pre_bench_gate_refuses_cold_cache(tmp_path):
    env = dict(os.environ, ANOMOD_CACHE_DIR=str(tmp_path / "cache"),
               ANOMOD_DATA_ROOT=str(tmp_path / "data"))
    script = str(SCRIPTS / "pre_bench_check.py")

    r = subprocess.run([sys.executable, script, "--traces", "40"],
                       capture_output=True, text=True, timeout=120, env=env)
    assert r.returncode == 1, r.stdout + r.stderr
    assert json.loads(r.stdout)["status"] == "cold"

    r = subprocess.run([sys.executable, script, "--traces", "40", "--cold"],
                       capture_output=True, text=True, timeout=120, env=env)
    assert r.returncode == 0

    # warm the exact bench key, then the gate passes
    cfg = _cfg(tmp_path)
    dataset.load_bench_corpus("TT", 40, cfg)
    r = subprocess.run([sys.executable, script, "--traces", "40"],
                       capture_output=True, text=True, timeout=120, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    assert json.loads(r.stdout)["status"] == "warm"

    # disabled caching is also a refusal (nothing can ever be warm)
    env["ANOMOD_CACHE_DIR"] = "off"
    r = subprocess.run([sys.executable, script, "--traces", "40"],
                       capture_output=True, text=True, timeout=120, env=env)
    assert r.returncode == 2


def test_ingest_cli_warm_cache(tmp_path, capsys):
    from anomod.cli import main
    rc = main(["ingest", "--warm-cache", "--testbed", "TT",
               "--traces", "8", "--bench-traces", "0",
               "--cache-dir", str(tmp_path / "c"),
               "--data-root", str(tmp_path / "d")])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["entries"] == out["stores"] > 0
    assert out["warmed"] == ["TT"]
    # second warm pass: all hits, no new stores
    rc = main(["ingest", "--warm-cache", "--testbed", "TT",
               "--traces", "8", "--bench-traces", "0",
               "--cache-dir", str(tmp_path / "c"),
               "--data-root", str(tmp_path / "d")])
    assert rc == 0
    out2 = json.loads(capsys.readouterr().out)
    assert out2["misses"] == 0 and out2["hits"] >= 65


def test_bench_corpus_cold_warm_accounting(tmp_path):
    cfg = _cfg(tmp_path)
    b1, cold = dataset.load_bench_corpus("TT", 60, cfg)
    assert not cold["cache_hit"] and cold["parse_s"] > 0
    b2, warm = dataset.load_bench_corpus("TT", 60, cfg)
    assert warm["cache_hit"]
    assert warm["parse_s"] == pytest.approx(cold["parse_s"])
    _assert_batches_equal(b1, b2, "bench-corpus")
    assert dataset.bench_cache_status("TT", 60, cfg) == (1, 1)
    assert dataset.bench_cache_status("TT", 61, cfg) == (0, 1)
