"""Sharded replay on the virtual 8-device CPU mesh: parity with single-chip."""

import numpy as np
import pytest

from anomod import labels, synth
from anomod.parallel import make_mesh, sharded_throughput
from anomod.parallel.mesh import shard_chunks
from anomod.replay import ReplayConfig, replay_numpy, stage_columns
from anomod.schemas import concat_span_batches


@pytest.fixture(scope="module")
def batch():
    return concat_span_batches([
        synth.generate_spans(l, n_traces=30)
        for l in labels.labels_for_testbed("TT")])


def test_mesh_has_8_virtual_devices():
    import jax
    assert len(jax.devices()) == 8
    mesh = make_mesh()
    assert mesh.devices.size == 8


def test_shard_chunks_preserves_rows(batch):
    cfg = ReplayConfig(n_services=batch.n_services, chunk_size=512)
    chunks, n = stage_columns(batch, cfg)
    sh = shard_chunks(chunks, 8, dead_sid=cfg.sw)
    assert sh["sid"].shape[0] == 8
    assert int(sh["valid"].sum()) == n
    # fill chunks carry the DEAD segment id, never a real one (the old
    # sid.max() heuristic leaked a real sid when the corpus length was an
    # exact chunk multiple — the HLL plane then counted phantom traces)
    pad_rows = sh["sid"].reshape(-1, sh["sid"].shape[-1])[chunks["sid"].shape[0]:]
    assert pad_rows.size == 0 or (pad_rows == cfg.sw).all()


def test_sharded_replay_matches_numpy(batch):
    cfg = ReplayConfig(n_services=batch.n_services, chunk_size=512)
    chunks, n = stage_columns(batch, cfg)
    ref = replay_numpy(chunks, cfg)
    mesh = make_mesh()
    r = sharded_throughput(batch, mesh, cfg, repeats=1)
    assert r.n_spans == n
    # independently recompute the state for assertion
    from anomod.parallel.replay import make_sharded_replay_fn
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    sharded = shard_chunks(chunks, 8, dead_sid=cfg.sw)
    flat = {k: v.reshape(-1, v.shape[-1]) for k, v in sharded.items()}
    dev = {k: jax.device_put(v, NamedSharding(mesh, P("data")))
           for k, v in flat.items()}
    out = make_sharded_replay_fn(cfg, mesh)(dev)
    np.testing.assert_allclose(np.asarray(out.agg), ref.agg, rtol=1e-3)
    np.testing.assert_allclose(np.asarray(out.hist), ref.hist, rtol=1e-6)
    assert int(np.asarray(out.agg)[:, 0].sum()) == batch.n_spans
    # the fused pallas kernel composed with shard_map + psum agrees too
    # (interpret path on the CPU mesh)
    pout = make_sharded_replay_fn(cfg, mesh, kernel="pallas")(dev)
    np.testing.assert_allclose(np.asarray(pout.agg), ref.agg, rtol=1e-3)
    np.testing.assert_allclose(np.asarray(pout.hist), ref.hist, rtol=1e-6)


def test_graft_entry_dryrun():
    import sys
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as g
    fn, args = g.entry()
    import jax
    out = jax.jit(fn)(*args)
    assert out.shape[0] > 0
    g.dryrun_multichip(8)


def test_hybrid_mesh_single_process():
    from anomod.parallel.multihost import (dcn_data_parallel_spec,
                                           initialize_distributed,
                                           make_hybrid_mesh)
    initialize_distributed()  # no-op single-process
    mesh = make_hybrid_mesh()
    assert mesh.axis_names == ("dcn", "data")
    assert mesh.shape["dcn"] == 1
    assert mesh.shape["data"] == 8
    spec = dcn_data_parallel_spec(mesh)
    assert spec == __import__("jax").sharding.PartitionSpec(("dcn", "data"))


def test_seqpar_linear_recurrence_matches_single_device():
    import jax
    import jax.numpy as jnp
    from anomod.parallel import make_mesh
    from anomod.parallel.seqscan import linear_recurrence, make_seqpar_recurrence

    rng = np.random.default_rng(3)
    T, S, F = 64, 12, 5          # 64 windows sharded over 8 devices
    xs = rng.normal(0, 1, (T, S, F)).astype(np.float32)
    decay = rng.uniform(0.5, 0.99, (S, F)).astype(np.float32)

    ref = np.asarray(linear_recurrence(jnp.asarray(xs), jnp.asarray(decay)))
    # sequential oracle
    h = np.zeros((S, F), np.float32)
    seq = np.zeros_like(xs)
    for t in range(T):
        h = decay * h + xs[t]
        seq[t] = h
    np.testing.assert_allclose(ref, seq, rtol=1e-4, atol=1e-5)

    mesh = make_mesh(8)
    fn = make_seqpar_recurrence(mesh)
    out = np.asarray(fn(jnp.asarray(xs), jnp.asarray(decay)))
    np.testing.assert_allclose(out, seq, rtol=1e-4, atol=1e-5)


def test_sharded_replay_hll_plane(batch):
    """with_hll=True: the sharded distinct-trace registers (per-shard
    scatter-max + pmax over ICI) are register-EXACT vs the single-chip
    with_hll replay, for both per-shard kernels, and the estimates track
    the true distinct-trace counts."""
    from anomod.ops.hll import hll_estimate
    from anomod.parallel.replay import make_sharded_replay_fn, stage_sharded
    from anomod.replay import make_replay_fn

    cfg = ReplayConfig(n_services=batch.n_services, chunk_size=512)
    chunks, n = stage_columns(batch, cfg)
    single = make_replay_fn(cfg, with_hll=True)(
        {k: np.asarray(v) for k, v in chunks.items()})
    ref_regs = np.asarray(single.hll)
    assert ref_regs.shape == (cfg.n_services, cfg.hll_m)

    mesh = make_mesh()
    dev, _ = stage_sharded(batch, mesh, cfg)
    for kernel in ("xla", "pallas"):
        out = make_sharded_replay_fn(cfg, mesh, kernel=kernel,
                                     with_hll=True)(dev)
        np.testing.assert_array_equal(np.asarray(out.hll), ref_regs,
                                      err_msg=kernel)
    # estimates track the exact per-service distinct-trace counts
    est = hll_estimate(ref_regs)
    svc_of_span = batch.service
    for s in np.unique(svc_of_span)[:5]:
        true = len(np.unique(batch.trace[svc_of_span == s]))
        assert abs(est[s] - true) / max(true, 1) < 0.25, (s, est[s], true)


def test_sharded_hll_exact_chunk_multiple_no_phantom():
    """Regression for the shard_chunks dead-sid bug: when the corpus length
    is an exact chunk multiple (stage_columns adds NO padding rows) and the
    chunk count doesn't divide the mesh, the shard-padding chunks must not
    leak a phantom trace id into the HLL registers."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from anomod.parallel.replay import make_sharded_replay_fn
    from anomod.replay import make_replay_fn

    cfg = ReplayConfig(n_services=4, n_windows=2, chunk_size=128, hll_p=6)
    rng = np.random.default_rng(3)
    n = cfg.chunk_size * 3            # exact multiple, 3 chunks on 8 devices
    chunks = {
        "sid": rng.integers(0, cfg.sw, n).astype(np.int32),
        "tid": rng.integers(1, 50, n).astype(np.int32),
        "dur": rng.uniform(1, 5, n).astype(np.float32),
        "dur_raw": rng.uniform(10, 50, n).astype(np.float32),
        "err": np.zeros(n, np.float32),
        "s5": np.zeros(n, np.float32),
        "valid": np.ones(n, np.float32),
    }
    chunks = {k: v.reshape(3, cfg.chunk_size) for k, v in chunks.items()}
    single = make_replay_fn(cfg, with_hll=True)(chunks)

    mesh = make_mesh()
    sharded = shard_chunks(chunks, 8, dead_sid=cfg.sw)
    flat = {k: v.reshape(-1, v.shape[-1]) for k, v in sharded.items()}
    dev = {k: jax.device_put(v, NamedSharding(mesh, P("data")))
           for k, v in flat.items()}
    out = make_sharded_replay_fn(cfg, mesh, with_hll=True)(dev)
    np.testing.assert_array_equal(np.asarray(out.hll),
                                  np.asarray(single.hll))


def test_sharded_replay_scattered_merge(batch):
    """merge='scattered' (psum_scatter): each device keeps its SW/D slice;
    reassembled across shards the state equals the replicated-psum merge
    exactly (same reduction, half the ICI traffic)."""
    import pytest

    from anomod.parallel.replay import make_sharded_replay_fn, stage_sharded

    cfg = ReplayConfig(n_services=batch.n_services, chunk_size=512)
    assert cfg.sw % 8 == 0
    mesh = make_mesh()
    dev, _ = stage_sharded(batch, mesh, cfg)
    rep = make_sharded_replay_fn(cfg, mesh)(dev)
    sc = make_sharded_replay_fn(cfg, mesh, merge="scattered")(dev)
    # the scattered output is a global array sharded over dim 0; asarray
    # reassembles the full state
    np.testing.assert_allclose(np.asarray(sc.agg), np.asarray(rep.agg),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(sc.hist), np.asarray(rep.hist),
                               rtol=1e-6)
    # each shard holds exactly SW/8 rows
    assert sc.agg.sharding.shard_shape(sc.agg.shape)[0] == cfg.sw // 8
    with pytest.raises(ValueError, match="divisible"):
        make_sharded_replay_fn(
            ReplayConfig(n_services=3, n_windows=3), mesh, merge="scattered")
    with pytest.raises(ValueError, match="merge mode"):
        make_sharded_replay_fn(cfg, mesh, merge="gather")
