"""Deploy-plan model: flags, secret generation, action ordering."""

import pytest

from anomod.deploy import (
    Action, DeployFlags, TT_DB_SERVICES, execute_plan, gen_mysql_secrets,
    mysql_secret_doc, render_plan, sn_compose_plan, tt_deploy_plan,
)


def test_flags_parse():
    f = DeployFlags.parse(["--with-tracing", "--with-monitoring"])
    assert f.with_tracing and f.with_monitoring
    assert not f.independent_db
    with pytest.raises(ValueError):
        DeployFlags.parse(["--bogus"])


def test_27_db_services_match_reference_list():
    assert len(TT_DB_SERVICES) == 27
    assert "order" in TT_DB_SERVICES and "wait-order" in TT_DB_SERVICES


def test_secret_env_prefix_convention():
    doc = mysql_secret_doc("consign-price", "tsdb-mysql-leader",
                           "ts", "Ts_123456", "ts")
    assert doc["metadata"]["name"] == "ts-consign-price-mysql"
    keys = doc["stringData"]
    assert keys["CONSIGN_PRICE_MYSQL_HOST"] == "tsdb-mysql-leader"
    assert keys["CONSIGN_PRICE_MYSQL_PORT"] == "3306"
    assert set(k.rsplit("_", 1)[1] for k in keys) == {
        "HOST", "PORT", "DATABASE", "USER", "PASSWORD"}


def test_shared_vs_independent_hosts():
    shared = gen_mysql_secrets(shared_host="tsdb-mysql-leader")
    assert all(next(iter(d["stringData"].values())) == "tsdb-mysql-leader"
               or "HOST" not in next(iter(d["stringData"]))
               for d in shared)
    assert {d["stringData"][k] for d in shared
            for k in d["stringData"] if k.endswith("_HOST")} == \
        {"tsdb-mysql-leader"}
    per = gen_mysql_secrets()
    hosts = {d["stringData"][k] for d in per
             for k in d["stringData"] if k.endswith("_HOST")}
    assert len(hosts) == 27 and "ts-order-mysql-leader" in hosts


def test_plan_ordering_infra_before_services():
    plan = tt_deploy_plan(DeployFlags(with_tracing=True, with_monitoring=True))
    rendered = render_plan(plan)
    # infra (nacosdb → nacos → rabbitmq) precedes tsdb, which precedes apply
    order = [rendered.index(s) for s in
             ("install nacosdb", "install nacos ", "install rabbitmq",
              "install tsdb", "secret.yaml", "svc.yaml", "sw_deploy.yaml",
              "sw_deploy.tcpserver.includes.yaml", "skywalking", "prometheus")]
    assert order == sorted(order)
    # every helm install has a rollout barrier except none (all do here)
    helm = [a for a in plan if a.kind == "helm"]
    assert all(a.barrier is not None for a in helm)


def test_independent_db_plan_has_27_mysql_releases():
    plan = tt_deploy_plan(DeployFlags(independent_db=True))
    helm = [a for a in plan if a.kind == "helm" and "-mysql" not in a.argv[2]]
    mysqls = [a for a in plan if a.kind == "helm"
              and a.argv[2].startswith("ts-")]
    assert len(mysqls) == 27
    census = execute_plan(plan)
    assert census["barriers"] == len([a for a in plan if a.barrier])


def test_no_tracing_uses_plain_deploy():
    rendered = render_plan(tt_deploy_plan(DeployFlags()))
    assert "yamls/deploy.yaml" in rendered
    assert "sw_deploy" not in rendered and "skywalking" not in rendered


def test_sn_compose_lifecycle():
    up = render_plan(sn_compose_plan(up=True))
    down = render_plan(sn_compose_plan(up=False))
    assert "docker-compose-gcov.yml up -d" in up
    assert "down --remove-orphans" in down


def test_execute_plan_advances_cluster_clock():
    from anomod.recovery import SyntheticCluster
    cluster = SyntheticCluster([])
    t0 = cluster.now
    execute_plan(tt_deploy_plan(DeployFlags(with_tracing=True)), cluster)
    assert cluster.now > t0


def test_all_flag_expands_to_full_stack():
    plan = tt_deploy_plan(DeployFlags(all=True))
    rendered = render_plan(plan)
    mysqls = [a for a in plan if a.kind == "helm" and a.argv[2].startswith("ts-")]
    assert len(mysqls) == 27                      # deploy_tt_mysql_each_service
    assert "sw_deploy.yaml" in rendered           # deploy_tt_dp_sw
    assert "skywalking" in rendered and "prometheus" in rendered
