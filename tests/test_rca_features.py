"""anomod.rca_features: the ONE windowed-feature definition shared by the
offline RCA harness (anomod.rca) and the online serve-tick RCA plane
(anomod.serve.rca) — offline batch extraction and online single-graph
extraction must be bit-exact on the same spans, forever."""

import numpy as np

from anomod.graph import build_service_graph
from anomod.replay import ReplayConfig
from anomod.schemas import SpanBatch


def _spans_with_calls(n, n_services, seed, t_span_s=60.0):
    """A batch with real parent links (cross-service calls), so the edge
    feature paths and the live service graph are non-trivial."""
    rng = np.random.default_rng(seed)
    svc = rng.integers(0, n_services, n).astype(np.int32)
    parent = np.full(n, -1, np.int32)
    # every second span is a child of the previous span (cross-service
    # where the services differ)
    parent[1::2] = np.arange(0, n - 1, 2, dtype=np.int32)
    err = rng.random(n) < 0.05
    return SpanBatch(
        trace=rng.integers(0, 16, n).astype(np.int32),
        parent=parent,
        service=svc,
        endpoint=np.zeros(n, np.int32),
        start_us=np.sort(rng.integers(0, int(t_span_s * 1e6),
                                      n)).astype(np.int64),
        duration_us=rng.integers(1, 1_000_000, n).astype(np.int64),
        is_error=err.astype(np.bool_),
        status=np.where(err, 500, 200).astype(np.int16),
        kind=np.zeros(n, np.int8),
        services=tuple(f"s{i}" for i in range(n_services)),
        endpoints=("e",),
        trace_ids=tuple(f"t{i:02d}" for i in range(16))).validate()


def test_offline_and_online_paths_share_one_definition():
    """The offline harness's underscore names must BE the shared module's
    functions (import-level identity, not copies that could drift)."""
    from anomod import rca, rca_features
    assert rca._windowed_features is rca_features.windowed_features
    assert rca._edge_feature_block is rca_features.edge_feature_block


def test_windowed_features_offline_vs_online_bit_exact():
    """The online extractor (anomod.serve.rca.online_node_features) rides
    windowed_features; its windowed block must be byte-identical to what
    the offline batch path computes on the same spans."""
    from anomod.rca import _windowed_features
    from anomod.rca_features import windowed_features
    services = tuple(f"s{i}" for i in range(5))
    cfg = ReplayConfig(n_services=5, n_windows=8, window_us=5_000_000,
                       chunk_size=1024)
    batch = _spans_with_calls(600, 5, seed=11)
    off = _windowed_features(batch, services, cfg)
    on = windowed_features(batch, services, cfg)
    assert off.dtype == np.float32 and off.shape == (5, 8, 4)
    assert off.tobytes() == on.tobytes()
    # the edge-feature variant too (the link-fault evidence channel)
    off8 = _windowed_features(batch, services, cfg, edge_features=True)
    on8 = windowed_features(batch, services, cfg, edge_features=True)
    assert off8.shape == (5, 8, 8)
    assert off8.tobytes() == on8.tobytes()


def test_edge_feature_block_offline_vs_online_bit_exact():
    from anomod.rca import _edge_feature_block
    from anomod.rca_features import edge_feature_block
    services = tuple(f"s{i}" for i in range(5))
    cfg = ReplayConfig(n_services=5, n_windows=8, window_us=5_000_000,
                       chunk_size=1024)
    batch = _spans_with_calls(600, 5, seed=13)
    g = build_service_graph(batch, services=services)
    assert g.n_edges > 0
    off = _edge_feature_block(batch, services, g, cfg)
    on = edge_feature_block(batch, services, g, cfg)
    assert off.shape == (g.n_edges, 8, 4)
    assert off.tobytes() == on.tobytes()


def test_online_node_features_reduce_windowed_block():
    """The serve-tick feature vector is a pure reduction of the shared
    windowed block: per-window means + recent-vs-early trends."""
    from anomod.rca_features import windowed_features
    from anomod.serve.rca import online_node_features
    services = tuple(f"s{i}" for i in range(5))
    cfg = ReplayConfig(n_services=5, n_windows=8, window_us=5_000_000,
                       chunk_size=1024)
    batch = _spans_with_calls(600, 5, seed=17)
    x = online_node_features(batch, services, cfg)
    wf = windowed_features(batch, services, cfg)
    q = cfg.n_windows // 4
    want = np.concatenate(
        [wf.mean(axis=1), wf[:, -q:].mean(axis=1) - wf[:, :q].mean(axis=1)],
        axis=-1).astype(np.float32)
    assert x.tobytes() == want.tobytes()
    # no spans = a well-shaped zero block, never a crash
    z = online_node_features(None, services, cfg)
    assert z.shape == (5, 8) and not z.any()


def test_pad_edge_arrays_contract():
    from anomod.rca_features import pad_edge_arrays
    import pytest
    services = tuple(f"s{i}" for i in range(5))
    batch = _spans_with_calls(600, 5, seed=19)
    g = build_service_graph(batch, services=services)
    src, dst, mask = pad_edge_arrays(g, g.n_edges + 3)
    assert src.shape == (g.n_edges + 3,) and mask.sum() == g.n_edges
    assert np.array_equal(src[:g.n_edges], g.edge_src)
    assert np.array_equal(dst[:g.n_edges], g.edge_dst)
    assert not mask[g.n_edges:].any()
    with pytest.raises(ValueError, match="edges"):
        pad_edge_arrays(g, g.n_edges - 1)
