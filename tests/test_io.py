"""Loader tests: synth JSON round-trips + golden tests on materialized
reference artifacts (gcov text, JaCoCo summaries, coverage.xml)."""

import json
from pathlib import Path

import numpy as np
import pytest

from anomod import labels, synth
from anomod.config import Config
from anomod.io import api as api_io
from anomod.io import coverage as cov_io
from anomod.io import dataset, logs as logs_io, metrics as met_io
from anomod.io import sn_traces, tt_traces

REF = Path("/root/reference")


def test_skywalking_roundtrip():
    l = labels.label_for("Lv_P_CPU_preserve")
    b = synth.generate_spans(l, n_traces=10)
    doc = synth.spans_to_skywalking_json(b, l.experiment)
    b2 = tt_traces.spans_from_skywalking(doc)
    assert b2.n_spans == b.n_spans
    assert b2.n_traces == b.n_traces
    assert set(b2.services) == set(np.array(b.services)[np.unique(b.service)])
    # parent structure survives: same number of roots
    assert (b2.parent == -1).sum() == (b.parent == -1).sum()
    # per-service error counts survive
    for svc in b2.services:
        i1 = b.services.index(svc)
        i2 = b2.services.index(svc)
        assert b.is_error[b.service == i1].sum() == b2.is_error[b2.service == i2].sum()


def test_jaeger_roundtrip():
    l = labels.label_for("Svc_Kill_Media")
    b = synth.generate_spans(l, n_traces=10)
    doc = synth.spans_to_jaeger_json(b)
    b2 = sn_traces.spans_from_jaeger(doc)
    assert b2.n_spans == b.n_spans
    assert (b2.parent == -1).sum() == (b.parent == -1).sum()
    np.testing.assert_array_equal(np.sort(b2.duration_us), np.sort(b.duration_us))


def test_api_jsonl_roundtrip(tmp_path):
    l = labels.label_for("Lv_S_HTTPABORT_preserve")
    a = synth.generate_api(l, n_records=50)
    p = tmp_path / "openapi_responses.jsonl"
    api_io.write_api_jsonl(a, p)
    a2 = api_io.load_api_jsonl(p)
    assert a2.n_records == 50
    np.testing.assert_array_equal(a2.status, a.status)
    np.testing.assert_allclose(a2.latency_ms, np.round(a.latency_ms, 2), rtol=1e-4)


def test_tt_metric_csv_roundtrip(tmp_path):
    l = labels.label_for("Lv_D_cachelimit")
    m = synth.generate_metrics(l, duration_s=300)
    p = tmp_path / "exp_metrics_x.csv"
    met_io.write_metric_batch_tt_csv(m, p)
    m2 = met_io.load_tt_metric_csv(p)
    assert m2.n_samples == m.n_samples
    assert set(m2.metric_names) == set(m.metric_names)


# ---- golden tests against materialized reference artifacts ----

@pytest.mark.skipif(not REF.is_dir(), reason="reference checkout not present")
def test_golden_tt_coverage_summary():
    # TOTAL Lines 500 Cover 43% (BASELINE.md example)
    d = REF / "TT_data/coverage_report/Lv_C_exception_injection_20251103T185917Z_em"
    batch = cov_io.load_tt_coverage_report(d)
    assert batch is not None
    i = batch.services.index("ts-order-service")
    ratio = batch.service_ratio()[i]
    assert abs(ratio - 0.43) < 0.02


@pytest.mark.skipif(not REF.is_dir(), reason="reference checkout not present")
def test_golden_sn_gcov():
    d = REF / "SN_data/coverage_data"
    exp = next(p for p in sorted(d.iterdir())
               if p.name.startswith("Perf_CPU_Contention"))
    batch = cov_io.load_sn_coverage_dir(exp)
    assert batch is not None
    assert batch.lines_total.sum() > 0
    r = batch.service_ratio()
    assert ((r >= 0) & (r <= 1)).all()


@pytest.mark.skipif(not REF.is_dir(), reason="reference checkout not present")
def test_golden_sn_log_summary():
    d = REF / "SN_data/log_data"
    exp = next(p for p in sorted(d.iterdir())
               if p.name.startswith("Normal_Baseline"))
    _, summaries = logs_io.load_sn_log_dir(exp)
    assert summaries, "summary.txt should parse"
    by_name = {s.service: s for s in summaries}
    # golden values read directly from the materialized summary.txt
    assert by_name["ComposePostService"].n_lines == 2401
    assert by_name["ComposePostService"].size_bytes == 352 * 1024
    assert len(by_name) == 12
    # an experiment with non-zero error counts
    code_exp = next(p for p in sorted(d.iterdir())
                    if p.name.startswith("Code_Stop_UserService"))
    _, s2 = logs_io.load_sn_log_dir(code_exp)
    assert any(s.n_error > 0 for s in s2)


def test_pod_to_service():
    assert logs_io.pod_to_service("ts-order-service-86d6f7876-99bhf") == "ts-order-service"
    assert logs_io.pod_to_service("nacos-0") == "nacos"
    assert logs_io.pod_to_service("rabbitmq-6767c689c-8lc9n") == "rabbitmq"


@pytest.mark.skipif(not REF.is_dir(), reason="reference checkout not present")
def test_discover_reference_experiments():
    sn = dataset.discover("SN")
    tt = dataset.discover("TT")
    assert len(sn) == 13
    assert len(tt) == 13
    for e in sn + tt:
        assert "traces" in e.dirs


@pytest.mark.skipif(not REF.is_dir(), reason="reference checkout not present")
def test_load_experiment_with_synth_fallback():
    # trace payloads are LFS stubs in the checkout -> synth fallback kicks in
    exp = dataset.load_experiment("Lv_P_CPU_preserve", n_synth_traces=20)
    assert exp.spans is not None and exp.spans.n_spans > 0
    assert exp.coverage is not None   # real (materialized XML/summary)
    assert exp.synthetic              # at least one modality was synthesized


def test_load_unknown_experiment():
    with pytest.raises(KeyError):
        dataset.load_experiment("Nope")


def test_parse_gcov():
    text = """        -:    0:Source:/x/y.cpp
        -:    1:#include <x>
        5:    2:int main() {
    #####:    3:  return 1;
        -:    4:}
"""
    fc = cov_io.parse_gcov(text, "svc", "x/y.cpp")
    assert fc.lines_total == 2
    assert fc.lines_covered == 1


def test_es_trace_loader_roundtrip(tmp_path):
    """Enhanced (Elasticsearch) collector schema -> SpanBatch."""
    import base64
    from anomod.io import tt_traces_es
    doc = {
        "timestamp": "x", "total_traces": 2,
        "traces": [
            {"trace_id": "t1",
             "service_id": base64.b64encode(b"ts-travel-service").decode() + ".1",
             "service_name": "",
             "endpoint_name": "/api/v1/travelservice/trips",
             "start_time": 1762180000000, "end_time": 1762180000150,
             "latency": 150, "is_error": 0},
            {"trace_id": "t2", "service_id": "ts-order-service.1",
             "service_name": "ts-order-service",
             "endpoint_name": "/api/v1/orderservice",
             "start_time": 1762180001000, "end_time": 1762180001500,
             "latency": 500, "is_error": 1},
        ],
    }
    p = tmp_path / "detailed_traces_x.json"
    p.write_text(json.dumps(doc))
    b = tt_traces_es.load_detailed_traces_json(p)
    assert b.n_spans == 2
    assert b.n_traces == 2
    assert tt_traces_es.decode_service_id(
        base64.b64encode(b"ts-travel-service").decode() + ".1") == "ts-travel-service"
    i = b.services.index("ts-order-service")
    assert bool(b.is_error[b.service == i][0])
    assert int(b.duration_us[b.service == i][0]) == 500_000


def test_es_trace_pattern_analysis(tmp_path):
    """ES pattern-analysis artifact: schema + values matched to the
    reference's analyze_trace_patterns / trace_analysis_<ts>.json
    (enhanced_trace_collector.py:216-296,316-323)."""
    from anomod.io import tt_traces_es
    records = [
        {"trace_id": "t1", "service_name": "ts-travel-service",
         "endpoint_name": "/trips", "start_time": 1762180000000,
         "latency": 100, "is_error": 0},
        {"trace_id": "t2", "service_name": "ts-travel-service",
         "endpoint_name": "/trips", "start_time": 1762180002000,
         "latency": 300, "is_error": 1},
        {"trace_id": "t3", "service_name": "ts-order-service",
         "endpoint_name": "/orders", "start_time": 1762180001000,
         "latency": 0, "is_error": 0},   # zero latency excluded from stats
    ]
    p = tmp_path / "detailed_traces_x.json"
    p.write_text(json.dumps({"traces": records}))
    batch = tt_traces_es.load_detailed_traces_json(p)
    a = tt_traces_es.analyze_trace_patterns(batch)
    assert a["total_traces"] == 3
    assert sorted(a["unique_services"]) == ["ts-order-service",
                                            "ts-travel-service"]
    assert a["service_call_counts"] == {"ts-travel-service": 2,
                                        "ts-order-service": 1}
    assert a["endpoint_call_counts"] == {"/trips": 2, "/orders": 1}
    assert a["error_traces"] == 1
    assert a["latency_stats"] == {"min": 100.0, "max": 300.0,
                                  "avg": 200.0, "count": 2}
    assert a["time_range"]["earliest"] == 1762180000000
    assert a["time_range"]["latest"] == 1762180002000
    assert "earliest_datetime" in a["time_range"]

    # artifact roundtrip: envelope schema + report text
    out = tt_traces_es.write_trace_analysis(batch, tmp_path / "es",
                                            timestamp="20251103_120000")
    doc = tt_traces_es.load_trace_analysis(out)
    assert doc["timestamp"] == "20251103_120000"
    assert doc["analysis"]["total_traces"] == 3
    report = (tmp_path / "es" / "trace_analysis_20251103_120000.txt"
              ).read_text()
    assert "Error rate: 33.33%" in report
    assert "1. ts-travel-service: 2 calls" in report
    assert "Avg latency: 200.00 ms" in report

    # empty corpus keeps the reference's empty-shape contract
    from anomod.schemas import empty_span_batch
    empty = tt_traces_es.analyze_trace_patterns(empty_span_batch())
    assert empty["latency_stats"] is None
    assert empty["time_range"] == {"earliest": None, "latest": None}


def test_tt_metric_csv_embedded_newline_fallback(tmp_path):
    """RFC-4180 quoted newlines desync the native line-based scanner; the
    loader must detect the row-count mismatch and fall back to pure Python
    so every row keeps its own timestamp/value."""
    p = tmp_path / "exp_metrics_x.csv"
    p.write_text(
        "metric_name,timestamp,datetime,value,labels\n"
        'node_load1,1700000000,2023-11-14T22:13:20,1.5,"pod=""a\nb"""\n'
        "node_load1,1700000060,2023-11-14T22:14:20,2.5,x\n"
    )
    from anomod.io.metrics import load_tt_metric_csv
    batch = load_tt_metric_csv(p)
    assert batch is not None and batch.n_samples == 2
    assert sorted(batch.value.tolist()) == [1.5, 2.5]
    assert sorted(batch.t_s.tolist()) == [1700000000.0, 1700000060.0]
