"""Ring attention vs full attention; TraceTransformer RCA training."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from conftest import make_qkv as _qkv

from anomod.parallel.mesh import make_mesh
from anomod.parallel.ring_attention import (full_attention,
                                            make_ring_attention,
                                            ring_attention_local)


def test_ring_matches_full_attention_8dev():
    mesh = make_mesh(8)
    q, k, v = _qkv(64, 4, 16)
    ring = make_ring_attention(mesh)
    out = np.asarray(ring(q, k, v))
    ref = np.asarray(full_attention(q, k, v))
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)


def test_ring_matches_full_attention_odd_shapes():
    mesh = make_mesh(4, axis="sp")
    q, k, v = _qkv(40, 2, 8, seed=3)       # L=40 over 4 devices
    ring = make_ring_attention(mesh, axis="sp")
    np.testing.assert_allclose(np.asarray(ring(q, k, v)),
                               np.asarray(full_attention(q, k, v)),
                               rtol=2e-4, atol=2e-5)


def test_ring_single_device_degenerates_to_full():
    mesh = make_mesh(1)
    q, k, v = _qkv(16, 1, 8, seed=5)
    ring = make_ring_attention(mesh)
    np.testing.assert_allclose(np.asarray(ring(q, k, v)),
                               np.asarray(full_attention(q, k, v)),
                               rtol=2e-4, atol=2e-5)


def test_ring_output_sharded_on_sequence():
    mesh = make_mesh(8)
    q, k, v = _qkv(64, 4, 16, seed=9)
    out = make_ring_attention(mesh)(q, k, v)
    shard_shapes = {s.data.shape for s in out.addressable_shards}
    assert shard_shapes == {(8, 4, 16)}    # L/P rows per device


def test_trace_transformer_forward():
    from anomod.models.transformer import TraceTransformer
    model = TraceTransformer(d_model=16, n_heads=2, n_layers=1, mlp_hidden=32,
                             hidden=16)
    S, W, F = 12, 8, 6
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(S, W, F)).astype(np.float32))
    adj = jnp.asarray(rng.integers(0, 5, size=(S, S)).astype(np.float32))
    params = model.init(jax.random.PRNGKey(0), x, adj)
    scores = model.apply(params, x, adj)
    assert scores.shape == (S,)
    assert np.isfinite(np.asarray(scores)).all()


@pytest.mark.slow
def test_transformer_rca_end_to_end():
    from anomod.rca import train_rca
    r = train_rca("SN", "transformer", train_seeds=range(2),
                  eval_seeds=range(100, 102), epochs=60, n_traces=32)
    assert r.top1 >= 0.8
    assert r.detection_auc >= 0.9
