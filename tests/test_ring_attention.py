"""Ring attention vs full attention; TraceTransformer RCA training."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from conftest import make_qkv as _qkv

from anomod.parallel.mesh import make_mesh
from anomod.parallel.ring_attention import (full_attention,
                                            make_ring_attention,
                                            ring_attention_local)


def test_ring_matches_full_attention_8dev():
    mesh = make_mesh(8)
    q, k, v = _qkv(64, 4, 16)
    ring = make_ring_attention(mesh)
    out = np.asarray(ring(q, k, v))
    ref = np.asarray(full_attention(q, k, v))
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)


def test_ring_matches_full_attention_odd_shapes():
    mesh = make_mesh(4, axis="sp")
    q, k, v = _qkv(40, 2, 8, seed=3)       # L=40 over 4 devices
    ring = make_ring_attention(mesh, axis="sp")
    np.testing.assert_allclose(np.asarray(ring(q, k, v)),
                               np.asarray(full_attention(q, k, v)),
                               rtol=2e-4, atol=2e-5)


def test_ring_single_device_degenerates_to_full():
    mesh = make_mesh(1)
    q, k, v = _qkv(16, 1, 8, seed=5)
    ring = make_ring_attention(mesh)
    np.testing.assert_allclose(np.asarray(ring(q, k, v)),
                               np.asarray(full_attention(q, k, v)),
                               rtol=2e-4, atol=2e-5)


def test_ring_output_sharded_on_sequence():
    mesh = make_mesh(8)
    q, k, v = _qkv(64, 4, 16, seed=9)
    out = make_ring_attention(mesh)(q, k, v)
    shard_shapes = {s.data.shape for s in out.addressable_shards}
    assert shard_shapes == {(8, 4, 16)}    # L/P rows per device


def test_trace_transformer_forward():
    from anomod.models.transformer import TraceTransformer
    model = TraceTransformer(d_model=16, n_heads=2, n_layers=1, mlp_hidden=32,
                             hidden=16)
    S, W, F = 12, 8, 6
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(S, W, F)).astype(np.float32))
    adj = jnp.asarray(rng.integers(0, 5, size=(S, S)).astype(np.float32))
    params = model.init(jax.random.PRNGKey(0), x, adj)
    scores = model.apply(params, x, adj)
    assert scores.shape == (S,)
    assert np.isfinite(np.asarray(scores)).all()


@pytest.mark.slow
def test_transformer_rca_end_to_end():
    from anomod.rca import train_rca
    r = train_rca("SN", "transformer", train_seeds=range(2),
                  eval_seeds=range(100, 102), epochs=60, n_traces=32)
    assert r.top1 >= 0.8
    assert r.detection_auc >= 0.9


def test_sp_transformer_matches_single_chip():
    """The full TraceTransformer forward with its attention core replaced
    by each sequence-parallel plane (same params!) matches the single-chip
    model: the long-context path is the production scorer, not a separate
    implementation."""
    import jax
    import numpy as np

    from anomod.models.transformer import TraceTransformer
    from anomod.parallel import make_mesh
    from anomod.parallel.sp_transformer import make_sp_transformer

    S, W, F = 16, 8, 5                     # S*W = 128 tokens, 16/device
    model = TraceTransformer(d_model=32, n_heads=8, n_layers=2,
                             mlp_hidden=48)
    rng = np.random.default_rng(9)
    x = rng.normal(size=(S, W, F)).astype(np.float32)
    adj = rng.integers(0, 4, (S, S)).astype(np.float32)
    params = model.init(jax.random.PRNGKey(0), x, adj)
    ref = np.asarray(model.apply(params, x, adj))
    assert ref.shape == (S,)

    # ring on the full 8-device mesh; ulysses needs n_heads % P == 0
    for plane, n_dev in (("ring", 8), ("ulysses", 8), ("ulysses", 4)):
        mesh = make_mesh(n_dev)
        _, apply_fn = make_sp_transformer(mesh, model, plane=plane)
        out = np.asarray(apply_fn(params, x, adj))
        np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5,
                                   err_msg=f"{plane}@{n_dev}")
    import pytest
    with pytest.raises(ValueError, match="plane"):
        make_sp_transformer(make_mesh(8), model, plane="blockwise")
