"""Scenario driver: routing, flow dependencies, chaos coupling."""

import numpy as np

from anomod import chaos, scenario


def test_route_longest_prefix():
    assert scenario.route("/api/v1/orderservice/order/refresh") == "ts-order-service"
    assert scenario.route("/api/v1/orderOtherService/orderOther/refresh") == \
        "ts-order-other-service"
    assert scenario.route("/api/v1/users/login") == "ts-user-service"
    assert scenario.route("/api/v1/travelservice/trips/left") == "ts-travel-service"
    assert scenario.route("/api/v1/nosuchservice/x") == "ts-gateway-service"


def test_core_flow_order_dependencies():
    d = scenario.ScenarioDriver(seed=1)
    specs = d.core_business_flow()
    paths = [s.path for s in specs]
    # pay must come after a preserve created an order
    i_preserve = paths.index("/api/v1/preserveservice/preserve")
    i_pay = paths.index("/api/v1/inside_pay_service/inside_payment")
    assert i_preserve < i_pay
    # collect → enter → rebook chain on a paid order
    i_collect = next(i for i, p in enumerate(paths) if "/collected/" in p)
    i_enter = next(i for i, p in enumerate(paths) if "/execute/execute/" in p)
    assert i_collect < i_enter < paths.index("/api/v1/rebookservice/rebook")


def test_iteration_covers_most_services():
    d = scenario.ScenarioDriver()
    specs = d.iteration()
    covered = scenario.services_covered(specs)
    # the reference suite touches every service category; our program touches
    # the vast majority of the 45-service topology in one pass
    assert len(covered) >= 30
    for svc in ("ts-order-service", "ts-preserve-service", "ts-cancel-service",
                "ts-execute-service", "ts-rebook-service",
                "ts-admin-user-service", "ts-voucher-service"):
        assert svc in covered


def test_token_refresh_every_10_iterations():
    d = scenario.ScenarioDriver()
    refreshes = 0
    for _ in range(20):
        specs = d.iteration()
        refreshes += sum(1 for s in specs if s.flow == "token_refresh")
    assert refreshes == 2


def test_gateway_deterministic():
    a = scenario.run_scenario(iterations=2, seed=7)
    b = scenario.run_scenario(iterations=2, seed=7)
    assert np.array_equal(a.status, b.status)
    assert np.allclose(a.latency_ms, b.latency_ms)
    assert a.endpoints == b.endpoints
    c = scenario.run_scenario(iterations=2, seed=8)
    assert not np.allclose(a.latency_ms, c.latency_ms)


def test_chaos_conditions_traffic():
    ctl = chaos.ChaosController()
    base = scenario.run_scenario(iterations=3, seed=3)
    with ctl.inject("Lv_S_HTTPABORT_preserve"):
        hurt = scenario.run_scenario(iterations=3, seed=3, controller=ctl)
    # preserve-service requests get slower and fail often under the fault
    tgt = [i for i, e in enumerate(hurt.endpoints) if "preserveservice" in e]
    assert tgt
    mask_h = np.isin(hurt.endpoint, tgt)
    mask_b = np.isin(base.endpoint,
                     [i for i, e in enumerate(base.endpoints) if "preserveservice" in e])
    err_h = (hurt.status[mask_h] >= 500).mean()
    err_b = (base.status[mask_b] >= 500).mean()
    assert err_h > 0.3 > err_b
    assert hurt.latency_ms[mask_h].mean() > base.latency_ms[mask_b].mean()
    # 70% abort → 503 replace code (Lv_S_HTTPABORT_preserve.yaml:24)
    bad = hurt.status[mask_h][hurt.status[mask_h] >= 500]
    assert (bad == 503).all()
    # other services untouched
    other_h = hurt.latency_ms[~mask_h].mean()
    other_b = base.latency_ms[~mask_b].mean()
    assert abs(other_h - other_b) / other_b < 0.5


def test_api_batch_schema():
    batch = scenario.run_scenario(iterations=1, seed=0)
    assert batch.n_records == len(batch.status) == len(batch.latency_ms)
    assert batch.endpoint.max() < len(batch.endpoints)
    assert (np.diff(batch.t_s) > 0).all()   # monotone wall clock
    # endpoint vocab uses templates, not instantiated ids
    assert not any("order-" in e for e in batch.endpoints)
