"""Catalog-name parity with the reference collection scripts.

These tests parse the reference files at test time and assert our catalog
constants match name-for-name, so catalog drift is caught mechanically:

- SN: the ``--output .../<name>.csv`` targets of
  SN_collection-scripts/Dataset/metric_data/collect_metric.sh
- TT: the ``metric_categories`` level groups and the TT-specific query list
  of TT_collection-scripts/T-Dataset/metric_collector.py
"""


import re
from pathlib import Path

import numpy as np
import pytest

from anomod import metrics_catalog as mc

_REF = Path("/root/reference")
_SN_SH = _REF / "SN_collection-scripts/Dataset/metric_data/collect_metric.sh"
_TT_PY = _REF / "TT_collection-scripts/T-Dataset/metric_collector.py"

needs_ref = pytest.mark.skipif(not _REF.is_dir(),
                               reason="reference checkout not present")


@needs_ref
def test_sn_catalog_matches_collect_metric_sh():
    text = _SN_SH.read_text()
    ref_files = re.findall(r'--output\s+"\$OUTPUT_DIR/([\w.]+)\.csv"', text)
    assert ref_files, "no --output targets parsed from collect_metric.sh"
    assert list(mc.SN_METRIC_FILES) == ref_files


@needs_ref
def test_tt_catalog_matches_metric_collector_py():
    text = _TT_PY.read_text()
    # pull each level group's metrics list out of the metric_categories dict
    # (entries contain brackets/braces, so match to the ]-on-its-own-line
    # that closes the list, then collect the quoted strings)
    groups = {}
    for level in ("performance", "service", "database"):
        m = re.search(
            rf"'{level}':\s*{{.*?'metrics':\s*\[(.*?)\n\s*\]", text, re.S)
        assert m, f"level {level} not found in metric_collector.py"
        groups[level] = re.findall(r"'([^']+)'", m.group(1))
    for level, ref_list in groups.items():
        assert list(mc.TT_METRIC_CATEGORIES[level]) == ref_list, level


@needs_ref
def test_tt_specific_queries_match_reference():
    text = _TT_PY.read_text()
    m = re.search(r"train_ticket_queries\s*=\s*\[(.*?)\n\s*\]", text, re.S)
    assert m
    ref_queries = re.findall(r"'([^']+)'", m.group(1))
    assert list(mc.TT_SPECIFIC_QUERIES) == ref_queries


def test_normalize_metric_name():
    assert mc.normalize_metric_name("node_load5") == "node_load5"
    assert mc.normalize_metric_name(
        "rate(node_cpu_seconds_total[5m])") == "node_cpu_seconds_total"
    assert mc.normalize_metric_name(
        'kube_pod_status_phase{namespace="default"}') == "kube_pod_status_phase"
    assert mc.normalize_metric_name(
        'rate(container_network_receive_bytes_total{namespace="default"}[5m])'
    ) == "container_network_receive_bytes_total"
    with pytest.raises(ValueError):
        mc.normalize_metric_name("sum(foo) by (bar)")


def test_level_groups_cover_union():
    union = set()
    for level in ("performance", "service", "database"):
        union.update(mc.metrics_for_level(level))
    assert union == set(mc.TT_METRIC_NAMES)
    # ~31 unique metrics in the three groups (VERDICT.md item 3)
    assert len(mc.TT_METRIC_NAMES) >= 30


def test_experiment_window_clamp_semantics():
    now = 2_000_000.0
    # normal: earliest pod start within 24 h
    s, e = mc.experiment_window([now - 3600.0, now - 7200.0], now)
    assert (s, e) == (now - 7200.0, now)
    # clamp: pod older than 24 h
    s, e = mc.experiment_window([now - 48 * 3600.0], now)
    assert (s, e) == (now - 24 * 3600.0, now)
    # discovery returned nothing: 2 h safe window
    s, e = mc.experiment_window([], now)
    assert (s, e) == (now - 2 * 3600.0, now)
    # discovery errored: 1 h fallback
    s, e = mc.experiment_window(None, now, discovery_failed=True)
    assert (s, e) == (now - 3600.0, now)


def test_synth_emits_full_catalogs():
    from anomod import labels, synth
    sn = synth.generate_metrics(labels.label_for("Normal_Baseline"))
    assert sn.metric_names == mc.SN_METRIC_FILES
    tt = synth.generate_metrics(labels.label_for("Normal_case"))
    assert tt.metric_names == mc.TT_ALL_METRIC_NAMES
    # per-service families carry one series per service
    for name in ("microservice_error_rate",):
        mi = sn.metric_names.index(name)
        n_series = len(np.unique(sn.series[sn.metric == mi]))
        assert n_series == len(sn.services)
    for name in ("kube_pod_status_phase", "process_open_fds"):
        mi = tt.metric_names.index(name)
        n_series = len(np.unique(tt.series[tt.metric == mi]))
        assert n_series == len(tt.services)


def test_fault_conditioning_new_families():
    """The newly-modeled families must carry their fault's signature."""
    from anomod import labels, synth

    def series_values(batch, metric, svc=None):
        mi = batch.metric_names.index(metric)
        rows = batch.metric == mi
        if svc is not None:
            svc_i = batch.services.index(svc)
            s_ids = np.flatnonzero(
                np.asarray(batch.series_service) == svc_i)
            rows &= np.isin(batch.series, s_ids)
        return batch.value[rows], batch.t_s[rows]

    # SN: service-kill fault raises the target's error rate and drops its
    # request rate inside the anomaly window
    lab = labels.label_for("Svc_Kill_UserTimeline")
    m = synth.generate_metrics(lab)
    tgt = lab.target_service
    assert tgt in m.services
    v, _ = series_values(m, "microservice_error_rate", tgt)
    assert v.max() > 0.2
    v, _ = series_values(m, "microservice_request_rate", tgt)
    assert v.min() < 0.5 * np.median(v)
    # TT: pod-kill flips kube_pod_status_phase and bumps restarts
    lab = labels.label_for("Lv_S_KILLPOD_preserve")
    m = synth.generate_metrics(lab)
    v, _ = series_values(m, "kube_pod_status_phase", lab.target_service)
    assert (v == 0).any() and (v == 1).any()
    v, _ = series_values(m, "kube_pod_container_status_restarts_total",
                         lab.target_service)
    assert v.max() > 0
    # TT: connection-pool exhaustion spikes fds on the target
    lab = labels.label_for("Lv_D_CONNECTION_POOL_exhaustion")
    m = synth.generate_metrics(lab)
    v, _ = series_values(m, "process_open_fds", lab.target_service)
    assert v.max() > 3 * np.median(v)


def test_detector_level_features_populated():
    from anomod import labels, synth
    from anomod.detect import FEATURES, extract_features
    exp = synth.generate_experiment("Lv_D_TRANSACTION_timeout", n_traces=30)
    feats = extract_features(exp, exp.spans.services)
    i = FEATURES.index("metric_perf_log")
    assert feats.x[:, i:i + 3].max() > 0


def test_sn_store_families_per_owner_and_db_feature_fires():
    """SN store families are per-instance series attributed to the owning
    service (per-service Redis/Mongo in the compose stack), so the database
    level-keyed detector feature is live on SN."""
    from anomod import labels, synth
    from anomod.detect import FEATURES, extract_features
    lab = labels.label_for("DB_Redis_CacheLimit_HomeTimeline")
    m = synth.generate_metrics(lab)
    mi = m.metric_names.index("redis_memory_used")
    s_ids = np.unique(m.series[m.metric == mi])
    owners = {m.services[m.series_service[s]] for s in s_ids}
    assert lab.target_service in owners and len(owners) >= 3
    # target's redis shows the plateau drop; others don't
    tgt_i = m.services.index(lab.target_service)
    for s in s_ids:
        v = m.value[(m.metric == mi) & (m.series == s)]
        if m.series_service[s] == tgt_i:
            assert v.min() < 0.5 * np.median(v)
        else:
            assert v.min() > 0.5 * np.median(v)
    exp = synth.generate_experiment(lab.experiment, n_traces=30)
    x = extract_features(exp, exp.spans.services).x
    db_col = FEATURES.index("metric_db_log")
    assert x[:, db_col].max() > 0
