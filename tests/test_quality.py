"""De-saturated quality benchmark: the sweep must actually separate models.

VERDICT r1 weak-point 4: a benchmark where every cell is 1.0 cannot rank
models or catch regressions.  These tests pin (a) saturation only at full
severity, (b) genuine degradation in the hard regime, and (c) a floor the
trained GCN must hold there (the regression guard).
"""

import numpy as np
import pytest

from anomod import synth
from anomod.quality import severity_sweep


@pytest.fixture(scope="module")
def sweep_points():
    return severity_sweep(
        model_names=("zscore", "gcn"), severities=(1.0, 0.2, 0.05),
        train_seeds=range(3), eval_seeds=[100, 101], n_traces=40,
        epochs=60)


def _point(points, model, sev):
    return next(p for p in points if p.model == model and p.severity == sev)


def test_full_severity_saturates(sweep_points):
    assert _point(sweep_points, "zscore", 1.0).top1 == 1.0
    assert _point(sweep_points, "gcn", 1.0).top1 >= 0.9


def test_hard_regime_desaturated(sweep_points):
    """At severity 0.05 (≈1.2-1.4x latency, 2-4% errors) with confounders +
    noise, nobody scores 1.0 — the sweep has a hard end."""
    for model in ("zscore", "gcn"):
        assert _point(sweep_points, model, 0.05).top1 < 0.9


def test_gcn_floor_in_hard_regime(sweep_points):
    """Regression floor: the trained GCN must hold ≥0.4 top-1 / ≥0.6 top-3
    at severity 0.2 (measured 0.67/0.88 on this configuration)."""
    p = _point(sweep_points, "gcn", 0.2)
    assert p.top1 >= 0.4, p
    assert p.top3 >= 0.6, p


def test_model_separation(sweep_points):
    """The operating point must rank models: the trained GNN beats the
    training-free z-score baseline at severity 0.2."""
    assert (_point(sweep_points, "gcn", 0.2).top1
            > _point(sweep_points, "zscore", 0.2).top1)


def test_stream_row_floor_in_hard_regime():
    """The training-free multimodal STREAMING detector rides the same
    sweep contract and must hold its de-saturated floor: measured 0.75
    top-1 at severity 0.2 on the canonical table (within-experiment
    temporal calibration beats whole-experiment aggregates at low
    signal)."""
    pts = severity_sweep(model_names=("stream",), severities=(0.2,),
                         eval_seeds=[100], n_traces=60)
    assert len(pts) == 1
    assert pts[0].top1 >= 0.5, pts[0]
    assert pts[0].top3 >= 0.6, pts[0]


def test_zscore_and_model_paths_consume_identical_corpora():
    """Round-2 weak #3: both quality-table paths must score the SAME
    experiment bundles.  Records every synth.generate_experiment call made
    by the zscore path (_zscore_eval) and the learned-model path
    (build_dataset) for one (seed, severity) cell and asserts the labeled
    experiment streams are call-for-call identical, and that the generated
    spans are byte-identical."""
    from unittest import mock

    from anomod.quality import _zscore_eval
    from anomod.rca import build_dataset

    calls = {}
    real = synth.generate_experiment

    def record(tag):
        def wrapper(label, **kw):
            exp = real(label, **kw)
            calls.setdefault(tag, []).append(
                (label.experiment, tuple(sorted(kw.items())),
                 exp.spans.duration_us.tobytes(),
                 exp.spans.service.tobytes()))
            return exp
        return wrapper

    with mock.patch.object(synth, "generate_experiment", record("zscore")):
        _zscore_eval("TT", [100], n_traces=12, n_confounders=2,
                     hard=synth.HardMode(severity=0.2, noise=0.5))
    with mock.patch.object(synth, "generate_experiment", record("model")):
        build_dataset("TT", [100], n_traces=12,
                      hard=synth.HardMode(severity=0.2, noise=0.5),
                      n_confounders=2)
    z = calls["zscore"]
    # build_dataset additionally generates the per-seed normal BASELINE
    # (feature reference, not an eval bundle) — exclude it, then the
    # labeled streams must match exactly
    m = [c for c in calls["model"] if dict(c[1]).get("hard") is not None]
    assert z == m


def test_hardmode_severity_scales_effects():
    from anomod.labels import label_for
    lab = label_for("Lv_P_CPU_preserve")
    full_lat, full_err = synth._fault_effects(lab, 1.0)
    low_lat, low_err = synth._fault_effects(lab, 0.05)
    assert low_lat == pytest.approx(1.0 + (full_lat - 1.0) * 0.05)
    assert 1.0 < low_lat < 1.5
    assert low_err < full_err
    none_lat, none_err = synth._fault_effects(lab, 0.0)
    assert none_lat == 1.0 and none_err == pytest.approx(0.002)


def test_confounders_degrade_decoy_spans():
    from anomod.labels import label_for
    lab = label_for("Lv_D_TRANSACTION_timeout")
    decoy = "ts-food-service"
    assert decoy != lab.target_service
    hard = synth.HardMode(severity=1.0, confounders=(decoy,))
    b = synth.generate_spans(lab, n_traces=300, hard=hard)
    base = synth.generate_spans(lab, n_traces=300)
    di = b.services.index(decoy)
    in_w = lambda batch: ((batch.start_us - batch.start_us.min() >= 6e8)
                          & (batch.start_us - batch.start_us.min() < 1.2e9))
    sel = (b.service == di) & in_w(b)
    sel0 = (base.service == di) & in_w(base)
    assert sel.sum() and sel0.sum()
    med_hard = np.median(b.duration_us[sel])
    med_base = np.median(base.duration_us[sel0])
    assert med_hard > 1.2 * med_base  # ~1.5x decoy inflation


def test_shift_sweep_plumbing_zscore():
    """Shift-sweep smoke (training-free detector only, tiny corpora): every
    (model, shift) cell present, shift recorded on the points, and the
    edge-locus shift is genuinely harder for the node-evidence detector
    than in-distribution."""
    from anomod.quality import shift_sweep
    pts = shift_sweep(model_names=("zscore",),
                      shifts=("in-dist", "edge-locus"), severity=0.6,
                      train_seeds=range(1), eval_seeds=[100], n_traces=20,
                      epochs=1)
    assert {p.shift for p in pts} == {"in-dist", "edge-locus"}
    by = {p.shift: p for p in pts}
    assert by["edge-locus"].top1 <= by["in-dist"].top1


def test_edge_aware_sweep_plumbing():
    """--edge-aware smoke (tiny corpora, one cheap model): the mixed-locus
    training corpus builds with the doubled out-edge feature block, the
    trained model evaluates on every requested shift, and the sweep is
    deterministic plumbing end to end (no quality floor asserted at this
    budget)."""
    from anomod.quality import shift_sweep
    pts = shift_sweep(model_names=("gcn",),
                      shifts=("edge-locus",), severity=0.6,
                      train_seeds=range(2), eval_seeds=[100], n_traces=20,
                      epochs=5, edge_aware=True)
    assert len(pts) == 1 and pts[0].shift == "edge-locus"
    assert pts[0].n_eval > 0
