"""Test config: force JAX onto a virtual 8-device CPU mesh (no TPU needed).

The container's axon sitecustomize force-registers the TPU platform at
interpreter start, so env vars alone don't stick; the shared helper applies
the pre-init pin (anomod.utils.platform is the single home for the recipe).
"""

from anomod.utils.platform import pin_cpu

pin_cpu(8)
