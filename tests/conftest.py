"""Test config: force JAX onto a virtual 8-device CPU mesh (no TPU needed).

The container's axon sitecustomize force-registers the TPU platform at
interpreter start, so env vars alone don't stick; the shared helper applies
the pre-init pin (anomod.utils.platform is the single home for the recipe).
"""

from anomod.utils.platform import pin_cpu

pin_cpu(8)

import jax

# The suite's wall time is XLA:CPU *compile* time on this single-core box
# (the computations themselves are tiny).  Skipping the expensive HLO
# optimization passes cuts the full run ~6:10 -> ~4:00 with all numeric
# assertions intact — tests verify semantics against numpy oracles, not
# codegen.  Optimized-pipeline behavior is still exercised where it
# matters: tpu_tests/ (Mosaic-compiled kernels on the real chip) and the
# driver's bench/dryrun paths never load this conftest.
jax.config.update("jax_disable_most_optimizations", True)

# Persistent compilation cache: the suite re-JITs the same train/replay
# computations every run; caching compiled executables across runs cuts
# ~20% more wall time on this box (keyed by HLO hash, so code changes
# invalidate exactly the computations they touch).  Lives untracked under
# the repo root so driver re-runs in the same workspace hit it warm.
import os as _os

_cache_dir = _os.path.abspath(
    _os.path.join(_os.path.dirname(__file__), _os.pardir, ".jax_test_cache"))
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)


def make_qkv(L, H, D, seed=0):
    """Shared random q/k/v blocks for the sequence-parallel attention tests
    (one generator so cross-plane equivalence tests compare identical
    tensors)."""
    import jax.numpy as jnp
    import numpy as np
    rng = np.random.default_rng(seed)
    return tuple(jnp.asarray(rng.normal(size=(L, H, D)).astype(np.float32))
                 for _ in range(3))
