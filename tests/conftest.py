"""Test config: force JAX onto a virtual 8-device CPU mesh (no TPU needed).

The container's axon sitecustomize force-registers the TPU platform at
interpreter start (jax_platforms="axon,cpu"), so env vars alone don't stick —
we set the XLA host-device-count flag before jax initializes and then pin the
platform to cpu via jax.config (backends aren't initialized yet at conftest
import time, so this takes effect cleanly).
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
