"""Live telemetry loop tests (ISSUE-18): the embedded /metrics endpoint
plane driven over a REAL socket, the LiveFeed's watermark-tailed polling
against stub servers, and the wire-journal record→replay byte-parity pin
— a live run and its journal replay must produce identical decision
planes and equal canonical flight journals.
"""

import json
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from anomod.io.live import (HttpTransport, JaegerClient, PrometheusClient,
                            TransportError)
from anomod.obs.export import to_prometheus_text
from anomod.obs.http import PROM_CONTENT_TYPE, ObsHttpServer
from anomod.obs.registry import Registry, render_labels, set_registry
from anomod.serve.feed import (LiveFeed, RecordingTransport,
                               ReplayTransport, load_feed_journal,
                               parse_prometheus_text, run_live_feed)


class JsonStub:
    """The test_live.py stub: ``route(method, path, params, body) ->
    (status, doc)``; records every request for assertions."""

    def __init__(self, route):
        stub = self
        stub.requests = []

        class Handler(BaseHTTPRequestHandler):
            def _serve(self, method):
                import urllib.parse
                parsed = urllib.parse.urlparse(self.path)
                params = {k: v[0] for k, v in
                          urllib.parse.parse_qs(parsed.query).items()}
                length = int(self.headers.get("Content-Length") or 0)
                body = json.loads(self.rfile.read(length)) if length \
                    else None
                stub.requests.append((method, parsed.path, params, body))
                status, doc = route(method, parsed.path, params, body)
                payload = json.dumps(doc).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                self._serve("GET")

            def do_POST(self):
                self._serve("POST")

            def log_message(self, *a):  # quiet
                pass

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.thread = threading.Thread(target=self.server.serve_forever,
                                       daemon=True)
        self.thread.start()
        self.base_url = f"http://127.0.0.1:{self.server.server_port}"

    def close(self):
        self.server.shutdown()
        self.server.server_close()


@pytest.fixture
def stub_factory():
    stubs = []

    def make(route):
        s = JsonStub(route)
        stubs.append(s)
        return s

    yield make
    for s in stubs:
        s.close()


@pytest.fixture
def fresh_registry():
    reg = Registry(enabled=True)
    prev = set_registry(reg)
    yield reg
    set_registry(prev)


def _fast_transport():
    slept = []
    return HttpTransport(timeout=5.0, sleep=slept.append), slept


def _get(url):
    with urllib.request.urlopen(url, timeout=5.0) as r:
        return r.status, dict(r.headers), r.read()


# ---------------------------------------------------------------------------
# Config knobs
# ---------------------------------------------------------------------------

def test_feed_knob_validation(monkeypatch, tmp_path):
    from anomod.config import Config
    monkeypatch.setenv("ANOMOD_OBS_HTTP", "on")
    monkeypatch.setenv("ANOMOD_OBS_HTTP_PORT", "0")
    monkeypatch.setenv("ANOMOD_SERVE_FEED_LAG_S", "3.5")
    monkeypatch.setenv("ANOMOD_FEED_JOURNAL", str(tmp_path / "w.json"))
    cfg = Config()
    assert cfg.obs_http is True
    assert cfg.obs_http_port == 0
    assert cfg.serve_feed_lag_s == 3.5
    assert cfg.feed_journal == tmp_path / "w.json"

    monkeypatch.setenv("ANOMOD_OBS_HTTP", "maybe")
    with pytest.raises(ValueError, match="ANOMOD_OBS_HTTP must be"):
        Config()
    monkeypatch.setenv("ANOMOD_OBS_HTTP", "0")
    monkeypatch.setenv("ANOMOD_OBS_HTTP_PORT", "http")
    with pytest.raises(ValueError, match="ANOMOD_OBS_HTTP_PORT"):
        Config()
    monkeypatch.setenv("ANOMOD_OBS_HTTP_PORT", "70000")
    with pytest.raises(ValueError, match=r"\[0, 65535\]"):
        Config()
    monkeypatch.setenv("ANOMOD_OBS_HTTP_PORT", "9464")
    monkeypatch.setenv("ANOMOD_SERVE_FEED_LAG_S", "slow")
    with pytest.raises(ValueError, match="ANOMOD_SERVE_FEED_LAG_S"):
        Config()
    monkeypatch.setenv("ANOMOD_SERVE_FEED_LAG_S", "-1")
    with pytest.raises(ValueError, match=r"\[0, 3600\]"):
        Config()
    monkeypatch.setenv("ANOMOD_SERVE_FEED_LAG_S", "2.0")
    monkeypatch.setenv("ANOMOD_FEED_JOURNAL", "off")
    assert Config().feed_journal is None


# ---------------------------------------------------------------------------
# The endpoint plane over a real socket
# ---------------------------------------------------------------------------

def test_metrics_endpoint_matches_registry(fresh_registry):
    reg = fresh_registry
    reg.counter("anomod_serve_ticks_total").inc(7)
    reg.histogram("anomod_serve_tick_wall_s").observe(0.25)
    with ObsHttpServer(registry=reg, port=0) as srv:
        status, headers, body = _get(f"{srv.url}/metrics")
        assert status == 200
        assert headers["Content-Type"] == PROM_CONTENT_TYPE
        assert "version=0.0.4" in headers["Content-Type"]
        assert body.decode() == to_prometheus_text(reg)
        # HEAD: the scrape-probe verb — same headers, empty body
        req = urllib.request.Request(f"{srv.url}/metrics", method="HEAD")
        with urllib.request.urlopen(req, timeout=5.0) as r:
            assert r.status == 200
            assert int(r.headers["Content-Length"]) == len(body)
            assert r.read() == b""
        # /healthz liveness
        status, _, hz = _get(f"{srv.url}/healthz")
        doc = json.loads(hz)
        assert status == 200 and doc["status"] == "ok"
        assert doc["registry"]["enabled"] is True
        assert doc["registry"]["n_metrics"] >= 2
        # unknown route: structured 404 listing what exists
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(f"{srv.url}/nope")
        assert ei.value.code == 404
        assert "/metrics" in json.loads(ei.value.read())["routes"]
        # localhost-bound: the server never listens on other interfaces
        assert srv._httpd.server_address[0] == "127.0.0.1"


def test_adversarial_label_scrape_reparses_to_registry(fresh_registry):
    """The acceptance pin's read half: labels with every exposition
    metacharacter survive endpoint → wire → parse back to the
    registry's canonical unescaped rendering, and the re-parsed rows
    drive spans_from_metrics."""
    reg = fresh_registry
    nasty = 'multi\nline "quoted" back\\slash'
    reg.gauge("anomod_serve_backlog_spans", pod=nasty,
              plain="ok").set(42.5)
    reg.counter("anomod_ingest_rows_total").inc(3)
    with ObsHttpServer(registry=reg, port=0) as srv:
        _, _, body = _get(f"{srv.url}/metrics")
    rows = parse_prometheus_text(body.decode())
    want = {(m.name, render_labels(m.labels), m.value)
            for m in reg.metrics() if m.kind != "histogram"}
    assert want <= set(rows)
    # and the scraped rows feed the metric→span synthesis untouched
    from anomod.obs.export import rows_to_metric_batch
    from anomod.obs.selfscrape import spans_from_metrics
    stamped = [(float(i), name, lab, val)
               for i, (name, lab, val) in enumerate(rows * 3)]
    batch = rows_to_metric_batch(stamped)
    assert batch.n_samples == len(stamped)
    spans_from_metrics(batch)  # must not raise on adversarial labels


# ---------------------------------------------------------------------------
# Watermark-tailed incremental polling
# ---------------------------------------------------------------------------

def test_prometheus_since_watermark_monotone_no_redelivery(stub_factory):
    t0 = 1_700_000_000

    def route(method, path, params, body):
        return 200, {"status": "success", "data": {"resultType": "matrix",
                     "result": [{"metric": {"__name__": "up", "pod": "a"},
                                 "values": [[t0 + 15 * i, str(i)]
                                            for i in range(4)]}]}}

    stub = stub_factory(route)
    tp, _ = _fast_transport()
    client = PrometheusClient(stub.base_url, transport=tp)
    fresh, mark = client.query_range_since("up", t0 + 10, t0 + 60)
    assert [ts for ts, _, _ in fresh] == [t0 + 15, t0 + 30, t0 + 45]
    assert mark == t0 + 45                      # max delivered ts
    fresh2, mark2 = client.query_range_since("up", mark, t0 + 60)
    assert fresh2 == []                          # no redelivery
    assert mark2 == mark                         # monotone


def test_jaeger_since_watermark_monotone_no_redelivery(stub_factory):
    t0_us = 1_700_000_000_000_000

    def route(method, path, params, body):
        assert path == "/api/traces"
        assert int(params["start"]) >= 0
        return 200, {"data": [
            {"spans": [{"startTime": t0_us + 1_000_000, "duration": 50,
                        "operationName": "op"}]},
            {"spans": [{"startTime": t0_us + 2_000_000, "duration": 60,
                        "operationName": "op"}]},
        ]}

    stub = stub_factory(route)
    tp, _ = _fast_transport()
    client = JaegerClient(stub.base_url, transport=tp)
    fresh, mark = client.traces_since("svc", t0_us + 1_500_000,
                                      t0_us + 9_000_000)
    assert len(fresh) == 1                       # only the newer trace
    assert mark == t0_us + 2_000_000
    fresh2, mark2 = client.traces_since("svc", mark, t0_us + 9_000_000)
    assert fresh2 == [] and mark2 == mark


def test_feed_transport_retry_journals_final_response_once(stub_factory):
    calls = {"n": 0}

    def route(method, path, params, body):
        calls["n"] += 1
        if calls["n"] == 1:
            return 500, {"err": "boom"}
        return 200, {"status": "success",
                     "data": {"resultType": "matrix", "result": []}}

    stub = stub_factory(route)
    inner, slept = _fast_transport()
    rec = RecordingTransport(inner=inner)
    PrometheusClient(stub.base_url, transport=rec).query_range_since(
        "up", 0, 60)
    assert slept == [3.0]                        # the reference schedule
    assert len(stub.requests) == 2               # retried on the wire...
    assert len(rec.entries) == 1                 # ...journaled ONCE
    assert rec.entries[0]["kind"] == "json"


def test_gap_fill_clamps_stragglers_to_tick_edge(stub_factory,
                                                 fresh_registry):
    t0 = 1_700_000_000.0

    def route(method, path, params, body):
        return 200, {"status": "success", "data": {"resultType": "matrix",
                     "result": [{"metric": {"__name__": "up"},
                                 "values": [[t0 - 1.5, "1"]]}]}}

    stub = stub_factory(route)
    feed = LiveFeed(prom_url=stub.base_url, prom_queries=("up",),
                    n_tenants=2, n_services=2, lag_s=2.0, t0_wall_s=t0)
    # the row bridges to virtual 0.5s — behind a tick opening at 5.0s,
    # so it clamps forward to the open edge and counts a gap
    feed.arrivals(5.0, 6.0)
    assert feed.n_gaps == 1
    assert [r[0] for r in feed._mrows] == [5.0]  # clamped, not dropped


# ---------------------------------------------------------------------------
# The wire journal: record → replay byte parity (the acceptance pin)
# ---------------------------------------------------------------------------

def test_replay_transport_fails_loud():
    rt = ReplayTransport([{"kind": "text", "path": "/metrics",
                           "params": {}, "payload": None, "body": "x 1\n"}])
    with pytest.raises(TransportError, match="divergence"):
        rt.request_json("http://h/other")
    rt2 = ReplayTransport([])
    with pytest.raises(TransportError, match="exhausted"):
        rt2.request_text("http://h/metrics")


def test_load_feed_journal_refuses_foreign_docs(tmp_path):
    p = tmp_path / "not_feed.json"
    p.write_text(json.dumps({"flight_format": 1}))
    with pytest.raises(ValueError, match="feed wire journal"):
        load_feed_journal(p)


def _dogfood_kw():
    return dict(capacity_spans_per_s=2000.0, duration_s=6.0, tick_s=1.0,
                window_s=2.0, baseline_windows=2, buckets=(64,),
                n_windows=16, flight=True, flight_digest_every=2)


def test_live_vs_replay_byte_parity(fresh_registry, tmp_path):
    """THE acceptance pin: the dogfood closed loop (the framework
    scraping its own /metrics) recorded and replayed must agree on
    states, alerts, SLO, shed and the canonical flight journal."""
    from anomod.obs.flight import diff_journals
    jpath = tmp_path / "wire.json"
    with ObsHttpServer(port=0) as srv:
        eng_a, rep_a, feed = run_live_feed(
            scrape_url=f"{srv.url}/metrics", n_tenants=4, n_services=4,
            journal=jpath, **_dogfood_kw())
    assert jpath.exists()
    assert feed.n_polls >= 1 and rep_a.served_spans > 0
    doc = load_feed_journal(jpath)
    assert doc["header"]["n_tenants"] == 4
    assert len(doc["entries"]) == feed.n_polls
    eng_b, rep_b, feed_b = run_live_feed(replay=jpath, **_dogfood_kw())
    assert isinstance(feed_b.transport, ReplayTransport)
    assert feed_b.transport.n_served == len(doc["entries"])
    assert rep_b.served_spans == rep_a.served_spans
    assert rep_b.shed_fraction == rep_a.shed_fraction
    assert rep_b.latency == rep_a.latency
    for t in sorted(set(eng_a._tenant_replay) | set(eng_b._tenant_replay)):
        np.testing.assert_array_equal(
            np.asarray(eng_a._tenant_replay[t].state.agg),
            np.asarray(eng_b._tenant_replay[t].state.agg))
        np.testing.assert_array_equal(
            np.asarray(eng_a._tenant_replay[t].state.hist),
            np.asarray(eng_b._tenant_replay[t].state.hist))
    for t in sorted(set(eng_a._tenant_det) | set(eng_b._tenant_det)):
        assert eng_a.alerts_for(t) == eng_b.alerts_for(t)
    assert diff_journals(eng_a.flight_recorder.journal(),
                         eng_b.flight_recorder.journal()) is None
    assert eng_a.flight_recorder.canonical_bytes() \
        == eng_b.flight_recorder.canonical_bytes()
    # the replay header sizes the fleet even with no explicit knobs
    assert feed_b.n_tenants == 4 and len(feed_b.services) == 4


@pytest.mark.slow
def test_endpoint_on_vs_off_read_side_parity(fresh_registry):
    """A scraped endpoint never moves a decision byte: the same seeded
    run with the endpoint plane up (and scraped mid-run) matches the
    endpoint-less run on the canonical flight journal."""
    from anomod.serve.engine import run_power_law
    kw = dict(n_tenants=6, n_services=4, capacity_spans_per_s=1000,
              overload=2.0, duration_s=10, tick_s=1.0, seed=5,
              window_s=5.0, baseline_windows=2, fault_tenants=0,
              buckets=(64,), n_windows=16, flight=True,
              flight_digest_every=2)
    eng_off, rep_off = run_power_law(**kw)
    with ObsHttpServer(port=0) as srv:
        stop = threading.Event()

        def scraper():
            while not stop.is_set():
                try:
                    _get(f"{srv.url}/metrics")
                    _get(f"{srv.url}/healthz")
                except Exception:
                    pass
                stop.wait(0.02)

        t = threading.Thread(target=scraper, daemon=True)
        t.start()
        try:
            eng_on, rep_on = run_power_law(**kw)
        finally:
            stop.set()
            t.join(timeout=5.0)
    assert rep_on.shed_fraction == rep_off.shed_fraction
    assert rep_on.latency == rep_off.latency
    assert eng_on.flight_recorder.canonical_bytes() \
        == eng_off.flight_recorder.canonical_bytes()
