"""t-digest + HLL: accuracy vs exact, numpy/jax parity, merge associativity."""

import numpy as np
import pytest

from anomod.ops import (hll_add, hll_estimate, hll_init, hll_merge,
                        tdigest_build, tdigest_merge, tdigest_quantile)
from anomod.ops.tdigest import tdigest_merge_many


def test_tdigest_quantile_accuracy():
    rng = np.random.default_rng(0)
    vals = rng.lognormal(3.0, 1.0, 20_000).astype(np.float32)
    d = tdigest_build(vals, k=64)
    for q in (0.5, 0.9, 0.95, 0.99):
        exact = np.quantile(vals, q)
        approx = tdigest_quantile(d, q)
        assert abs(approx - exact) / exact < 0.05, (q, exact, approx)


def test_tdigest_merge_matches_full_build():
    rng = np.random.default_rng(1)
    a = rng.normal(100, 10, 8000).astype(np.float32)
    b = rng.normal(200, 30, 8000).astype(np.float32)
    d = tdigest_merge(tdigest_build(a, 64), tdigest_build(b, 64))
    full = np.concatenate([a, b])
    for q in (0.25, 0.5, 0.9):
        exact = np.quantile(full, q)
        assert abs(tdigest_quantile(d, q) - exact) / abs(exact) < 0.05


def test_tdigest_vmapped_lanes():
    rng = np.random.default_rng(2)
    vals = rng.lognormal(2.0, 0.7, (5, 4000)).astype(np.float32)
    d = tdigest_build(vals, k=32)
    assert d.mean.shape == (5, 32)
    q = tdigest_quantile(d, 0.5)
    for i in range(5):
        exact = np.quantile(vals[i], 0.5)
        assert abs(q[i] - exact) / exact < 0.06


def test_tdigest_jax_matches_numpy():
    import jax.numpy as jnp
    rng = np.random.default_rng(3)
    vals = rng.lognormal(3.0, 1.0, 4096).astype(np.float32)
    dn = tdigest_build(vals, k=64, xp=np)
    dj = tdigest_build(jnp.asarray(vals), k=64, xp=jnp)
    np.testing.assert_allclose(np.asarray(dj.mean), dn.mean, rtol=1e-4, atol=1e-3)
    np.testing.assert_allclose(np.asarray(dj.weight), dn.weight, rtol=1e-5)
    qn = tdigest_quantile(dn, 0.95)
    qj = tdigest_quantile(dj, jnp.float32(0.95), xp=jnp)
    assert abs(float(qj) - qn) / qn < 1e-3


def test_tdigest_merge_many_shards():
    rng = np.random.default_rng(4)
    shards = [rng.lognormal(3.0, 1.0, 5000).astype(np.float32) for _ in range(8)]
    digests = [tdigest_build(s, 64) for s in shards]
    merged = tdigest_merge_many(digests)
    full = np.concatenate(shards)
    for q in (0.5, 0.99):
        exact = np.quantile(full, q)
        assert abs(tdigest_quantile(merged, q) - exact) / exact < 0.05


def test_hll_estimate_accuracy():
    p = 12
    for true_n in (100, 5_000, 200_000):
        items = np.arange(true_n, dtype=np.int64) * 2654435761 % (2**31)
        regs = hll_add(hll_init(p), items.astype(np.int32), p=p)
        est = hll_estimate(regs)
        rel = abs(est - len(np.unique(items))) / len(np.unique(items))
        assert rel < 0.08, (true_n, est, rel)


def test_hll_merge_exact():
    p = 10
    a_items = np.arange(0, 3000, dtype=np.int32)
    b_items = np.arange(1500, 6000, dtype=np.int32)
    ra = hll_add(hll_init(p), a_items, p=p)
    rb = hll_add(hll_init(p), b_items, p=p)
    merged = hll_merge(ra, rb)
    both = hll_add(hll_add(hll_init(p), a_items, p=p), b_items, p=p)
    np.testing.assert_array_equal(merged, both)
    est = hll_estimate(merged)
    assert abs(est - 6000) / 6000 < 0.1


def test_hll_lanes_scatter():
    p = 8
    lanes = 4
    regs = hll_init(p, lanes=lanes)
    items = np.arange(8000, dtype=np.int32)
    lane = items % lanes
    regs = hll_add(regs, items, p=p, lane=lane)
    est = hll_estimate(regs)
    assert est.shape == (lanes,)
    for i in range(lanes):
        assert abs(est[i] - 2000) / 2000 < 0.15


def test_hll_jax_matches_numpy():
    import jax.numpy as jnp
    p = 10
    items = (np.arange(10_000, dtype=np.int64) * 2654435761 % (2**31)
             ).astype(np.int32)
    rn = hll_add(hll_init(p), items, p=p)
    rj = hll_add(hll_init(p, xp=jnp), jnp.asarray(items), p=p, xp=jnp)
    np.testing.assert_array_equal(np.asarray(rj), rn)
    lane = jnp.asarray(items % 3)
    rjl = hll_add(hll_init(p, lanes=3, xp=jnp), jnp.asarray(items), p=p,
                  lane=lane, xp=jnp)
    rnl = hll_add(hll_init(p, lanes=3), items, p=p, lane=np.asarray(items) % 3)
    np.testing.assert_array_equal(np.asarray(rjl), rnl)


def test_tdigest_quantile_skips_empty_centroids():
    """Regression: when n < k the k1 scale interleaves empty (weight 0,
    mean 0) buckets with populated ones; quantiles bracketing an empty
    bucket used to interpolate toward the 0 placeholder (p99 below p50)."""
    import jax.numpy as jnp
    from anomod.ops.tdigest import tdigest_build, tdigest_quantile
    rng = np.random.default_rng(0)
    vals = np.log1p(rng.lognormal(9.8, 0.5, 70).astype(np.float32))
    d = tdigest_build(vals, k=64)
    for xp, dd in ((np, d), (jnp, type(d)(mean=jnp.asarray(d.mean),
                                          weight=jnp.asarray(d.weight)))):
        p50 = float(tdigest_quantile(dd, 0.5, xp=xp))
        p99 = float(tdigest_quantile(dd, 0.99, xp=xp))
        assert p99 > p50
        assert abs(p99 - np.quantile(vals, 0.99)) < 0.05 * np.quantile(vals, 0.99)


def test_pallas_replay_kernel_interpret():
    """Fused pallas aggregation kernel vs numpy oracle (interpret mode on CPU)."""
    from anomod.ops.pallas_replay import (make_pallas_replay_fn,
                                          pallas_replay_numpy)
    rng = np.random.default_rng(7)
    n, S, H, B = 2048, 93, 16, 256
    sid = rng.integers(0, S + 1, n).astype(np.int32)
    # planes: valid / err / s5 exact 0/1, then dur_raw / dur / dur^2
    valid = (sid < S).astype(np.float32)
    err = (rng.random(n) < 0.1).astype(np.float32) * valid
    s5 = (rng.random(n) < 0.05).astype(np.float32) * valid
    dur_raw = rng.lognormal(10.0, 1.0, n).astype(np.float32)
    dur = np.log1p(dur_raw)
    planes = np.stack([valid, err, s5, dur_raw, dur, dur * dur])
    ref = pallas_replay_numpy(sid, planes, S, H)
    fn = make_pallas_replay_fn(S, H, block=B, interpret=True)
    out = np.asarray(fn(sid, planes))
    # 0/1 planes and histogram are bf16-exact; moments carry the hi/lo
    # split's ~1.5e-5 relative error (same bound as the XLA path)
    np.testing.assert_allclose(out[:, :3], ref[:, :3], rtol=0, atol=0)
    np.testing.assert_allclose(out[:, 6:], ref[:, 6:], rtol=0, atol=0)
    np.testing.assert_allclose(out[:, 3:6], ref[:, 3:6], rtol=1e-3)


def test_pallas_replay_matches_xla_replay_path():
    """Kernel parity with the staged-column oracle, plus the full
    measure_throughput(kernel='pallas') branch (which auto-selects the
    interpret path on non-TPU backends) against a real synthetic corpus."""
    from anomod.ops.pallas_replay import make_pallas_replay_fn
    from anomod.replay import (ReplayConfig, measure_throughput,
                               replay_numpy, stage_columns,
                               stage_pallas_planes)
    from anomod.labels import labels_for_testbed
    from anomod.synth import generate_spans
    import pytest
    label = labels_for_testbed("TT")[1]
    batch = generate_spans(label, n_traces=40)
    cfg = ReplayConfig(n_services=len(batch.services), chunk_size=2048)
    chunks, _ = stage_columns(batch, cfg)
    sid, planes = stage_pallas_planes(chunks)
    fn = make_pallas_replay_fn(cfg.sw, cfg.n_hist_buckets, block=256,
                               interpret=True)
    out = np.asarray(fn(sid, planes))
    ref = replay_numpy(chunks, cfg)
    np.testing.assert_allclose(out[:, :6], ref.agg, rtol=2e-3, atol=1e-2)
    np.testing.assert_allclose(out[:, 6:], ref.hist, rtol=0, atol=0)
    # the throughput harness's pallas branch end-to-end (staging, repack,
    # span-count sanity check) on the CPU backend's interpret path
    res = measure_throughput(batch, cfg, repeats=1, kernel="pallas")
    assert res.kernel == "pallas" and res.n_spans == batch.n_spans
    with pytest.raises(ValueError, match="unknown replay kernel"):
        measure_throughput(batch, cfg, repeats=1, kernel="fused")


def test_tdigest_by_segment_matches_per_service_quantiles():
    from anomod.ops.tdigest import tdigest_by_segment
    rng = np.random.default_rng(11)
    S = 7
    seg = rng.integers(0, S, 30_000).astype(np.int32)
    vals = rng.lognormal(3.0 + seg * 0.3, 0.8).astype(np.float32)
    d = tdigest_by_segment(vals, seg, S, k=64)
    assert d.mean.shape == (S, 64)
    q99 = tdigest_quantile(d, 0.99)
    for s in range(S):
        exact = np.quantile(vals[seg == s], 0.99)
        assert abs(q99[s] - exact) / exact < 0.06, (s, q99[s], exact)


def test_tdigest_by_segment_jax_matches_numpy():
    import jax.numpy as jnp
    from anomod.ops.tdigest import tdigest_by_segment
    rng = np.random.default_rng(12)
    seg = rng.integers(0, 5, 4000).astype(np.int32)
    vals = rng.lognormal(3.0, 1.0, 4000).astype(np.float32)
    dn = tdigest_by_segment(vals, seg, 5, k=32)
    dj = tdigest_by_segment(jnp.asarray(vals), jnp.asarray(seg), 5, k=32, xp=jnp)
    np.testing.assert_allclose(np.asarray(dj.weight), dn.weight, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(dj.mean), dn.mean, rtol=1e-3, atol=1e-2)


def test_pallas_hll_kernel_interpret():
    """Pallas HLL kernel vs the numpy HLL oracle (interpret mode on CPU)."""
    from anomod.ops.pallas_hll import make_pallas_hll_fn
    p = 10
    items = (np.arange(8192, dtype=np.int64) * 2654435761 % (2**31)
             ).astype(np.int32)
    ref = hll_add(hll_init(p), items, p=p)
    fn = make_pallas_hll_fn(p=p, block=1024, interpret=True)
    out = np.asarray(fn(items))
    np.testing.assert_array_equal(out, ref)
    est = hll_estimate(out)
    assert abs(est - 8192) / 8192 < 0.1


def test_pallas_tdigest_matches_numpy_oracle():
    """Pallas build kernel (interpret on CPU mesh) == numpy tdigest_build."""
    from anomod.ops.pallas_tdigest import tdigest_build_pallas
    from anomod.ops.tdigest import tdigest_build, tdigest_quantile
    rng = np.random.default_rng(0)
    vals = rng.lognormal(3.0, 1.0, size=(5, 256)).astype(np.float32)
    ref = tdigest_build(vals, k=32)
    out = tdigest_build_pallas(vals, k=32, interpret=True)
    np.testing.assert_allclose(np.asarray(out.weight), ref.weight, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out.mean), ref.mean,
                               rtol=1e-4, atol=1e-4)
    # quantiles through the shared query path agree too
    for q in (0.5, 0.9, 0.99):
        np.testing.assert_allclose(
            tdigest_quantile(
                type(ref)(np.asarray(out.mean), np.asarray(out.weight)), q),
            tdigest_quantile(ref, q), rtol=1e-4)


def test_tdigest_by_segment_pallas_matches_host():
    """Segment-staged kernel build (interpret on the CPU mesh) == the host
    tdigest_by_segment digests, through the shared segment_pad staging."""
    from anomod.ops.pallas_tdigest import tdigest_by_segment_pallas
    from anomod.ops.tdigest import tdigest_by_segment, tdigest_quantile
    rng = np.random.default_rng(21)
    S = 6
    seg = rng.integers(0, S, 3000).astype(np.int32)
    vals = rng.lognormal(3.0 + seg * 0.2, 0.7).astype(np.float32)
    host = tdigest_by_segment(vals, seg, S, k=32)
    pal = tdigest_by_segment_pallas(vals, seg, S, k=32, interpret=True)
    np.testing.assert_allclose(np.asarray(pal.weight), host.weight, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(pal.mean), host.mean,
                               rtol=1e-3, atol=1e-3)
    qp = tdigest_quantile(
        type(host)(np.asarray(pal.mean), np.asarray(pal.weight)), 0.99)
    qh = tdigest_quantile(host, 0.99)
    np.testing.assert_allclose(qp, qh, rtol=2e-3)
    # accuracy vs exact is the host path's covered contract
    # (test_tdigest_by_segment_matches_per_service_quantiles); here only
    # sanity-check the tail is a tail
    assert (qp > tdigest_quantile(host, 0.5)).all()


def test_pallas_tdigest_merge_matches_numpy():
    from anomod.ops.pallas_tdigest import (tdigest_build_pallas,
                                           tdigest_merge_pallas)
    from anomod.ops.tdigest import TDigest, tdigest_build, tdigest_merge
    rng = np.random.default_rng(1)
    a_vals = rng.normal(10, 2, size=(3, 128)).astype(np.float32)
    b_vals = rng.normal(14, 3, size=(3, 128)).astype(np.float32)
    ref = tdigest_merge(tdigest_build(a_vals, k=32),
                        tdigest_build(b_vals, k=32))
    pa = tdigest_build_pallas(a_vals, k=32, interpret=True)
    pb = tdigest_build_pallas(b_vals, k=32, interpret=True)
    out = tdigest_merge_pallas(pa, pb, interpret=True)
    np.testing.assert_allclose(np.asarray(out.weight), ref.weight, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out.mean), ref.mean,
                               rtol=1e-3, atol=1e-3)


def test_pallas_tdigest_weighted_and_padded():
    """Zero-weight padding slots must not disturb the digest."""
    from anomod.ops.pallas_tdigest import tdigest_build_pallas
    from anomod.ops.tdigest import tdigest_build
    rng = np.random.default_rng(2)
    vals = rng.uniform(0, 100, size=(2, 64)).astype(np.float32)
    w = np.ones_like(vals)
    w[:, 48:] = 0.0  # padding tail
    ref = tdigest_build(vals, k=16, weights=w)
    out = tdigest_build_pallas(vals, k=16, weights=w, interpret=True)
    np.testing.assert_allclose(np.asarray(out.weight), ref.weight, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out.mean), ref.mean,
                               rtol=1e-4, atol=1e-4)
