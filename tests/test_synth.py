"""Synthetic generator: determinism, schema validity, fault conditioning."""

import numpy as np
import pytest

from anomod import labels, synth
from anomod.schemas import KIND_ENTRY, concat_span_batches


def test_labels_cover_both_testbeds():
    assert len(labels.SN_LABELS) == 13
    assert len(labels.TT_LABELS) == 13
    assert sum(l.is_anomaly for l in labels.SN_LABELS) == 12
    assert sum(l.is_anomaly for l in labels.TT_LABELS) == 12
    # every anomaly level appears 3x per testbed
    for tb in ("SN", "TT"):
        lv = [l.anomaly_level for l in labels.anomalous_labels(tb)]
        for level in ("performance", "service", "database", "code"):
            assert lv.count(level) == 3, (tb, level)


def test_canonical_experiment_names():
    assert labels.canonical_experiment(
        "Lv_P_CPU_preserve_20251103T140939Z_em") == "Lv_P_CPU_preserve"
    assert labels.canonical_experiment(
        "Perf_CPU_Contention_20251103_222601_traces_2025-11-03_22-46-44"
    ) == "Perf_CPU_Contention"
    assert labels.label_for(
        "Normal_Baseline_20251103_220228_metrics_2025-11-03_22-22-55"
    ).anomaly_level == "normal"


def test_spans_deterministic():
    l = labels.label_for("Lv_P_CPU_preserve")
    a = synth.generate_spans(l, n_traces=50)
    b = synth.generate_spans(l, n_traces=50)
    np.testing.assert_array_equal(a.start_us, b.start_us)
    np.testing.assert_array_equal(a.parent, b.parent)
    assert a.services == b.services


def test_spans_valid_structure():
    for name in ("Normal_case", "Lv_S_HTTPABORT_preserve", "Normal_Baseline",
                 "Svc_Kill_Media"):
        l = labels.label_for(name)
        b = synth.generate_spans(l, n_traces=30).validate()
        assert b.n_spans > 30
        # parents precede or equal structure: parent service differs or same
        roots = (b.parent == -1)
        assert roots.sum() == 30  # one root per trace
        # every non-root's parent belongs to the same trace
        nz = ~roots
        assert (b.trace[nz] == b.trace[b.parent[nz]]).all()
        # start times sorted
        assert (np.diff(b.start_us) >= 0).all()


def _window_mask(batch):
    # fault effects live in the shared anomaly window [600, 1200)s
    base = batch.start_us.min()
    rel = batch.start_us - base
    return (rel >= 600_000_000) & (rel < 1_200_000_000)


def test_fault_conditioning_latency():
    norm = synth.generate_spans(labels.label_for("Normal_case"), n_traces=120)
    cpu = synth.generate_spans(labels.label_for("Lv_P_CPU_preserve"), n_traces=120)
    tgt = cpu.services.index("ts-preserve-service")
    m_norm = norm.duration_us[norm.service == tgt].mean()
    w = _window_mask(cpu)
    m_cpu = cpu.duration_us[(cpu.service == tgt) & w].mean()
    m_cpu_out = cpu.duration_us[(cpu.service == tgt) & ~w].mean()
    assert m_cpu > 3 * m_norm
    assert m_cpu > 3 * m_cpu_out  # effect confined to the window


def test_fault_conditioning_errors():
    ab = synth.generate_spans(labels.label_for("Lv_S_HTTPABORT_preserve"),
                              n_traces=120)
    tgt = ab.services.index("ts-preserve-service")
    w = _window_mask(ab)
    err_rate = ab.is_error[(ab.service == tgt) & w].mean()
    other = ab.is_error[(ab.service != tgt) & w].mean()
    assert err_rate > 0.4
    assert err_rate > 3 * other


def test_fault_signal_survives_tt_metric_truncation():
    # target services beyond the first-12 truncation still get series
    m = synth.generate_metrics(labels.label_for("Lv_D_TRANSACTION_timeout"))
    tgt = m.services.index("ts-order-service")
    assert (m.series_service == tgt).any()


def test_host_level_fault_has_log_signal():
    cpu, _ = synth.generate_logs(labels.label_for("Perf_CPU_Contention"))
    norm, _ = synth.generate_logs(labels.label_for("Normal_Baseline"))
    from anomod.schemas import LOG_ERROR
    assert (cpu.level == LOG_ERROR).mean() > 3 * (norm.level == LOG_ERROR).mean()


def test_metrics_cpu_fault_sanity():
    # reference sanity check: CPU fault drives system cpu >90%
    # (SN_collection-scripts/README.md:106)
    m = synth.generate_metrics(labels.label_for("Perf_CPU_Contention"))
    cpu_idx = m.metric_names.index("system_cpu_usage")
    vals = m.value[m.metric == cpu_idx]
    assert vals.max() > 90
    norm = synth.generate_metrics(labels.label_for("Normal_Baseline"))
    nvals = norm.value[norm.metric == cpu_idx]
    assert nvals.max() < 90


def test_full_experiment_bundle():
    exp = synth.generate_experiment("Lv_C_exception_injection", n_traces=20)
    assert exp.testbed == "TT"
    assert exp.spans.n_spans > 0
    assert exp.metrics.n_samples > 0
    assert exp.logs.n_lines > 0
    assert exp.api.n_records == 600
    assert exp.coverage.service_ratio().shape[0] == len(synth.TT_SERVICES)
    assert len(exp.log_summaries) == len(synth.TT_SERVICES)


def test_concat_batches():
    a = synth.generate_spans(labels.label_for("Normal_case"), n_traces=10)
    b = synth.generate_spans(labels.label_for("Lv_D_cachelimit"), n_traces=10)
    c = concat_span_batches([a, b])
    assert c.n_spans == a.n_spans + b.n_spans
    assert c.n_traces == 20
    # parent indices remain within-trace
    nz = c.parent >= 0
    assert (c.trace[nz] == c.trace[c.parent[nz]]).all()


def test_skywalking_json_roundtrip_schema():
    l = labels.label_for("Lv_P_CPU_preserve")
    b = synth.generate_spans(l, n_traces=5)
    doc = synth.spans_to_skywalking_json(b, l.experiment)
    assert doc["metadata"]["span_count"] == b.n_spans
    assert len(doc["traces"]) == 5
    sp = doc["traces"][0]["spans"][0]
    for key in ("node_id", "trace_id", "segment_id", "span_id", "parent_span_id",
                "service_code", "start_timestamp_ms", "end_timestamp_ms",
                "duration_ms", "endpoint_name", "type", "is_error", "refs"):
        assert key in sp


def test_jaeger_json_schema():
    l = labels.label_for("Normal_Baseline")
    b = synth.generate_spans(l, n_traces=5)
    doc = synth.spans_to_jaeger_json(b)
    assert len(doc["data"]) == 5
    tr = doc["data"][0]
    assert "processes" in tr and "spans" in tr
    sp = tr["spans"][0]
    for key in ("traceID", "spanID", "processID", "operationName",
                "startTime", "duration", "references", "tags"):
        assert key in sp


def test_workload_helpers():
    from anomod.workload import is_valid_uri_or_empty, resolve_location
    assert resolve_location("", "http://h:8080/api/x") == "http://h:8080/api/x"
    assert resolve_location("http://other/api/y", "http://h:8080/api/x") \
        == "http://other/api/y"
    assert resolve_location("/api/y/123", "http://h:8080/api/x") \
        == "http://h:8080/api/y/123"
    assert is_valid_uri_or_empty("")
    assert is_valid_uri_or_empty("/api/v1/orders/5")
    assert is_valid_uri_or_empty("http://x/y?z=1")
    assert not is_valid_uri_or_empty("has space")


def test_sn_request_mix_weighting():
    # home-timeline-rooted templates dominate SN traffic (wrk2 60/30/10 mix)
    b = synth.generate_spans(labels.label_for("Normal_Baseline"), n_traces=300)
    ht = b.services.index("home-timeline-service")
    ut = b.services.index("user-timeline-service")
    # count ROOT-adjacent entries: spans whose parent is the nginx root
    root_child = b.parent >= 0
    nginx = b.services.index("nginx-web-server")
    first_hop = root_child & (b.service[np.clip(b.parent, 0, None)] == nginx)
    ht_n = (b.service[first_hop] == ht).sum()
    ut_n = (b.service[first_hop] == ut).sum()
    assert ht_n > ut_n  # 60% vs 30%
    # every template still present: all 12 services appear
    assert len(np.unique(b.service)) == len(b.services)


# ---------------------------------------------------------------------------
# Distribution-shift axes (HardMode effect_shape / fault_profile / fault_locus)
# ---------------------------------------------------------------------------

def _culprit_window_latency(batch, svc_name, lo_s=600, hi_s=1200):
    """Median in-window latency of one service's spans."""
    si = batch.services.index(svc_name)
    rel = (batch.start_us - batch.start_us.min()) / 1e6
    sel = (batch.service == si) & (rel >= lo_s) & (rel < hi_s)
    return batch.duration_us[sel]


def test_anomaly_window_profiles():
    t = np.arange(0, 1800, 5)
    sus = synth.anomaly_window_mask(t, "sustained")
    bur = synth.anomaly_window_mask(t, "bursty")
    par = synth.anomaly_window_mask(t, "partial")
    assert sus.sum() == ((t >= 600) & (t < 1200)).sum()
    # bursty: alternating 60 s bursts -> half the window, starting on
    assert bur.sum() == sus.sum() // 2
    assert bur[(t >= 600) & (t < 660)].all()
    assert not bur[(t >= 660) & (t < 720)].any()
    # partial: first half only
    assert par[(t >= 600) & (t < 900)].all()
    assert not par[(t >= 900)].any()
    assert not (bur & ~sus).any() and not (par & ~sus).any()
    with pytest.raises(ValueError, match="fault_profile"):
        synth.anomaly_window_mask(t, "ramp")


def test_effect_shapes_shift_latency_distribution():
    lab = labels.label_for("Lv_P_CPU_preserve")
    base = _culprit_window_latency(
        synth.generate_spans(labels.label_for("Normal_case"), n_traces=400),
        lab.target_service)
    shapes = {}
    for shape in ("mult", "add", "tail"):
        b = synth.generate_spans(lab, n_traces=400,
                                 hard=synth.HardMode(effect_shape=shape))
        shapes[shape] = _culprit_window_latency(b, lab.target_service)
    med0, p99_0 = np.median(base), np.quantile(base, 0.99)
    # mult: the whole distribution scales (median strongly inflated)
    assert np.median(shapes["mult"]) > 3 * med0
    # add: location moves by a constant, so the median moves but the
    # relative spread shrinks vs mult (spread does not scale)
    assert np.median(shapes["add"]) > 2 * med0
    iqr = lambda a: (np.quantile(a, 0.75) - np.quantile(a, 0.25)) / np.median(a)
    assert iqr(shapes["add"]) < 0.6 * iqr(shapes["mult"])
    # tail: the median barely moves, the p99 strongly does
    assert np.median(shapes["tail"]) < 1.8 * med0
    assert np.quantile(shapes["tail"], 0.99) > 3 * p99_0
    with pytest.raises(ValueError, match="effect_shape"):
        synth.generate_spans(lab, n_traces=10,
                             hard=synth.HardMode(effect_shape="step"))


def test_edge_locus_moves_signal_to_callees():
    lab = labels.label_for("Lv_P_CPU_preserve")
    node = synth.generate_spans(lab, n_traces=400)
    edge = synth.generate_spans(lab, n_traces=400,
                                hard=synth.HardMode(fault_locus="edge"))
    normal = synth.generate_spans(labels.label_for("Normal_case"), n_traces=400)
    # the culprit's own spans stay healthy under edge locus
    cul_edge = _culprit_window_latency(edge, lab.target_service)
    cul_norm = _culprit_window_latency(normal, lab.target_service)
    assert np.median(cul_edge) < 1.5 * np.median(cul_norm)
    assert np.median(_culprit_window_latency(node, lab.target_service)) \
        > 3 * np.median(cul_norm)
    # the callee side of the culprit's outgoing calls degrades instead
    ti = edge.services.index(lab.target_service)
    for b, expect_hot in ((edge, True), (normal, False)):
        rel = (b.start_us - b.start_us.min()) / 1e6
        cross = (b.parent >= 0) \
            & (b.service[np.clip(b.parent, 0, None)] == ti) \
            & (b.service != ti)  # callee side, excluding entry->exit self-edges
        callee = cross & (rel >= 600) & (rel < 1200)
        out_w = cross & ~((rel >= 600) & (rel < 1200))
        assert callee.sum() > 20
        ratio = np.median(b.duration_us[callee]) / np.median(b.duration_us[out_w])
        assert (ratio > 3) if expect_hot else (ratio < 1.6), ratio
    # and the node-scoped modalities stay healthy (link fault): culprit log
    # error rate matches the healthy baseline
    logs_e, _ = synth.generate_logs(lab, hard=synth.HardMode(fault_locus="edge"))
    logs_n, _ = synth.generate_logs(lab)
    from anomod.schemas import LOG_ERROR
    def err_rate(lb):
        sel = lb.service == lb.services.index(lab.target_service)
        return (lb.level[sel] == LOG_ERROR).mean()
    assert err_rate(logs_n) > 5 * err_rate(logs_e)


def test_bursty_profile_is_cross_modality():
    """The fault-timing shift must move metrics and spans together."""
    lab = labels.label_for("Lv_P_CPU_preserve")
    m = synth.generate_metrics(lab, hard=synth.HardMode(fault_profile="bursty"))
    i = m.metric_names.index("container_cpu_usage_seconds_total")
    ti = m.services.index(lab.target_service)
    svc_of_sample = m.series_service[m.series]
    sel = (m.metric == i) & (svc_of_sample == ti)
    t_rel = m.t_s[sel] - m.t_s.min()
    v = m.value[sel]
    on = v[(t_rel >= 600) & (t_rel < 660)]
    off = v[(t_rel >= 660) & (t_rel < 720)]
    assert len(on) and len(off)
    assert on.mean() > 2 * off.mean()  # fault active only during bursts


def test_edge_locus_no_artifact_leak_for_leaf_target():
    """A zero-out-edge target under edge locus faults NO edge — its corpus
    must carry no localizing artifact.  Coverage and API previously leaked
    the target's identity here (coverage ratio drop was not locus-gated;
    api degraded target-owned routes regardless of out-edges), which let
    trained models 'recover' culprits from corpora with zero fault signal."""
    lab = labels.label_for("Svc_Kill_Media")          # media has no callees
    assert not any(a == lab.target_service for a, _c in synth.SN_EDGES)
    hard = synth.HardMode(fault_locus="edge")
    # coverage: target ratio must match the node-locus baseline jitter band
    cov_e = synth.generate_coverage(lab, hard=hard)
    cov_n = synth.generate_coverage(labels.label_for("Normal_Baseline"))
    def ratio(cb, svc):
        return float(cb.service_ratio()[cb.services.index(svc)])
    assert abs(ratio(cov_e, lab.target_service)
               - ratio(cov_n, lab.target_service)) < 0.05
    # api: no 5xx concentration and no latency inflation anywhere
    api_e = synth.generate_api(lab, hard=hard)
    assert (api_e.status >= 500).mean() < 0.01
    # a target WITH out-edges keeps the end-to-end route degradation
    lab2 = labels.label_for("Svc_Kill_UserTimeline")
    assert any(a == lab2.target_service for a, _c in synth.SN_EDGES)
    api2 = synth.generate_api(lab2, hard=hard)
    assert (api2.status >= 500).mean() > 0.01
    # node-locus coverage still shifts on the culprit (the gate is
    # locus-scoped, not a blanket removal)
    cov_node = synth.generate_coverage(lab)
    assert ratio(cov_n, lab.target_service) \
        - ratio(cov_node, lab.target_service) > 0.05
