"""Multi-tenant serving plane: replay parity, weighted-fair admission,
priority-ordered shedding, SLO accounting, env contract.

The two acceptance-critical pins:

- PARITY: the batched+bucketed serving plane emits the EXACT (bit-
  identical, CPU, seeded) alert stream of a per-tenant sequential
  StreamReplay/OnlineDetector on the same spans — padding rows target
  the dead lane, real rows keep their sequential positions, so the f32
  state and every alert float match to the bit.
- OVERLOAD: under a seeded 2x overload, shedding is priority-ordered
  (gold < silver < bronze shed fractions) and the whole report is
  deterministic (wall-clock fields aside).
"""

import dataclasses

import numpy as np
import pytest

from anomod import labels, synth
from anomod.replay import ReplayConfig
from anomod.schemas import take_spans
from anomod.serve import (AdmissionController, BucketedStreamReplay,
                          BucketRunner, PowerLawTraffic, ScriptedTraffic,
                          ServeEngine, TenantSpec, split_plan)
from anomod.serve.engine import run_power_law
from anomod.serve.batcher import validate_buckets
from anomod.serve.traffic import TenantFault
from anomod.stream import OnlineDetector, StreamReplay


# ---------------------------------------------------------------------------
# batcher: split plan + bucket contract + state parity
# ---------------------------------------------------------------------------

def test_split_plan_full_chunks_then_bucketed_tail():
    assert split_plan(0, 4096, (256, 1024)) == []
    assert split_plan(100, 4096, (256, 1024)) == [(0, 100, 256)]
    assert split_plan(256, 4096, (256, 1024)) == [(0, 256, 256)]
    assert split_plan(300, 4096, (256, 1024)) == [(0, 300, 1024)]
    # tail wider than every bucket pads to the full chunk width
    assert split_plan(2000, 4096, (256, 1024)) == [(0, 2000, 4096)]
    assert split_plan(5000, 4096, (256, 1024)) == [
        (0, 4096, 4096), (4096, 5000, 1024)]
    # buckets wider than chunk_size never stage (parity would break)
    assert split_plan(100, 512, (256, 1024)) == [(0, 100, 256)]
    assert split_plan(400, 512, (256, 1024)) == [(0, 400, 512)]


def test_validate_buckets_contract():
    assert validate_buckets((256, 1024)) == (256, 1024)
    assert validate_buckets(["8", "16"]) == (8, 16)
    with pytest.raises(ValueError):
        validate_buckets(())
    with pytest.raises(ValueError):
        validate_buckets((1024, 256))          # not ascending
    with pytest.raises(ValueError):
        validate_buckets((256, 256))           # not strictly ascending
    with pytest.raises(ValueError):
        validate_buckets((0, 256))
    with pytest.raises(ValueError):
        validate_buckets(("x",))


def test_bucketed_replay_state_bit_identical_to_stream_replay():
    """Same pushes through the bucketed runner and the sequential
    StreamReplay give bit-identical f32 state (the parity mechanism)."""
    batch = synth.generate_spans(labels.label_for("Lv_P_CPU_preserve"),
                                 n_traces=60)
    cfg = ReplayConfig(n_services=batch.n_services, chunk_size=2048)
    order = np.argsort(batch.start_us, kind="stable")
    batch = take_spans(batch, order)
    t0 = int(batch.start_us.min())

    seq = StreamReplay(cfg, t0)
    bucketed = BucketedStreamReplay(cfg, t0, BucketRunner(cfg, (256, 1024)))
    cuts = [0, 137, 700, 2500, batch.n_spans]
    for lo, hi in zip(cuts, cuts[1:]):
        mb = take_spans(batch, slice(lo, hi))
        assert seq.push(mb) == bucketed.push(mb)   # same window binning
    assert seq.window_offset == bucketed.window_offset
    np.testing.assert_array_equal(np.asarray(seq.state.agg),
                                  np.asarray(bucketed.state.agg))
    np.testing.assert_array_equal(np.asarray(seq.state.hist),
                                  np.asarray(bucketed.state.hist))


# ---------------------------------------------------------------------------
# admission: WFQ order, backpressure, priority eviction
# ---------------------------------------------------------------------------

def _spans(n):
    from anomod.schemas import SpanBatch
    return SpanBatch(
        trace=np.zeros(n, np.int32), parent=np.full(n, -1, np.int32),
        service=np.zeros(n, np.int32), endpoint=np.zeros(n, np.int32),
        start_us=np.arange(n, dtype=np.int64),
        duration_us=np.ones(n, np.int64),
        is_error=np.zeros(n, np.bool_), status=np.full(n, 200, np.int16),
        kind=np.zeros(n, np.int8), services=("s",), endpoints=("e",),
        trace_ids=("t",)).validate()


def test_wfq_serves_by_weight_and_keeps_tenant_fifo():
    specs = [TenantSpec(0, "gold", priority=0),     # weight 4
             TenantSpec(1, "bronze", priority=2)]   # weight 1
    adm = AdmissionController(specs, max_backlog=10_000,
                              max_tenant_backlog=10_000)
    for i in range(4):
        assert adm.offer(0, _spans(100), now_s=0.0)
        assert adm.offer(1, _spans(100), now_s=0.0)
    served = adm.drain(500)
    got = [(qb.tenant_id, qb.seq) for qb in served]
    # weight 4 vs 1: gold finishes tags at 25/wf spacing vs 100 -> gold's
    # first four batches drain before bronze's second
    assert [t for t, _ in got[:4]].count(0) >= 3
    # per-tenant FIFO: seqs strictly increase within each tenant
    for tid in (0, 1):
        seqs = [s for t, s in got if t == tid]
        assert seqs == sorted(seqs)


def test_per_tenant_backlog_bounds_runaway_feed():
    specs = [TenantSpec(0, "noisy", priority=0),
             TenantSpec(1, "quiet", priority=2)]
    adm = AdmissionController(specs, max_backlog=10_000,
                              max_tenant_backlog=250)
    assert adm.offer(0, _spans(200), now_s=0.0)
    assert not adm.offer(0, _spans(200), now_s=0.0)   # own overflow shed
    assert adm.offer(1, _spans(200), now_s=0.0)       # nobody else pays
    assert adm.counters[0].shed_spans == 200
    assert adm.counters[1].shed_spans == 0


def test_global_overflow_evicts_strictly_lower_priority_only():
    specs = [TenantSpec(0, "gold", priority=0),
             TenantSpec(1, "bronze", priority=2)]
    adm = AdmissionController(specs, max_backlog=500,
                              max_tenant_backlog=500)
    assert adm.offer(1, _spans(400), now_s=0.0)
    # gold arrival displaces queued bronze work
    assert adm.offer(0, _spans(400), now_s=1.0)
    assert adm.counters[1].shed_spans == 400
    assert adm.backlog_spans == 400
    # bronze arrival cannot displace queued gold work: it is shed itself
    assert not adm.offer(1, _spans(400), now_s=2.0)
    assert adm.counters[0].shed_spans == 0


def test_oversized_batch_admits_against_empty_queue():
    """A batch wider than a backlog bound must still admit when nothing
    is queued (the admission mirror of drain()'s one-batch overdraw) —
    otherwise it would be starved forever at any load (review finding)."""
    specs = [TenantSpec(0, "t", priority=1)]
    adm = AdmissionController(specs, max_backlog=100,
                              max_tenant_backlog=100)
    assert adm.offer(0, _spans(500), now_s=0.0)       # idle: overdraw
    assert not adm.offer(0, _spans(10), now_s=0.0)    # now bounded
    assert adm.drain(1_000_000)
    assert adm.offer(0, _spans(500), now_s=1.0)       # drained: again ok
    # a gold mega-batch may still displace an all-bronze backlog wholesale
    specs = [TenantSpec(0, "gold", priority=0),
             TenantSpec(1, "bronze", priority=2)]
    adm = AdmissionController(specs, max_backlog=100,
                              max_tenant_backlog=100)
    assert adm.offer(1, _spans(80), now_s=0.0)
    assert adm.offer(0, _spans(500), now_s=1.0)
    assert adm.counters[1].shed_spans == 80


def test_eviction_is_transactional_when_infeasible():
    """An arrival that cannot fit even after evicting ALL lower-priority
    work must be shed alone — evicting victims it still can't use would
    lose both (review finding)."""
    specs = [TenantSpec(0, "gold", priority=0),
             TenantSpec(1, "bronze", priority=2)]
    adm = AdmissionController(specs, max_backlog=500,
                              max_tenant_backlog=500)
    assert adm.offer(1, _spans(400), now_s=0.0)
    assert adm.offer(0, _spans(100), now_s=0.0)       # backlog full: 500
    # gold 450 needs 450 headroom; only 400 bronze is evictable -> the
    # arrival sheds and the queued work survives untouched
    assert not adm.offer(0, _spans(450), now_s=1.0)
    assert adm.backlog_spans == 500
    assert adm.counters[1].shed_spans == 0
    assert adm.counters[0].shed_spans == 450


def test_evict_heap_stays_bounded_on_long_healthy_run():
    """Drained batches must not accumulate forever in the eviction heap
    on a never-overloaded controller (review finding)."""
    specs = [TenantSpec(0, "t", priority=1)]
    adm = AdmissionController(specs, max_backlog=10_000,
                              max_tenant_backlog=10_000)
    for _ in range(2000):
        adm.offer(0, _spans(10), now_s=0.0)
        adm.drain(1_000_000)
    assert adm.backlog_spans == 0
    assert len(adm._evict_heap) < 200


def test_drain_overdraws_at_most_one_batch():
    specs = [TenantSpec(0, "t", priority=1)]
    adm = AdmissionController(specs, max_backlog=10_000,
                              max_tenant_backlog=10_000)
    adm.offer(0, _spans(300), now_s=0.0)
    adm.offer(0, _spans(300), now_s=0.0)
    served = adm.drain(100)          # budget smaller than one batch
    assert len(served) == 1          # overdraw by one, never deadlock
    assert adm.backlog_spans == 300


# ---------------------------------------------------------------------------
# traffic: determinism, power-law shape, batch cap
# ---------------------------------------------------------------------------

def test_powerlaw_traffic_deterministic_and_capped():
    def collect(seed):
        tr = PowerLawTraffic(n_tenants=8, total_rate_spans_per_s=2000,
                             seed=seed, n_services=4, batch_cap=128)
        out = []
        for k in range(5):
            out.append([(t, b.n_spans, b.start_us.tolist())
                        for t, b in tr.arrivals(k * 1.0, (k + 1) * 1.0)])
        return out
    a, b_, c = collect(1), collect(1), collect(2)
    assert a == b_                       # seeded determinism
    assert a != c                        # seed actually matters
    assert all(n <= 128 for tick in a for _, n, _ in tick)
    # power law: the head tenant offers more than the tail tenant
    tr = PowerLawTraffic(n_tenants=8, total_rate_spans_per_s=2000,
                         alpha=1.2, seed=0)
    assert tr.specs[0].rate_spans_per_s > 3 * tr.specs[7].rate_spans_per_s


def test_scripted_traffic_slices_by_virtual_time():
    b = synth.generate_spans(labels.label_for("Normal_case"), n_traces=30)
    t0 = int(b.start_us.min())
    tr = ScriptedTraffic({0: b}, [TenantSpec(0, "t")], t0)
    total = 0
    t, end = 0.0, tr.end_s() + 60.0
    while t < end:
        for tid, mb in tr.arrivals(t, t + 60.0):
            assert tid == 0
            assert (mb.start_us >= t0 + t * 1e6).all()
            assert (mb.start_us < t0 + (t + 60.0) * 1e6).all()
            total += mb.n_spans
        t += 60.0
    assert total == b.n_spans


# ---------------------------------------------------------------------------
# the acceptance pins
# ---------------------------------------------------------------------------

def test_serving_plane_alert_stream_bit_identical_to_sequential():
    """THE parity criterion: multi-tenant batched+bucketed serving emits
    the exact alert stream of per-tenant sequential StreamReplay/
    OnlineDetector on the same spans (CPU, seeded)."""
    streams = {
        0: synth.generate_spans(labels.label_for("Lv_P_CPU_preserve"),
                                n_traces=120),
        1: synth.generate_spans(
            labels.label_for("Lv_C_travel_detail_failure"), n_traces=120),
    }
    services = streams[0].services
    t0 = min(int(b.start_us.min()) for b in streams.values())
    cfg = ReplayConfig(n_services=len(services), chunk_size=4096)
    specs = [TenantSpec(tenant_id=i, name=f"t{i}", priority=i % 3)
             for i in streams]
    traffic = ScriptedTraffic(streams, specs, t0)
    duration = traffic.end_s() + 60.0

    eng = ServeEngine(specs, services, cfg, t0_us=t0,
                      capacity_spans_per_s=10_000_000, tick_s=60.0,
                      buckets=(256, 1024), max_backlog=10_000_000,
                      max_tenant_backlog=10_000_000, baseline_windows=8)
    rep = eng.run(traffic, duration_s=duration)
    assert rep.shed_spans == 0                      # ample capacity
    assert rep.n_alerts > 0                         # faults actually alert

    for tid in streams:
        solo = OnlineDetector(services, cfg, t0,
                              replay=StreamReplay(cfg, t0),
                              baseline_windows=8)
        t = 0.0
        while t < duration:
            for tid2, mb in traffic.arrivals(t, t + 60.0):
                if tid2 == tid:
                    solo.push(mb)
            t += 60.0
        solo.finish()
        assert [dataclasses.asdict(a) for a in eng.alerts_for(tid)] \
            == [dataclasses.asdict(a) for a in solo.alerts]


def test_multimodal_serving_parity_with_sequential_detector():
    """Log/metric/api micro-batches ride the serving plane too
    (MultimodalDetector per tenant): the alert stream stays bit-identical
    to a sequential multimodal baseline fed the same one-clock slices."""
    from anomod.stream import MultimodalDetector
    label = labels.label_for("Svc_Kill_UserTimeline")
    exp = synth.generate_experiment(label, n_traces=100, seed=0)
    services = exp.spans.services
    t0 = int(exp.spans.start_us.min())
    cfg = ReplayConfig(n_services=len(services), chunk_size=4096)
    specs = [TenantSpec(tenant_id=0, name="t0")]
    traffic = ScriptedTraffic({0: exp.spans}, specs, t0,
                              experiments={0: exp})
    duration = traffic.end_s() + 60.0

    eng = ServeEngine(specs, services, cfg, t0_us=t0,
                      capacity_spans_per_s=10_000_000, tick_s=60.0,
                      buckets=(256, 1024), max_backlog=10_000_000,
                      max_tenant_backlog=10_000_000, baseline_windows=8,
                      multimodal=True, testbed=label.testbed)
    rep = eng.run(traffic, duration_s=duration)
    assert rep.modality_events["logs"] > 0
    assert rep.modality_events["metrics"] > 0
    assert rep.modality_events["api"] > 0

    solo = MultimodalDetector(services, cfg, t0, testbed=label.testbed,
                              replay=StreamReplay(cfg, t0),
                              baseline_windows=8)
    t = 0.0
    while t < duration:
        for _, kind, mb in traffic.modality_arrivals(t, t + 60.0):
            getattr(solo, f"push_{kind}")(mb)
        for _, mb in traffic.arrivals(t, t + 60.0):
            solo.push(mb)
        t += 60.0
    solo.finish()
    assert solo.alerts                           # the kill fault alerts
    assert [dataclasses.asdict(a) for a in eng.alerts_for(0)] \
        == [dataclasses.asdict(a) for a in solo.alerts]


def _overload_report(seed, score=False):
    traffic = PowerLawTraffic(
        n_tenants=12, total_rate_spans_per_s=2000, alpha=0.0, seed=seed,
        n_services=4, batch_cap=128)
    cfg = ReplayConfig(n_services=4, n_windows=16, window_us=5_000_000,
                       chunk_size=1024)
    eng = ServeEngine(traffic.specs, traffic.services, cfg,
                      capacity_spans_per_s=1000, tick_s=1.0,
                      buckets=(128, 512), max_backlog=1500,
                      max_tenant_backlog=1500, score=score,
                      baseline_windows=4)
    return eng.run(traffic, duration_s=40.0)


def test_overload_shedding_is_priority_ordered_and_deterministic():
    """Seeded 2x overload: shed fractions order strictly by priority
    class, and the whole report reproduces bit-for-bit (wall-clock
    fields aside)."""
    rep = _overload_report(5)
    assert rep.offered_spans > 1.8 * rep.served_spans   # real overload
    assert 0.3 < rep.shed_fraction < 0.7
    pp = rep.per_priority
    assert pp[0]["shed_fraction"] < pp[1]["shed_fraction"] \
        < pp[2]["shed_fraction"]
    # gold's weighted share exceeds its equal-rate demand -> barely shed
    assert pp[0]["shed_fraction"] < 0.1
    # backpressure: the backlog never exceeded its bound
    assert rep.peak_backlog_spans <= rep.max_backlog
    # queueing under overload is visible in the latency sketch
    assert rep.latency["p99_latency_s"] > 0

    wall = ("serve_wall_s", "sustained_spans_per_sec", "compile_s",
            "lane_compile_s", "stage_wall_s", "dispatch_wall_s",
            "fold_wall_s", "score_wall_s", "ckpt_wall_s",
            "recovery_wall_s")
    a = {k: v for k, v in _overload_report(5).to_dict().items()
         if k not in wall}
    b = {k: v for k, v in _overload_report(5).to_dict().items()
         if k not in wall}
    assert a == b


def test_engine_smoke_scores_and_detects_fault_under_load():
    """Tier-1 smoke (<5s): a small scored run serves, sheds, tracks SLOs
    and detects a scripted tenant fault."""
    traffic = PowerLawTraffic(
        n_tenants=6, total_rate_spans_per_s=1200, alpha=0.0, seed=3,
        n_services=4, batch_cap=256,
        faults={1: TenantFault("latency", service=1, onset_s=30.0,
                               factor=12.0)})
    cfg = ReplayConfig(n_services=4, n_windows=16, window_us=5_000_000,
                       chunk_size=1024)
    eng = ServeEngine(traffic.specs, traffic.services, cfg,
                      capacity_spans_per_s=900, tick_s=1.0,
                      buckets=(256,), max_backlog=2000, baseline_windows=4)
    rep = eng.run(traffic, duration_s=60.0)
    assert rep.served_spans > 0 and rep.shed_spans > 0
    assert rep.fault_detection == {
        "n_fault_tenants": 1, "n_detected": 1,
        "median_alert_latency_windows":
            rep.fault_detection["median_alert_latency_windows"]}
    assert rep.fault_detection["median_alert_latency_windows"] is not None
    assert rep.fault_detection["median_alert_latency_windows"] <= 4
    assert rep.sustained_spans_per_sec > 0
    d = rep.to_dict()
    import json
    json.dumps(d)                                  # report is JSON-able
    assert d["dispatches_by_width"] and \
        set(d["dispatches_by_width"]) <= {"256", "1024"}


def test_mesh_serve_matches_bucketed_alert_set():
    """With ``mesh=`` every tenant's plane is the pod-sharded
    ShardedStreamReplay, reused unchanged.  psum merge reorders the f32
    moment additions, so the pin is alert (window, service) identity,
    not bit equality (same contract as the existing sharded-stream
    parity tests)."""
    from anomod.parallel import make_mesh
    traffic = PowerLawTraffic(
        n_tenants=2, total_rate_spans_per_s=600, alpha=0.0, seed=2,
        n_services=4, batch_cap=256,
        faults={0: TenantFault("latency", service=1, onset_s=30.0,
                               factor=12.0)})
    cfg = ReplayConfig(n_services=4, n_windows=16, window_us=5_000_000,
                       chunk_size=512)
    kw = dict(capacity_spans_per_s=10_000, tick_s=1.0, buckets=(256,),
              max_backlog=100_000, max_tenant_backlog=100_000,
              baseline_windows=4)
    eng_mesh = ServeEngine(traffic.specs, traffic.services, cfg,
                           mesh=make_mesh(2), **kw)
    eng_mesh.run(traffic, duration_s=50.0)
    traffic2 = PowerLawTraffic(
        n_tenants=2, total_rate_spans_per_s=600, alpha=0.0, seed=2,
        n_services=4, batch_cap=256,
        faults={0: TenantFault("latency", service=1, onset_s=30.0,
                               factor=12.0)})
    eng_bkt = ServeEngine(traffic2.specs, traffic2.services, cfg, **kw)
    eng_bkt.run(traffic2, duration_s=50.0)
    for tid in (0, 1):
        assert [(a.window, a.service) for a in eng_mesh.alerts_for(tid)] \
            == [(a.window, a.service) for a in eng_bkt.alerts_for(tid)]
    assert eng_mesh.alerts_for(0)          # the fault actually alerted


def test_tracer_records_serving_phases():
    """The fused tick wraps its one dispatch phase in serve.score_fused;
    the unfused escape hatch keeps the historical per-batch serve.score
    span."""
    from anomod.utils.tracing import Tracer

    def phases(fuse):
        tracer = Tracer("anomod-serve")
        traffic = PowerLawTraffic(n_tenants=3, total_rate_spans_per_s=300,
                                  seed=0, n_services=4)
        cfg = ReplayConfig(n_services=4, n_windows=16, window_us=5_000_000,
                           chunk_size=512)
        eng = ServeEngine(traffic.specs, traffic.services, cfg,
                          capacity_spans_per_s=500, tick_s=1.0,
                          buckets=(256,), score=False, tracer=tracer,
                          fuse=fuse)
        eng.run(traffic, duration_s=10.0)
        return {s["operationName"]
                for s in tracer.to_jaeger()["data"][0]["spans"]}

    fused = phases(True)
    assert {"serve.run", "serve.admit", "serve.drain",
            "serve.score_fused"} <= fused
    assert "serve.score" not in fused
    unfused = phases(False)
    assert {"serve.run", "serve.admit", "serve.drain",
            "serve.score"} <= unfused
    assert "serve.score_fused" not in unfused


# ---------------------------------------------------------------------------
# env contract
# ---------------------------------------------------------------------------

def test_serve_env_knobs_registered_and_validated(monkeypatch):
    from anomod.config import Config
    monkeypatch.setenv("ANOMOD_SERVE_BUCKETS", "128, 512,2048")
    monkeypatch.setenv("ANOMOD_SERVE_MAX_BACKLOG", "5000")
    cfg = Config()
    assert cfg.serve_buckets == (128, 512, 2048)
    assert cfg.serve_max_backlog == 5000

    monkeypatch.setenv("ANOMOD_SERVE_BUCKETS", "512,128")
    with pytest.raises(ValueError, match="ANOMOD_SERVE_BUCKETS"):
        Config()
    monkeypatch.setenv("ANOMOD_SERVE_BUCKETS", "banana")
    with pytest.raises(ValueError, match="ANOMOD_SERVE_BUCKETS"):
        Config()
    monkeypatch.delenv("ANOMOD_SERVE_BUCKETS")
    monkeypatch.setenv("ANOMOD_SERVE_MAX_BACKLOG", "0")
    with pytest.raises(ValueError, match="ANOMOD_SERVE_MAX_BACKLOG"):
        Config()
    monkeypatch.setenv("ANOMOD_SERVE_MAX_BACKLOG", "many")
    with pytest.raises(ValueError, match="ANOMOD_SERVE_MAX_BACKLOG"):
        Config()
    monkeypatch.delenv("ANOMOD_SERVE_MAX_BACKLOG")
    from anomod.serve.batcher import DEFAULT_BUCKETS
    assert Config().serve_buckets == DEFAULT_BUCKETS


# ---------------------------------------------------------------------------
# tenant-fused scoring: lane-stacked dispatch + coalescing (the PR-4 pins)
# ---------------------------------------------------------------------------

def _rand_spans(n, n_services, seed, t_lo_s=0.0, t_hi_s=60.0):
    from anomod.schemas import SpanBatch
    rng = np.random.default_rng(seed)
    err = rng.random(n) < 0.05
    return SpanBatch(
        trace=rng.integers(0, 16, n).astype(np.int32),
        parent=np.full(n, -1, np.int32),
        service=rng.integers(0, n_services, n).astype(np.int32),
        endpoint=np.zeros(n, np.int32),
        start_us=np.sort(rng.integers(int(t_lo_s * 1e6), int(t_hi_s * 1e6),
                                      n)).astype(np.int64),
        duration_us=rng.integers(1, 1_000_000, n).astype(np.int64),
        is_error=err.astype(np.bool_),
        status=np.where(err, 500, 200).astype(np.int16),
        kind=np.zeros(n, np.int8),
        services=tuple(f"s{i}" for i in range(n_services)),
        endpoints=("e",),
        trace_ids=tuple(f"t{i:02d}" for i in range(16))).validate()


def test_run_lanes_bit_identical_to_single_dispatch():
    """The fused mechanism itself: lane-stacked dispatches (including a
    dead-padded group) produce per-lane states bit-identical to
    dispatching each lane's chunk alone."""
    from anomod.replay import N_FEATS, ReplayState
    cfg = ReplayConfig(n_services=6, n_windows=8, window_us=5_000_000,
                       chunk_size=512)
    runner = BucketRunner(cfg, (128, 512), lane_buckets=(1, 2, 4))
    runner.warm()
    rng = np.random.default_rng(0)

    def rand_state():
        return ReplayState(
            agg=rng.lognormal(3, 2, (cfg.sw, N_FEATS)).astype(np.float32),
            hist=rng.lognormal(1, 1,
                               (cfg.sw, cfg.n_hist_buckets)).astype(
                                   np.float32))

    # five lanes of width-128 chunks: lane_plan -> a full 4-bucket group
    # plus a dead-padded 1-bucket group
    work = []
    for i in range(5):
        plan = runner.stage_plan(_rand_spans(100 + i, 6, seed=i), 0)
        assert [w for w, _ in plan] == [128]
        work.append((rand_state(), plan[0][1]))
    seq = [runner.dispatch(st, cols, 128) for st, cols in work]
    fused = runner.run_lanes(128, list(work))
    for a, b in zip(seq, fused):
        np.testing.assert_array_equal(np.asarray(a.agg), np.asarray(b.agg))
        np.testing.assert_array_equal(np.asarray(a.hist),
                                      np.asarray(b.hist))
    assert runner.fused_dispatches == 2
    assert runner.lanes_by_bucket == {4: 1, 1: 1}
    assert runner.staged_lanes == 5 and runner.live_lanes == 5
    assert runner.lane_pad_waste == 0.0


def test_scatter_step_bit_identical_to_matmul_step():
    """The CPU engine swap the fused path leans on: the segment-sum
    (scatter) formulation of the chunk step produces the BIT-identical
    f32 state of the one-hot matmul formulation, single-lane and
    lane-stacked (delta + host add) alike."""
    import jax

    from anomod.replay import (N_FEATS, ReplayState, make_chunk_step,
                               make_lane_delta, stage_columns)
    cfg = ReplayConfig(n_services=6, n_windows=8, window_us=5_000_000,
                       chunk_size=256)
    mat = jax.jit(lambda st, ch: make_chunk_step(
        cfg, engine="matmul")(st, ch)[0])
    sca = jax.jit(lambda st, ch: make_chunk_step(
        cfg, engine="scatter")(st, ch)[0])
    lane = jax.jit(make_lane_delta(cfg, engine="scatter"))
    rng = np.random.default_rng(3)
    states, chunks = [], []
    for i in range(4):
        st = ReplayState(
            agg=rng.lognormal(3, 2, (cfg.sw, N_FEATS)).astype(np.float32),
            hist=rng.lognormal(
                1, 1, (cfg.sw, cfg.n_hist_buckets)).astype(np.float32))
        staged, _ = stage_columns(_rand_spans(100 + 30 * i, 6, seed=10 + i),
                                  cfg, t0_us=0)
        ch = {k: v[0] for k, v in staged.items()}
        states.append(st)
        chunks.append(ch)
        a, b = mat(st, ch), sca(st, ch)
        np.testing.assert_array_equal(np.asarray(a.agg), np.asarray(b.agg))
        np.testing.assert_array_equal(np.asarray(a.hist),
                                      np.asarray(b.hist))
    dagg, dhist = lane({k: np.stack([c[k] for c in chunks])
                        for k in chunks[0]})
    dagg, dhist = np.asarray(dagg), np.asarray(dhist)
    for i, (st, ch) in enumerate(zip(states, chunks)):
        want = mat(st, ch)
        np.testing.assert_array_equal(np.asarray(want.agg),
                                      st.agg + dagg[i])
        np.testing.assert_array_equal(np.asarray(want.hist),
                                      st.hist + dhist[i])


@pytest.mark.parametrize(
    "seed", [0, pytest.param(1, marks=pytest.mark.slow)])
def test_fused_scoring_bit_identical_to_sequential_with_coalescing(seed):
    """THE fused parity pin: a fused engine run under overload — with
    same-tenant micro-batches genuinely coalescing per tick — emits
    per-tenant states AND alert streams bit-identical to a sequential
    per-tenant StreamReplay/OnlineDetector fed the same per-tick
    coalesced batches (CPU).  SLO parity is pinned separately against
    the unfused engine (identical admission ⇒ identical latencies)."""
    from anomod.schemas import concat_span_batches

    def traffic():
        return PowerLawTraffic(
            n_tenants=6, total_rate_spans_per_s=1800, alpha=0.6, seed=seed,
            n_services=4, batch_cap=64,
            faults={0: TenantFault("latency", service=1, onset_s=30.0,
                                   factor=12.0)})
    cfg = ReplayConfig(n_services=4, n_windows=16, window_us=5_000_000,
                       chunk_size=1024)
    tr = traffic()
    eng = ServeEngine(tr.specs, tr.services, cfg,
                      capacity_spans_per_s=1200, tick_s=1.0,
                      buckets=(128, 512), lane_buckets=(1, 2, 4, 8),
                      max_backlog=2400, baseline_windows=4, fuse=True)
    eng.runner.warm()
    eng.runner.warm_lanes()
    served_log = []
    for k in range(50):
        served_log.append(eng.tick(tr.arrivals(k * 1.0, (k + 1) * 1.0)))
    for det in eng._tenant_det.values():
        det.finish()
    # the regrouping must actually be exercised: some tick coalesced >= 2
    # micro-batches of one tenant, and some fused dispatch ran > 1 lane
    assert any(
        int(np.bincount([qb.tenant_id for qb in served]).max()) >= 2
        for served in served_log if served)
    assert any(b > 1 for b in eng.runner.lanes_by_bucket)
    assert eng.report(traffic=tr).n_alerts > 0      # the fault alerted

    for tid in sorted({qb.tenant_id for served in served_log
                       for qb in served}):
        solo = OnlineDetector(tr.services, cfg, 0,
                              replay=StreamReplay(cfg, 0),
                              baseline_windows=4)
        for served in served_log:
            mine = [qb.spans for qb in served if qb.tenant_id == tid]
            if mine:
                solo.push(mine[0] if len(mine) == 1
                          else concat_span_batches(mine))
        solo.finish()
        assert [dataclasses.asdict(a) for a in eng.alerts_for(tid)] \
            == [dataclasses.asdict(a) for a in solo.alerts]
        rep = eng._tenant_replay[tid]
        assert rep.window_offset == solo.replay.window_offset
        assert rep.n_spans == solo.replay.n_spans
        np.testing.assert_array_equal(np.asarray(rep.state.agg),
                                      np.asarray(solo.replay.state.agg))
        np.testing.assert_array_equal(np.asarray(rep.state.hist),
                                      np.asarray(solo.replay.state.hist))


def test_fused_and_unfused_slo_and_admission_identical():
    """Fusion must not move a single admission/shed/SLO number: the
    drained batches and their latency samples are identical, so the
    report's counters and latency quantiles match exactly."""
    def go(fuse):
        _, rep = run_power_law(
            n_tenants=8, n_services=4, capacity_spans_per_s=1000,
            overload=2.0, duration_s=30, tick_s=1.0, seed=4,
            window_s=5.0, baseline_windows=4, fault_tenants=0,
            buckets=(128, 512), max_backlog=1500, fuse=fuse)
        return rep
    a, b = go(True), go(False)
    assert a.fused and not b.fused
    for f in ("offered_spans", "admitted_spans", "served_spans",
              "shed_spans", "served_batches", "peak_backlog_spans",
              "latency", "per_priority", "dispatches_by_width"):
        assert getattr(a, f) == getattr(b, f), f


def test_fused_compile_count_pin():
    """Exactly ONE compile per (width, lane-bucket) shape over a long
    fused run: the warm grid covers everything the tick loop can
    dispatch, and nothing recompiles mid-serve (via the jit compile
    counters the observability plane already keeps)."""
    from anomod.obs.registry import Registry, set_registry
    reg = Registry(enabled=True)
    prev = set_registry(reg)
    try:
        eng, rep = run_power_law(
            n_tenants=10, n_services=4, capacity_spans_per_s=1500,
            overload=1.5, duration_s=60, tick_s=0.5, seed=6,
            window_s=5.0, baseline_windows=4, fault_tenants=0,
            buckets=(128, 512), lane_buckets=(1, 2, 4), fuse=True,
            n_windows=16)
        grid = {(w, l) for w in eng.runner.widths
                for l in eng.runner.lane_buckets}
        assert eng.runner.lane_shapes == grid
        assert reg.counter(
            "anomod_serve_fused_compile_total").value == len(grid)
        assert rep.fused_dispatches > 0
        # fused-path telemetry rides along: lanes histogram + pad gauges
        assert reg.counter(
            "anomod_serve_fused_dispatches_total").value \
            == rep.fused_dispatches
        assert reg.histogram("anomod_serve_fused_lanes").count \
            == rep.fused_dispatches
        assert 0.0 <= reg.gauge(
            "anomod_serve_lane_pad_waste_fraction").value < 1.0
    finally:
        set_registry(prev)


def test_fused_engine_smoke():
    """Tier-1 fused smoke (<5s): a small fused run serves, sheds, fuses
    dispatches and still detects the scripted fault."""
    traffic = PowerLawTraffic(
        n_tenants=6, total_rate_spans_per_s=1200, alpha=0.0, seed=3,
        n_services=4, batch_cap=128,
        faults={1: TenantFault("latency", service=1, onset_s=30.0,
                               factor=12.0)})
    cfg = ReplayConfig(n_services=4, n_windows=16, window_us=5_000_000,
                       chunk_size=1024)
    eng = ServeEngine(traffic.specs, traffic.services, cfg,
                      capacity_spans_per_s=900, tick_s=1.0,
                      buckets=(256,), lane_buckets=(1, 2, 4, 8),
                      max_backlog=2000, baseline_windows=4, fuse=True)
    rep = eng.run(traffic, duration_s=60.0)
    assert rep.fused is True
    assert rep.served_spans > 0 and rep.shed_spans > 0
    assert rep.fused_dispatches > 0
    assert rep.lanes_by_bucket and 0.0 <= rep.lane_pad_waste < 1.0
    assert rep.fault_detection["n_detected"] == 1
    d = rep.to_dict()
    import json
    json.dumps(d)
    assert d["lane_buckets"] == [1, 2, 4, 8]
    assert set(d["lanes_by_bucket"]) <= {"1", "2", "4", "8"}


def test_credit_clamp_bounds_float_drift():
    """The per-tick credit float is clamped to its physical envelope
    (one tick's budget of carry either way, plus at most one batch's
    overdraw), so accumulated sub-span rounding on a fractional tick
    budget can never drift into phantom capacity or phantom debt."""
    traffic = PowerLawTraffic(n_tenants=2, total_rate_spans_per_s=100,
                              seed=0, n_services=4)
    cfg = ReplayConfig(n_services=4, n_windows=16, window_us=5_000_000,
                       chunk_size=512)
    eng = ServeEngine(traffic.specs, traffic.services, cfg,
                      capacity_spans_per_s=333.3, tick_s=0.3,
                      buckets=(256,), score=False)
    budget = 333.3 * 0.3
    # phantom capacity: a corrupted/drifted positive credit is pulled
    # back to at most one tick's budget
    eng._credit = 1e9
    eng.tick([])
    assert eng._credit <= budget + 1e-9
    # phantom debt: a drifted negative credit floors at one budget
    eng._credit = -1e9
    eng.tick([])
    assert eng._credit >= -budget - 1e-9
    # steady state with a non-representable tick budget stays bounded
    # and dust-free forever
    for k in range(300):
        eng.tick(traffic.arrivals(k * 0.3, (k + 1) * 0.3))
        assert -max(budget, 512) - 1e-9 <= eng._credit <= budget + 1e-9
        assert eng._credit == 0.0 or abs(eng._credit) >= 1e-9


def test_credit_clamp_does_not_forgive_multi_budget_overdraw():
    """A batch wider than several tick budgets legitimately overdraws;
    its debt is paid down across idle ticks and the clamp must NOT
    forgive it mid-repayment (the floor remembers the widest served
    batch, review finding)."""
    specs = [TenantSpec(0, "t", priority=1)]
    cfg = ReplayConfig(n_services=1, n_windows=8, window_us=5_000_000,
                       chunk_size=512)
    eng = ServeEngine(specs, ("s",), cfg, capacity_spans_per_s=100.0,
                      tick_s=1.0, buckets=(512,), score=False,
                      max_backlog=1000, max_tenant_backlog=1000)
    served = eng.tick([(0, _spans(350))])      # overdraw: 100 - 350
    assert [qb.n_spans for qb in served] == [350]
    assert eng._credit == pytest.approx(-250.0)
    eng.tick([])                               # repaying: -250 + 100
    assert eng._credit == pytest.approx(-150.0)   # NOT clamped to -100
    eng.tick([])
    assert eng._credit == pytest.approx(-50.0)
    eng.tick([])                               # debt paid; positive again
    assert eng._credit == pytest.approx(50.0)


def test_lane_env_knobs_registered_and_validated(monkeypatch):
    from anomod.config import Config
    monkeypatch.setenv("ANOMOD_SERVE_LANE_BUCKETS", "1, 4,16")
    monkeypatch.setenv("ANOMOD_SERVE_FUSE", "0")
    cfg = Config()
    assert cfg.serve_lane_buckets == (1, 4, 16)
    assert cfg.serve_fuse is False
    monkeypatch.setenv("ANOMOD_SERVE_FUSE", "1")
    assert Config().serve_fuse is True

    monkeypatch.setenv("ANOMOD_SERVE_LANE_BUCKETS", "16,4")
    with pytest.raises(ValueError, match="ANOMOD_SERVE_LANE_BUCKETS"):
        Config()
    monkeypatch.setenv("ANOMOD_SERVE_LANE_BUCKETS", "0,4")
    with pytest.raises(ValueError, match="ANOMOD_SERVE_LANE_BUCKETS"):
        Config()
    monkeypatch.setenv("ANOMOD_SERVE_LANE_BUCKETS", "x")
    with pytest.raises(ValueError, match="ANOMOD_SERVE_LANE_BUCKETS"):
        Config()
    monkeypatch.delenv("ANOMOD_SERVE_LANE_BUCKETS")
    from anomod.config import DEFAULT_SERVE_LANE_BUCKETS
    assert Config().serve_lane_buckets == DEFAULT_SERVE_LANE_BUCKETS
    # the env-contract gate sees both knobs as Config-covered
    import sys as _sys
    from pathlib import Path as _Path
    _sys.path.insert(0, str(_Path(__file__).parent.parent / "scripts"))
    try:
        import check_env_contract as cec
        refs = cec.referenced_vars(_Path(cec.ROOT))
        corpus = cec.covered_vars(_Path(cec.ROOT))
        for knob in ("ANOMOD_SERVE_LANE_BUCKETS", "ANOMOD_SERVE_FUSE"):
            assert knob in refs and knob in corpus
    finally:
        _sys.path.pop(0)


# ---------------------------------------------------------------------------
# tenant-sharded scale-out + pipelined dispatch (the PR-5 pins)
# ---------------------------------------------------------------------------

def _report_decision_fields(rep):
    """Everything in the report that must be shard-count/pipeline-depth
    invariant (the exclusion list is engine.py's ONE definition, shared
    with the pre-bench fan-out smoke)."""
    from anomod.serve.engine import SHARD_VARIANT_REPORT_FIELDS
    return {k: v for k, v in rep.to_dict().items()
            if k not in SHARD_VARIANT_REPORT_FIELDS}


def test_shard_plan_deterministic_balanced_and_covering():
    from anomod.serve.shard import plan_shards, rendezvous_shard
    tr = PowerLawTraffic(n_tenants=200, total_rate_spans_per_s=50_000,
                         alpha=1.2, seed=0, n_services=12)
    for n in (2, 4, 8):
        plan = plan_shards(tr.specs, n)
        assert set(plan) == {s.tenant_id for s in tr.specs}   # covering
        assert set(plan.values()) <= set(range(n))
        assert plan == plan_shards(tr.specs, n)               # stable
        # the load-balance pass spreads the Zipf head: offered-rate
        # share per shard within 15% of perfect — except that a single
        # tenant is indivisible, so the unavoidable floor is the head
        # tenant's own rate (at 8 shards the ~26% head exceeds the
        # 12.5% perfect share; the optimum parks it alone)
        loads = [0.0] * n
        for s in tr.specs:
            loads[plan[s.tenant_id]] += s.rate_spans_per_s
        head = max(s.rate_spans_per_s for s in tr.specs)
        assert max(loads) <= max(1.15 * sum(loads) / n, head * 1.001)
        # ...and an irreducible head shard must not stop the REST of
        # the fleet from leveling
        rest = sorted(loads)[:-1]
        if rest:
            assert max(rest) <= \
                1.15 * max(sum(rest) / len(rest), head)
    assert plan_shards(tr.specs, 1) == {s.tenant_id: 0 for s in tr.specs}
    # rendezvous base is pure and process-stable
    assert rendezvous_shard(17, 4) == rendezvous_shard(17, 4)
    with pytest.raises(ValueError):
        plan_shards(tr.specs, 0)


def test_served_rate_model_under_overload():
    """The balance weights under overload follow the WFQ share model:
    demand-limited tenants keep their offer, the rest split by weight;
    the total matches capacity."""
    from anomod.serve.shard import served_rate_model
    specs = [TenantSpec(0, "gold", priority=0, rate_spans_per_s=100.0),
             TenantSpec(1, "bronze", priority=2, rate_spans_per_s=1000.0),
             TenantSpec(2, "silver", priority=1, rate_spans_per_s=10.0)]
    served = served_rate_model(specs, capacity_spans_per_s=500.0)
    assert sum(served.values()) == pytest.approx(500.0, rel=1e-3)
    # gold and silver offer less than their weighted fair share: both
    # are demand-limited and keep their whole offer; bronze (the only
    # backlogged tenant) gets exactly the remainder
    assert served[0] == pytest.approx(100.0)
    assert served[2] == pytest.approx(10.0)
    assert served[1] == pytest.approx(390.0, rel=1e-3)
    # two backlogged tenants split the remainder by weight (4:1)
    specs2 = [TenantSpec(0, "g", priority=0, rate_spans_per_s=1000.0),
              TenantSpec(1, "b", priority=2, rate_spans_per_s=1000.0)]
    served2 = served_rate_model(specs2, capacity_spans_per_s=500.0)
    assert served2[0] / served2[1] == pytest.approx(4.0, rel=1e-2)
    # ample capacity: the offered rates verbatim
    ample = served_rate_model(specs, capacity_spans_per_s=5000.0)
    assert ample == {0: 100.0, 1: 1000.0, 2: 10.0}


@pytest.mark.parametrize("seed", [3, 11])
def test_sharded_engine_identical_to_single_shard(seed):
    """THE scale-out parity pin: an N-shard engine (worker threads,
    pipelined dispatch) emits per-tenant states, alert streams, SLO
    quantiles and admission/shed decisions IDENTICAL to the 1-shard
    synchronous engine on the same seed — with coalescing and
    pipelining genuinely exercised."""
    def go(shards, pipeline):
        return run_power_law(
            n_tenants=10, n_services=4, capacity_spans_per_s=1500,
            overload=2.0, duration_s=40, tick_s=0.5, seed=seed,
            window_s=5.0, baseline_windows=4, fault_tenants=1,
            buckets=(64, 128, 512), lane_buckets=(1, 2, 4),
            max_backlog=3000, n_windows=16, shards=shards,
            pipeline=pipeline)

    e1, r1 = go(1, 1)                     # the synchronous baseline
    base = _report_decision_fields(r1)
    assert r1.shed_spans > 0              # overload regime is real
    for shards, pipeline in ((1, 2), (2, 2), (4, 3)):
        en, rn = go(shards, pipeline)
        assert _report_decision_fields(rn) == base, \
            f"report diverged at shards={shards}"
        assert rn.shards == shards and rn.pipeline == pipeline
        for tid in e1._tenant_det:
            assert [dataclasses.asdict(a) for a in e1.alerts_for(tid)] \
                == [dataclasses.asdict(a) for a in en.alerts_for(tid)]
            s1 = e1._tenant_replay[tid].state
            s2 = en._tenant_replay[tid].state
            np.testing.assert_array_equal(np.asarray(s1.agg),
                                          np.asarray(s2.agg))
            np.testing.assert_array_equal(np.asarray(s1.hist),
                                          np.asarray(s2.hist))
        if shards > 1:
            # occupancy fields: every shard got tenants, spans add up
            assert sum(rn.shard_tenants.values()) == 10
            assert sum(rn.shard_spans.values()) == rn.served_spans
            assert rn.shard_imbalance >= 1.0
    # pipelining was actually exercised: a depth-2 run kept dispatches
    # in flight (the runner drained them at tick end)
    en, rn = go(2, 2)
    assert all(r.pipeline == 2 for r in en._runners)
    assert rn.fused_dispatches > 0


def test_submit_lanes_pipelined_bit_identical_to_run_lanes():
    """The pipelined submit/drain path (deferred readback, per-slot
    scratch) folds the exact bits of the synchronous run_lanes path, at
    several depths, including multi-round (multi-chunk) tenants whose
    deltas are in flight simultaneously."""
    cfg = ReplayConfig(n_services=6, n_windows=8, window_us=5_000_000,
                       chunk_size=512)

    def fresh_replays(runner, n):
        out = []
        for i in range(n):
            r = BucketedStreamReplay(cfg, 0, runner)
            out.append(r)
        return out

    batches = [_rand_spans(80 + 97 * i, 6, seed=100 + i) for i in range(5)]
    # synchronous reference
    ref_runner = BucketRunner(cfg, (128, 512), lane_buckets=(1, 2, 4))
    ref_runner.warm()
    refs = fresh_replays(ref_runner, 5)
    for r, b in zip(refs, batches):
        r.push(b)
    for depth in (2, 3):
        runner = BucketRunner(cfg, (128, 512), lane_buckets=(1, 2, 4),
                              pipeline=depth)
        runner.warm()
        runner.warm_lanes()
        replays = fresh_replays(runner, 5)
        plans = [r.plan_push(b) for r, b in zip(replays, batches)]
        rnd = 0
        while True:
            groups = {}
            for i, (_, plan) in enumerate(plans):
                if rnd < len(plan):
                    groups.setdefault(plan[rnd][0], []).append(i)
            if not groups:
                break
            for width in sorted(groups):
                runner.submit_lanes(width,
                                    [(replays[i], plans[i][1][rnd][1])
                                     for i in groups[width]])
            rnd += 1
        assert runner.inflight_dispatches <= depth - 1
        runner.drain_lanes()
        assert runner.inflight_dispatches == 0
        for ref, got in zip(refs, replays):
            np.testing.assert_array_equal(np.asarray(ref.state.agg),
                                          np.asarray(got.state.agg))
            np.testing.assert_array_equal(np.asarray(ref.state.hist),
                                          np.asarray(got.state.hist))


def test_abort_lanes_discards_inflight_without_folding():
    """Failed-tick cleanup: aborting in-flight dispatches materializes
    them (scratch stays safe to refill) but folds NOTHING — the paired
    replays keep their pre-submit states, and a later drain/run_lanes
    cannot absorb the aborted work."""
    cfg = ReplayConfig(n_services=4, n_windows=8, window_us=5_000_000,
                       chunk_size=256)
    runner = BucketRunner(cfg, (64, 256), lane_buckets=(1, 2),
                          pipeline=3)
    runner.warm()
    runner.warm_lanes()
    replays = [BucketedStreamReplay(cfg, 0, runner) for _ in range(2)]
    plans = [r.plan_push(_rand_spans(60 + i, 4, seed=40 + i))
             for i, r in enumerate(replays)]
    before = [np.asarray(r.state.agg).copy() for r in replays]
    runner.submit_lanes(64, [(r, p[1][0][1])
                             for r, p in zip(replays, plans)])
    assert runner.inflight_dispatches == 1
    runner.abort_lanes()
    assert runner.inflight_dispatches == 0
    for r, b in zip(replays, before):
        np.testing.assert_array_equal(np.asarray(r.state.agg), b)
    # the runner keeps serving after an abort: a fresh push folds
    replays[0].push(_rand_spans(50, 4, seed=99))
    assert replays[0].n_spans > 0


def test_per_shard_compile_count_pin():
    """Exactly one compile per (width, lane-bucket) per SHARD: each
    shard runner owns its executables and compiles its grid once; the
    per-shard registries fold the compile counters into the process
    registry, so the fleet total is shards x grid."""
    from anomod.obs.registry import Registry, set_registry
    reg = Registry(enabled=True)
    prev = set_registry(reg)
    try:
        eng, rep = run_power_law(
            n_tenants=10, n_services=4, capacity_spans_per_s=1500,
            overload=1.5, duration_s=40, tick_s=0.5, seed=6,
            window_s=5.0, baseline_windows=4, fault_tenants=0,
            buckets=(128, 512), lane_buckets=(1, 2, 4), fuse=True,
            n_windows=16, shards=2, pipeline=2)
        grid = {(w, l) for w in eng.runner.widths
                for l in eng.runner.lane_buckets}
        for r in eng._runners:
            assert r.lane_shapes == grid          # full grid, per shard
        assert reg.counter(
            "anomod_serve_fused_compile_total").value == 2 * len(grid)
        assert rep.fused_dispatches > 0
        # shard-labeled gauge twins landed in the process registry
        assert reg.gauge("anomod_serve_lane_pad_waste_fraction",
                         shard="0").value >= 0.0
        # run-end histogram fold (merge_digest seam): lane counts from
        # both shards are in the process histogram
        assert reg.histogram("anomod_serve_fused_lanes").count == \
            rep.fused_dispatches
    finally:
        set_registry(prev)


def test_sharded_unfused_and_scoreless_paths():
    """The escape hatches compose: shards>1 with fuse=0 (per-batch
    pushes on the worker) and score=False (replay-plane only) both
    reproduce the 1-shard output."""
    def go(shards, fuse, score):
        return run_power_law(
            n_tenants=6, n_services=4, capacity_spans_per_s=1000,
            overload=1.5, duration_s=20, tick_s=1.0, seed=2,
            window_s=5.0, baseline_windows=4, fault_tenants=0,
            buckets=(128, 512), max_backlog=2000, n_windows=16,
            shards=shards, fuse=fuse, score=score)
    for fuse, score in ((False, True), (True, False)):
        e1, r1 = go(1, fuse, score)
        e2, r2 = go(2, fuse, score)
        assert _report_decision_fields(r1) == _report_decision_fields(r2)
        for tid, rep1 in e1._tenant_replay.items():
            rep2 = e2._tenant_replay[tid]
            np.testing.assert_array_equal(np.asarray(rep1.state.agg),
                                          np.asarray(rep2.state.agg))


def test_mesh_refuses_shards():
    from anomod.parallel import make_mesh
    traffic = PowerLawTraffic(n_tenants=2, total_rate_spans_per_s=100,
                              seed=0, n_services=4)
    cfg = ReplayConfig(n_services=4, n_windows=16, window_us=5_000_000,
                       chunk_size=512)
    with pytest.raises(ValueError, match="mesh"):
        ServeEngine(traffic.specs, traffic.services, cfg,
                    mesh=make_mesh(2), shards=2)


def test_shard_worker_propagates_errors():
    from anomod.serve.shard import ShardWorker
    w = ShardWorker(0)
    try:
        def boom():
            raise RuntimeError("shard exploded")
        w.submit(boom)
        with pytest.raises(RuntimeError, match="shard exploded"):
            w.join()
        # the worker survives and keeps serving
        hit = []
        w.submit(lambda: hit.append(1))
        w.join()
        assert hit == [1]
    finally:
        w.close()
    assert not w.alive


def test_shard_env_knobs_registered_and_validated(monkeypatch):
    from anomod.config import Config
    monkeypatch.setenv("ANOMOD_SERVE_SHARDS", "4")
    monkeypatch.setenv("ANOMOD_SERVE_PIPELINE", "3")
    monkeypatch.setenv("ANOMOD_JIT_CACHE", "1")
    cfg = Config()
    assert cfg.serve_shards == 4
    assert cfg.serve_pipeline == 3
    assert cfg.jit_cache is True

    for var, bad in (("ANOMOD_SERVE_SHARDS", "0"),
                     ("ANOMOD_SERVE_SHARDS", "many"),
                     ("ANOMOD_SERVE_SHARDS", "999"),
                     ("ANOMOD_SERVE_PIPELINE", "0"),
                     ("ANOMOD_SERVE_PIPELINE", "deep")):
        monkeypatch.setenv(var, bad)
        with pytest.raises(ValueError, match=var):
            Config()
        monkeypatch.delenv(var)
    monkeypatch.setenv("ANOMOD_JIT_CACHE", "off")
    assert Config().jit_cache is False
    monkeypatch.delenv("ANOMOD_JIT_CACHE")
    cfg = Config()
    assert cfg.serve_shards == 1          # default: the escape hatch
    assert cfg.serve_pipeline == 2
    assert cfg.jit_cache is False
    # the env-contract gate sees all three knobs as Config-covered
    import sys as _sys
    from pathlib import Path as _Path
    _sys.path.insert(0, str(_Path(__file__).parent.parent / "scripts"))
    try:
        import check_env_contract as cec
        refs = cec.referenced_vars(_Path(cec.ROOT))
        corpus = cec.covered_vars(_Path(cec.ROOT))
        for knob in ("ANOMOD_SERVE_SHARDS", "ANOMOD_SERVE_PIPELINE",
                     "ANOMOD_JIT_CACHE"):
            assert knob in refs and knob in corpus
    finally:
        _sys.path.pop(0)


# ---------------------------------------------------------------------------
# the state seams sharding leans on (get_state/set_state, raw staging)
# ---------------------------------------------------------------------------

def test_state_seam_roundtrip_under_interleaved_shard_order():
    """StreamReplay.get_state/set_state round-trips: externally folding
    each tenant's staged chunks through the seam — in ANY cross-tenant
    interleaving — reproduces push() bit-exactly per tenant (per-tenant
    chunk order is the only ordering that matters)."""
    cfg = ReplayConfig(n_services=4, n_windows=8, window_us=5_000_000,
                       chunk_size=256)
    runner = BucketRunner(cfg, (64, 256), lane_buckets=(1, 2))
    runner.warm()
    batches = {t: _rand_spans(300 + 50 * t, 4, seed=t) for t in range(3)}

    ref = {}
    for t, b in batches.items():
        r = BucketedStreamReplay(cfg, 0, runner)
        r.push(b)
        ref[t] = r.state

    # two different shard-style interleavings of the same per-tenant
    # chunk streams (round-robin and reversed-tenant order)
    for order in ("round_robin", "reversed"):
        replays = {t: BucketedStreamReplay(cfg, 0, runner)
                   for t in batches}
        plans = {t: replays[t].plan_push(b)[1]
                 for t, b in batches.items()}
        queue = []
        max_rounds = max(len(p) for p in plans.values())
        tenant_order = sorted(batches) if order == "round_robin" \
            else sorted(batches, reverse=True)
        for rnd in range(max_rounds):
            for t in tenant_order:
                if rnd < len(plans[t]):
                    queue.append((t, plans[t][rnd]))
        for t, (width, cols) in queue:
            st = replays[t].get_state()
            replays[t].set_state(runner.dispatch(st, cols, width))
        for t in batches:
            np.testing.assert_array_equal(np.asarray(ref[t].agg),
                                          np.asarray(replays[t].state.agg))
            np.testing.assert_array_equal(
                np.asarray(ref[t].hist), np.asarray(replays[t].state.hist))


def test_stage_columns_raw_roundtrip_matches_padded_staging():
    """stage_columns_raw + the scratch-fill pad (dead-chunk fill values)
    reproduces stage_columns' padded chunks byte-for-byte — the staging
    seam the shard runners' pinned scratch relies on."""
    from anomod.replay import dead_chunk, stage_columns, stage_columns_raw
    cfg = ReplayConfig(n_services=4, n_windows=8, window_us=5_000_000,
                       chunk_size=256)
    batch = _rand_spans(500, 4, seed=9)
    padded, n = stage_columns(batch, cfg, t0_us=0)
    raw = stage_columns_raw(batch, cfg, t0_us=0)
    assert n == batch.n_spans
    dead = dead_chunk(cfg, cfg.chunk_size, xp=np)
    for k, v in raw.items():
        flat = padded[k].reshape(-1)
        np.testing.assert_array_equal(flat[:n], v)        # live rows
        fill = cfg.sw if k == "sid" else 0
        assert (flat[n:] == fill).all()                   # pad rows
        assert (np.asarray(dead[k]) == fill).all()        # one fill def
        assert flat.dtype == v.dtype


def test_serve_cli_emits_report(capsys):
    from anomod.cli import main
    rc = main(["serve", "--tenants", "4", "--services", "4",
               "--duration", "20", "--capacity", "400",
               "--overload", "2.0", "--buckets", "128,512",
               "--max-backlog", "800", "--fault-tenants", "0",
               "--no-score", "--seed", "1"])
    assert rc == 0
    import json
    out = json.loads(capsys.readouterr().out)
    assert out["n_tenants"] == 4
    assert out["offered_spans"] > 0
    assert out["buckets"] == [128, 512]
    assert 0.0 <= out["shed_fraction"] <= 1.0


# ---------------------------------------------------------------------------
# GIL-free native staging + the serve-tick wall decomposition (ISSUE-7)
# ---------------------------------------------------------------------------

def _small_serve_kw(seed=5):
    return dict(n_tenants=6, n_services=4, capacity_spans_per_s=1000,
                overload=2.0, duration_s=20, tick_s=1.0, seed=seed,
                window_s=5.0, baseline_windows=4, fault_tenants=1,
                buckets=(64, 256), lane_buckets=(1, 2, 4),
                max_backlog=1500, n_windows=16)


def _engine_fingerprint(eng):
    return {
        tid: ([dataclasses.asdict(a) for a in eng.alerts_for(tid)],
              np.asarray(eng._tenant_replay[tid].state.agg).tobytes(),
              np.asarray(eng._tenant_replay[tid].state.hist).tobytes())
        for tid in sorted(set(eng._tenant_det) | set(eng._tenant_replay))}


from anomod.io import native as _native_io


@pytest.mark.skipif(not _native_io.available(),
                    reason="native lib not built")
def test_native_staging_engine_byte_identical_to_python():
    """THE native-staging parity pin, end to end: a seeded overloaded
    fused run with the C++ GIL-free scratch packing emits per-tenant
    alerts and replay states byte-identical to the interpreter fill on
    the same seed — and the report says which path staged."""
    from anomod.serve.engine import run_power_law
    e_nat, r_nat = run_power_law(native=True, **_small_serve_kw())
    e_py, r_py = run_power_law(native=False, **_small_serve_kw())
    assert r_nat.native_staging is True and r_py.native_staging is False
    assert r_nat.native_staged_dispatches > 0
    assert r_py.native_staged_dispatches == 0
    assert _engine_fingerprint(e_nat) == _engine_fingerprint(e_py)
    # admission/SLO are staging-invariant by construction
    assert r_nat.shed_fraction == r_py.shed_fraction
    assert r_nat.latency == r_py.latency


def test_scratch_ring_refill_hazard_regression_depths_1_to_3():
    """The refill-under-dispatch hazard regression at every supported
    small pipeline depth: depths 1 (synchronous), 2 (double-buffered)
    and 3 must produce byte-identical states and alerts — a slot
    refilled under a dispatch that can still read it would corrupt the
    fold at depth >= 2 only, which is exactly what this pins against
    the depth-1 oracle (native staging wherever available)."""
    from anomod.serve.engine import run_power_law
    prints = []
    for depth in (1, 2, 3):
        eng, rep = run_power_law(pipeline=depth, **_small_serve_kw(seed=7))
        assert rep.pipeline == depth
        prints.append(_engine_fingerprint(eng))
    assert prints[0] == prints[1] == prints[2]


def test_serve_report_carries_wall_decomposition():
    """The staging decomposition the bench block reads: stage/dispatch/
    fold walls accounted per runner, summing to less than the serve
    wall (the rest is admission/detector bookkeeping)."""
    from anomod.serve.engine import run_power_law
    _, rep = run_power_law(**_small_serve_kw())
    assert rep.stage_wall_s > 0
    assert rep.dispatch_wall_s > 0
    assert rep.fold_wall_s > 0
    assert rep.stage_wall_s + rep.dispatch_wall_s + rep.fold_wall_s \
        + rep.score_wall_s <= rep.serve_wall_s + 1e-6
    # decomposition fields are wall measurements: excluded from the
    # shard-determinism comparison by the ONE shared list
    from anomod.serve.engine import SHARD_VARIANT_REPORT_FIELDS
    for f in ("stage_wall_s", "dispatch_wall_s", "fold_wall_s",
              "score_wall_s", "native_staged_dispatches"):
        assert f in SHARD_VARIANT_REPORT_FIELDS


def test_lane_engine_knob_registered_and_validated(monkeypatch):
    """ANOMOD_SERVE_LANE_ENGINE joins the validated Config env contract:
    auto/matmul/scatter/pallas parse, anything else fails loudly.  The
    hands-off default FOLLOWS the step engine (bit-parity backend-stable
    — on this CPU box both resolve to scatter); pallas is an explicit
    opt-in that routes the runner's fused surface to the Mosaic kernel;
    and an explicit ``engine=`` still pins BOTH surfaces to one
    formulation regardless of the knob (the parity tests rely on that).
    """
    from anomod.config import Config, set_config
    from anomod.replay import default_lane_engine, default_step_engine
    assert Config().serve_lane_engine == "auto"
    monkeypatch.setenv("ANOMOD_SERVE_LANE_ENGINE", "pallas")
    assert Config().serve_lane_engine == "pallas"
    monkeypatch.setenv("ANOMOD_SERVE_LANE_ENGINE", "banana")
    with pytest.raises(ValueError, match="ANOMOD_SERVE_LANE_ENGINE"):
        Config()

    cfg = ReplayConfig(n_services=4, n_windows=8, window_us=5_000_000,
                       chunk_size=256)
    try:
        monkeypatch.delenv("ANOMOD_SERVE_LANE_ENGINE")
        set_config(Config())
        assert default_lane_engine() == default_step_engine()
        runner = BucketRunner(cfg, (64, 256), lane_buckets=(1, 2))
        assert runner.lane_engine == runner.engine
        monkeypatch.setenv("ANOMOD_SERVE_LANE_ENGINE", "pallas")
        set_config(Config())
        assert default_lane_engine() == "pallas"
        runner = BucketRunner(cfg, (64, 256), lane_buckets=(1, 2))
        assert runner.lane_engine == "pallas"
        # an explicit engine= pins both surfaces, knob notwithstanding
        runner = BucketRunner(cfg, (64, 256), lane_buckets=(1, 2),
                              engine="scatter")
        assert runner.engine == runner.lane_engine == "scatter"
    finally:
        monkeypatch.delenv("ANOMOD_SERVE_LANE_ENGINE", raising=False)
        set_config(Config())


def test_native_knob_registered_and_validated(monkeypatch):
    """ANOMOD_NATIVE joins the validated Config env contract: auto/on/off
    (with 1/0 aliases) parse, anything else fails loudly; off forces the
    interpreter fill even when the .so is fine; on REFUSES to construct
    a runner when the runtime is unusable, quoting the build reason."""
    from anomod.config import Config
    from anomod.io import native as native_io
    assert Config().native == "auto"
    monkeypatch.setenv("ANOMOD_NATIVE", "1")
    assert Config().native == "on"
    monkeypatch.setenv("ANOMOD_NATIVE", "off")
    assert Config().native == "off"
    monkeypatch.setenv("ANOMOD_NATIVE", "banana")
    with pytest.raises(ValueError, match="ANOMOD_NATIVE"):
        Config()

    cfg = ReplayConfig(n_services=4, n_windows=8, window_us=5_000_000,
                       chunk_size=256)
    monkeypatch.setenv("ANOMOD_NATIVE", "off")
    from anomod.config import set_config
    try:
        set_config(Config())
        runner = BucketRunner(cfg, (64, 256), lane_buckets=(1, 2))
        assert runner.native_stage is False
        # =on with an unusable runtime: fail loud with the reason, never
        # silently serve the slow path
        monkeypatch.setenv("ANOMOD_NATIVE", "on")
        set_config(Config())
        monkeypatch.setattr(native_io, "available", lambda: False)
        with pytest.raises(RuntimeError, match="ANOMOD_NATIVE"):
            BucketRunner(cfg, (64, 256), lane_buckets=(1, 2))
    finally:
        monkeypatch.delenv("ANOMOD_NATIVE")
        set_config(Config())
