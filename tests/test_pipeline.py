"""Pipeline-parallel (pp) and expert-parallel (ep) planes on the CPU mesh."""

import numpy as np
import pytest

from anomod.parallel.pipeline import (PipelineConfig, init_pipeline,
                                      make_pipe_mesh, make_pipeline_forward,
                                      make_pipeline_train_step)


@pytest.fixture(scope="module")
def mesh4():
    return make_pipe_mesh(4)


def _rand_inputs(rng, B, S, W, F):
    x = rng.normal(size=(B, S, W, F)).astype(np.float32)
    adj = rng.integers(0, 3, size=(B, S, S)).astype(np.float32)
    return x, adj


def test_pipeline_forward_matches_sequential(mesh4):
    import jax
    cfg = PipelineConfig(n_microbatches=2, layers_per_stage=2,
                         d_model=16, n_heads=2, mlp_hidden=32)
    S, W, F = 6, 4, 5
    params = init_pipeline(jax.random.PRNGKey(0), mesh4, cfg, S, W, F)
    forward, reference = make_pipeline_forward(mesh4, cfg, S, W)
    x, adj = _rand_inputs(np.random.default_rng(0), 4, S, W, F)
    got = np.asarray(jax.jit(forward)(params, x, adj))
    want = np.asarray(jax.jit(reference)(params, x, adj))
    assert got.shape == (4, S)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_pipeline_grads_match_sequential(mesh4):
    import jax
    import jax.numpy as jnp
    cfg = PipelineConfig(n_microbatches=2, layers_per_stage=1,
                         d_model=16, n_heads=2, mlp_hidden=32)
    S, W, F = 5, 4, 3
    params = init_pipeline(jax.random.PRNGKey(1), mesh4, cfg, S, W, F)
    forward, reference = make_pipeline_forward(mesh4, cfg, S, W)
    x, adj = _rand_inputs(np.random.default_rng(1), 2, S, W, F)

    def make_loss(f):
        return lambda p: (f(p, jnp.asarray(x), jnp.asarray(adj)) ** 2).sum()

    g_pipe = jax.jit(jax.grad(make_loss(forward)))(params)
    g_ref = jax.jit(jax.grad(make_loss(reference)))(params)
    flat_p, _ = jax.tree_util.tree_flatten(g_pipe)
    flat_r, _ = jax.tree_util.tree_flatten(g_ref)
    assert flat_p and len(flat_p) == len(flat_r)
    for a, b in zip(flat_p, flat_r):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=1e-4)
    assert any(float(np.abs(np.asarray(l)).max()) > 0 for l in flat_p)


def test_pipeline_train_step_learns(mesh4):
    from anomod.rca import _stack, build_dataset
    samples, _ = build_dataset("SN", seeds=[0], n_traces=12, n_windows=4)
    stacked = _stack(samples[:12])          # 12 = 6 microbatches of 2
    cfg = PipelineConfig(n_microbatches=6, layers_per_stage=1,
                         d_model=16, n_heads=2, mlp_hidden=32)
    params, opt_state, step, put_batch = make_pipeline_train_step(
        mesh4, cfg, stacked)
    batch = put_batch(stacked)
    losses = []
    for _ in range(8):
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_moe_expert_parallel_train_step():
    """ep plane: expert kernels sharded over the model axis of a 2-D mesh."""
    import jax
    from jax.sharding import PartitionSpec as P
    from anomod.parallel.train import (make_distributed_train_step,
                                       make_mesh2d)
    from anomod.rca import _stack, build_dataset

    mesh = make_mesh2d(8, model_axis=2)     # (data=4, model=2)
    samples, _ = build_dataset("SN", seeds=[0], n_traces=12, n_windows=4)
    stacked = _stack((samples * 2)[:16])    # dp axis 4 | 16
    params, opt_state, step, put_batch = make_distributed_train_step(
        "moe", stacked, mesh)
    # expert kernels [E, d, h] must actually be sharded over the model axis
    leaves = jax.tree_util.tree_leaves(params)
    expert = [l for l in leaves if l.ndim == 3]
    assert expert, "MoE params should include 3-D expert kernels"
    assert any(l.sharding.spec == P("model", None, None) for l in expert)
    batch = put_batch(stacked)
    params, opt_state, loss = step(params, opt_state, batch)
    assert np.isfinite(float(loss))
