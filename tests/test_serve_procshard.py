"""Process-shard serving: the GIL escape (ANOMOD_SERVE_WORKER=process,
ISSUE-20).

The central pin: with the knob ON, each shard's WHOLE scoring plane —
detectors, replay states, its BucketRunner, its metrics registry —
lives in a spawn-context worker process behind the same ShardWorker
seam, driven by a picklable per-tick command protocol, and every
decision plane (tenant states, alert streams, SLO, shed, the canonical
flight journal) is BYTE-identical to the thread engine of the same
seed — and to the same run on ONE process.  The thread engine stays
the parity oracle (``ANOMOD_SERVE_WORKER=thread``, the default).

The second pin is the tick barrier itself: cross-shard registry merges
serialize as SPARSE touched-key deltas (``ANOMOD_SERVE_FOLD=sparse``)
or dense full walks, combined in fixed (shard, seq) order — scrape
output byte-identical either way, with the sparse payload bounded at
half the dense walk's bytes on the module scenario.  State digests
cross the pipe as per-tenant ``(crc, len)`` fragments folded through
``crc32_combine`` — pinned bit-equal to the sequential walk here.

Tier-1 covers the parity core, worker-crash respawn through
supervision, elastic scaling across process workers, the knob/refusal
matrix and the env contract; wall-clock scaling claims live in
bench.py (gated on a >= 4-core box), never here.
"""

import dataclasses
import zlib

import numpy as np
import pytest

from anomod.obs.flight import (crc32_combine, diff_journals,
                               fold_digest_parts, state_digest,
                               state_digest_parts)
from anomod.obs.registry import Registry, delta_nbytes, set_registry
from anomod.serve.engine import (SHARD_VARIANT_REPORT_FIELDS, ServeEngine,
                                 run_power_law)

#: the compact seeded scenario (the supervise-module idiom): 20 virtual
#: ticks, alerts firing mid-run, so every canonical plane is LIVE when
#: it crosses the process boundary
KW = dict(n_tenants=6, n_services=4, capacity_spans_per_s=1000,
          overload=2.0, duration_s=20, tick_s=1.0, seed=5,
          window_s=2.0, baseline_windows=4, fault_tenants=1,
          buckets=(64, 256), lane_buckets=(1, 2, 4), max_backlog=1500,
          n_windows=16, flight_digest_every=4)

#: report fields that legitimately differ between a fault-free
#: unsupervised run and a supervised recovered one (the supervise
#: module's inventory plus the supervision config bits themselves)
RECOVERY_REPORT_FIELDS = ("supervised", "ckpt_every", "n_checkpoints",
                          "n_shard_crashes", "n_respawns",
                          "n_restored_ticks", "n_quarantined",
                          "n_migrated_tenants")

#: the policy-module inventory: executed decision counts + the mode
POLICY_REPORT_FIELDS = ("policy", "n_scale_ups", "n_scale_downs",
                        "n_rebalances", "n_policy_migrations",
                        "brownout_ticks", "n_checkpoints")


def _run(**kw):
    """One engine run under its OWN enabled registry (the bench-leg
    idiom): the barrier folds need somewhere to land, and the module's
    runs must not cross-pollinate one shared registry."""
    prev = set_registry(Registry(enabled=True))
    try:
        return run_power_law(**kw)
    finally:
        set_registry(prev)


@pytest.fixture(scope="module")
def thread_ref():
    """ONE thread-engine 2-shard pipelined reference run — the parity
    oracle every process leg in this module compares against."""
    eng, rep = _run(shards=2, pipeline=2, worker="thread",
                    fold="sparse", **KW)
    return eng, rep, eng.flight_recorder.journal()


@pytest.fixture(scope="module")
def proc_run():
    eng, rep = _run(shards=2, pipeline=2, worker="process",
                    fold="sparse", **KW)
    return eng, rep


@pytest.fixture(scope="module")
def proc_one():
    eng, rep = _run(shards=1, worker="process", fold="sparse", **KW)
    return eng, rep


@pytest.fixture(scope="module")
def proc_dense():
    eng, rep = _run(shards=2, pipeline=2, worker="process",
                    fold="dense", **KW)
    return eng, rep


def assert_proc_parity(reference, eng, rep, extra_skip=()):
    """Identical alert streams (read through the coordinator mirrors —
    a process engine's replay planes live in its children), identical
    report decision fields, equal canonical flight journals.  Tenant
    STATE bytes are pinned by the journal's state digests (digest
    cadence 4 over 20 ticks), computed where the states live."""
    ref_eng, ref_rep, ref_journal = reference
    tids = sorted(ref_eng._tenant_det)
    assert tids == sorted(eng._tenant_det)
    for tid in tids:
        assert [dataclasses.asdict(a) for a in ref_eng.alerts_for(tid)] \
            == [dataclasses.asdict(a) for a in eng.alerts_for(tid)], \
            f"tenant {tid} alert stream diverges"
    skip = set(SHARD_VARIANT_REPORT_FIELDS) | set(extra_skip)
    a = {k: v for k, v in ref_rep.to_dict().items() if k not in skip}
    b = {k: v for k, v in rep.to_dict().items() if k not in skip}
    assert a == b, sorted(k for k in a if a[k] != b[k])
    d = diff_journals(ref_journal, eng.flight_recorder.journal())
    assert d is None, d


# ---------------------------------------------------------------------------
# the parity core
# ---------------------------------------------------------------------------

def test_process_byte_parity(thread_ref, proc_run):
    """The headline pin: N shard processes are byte-identical to N
    shard threads on every decision plane — and actually ran as
    processes (the report names the resolved engine)."""
    eng, rep = proc_run
    assert rep.worker == "process" and thread_ref[1].worker == "thread"
    assert rep.fold == "sparse"
    assert rep.n_alerts > 0          # parity would be vacuous silent
    assert_proc_parity(thread_ref, eng, rep)


def test_two_vs_one_process_parity(proc_run, proc_one):
    """Decomposition honesty: 2 processes vs 1 process of the same
    seed — byte-identical decisions, so process-count changes move
    only wall-clock."""
    eng2, rep2 = proc_run
    eng1, rep1 = proc_one
    assert rep1.worker == "process"
    assert_proc_parity((eng2, rep2,
                        eng2.flight_recorder.journal()), eng1, rep1)


def test_audit_diff_thread_vs_process_journals(tmp_path, thread_ref,
                                               proc_run):
    """The forensic surface: dumped thread and process journals are
    equal under the `anomod audit diff` CLI itself (exit 0)."""
    from anomod.cli import main
    a = str(tmp_path / "thread.json")
    b = str(tmp_path / "proc.json")
    thread_ref[0].flight_recorder.dump(a)
    proc_run[0].flight_recorder.dump(b)
    assert main(["audit", "diff", a, b]) == 0


def test_flight_header_records_resolved_worker_and_fold(proc_run,
                                                        thread_ref):
    """The flight header records the RESOLVED knobs (the async-commit
    precedent), so `anomod audit replay` re-executes the run dict
    as-is on the same engine shape."""
    run = proc_run[0].flight_recorder.header["run"]
    assert run["worker"] == "process" and run["fold"] == "sparse"
    assert thread_ref[0].flight_recorder.header["run"]["worker"] \
        == "thread"


def test_process_rerun_deterministic(proc_run):
    """Same seed, same knob ⇒ same canonical journal bytes."""
    eng, _ = proc_run
    rerun, _ = _run(shards=2, pipeline=2, worker="process",
                    fold="sparse", **KW)
    assert rerun.flight_recorder.canonical_bytes() \
        == eng.flight_recorder.canonical_bytes()


# ---------------------------------------------------------------------------
# the sparse tick-barrier fold
# ---------------------------------------------------------------------------

def test_sparse_fold_payload_under_half_dense(proc_run, proc_dense):
    """The barrier-payload acceptance bound: the sparse fold ships at
    most half the dense walk's structural bytes on this scenario, and
    the two runs' canonical journals are equal (the fold discipline
    moves payload, never a scored byte)."""
    _, rep_sparse = proc_run
    eng_dense, rep_dense = proc_dense
    assert rep_dense.worker == "process" and rep_dense.fold == "dense"
    assert rep_sparse.fold_payload_bytes > 0
    assert rep_dense.fold_payload_bytes > 0
    assert rep_sparse.fold_payload_bytes \
        <= 0.5 * rep_dense.fold_payload_bytes
    d = diff_journals(proc_run[0].flight_recorder.journal(),
                      eng_dense.flight_recorder.journal())
    assert d is None, d


def test_sparse_and_dense_deltas_apply_identically():
    """The registry-level pin behind the scrape-parity contract: the
    same source registry history folded sparse and folded dense lands
    the destination registries on identical metric samples — dense
    just ships more bytes to say it."""

    def _mk_src():
        src = Registry(enabled=True)
        src.counter("c_total", shard="0").inc(3.0)
        src.counter("c_once_total").inc(2.5)       # touched tick 0 only
        src.gauge("g_frac", lane="1").set(0.25)    # ditto
        src.histogram("h_seconds").observe(0.5)
        return src

    def _fold(src, mode):
        dst, st = Registry(enabled=True), {}
        # tick 0: everything dirty
        dst.apply_delta(src.delta_snapshot(st, mode=mode), shard="0")
        # tick 1: only c_total moves — sparse must skip the rest
        src.counter("c_total", shard="0").inc(4.0)
        dst.apply_delta(src.delta_snapshot(st, mode=mode), shard="0")
        # run end: final drains the histograms
        dst.apply_delta(src.delta_snapshot(st, mode=mode, final=True),
                        shard="0")
        return dst

    def _samples(reg):
        return sorted((m.name, m.rendered, tuple(sorted(m.samples())))
                      for m in reg.metrics())

    assert _samples(_fold(_mk_src(), "sparse")) \
        == _samples(_fold(_mk_src(), "dense"))
    # and the sparse tick-1 delta is strictly smaller: the untouched
    # once-families are skipped entirely
    src_s, src_d, st_s, st_d = _mk_src(), _mk_src(), {}, {}
    src_s.delta_snapshot(st_s, mode="sparse")
    src_d.delta_snapshot(st_d, mode="dense")
    src_s.counter("c_total", shard="0").inc(1.0)
    src_d.counter("c_total", shard="0").inc(1.0)
    sparse_1 = src_s.delta_snapshot(st_s, mode="sparse")
    dense_1 = src_d.delta_snapshot(st_d, mode="dense")
    assert delta_nbytes(sparse_1) < delta_nbytes(dense_1)
    with pytest.raises(ValueError, match="dense|sparse"):
        _mk_src().delta_snapshot({}, mode="csr")


# ---------------------------------------------------------------------------
# digest fragments across the pipe
# ---------------------------------------------------------------------------

def test_crc32_combine_matches_zlib():
    """The pure-Python crc32_combine is bit-equal to crc32 over the
    concatenation — the identity the fragment fold rests on."""
    rng = np.random.default_rng(11)
    for n_a, n_b in ((0, 1), (1, 0), (7, 13), (256, 1024), (4096, 3)):
        a = rng.integers(0, 256, n_a, dtype=np.uint8).tobytes()
        b = rng.integers(0, 256, n_b, dtype=np.uint8).tobytes()
        assert crc32_combine(zlib.crc32(a), zlib.crc32(b), len(b)) \
            == zlib.crc32(a + b)


def test_fold_digest_parts_matches_sequential_walk(thread_ref):
    """Per-tenant (crc, len) fragments — computed per shard, folded in
    global sorted-tenant order — land on state_digest's sequential
    walk bit-for-bit, including a non-zero running prefix."""
    replays = thread_ref[0]._tenant_replay
    assert len(replays) >= 4
    parts = state_digest_parts(replays)
    assert fold_digest_parts(parts) == state_digest(replays)
    # shard-split the fleet arbitrarily: the fold is split-invariant
    tids = sorted(replays)
    shard_a = {t: replays[t] for t in tids[::2]}
    shard_b = {t: replays[t] for t in tids[1::2]}
    mixed = state_digest_parts(shard_a) + state_digest_parts(shard_b)
    assert fold_digest_parts(mixed, prev=0xDEAD) \
        == state_digest(replays, prev=0xDEAD)


# ---------------------------------------------------------------------------
# supervision + elasticity across the process boundary
# ---------------------------------------------------------------------------

def test_worker_crash_respawns_with_no_score_gap(thread_ref):
    """A worker-process KILL mid-run, under supervision: the
    coordinator respawns a FRESH (empty) child, restores it from the
    checkpoint through the snapshot seams, re-executes the logged
    slices — and the run stays byte-identical to the fault-free
    thread run of the same seed."""
    eng, rep = _run(shards=2, pipeline=2, worker="process",
                    fold="sparse", ckpt_every=4,
                    chaos="crash@6:shard=1:phase=fold:repeat=1", **KW)
    assert rep.worker == "process"
    assert rep.n_shard_crashes >= 1
    assert rep.n_respawns >= 1
    assert rep.n_restored_ticks >= 1
    assert_proc_parity(thread_ref, eng, rep,
                       extra_skip=RECOVERY_REPORT_FIELDS)


def test_policy_scales_across_process_workers():
    """The elastic policy migrates tenants ACROSS process boundaries
    (snapshot out of one child, install into another): a full
    up→down episode under a scripted surge, byte-identical to the
    static THREAD run of the same seed+surge."""
    pkw = dict(n_tenants=6, n_services=4, capacity_spans_per_s=1000,
               overload=0.6, duration_s=24, tick_s=1.0, seed=5,
               window_s=5.0, baseline_windows=4, fault_tenants=0,
               buckets=(64, 256), lane_buckets=(1, 2, 4),
               max_backlog=1500, n_windows=16, flight_digest_every=4)
    surge = "surge@6:factor=6:ticks=6"
    eng_s, rep_s = _run(shards=1, chaos=surge, worker="thread", **pkw)
    eng_e, rep_e = _run(shards=1, chaos=surge, worker="process",
                        policy="auto", min_shards=1, max_shards=2,
                        cooldown_ticks=3, **pkw)
    assert rep_e.worker == "process"
    assert rep_e.n_scale_ups >= 1 and rep_e.n_scale_downs >= 1
    assert rep_e.n_policy_migrations >= 1
    assert_proc_parity((eng_s, rep_s,
                        eng_s.flight_recorder.journal()),
                       eng_e, rep_e,
                       extra_skip=set(POLICY_REPORT_FIELDS)
                       | set(RECOVERY_REPORT_FIELDS))


# ---------------------------------------------------------------------------
# the knob / refusal matrix
# ---------------------------------------------------------------------------

def _mk_engine(**kw):
    from anomod.replay import ReplayConfig
    from anomod.serve import PowerLawTraffic
    traffic = PowerLawTraffic(n_tenants=2, total_rate_spans_per_s=100,
                              seed=0, n_services=4)
    cfg = ReplayConfig(n_services=4, n_windows=16, window_us=5_000_000,
                       chunk_size=512)
    return ServeEngine(traffic.specs, traffic.services, cfg, **kw)


def test_worker_and_fold_knobs_validated():
    with pytest.raises(ValueError, match="thread|process"):
        _mk_engine(worker="greenlet")
    with pytest.raises(ValueError, match="dense|sparse"):
        _mk_engine(fold="csr")


def test_env_knobs_validated(monkeypatch):
    from anomod.config import Config, set_config
    monkeypatch.setenv("ANOMOD_SERVE_WORKER", "goroutine")
    with pytest.raises(ValueError, match="ANOMOD_SERVE_WORKER"):
        Config()
    monkeypatch.delenv("ANOMOD_SERVE_WORKER")
    monkeypatch.setenv("ANOMOD_SERVE_FOLD", "blocked")
    with pytest.raises(ValueError, match="ANOMOD_SERVE_FOLD"):
        Config()
    monkeypatch.delenv("ANOMOD_SERVE_FOLD")
    monkeypatch.setenv("ANOMOD_SERVE_WORKER_START_TIMEOUT_S", "0")
    with pytest.raises(ValueError,
                       match="ANOMOD_SERVE_WORKER_START_TIMEOUT_S"):
        Config()
    monkeypatch.delenv("ANOMOD_SERVE_WORKER_START_TIMEOUT_S")
    set_config(Config())


@pytest.mark.parametrize("blocker_kw", [
    dict(async_commit=True),
    dict(tier_hot=8),
    dict(perf=True),
    dict(census=True),
])
def test_process_refused_with_in_process_planes(blocker_kw):
    """Planes that share coordinator memory with the score plane
    cannot cross the process boundary: an EXPLICIT worker='process'
    alongside one is a hard error (the shards-on-mesh idiom)."""
    with pytest.raises(ValueError, match="process shard workers"):
        _mk_engine(worker="process", **blocker_kw)


def test_mesh_refuses_explicit_process_worker():
    from anomod.parallel import make_mesh
    with pytest.raises(ValueError, match="mesh"):
        _mk_engine(worker="process", mesh=make_mesh(2))


def test_env_sourced_process_degrades_not_raises(monkeypatch):
    """An env-sourced ANOMOD_SERVE_WORKER=process degrades to the
    thread engine under a blocking plane, so globally exported knobs
    never break existing workflows — the policy/state idiom."""
    from anomod.config import Config, set_config
    monkeypatch.setenv("ANOMOD_SERVE_WORKER", "process")
    set_config(Config())
    try:
        eng = _mk_engine(perf=True)
        assert eng.worker_mode == "thread"
    finally:
        monkeypatch.delenv("ANOMOD_SERVE_WORKER")
        set_config(Config())


# ---------------------------------------------------------------------------
# the tier-1 smoke
# ---------------------------------------------------------------------------

def test_procshard_smoke_fast():
    """A minimal process-worker run spawns, serves, folds and joins —
    the cheap canary a broken spawn path fails in seconds, not at the
    module fixtures."""
    eng, rep = _run(n_tenants=2, n_services=4, capacity_spans_per_s=500,
                    overload=1.0, duration_s=4, tick_s=1.0, seed=3,
                    window_s=2.0, baseline_windows=2, fault_tenants=0,
                    buckets=(64,), lane_buckets=(1,), max_backlog=800,
                    n_windows=16, shards=1, worker="process")
    assert rep.worker == "process"
    assert rep.served_spans > 0
    # run end closed and reaped every child
    assert not (eng._workers or [])

