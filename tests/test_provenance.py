"""Capture-provenance record contract (anomod/provenance.py) — the
machinery the round-3 evidence protocol rides on."""

import json

from anomod import provenance


def test_capture_record_is_self_describing():
    rec = provenance.capture_record("m", 1.5, "u", kernel="pallas",
                                    device="TPU v5 lite0")
    assert rec["metric"] == "m" and rec["value"] == 1.5 and rec["unit"] == "u"
    assert rec["kernel"] == "pallas"
    # environment stamps present
    assert rec["jax_version"]
    assert rec["timestamp_utc"].endswith("Z")
    # repo is a git checkout, so a sha must be resolvable
    assert len(rec["git_sha"].split("-")[0]) == 40


def test_write_capture_filename_and_collisions(tmp_path):
    rec = provenance.capture_record("tt_replay_throughput", 2.0, "u",
                                    device="TPU v5 lite0")
    paths = [provenance.write_capture(rec, outdir=str(tmp_path))
             for _ in range(3)]
    assert all(p is not None for p in paths)
    assert len(set(paths)) == 3          # same-second captures never clobber
    assert all("_tpu" in p for p in paths)
    # device-class suffix distinguishes a CPU fallback from an on-chip run
    cpu = provenance.write_capture(
        provenance.capture_record("x", 1.0, "u", device="TFRT_CPU_0"),
        outdir=str(tmp_path))
    assert cpu.endswith("_cpu.json")
    loaded = json.loads(open(paths[0]).read())
    assert loaded["value"] == 2.0


def test_write_capture_never_raises(tmp_path):
    target = tmp_path / "not_a_dir"
    target.write_text("file blocks mkdir")
    rec = provenance.capture_record("m", 1.0, "u")
    assert provenance.write_capture(rec, outdir=str(target / "sub")) is None


def test_git_sha_dirty_only_for_tracked_changes(tmp_path):
    # untracked files (like the capture being written) must NOT dirty the
    # sha — only modified tracked files make the measured tree
    # unreproducible.  Use a scratch repo so the test doesn't depend on
    # this checkout's state.
    import subprocess
    r = tmp_path / "repo"
    r.mkdir()
    subprocess.run(["git", "init", "-q"], cwd=r, check=True)
    (r / "a.txt").write_text("x")
    subprocess.run(["git", "add", "a.txt"], cwd=r, check=True)
    subprocess.run(["git", "-c", "user.email=t@t", "-c", "user.name=t",
                    "commit", "-qm", "c"], cwd=r, check=True)
    clean = provenance.git_sha(cwd=str(r))
    assert clean and not clean.endswith("-dirty")
    (r / "untracked.json").write_text("{}")
    assert provenance.git_sha(cwd=str(r)) == clean
    (r / "a.txt").write_text("changed")
    assert provenance.git_sha(cwd=str(r)).endswith("-dirty")
