"""Real 2-process jax.distributed exercise of the multi-host mesh path
(anomod/parallel/multihost.py): two coordinator-connected CPU processes,
4 virtual devices each, hybrid (dcn=2, data=4) mesh, psum + HLL
register-merge collectives across the process boundary."""

import json
import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest

_WORKER = Path(__file__).with_name("multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


#: the jaxlib limitation fingerprint: CPU collectives backends that cannot
#: run cross-process computations raise exactly this (the capability is a
#: jaxlib build property, not a bug in the mesh path under test — real
#: multi-host runs go over TPU/GPU backends that do implement it)
_CPU_BACKEND_LIMITATION = (
    "Multiprocess computations aren't implemented on the CPU backend")


def test_two_process_hybrid_mesh_collectives():
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PYTHONPATH"] = os.pathsep.join(
        [str(_WORKER.parent.parent)]
        + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else []))
    procs = [subprocess.Popen(
        [sys.executable, str(_WORKER), str(pid), "2", str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)
        for pid in range(2)]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=240)
            if p.returncode != 0 and _CPU_BACKEND_LIMITATION in (out + err):
                pytest.skip(
                    "this jaxlib's CPU backend cannot run multiprocess "
                    f"computations ({_CPU_BACKEND_LIMITATION!r}); the "
                    "2-process mesh path needs a collectives-capable "
                    "backend (TPU/GPU, or a jaxlib with CPU gloo/mpi "
                    "collectives)")
            assert p.returncode == 0, f"worker failed:\n{out}\n{err}"
            outs.append(out)
    finally:
        # a failed/timed-out worker must not leave its peer blocked on the
        # dead coordinator
        for q in procs:
            if q.poll() is None:
                q.kill()

    results = []
    for out in outs:
        lines = [l for l in out.splitlines() if l.startswith("MHRESULT ")]
        assert lines, f"no MHRESULT line in: {out}"
        results.append(json.loads(lines[0][len("MHRESULT "):]))

    import math

    for r in results:
        assert r["process_count"] == 2
        assert r["global_devices"] == 8
        # psum across the process boundary reduced every shard
        assert r["psum"] == r["expected_psum"] == 28.0
        # merged HLL sees all 8 disjoint ranges (~2% p=10 error)
        assert r["hll_estimate"] == pytest.approx(r["true_distinct"],
                                                  rel=0.05)
        # the dp train step across the process boundary produced a real
        # finite loss and updated params
        assert math.isfinite(r["train_loss"]) and r["train_loss"] > 0
        assert math.isfinite(r["param_sum"])
    # replicated results are identical on both hosts: the collectives AND
    # the post-update model state (same gradients => same params)
    assert results[0]["psum"] == results[1]["psum"]
    assert results[0]["hll_estimate"] == results[1]["hll_estimate"]
    assert results[0]["train_loss"] == results[1]["train_loss"]
    assert results[0]["param_sum"] == results[1]["param_sum"]
