"""GNN models + RCA harness: shapes, gradients, and a fast end-to-end train."""

import numpy as np
import pytest

from anomod.rca import build_dataset, make_model, train_rca


@pytest.fixture(scope="module")
def sn_data():
    samples, services = build_dataset("SN", seeds=[0], n_traces=40)
    return samples, services


def test_build_dataset_shapes(sn_data):
    samples, services = sn_data
    assert len(samples) == 13
    S = len(services)
    for s in samples:
        assert s.x.shape[0] == S
        assert s.x_t.shape[0] == S
        assert s.adj.shape == (S, S)
        assert s.edge_src.shape == s.edge_dst.shape == s.edge_mask.shape
    # anomalous samples with service targets carry valid indices
    tgts = [s.target for s in samples if s.target >= 0]
    assert len(tgts) >= 9
    assert all(0 <= t < S for t in tgts)


@pytest.mark.parametrize("name", ["gcn", "gat", "sage", "temporal", "lru",
                                  "transformer", "moe"])
def test_model_forward_and_grad(name, sn_data):
    import jax
    import jax.numpy as jnp
    samples, services = sn_data
    s = samples[1]
    model = make_model(name)
    rng = jax.random.PRNGKey(0)
    if name == "gcn":
        args = (jnp.asarray(s.x), jnp.asarray(s.adj, jnp.float32))
    elif name in ("temporal", "lru", "transformer", "moe"):
        W = s.x_t.shape[1]
        fused = np.concatenate(
            [s.x_t, np.repeat(s.x[:, None, :], W, axis=1)], axis=-1)
        args = (jnp.asarray(fused), jnp.asarray(s.adj, jnp.float32))
    else:
        args = (jnp.asarray(s.x), jnp.asarray(s.edge_src),
                jnp.asarray(s.edge_dst), jnp.asarray(s.edge_mask))
    params = model.init(rng, *args)
    scores = model.apply(params, *args)
    assert scores.shape == (len(services),)
    assert np.isfinite(np.asarray(scores)).all()

    def loss(p):
        return (model.apply(p, *args) ** 2).sum()

    grads = jax.grad(loss)(params)
    leaves = jax.tree_util.tree_leaves(grads)
    assert leaves and all(np.isfinite(np.asarray(l)).all() for l in leaves)
    assert any(float(np.abs(np.asarray(l)).max()) > 0 for l in leaves)


def test_train_rca_end_to_end_fast():
    r = train_rca("SN", "gcn", train_seeds=range(4), eval_seeds=[50],
                  epochs=250, n_traces=40)
    # GCN must localize culprits on held-out seeds (numpy baseline gets 1.0)
    assert r.top1 >= 0.7, (r.top1, r.top3)
    assert r.detection_auc >= 0.8


def test_edge_feature_block_opt_in():
    """edge_features doubles the windowed block with out-edge aggregates:
    a link fault that is invisible in the target's NODE features lands in
    its OUT-EDGE error-rate block (the evidence channel the edge-aware
    training variant learns from)."""
    import numpy as np
    from anomod import labels, synth
    from anomod.rca import _windowed_features
    from anomod.replay import ReplayConfig

    lab = labels.label_for("Lv_C_travel_detail_failure")
    services = tuple(synth.TT_SERVICES)
    cfg = ReplayConfig(n_services=len(services), n_windows=8,
                       chunk_size=2048, window_us=300_000_000)
    hard = synth.HardMode(severity=1.0, fault_locus="edge")
    spans = synth.generate_spans(lab, n_traces=120, seed=3, hard=hard)
    f4 = _windowed_features(spans, services, cfg)
    f8 = _windowed_features(spans, services, cfg, edge_features=True)
    assert f4.shape[-1] == 4 and f8.shape[-1] == 8
    assert np.array_equal(f8[..., :4], f4)      # node block unchanged
    ti = services.index(lab.target_service)
    # fault window (middle third of 8 coarse windows); at full severity
    # the culprit's out-edge error rate carries the direct fault signal
    # (its node error rate also rises, but only via parent-ward error
    # propagation — the same blast every ancestor sees)
    node_err = f8[ti, 3:6, 1].max()
    edge_err = f8[ti, 3:6, 5].max()
    assert edge_err > 0.3 and edge_err > 1.5 * max(node_err, 0.02)
    # spans with no parent info at all -> zero edge block, same shape
    orphans = spans._replace(parent=np.full(spans.n_spans, -1, np.int32))
    fz = _windowed_features(orphans, services, cfg, edge_features=True)
    assert fz.shape == f8.shape and not fz[..., 4:].any()
