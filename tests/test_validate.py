"""Data-quality validation + trace dedup."""

import numpy as np

from anomod import synth, labels
from anomod.schemas import concat_span_batches, Experiment
from anomod.validate import dedup_traces, validate_experiment


def test_validate_clean_experiment():
    exp = synth.generate_experiment("Lv_P_CPU_preserve", n_traces=30)
    rep = validate_experiment(exp)
    assert rep.ok, [i.message for i in rep.issues if i.severity == "error"]
    assert rep.counts["spans"] > 0
    assert rep.counts["metric_samples"] > 0
    d = rep.to_dict()
    assert d["experiment"] == "Lv_P_CPU_preserve"


def test_validate_missing_modalities():
    exp = Experiment(name="Normal_case", testbed="TT")
    rep = validate_experiment(exp)
    assert not rep.ok
    mods = {i.modality for i in rep.issues if i.severity == "error"}
    assert "traces" in mods and "metrics" in mods


def test_dedup_traces_removes_repeats():
    b = synth.generate_spans(labels.label_for("Normal_case"), n_traces=20)
    doubled = concat_span_batches([b, b])
    # concat re-interns trace ids so both copies share ids -> true duplicates
    dd = dedup_traces(doubled)
    assert dd.n_spans == b.n_spans
    # parent links stay consistent
    nz = dd.parent >= 0
    assert (dd.trace[nz] == dd.trace[dd.parent[nz]]).all()


def test_dedup_noop_on_clean_batch():
    b = synth.generate_spans(labels.label_for("Normal_case"), n_traces=20)
    dd = dedup_traces(b)
    assert dd.n_spans == b.n_spans


def test_parent_resolution_rate_reported_and_warned():
    """The report carries the parent-resolution rate (the edge planes'
    prerequisite) and warns when the parentSpanId join mostly failed."""
    import numpy as np
    from anomod import labels, synth
    from anomod.validate import validate_experiment

    exp = synth.generate_experiment(labels.label_for("Normal_case"),
                                    n_traces=40)
    rep = validate_experiment(exp)
    rate = rep.counts["parent_resolution_rate"]
    assert 0.5 < rate < 1.0                # roots exist, joins resolve
    assert not any("resolved parent" in i.message for i in rep.issues)
    import dataclasses
    broken = dataclasses.replace(exp, spans=exp.spans._replace(
        parent=np.full(exp.spans.n_spans, -1, np.int32)))
    rep2 = validate_experiment(broken)
    assert rep2.counts["parent_resolution_rate"] == 0.0
    assert any("resolved parent" in i.message for i in rep2.issues)


def test_from_data_with_fresh_cache_dir_reports_zero_counters(tmp_path,
                                                              monkeypatch):
    """Regression (serving-plane PR satellite): `anomod validate
    --from-data` pointed at an EMPTY/fresh ANOMOD_CACHE_DIR must not
    crash — the corpus loads through an all-miss cache and the report
    carries honest zero-hit counters."""
    import dataclasses

    from anomod.config import Config
    from anomod.io import cache as ingest_cache
    from anomod.io import dataset
    from anomod.validate import corpus_summary, validate_experiment

    fresh = tmp_path / "fresh-cache"          # does not even exist yet
    cfg = dataclasses.replace(Config(), cache_dir=fresh,
                              data_root=tmp_path / "no-data-root")
    ingest_cache.reset_stats()
    exp = dataset.load_experiment("Normal_case", cfg=cfg,
                                  modalities=["traces", "logs"],
                                  n_synth_traces=3)
    rep = validate_experiment(exp)
    out = corpus_summary("TT", [rep],
                         cache_stats=ingest_cache.stats().to_dict())
    assert out["ingest_cache"]["hits"] == 0
    assert out["ingest_cache"]["errors"] == 0
    assert out["ingest_cache"]["misses"] > 0
    assert out["reports"][0]["counts"]["spans"] > 0
    # and the fresh dir is now a populated cache: a second load hits
    ingest_cache.reset_stats()
    dataset.load_experiment("Normal_case", cfg=cfg,
                            modalities=["traces", "logs"],
                            n_synth_traces=3)
    assert ingest_cache.stats().hits > 0
