"""Data-quality validation + trace dedup."""

import numpy as np

from anomod import synth, labels
from anomod.schemas import concat_span_batches, Experiment
from anomod.validate import dedup_traces, validate_experiment


def test_validate_clean_experiment():
    exp = synth.generate_experiment("Lv_P_CPU_preserve", n_traces=30)
    rep = validate_experiment(exp)
    assert rep.ok, [i.message for i in rep.issues if i.severity == "error"]
    assert rep.counts["spans"] > 0
    assert rep.counts["metric_samples"] > 0
    d = rep.to_dict()
    assert d["experiment"] == "Lv_P_CPU_preserve"


def test_validate_missing_modalities():
    exp = Experiment(name="Normal_case", testbed="TT")
    rep = validate_experiment(exp)
    assert not rep.ok
    mods = {i.modality for i in rep.issues if i.severity == "error"}
    assert "traces" in mods and "metrics" in mods


def test_dedup_traces_removes_repeats():
    b = synth.generate_spans(labels.label_for("Normal_case"), n_traces=20)
    doubled = concat_span_batches([b, b])
    # concat re-interns trace ids so both copies share ids -> true duplicates
    dd = dedup_traces(doubled)
    assert dd.n_spans == b.n_spans
    # parent links stay consistent
    nz = dd.parent >= 0
    assert (dd.trace[nz] == dd.trace[dd.parent[nz]]).all()


def test_dedup_noop_on_clean_batch():
    b = synth.generate_spans(labels.label_for("Normal_case"), n_traces=20)
    dd = dedup_traces(b)
    assert dd.n_spans == b.n_spans


def test_parent_resolution_rate_reported_and_warned():
    """The report carries the parent-resolution rate (the edge planes'
    prerequisite) and warns when the parentSpanId join mostly failed."""
    import numpy as np
    from anomod import labels, synth
    from anomod.validate import validate_experiment

    exp = synth.generate_experiment(labels.label_for("Normal_case"),
                                    n_traces=40)
    rep = validate_experiment(exp)
    rate = rep.counts["parent_resolution_rate"]
    assert 0.5 < rate < 1.0                # roots exist, joins resolve
    assert not any("resolved parent" in i.message for i in rep.issues)
    import dataclasses
    broken = dataclasses.replace(exp, spans=exp.spans._replace(
        parent=np.full(exp.spans.n_spans, -1, np.int32)))
    rep2 = validate_experiment(broken)
    assert rep2.counts["parent_resolution_rate"] == 0.0
    assert any("resolved parent" in i.message for i in rep2.issues)
