"""Ulysses all-to-all sequence parallelism: exactness vs full attention,
equivalence with ring attention, gradient parity, and layout round-trip."""

import jax
import numpy as np
import pytest
from conftest import make_qkv as _qkv

from anomod.parallel.mesh import make_mesh
from anomod.parallel.ring_attention import full_attention, make_ring_attention
from anomod.parallel.ulysses import make_ulysses_attention


def test_ulysses_matches_full_attention_8dev():
    mesh = make_mesh(8)
    q, k, v = _qkv(64, 8, 16)          # H=8 divides by the 8-device axis
    fn = make_ulysses_attention(mesh)
    np.testing.assert_allclose(np.asarray(fn(q, k, v)),
                               np.asarray(full_attention(q, k, v)),
                               rtol=2e-4, atol=2e-5)


def test_ulysses_matches_ring_attention():
    """The two sequence-parallel planes are drop-in interchangeable."""
    mesh = make_mesh(4, axis="sp")
    q, k, v = _qkv(40, 4, 8, seed=3)
    uly = make_ulysses_attention(mesh, axis="sp")
    ring = make_ring_attention(mesh, axis="sp")
    np.testing.assert_allclose(np.asarray(uly(q, k, v)),
                               np.asarray(ring(q, k, v)),
                               rtol=2e-4, atol=2e-5)


def test_ulysses_single_device_degenerates_to_full():
    mesh = make_mesh(1)
    q, k, v = _qkv(16, 2, 8, seed=5)
    fn = make_ulysses_attention(mesh)
    np.testing.assert_allclose(np.asarray(fn(q, k, v)),
                               np.asarray(full_attention(q, k, v)),
                               rtol=1e-5, atol=1e-6)


def test_ulysses_gradients_match_full_attention():
    mesh = make_mesh(8)
    q, k, v = _qkv(32, 8, 8, seed=7)
    fn = make_ulysses_attention(mesh)

    def loss_sp(args):
        return (fn(*args) ** 2).sum()

    def loss_full(args):
        return (full_attention(*args) ** 2).sum()

    g_sp = jax.grad(loss_sp)((q, k, v))
    g_full = jax.grad(loss_full)((q, k, v))
    for a, b in zip(g_sp, g_full):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)


def test_ulysses_output_sharding_matches_input():
    mesh = make_mesh(8)
    q, k, v = _qkv(64, 8, 16, seed=9)
    out = make_ulysses_attention(mesh)(q, k, v)
    assert out.shape == q.shape
    spec = out.sharding.spec
    assert tuple(spec) [0] == "data"


def test_ulysses_requires_divisible_heads():
    mesh = make_mesh(8)
    q, k, v = _qkv(64, 6, 16)          # 6 heads over 8 devices
    fn = make_ulysses_attention(mesh)
    with pytest.raises(ValueError, match="divisible"):
        fn(q, k, v)
