"""Coverage dump/merge/report pipeline (jacococli analog)."""

import numpy as np
import pytest

from anomod import synth
from anomod.io import coverage_report as cr
from anomod.io.coverage import load_tt_coverage_report, parse_jacoco_xml, \
    parse_summary_txt


def _dump(service="ts-order-service", n=20, covered_idx=(0, 1, 2)):
    mask = np.zeros(n, bool)
    mask[list(covered_idx)] = True
    return cr.CoverageDump(service, {"a/File.java": mask})


def test_merge_is_probe_union():
    a = _dump(covered_idx=(0, 1, 2))
    b = _dump(covered_idx=(2, 3))
    m = cr.merge_dumps([a, b])
    assert m.lines_covered == 4          # {0,1,2,3}
    assert m.lines_total == 20
    # merge of disjoint files unions the file set
    c = cr.CoverageDump("ts-order-service",
                        {"b/Other.java": np.ones(5, bool)})
    m2 = cr.merge_dumps([a, c])
    assert set(m2.files) == {"a/File.java", "b/Other.java"}
    assert m2.lines_covered == 3 + 5
    # length mismatch pads with uncovered
    d = cr.CoverageDump("ts-order-service",
                        {"a/File.java": np.ones(25, bool)})
    m3 = cr.merge_dumps([a, d])
    assert m3.files["a/File.java"].size == 25
    assert m3.lines_covered == 25


def test_merge_rejects_cross_service():
    with pytest.raises(ValueError):
        cr.merge_dumps([_dump("ts-a"), _dump("ts-b")])
    with pytest.raises(ValueError):
        cr.merge_dumps([])


def test_dump_save_load_roundtrip(tmp_path):
    d = _dump(n=77, covered_idx=tuple(range(0, 77, 3)))
    p = tmp_path / "dump.npz"
    cr.save_dump(d, p)
    back = cr.load_dump(p)
    assert back.service == d.service
    assert set(back.files) == set(d.files)
    assert np.array_equal(back.files["a/File.java"], d.files["a/File.java"])


def test_xml_and_summary_roundtrip():
    d = _dump(n=500, covered_idx=tuple(range(215)))
    xml = cr.write_jacoco_xml(d)
    total = cr.parse_total_from_xml(xml)
    assert total == {"covered": 215, "missed": 285}
    # the existing per-sourcefile parser reads the same document
    files = parse_jacoco_xml(xml, "ts-order-service")
    assert files[0].lines_covered == 215 and files[0].lines_total == 500

    txt = cr.write_summary_txt("ts-order-service", 500, 215)
    fc = parse_summary_txt(txt, "ts-order-service")
    assert fc.lines_total == 500 and fc.lines_covered == 215
    assert "Cover  43%" in txt   # the reference example ratio


def test_batch_dump_batch_roundtrip():
    exp = synth.generate_experiment("Lv_C_exception_injection", n_traces=20)
    dumps = cr.batch_to_dumps(exp.coverage)
    back = cr.dumps_to_batch(dumps)
    assert back.lines_total.sum() == exp.coverage.lines_total.sum()
    assert back.lines_covered.sum() == exp.coverage.lines_covered.sum()


def test_collect_coverage_reports_tree(tmp_path):
    exp = synth.generate_experiment("Normal_case", n_traces=10)
    dumps = cr.batch_to_dumps(exp.coverage)
    # two pods per service dump the same coverage → merge is idempotent union
    pods = {f"{d.service}-pod-a": [d] for d in dumps[:5]}
    pods.update({f"{d.service}-pod-b": [d] for d in dumps[:5]})
    totals = cr.collect_coverage_reports(
        pods, tmp_path / "coverage_data", tmp_path / "coverage_report")
    assert len(totals) == 5
    svc = dumps[0].service
    sdir = tmp_path / "coverage_report" / svc
    assert (sdir / "coverage.xml").exists()
    assert (sdir / "coverage-summary.txt").exists()
    assert (sdir / "merged.npz").exists()
    # merged union of identical dumps == the single dump
    assert totals[svc]["lines_covered"] == dumps[0].lines_covered
    # the existing loader reads the produced report tree
    batch = load_tt_coverage_report(tmp_path / "coverage_report")
    assert batch is not None and len(batch.services) == 5
    # exec-analog archives present per pod
    assert len(list((tmp_path / "coverage_data").glob("*.npz"))) == 10
