"""Online RCA in the serve tick (anomod.serve.rca): determinism pins.

The contract the ISSUE-6 subsystem ships under:

- VERDICTS: byte-identical across reruns of the same seed and across
  1-shard vs 2-shard runs (the sampler is seeded by (tenant, alert
  window) alone and evidence is anchored to the triggering alert
  window).
- NON-INTERFERENCE: RCA on vs off leaves detector states, alerts, SLO
  quantiles and shed decisions byte-identical (RCA is a pure read-side
  consumer of the alert stream).
- COMPILE: exactly one XLA compile per (nodes, neighbors) RCA bucket
  over a sustained run, pinned via the registry compile counters.
- ONSET RULE: golden metrics, ``alerts_for`` and RCA hit accounting all
  apply the ONE ``onset_eligible`` rule — an alert exactly AT the onset
  boundary window counts.
"""

import dataclasses

import numpy as np
import pytest

from anomod.serve.engine import (SHARD_VARIANT_REPORT_FIELDS, ServeEngine,
                                 onset_eligible, onset_eligible_alerts,
                                 run_power_law)

#: the fields the RCA plane adds that legitimately differ between an
#: RCA-on and an RCA-off run of the same seed (everything else in the
#: report must be byte-identical between the two)
_RCA_ONLY_FIELDS = ("rca_enabled", "n_rca_runs", "rca_topk_hits",
                    "rca_eligible", "rca_latency",
                    "rca_alert_to_culprit_s", "rca_wall_s")

_RUN_KW = dict(n_tenants=8, n_services=6, capacity_spans_per_s=2000,
               overload=2.0, duration_s=60, tick_s=1.0, seed=3,
               window_s=5.0, baseline_windows=4, fault_tenants=2,
               buckets=(64, 256), lane_buckets=(1, 2, 4),
               max_backlog=3000, n_windows=16)


def _verdict_dicts(engine):
    return [v.to_dict() for v in engine.rca_verdicts]


def test_rca_emits_verdicts_and_hits_injected_culprit():
    """The product smoke: under the seeded overload run with scripted
    latency faults, every fault tenant gets an onset-eligible verdict
    and the injected culprit ranks top-1."""
    from anomod.utils.tracing import Tracer
    tracer = Tracer("anomod-serve")
    eng, rep = run_power_law(shards=1, rca=True, tracer=tracer, **_RUN_KW)
    assert rep.rca_enabled is True
    assert "serve.rca" in {s["operationName"]
                           for s in tracer.to_jaeger()["data"][0]["spans"]}
    assert rep.n_rca_runs == len(eng.rca_verdicts) > 0
    assert rep.rca_eligible == rep.fault_detection["n_fault_tenants"] == 2
    assert rep.rca_topk_hits[1] == 2          # culprit ranks first
    assert rep.rca_topk_hits[3] == rep.rca_topk_hits[5] == 2
    assert rep.rca_latency["p99_s"] is not None
    assert rep.rca_alert_to_culprit_s["p50_s"] is not None
    assert rep.rca_wall_s > 0
    for v in eng.rca_verdicts:
        assert len(v.services) == len(v.scores) <= 5
        assert v.scored_s >= v.enqueued_s
        assert v.bucket[0] >= 6
    d = rep.to_dict()
    import json
    json.dumps(d)
    assert set(d["rca_topk_hits"]) == {"1", "3", "5"}


def test_rca_verdicts_byte_identical_across_reruns():
    eng_a, _ = run_power_law(shards=1, rca=True, **_RUN_KW)
    eng_b, _ = run_power_law(shards=1, rca=True, **_RUN_KW)
    assert _verdict_dicts(eng_a) == _verdict_dicts(eng_b)


def test_rca_verdicts_byte_identical_1_vs_2_shards():
    """RCA runs on the shard that owns the tenant; the barrier fold in
    enqueue order makes the N-shard verdict stream identical to the
    1-shard engine's — and the rest of the report stays pinned too."""
    eng1, rep1 = run_power_law(shards=1, rca=True, **_RUN_KW)
    eng2, rep2 = run_power_law(shards=2, rca=True, **_RUN_KW)
    assert _verdict_dicts(eng1) == _verdict_dicts(eng2)
    skip = set(SHARD_VARIANT_REPORT_FIELDS)
    a = {k: v for k, v in rep1.to_dict().items() if k not in skip}
    b = {k: v for k, v in rep2.to_dict().items() if k not in skip}
    assert a == b, sorted(k for k in a if a[k] != b[k])


def test_rca_on_off_leaves_decisions_byte_identical():
    """RCA is a read-side consumer: detector states, alert streams,
    SLO quantiles, admission and shed are untouched by enabling it."""
    eng_off, rep_off = run_power_law(shards=1, rca=False, **_RUN_KW)
    eng_on, rep_on = run_power_law(shards=1, rca=True, **_RUN_KW)
    assert rep_off.rca_enabled is False and rep_off.n_rca_runs == 0
    for tid in sorted(set(eng_off._tenant_det) | set(eng_on._tenant_det)):
        assert [dataclasses.asdict(a) for a in eng_off.alerts_for(tid)] \
            == [dataclasses.asdict(a) for a in eng_on.alerts_for(tid)]
        s0 = eng_off._tenant_replay[tid].state
        s1 = eng_on._tenant_replay[tid].state
        assert np.array_equal(np.asarray(s0.agg), np.asarray(s1.agg))
        assert np.array_equal(np.asarray(s0.hist), np.asarray(s1.hist))
    skip = set(SHARD_VARIANT_REPORT_FIELDS) | set(_RCA_ONLY_FIELDS)
    a = {k: v for k, v in rep_off.to_dict().items() if k not in skip}
    b = {k: v for k, v in rep_on.to_dict().items() if k not in skip}
    assert a == b, sorted(k for k in a if a[k] != b[k])
    # the headline decision numbers, spelled out
    assert rep_off.shed_fraction == rep_on.shed_fraction
    assert rep_off.latency == rep_on.latency


def test_rca_budget_queues_and_settles_deterministically():
    """A 1-run-per-tick budget defers inference without changing any
    verdict: evidence anchors to the triggering alert window, so the
    delayed stream carries the same rankings with later scored_s."""
    # squeeze the budget via the engine ctor (run_power_law has no
    # budget knob — drive the engine directly)
    from anomod.serve.traffic import PowerLawTraffic, TenantFault
    from anomod.serve.engine import serve_plane_cfg
    onset_s = (4 + 2) * 5.0
    faults = {t: TenantFault("latency", service=1, onset_s=onset_s,
                             factor=10.0) for t in range(2)}
    def go(budget):
        traffic = PowerLawTraffic(
            n_tenants=8, total_rate_spans_per_s=4000, alpha=1.2, seed=3,
            n_services=6, faults=faults)
        eng = ServeEngine(traffic.specs, traffic.services,
                          serve_plane_cfg(6, 5.0, 16),
                          capacity_spans_per_s=2000, tick_s=1.0,
                          buckets=(64, 256), lane_buckets=(1, 2, 4),
                          max_backlog=3000, baseline_windows=4,
                          rca=True, rca_budget=budget)
        return eng, eng.run(traffic, duration_s=60.0)
    wide, rep_wide = go(budget=64)
    tight, rep_tight = go(budget=1)
    strip = lambda vs: [{k: v for k, v in d.items() if k != "scored_s"}
                        for d in vs]
    # the ITEM SET is budget-invariant: alerts firing while earlier
    # items still queue get their OWN item (never absorbed into a stale
    # one), so only scored_s moves — hit accounting included
    assert strip(_verdict_dicts(wide)) == strip(_verdict_dicts(tight))
    assert rep_wide.rca_topk_hits == rep_tight.rca_topk_hits
    assert rep_wide.rca_eligible == rep_tight.rca_eligible
    # the tight budget genuinely deferred at least one run
    assert max(v.scored_s - v.enqueued_s for v in tight.rca_verdicts) \
        >= max(v.scored_s - v.enqueued_s for v in wide.rca_verdicts)


def test_rca_alert_across_traffic_gap_keeps_pregap_evidence():
    """An alert that fires across a tenant traffic gap longer than the
    evidence window must still score its pre-gap evidence.  A faulted
    window left OPEN when the tenant's feed pauses closes at resume —
    anchored at the pre-gap window while the buffer's high-water mark
    jumps past the gap.  This tick's alerts enqueue BEFORE the evidence
    buffer prunes, so the pruning floor covers the new alert's reach
    (regression: the floor was computed from the queue before enqueue,
    and the resume tick's buffering dropped every pre-gap span first —
    the verdict then scored on an empty evidence window, n_spans=0)."""
    from anomod.serve.engine import serve_plane_cfg
    from anomod.serve.traffic import PowerLawTraffic, TenantFault

    gap_lo_s, gap_hi_s = 27.0, 55.0     # 28 s >> (windows+1) * 5 s

    class GapTraffic:
        def __init__(self, inner):
            self.inner = inner

        def arrivals(self, lo, hi):
            return [(tid, b) for tid, b in self.inner.arrivals(lo, hi)
                    if not (tid == 0 and gap_lo_s <= lo < gap_hi_s)]

    faults = {0: TenantFault("latency", service=1, onset_s=25.0,
                             factor=10.0)}
    traffic = GapTraffic(PowerLawTraffic(
        n_tenants=2, total_rate_spans_per_s=800, alpha=0.0, seed=3,
        n_services=6, faults=faults))
    eng = ServeEngine(traffic.inner.specs, traffic.inner.services,
                      serve_plane_cfg(6, 5.0, 16),
                      capacity_spans_per_s=2000, tick_s=1.0,
                      buckets=(64, 256), lane_buckets=(1, 2),
                      max_backlog=5000, baseline_windows=4,
                      rca=True, rca_windows=3)
    eng.run(traffic, duration_s=65.0)
    # the faulted window (5 = [25, 30) s) closed at resume: its alert
    # trails the newest buffered span by the whole gap
    pregap = [v for v in eng.rca_verdicts
              if v.tenant_id == 0 and v.alert_window == 5]
    assert len(pregap) == 1
    assert pregap[0].enqueued_s >= gap_hi_s          # fired at resume
    assert pregap[0].n_spans > 0                     # evidence survived
    assert pregap[0].services[0] == "svc01"          # and localizes


def test_rca_compile_count_pin():
    """Exactly one XLA compile per (nodes, neighbors) RCA bucket over a
    sustained run, via the registry compile counters — and only the
    bucket the service table lands in ever executes."""
    from anomod.obs.registry import Registry, set_registry
    reg = Registry(enabled=True)
    prev = set_registry(reg)
    try:
        eng, rep = run_power_law(shards=1, rca=True, **_RUN_KW)
        runner = eng._rca_planes[0].runner
        assert runner.bucket_shapes == set(runner.buckets)
        assert reg.counter("anomod_serve_rca_compile_total").value \
            == len(runner.buckets)
        assert reg.counter("anomod_serve_rca_runs_total").value \
            == rep.n_rca_runs > 0
        # every run used the one bucket that holds the 6-service table
        assert set(runner.runs_by_bucket) == {runner.bucket_for(6)}
        assert reg.histogram("anomod_serve_rca_seconds").count \
            == rep.n_rca_runs
    finally:
        set_registry(prev)


def test_rca_env_knobs_registered_and_validated(monkeypatch):
    from anomod.config import Config
    monkeypatch.setenv("ANOMOD_SERVE_RCA", "1")
    monkeypatch.setenv("ANOMOD_SERVE_RCA_BUCKETS", "8x4, 32x8")
    monkeypatch.setenv("ANOMOD_SERVE_RCA_TOPK", "3")
    monkeypatch.setenv("ANOMOD_SERVE_RCA_BUDGET", "2")
    monkeypatch.setenv("ANOMOD_SERVE_RCA_WINDOWS", "6")
    cfg = Config()
    assert cfg.serve_rca is True
    assert cfg.serve_rca_buckets == ((8, 4), (32, 8))
    assert cfg.serve_rca_topk == 3
    assert cfg.serve_rca_budget == 2
    assert cfg.serve_rca_windows == 6
    for var, bad in (("ANOMOD_SERVE_RCA_BUCKETS", "32x8,8x4"),
                     ("ANOMOD_SERVE_RCA_BUCKETS", "banana"),
                     ("ANOMOD_SERVE_RCA_BUCKETS", "8x0"),
                     ("ANOMOD_SERVE_RCA_TOPK", "0"),
                     ("ANOMOD_SERVE_RCA_BUDGET", "none"),
                     ("ANOMOD_SERVE_RCA_WINDOWS", "1")):
        monkeypatch.setenv(var, bad)
        with pytest.raises(ValueError, match=var):
            Config()
        monkeypatch.delenv(var)
    assert Config().serve_rca is True     # the enable flag survived
    monkeypatch.delenv("ANOMOD_SERVE_RCA")
    from anomod.config import DEFAULT_SERVE_RCA_BUCKETS
    cfg = Config()
    assert cfg.serve_rca is False
    assert cfg.serve_rca_buckets == DEFAULT_SERVE_RCA_BUCKETS


def test_rca_requires_scoring_and_bucket_capacity():
    from anomod.serve.queues import TenantSpec
    from anomod.replay import ReplayConfig
    specs = [TenantSpec(tenant_id=0, name="t0", priority=0,
                        rate_spans_per_s=10.0)]
    services = tuple(f"s{i}" for i in range(4))
    cfg = ReplayConfig(n_services=4, n_windows=16, window_us=5_000_000,
                       chunk_size=1024)
    with pytest.raises(ValueError, match="score"):
        ServeEngine(specs, services, cfg, score=False, rca=True)
    with pytest.raises(ValueError, match="bucket"):
        ServeEngine(specs, services, cfg, rca=True,
                    rca_buckets=((2, 2),))


# ---------------------------------------------------------------------------
# the ONE onset-eligibility rule (golden metrics / alerts_for / RCA hits)
# ---------------------------------------------------------------------------

def test_onset_boundary_alert_counts_everywhere():
    """An alert exactly AT the onset window is eligible (>=, not >) —
    in the helper, in alerts_for's filter, in the golden fault-detection
    metrics, and in the RCA hit accounting."""
    from anomod.stream import Alert
    assert onset_eligible(7, 7) is True
    assert onset_eligible(6, 7) is False
    mk = lambda w: Alert(window=w, service=1, service_name="svc01",
                         score=5.0, z_latency=5.0, z_error=0.0,
                         z_drop=0.0)
    alerts = [mk(6), mk(7), mk(9)]
    assert [a.window for a in onset_eligible_alerts(alerts, 7)] == [7, 9]

    class _Traffic:
        pass

    from anomod.serve.traffic import TenantFault
    eng, rep = run_power_law(shards=1, rca=True, **_RUN_KW)
    # the scripted fault's onset window for this run
    fault = TenantFault("latency", service=1,
                        onset_s=(4 + 2) * 5.0, factor=10.0)
    onset_w = int(fault.onset_s // 5.0)
    det = eng._tenant_det[0]
    # plant a pre-onset noise alert AND a boundary alert on the culprit
    planted = [mk(onset_w - 1), mk(onset_w)]
    det.alerts[:0] = planted
    try:
        # alerts_for honors the same rule
        got = eng.alerts_for(0, onset_window=onset_w)
        assert planted[0] not in got and planted[1] in got
        tr = _Traffic()
        tr.faults = {0: fault}
        fd = eng._fault_detection(tr)
        # the boundary alert is the detection: latency 0 windows, never
        # the pre-onset one (which would read -1)
        assert fd["n_detected"] == 1
        assert fd["median_alert_latency_windows"] == 0.0
        # RCA hit accounting applies the identical rule to the verdict's
        # triggering alert window
        eng.rca_verdicts = [dataclasses.replace(
            v, alert_window=onset_w - 1) for v in eng.rca_verdicts
            if v.tenant_id == 0][:1]
        hits, eligible = eng._rca_hits(tr)
        assert eligible == 0 and hits == {1: 0, 3: 0, 5: 0}
        eng.rca_verdicts = [dataclasses.replace(
            v, alert_window=onset_w) for v in eng.rca_verdicts]
        hits, eligible = eng._rca_hits(tr)
        assert eligible == 1
    finally:
        del det.alerts[:2]
