"""Black-box flight recorder (anomod.obs.flight) + `anomod audit`.

The acceptance-critical pins: same seed ⇒ BYTE-identical canonical
journals across reruns, 1-vs-2 shards, host-vs-device tenant state and
pipeline depths 1–3; a deliberately-injected divergence bisects to the
correct first tick AND plane through ``audit diff`` (nonzero exit); ring
drops are counted, never silent; the alert-triggered forensic bundle
publishes atomically; and the recorder is a pure read-side consumer
(identical engine decisions with the journal on or off).
"""

import copy
import json
from pathlib import Path

import numpy as np
import pytest

from anomod.obs.flight import (FLIGHT_FORMAT, FLIGHT_VARIANT_KEYS, PLANES,
                               canonical_ticks, diff_journals, load_journal)
from anomod.serve.engine import run_power_law

#: the shared tiny seeded run: small enough for tier-1, long enough past
#: the fault onset (12 virtual s at window 2.0 / baseline 4) that the
#: score AND rca planes carry live digests — a determinism pin over
#: all-zero planes would prove nothing
RUN_KW = dict(n_tenants=6, n_services=4, capacity_spans_per_s=1000,
              overload=2.0, duration_s=24, tick_s=1.0, seed=5,
              window_s=2.0, baseline_windows=4, fault_tenants=1,
              buckets=(64, 256), lane_buckets=(1, 2, 4), max_backlog=1500,
              n_windows=16, flight=True, flight_digest_every=4)


def _run(**overrides):
    kw = {**RUN_KW, **overrides}
    return run_power_law(**kw)


@pytest.fixture(scope="module")
def baseline():
    """One reference run (1 shard, device state, pipeline default,
    RCA on) every variant below diffs against."""
    eng, rep = _run(rca=True)
    return eng, rep


# ---------------------------------------------------------------------------
# journal determinism: the byte-parity surface
# ---------------------------------------------------------------------------

def test_rerun_byte_identical(baseline):
    eng, rep = baseline
    eng2, rep2 = _run(rca=True)
    assert eng.flight_recorder.canonical_bytes() \
        == eng2.flight_recorder.canonical_bytes()
    # the journal covered every tick plus the run-end settlement record
    assert eng.flight_recorder.n_recorded == rep.ticks + 1
    assert rep.flight_enabled and rep.flight_recorded_ticks == rep.ticks + 1
    assert rep.flight_dropped_ticks == 0
    # the planes under pin are LIVE (alerts fired, verdicts ran, state
    # digests landed) — an all-zero journal would vacuously match
    recs = eng.flight_recorder.records()
    assert any(t["score"]["digest"] for t in recs)
    assert any(t["rca"]["digest"] for t in recs)
    assert any(t["fold"]["state_digest"] is not None for t in recs)
    assert recs[-1].get("final") is True
    assert recs[-1]["fold"]["state_digest"] is not None


@pytest.mark.parametrize("overrides", [
    dict(shards=2),
    dict(state="host"),
    dict(pipeline=1),
    dict(pipeline=3),
], ids=["2-shards", "host-state", "pipeline-1", "pipeline-3"])
def test_variant_journals_pinned_identical(baseline, overrides):
    """The determinism contracts as runnable forensics: an N-shard /
    host-seam / any-pipeline-depth run's canonical journal is
    byte-identical to the baseline's — diff finds nothing, and the raw
    canonical bytes match too."""
    eng, _ = baseline
    eng2, _ = _run(rca=True, **overrides)
    assert diff_journals(eng.flight_recorder.journal(),
                         eng2.flight_recorder.journal()) is None
    assert eng.flight_recorder.canonical_bytes() \
        == eng2.flight_recorder.canonical_bytes()


def test_flight_off_is_read_side_only(baseline):
    """The recorder must never influence a decision: the same seed with
    flight OFF produces identical alerts, states and report decisions."""
    import dataclasses

    from anomod.serve.engine import SHARD_VARIANT_REPORT_FIELDS
    eng, rep = baseline
    eng2, rep2 = _run(rca=True, flight=False)
    assert eng2.flight_recorder is None and rep2.flight_enabled is False
    for tid in eng._tenant_det:
        assert [dataclasses.asdict(a) for a in eng.alerts_for(tid)] \
            == [dataclasses.asdict(a) for a in eng2.alerts_for(tid)]
        s1, s2 = eng._tenant_replay[tid].state, eng2._tenant_replay[tid].state
        assert np.array_equal(np.asarray(s1.agg), np.asarray(s2.agg))
        assert np.array_equal(np.asarray(s1.hist), np.asarray(s2.hist))
    skip = set(SHARD_VARIANT_REPORT_FIELDS) | {
        "flight_enabled", "flight_recorded_ticks", "flight_dropped_ticks"}
    a = {k: v for k, v in rep.to_dict().items() if k not in skip}
    b = {k: v for k, v in rep2.to_dict().items() if k not in skip}
    assert a == b


# ---------------------------------------------------------------------------
# divergence bisection
# ---------------------------------------------------------------------------

def test_injected_divergence_bisects_to_tick_and_plane(baseline):
    eng, _ = baseline
    a = eng.flight_recorder.journal()
    # one tampered plane at one tick → exactly that (tick, plane)
    for plane, key in (("admission", "digest"), ("score", "digest"),
                       ("rca", "digest"), ("dispatch", "chunks")):
        b = copy.deepcopy(a)
        b["ticks"][15][plane][key] = (b["ticks"][15][plane][key] or 0) + 1
        d = diff_journals(a, b)
        assert d is not None
        assert (d["tick"], d["plane"]) == (15, plane), d
    # fold tampering must land on a DIGEST tick to be visible — pick one
    b = copy.deepcopy(a)
    digest_ticks = [i for i, t in enumerate(b["ticks"])
                    if t["fold"]["state_digest"] is not None]
    b["ticks"][digest_ticks[1]]["fold"]["state_digest"] ^= 0xFF
    d = diff_journals(a, b)
    assert d is not None and d["plane"] == "fold"
    assert d["tick"] == b["ticks"][digest_ticks[1]]["tick"]
    # two tampered planes in one tick → the CAUSALLY earliest is named
    b = copy.deepcopy(a)
    b["ticks"][10]["score"]["digest"] += 1
    b["ticks"][10]["admission"]["digest"] += 1
    d = diff_journals(a, b)
    assert (d["tick"], d["plane"]) == (10, "admission")
    # truncation is length divergence, never silence
    b = copy.deepcopy(a)
    b["ticks"] = b["ticks"][:12]
    d = diff_journals(a, b)
    assert d["plane"] == "length" and d["index"] == 12


def test_real_perturbation_diverges_early(baseline):
    """A genuinely different run (different seed) must diverge — and at
    the first tick the seeded arrivals differ, in the admission plane
    (the causally-first decision), not in some downstream echo."""
    eng, _ = baseline
    eng2, _ = _run(rca=True, seed=6)
    d = diff_journals(eng.flight_recorder.journal(),
                      eng2.flight_recorder.journal())
    assert d is not None
    assert d["plane"] == "admission"
    assert d["tick"] == 0       # power-law arrivals differ from tick one


def test_variant_keys_excluded_from_canonical(baseline):
    """Wall clocks and shard/lane topology are journal-variant: present
    in the dump for forensics, absent from the parity surface."""
    eng, _ = baseline
    recs = eng.flight_recorder.records()
    assert all(set(FLIGHT_VARIANT_KEYS) <= set(r) for r in recs)
    for rec in canonical_ticks(recs):
        assert not set(FLIGHT_VARIANT_KEYS) & set(rec)
        assert set(PLANES) <= set(rec)
    # per-shard legs fold in shard order at the barrier
    eng2, _ = _run(shards=2)
    for rec in eng2.flight_recorder.records():
        legs = rec["topology"]["shard_legs"]
        assert [leg["shard"] for leg in legs] == sorted(
            leg["shard"] for leg in legs)


# ---------------------------------------------------------------------------
# ring bounding: loss is counted, never silent
# ---------------------------------------------------------------------------

def test_ring_drops_are_counted():
    eng, rep = _run(flight_max_ticks=4)
    fr = eng.flight_recorder
    assert len(fr.records()) == 4
    assert fr.n_recorded == rep.ticks + 1
    assert fr.n_dropped == fr.n_recorded - 4
    assert rep.flight_dropped_ticks == fr.n_dropped > 0
    # the ring keeps the NEWEST ticks (the forensically useful end)
    assert fr.records()[-1].get("final") is True


def test_recorder_validation():
    from anomod.obs.flight import FlightRecorder
    with pytest.raises(ValueError):
        FlightRecorder({}, max_ticks=0)
    with pytest.raises(ValueError):
        FlightRecorder({}, digest_every=0)


def test_flight_knobs_validated(monkeypatch):
    from anomod.config import Config
    monkeypatch.setenv("ANOMOD_FLIGHT", "0")
    monkeypatch.setenv("ANOMOD_FLIGHT_DIGEST_EVERY", "32")
    monkeypatch.setenv("ANOMOD_FLIGHT_MAX_TICKS", "128")
    cfg = Config()
    assert cfg.flight is False
    assert cfg.flight_digest_every == 32
    assert cfg.flight_max_ticks == 128
    assert cfg.flight_dump_dir is None
    monkeypatch.setenv("ANOMOD_FLIGHT_DUMP_DIR", "/tmp/fd")
    assert Config().flight_dump_dir == Path("/tmp/fd")
    for var, bad in (("ANOMOD_FLIGHT_DIGEST_EVERY", "0"),
                     ("ANOMOD_FLIGHT_DIGEST_EVERY", "banana"),
                     ("ANOMOD_FLIGHT_MAX_TICKS", "-1"),
                     ("ANOMOD_FLIGHT_MAX_TICKS", "many")):
        monkeypatch.setenv(var, bad)
        with pytest.raises(ValueError):
            Config()
        monkeypatch.delenv(var)


def test_flight_knobs_env_contract_covered():
    """Every new ANOMOD_FLIGHT* knob is in the validated Config contract
    (check_env_contract green — the CI gate's clause of the issue)."""
    import sys as _sys
    _sys.path.insert(0, str(Path(__file__).parent.parent / "scripts"))
    try:
        import check_env_contract as cec
    finally:
        _sys.path.pop(0)
    refs = cec.referenced_vars(Path(cec.ROOT))
    corpus = cec.covered_vars(Path(cec.ROOT))
    for knob in ("ANOMOD_FLIGHT", "ANOMOD_FLIGHT_DIGEST_EVERY",
                 "ANOMOD_FLIGHT_MAX_TICKS", "ANOMOD_FLIGHT_DUMP_DIR"):
        assert knob in refs and knob in corpus


# ---------------------------------------------------------------------------
# header + dump + audit CLI
# ---------------------------------------------------------------------------

def test_header_is_self_describing(baseline):
    eng, _ = baseline
    h = eng.flight_recorder.header
    assert h["flight_format"] == FLIGHT_FORMAT
    assert h["run"]["seed"] == RUN_KW["seed"]
    assert h["engine"]["n_tenants"] == RUN_KW["n_tenants"]
    assert h["engine"]["serve_state"] in ("host", "device")
    assert h["config"]["flight_digest_every"] >= 1
    assert "jax" in h["versions"] and "numpy" in h["versions"]
    assert h["digest_every"] == 4
    # every env-defaulted knob that can move a canonical plane is
    # recorded RESOLVED, never as the raw None the replay process would
    # re-resolve from ITS env (env drift must not read as divergence)
    run = h["run"]
    assert run["buckets"] == list(RUN_KW["buckets"])
    assert run["lane_buckets"] == list(RUN_KW["lane_buckets"])
    assert run["max_backlog"] == RUN_KW["max_backlog"]
    assert run["fuse"] is True and run["rca"] is True
    assert run["shards"] == 1 and run["pipeline"] >= 1
    assert run["state"] in ("host", "device")


def test_dump_atomic_and_loadable(tmp_path, baseline):
    eng, _ = baseline
    path = tmp_path / "flight.json"
    path.write_text('{"stale": true}')
    doc = eng.flight_recorder.dump(path)
    assert list(tmp_path.glob("*.tmp")) == []
    loaded = load_journal(path)
    assert loaded["n_recorded"] == doc["n_recorded"]
    assert diff_journals(loaded, eng.flight_recorder.journal()) is None
    # a non-flight document must refuse to load, not diff as nonsense
    other = tmp_path / "other.json"
    other.write_text('{"ticks": "lol"}')
    with pytest.raises(ValueError):
        load_journal(other)


def test_audit_cli_record_replay_diff(tmp_path):
    from anomod.cli import main
    a, b = str(tmp_path / "a.json"), str(tmp_path / "b.json")
    common = ["--tenants", "6", "--services", "4", "--duration", "20",
              "--capacity", "1000", "--seed", "5", "--tick", "1.0",
              "--window-seconds", "2.0", "--baseline-windows", "4",
              "--digest-every", "4"]
    assert main(["audit", "record", "--out", a] + common) == 0
    # forensic replay at 2 shards from the journal header alone
    assert main(["audit", "replay", a, "--out", b, "--shards", "2"]) == 0
    assert main(["audit", "diff", a, b]) == 0
    doc = load_journal(b)
    assert doc["header"]["engine"]["shards"] == 2
    # a tampered journal diffs nonzero and names tick+plane
    doc["ticks"][7]["admission"]["digest"] ^= 1
    c = tmp_path / "c.json"
    c.write_text(json.dumps(doc))
    assert main(["audit", "diff", a, str(c)]) == 1


def test_forensic_bundle_on_alert(tmp_path, monkeypatch):
    """ANOMOD_FLIGHT_DUMP_DIR: the first alerting tick publishes ONE
    ring+registry+trace bundle, atomically."""
    from anomod.config import Config, get_config, set_config
    from anomod.obs.registry import Registry, set_registry
    monkeypatch.setenv("ANOMOD_FLIGHT_DUMP_DIR", str(tmp_path / "dumps"))
    prev_cfg = get_config()
    reg = Registry(enabled=True)
    prev_reg = set_registry(reg)
    set_config(Config())
    try:
        eng, rep = _run()
    finally:
        set_config(prev_cfg)
        set_registry(prev_reg)
    assert rep.n_alerts > 0
    dumps = sorted((tmp_path / "dumps").glob("flight_forensic_*.json"))
    assert len(dumps) == 1                      # once per run, bounded
    assert not list((tmp_path / "dumps").glob("*.tmp"))
    doc = json.loads(dumps[0].read_text())
    assert doc["bundle"] == "anomod-flight-forensic"
    assert "alert" in doc["reason"]
    assert doc["flight"]["ticks"]
    assert doc["registry"]["snapshot"]
    assert doc["trace"]["data"][0]["spans"]     # tracer rode the engine
    assert reg.counter("anomod_flight_dumps_total").value == 1
