"""Live-transport adapter tests: every client driven against an in-process
stub HTTP server replaying reference-shaped payloads, and every written
artifact round-tripped through the matching offline loader.

This is the wire-level contract the reference exercises against real infra
(Prometheus / Jaeger / SkyWalking OAP / Elasticsearch); the stubs make it a
CI property: client -> artifact -> loader == directly-loaded truth.
"""

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from anomod import labels, synth
from anomod.io.live import (CollectReport, ElasticsearchClient,
                            HttpTransport, JaegerClient, PrometheusClient,
                            SkyWalkingClient, TransportError)


class JsonStub:
    """Minimal threaded JSON-over-HTTP stub: ``route(method, path, params,
    body) -> (status, doc)``; records every request for assertions."""

    def __init__(self, route):
        stub = self
        stub.requests = []

        class Handler(BaseHTTPRequestHandler):
            def _serve(self, method):
                parsed = urllib.parse.urlparse(self.path)
                params = {k: v[0] for k, v in
                          urllib.parse.parse_qs(parsed.query).items()}
                length = int(self.headers.get("Content-Length") or 0)
                body = json.loads(self.rfile.read(length)) if length else None
                stub.requests.append((method, parsed.path, params, body))
                status, doc = route(method, parsed.path, params, body)
                payload = json.dumps(doc).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                self._serve("GET")

            def do_POST(self):
                self._serve("POST")

            def log_message(self, *a):  # quiet
                pass

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.thread = threading.Thread(target=self.server.serve_forever,
                                       daemon=True)
        self.thread.start()
        self.base_url = f"http://127.0.0.1:{self.server.server_port}"

    def close(self):
        self.server.shutdown()
        self.server.server_close()


@pytest.fixture
def stub_factory():
    stubs = []

    def make(route):
        s = JsonStub(route)
        stubs.append(s)
        return s

    yield make
    for s in stubs:
        s.close()


def _fast_transport():
    """No real sleeping in tests; the recorded schedule is asserted."""
    slept = []
    return HttpTransport(timeout=5.0, sleep=slept.append), slept


# ---------------------------------------------------------------------------
# Transport retry/backoff
# ---------------------------------------------------------------------------

def test_transport_retries_with_reference_backoff(stub_factory):
    """First attempt 500s, second succeeds; the wait is the reference's
    min(3*attempt, 10) schedule (trace_collector.py:279-291)."""
    calls = {"n": 0}

    def route(method, path, params, body):
        calls["n"] += 1
        if calls["n"] == 1:
            return 500, {"err": "boom"}
        return 200, {"ok": True}

    stub = stub_factory(route)
    tp, slept = _fast_transport()
    assert tp.request_json(stub.base_url + "/x") == {"ok": True}
    assert slept == [3.0]


def test_transport_exhausts_and_raises(stub_factory):
    stub = stub_factory(lambda *a: (500, {}))
    tp, slept = _fast_transport()
    with pytest.raises(TransportError):
        tp.request_json(stub.base_url + "/x")
    assert slept == [3.0, 6.0]          # attempts 1 and 2; 3rd raises
    assert len(stub.requests) == 3


# ---------------------------------------------------------------------------
# Prometheus
# ---------------------------------------------------------------------------

def _prom_payload(series):
    """Reference-shaped query_range success doc."""
    return {"status": "success",
            "data": {"resultType": "matrix",
                     "result": [{"metric": labels_, "values": values}
                                for labels_, values in series]}}


def test_prometheus_sn_csv_roundtrips_through_loader(stub_factory, tmp_path):
    """collect_sn -> per-query CSVs -> load_sn_metric_dir recovers values,
    labels, and pod->service normalization."""
    t0 = 1_700_000_000

    def route(method, path, params, body):
        assert path == "/api/v1/query_range"
        assert {"query", "start", "end", "step"} <= set(params)
        if params["query"] == "microservice_request_rate":
            return 200, _prom_payload([
                ({"service": "nginx-web-server", "job": "prom"},
                 [[t0 + 15 * i, str(1.5 + i)] for i in range(4)]),
                ({"service": "compose-post-service", "job": "prom"},
                 [[t0 + 15 * i, str(9.0 + i)] for i in range(4)]),
            ])
        if params["query"] == "system_cpu_usage":
            return 200, _prom_payload([
                ({"instance": "node0"}, [[t0, "0.93"]]),
            ])
        return 200, {"status": "success", "data": {"result": []}}

    stub = stub_factory(route)
    tp, _ = _fast_transport()
    client = PrometheusClient(stub.base_url, transport=tp)
    rep = client.collect_sn(
        {"microservice_request_rate": "microservice_request_rate",
         "system_cpu_usage": "system_cpu_usage",
         "redis_memory_used": "redis_memory_used"},
        tmp_path, t0, t0 + 60)
    assert isinstance(rep, CollectReport)
    assert rep.n_skipped == 1                     # empty query -> no file
    assert sorted(p.split("/")[-1] for p in rep.files) == \
        ["microservice_request_rate.csv", "system_cpu_usage.csv"]

    from anomod.io.metrics import load_sn_metric_dir
    mb = load_sn_metric_dir(tmp_path)
    assert mb is not None
    assert set(mb.metric_names) == {"microservice_request_rate",
                                    "system_cpu_usage"}
    assert mb.n_samples == 9
    # label columns survive: the request-rate series resolve to services
    assert {"nginx-web-server", "compose-post-service"} <= set(mb.services)
    mi = mb.metric_names.index("microservice_request_rate")
    sel = mb.metric == mi
    assert sel.sum() == 8
    assert np.isclose(sorted(mb.value[sel])[-1], 12.0)   # 9.0 + 3


def test_prometheus_error_status_raises(stub_factory):
    stub = stub_factory(lambda *a: (200, {"status": "error",
                                          "error": "bad query"}))
    tp, _ = _fast_transport()
    with pytest.raises(TransportError, match="bad query"):
        PrometheusClient(stub.base_url, transport=tp).query_range(
            "x", 0, 1)


def test_prometheus_tt_long_csv_roundtrips(stub_factory, tmp_path):
    """collect_tt -> one long CSV (raw query as metric_name, label columns
    spread, __name__ dropped) -> load_tt_metric_csv."""
    t0 = 1_700_000_000

    def route(method, path, params, body):
        if params["query"] == "rate(node_cpu_seconds_total[5m])":
            return 200, _prom_payload([
                ({"__name__": "node_cpu_seconds_total", "pod": "ts-order-service-7f9b5"},
                 [[t0, "0.4"], [t0 + 15, "0.5"]]),
            ])
        if params["query"] == "up":
            return 200, _prom_payload([
                ({"pod": "ts-travel-service-x1y2z"}, [[t0, "1"]]),
            ])
        return 200, {"status": "success", "data": {"result": []}}

    stub = stub_factory(route)
    tp, _ = _fast_transport()
    out = tmp_path / "exp_metrics_1.csv"
    rep = PrometheusClient(stub.base_url, transport=tp).collect_tt(
        ["rate(node_cpu_seconds_total[5m])", "up", "node_load5"],
        out, t0, t0 + 60)
    assert rep.n_records == 3 and rep.n_skipped == 1

    header = out.read_text().splitlines()[0].split(",")
    assert header[:4] == ["metric_name", "timestamp", "datetime", "value"]
    assert "__name__" not in header and "pod" in header

    from anomod.io.metrics import load_tt_metric_csv
    mb = load_tt_metric_csv(out)
    assert mb is not None and mb.n_samples == 3
    assert "rate(node_cpu_seconds_total[5m])" in mb.metric_names
    # pod label -> normalized service names
    assert {"ts-order-service", "ts-travel-service"} <= set(mb.services)


# ---------------------------------------------------------------------------
# Jaeger
# ---------------------------------------------------------------------------

def _jaeger_stub_route(doc):
    """Serve /api/services + per-service /api/traces from one Jaeger doc,
    overlapping across services so dedup is exercised."""
    svc_names = sorted({p["serviceName"] for tr in doc["data"]
                        for p in tr["processes"].values()})

    def route(method, path, params, body):
        if path == "/api/services":
            return 200, {"data": svc_names}
        if path == "/api/traces":
            svc = params["service"]
            assert "limit" in params and "lookback" in params
            data = [tr for tr in doc["data"]
                    if any(p["serviceName"] == svc
                           for p in tr["processes"].values())]
            return 200, {"data": data}
        return 404, {}

    return route


def test_jaeger_collect_all_dedups_and_roundtrips(stub_factory, tmp_path):
    from anomod.io.sn_traces import load_jaeger_json, spans_from_jaeger

    batch = synth.generate_spans(labels.label_for("Perf_CPU_Contention"),
                                 n_traces=25, seed=7)
    doc = synth.spans_to_jaeger_json(batch)
    stub = stub_factory(_jaeger_stub_route(doc))
    tp, _ = _fast_transport()
    out = tmp_path / "all_traces.json"
    rep = JaegerClient(stub.base_url, transport=tp).collect_all(out)
    # every trace fetched exactly once despite appearing under many services
    assert rep.n_records == len(doc["data"])
    assert rep.n_skipped > 0                      # overlap existed

    got = load_jaeger_json(out)
    truth = spans_from_jaeger(doc)
    assert got.n_spans == truth.n_spans
    assert sorted(got.services) == sorted(truth.services)
    assert int(got.is_error.sum()) == int(truth.is_error.sum())
    assert int(got.duration_us.sum()) == int(truth.duration_us.sum())
    # per-trace span counts keyed by trace id (order-independent)
    def per_trace(b):
        return {b.trace_ids[t]: int((b.trace == t).sum())
                for t in range(len(b.trace_ids))}
    assert per_trace(got) == per_trace(truth)


# ---------------------------------------------------------------------------
# SkyWalking GraphQL
# ---------------------------------------------------------------------------

def _artifact_to_graphql(artifact):
    """Invert the collector artifact into raw OAP GraphQL responses: the
    summaries the trace-list query returns and the span dicts the
    trace-detail query returns."""
    summaries, spans_by_tid = [], {}
    for t in artifact["traces"]:
        summaries.append({"traceIds": [t["trace_id"]],
                          "duration": t["summary"]["duration"],
                          "start": 0,
                          "isError": t["summary"]["is_error"],
                          "endpointNames": []})
        spans_by_tid[t["trace_id"]] = [{
            "traceId": sp["trace_id"], "segmentId": sp["segment_id"],
            "spanId": sp["span_id"], "parentSpanId": sp["parent_span_id"],
            "serviceCode": sp["service_code"],
            "startTime": sp["start_timestamp_ms"],
            "endTime": sp["end_timestamp_ms"],
            "endpointName": sp["endpoint_name"], "type": sp["type"],
            "peer": sp["peer"], "component": sp["component"],
            "isError": sp["is_error"], "layer": sp["layer"],
            "tags": sp["tags"], "refs": sp["refs"],
        } for sp in t["spans"]]
    return summaries, spans_by_tid


def _sw_stub_route(summaries, spans_by_tid):
    def route(method, path, params, body):
        q = body["query"]
        if "queryBasicTraces" in q:
            paging = body["variables"]["condition"]["paging"]
            n, size = paging["pageNum"], paging["pageSize"]
            page = summaries[(n - 1) * size:n * size]
            return 200, {"data": {"data": {"total": len(summaries),
                                           "traces": page}}}
        if "queryTrace" in q:
            tid = body["variables"]["traceId"]
            return 200, {"data": {"trace":
                                  {"spans": spans_by_tid.get(tid, [])}}}
        return 400, {"errors": [{"message": "unknown query"}]}

    return route


def test_skywalking_paginated_collect_matches_direct_artifact(
        stub_factory, tmp_path):
    """Full client path — paginated summaries, per-trace detail, artifact
    build — produces a SpanBatch IDENTICAL to loading the directly-emitted
    collector artifact."""
    from anomod.io.tt_traces import load_skywalking_json, spans_from_skywalking

    batch = synth.generate_spans(labels.label_for("Lv_D_TRANSACTION_timeout"),
                                 n_traces=9, seed=3)
    artifact = synth.spans_to_skywalking_json(batch, "Lv_D_TRANSACTION_timeout")
    summaries, spans_by_tid = _artifact_to_graphql(artifact)
    # a duplicate summary entry exercises traceID dedup
    summaries.append(summaries[0])
    stub = stub_factory(_sw_stub_route(summaries, spans_by_tid))
    tp, _ = _fast_transport()
    out = tmp_path / "live_skywalking_traces.json"
    rep = SkyWalkingClient(stub.base_url + "/graphql",
                           transport=tp).collect(
        out, experiment="Lv_D_TRANSACTION_timeout", limit=1000,
        hours_back=1.0, page_size=4, now_s=1_700_000_000.0)
    assert rep.n_records == batch.n_spans

    # pagination actually happened: ceil((9+1)/4) = 3 list pages
    list_calls = [r for r in stub.requests
                  if r[3] and "queryBasicTraces" in r[3]["query"]]
    assert len(list_calls) == 3

    got = load_skywalking_json(out)
    truth = spans_from_skywalking(artifact)
    assert got.n_spans == truth.n_spans
    for f in ("trace", "parent", "service", "endpoint", "start_us",
              "duration_us", "is_error", "status", "kind"):
        np.testing.assert_array_equal(getattr(got, f), getattr(truth, f),
                                      err_msg=f)
    assert got.services == truth.services
    assert got.trace_ids == truth.trace_ids
    # parent graph survived the wire: same resolution rate, same edges
    assert int((got.parent >= 0).sum()) == int((truth.parent >= 0).sum())


def test_skywalking_graphql_error_payload_raises(stub_factory):
    stub = stub_factory(lambda *a: (200, {"errors": [{"message": "nope"}]}))
    tp, _ = _fast_transport()
    with pytest.raises(TransportError, match="graphql error"):
        SkyWalkingClient(stub.base_url, transport=tp).trace_spans("t1")


# ---------------------------------------------------------------------------
# Elasticsearch
# ---------------------------------------------------------------------------

def test_es_segments_roundtrip_through_loader(stub_factory, tmp_path):
    """Segment search -> detailed_traces artifact -> tt_traces_es loader
    (base64 service ids decoded by the LOADER, latency in ms -> µs)."""
    import base64

    from anomod.io.tt_traces_es import load_detailed_traces_json

    def b64(name):
        return base64.b64encode(name.encode()).decode() + ".1"

    sources = [
        {"trace_id": "t-1", "segment_id": "seg-a",
         "service_id": b64("ts-order-service"), "endpoint_name": "/order",
         "start_time": 1_700_000_000_000, "end_time": 1_700_000_000_120,
         "latency": 120, "is_error": 0},
        {"trace_id": "t-1", "segment_id": "seg-b",
         "service_id": b64("ts-travel-service"), "endpoint_name": "/travel",
         "start_time": 1_700_000_000_050, "end_time": 1_700_000_000_090,
         "latency": 40, "is_error": 1},
        {"trace_id": "t-2", "segment_id": "seg-c",
         "service_id": b64("ts-order-service"), "endpoint_name": "/order",
         "start_time": 1_700_000_001_000, "end_time": 1_700_000_001_030,
         "latency": 30, "is_error": 0},
    ]

    def route(method, path, params, body):
        assert method == "POST" and path == "/sw_segment-*/_search"
        rng = body["query"]["bool"]["must"][0]["range"]["start_time"]
        assert rng["gte"] < rng["lte"]             # windowed, ms epoch
        assert body["size"] == 500
        assert body["sort"] == [{"start_time": {"order": "desc"}}]
        return 200, {"hits": {"hits": [{"_source": s} for s in sources]}}

    stub = stub_factory(route)
    tp, _ = _fast_transport()
    out = tmp_path / "detailed_traces_1.json"
    rep = ElasticsearchClient(stub.base_url, transport=tp).collect(
        out, size=500, hours_back=2.0, now_s=1_700_000_100.0)
    assert rep.n_records == 3

    got = load_detailed_traces_json(out)
    assert got.n_spans == 3
    assert set(got.services) == {"ts-order-service", "ts-travel-service"}
    assert sorted(got.duration_us.tolist()) == [30_000, 40_000, 120_000]
    assert int(got.is_error.sum()) == 1
    assert len(got.trace_ids) == 2


# ---------------------------------------------------------------------------
# CLI surface
# ---------------------------------------------------------------------------

def test_cli_collect_jaeger(stub_factory, tmp_path, capsys):
    from anomod.cli import main

    batch = synth.generate_spans(labels.label_for("Perf_CPU_Contention"),
                                 n_traces=6, seed=1)
    doc = synth.spans_to_jaeger_json(batch)
    stub = stub_factory(_jaeger_stub_route(doc))
    out = tmp_path / "all_traces.json"
    assert main(["collect", "jaeger", "--url", stub.base_url,
                 "--out", str(out)]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["kind"] == "jaeger"
    assert rep["n_records"] == len(doc["data"])

    from anomod.io.sn_traces import load_jaeger_json
    assert load_jaeger_json(out).n_spans == batch.n_spans


def test_cli_collect_prometheus_sn_catalog(stub_factory, tmp_path, capsys):
    """The CLI sweeps the full SN catalog (24 identity queries against the
    stub); only families the stub answers produce CSVs."""
    from anomod.cli import main
    from anomod.metrics_catalog import SN_METRIC_FILES

    t0 = 1_700_000_000

    def route(method, path, params, body):
        if params["query"] in ("system_load1", "redis_command_rate"):
            return 200, _prom_payload([({"instance": "n0"}, [[t0, "2.5"]])])
        return 200, {"status": "success", "data": {"result": []}}

    stub = stub_factory(route)
    out = tmp_path / "metric_data"
    assert main(["collect", "prometheus", "--url", stub.base_url,
                 "--out", str(out), "--testbed", "SN"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["kind"] == "prometheus_sn"
    assert rep["n_skipped"] == len(SN_METRIC_FILES) - 2
    assert len(rep["files"]) == 2
