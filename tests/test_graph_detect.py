"""Service graph + detector: structure, numpy/jax parity, label eval."""

import numpy as np
import pytest

from anomod import detect, labels, synth
from anomod.graph import build_service_graph, depths, service_stats


def _spans(name, n=60):
    return synth.generate_spans(labels.label_for(name), n_traces=n)


def test_depths_match_parent_chain():
    b = _spans("Normal_case", 20)
    d = depths(b)
    roots = b.parent == -1
    assert (d[roots] == 0).all()
    nz = ~roots
    assert (d[nz] == d[b.parent[nz]] + 1).all()


def test_service_graph_structure():
    b = _spans("Normal_case", 50)
    g = build_service_graph(b)
    assert g.n_services == len(b.services)
    assert g.adj_counts.sum() > 0
    # every recorded edge appears in CSR
    for e in range(g.n_edges):
        s, t = g.edge_src[e], g.edge_dst[e]
        assert t in g.neighbors[s][g.neighbor_mask[s]]
    # gateway is a source: out-degree > 0, in-degree == 0
    gw = b.services.index("ts-gateway-service")
    assert g.adj_counts[gw].sum() > 0
    assert g.adj_counts[:, gw].sum() == 0


def test_service_graph_pinned_services():
    b = _spans("Normal_case", 30)
    pinned = ("ts-gateway-service", "ts-preserve-service", "not-a-service")
    g = build_service_graph(b, services=pinned)
    assert g.n_services == 3
    assert g.adj_counts.shape == (3, 3)


def test_service_stats_percentiles():
    b = _spans("Lv_P_CPU_preserve", 100)
    st = service_stats(b)
    tgt = b.services.index("ts-preserve-service")
    assert st.lat_p99_us[tgt] >= st.lat_p95_us[tgt] >= st.lat_p50_us[tgt] > 0
    # cross-check p50 against direct numpy on one service
    dur = b.duration_us[b.service == tgt].astype(float)
    assert abs(st.lat_p50_us[tgt] - np.quantile(dur, 0.5)) / np.quantile(dur, 0.5) < 0.25


@pytest.mark.parametrize("testbed", ["SN", "TT"])
def test_detector_eval_on_synth_corpus(testbed):
    corpus = [synth.generate_experiment(l, n_traces=80)
              for l in labels.labels_for_testbed(testbed)]
    summary = detect.evaluate_corpus(corpus)
    # the numpy baseline must localize well on synthetic ground truth
    assert summary.n_rca_cases >= 9
    assert summary.top1 >= 0.8, [
        (r.experiment, r.ranked_services[:3]) for r in summary.results
        if r.is_anomaly_true and r.target_service and not r.hit(1)]
    assert summary.detection_accuracy >= 0.9


def test_backend_parity_cpu_vs_jax():
    corpus = [synth.generate_experiment(l, n_traces=40)
              for l in labels.labels_for_testbed("TT")]
    cpu = detect.evaluate_corpus(corpus, backend="cpu")
    jx = detect.evaluate_corpus(corpus, backend="jax")
    assert cpu.top1 == jx.top1
    assert cpu.top3 == jx.top3
    for a, b in zip(cpu.results, jx.results):
        assert a.ranked_services[0] == b.ranked_services[0]
        assert abs(a.score - b.score) < 1e-4


def test_api_and_coverage_features_populated():
    from anomod import detect, labels, synth
    exp = synth.generate_experiment("Lv_C_exception_injection", n_traces=60)
    services = exp.spans.services
    x = detect.extract_features(exp, services).x
    assert x.shape[1] == len(detect.FEATURES) == 13
    assert x[:, 8].max() > 0          # api latency attributed to some service
    assert x[:, 9].max() > 0          # coverage ratios present


def test_api_modality_alone_localizes_target():
    """Per-endpoint API stats routed to the owning service must rank the
    culprit when span/log/metric features are ablated."""
    import dataclasses
    import numpy as np
    from anomod import detect, labels, synth
    label = labels.label_for("Lv_S_HTTPABORT_preserve")
    normal = synth.generate_experiment("Normal_case", n_traces=60)
    exp = synth.generate_experiment(label, n_traces=60)
    services = exp.spans.services
    feat = detect.extract_features(exp, services).x
    base = detect.extract_features(normal, services).x
    api_cols = [7, 8]
    mask = np.zeros_like(feat)
    mask[:, api_cols] = 1.0
    scores = detect.service_scores(feat * mask, base * mask)
    top = services[int(np.argmax(scores))]
    assert top == label.target_service


def test_coverage_shift_concentrates_on_culprit():
    import numpy as np
    from anomod import detect, labels, synth
    label = labels.label_for("Lv_C_security_check")
    normal = synth.generate_experiment("Normal_case", n_traces=40)
    exp = synth.generate_experiment(label, n_traces=40)
    services = exp.spans.services
    feat = detect.extract_features(exp, services).x
    base = detect.extract_features(normal, services).x
    d_cov = np.abs(feat[:, 9] - base[:, 9])
    assert services[int(np.argmax(d_cov))] == label.target_service


def test_modality_missing_on_one_side_does_not_corrupt_scores():
    """A baseline collected without coverage/api must not poison deltas."""
    import dataclasses
    import numpy as np
    from anomod import detect, labels, synth
    normal = synth.generate_experiment("Normal_case", n_traces=60)
    exp = synth.generate_experiment("Lv_P_CPU_preserve", n_traces=60)
    services = exp.spans.services
    stripped = dataclasses.replace(normal, api=None, coverage=None)
    feat = detect.extract_features(exp, services).x
    base = detect.extract_features(stripped, services).x
    scores = np.asarray(detect.service_scores(feat, base))
    top = services[int(np.argmax(scores))]
    assert top == "ts-preserve-service"


def test_endpoint_owner_handles_nonstandard_ports():
    from anomod.suite import endpoint_owner
    assert endpoint_owner("http://10.0.0.5:30001/wrk2-api/user/login",
                          "SN") == "user-service"
    assert endpoint_owner("/wrk2-api/post/compose", "SN") == "compose-post-service"
    assert endpoint_owner("/api/v1/preserveservice", "TT") == "ts-preserve-service"
    assert endpoint_owner("/api/v1/unknownthing", "TT") == "ts-gateway-service"
