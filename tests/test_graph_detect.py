"""Service graph + detector: structure, numpy/jax parity, label eval."""

import numpy as np
import pytest

from anomod import detect, labels, synth
from anomod.graph import build_service_graph, depths, service_stats


def _spans(name, n=60):
    return synth.generate_spans(labels.label_for(name), n_traces=n)


def test_depths_match_parent_chain():
    b = _spans("Normal_case", 20)
    d = depths(b)
    roots = b.parent == -1
    assert (d[roots] == 0).all()
    nz = ~roots
    assert (d[nz] == d[b.parent[nz]] + 1).all()


def test_service_graph_structure():
    b = _spans("Normal_case", 50)
    g = build_service_graph(b)
    assert g.n_services == len(b.services)
    assert g.adj_counts.sum() > 0
    # every recorded edge appears in CSR
    for e in range(g.n_edges):
        s, t = g.edge_src[e], g.edge_dst[e]
        assert t in g.neighbors[s][g.neighbor_mask[s]]
    # gateway is a source: out-degree > 0, in-degree == 0
    gw = b.services.index("ts-gateway-service")
    assert g.adj_counts[gw].sum() > 0
    assert g.adj_counts[:, gw].sum() == 0


def test_service_graph_pinned_services():
    b = _spans("Normal_case", 30)
    pinned = ("ts-gateway-service", "ts-preserve-service", "not-a-service")
    g = build_service_graph(b, services=pinned)
    assert g.n_services == 3
    assert g.adj_counts.shape == (3, 3)


def test_service_stats_percentiles():
    b = _spans("Lv_P_CPU_preserve", 100)
    st = service_stats(b)
    tgt = b.services.index("ts-preserve-service")
    assert st.lat_p99_us[tgt] >= st.lat_p95_us[tgt] >= st.lat_p50_us[tgt] > 0
    # cross-check p50 against direct numpy on one service
    dur = b.duration_us[b.service == tgt].astype(float)
    assert abs(st.lat_p50_us[tgt] - np.quantile(dur, 0.5)) / np.quantile(dur, 0.5) < 0.25


@pytest.mark.parametrize("testbed", ["SN", "TT"])
def test_detector_eval_on_synth_corpus(testbed):
    corpus = [synth.generate_experiment(l, n_traces=80)
              for l in labels.labels_for_testbed(testbed)]
    summary = detect.evaluate_corpus(corpus)
    # the numpy baseline must localize well on synthetic ground truth
    assert summary.n_rca_cases >= 9
    assert summary.top1 >= 0.8, [
        (r.experiment, r.ranked_services[:3]) for r in summary.results
        if r.is_anomaly_true and r.target_service and not r.hit(1)]
    assert summary.detection_accuracy >= 0.9


def test_backend_parity_cpu_vs_jax():
    corpus = [synth.generate_experiment(l, n_traces=40)
              for l in labels.labels_for_testbed("TT")]
    cpu = detect.evaluate_corpus(corpus, backend="cpu")
    jx = detect.evaluate_corpus(corpus, backend="jax")
    assert cpu.top1 == jx.top1
    assert cpu.top3 == jx.top3
    for a, b in zip(cpu.results, jx.results):
        assert a.ranked_services[0] == b.ranked_services[0]
        assert abs(a.score - b.score) < 1e-4
