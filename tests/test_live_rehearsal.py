"""End-to-end LIVE collection rehearsal: stub infra -> 5-modality tree ->
loaders -> validator -> detector, zero synth fallback.

The round-4 verdict's last live-story gap: prove that the live clients
(HTTP transports from anomod.io.live + exec transports from
anomod.io.live_exec) compose into a full collection run whose OUTPUT TREE
is byte-compatible with the archive layout — i.e. a user can point the
collectors at running infra and get a drop-in experiment the offline
stack consumes unmodified (collect_all_modalities.sh:114-254's promise).

Two flavors — TT (kubernetes/SkyWalking stack) and SN (compose/Jaeger
stack, test at the bottom: jaeger + prometheus-SN CSV + docker-logs +
gcov flush/collect + api family).

TT flavor, per modality:
  traces   — SkyWalking GraphQL stub server (from test_live) serving the
             fault experiment's spans; SkyWalkingClient.collect
  metrics  — Prometheus stub serving query_range; collect_tt long CSV
  logs     — fake kubectl cluster whose `kubectl logs` replay each pod's
             LogBatch lines; KubeLogCollector
  coverage — fake jacococli dump/cp loop delivering CoverageDump bytes;
             JacocoCoverageCollector renders the report tree
  api      — the in-process OpenAPI monitor family writer (the monitor IS
             the live api collector in this design — there is no separate
             HTTP backend to stub)

The tree is then consumed STRICTLY (synth_on_lfs=False): every modality
must load real, the validator must pass, and the detector must rank the
injected culprit over the fault-free baseline tree collected the same
way.
"""

import json

import numpy as np
import pytest

from anomod import labels, synth
from anomod.config import Config
from anomod.io.live import HttpTransport, PrometheusClient, SkyWalkingClient
from anomod.io.live_exec import (ExecResult, ExecRunner,
                                 JacocoCoverageCollector, KubeLogCollector)
from test_live import JsonStub, _artifact_to_graphql, _sw_stub_route


@pytest.fixture
def stub_factory():
    stubs = []

    def make(route):
        s = JsonStub(route)
        stubs.append(s)
        return s

    yield make
    for s in stubs:
        s.close()

STAMP = "20260731_130000"
TS2 = "20260731_130500"


def _prom_route(queries):
    """query_range stub: every query answers one constant series."""
    def route(method, path, params, body):
        assert path.endswith("/api/v1/query_range")
        start = float(params["start"])
        return 200, {"status": "success", "data": {"result": [{
            "metric": {"__name__": "stub", "service": "ts-order-service"},
            "values": [[start + 15 * i, "1.0"] for i in range(4)],
        }]}}
    return route


class FakePods:
    """kubectl/jacoco answers derived from one synthetic Experiment."""

    def __init__(self, exp):
        self.exp = exp
        self.pods = [f"{svc}-86d6f7876-9{si:02d}bh"
                     for si, svc in enumerate(exp.logs.services)]

    def _log_text(self, svc_idx):
        from anomod.schemas import LOG_ERROR, LOG_INFO, LOG_WARN
        lvl_name = {LOG_INFO: "INFO", LOG_WARN: "WARN", LOG_ERROR: "ERROR"}
        lg = self.exp.logs
        rows = np.flatnonzero(lg.service == svc_idx)
        return "".join(
            f"2026-07-31 13:00:00 {lvl_name.get(int(lg.level[r]), 'DEBUG')} "
            f"{lg.services[svc_idx]}: request handled\n" for r in rows)

    def __call__(self, cmd):
        joined = " ".join(cmd)
        if "jsonpath" in joined:
            return ExecResult(0, " ".join(p for p in self.pods
                                          if p.startswith("ts-")))
        if cmd[:3] == ["kubectl", "get", "pods"]:
            return ExecResult(0, json.dumps({"items": [
                {"metadata": {"name": p}} for p in self.pods]}))
        if cmd[:2] == ["kubectl", "logs"]:
            if "--previous" in cmd:
                return ExecResult(1, "", "no previous container")
            svc_idx = self.pods.index(cmd[2])
            return ExecResult(0, self._log_text(svc_idx))
        if cmd[:2] == ["kubectl", "get"] and "events" in cmd:
            return ExecResult(0, '{"items": []}')
        if "test -f /jacoco/jacococli.jar" in joined \
                or "jacococli.jar dump" in joined:
            return ExecResult(0)
        if "ls -1 /coverage/*.exec" in joined:
            pod = cmd[cmd.index("exec") + 1]
            return ExecResult(0, f"/coverage/jacoco-{pod}.exec\n")
        if len(cmd) > 3 and cmd[3] == "cp":
            from pathlib import Path

            from anomod.io.coverage_report import batch_to_dumps, save_dump
            pod = cmd[4].split(":", 1)[0]
            dst = Path(cmd[5])
            from anomod.io.logs import pod_to_service
            svc = pod_to_service(pod)
            dump = next(d for d in batch_to_dumps(self.exp.coverage)
                        if d.service == svc)
            save_dump(dump, dst)
            if not dst.exists():
                dst.with_name(dst.name + ".npz").rename(dst)
            return ExecResult(0)
        return ExecResult(1, "", f"unscripted: {joined}")


def _collect_tree(exp, label, root, stub_factory):
    """One experiment through every live collector into the archive
    layout the TT discover() walks (dir naming run_all_experiments.sh:
    ``<Exp>_<ts>_em`` for anomalies, ``<Exp>_em_<ts>`` for the normal)."""
    ts = "20260731T130500Z"
    base = (f"{exp.name}_{ts}_em" if label.is_anomaly
            else f"{exp.name}_em_{ts}")
    tt = root / "TT_data"

    # traces: spans -> collector artifact -> GraphQL stub -> live client
    doc = synth.spans_to_skywalking_json(exp.spans, experiment=base)
    summaries, spans_by_tid = _artifact_to_graphql(doc)
    stub = stub_factory(_sw_stub_route(summaries, spans_by_tid))
    tp = HttpTransport(timeout=5.0, sleep=lambda s: None)
    tdir = tt / "trace_data" / base
    SkyWalkingClient(stub.base_url, transport=tp).collect(
        tdir / f"{base}_skywalking_traces_{STAMP}.json",
        experiment=base, limit=len(summaries))

    # metrics: prometheus stub -> TT long CSV
    pstub = stub_factory(_prom_route(None))
    mdir = tt / "metric_data" / base
    PrometheusClient(pstub.base_url, transport=tp).collect_tt(
        ["node_cpu_seconds_total", "jvm_memory_used_bytes"],
        mdir / f"{base}_metrics_{STAMP}.csv", 0.0, 60.0)

    # logs + coverage through the fake cluster
    fake = FakePods(exp)
    runner = ExecRunner(run_fn=fake)
    KubeLogCollector(runner=runner).collect(
        tt / "log_data" / base, stamp=STAMP)
    JacocoCoverageCollector(runner=runner).collect(
        tt / "coverage_data" / base,
        tt / "coverage_report" / base)

    # api: the in-process monitor family writer
    from anomod.io.api import write_api_artifact_family
    write_api_artifact_family(
        exp.api, tt / "api_responses" / base)


@pytest.mark.slow
def test_live_rehearsal_tt_five_modalities(tmp_path, stub_factory):
    fault = labels.label_for("Lv_S_KILLPOD_preserve")
    normal = next(l for l in labels.labels_for_testbed("TT")
                  if not l.is_anomaly)
    exps = {}
    for label in (normal, fault):
        exps[label.experiment] = synth.generate_experiment(
            label, n_traces=60, seed=11)
        _collect_tree(exps[label.experiment], label, tmp_path, stub_factory)

    # strict consumption: no synth fallback anywhere
    cfg = Config(data_root=tmp_path, synth_on_lfs=False)
    from anomod.io import dataset
    from anomod.validate import validate_experiment
    loaded = {}
    for name in exps:
        exp = dataset.load_experiment(name, testbed="TT", cfg=cfg)
        assert not exp.synthetic, f"synth fallback hit for {name}"
        for modality in ("spans", "metrics", "logs", "api", "coverage"):
            assert getattr(exp, modality) is not None, (name, modality)
        rep = validate_experiment(exp)
        assert rep.ok, rep
        loaded[name] = exp

    # the detector consumes the collected tree and localizes the culprit
    from anomod import detect
    services = tuple(synth.TT_SERVICES)
    base_x = detect.extract_features(loaded[normal.experiment], services).x
    x = detect.extract_features(loaded[fault.experiment], services).x
    scores = np.asarray(detect.service_scores(x, base_x))
    top = [services[i] for i in np.argsort(-scores)[:3]]
    assert fault.target_service in top, (fault.target_service, top)


class FakeSNDocker:
    """docker answers for the SN flavor, derived from one Experiment:
    per-container logs replay the LogBatch, the gcov collect script
    writes each service's coverage masks into the mounted report tree."""

    def __init__(self, exp, mount):
        self.exp = exp
        self.mount = mount
        self.containers = {svc: f"c{si:02d}"
                           for si, svc in enumerate(exp.logs.services)}

    def _log_text(self, svc_idx):
        from anomod.schemas import LOG_ERROR, LOG_INFO, LOG_WARN
        lvl_name = {LOG_INFO: "INFO", LOG_WARN: "WARN", LOG_ERROR: "ERROR"}
        lg = self.exp.logs
        rows = np.flatnonzero(lg.service == svc_idx)
        return "".join(
            f"2026-07-31 13:00:00 {lvl_name.get(int(lg.level[r]), 'DEBUG')} "
            f"{lg.services[svc_idx]}: handled\n" for r in rows)

    def __call__(self, cmd):
        from anomod.io.live_exec import ExecResult
        joined = " ".join(cmd)
        if cmd[:2] == ["docker", "ps"]:
            # honor the requested --format, as real docker does: the two
            # collectors ask for different column sets
            names_only = "{{.Names}}" == cmd[-1]
            rows = [(f"socialnetwork_{svc}_1" if names_only
                     else f"{cid} socialnetwork_{svc}_1")
                    for svc, cid in self.containers.items()]
            return ExecResult(0, "\n".join(rows) + "\n")
        if cmd[:2] == ["docker", "logs"]:
            cid = cmd[-1]
            svc_idx = [c for c in self.containers.values()].index(cid)
            return ExecResult(0, self._log_text(svc_idx))
        if "kill -USR1 1" in joined:
            return ExecResult(0)
        if "collect_coverage.sh" in joined:
            env = dict(kv.split("=", 1) for kv in cmd[3:-2:2])
            svc = env["SERVICE_NAME"]
            cb = self.exp.coverage
            if svc not in cb.services:
                return ExecResult(0)
            d = (self.mount / f"{env['EXPERIMENT_BASE_NAME']}_"
                              f"{env['TIMESTAMP']}" / svc)
            d.mkdir(parents=True, exist_ok=True)
            si = cb.services.index(svc)
            for row in np.flatnonzero(cb.service == si):
                path = cb.paths[int(row)]
                total = int(cb.lines_total[row])
                covered = int(cb.lines_covered[row])
                lines = [f"        -:    0:Source:{path}"]
                for i in range(1, total + 1):
                    mark = "3" if i <= covered else "#####"
                    lines.append(f"        {mark}:{i:5d}:l{i};")
                (d / (path.replace("/", "#") + ".gcov")).write_text(
                    "\n".join(lines) + "\n")
            return ExecResult(0)
        return ExecResult(1, "", f"unscripted: {joined}")


def _collect_sn_tree(exp, root, stub_factory):
    """SN flavor: jaeger + prometheus-SN + docker-logs + gcov + api."""
    from test_live import _jaeger_stub_route

    from anomod.io.live import JaegerClient
    from anomod.io.live_exec import (DockerLogCollector, ExecRunner,
                                     GcovCoverageCollector)
    ts1, ts2 = "20260731T130000Z", "20260731T130500Z"
    base = f"{exp.name}_{ts1}"
    sn = root / "SN_data"
    tp = HttpTransport(timeout=5.0, sleep=lambda s: None)

    doc = synth.spans_to_jaeger_json(exp.spans)
    stub = stub_factory(_jaeger_stub_route(doc))
    tdir = sn / "trace_data" / f"{base}_traces_{ts2}"
    JaegerClient(stub.base_url, transport=tp).collect_all(
        tdir / "all_traces.json")

    pstub = stub_factory(_prom_route(None))
    mdir = sn / "metric_data" / f"{base}_metrics_{ts2}"
    PrometheusClient(pstub.base_url, transport=tp).write_query_csv(
        "rate(http_requests_total[1m])", "request_rate", mdir, 0.0, 60.0)

    mount = root / f"mount_{exp.name}"
    fake = FakeSNDocker(exp, mount)
    runner = ExecRunner(run_fn=fake)
    DockerLogCollector(runner=runner).collect(
        sn / "log_data" / f"{base}_logs_{ts2}", stamp="TS")
    GcovCoverageCollector(runner=runner).collect(
        mount, sn / "coverage_data" / f"{base}_coverage_{ts2}",
        base=base, stamp="TS")

    from anomod.io.api import write_api_artifact_family
    write_api_artifact_family(
        exp.api, sn / "api_responses" / f"{base}_openapi_{ts2}")


@pytest.mark.slow
def test_live_rehearsal_sn_five_modalities(tmp_path, stub_factory):
    fault = labels.label_for("Svc_Kill_Media")
    normal = next(l for l in labels.labels_for_testbed("SN")
                  if not l.is_anomaly)
    exps = {}
    for label in (normal, fault):
        exps[label.experiment] = synth.generate_experiment(
            label, n_traces=80, seed=5)
        _collect_sn_tree(exps[label.experiment], tmp_path, stub_factory)

    cfg = Config(data_root=tmp_path, synth_on_lfs=False)
    from anomod.io import dataset
    from anomod.validate import validate_experiment
    loaded = {}
    for name in exps:
        exp = dataset.load_experiment(name, testbed="SN", cfg=cfg)
        assert not exp.synthetic, f"synth fallback hit for {name}"
        for modality in ("spans", "metrics", "logs", "api", "coverage"):
            assert getattr(exp, modality) is not None, (name, modality)
        rep = validate_experiment(exp)
        assert rep.ok, rep
        loaded[name] = exp

    from anomod import detect
    services = tuple(synth.SN_SERVICES)
    base_x = detect.extract_features(loaded[normal.experiment], services).x
    x = detect.extract_features(loaded[fault.experiment], services).x
    scores = np.asarray(detect.service_scores(x, base_x))
    top = [services[i] for i in np.argsort(-scores)[:3]]
    assert fault.target_service in top, (fault.target_service, top)
