"""The deferred-commit serve tick (ANOMOD_SERVE_ASYNC_COMMIT, ISSUE-16).

The central pin: with the knob ON, tick N's fold+score dispatch is
issued WITHOUT waiting, tick N+1's coordinator phases (admission,
drain, shed, SLO) run under the in-flight XLA work, and tick N's
results drain at a commit barrier placed just before they are first
read — and every decision plane (tenant states, alert streams, SLO,
shed, the canonical flight journal) is BYTE-identical to the
synchronous engine of the same seed.  The synchronous engine stays the
parity oracle (``ANOMOD_SERVE_ASYNC_COMMIT=0``); only wall-time
attribution moves (the hidden wait lands on the ``commit_defer`` perf
leg, a consciously variant report field).

Tier-1 covers the parity core, the chaos-hook ordering across the
deferred commit (pre-mutation issue-side phases and the post-mutation
``commit`` case), elastic episodes landing mid-defer, and the env
contract; the exhaustive phase × shards × pipeline cross stays in the
supervise module.
"""

import dataclasses
from pathlib import Path

import numpy as np
import pytest

from anomod.obs.flight import diff_journals
from anomod.serve.engine import (SHARD_VARIANT_REPORT_FIELDS, ServeEngine,
                                 run_power_law)

#: the compact seeded scenario (the supervise-module idiom): 20 virtual
#: ticks, alerts firing, several checkpoints — every canonical plane
#: LIVE while commits are deferred
KW = dict(n_tenants=6, n_services=4, capacity_spans_per_s=1000,
          overload=2.0, duration_s=20, tick_s=1.0, seed=5,
          window_s=2.0, baseline_windows=4, fault_tenants=1,
          buckets=(64, 256), lane_buckets=(1, 2, 4), max_backlog=1500,
          n_windows=16, flight_digest_every=4, ckpt_every=4,
          flight=True)

#: report fields that legitimately differ between a synchronous and a
#: deferred-commit run of the same seed: the mode bit and its tick
#: count are CONFIG state (canonical on purpose — they differ exactly
#: when the config differs); every wall leg is already shard-variant
ASYNC_REPORT_FIELDS = ("async_commit", "async_ticks")


@pytest.fixture(scope="module")
def sync_ref():
    """ONE synchronous 2-shard pipelined reference run — the parity
    oracle every async leg in this module compares against."""
    eng, rep = run_power_law(shards=2, pipeline=2, async_commit=False,
                             **KW)
    return eng, rep, eng.flight_recorder.journal()


@pytest.fixture(scope="module")
def async_run():
    eng, rep = run_power_law(shards=2, pipeline=2, async_commit=True,
                             **KW)
    return eng, rep


def assert_async_parity(reference, eng, rep, extra_skip=()):
    """Byte-identical tenant states + alert streams, identical report
    decision fields, equal canonical flight journals (the supervise
    module's no-score-gap shape, crossed over the async seam)."""
    ref_eng, ref_rep, ref_journal = reference
    for tid in sorted(ref_eng._tenant_det):
        assert [dataclasses.asdict(a) for a in ref_eng.alerts_for(tid)] \
            == [dataclasses.asdict(a) for a in eng.alerts_for(tid)], \
            f"tenant {tid} alert stream diverges"
        s1 = ref_eng._tenant_replay[tid].state
        s2 = eng._tenant_replay[tid].state
        assert np.array_equal(np.asarray(s1.agg), np.asarray(s2.agg)), \
            f"tenant {tid} agg plane diverges"
        assert np.array_equal(np.asarray(s1.hist), np.asarray(s2.hist)), \
            f"tenant {tid} hist plane diverges"
    skip = set(SHARD_VARIANT_REPORT_FIELDS) | set(ASYNC_REPORT_FIELDS) \
        | set(extra_skip)
    a = {k: v for k, v in ref_rep.to_dict().items() if k not in skip}
    b = {k: v for k, v in rep.to_dict().items() if k not in skip}
    assert a == b, sorted(k for k in a if a[k] != b[k])
    d = diff_journals(ref_journal, eng.flight_recorder.journal())
    assert d is None, d


# ---------------------------------------------------------------------------
# the parity core
# ---------------------------------------------------------------------------

def test_async_commit_byte_parity(sync_ref, async_run):
    """The headline pin: the deferred-commit engine is byte-identical
    to the synchronous oracle on every decision plane, and actually
    ran deferred (every tick but the forced-sync checkpoint-cadence
    ones took the async tail)."""
    eng, rep = async_run
    assert rep.async_commit is True and sync_ref[1].async_commit is False
    assert rep.async_ticks > 0 and sync_ref[1].async_ticks == 0
    assert rep.commit_defer_wall_s >= 0.0
    assert_async_parity(sync_ref, eng, rep)


def test_async_commit_rerun_deterministic(async_run):
    """Same seed, same knob ⇒ same canonical journal bytes — the async
    engine is as rerun-deterministic as the oracle it mirrors."""
    eng, _ = async_run
    rerun, _ = run_power_law(shards=2, pipeline=2, async_commit=True,
                             **KW)
    assert rerun.flight_recorder.canonical_bytes() \
        == eng.flight_recorder.canonical_bytes()


def test_async_header_replays_resolved(async_run, sync_ref):
    """The flight header records the RESOLVED mode (the elastic-policy
    precedent): `anomod audit replay` re-executes the run dict as-is
    and must land on the same canonical bytes — and the header's
    engine block names the seam so forensics can see which tick
    structure produced a journal."""
    eng, _ = async_run
    h = eng.flight_recorder.header
    assert h["engine"]["async_commit"] is True
    assert sync_ref[0].flight_recorder.header["engine"]["async_commit"] \
        is False
    run = dict(h["run"])
    assert run["async_commit"] is True
    run["buckets"] = tuple(run["buckets"])
    run["lane_buckets"] = tuple(run["lane_buckets"])
    run.setdefault("flight", True)
    replay, _ = run_power_law(**run)
    assert replay.flight_recorder.canonical_bytes() \
        == eng.flight_recorder.canonical_bytes()


def test_mesh_refuses_explicit_async_commit(monkeypatch):
    """The mesh plane manages its own sharded dispatch: an EXPLICIT
    async_commit=True on a mesh engine is a hard error (the
    shards-on-mesh idiom), while an env-sourced knob degrades to the
    synchronous tick so exported globals never break mesh runs."""
    from anomod.config import Config, set_config
    from anomod.parallel import make_mesh
    from anomod.replay import ReplayConfig
    from anomod.serve import PowerLawTraffic
    traffic = PowerLawTraffic(n_tenants=2, total_rate_spans_per_s=100,
                              seed=0, n_services=4)
    cfg = ReplayConfig(n_services=4, n_windows=16, window_us=5_000_000,
                       chunk_size=512)
    with pytest.raises(ValueError, match="mesh"):
        ServeEngine(traffic.specs, traffic.services, cfg,
                    mesh=make_mesh(2), async_commit=True)
    monkeypatch.setenv("ANOMOD_SERVE_ASYNC_COMMIT", "1")
    set_config(Config())
    try:
        eng = ServeEngine(traffic.specs, traffic.services, cfg,
                          mesh=make_mesh(2))
        assert eng.async_commit is False
    finally:
        monkeypatch.delenv("ANOMOD_SERVE_ASYNC_COMMIT")
        set_config(Config())


# ---------------------------------------------------------------------------
# chaos-hook ordering across the deferred commit (satellite: the
# pre/post-mutation cases)
# ---------------------------------------------------------------------------

def test_chaos_hooks_fire_on_origin_tick_across_defer():
    """The injection-point contract: with commits deferred, the chaos
    phases still fire in the synchronous order and on the ORIGIN tick
    — ``stage``/``dispatch`` at issue time (pre-mutation), ``fold``/
    ``score``/``commit`` at the barrier (post-mutation), never keyed
    on the tick the barrier happens to land in.  Probed by recording
    every (phase, tick) hit through a live deferred run."""
    hits = []
    from anomod.serve import chaos as chaos_mod
    orig_hit = chaos_mod.ServeChaos.hit

    class _Recording(chaos_mod.ServeChaos):
        def hit(self, phase, tick, shard):
            hits.append((phase, tick, shard))
            return orig_hit(self, phase, tick, shard)

    import anomod.serve.engine as engine_mod
    orig_cls = chaos_mod.ServeChaos
    chaos_mod.ServeChaos = _Recording
    engine_orig = getattr(engine_mod, "ServeChaos", None)
    if engine_orig is not None:
        engine_mod.ServeChaos = _Recording
    try:
        # a stall is output-neutral: hooks fire, nothing recovers
        run_power_law(shards=1, chaos="stall@6:shard=0:ms=1",
                      async_commit=True, **KW)
    finally:
        chaos_mod.ServeChaos = orig_cls
        if engine_orig is not None:
            engine_mod.ServeChaos = engine_orig
    assert hits, "chaos hooks never consulted"
    by_tick = {}
    for phase, tick, shard in hits:
        by_tick.setdefault(tick, []).append(phase)
    # every scored tick ran the full synchronous phase order, keyed on
    # its OWN tick even though fold/score/commit fired one tick later
    full = [seq for seq in by_tick.values() if len(seq) >= 5]
    assert full, by_tick
    for seq in full:
        assert seq == ["stage", "dispatch", "fold", "commit"] or \
            seq[:2] == ["stage", "dispatch"] and seq[-1] == "commit", seq


def test_chaos_pre_mutation_issue_fault_recovers(sync_ref):
    """A dispatch-phase fault fires at ISSUE time (before any state
    mutation lands): the deferred tick fails inline, recovery restores
    + re-executes synchronously, and the run stays byte-identical to
    the fault-free oracle."""
    eng, rep = run_power_law(shards=2, pipeline=2,
                             chaos="crash@6:shard=0:phase=dispatch",
                             async_commit=True, **KW)
    assert rep.n_shard_crashes >= 1
    assert_async_parity(sync_ref, eng, rep,
                        extra_skip=("n_shard_crashes", "n_respawns",
                                    "n_restored_ticks"))


def test_chaos_post_mutation_commit_fault_recovers(sync_ref):
    """The post-mutation hard case: a ``commit``-phase fault fires at
    the BARRIER, after the deferred drain has already folded state
    deltas — one tick later in wall order than it was scripted.
    Recovery must key on the origin tick (a wrong key would re-trip
    the repeat=1 budget or skip the fault entirely) and restore the
    pre-mutation checkpoint, landing byte-identical to the oracle."""
    eng, rep = run_power_law(shards=2, pipeline=2,
                             chaos="except@9:shard=1:phase=commit",
                             async_commit=True, **KW)
    assert rep.n_shard_crashes >= 1 and rep.n_restored_ticks >= 1
    assert_async_parity(sync_ref, eng, rep,
                        extra_skip=("n_shard_crashes", "n_respawns",
                                    "n_restored_ticks"))


def test_chaos_every_phase_async_matches_sync_recovery(sync_ref):
    """The supervise module's five-phase campaign, re-run with commits
    deferred: the same scripted faults recover to the same bytes —
    the async seam adds no recovery divergence at ANY phase."""
    script = ("crash@6:shard=0:phase=dispatch;"
              "except@9:shard=1:phase=score;"
              "except@15:shard=1:phase=commit;"
              "crash@17:shard=0:phase=stage;"
              "stall@10:shard=0:ms=1")
    eng, rep = run_power_law(shards=2, pipeline=2, chaos=script,
                             async_commit=True, **KW)
    assert rep.n_shard_crashes == 4
    assert_async_parity(sync_ref, eng, rep,
                        extra_skip=("n_shard_crashes", "n_respawns",
                                    "n_restored_ticks"))


# ---------------------------------------------------------------------------
# elastic scaling landing mid-defer (satellite: PR-13 episodes stay
# deterministic under audit replay)
# ---------------------------------------------------------------------------

#: the policy-module surge scenario: sub-capacity base load, a 6x surge
#: forcing one scale-up and one scale-down inside the run
EL_KW = dict(n_tenants=6, n_services=4, capacity_spans_per_s=1000,
             overload=0.6, duration_s=24, tick_s=1.0, seed=5,
             window_s=5.0, baseline_windows=4, fault_tenants=0,
             buckets=(64, 256), lane_buckets=(1, 2, 4),
             max_backlog=1500, n_windows=16, flight_digest_every=4,
             flight=True)
SURGE = "surge@6:factor=6:ticks=6"


def _scaling_events(eng):
    return [ev for t in eng.flight_recorder.records()
            for ev in t.get("scaling", ())]


def test_elastic_episodes_mid_defer_deterministic():
    """Scale-up/down episodes landing while a commit is deferred: the
    policy executes AT the barrier (scale-down can never retire a
    runner with in-flight work), the episode schedule is identical to
    the synchronous policy run, and an `anomod audit replay` from the
    async run's header alone reproduces the canonical bytes."""
    e_sync, _ = run_power_law(shards=1, chaos=SURGE, policy="auto",
                              min_shards=1, max_shards=2,
                              cooldown_ticks=5, async_commit=False,
                              **EL_KW)
    e_async, rep = run_power_law(shards=1, chaos=SURGE, policy="auto",
                                 min_shards=1, max_shards=2,
                                 cooldown_ticks=5, async_commit=True,
                                 **EL_KW)
    events = _scaling_events(e_async)
    kinds = [ev["kind"] for ev in events]
    assert "scale_up" in kinds and "scale_down" in kinds
    assert events == _scaling_events(e_sync)
    assert e_async.flight_recorder.canonical_bytes() \
        == e_sync.flight_recorder.canonical_bytes()
    assert rep.async_ticks > 0
    # the audit-replay leg: the header run dict re-executes RESOLVED
    run = dict(e_async.flight_recorder.header["run"])
    assert run["async_commit"] is True and run["policy"] == "auto"
    run["buckets"] = tuple(run["buckets"])
    run["lane_buckets"] = tuple(run["lane_buckets"])
    replay, _ = run_power_law(**run)
    assert _scaling_events(replay) == events
    assert replay.flight_recorder.canonical_bytes() \
        == e_async.flight_recorder.canonical_bytes()


# ---------------------------------------------------------------------------
# env contract (satellite: garbage values raise, knobs covered)
# ---------------------------------------------------------------------------

def test_async_env_knobs_registered_and_validated(monkeypatch):
    from anomod.config import Config
    monkeypatch.delenv("ANOMOD_SERVE_ASYNC_COMMIT", raising=False)
    monkeypatch.delenv("ANOMOD_SERVE_NATIVE_DRAIN", raising=False)
    cfg = Config()
    assert cfg.serve_async_commit is False       # sync stays the oracle
    assert cfg.serve_native_drain == "auto"

    for tok in ("1", "on", "true", "YES"):
        monkeypatch.setenv("ANOMOD_SERVE_ASYNC_COMMIT", tok)
        assert Config().serve_async_commit is True
    for tok in ("0", "off", "false", "no", ""):
        monkeypatch.setenv("ANOMOD_SERVE_ASYNC_COMMIT", tok)
        assert Config().serve_async_commit is False
    # garbage RAISES — the knob flips the whole tick structure, so a
    # typo must fail at config construction, not serve synchronously
    for bad in ("treu", "2", "banana", "async"):
        monkeypatch.setenv("ANOMOD_SERVE_ASYNC_COMMIT", bad)
        with pytest.raises(ValueError,
                           match="ANOMOD_SERVE_ASYNC_COMMIT"):
            Config()
    monkeypatch.delenv("ANOMOD_SERVE_ASYNC_COMMIT")

    for tok, want in (("auto", "auto"), ("1", "on"), ("on", "on"),
                      ("0", "off"), ("OFF", "off")):
        monkeypatch.setenv("ANOMOD_SERVE_NATIVE_DRAIN", tok)
        assert Config().serve_native_drain == want
    for bad in ("fast", "numpy", "2", "native"):
        monkeypatch.setenv("ANOMOD_SERVE_NATIVE_DRAIN", bad)
        with pytest.raises(ValueError,
                           match="ANOMOD_SERVE_NATIVE_DRAIN"):
            Config()


def test_drain_engine_ctor_validates():
    """The AdmissionController mirror of the env contract: an explicit
    garbage ``drain_engine=`` fails loudly at construction."""
    from anomod.serve import AdmissionController, TenantSpec
    specs = [TenantSpec(tenant_id=0, name="t0", priority=0)]
    with pytest.raises(ValueError, match="drain_engine"):
        AdmissionController(specs, max_backlog=100,
                            drain_engine="banana")
    for mode in ("auto", "on", "off"):
        adm = AdmissionController(specs, max_backlog=100,
                                  drain_engine=mode)
        assert adm.drain_engine in ("heap", "numpy", "native")


def test_async_knobs_env_contract_covered():
    """Every new ISSUE-16 knob is in the validated Config contract
    (check_env_contract green — the CI-gate clause)."""
    import sys as _sys
    _sys.path.insert(0, str(Path(__file__).parent.parent / "scripts"))
    try:
        import check_env_contract as cec
    finally:
        _sys.path.pop(0)
    refs = cec.referenced_vars(Path(cec.ROOT))
    corpus = cec.covered_vars(Path(cec.ROOT))
    for knob in ("ANOMOD_SERVE_ASYNC_COMMIT",
                 "ANOMOD_SERVE_NATIVE_DRAIN"):
        assert knob in refs and knob in corpus


def test_report_carries_async_fields(sync_ref):
    """The report names the seam: the mode bit, how many ticks ran
    deferred, and the (variant) hidden-wait wall — and the variant
    list covers ONLY the wall, so the mode stays parity-checked."""
    d = sync_ref[1].to_dict()
    assert d["async_commit"] is False and d["async_ticks"] == 0
    assert "commit_defer_wall_s" in d
    assert "commit_defer_wall_s" in SHARD_VARIANT_REPORT_FIELDS
    assert "async_commit" not in SHARD_VARIANT_REPORT_FIELDS
    assert "async_ticks" not in SHARD_VARIANT_REPORT_FIELDS


# ---------------------------------------------------------------------------
# CLI contract
# ---------------------------------------------------------------------------

def test_cli_async_flag_conflicts():
    from anomod.cli import main
    base = ["serve", "--tenants", "2", "--duration", "1"]
    with pytest.raises(SystemExit):      # contradiction
        main(base + ["--async-commit", "--no-async-commit"])
    with pytest.raises(SystemExit):      # mesh runs synchronous
        main(base + ["--devices", "1", "--async-commit"])
