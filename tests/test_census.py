"""Fleet census observatory (anomod.obs.census): read-side byte-parity,
deterministic census streams, hot-set shard invariance, pool-bytes
reconciliation, the registered-fleet probe, the census diff judge, and
the scrape-path export of the census gauges."""

import dataclasses
import json

import numpy as np
import pytest

from anomod.obs.census import (CENSUS_PLANES, collect_resident_bytes,
                               diff_census, fit_slope, fit_zipf,
                               fleet_probe, plane_nbytes,
                               pool_slot_nbytes,
                               process_resident_bytes,
                               span_batch_nbytes)
from anomod.serve.engine import run_power_law

#: the tiny seeded run every engine-level census pin shares (window 2 s
#: so the scripted fault fires inside the run — the alert stream is
#: LIVE, not vacuously equal)
KW = dict(n_tenants=5, n_services=4, capacity_spans_per_s=1000,
          overload=2.0, duration_s=20, tick_s=1.0, seed=9,
          window_s=2.0, baseline_windows=4, fault_tenants=2,
          buckets=(64, 256), lane_buckets=(1, 2, 4), max_backlog=1500,
          n_windows=16, shards=1, pipeline=2)


def _census_stream(eng):
    """The journal's census variant stream (census ticks only),
    serialized deterministically — the byte-equality surface."""
    docs = [rec["census"] for rec in eng.flight_recorder.records()
            if rec["census"]["planes"]]
    return json.dumps(docs, sort_keys=True, separators=(",", ":"))


@pytest.fixture(scope="module")
def census_pair():
    eng_off, rep_off = run_power_law(**KW)
    eng_on, rep_on = run_power_law(census=True, census_every=4, **KW)
    return eng_off, rep_off, eng_on, rep_on


# ---------------------------------------------------------------------------
# the read-side contract + determinism pins
# ---------------------------------------------------------------------------

def test_census_read_side_byte_parity(census_pair):
    """Census on/off leaves every decision byte-identical: per-tenant
    alert streams, replay states, SLO quantiles, shed, and the
    CANONICAL flight journal."""
    eng_off, rep_off, eng_on, rep_on = census_pair
    assert rep_on.n_alerts > 0            # the pin is live, not vacuous
    assert rep_off.census_enabled is False and rep_on.census_enabled
    for tid in eng_off._tenant_det:
        assert [dataclasses.asdict(a) for a in eng_off.alerts_for(tid)] \
            == [dataclasses.asdict(a) for a in eng_on.alerts_for(tid)]
        s1 = eng_off._tenant_replay[tid].state
        s2 = eng_on._tenant_replay[tid].state
        assert np.array_equal(np.asarray(s1.agg), np.asarray(s2.agg))
        assert np.array_equal(np.asarray(s1.hist), np.asarray(s2.hist))
    assert rep_off.latency == rep_on.latency
    assert rep_off.shed_fraction == rep_on.shed_fraction
    assert eng_off.flight_recorder.canonical_bytes() \
        == eng_on.flight_recorder.canonical_bytes()


def test_census_off_report_fields_empty(census_pair):
    _, rep_off, _, _ = census_pair
    assert rep_off.census_ticks == 0
    assert rep_off.census_hot_set == {}
    assert rep_off.census_resident_bytes == {}


def test_census_stream_byte_equal_across_reruns(census_pair):
    """Same seed ⇒ the census VARIANT stream is byte-equal across
    reruns — unlike walls/perf, census records carry no wall clocks."""
    _, _, eng_on, _ = census_pair
    eng2, _ = run_power_law(census=True, census_every=4, **KW)
    assert _census_stream(eng_on) == _census_stream(eng2)


def test_census_hot_set_invariant_across_shards(census_pair):
    """The hot-set/Zipf census derives from coordinator admission
    decisions alone: a 2-shard census-on run reports the SAME
    census_hot_set and census_ticks as the 1-shard run (the canonical
    half of the census report; resident bytes are consciously
    variant)."""
    _, _, eng_on, rep_on = census_pair
    kw = dict(KW)
    kw["shards"] = 2
    eng2, rep2 = run_power_law(census=True, census_every=4, **kw)
    assert rep2.census_hot_set == rep_on.census_hot_set
    assert rep2.census_ticks == rep_on.census_ticks
    # resident bytes exist on both, and the 2-shard run censuses
    # per-shard pool/scratch planes for BOTH shards
    doc = [rec["census"] for rec in eng2.flight_recorder.records()
           if rec["census"]["planes"]][-1]
    pool_shards = {p["shard"] for p in doc["planes"]
                   if p["plane"] == "pool"}
    assert pool_shards == {0, 1}
    # the canonical report surface stays equal (the fan-out contract,
    # census-on this time)
    from anomod.serve.engine import SHARD_VARIANT_REPORT_FIELDS
    a = {k: v for k, v in rep_on.to_dict().items()
         if k not in SHARD_VARIANT_REPORT_FIELDS}
    b = {k: v for k, v in rep2.to_dict().items()
         if k not in SHARD_VARIANT_REPORT_FIELDS}
    assert a == b


def test_census_planes_schema_and_reconciliation(census_pair):
    """Per-(shard, plane) records drain in (shard, plane) order; the
    pool total reconciles EXACTLY with (capacity + 1) × per-slot
    nbytes; the by_plane totals sum to the census total."""
    _, _, eng_on, rep_on = census_pair
    docs = [rec["census"] for rec in eng_on.flight_recorder.records()
            if rec["census"]["planes"]]
    assert len(docs) == rep_on.census_ticks
    last = docs[-1]
    order = [(p["shard"], p["plane"]) for p in last["planes"]]
    assert order == sorted(order)
    # CENSUS_PLANES is the one plane inventory: this RCA-off,
    # tiering-off run emits exactly the other planes, and nothing
    # outside the inventory
    assert {p["plane"] for p in last["planes"]} \
        == set(CENSUS_PLANES) - {"rca", "tier"}
    assert last["pool_reconciled"] is True
    by_plane = {}
    for p in last["planes"]:
        by_plane[p["plane"]] = by_plane.get(p["plane"], 0) + p["bytes"]
    assert last["total_bytes"] == sum(by_plane.values())
    pool = [p for p in last["planes"] if p["plane"] == "pool"][0]
    assert pool["mode"] == "device"
    assert pool["bytes"] == (pool["capacity"] + 1) * pool["slot_bytes"]
    assert pool["slot_bytes"] == pool_slot_nbytes(eng_on.cfg)
    assert 0 < pool["slots_used"] <= pool["capacity"]
    adm = [p for p in last["planes"] if p["plane"] == "admission"][0]
    assert adm["registered"] == KW["n_tenants"]
    # report mirror
    rb = rep_on.census_resident_bytes
    assert rb["total"] == last["total_bytes"]
    assert rb["pool_reconciled"] is True
    assert rb["peak_total"] >= rb["total"]
    # hot-set doc sanity
    hs = rep_on.census_hot_set
    assert hs["registered"] == KW["n_tenants"]
    assert 0 < hs["ever_served"] <= hs["registered"]
    assert hs["resident"] == len(eng_on._tenant_replay)
    assert all(v <= hs["ever_served"]
               for v in hs["hot_by_decay"].values())
    ticks = [c["last_served_tick"] for c in hs["coldest"]]
    assert ticks == sorted(ticks)          # coldest first


def test_census_survives_elastic_scaling():
    """An elastic census-on run (scale 1→2→1 under a scripted surge)
    keeps censusing through the topology changes — per-shard planes
    appear for the appended shard — and its hot-set census equals the
    static run's (scaling moves capacity, never an admission
    decision)."""
    kw = dict(n_tenants=6, n_services=4, capacity_spans_per_s=1000,
              overload=0.6, duration_s=24, tick_s=1.0, seed=5,
              window_s=5.0, baseline_windows=4, fault_tenants=0,
              buckets=(64, 256), lane_buckets=(1, 2, 4),
              max_backlog=1500, n_windows=16,
              flight_digest_every=4, chaos="surge@6:factor=6:ticks=6")
    eng_s, rep_s = run_power_law(shards=1, census=True, census_every=4,
                                 **kw)
    eng_e, rep_e = run_power_law(shards=1, policy="auto", min_shards=1,
                                 max_shards=2, cooldown_ticks=3,
                                 census=True, census_every=4, **kw)
    assert rep_e.n_scale_ups >= 1 and rep_e.n_scale_downs >= 1
    assert rep_e.census_ticks == rep_s.census_ticks
    assert rep_e.census_hot_set == rep_s.census_hot_set
    docs = [rec["census"] for rec in eng_e.flight_recorder.records()
            if rec["census"]["planes"]]
    peak_shards = max(max(p["shard"] for p in d["planes"]
                          if p["plane"] == "pool") for d in docs)
    assert peak_shards == 1            # shard 1 was censused at peak
    assert all(d["pool_reconciled"] is True for d in docs)


def test_census_audit_replay_byte_equal():
    """`anomod audit replay` of a census-on journal re-records the
    SAME census stream: the census knobs ride the flight header
    resolved, and the stream carries no wall clock."""
    kw = dict(KW)
    kw["duration_s"] = 12.0
    eng, _ = run_power_law(census=True, census_every=4, **kw)
    run = dict(eng.flight_recorder.header["run"])
    assert run["census"] is True and run["census_every"] == 4
    run["buckets"] = tuple(run["buckets"])
    run["lane_buckets"] = tuple(run["lane_buckets"])
    eng2, _ = run_power_law(**run)
    assert _census_stream(eng) == _census_stream(eng2)


# ---------------------------------------------------------------------------
# byte-accounting helpers
# ---------------------------------------------------------------------------

def test_span_batch_nbytes_exact():
    """The O(1) fixed-width fast path equals the per-array sum — the
    pin that keeps SPAN_ROW_BYTES honest against the real schema."""
    from anomod import labels, synth
    batch = synth.generate_spans(labels.ALL_LABELS[0], n_traces=5)
    want = sum(arr.nbytes for arr in (
        batch.trace, batch.parent, batch.service, batch.endpoint,
        batch.start_us, batch.duration_us, batch.is_error,
        batch.status, batch.kind))
    assert span_batch_nbytes(batch) == want
    assert want == batch.n_spans * 36      # the schema's 36 B/span


def test_pool_reconciliation_survives_growth():
    """The (capacity + 1) × per-slot pin holds through pool doubling
    (growth concatenates zero rows — the shape algebra must follow)."""
    from anomod.replay import TenantStatePool
    from anomod.serve.engine import serve_plane_cfg
    cfg = serve_plane_cfg(4, 5.0, 8)
    pool = TenantStatePool(cfg, capacity=2)
    for _ in range(6):
        pool.acquire()                     # forces two doublings
    got = plane_nbytes(pool.agg) + plane_nbytes(pool.hist)
    assert got == (pool.capacity + 1) * pool_slot_nbytes(cfg)
    assert pool.capacity >= 6


def test_process_resident_bytes_informational():
    got = process_resident_bytes()
    assert got is None or got > 0


# ---------------------------------------------------------------------------
# estimators
# ---------------------------------------------------------------------------

def test_fit_zipf_recovers_alpha():
    alpha = 1.3
    counts = [int(1e6 / r ** alpha) for r in range(1, 200)]
    got = fit_zipf(counts)
    assert got is not None and abs(got - alpha) < 0.05
    assert fit_zipf([5, 3]) is None        # below 3 points: no fit
    assert fit_zipf([]) is None


def test_fit_slope_linear():
    xs = [1000, 10000, 100000]
    ys = [3e-6 * x + 0.25 for x in xs]
    slope, icpt = fit_slope(xs, ys)
    assert abs(slope - 3e-6) < 1e-12
    assert abs(icpt - 0.25) < 1e-9


# ---------------------------------------------------------------------------
# the registered-fleet probe (cost attribution)
# ---------------------------------------------------------------------------

def test_fleet_probe_scales_with_registered():
    doc = fleet_probe(sizes=(50, 200, 800), hot=20, ticks=4, seed=0)
    assert doc["sizes"] == [50, 200, 800]
    assert len(doc["rows"]) == 3
    by_size = [r["resident_bytes"] for r in doc["rows"]]
    # resident bytes grow strictly with the REGISTERED count even
    # though only 20 tenants ever offer a span — the O(registered)
    # baseline the tiering refactor must flatten
    assert by_size[0] < by_size[1] < by_size[2]
    assert all(r["hot"] == 20 for r in doc["rows"])
    assert all(r["pool_reconciled"] is True for r in doc["rows"])
    assert doc["bytes_slope_per_registered"] > 0
    assert np.isfinite(doc["wall_slope_s_per_registered"])
    assert all(r["median_tick_wall_s"] > 0 for r in doc["rows"])
    # zero measured ticks would fit a slope over NaN walls: refused
    with pytest.raises(ValueError):
        fleet_probe(sizes=(50, 200), hot=10, ticks=0)


# ---------------------------------------------------------------------------
# `anomod census diff` — the before/after judge
# ---------------------------------------------------------------------------

def _capture(pool=1000, sweep_bytes=3.5, wall=2e-7):
    return {"census": {
        "resident_bytes": {"total": pool + 500,
                           "by_plane": {"pool": pool, "slo": 500}},
        "sweep": {"sizes": [1000, 100000], "hot": 50,
                  "bytes_slope_per_registered": sweep_bytes,
                  "wall_slope_s_per_registered": wall,
                  "wall_intercept_s": 0.04}}}


def test_diff_census_identical_ok():
    doc = diff_census(_capture(), _capture(), tolerance=0.35)
    assert doc["status"] == "ok"
    assert doc["bytes_regressions"] == []
    assert doc["slope_regressions"] == []
    assert doc["sweep_comparable"] is True


def test_diff_census_flags_byte_growth_exactly():
    doc = diff_census(_capture(pool=1000), _capture(pool=1001),
                      tolerance=0.35)
    assert doc["status"] == "bytes-regression"
    assert doc["bytes_regressions"][0]["plane"] == "pool"
    assert doc["bytes_regressions"][0]["delta"] == 1
    # shrinkage (the tiering win) is never a regression
    doc = diff_census(_capture(pool=1000), _capture(pool=10),
                      tolerance=0.35)
    assert doc["status"] == "ok"


def test_diff_census_wall_slope_tolerance():
    # within the noise tolerance: ok
    doc = diff_census(_capture(wall=2e-7), _capture(wall=2.4e-7),
                      tolerance=0.35)
    assert doc["status"] == "ok"
    # a 3x wall-slope regression clears any sane tolerance: flagged
    doc = diff_census(_capture(wall=2e-7), _capture(wall=6e-7),
                      tolerance=0.35)
    assert doc["status"] == "slope-regression"
    assert doc["slope_regressions"][0]["slope"] == \
        "wall_slope_s_per_registered"
    # the BYTES slope is deterministic: any growth flags, exactly
    doc = diff_census(_capture(sweep_bytes=3.5),
                      _capture(sweep_bytes=3.6), tolerance=0.35)
    assert doc["status"] == "slope-regression"
    assert doc["slope_regressions"][0]["exact"] is True


def test_diff_census_flat_baseline_still_guards():
    """THE post-tiering scenario: once the baseline wall slope sits at
    ~0 (or dips negative from the fit), a pure ratio test would never
    flag O(registered) cost creeping back — the scale-aware floor
    (tolerance × A's intercept at the sweep's top size) must."""
    for base in (0.0, -1e-8):
        doc = diff_census(_capture(wall=base), _capture(wall=5e-6),
                          tolerance=0.35)
        assert doc["status"] == "slope-regression", base
    # slope noise on a genuinely-flat curve stays under the floor
    doc = diff_census(_capture(wall=0.0), _capture(wall=1e-8),
                      tolerance=0.35)
    assert doc["status"] == "ok"


def test_diff_census_missing_block_and_shape_mismatch():
    doc = diff_census({"metric": "x"}, _capture())
    assert doc["status"] == "census-missing"
    assert doc["missing_in"] == ["a"]
    # mismatched sweep shapes: slope rows become informational, never
    # a verdict
    b = _capture(wall=9e-7)
    b["census"]["sweep"]["sizes"] = [100, 2000]
    doc = diff_census(_capture(), b, tolerance=0.35)
    assert doc["sweep_comparable"] is False
    assert doc["status"] == "ok" and doc["notes"]


# ---------------------------------------------------------------------------
# scrape-path export (satellite: gauges flow through selfscrape/export)
# ---------------------------------------------------------------------------

def test_census_gauges_flow_through_scrape_paths(tmp_path):
    """The census gauges ride the registry scrape journal end to end:
    Prometheus text names them, the TT-CSV export round-trips them,
    and the self-scrape metric→span mapping files them under a
    ``census`` subsystem."""
    from anomod.io.metrics import load_tt_metric_csv
    from anomod.obs.export import export_tt_csv, to_prometheus_text
    from anomod.obs.registry import Registry, set_registry, subsystem_of
    from anomod.obs.selfscrape import spans_from_metrics
    assert subsystem_of("anomod_census_resident_bytes") == "census"
    reg = Registry(enabled=True)
    prev = set_registry(reg)
    try:
        kw = dict(KW)
        kw["duration_s"] = 10.0
        run_power_law(census=True, census_every=4, **kw)
    finally:
        set_registry(prev)
    text = to_prometheus_text(reg)
    for name in ("anomod_census_resident_bytes",
                 "anomod_census_pool_bytes",
                 "anomod_census_registered_tenants",
                 "anomod_census_ticks_total"):
        assert name in text
    csv = tmp_path / "census_scrape.csv"
    n = export_tt_csv(reg, csv)
    assert n > 0
    batch = load_tt_metric_csv(csv)
    assert any(m.startswith("anomod_census_")
               for m in batch.metric_names)
    spans = spans_from_metrics(batch)
    assert "census" in spans.services


# ---------------------------------------------------------------------------
# knob validation + CLI
# ---------------------------------------------------------------------------

def test_census_knob_validation(monkeypatch):
    from anomod.config import Config
    for var, bad in (("ANOMOD_CENSUS_EVERY", "0"),
                     ("ANOMOD_CENSUS_EVERY", "x"),
                     ("ANOMOD_CENSUS_DECAY_TICKS", "16,4"),
                     ("ANOMOD_CENSUS_DECAY_TICKS", "a,b"),
                     ("ANOMOD_CENSUS_SWEEP", "1000"),
                     ("ANOMOD_CENSUS_SWEEP", "1000,1000"),
                     ("ANOMOD_CENSUS_COLDEST_K", "-1")):
        monkeypatch.setenv(var, bad)
        with pytest.raises(ValueError):
            Config()
        monkeypatch.delenv(var)
    monkeypatch.setenv("ANOMOD_CENSUS", "1")
    monkeypatch.setenv("ANOMOD_CENSUS_EVERY", "16")
    monkeypatch.setenv("ANOMOD_CENSUS_DECAY_TICKS", "2,8")
    monkeypatch.setenv("ANOMOD_CENSUS_SWEEP", "100,200")
    monkeypatch.setenv("ANOMOD_CENSUS_COLDEST_K", "3")
    cfg = Config()
    assert cfg.census is True and cfg.census_every == 16
    assert cfg.census_decay_ticks == (2, 8)
    assert cfg.census_sweep == (100, 200)
    assert cfg.census_coldest_k == 3


def test_census_engine_rejects_bad_cadence():
    with pytest.raises(ValueError):
        run_power_law(census=True, census_every=0, **KW)


def test_census_cli_record_probe_diff(tmp_path, capsys):
    from anomod.cli import main
    out = tmp_path / "census.json"
    rc = main(["census", "record", "--out", str(out), "--tenants", "5",
               "--duration", "8", "--capacity", "800", "--tick", "1.0",
               "--every", "4"])
    assert rc == 0
    line = json.loads(capsys.readouterr().out)
    assert line["census_ticks"] >= 1
    assert line["pool_reconciled"] is True
    doc = json.loads(out.read_text())
    assert doc["census_format"] == 1
    assert doc["stream"] and all(d["planes"] for d in doc["stream"])
    rc = main(["census", "probe", "--sizes", "40,160", "--hot", "10",
               "--ticks", "3"])
    assert rc == 0
    probe = json.loads(capsys.readouterr().out)
    assert probe["sweep"]["sizes"] == [40, 160]
    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    a.write_text(json.dumps(_capture()))
    b.write_text(json.dumps(_capture()))
    assert main(["census", "diff", str(a), str(b)]) == 0
    capsys.readouterr()
    b.write_text(json.dumps(_capture(pool=2000)))
    assert main(["census", "diff", str(a), str(b)]) == 1
    capsys.readouterr()
    b.write_text(json.dumps({"metric": "x"}))
    assert main(["census", "diff", str(a), str(b)]) == 2
    capsys.readouterr()
    # mode-mismatched flags fail loud
    with pytest.raises(SystemExit):
        main(["census", "diff", str(a), str(b), "--out", "x.json"])
    capsys.readouterr()
    with pytest.raises(SystemExit):
        main(["census", "record", "--out", str(out), "--sizes", "1,2"])
    capsys.readouterr()
    with pytest.raises(SystemExit):
        main(["census", "probe", "--tolerance", "0.5"])
    capsys.readouterr()
    with pytest.raises(SystemExit):       # record-only flag on probe
        main(["census", "probe", "--duration", "120"])
    capsys.readouterr()
    with pytest.raises(SystemExit):       # probe-only flag on diff
        main(["census", "diff", str(a), str(b), "--hot", "5"])
    capsys.readouterr()
    with pytest.raises(SystemExit):       # ticks must measure
        main(["census", "probe", "--ticks", "0"])
    capsys.readouterr()
