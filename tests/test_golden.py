"""Pin the committed golden report (docs/GOLDEN_REPORT.md) to the real
reference checkout: the census numbers and the report's central claims are
re-derived from the actual trees, so the committed document cannot drift
from the data it describes.  Skips cleanly when the checkout is absent."""

from pathlib import Path

import pytest

REFERENCE = Path("/root/reference")
REPORT = Path(__file__).parent.parent / "docs" / "GOLDEN_REPORT.md"

pytestmark = pytest.mark.skipif(
    not (REFERENCE / "TT_data").is_dir(),
    reason="reference checkout not mounted")


def _cfg():
    """Pin the data root to the reference checkout the skipif guards —
    an ANOMOD_DATA_ROOT override must not redirect these assertions."""
    from anomod.config import Config
    return Config(data_root=REFERENCE)


def test_census_counts_match_committed_report():
    from anomod.golden import _count_files

    sn_cov = _count_files(REFERENCE / "SN_data" / "coverage_data")
    tt_cov = _count_files(REFERENCE / "TT_data" / "coverage_report")
    assert sn_cov == {"n_files": 8544, "n_lfs_stubs": 0, "n_real": 8544}
    assert tt_cov["n_files"] == 28041
    # the 533 stubs are exactly the 13 x 41 coverage.xml payloads; every
    # coverage-summary.txt is real
    assert tt_cov["n_lfs_stubs"] == 533
    text = REPORT.read_text()
    assert "| coverage_data | 8544 | 0 | 8544 |" in text
    assert "| coverage_report | 28041 | 533 | 27508 |" in text


def test_real_coverage_loads_for_all_experiments():
    """Both coverage trees load through the typed loaders for every one of
    the 13 experiments (the report's real_loads coverage=13 rows)."""
    from anomod.golden import _try_load
    from anomod.io import dataset

    for tb in ("SN", "TT"):
        eds = dataset.discover(tb, _cfg())
        assert len(eds) == 13
        with_cov = [e for e in eds if "coverage" in e.dirs]
        assert len(with_cov) == 13
        # one full load per testbed proves the loader path; the golden CLI
        # run loads all 26 (census pinned above keeps this cheap in CI)
        cb = _try_load(tb, "coverage", with_cov[0].dirs["coverage"])
        assert cb is not None and len(cb.services) >= 10


def test_tt_real_coverage_is_experiment_invariant():
    """The committed report's headline TT finding: the shipped
    coverage-summary artifacts are IDENTICAL across experiments — zero
    per-experiment signal in the real TT coverage modality."""
    from anomod.io.coverage import load_tt_coverage_report

    dirs = sorted((REFERENCE / "TT_data" / "coverage_report").iterdir())
    dirs = [d for d in dirs if d.is_dir()]
    a = load_tt_coverage_report(dirs[1])
    b = load_tt_coverage_report(dirs[5])
    ra = dict(zip(a.services, a.service_ratio()))
    rb = dict(zip(b.services, b.service_ratio()))
    assert set(ra) == set(rb) and len(ra) == 41
    assert all(abs(ra[s] - rb[s]) <= 1e-12 for s in ra)
    assert "carries no culprit signal" in REPORT.read_text()


def test_sn_coverage_detection_matches_committed_report():
    """The round-5 result: the three Code_Stop culprits are identified by
    ARTIFACT ABSENCE (each is the one service missing from its own
    experiment's coverage tree — a stopped binary cannot flush gcov
    counters), and Svc_Kill_SocialGraph self-attributes through its
    unique non-repeated delta: top-1 1.0 over the 4 scored faults, up
    from 0.25 under raw |delta| ranking (the round-4 shared-top-delta
    artifact was deterministic pipeline blast, now discounted)."""
    from anomod.golden import coverage_signal

    r = coverage_signal("SN", _cfg())
    assert r["scored"] == 4
    assert r["top1"] == 1.0
    assert r["n_absent_artifacts"] == 3
    rows = {e["experiment"]: e for e in r["experiments"]}
    for stop in ("Code_Stop_MediaService", "Code_Stop_TextService",
                 "Code_Stop_UserService"):
        assert rows[stop]["top1_hit"], stop
        assert rows[stop]["top3"][0].get("absent") is True
    assert rows["Svc_Kill_SocialGraph"]["top1_hit"]
    assert "absent" not in rows["Svc_Kill_SocialGraph"]["top3"][0]
    text = REPORT.read_text()
    assert "3 culprits identified by artifact absence" in text


def test_sn_log_detection_matches_committed_report():
    """The committed log-modality result: 6 scored faults, all hit.
    Kills hit through the unique-mover volume channel (a ~0.2% line-count
    dip at exactly the killed service in an otherwise bit-frozen
    cumulative log plane); Code_Stop culprits hit through the ABSENCE
    tier — their summary.txt literally records "no log file found" for
    the stopped service, so it has no countable row at all."""
    from anomod.golden import log_signal

    r = log_signal("SN", _cfg())
    assert r["scored"] == 6
    assert r["top1"] == 1.0
    rows = {e["experiment"]: e for e in r["experiments"]}
    for kill in ("Svc_Kill_Media", "Svc_Kill_SocialGraph",
                 "Svc_Kill_UserTimeline"):
        assert rows[kill]["top1_hit"], kill
        assert "absent" not in rows[kill]["top3"][0]
    for stop in ("Code_Stop_MediaService", "Code_Stop_TextService",
                 "Code_Stop_UserService"):
        assert rows[stop]["top1_hit"], stop
        assert rows[stop]["top3"][0].get("absent") is True
    text = REPORT.read_text()
    assert "top-1 1.0, top-3 1.0 over 6 scored faults" in text


def test_tt_logs_are_fully_stubbed():
    """TT log_data carries no real content in the shipped checkout — the
    log-modality section must say 0 loaded, not fabricate rows from
    zero-line stub parses."""
    from anomod.golden import log_signal

    r = log_signal("TT", _cfg())
    assert r["n_loaded"] == 0
    assert r.get("scored") in (None, 0)


def test_sn_real_coverage_carries_signal():
    """SN gcov coverage DOES vary per experiment (max |delta| ~0.089 in
    the committed run) — the modality is weak but real there."""
    from anomod.golden import _try_load
    from anomod.io import dataset

    eds = {e.name: e for e in dataset.discover("SN", _cfg())}
    normal = _try_load("SN", "coverage",
                       eds["Normal_Baseline"].dirs["coverage"])
    fault = _try_load("SN", "coverage",
                      eds["Code_Stop_TextService"].dirs["coverage"])
    rn = dict(zip(normal.services, normal.service_ratio()))
    rf = dict(zip(fault.services, fault.service_ratio()))
    deltas = [abs(rf[s] - rn[s]) for s in rf if s in rn]
    assert max(deltas) > 0.05
    assert "real per-experiment signal present" in REPORT.read_text()
