"""Fault-injection subsystem: CRD roundtrip, blade argv, lifecycle."""

import json

import pytest

from anomod import chaos, labels


def test_mesh_crd_roundtrip_all():
    for exp in chaos.mesh_experiments():
        label = labels.label_for(exp)
        doc = chaos.build_mesh_crd(label)
        assert doc["apiVersion"] == "chaos-mesh.org/v1alpha1"
        meta = doc["metadata"]["labels"]
        assert meta["anomaly_level"] == label.anomaly_level
        assert meta["anomaly_type"] == label.anomaly_type
        back = chaos.parse_mesh_crd_yaml(chaos.mesh_crd_yaml(exp))
        assert back == label


def test_mesh_covers_every_tt_chaosmesh_label():
    want = {l.experiment for l in labels.TT_LABELS if l.chaos_tool == "chaosmesh"}
    assert want == set(chaos.mesh_experiments())


def test_mesh_crd_shapes():
    cpu = chaos.build_mesh_crd("Lv_P_CPU_preserve")
    assert cpu["kind"] == "StressChaos"
    assert cpu["spec"]["stressors"]["cpu"] == {"workers": 2, "load": 80}
    assert cpu["spec"]["selector"]["labelSelectors"]["app"] == "ts-preserve-service"

    kill = chaos.build_mesh_crd("Lv_S_KILLPOD_preserve")
    assert kill["kind"] == "Schedule"
    assert kill["spec"]["schedule"] == "@every 3s"
    assert kill["spec"]["podChaos"]["action"] == "pod-kill"
    # Schedule nests selector/mode inside podChaos, not at spec level
    assert "selector" not in kill["spec"] and "mode" not in kill["spec"]

    http = chaos.build_mesh_crd("Lv_S_HTTPABORT_preserve")
    assert http["spec"]["abort"] is True
    assert http["spec"]["replace"]["code"] == 503
    assert http["spec"]["value"] == "70"

    delay = chaos.build_mesh_crd("Lv_D_TRANSACTION_timeout")
    assert delay["spec"]["delay"]["latency"] == "15s"
    assert delay["spec"]["direction"] == "to"

    pool = chaos.build_mesh_crd("Lv_D_CONNECTION_POOL_exhaustion")
    assert pool["spec"]["direction"] == "from"
    sel = pool["spec"]["target"]["selector"]["expressionSelectors"][0]
    assert "ts-order-service" in sel["values"]


def test_blade_commands_sn():
    cpu = chaos.blade_create_command("Perf_CPU_Contention")
    assert cpu.args[:3] == ("create", "cpu", "load") and not cpu.needs_sudo

    net = chaos.blade_create_command("Perf_Network_Loss")
    assert net.needs_sudo and "docker0" in net.args

    kill = chaos.blade_create_command("Svc_Kill_Media")
    assert "MediaService" in kill.args and "--signal" in kill.args

    redis = chaos.blade_create_command("DB_Redis_CacheLimit_HomeTimeline")
    assert any("home-timeline-redis" in a for a in redis.args)

    # code-level SN faults are docker stop, not blade
    assert chaos.blade_create_command("Code_Stop_UserService") is None
    assert chaos.docker_command("Code_Stop_UserService") == (
        "docker", "stop", "socialnetwork_user-service_1")


def test_blade_commands_tt_jvm():
    sec = chaos.blade_create_command("Lv_C_security_check")
    assert sec.k8s and "container-jvm" in sec.args and "return" in sec.args
    assert "security.service.SecurityServiceImpl" in sec.args

    exc = chaos.blade_create_command("Lv_C_exception_injection")
    assert "throwCustomException" in exc.args
    assert "CHAOS_EXCEPTION_INJECTION" in exc.args

    trv = chaos.blade_create_command("Lv_C_travel_detail_failure")
    assert "getTripAllDetailInfo" in trv.args


def test_parse_blade_output_formats():
    assert chaos.parse_blade_output(
        '{"code":200,"success":true,"result":"abc123"}') == "abc123"
    assert chaos.parse_blade_output('{"Uid":"def456","ok":1}') == "def456"
    assert chaos.parse_blade_output("created\nuid: 789xyz\n") == "789xyz"
    assert chaos.parse_blade_output("nothing here") is None


def test_controller_lifecycle():
    ctl = chaos.ChaosController()
    out = ctl.create_result_json("Lv_P_CPU_preserve")
    uid = chaos.parse_blade_output(out)
    assert uid and len(ctl.status()) == 1

    # active fault conditions the target service, not others
    lat, err = ctl.active_effects("ts-preserve-service")
    assert lat > 1.0
    lat2, _ = ctl.active_effects("ts-station-service")
    assert lat2 == 1.0

    assert ctl.destroy(uid)
    assert not ctl.destroy(uid)
    assert ctl.status() == []


def test_controller_sweep_and_context():
    ctl = chaos.ChaosController()
    ctl.create("Perf_CPU_Contention")
    ctl.create("Svc_Kill_Media")
    assert ctl.destroy_all() == 2

    with ctl.inject("Lv_D_TRANSACTION_timeout") as h:
        assert ctl.status() == [h]
        lat, err = ctl.active_effects("ts-order-service")
        assert lat >= 10.0
    assert ctl.status() == []

    # normal experiments inject nothing
    h = ctl.create("Normal_case")
    assert h.plan == "none" and ctl.status() == []


def test_host_level_fault_hits_every_service():
    ctl = chaos.ChaosController()
    ctl.create("Perf_CPU_Contention")  # SN host-level: target_service == ""
    lat, _ = ctl.active_effects("user-service")
    assert lat > 1.0


def test_unknown_experiment_rejected():
    with pytest.raises(ValueError):
        chaos.build_mesh_crd("Lv_C_security_check")  # blade, not mesh
    with pytest.raises(ValueError):
        chaos.ChaosController().create("NoSuchExperiment")
