"""Dead-tunnel resilience: probe-or-pin and mid-run CPU failover.

The axon device tunnel can die *between* runs (bench.py probes and pins
pre-init) or *during* one (a 25-minute sweep dies at the next compile).
These tests cover the second path: utils.platform.with_cpu_failover and its
integration into the quality sweep engine.  pin_cpu is monkeypatched to a
recorder throughout — really repinning would collapse the suite's 8-device
mesh for every later test.
"""

import pytest

from anomod.utils import platform


def test_with_cpu_failover_passthrough():
    assert platform.with_cpu_failover(lambda: 42) == 42


def test_with_cpu_failover_retries_on_device_backend(monkeypatch):
    monkeypatch.delenv("ANOMOD_CPU_DEVICES", raising=False)
    pins = []
    monkeypatch.setattr(platform, "pin_cpu", lambda n=1: pins.append(n))
    seen = []
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("UNAVAILABLE: remote_compile: conn refused")
        return "ok"

    out = platform.with_cpu_failover(flaky, on_failover=seen.append,
                                     _platform=lambda: "tpu")
    assert out == "ok"
    assert calls["n"] == 2
    assert pins == [1]
    assert len(seen) == 1 and "UNAVAILABLE" in str(seen[0])


def test_with_cpu_failover_reraises_when_already_cpu(monkeypatch):
    monkeypatch.setattr(platform, "pin_cpu",
                        lambda n=1: pytest.fail("must not repin on cpu"))

    def broken():
        raise RuntimeError("a real bug, not a dead tunnel")

    with pytest.raises(RuntimeError, match="real bug"):
        platform.with_cpu_failover(broken, _platform=lambda: "cpu")


def test_with_cpu_failover_ignores_deterministic_device_errors(monkeypatch):
    """A device-side OOM/compile error is NOT backend loss: it must
    propagate (retrying it on CPU would bury the real bug under a
    mislabeled 'backend lost' note)."""
    monkeypatch.setattr(platform, "pin_cpu",
                        lambda n=1: pytest.fail("must not repin on OOM"))

    def oom():
        raise RuntimeError("RESOURCE_EXHAUSTED: out of memory allocating "
                           "1.2G on TPU_0")

    with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
        platform.with_cpu_failover(oom, _platform=lambda: "tpu")


def test_with_cpu_failover_single_shot(monkeypatch):
    """A second failure after the repoint propagates (no retry loop)."""
    monkeypatch.setattr(platform, "pin_cpu", lambda n=1: None)

    def always():
        raise RuntimeError("UNAVAILABLE: still dead")

    with pytest.raises(RuntimeError, match="still dead"):
        platform.with_cpu_failover(always, _platform=lambda: "tpu")


def test_ensure_live_backend_skip_env(monkeypatch):
    monkeypatch.setenv("ANOMOD_SKIP_PROBE", "1")
    monkeypatch.setattr(platform, "probe_device_platform",
                        lambda *a, **k: pytest.fail("probe must be skipped"))
    assert "skipped" in platform.ensure_live_backend()


def test_ensure_live_backend_pins_on_dead_probe(monkeypatch):
    monkeypatch.delenv("ANOMOD_SKIP_PROBE", raising=False)
    pins = []
    monkeypatch.setattr(platform, "pin_cpu", lambda n=1: pins.append(n))
    monkeypatch.setattr(platform, "probe_device_platform",
                        lambda *a, **k: ("", "probe timed out after 45s"))
    note = platform.ensure_live_backend(n_cpu_fallback=2)
    assert "unavailable" in note and "pinned cpu" in note
    assert pins == [2]


def test_enable_jit_cache_gated_and_idempotent(monkeypatch, tmp_path):
    """ANOMOD_JIT_CACHE: off (default) -> no-op/None; on + a cache dir
    -> jax's persistent compilation cache points at <dir>/jit; on with
    caching disabled entirely -> None.  Restores the suite's own cache
    config afterwards (conftest points it at .jax_test_cache)."""
    import jax

    import anomod.config as config
    prev_dir = jax.config.jax_compilation_cache_dir
    prev_min = jax.config.jax_persistent_cache_min_compile_time_secs
    try:
        monkeypatch.setenv("ANOMOD_CACHE_DIR", str(tmp_path))
        monkeypatch.delenv("ANOMOD_JIT_CACHE", raising=False)
        config.set_config(config.Config())
        assert platform.enable_jit_cache() is None        # default off
        monkeypatch.setenv("ANOMOD_JIT_CACHE", "1")
        config.set_config(config.Config())
        got = platform.enable_jit_cache()
        assert got == str(tmp_path / "jit")
        assert (tmp_path / "jit").is_dir()
        assert jax.config.jax_compilation_cache_dir == got
        assert platform.enable_jit_cache() == got         # idempotent
        monkeypatch.setenv("ANOMOD_CACHE_DIR", "off")     # caching off
        config.set_config(config.Config())
        assert platform.enable_jit_cache() is None
    finally:
        jax.config.update("jax_compilation_cache_dir", prev_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          prev_min)
        monkeypatch.delenv("ANOMOD_CACHE_DIR", raising=False)
        monkeypatch.delenv("ANOMOD_JIT_CACHE", raising=False)
        config.set_config(config.Config())


def test_checkpoint_mtime_distinguishes_fresh_from_stale(tmp_path):
    """The rca failover retry resumes only from a checkpoint whose publish
    time postdates the attempt start — checkpoint_mtime is that clock."""
    import time

    import jax.numpy as jnp

    from anomod.utils.checkpoint import checkpoint_mtime, save_train_state

    assert checkpoint_mtime(tmp_path / "nope") is None     # no checkpoint
    ck = tmp_path / "ck"
    save_train_state(ck, {"w": jnp.ones(2)}, {"m": jnp.zeros(2)}, step=5)
    m = checkpoint_mtime(ck)
    assert m is not None
    # just published -> fresh relative to a run that started a minute ago
    assert m >= time.time() - 60
    # backdate the publish marker: a checkpoint left by an EARLIER run
    # must read as stale relative to this run's start time
    import os
    past = time.time() - 3600
    os.utime(ck / "meta.json", (past, past))
    m_stale = checkpoint_mtime(ck)
    assert m_stale is not None and m_stale < time.time() - 3000


def _fake_train_result(name="gcn"):
    from anomod.rca import TrainResult
    return TrainResult(model_name=name, top1=1.0, top3=1.0,
                       detection_auc=1.0, n_eval=4, params={})


def test_rca_resilient_does_not_resume_stale_checkpoint(monkeypatch,
                                                        tmp_path):
    """Retry after a pre-save failure must NOT resume a checkpoint left in
    the dir by an earlier run (it predates this invocation)."""
    import jax.numpy as jnp

    from anomod import rca
    from anomod.utils.checkpoint import save_train_state

    monkeypatch.setattr(platform, "pin_cpu", lambda n=1: None)
    monkeypatch.setattr(platform, "_current_platform", lambda: "tpu")

    ck = tmp_path / "ck"
    save_train_state(ck, {"w": jnp.ones(2)}, {"m": jnp.zeros(2)}, step=300)

    seen = []

    def flaky_train(*a, resume=False, checkpoint_dir=None, **k):
        seen.append(resume)
        if len(seen) == 1:
            raise RuntimeError("UNAVAILABLE: tunnel died pre-save")
        return _fake_train_result()

    monkeypatch.setattr(rca, "train_rca", flaky_train)
    result, note = rca.train_rca_resilient(
        "TT", "gcn", resume=False, checkpoint_dir=ck)
    assert seen == [False, False]      # stale checkpoint not resumed
    assert result.top1 == 1.0
    assert note and "from scratch" in note


def test_rca_resilient_resumes_own_checkpoint(monkeypatch, tmp_path):
    """Retry resumes when the interrupted attempt itself published a save."""
    import jax.numpy as jnp

    from anomod import rca
    from anomod.utils.checkpoint import save_train_state

    monkeypatch.setattr(platform, "pin_cpu", lambda n=1: None)
    monkeypatch.setattr(platform, "_current_platform", lambda: "tpu")

    ck = tmp_path / "ck"
    seen = []

    def flaky_train(*a, resume=False, checkpoint_dir=None, **k):
        seen.append(resume)
        if len(seen) == 1:
            # periodic save lands, then the device dies
            save_train_state(ck, {"w": jnp.ones(2)}, {"m": jnp.zeros(2)},
                             step=50)
            raise RuntimeError("UNAVAILABLE: tunnel died mid-train")
        return _fake_train_result()

    monkeypatch.setattr(rca, "train_rca", flaky_train)
    result, note = rca.train_rca_resilient(
        "TT", "gcn", resume=False, checkpoint_dir=ck)
    assert seen == [False, True]       # own save -> resumed
    assert note and "last checkpoint" in note


def test_quality_sweep_survives_mid_run_backend_loss(monkeypatch):
    """Integration: the sweep engine finishes (and flags the failover) when
    a model's train+eval row dies with a backend RuntimeError mid-sweep."""
    from anomod import quality

    monkeypatch.delenv("ANOMOD_CPU_DEVICES", raising=False)
    pins = []
    monkeypatch.setattr(platform, "pin_cpu", lambda n=1: pins.append(n))
    monkeypatch.setattr(platform, "_current_platform", lambda: "tpu")

    orig = quality._train_model
    calls = {"n": 0}

    def flaky_train(*a, **k):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("UNAVAILABLE: remote_compile: conn refused")
        return orig(*a, **k)

    monkeypatch.setattr(quality, "_train_model", flaky_train)

    pts = quality.severity_sweep(
        testbed="TT", model_names=("gcn",), severities=(1.0,),
        train_seeds=range(3), eval_seeds=(100,), n_traces=8, epochs=2)
    assert len(pts) == 1 and pts[0].model == "gcn"
    assert calls["n"] == 2          # failed once, retried once
    assert pins == [1]
    assert quality.LAST_FAILOVER is not None
    assert "gcn" in quality.LAST_FAILOVER

    # a clean follow-up sweep resets the breadcrumb
    quality.severity_sweep(testbed="TT", model_names=("zscore",),
                           severities=(1.0,), train_seeds=range(3),
                           eval_seeds=(100,), n_traces=8, epochs=1)
    assert quality.LAST_FAILOVER is None
