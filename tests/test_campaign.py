"""Campaign materializer: reference-shaped trees that roundtrip the loaders."""

import numpy as np
import pytest

from anomod import detect
from anomod.campaign import run_campaign
from anomod.config import Config
from anomod.io import dataset


@pytest.fixture(scope="module")
def tt_tree(tmp_path_factory):
    out = tmp_path_factory.mktemp("campaign")
    done = run_campaign("TT", out, n_traces=60)
    return out, done


@pytest.fixture(scope="module")
def sn_tree(tmp_path_factory):
    out = tmp_path_factory.mktemp("campaign_sn")
    done = run_campaign("SN", out, n_traces=60)
    return out, done


def test_campaign_tt_tree_shape(tt_tree):
    out, done = tt_tree
    assert len(done) == 13
    root = out / "TT_data"
    for sub in ("trace_data", "metric_data", "log_data", "api_responses",
                "coverage_report"):
        assert (root / sub).is_dir()
        assert len(list((root / sub).iterdir())) == 13


def test_campaign_self_trace(tt_tree):
    """The campaign traces itself in Jaeger shape and the artifact loads
    back through the SN trace loader: one span per experiment with
    generate/materialize children under the campaign root span."""
    out, done = tt_tree
    from anomod.io.sn_traces import load_jaeger_json
    batch = load_jaeger_json(out / "campaign_trace_TT.json")
    names = set()
    import json
    doc = json.loads((out / "campaign_trace_TT.json").read_text())
    for s in doc["data"][0]["spans"]:
        names.add(s["operationName"])
    assert "campaign[TT]" in names
    assert sum(1 for n in names if n.startswith("experiment[")) == 13
    assert {"generate", "materialize"} <= names
    # loader roundtrip: spans parent-resolve into one rooted trace
    assert batch.n_spans == 1 + 13 * 3
    assert (batch.parent == -1).sum() == 1


def test_campaign_tt_roundtrip_loaders(tt_tree):
    out, _ = tt_tree
    cfg = Config(data_root=out, synth_on_lfs=False)
    found = dataset.discover("TT", cfg)
    assert len(found) == 13
    exp = dataset.load_experiment("Lv_P_CPU_preserve", "TT", cfg)
    assert not exp.synthetic            # everything loaded from disk
    assert exp.spans.n_spans > 0
    assert exp.metrics.n_samples > 0
    assert exp.logs.n_lines > 0
    assert exp.api.n_records > 0
    assert exp.coverage is not None


def test_campaign_sn_roundtrip_loaders(sn_tree):
    out, _ = sn_tree
    cfg = Config(data_root=out, synth_on_lfs=False)
    exp = dataset.load_experiment("Svc_Kill_Media", "SN", cfg)
    assert not exp.synthetic
    assert exp.spans.n_spans > 0
    assert exp.log_summaries           # summary.txt parsed back
    by_name = {s.service: s for s in exp.log_summaries}
    assert "MediaService" in by_name


def test_detector_on_materialized_corpus(tt_tree):
    """Full loop: campaign -> disk -> loaders -> detector -> labels."""
    out, _ = tt_tree
    cfg = Config(data_root=out, synth_on_lfs=False)
    corpus = dataset.load_corpus("TT", cfg)
    assert all(not e.synthetic for e in corpus)
    s = detect.evaluate_corpus(corpus)
    assert s.top1 >= 0.9, [(r.experiment, r.ranked_services[:3])
                           for r in s.results
                           if r.is_anomaly_true and r.target_service
                           and not r.hit(1)]
    assert s.detection_accuracy >= 0.9
