"""Device-resident tenant state (ANOMOD_SERVE_STATE): the bit-parity
pins behind PR 8's on-device scatter fold + batched window scoring.

The contract under test: ``device`` serving performs the SAME IEEE f32
arithmetic as the ``host`` seam in the SAME order — the pool's
scatter-add is ``state + delta`` per slot in dispatch order, its roll is
roll_ring_state's shift+zero, gather/put are pure copies, and the
batched COMMIT scorer is the sequential ``_score_through``'s own z core
with a leading tenant axis — so states, alerts, SLO and shed are
byte-identical across residencies, seeds, shard counts and pipeline
depths.  Nothing here is a tolerance check: every comparison is
``tobytes()`` or ``==``.
"""

import dataclasses

import numpy as np
import pytest

from anomod.replay import (N_FEATS, ReplayConfig, ReplayState,
                           TenantStatePool, fold_delta)
from anomod.schemas import SpanBatch
from anomod.stream import (OnlineDetector, StreamReplay,
                           roll_ring_state, score_closed_windows_batched)


def _cfg(S=4, W=8):
    return ReplayConfig(n_services=S, n_windows=W, window_us=5_000_000,
                        chunk_size=512)


def _rand_state(cfg, rng):
    return ReplayState(
        agg=rng.random((cfg.sw, N_FEATS)).astype(np.float32),
        hist=rng.random((cfg.sw, cfg.n_hist_buckets)).astype(np.float32))


def _assert_state_bytes(a: ReplayState, b: ReplayState):
    assert np.asarray(a.agg).tobytes() == np.asarray(b.agg).tobytes()
    assert np.asarray(a.hist).tobytes() == np.asarray(b.hist).tobytes()


# -- the pool itself ------------------------------------------------------
#
# Every structural pool test runs on BOTH engines: "numpy" (the CPU
# backend's in-place host-plane engine — what tier-1 serving uses) and
# "jax" (the donated-buffer device engine accelerators use; it works on
# CPU too, just with per-dispatch overhead).  One parity contract, two
# implementations, zero drift.

ENGINES = ("numpy", "jax")


@pytest.mark.parametrize("engine", ENGINES)
def test_pool_round_trip_bit_exact_under_interleavings(engine):
    """get_state/set_state seam via the pool: arbitrary cross-tenant
    interleavings of put/gather/roll/scatter_fold stay byte-identical
    to a host-side mirror applying fold_delta/roll_ring_state."""
    cfg = _cfg()
    rng = np.random.default_rng(42)
    pool = TenantStatePool(cfg, capacity=4, engine=engine)
    slots = [pool.acquire() for _ in range(4)]
    mirror = {s: pool.zero_state() for s in slots}
    for op in rng.integers(0, 4, 60):
        s = slots[int(rng.integers(0, len(slots)))]
        if op == 0:                                    # put
            st = _rand_state(cfg, rng)
            pool.put(s, st)
            mirror[s] = st
        elif op == 1:                                  # gather
            _assert_state_bytes(pool.gather(s), mirror[s])
        elif op == 2:                                  # roll
            k = int(rng.integers(1, cfg.n_windows + 2))
            pool.roll(s, k)
            mirror[s] = roll_ring_state(mirror[s], cfg, k)
        else:                                          # scatter_fold
            dagg = rng.random((2, cfg.sw, N_FEATS)).astype(np.float32)
            dhist = rng.random(
                (2, cfg.sw, cfg.n_hist_buckets)).astype(np.float32)
            other = slots[int(rng.integers(0, len(slots)))]
            picks = [s, other] if other != s else [s]
            pool.scatter_fold(picks, dagg, dhist)
            for i, sl in enumerate(picks):
                mirror[sl] = fold_delta(mirror[sl], dagg[i], dhist[i])
    for s in slots:
        _assert_state_bytes(pool.gather(s), mirror[s])


@pytest.mark.parametrize("engine", ENGINES)
def test_pool_scatter_duplicate_slots_fold_in_lane_order(engine):
    """A slot repeated within one dispatch folds in LANE order via wave
    splitting: ((state + d0) + d1), bit-for-bit — never a pre-combined
    d0 + d1 handed to one scatter (XLA's duplicate-index add order is
    unspecified, and a numpy fancy-index += drops duplicates; the waves
    make both deterministic)."""
    cfg = _cfg()
    rng = np.random.default_rng(7)
    pool = TenantStatePool(cfg, capacity=2, engine=engine)
    s = pool.acquire()
    st = _rand_state(cfg, rng)
    pool.put(s, st)
    dagg = rng.random((4, cfg.sw, N_FEATS)).astype(np.float32)
    dhist = rng.random((4, cfg.sw, cfg.n_hist_buckets)).astype(np.float32)
    pool.scatter_fold([s, s, s], dagg, dhist)  # lane 3 = dead pad
    want = st
    for i in range(3):
        want = fold_delta(want, dagg[i], dhist[i])
    _assert_state_bytes(pool.gather(s), want)


@pytest.mark.parametrize("engine", ENGINES)
def test_pool_roll_bit_identical_to_host_roll(engine):
    """The pool roll (shift plane columns, zero the tail) vs
    roll_ring_state on the same bits, every shift regime: partial,
    full-plane, and past-the-grid."""
    cfg = _cfg()
    rng = np.random.default_rng(3)
    for k in (1, 3, cfg.n_windows - 1, cfg.n_windows, 2 * cfg.n_windows):
        pool = TenantStatePool(cfg, capacity=2, engine=engine)
        s = pool.acquire()
        st = _rand_state(cfg, rng)
        pool.put(s, st)
        pool.roll(s, k)
        _assert_state_bytes(pool.gather(s), roll_ring_state(st, cfg, k))


@pytest.mark.parametrize("engine", ENGINES)
def test_pool_slot_exhaustion_growth_and_churn_reuse(engine):
    """Exhaustion grows the pool by doubling WITHOUT disturbing live
    states; release() returns a zeroed slot that the next acquire
    reuses (tenant churn must never leak a predecessor's bits)."""
    cfg = _cfg()
    rng = np.random.default_rng(0)
    pool = TenantStatePool(cfg, capacity=2, engine=engine)
    s1, s2 = pool.acquire(), pool.acquire()
    st1, st2 = _rand_state(cfg, rng), _rand_state(cfg, rng)
    pool.put(s1, st1)
    pool.put(s2, st2)
    assert pool.capacity == 2 and pool.live_slots == 2
    s3 = pool.acquire()                        # exhaustion -> growth
    assert pool.capacity == 4
    _assert_state_bytes(pool.gather(s1), st1)  # growth kept the bits
    _assert_state_bytes(pool.gather(s2), st2)
    pool.put(s3, _rand_state(cfg, rng))
    pool.release(s2)
    assert pool.live_slots == 2
    s2b = pool.acquire()                       # churn reuses the slot...
    assert s2b == s2
    z = pool.gather(s2b)                       # ...zeroed
    assert not np.asarray(z.agg).any() and not np.asarray(z.hist).any()
    _assert_state_bytes(pool.gather(s1), st1)


def test_pool_gather_window_matches_plane_column_and_pallas_twin():
    """The batched scorer's fused gather: [T, S, F] columns byte-equal
    to slicing the gathered rows, under the pow2 request padding — and
    the pallas gather kernel (interpret mode on CPU) returns the same
    bytes as the XLA formulation."""
    cfg = _cfg()
    pool = TenantStatePool(cfg, capacity=4, engine="numpy")
    jx = TenantStatePool(cfg, capacity=4, engine="jax")
    pal = TenantStatePool(cfg, capacity=4, gather_engine="pallas")
    for p in (pool, jx, pal):
        r = np.random.default_rng(5)
        for _ in range(3):
            p.put(p.acquire(), _rand_state(cfg, r))
    slots, cols = [2, 1, 3], [0, cfg.n_windows - 1, 3]
    got = pool.gather_window(slots, cols)
    assert got.shape == (3, cfg.n_services, N_FEATS)
    for j, (s, c) in enumerate(zip(slots, cols)):
        want = np.asarray(pool.agg[s]).reshape(
            cfg.n_services, cfg.n_windows, N_FEATS)[:, c]
        assert got[j].tobytes() == want.tobytes()
    assert jx.gather_window(slots, cols).tobytes() == got.tobytes()
    assert pal.gather_window(slots, cols).tobytes() == got.tobytes()
    with pytest.raises(ValueError):
        TenantStatePool(cfg, gather_engine="mosaic")
    with pytest.raises(ValueError):
        TenantStatePool(cfg, engine="cuda")


# -- the runner's device fold ---------------------------------------------


def _staged_work(runner, replays, seed, n=120):
    """One staged (width, [(replay, cols)]) group per replay via the
    real plan_push path (spans all land in the first few windows)."""
    rng = np.random.default_rng(seed)
    work_by_width = {}
    for rep in replays:
        svc = rng.integers(0, runner.cfg.n_services, n).astype(np.int32)
        b = SpanBatch(
            trace=np.arange(n, dtype=np.int32) % 7,
            parent=np.full(n, -1, np.int32), service=svc,
            endpoint=np.zeros(n, np.int32),
            start_us=np.sort(rng.integers(0, 3 * runner.cfg.window_us,
                                          n)).astype(np.int64),
            duration_us=rng.integers(900, 1100, n).astype(np.int64),
            is_error=np.zeros(n, np.bool_),
            status=np.full(n, 200, np.int16),
            kind=np.zeros(n, np.int8),
            services=tuple(f"s{i}" for i in range(runner.cfg.n_services)),
            endpoints=("ep",), trace_ids=tuple(f"t{i}" for i in range(7)),
        ).validate()
        _, plan = rep.plan_push(b)
        for width, cols in plan:
            work_by_width.setdefault(width, []).append((rep, cols))
    return work_by_width


def test_abort_lanes_leaves_pool_states_at_last_commit():
    """abort_lanes with IN-FLIGHT scatter folds: the pool keeps the
    last-committed bytes — an aborted tick's deltas never land, on the
    device path exactly as on the host path."""
    from anomod.serve.batcher import BucketRunner, PooledStreamReplay
    cfg = _cfg()
    runner = BucketRunner(cfg, (128, 512), lane_buckets=(1, 2, 4),
                          pipeline=3, state="device", pool_slots=4)
    reps = [PooledStreamReplay(cfg, 0, runner) for _ in range(3)]
    for width, group in _staged_work(runner, reps, seed=1).items():
        runner.submit_lanes(width, group)
    runner.drain_lanes()                       # committed baseline
    committed = [r.get_state() for r in reps]
    for width, group in _staged_work(runner, reps, seed=2).items():
        runner.submit_lanes(width, group)
    assert runner.inflight_dispatches > 0      # folds genuinely in flight
    runner.abort_lanes()
    for r, want in zip(reps, committed):
        _assert_state_bytes(r.get_state(), want)
    # and a post-abort tick folds normally from the committed states
    for width, group in _staged_work(runner, reps, seed=2).items():
        runner.submit_lanes(width, group)
    runner.drain_lanes()
    for r, was in zip(reps, committed):
        assert np.asarray(r.get_state().agg).tobytes() \
            != np.asarray(was.agg).tobytes()


def test_pooled_replay_state_seam_round_trips_interleaved():
    """PooledStreamReplay keeps get_state/set_state as the official
    surface: cross-tenant interleaved writes and reads round-trip
    byte-identically (the checkpoint/migration seam contract)."""
    from anomod.serve.batcher import BucketRunner, PooledStreamReplay
    cfg = _cfg()
    runner = BucketRunner(cfg, (128, 512), state="device", pool_slots=3)
    reps = [PooledStreamReplay(cfg, 0, runner) for _ in range(3)]
    rng = np.random.default_rng(11)
    states = [_rand_state(cfg, rng) for _ in reps]
    for i in (2, 0, 1):
        reps[i].set_state(states[i])
    for i in (1, 2, 0):
        _assert_state_bytes(reps[i].get_state(), states[i])
    reps[1].release()
    assert runner.pool.live_slots == 2
    _assert_state_bytes(reps[0].get_state(), states[0])


def test_released_replay_fails_loud_and_failed_ctor_frees_slot():
    """Lifecycle guards: every surface of a RELEASED PooledStreamReplay
    raises instead of touching the pool (pool.put(None, ...) would
    broadcast over every slot — silent fleet-wide corruption), a double
    release raises too, and a ctor that fails AFTER acquiring hands its
    slot back instead of leaking a pool row per retried admission."""
    from anomod.serve.batcher import BucketRunner, PooledStreamReplay
    cfg = _cfg()
    runner = BucketRunner(cfg, (128, 512), state="device", pool_slots=2)
    rep = PooledStreamReplay(cfg, 0, runner)
    keep = PooledStreamReplay(cfg, 0, runner)
    rng = np.random.default_rng(3)
    kept = _rand_state(cfg, rng)
    keep.set_state(kept)
    rep.release()
    for poke in (lambda: rep.get_state(),
                 lambda: rep.set_state(_rand_state(cfg, rng)),
                 lambda: rep._roll(1),
                 lambda: rep.release()):
        with pytest.raises(ValueError, match="released"):
            poke()
    _assert_state_bytes(keep.get_state(), kept)   # pool untouched
    # the pool's own seam refuses a None slot outright (defense in
    # depth below the replay guard)
    for op in (lambda: runner.pool.gather(None),
               lambda: runner.pool.put(None, kept)):
        with pytest.raises(TypeError):
            op()
    # ctor failure after acquire: cfg mismatch raises in the parent
    # ctor; the acquired slot must come back to the free list
    live = runner.pool.live_slots
    with pytest.raises(ValueError, match="cfg"):
        PooledStreamReplay(_cfg(W=16), 0, runner)
    assert runner.pool.live_slots == live


def test_host_runner_keeps_seam_and_refuses_pooled_replay():
    from anomod.serve.batcher import (BucketedStreamReplay, BucketRunner,
                                      PooledStreamReplay)
    cfg = _cfg()
    runner = BucketRunner(cfg, (128, 512), state="host")
    assert runner.pool is None
    assert isinstance(BucketedStreamReplay(cfg, 0, runner).state.agg,
                      np.ndarray)
    with pytest.raises(ValueError):
        PooledStreamReplay(cfg, 0, runner)
    with pytest.raises(ValueError):
        BucketRunner(cfg, (128, 512), state="vram")


# -- batched window scoring ----------------------------------------------


def _det_batches(seed, S=3, n_windows=14, per_w=24):
    """A seeded multi-push span stream crossing the calibration-freeze
    boundary, with a latency step so alerts actually fire."""
    rng = np.random.default_rng(seed)
    w_us = 5_000_000
    out = []
    for w in range(n_windows):
        n = per_w + int(rng.integers(0, 8))
        dur = rng.integers(900, 1100, n).astype(np.int64)
        if w >= 8:
            dur = dur * 25                     # post-calibration fault
        out.append(SpanBatch(
            trace=np.arange(n, dtype=np.int32) % 5,
            parent=np.full(n, -1, np.int32),
            service=rng.integers(0, S, n).astype(np.int32),
            endpoint=np.zeros(n, np.int32),
            start_us=np.sort(w * w_us + rng.integers(0, w_us, n)
                             ).astype(np.int64),
            duration_us=dur,
            is_error=rng.random(n) < 0.02,
            status=np.full(n, 200, np.int16),
            kind=np.zeros(n, np.int8),
            services=tuple(f"s{i}" for i in range(S)),
            endpoints=("ep",), trace_ids=tuple(f"t{i}" for i in range(5)),
        ).validate())
    return out


def _host_gather(work):
    """The test-local twin of the engine's host gather closure."""
    planes = {}

    def gather(items):
        out = np.empty((len(items), work[0][0]._n_svc, N_FEATS),
                       np.float32)
        for j, (i, c) in enumerate(items):
            pl = planes.get(i)
            if pl is None:
                pl = planes[i] = np.asarray(
                    work[i][0].replay.agg_plane(), np.float32)
            out[j] = pl[:, c]
        return out

    return gather


@pytest.mark.parametrize(
    "seed", [0, pytest.param(3, marks=pytest.mark.slow),
             pytest.param(9, marks=pytest.mark.slow)])
def test_batched_scoring_byte_identical_to_sequential(seed):
    """THE batched-scorer pin: score_closed_windows_batched over several
    tenants == per-tenant _score_through, byte-identical — alert stream
    (every field), hysteresis streaks, CUSUM carry, _scored_through —
    across the calibration-freeze boundary and through finish()."""
    cfg = ReplayConfig(n_services=3, n_windows=16, window_us=5_000_000,
                       chunk_size=512)
    svcs = tuple(f"s{i}" for i in range(3))

    def mk():
        return [OnlineDetector(svcs, cfg, 0,
                               replay=StreamReplay(cfg, 0),
                               baseline_windows=4, z_threshold=4.0)
                for _ in range(3)]

    seq, bat = mk(), mk()
    assert all(d.batch_scorable for d in seq)
    streams = [_det_batches(seed + 10 * t) for t in range(3)]
    for step in range(len(streams[0])):
        work = []
        for t in range(3):
            b = streams[t][step]
            # sequential: the one-call push path
            seq[t].push(b)
            # batched: replay push + bookkeep, then ONE vectorized pass
            d = bat[t]
            w = d.replay.push(d.replay_batch(b))
            through = d.note_bookkeep(b.n_spans, w)
            rng_ = (d.scoring_window_range(through)
                    if through is not None else None)
            if rng_ is not None:
                work.append((d, rng_[0], rng_[1]))
        if work:
            score_closed_windows_batched(work, _host_gather(work))
    fin_seq = [d.finish() for d in seq]
    fin_bat = [d.finish() for d in bat]
    for t in range(3):
        assert [dataclasses.asdict(a) for a in seq[t].alerts] == \
            [dataclasses.asdict(a) for a in bat[t].alerts]
        assert [dataclasses.asdict(a) for a in fin_seq[t]] == \
            [dataclasses.asdict(a) for a in fin_bat[t]]
        assert seq[t].alerts, "stream must actually alert to pin anything"
        assert seq[t]._scored_through == bat[t]._scored_through
        assert seq[t]._streak.tobytes() == bat[t]._streak.tobytes()
        assert seq[t]._cusum.tobytes() == bat[t]._cusum.tobytes()
        assert seq[t]._cusum_k.tobytes() == bat[t]._cusum_k.tobytes()


# -- the serving engine end to end ----------------------------------------


def _small_serve_kw(seed=5, duration=25):
    return dict(n_tenants=6, n_services=4, capacity_spans_per_s=1000,
                overload=2.0, duration_s=duration, tick_s=1.0, seed=seed,
                window_s=2.0, baseline_windows=4, fault_tenants=1,
                buckets=(64, 256), lane_buckets=(1, 2, 4),
                max_backlog=1500, n_windows=16)


def _fingerprint(eng):
    return {
        tid: ([dataclasses.asdict(a) for a in eng.alerts_for(tid)],
              np.asarray(eng._tenant_replay[tid].state.agg).tobytes(),
              np.asarray(eng._tenant_replay[tid].state.hist).tobytes())
        for tid in sorted(set(eng._tenant_det) | set(eng._tenant_replay))}


@pytest.mark.parametrize(
    "seed", [5, pytest.param(11, marks=pytest.mark.slow)])
def test_engine_device_vs_host_byte_identical(seed):
    """THE residency pin: a seeded overloaded fused run with the device
    pool emits per-tenant alerts, replay states, SLO quantiles and shed
    decisions byte-identical to the host seam — and the report records
    which residency served."""
    from anomod.serve.engine import run_power_law
    eh, rh = run_power_law(state="host", **_small_serve_kw(seed))
    ed, rd = run_power_law(state="device", **_small_serve_kw(seed))
    assert rh.serve_state == "host" and rd.serve_state == "device"
    assert _fingerprint(eh) == _fingerprint(ed)
    assert rh.latency == rd.latency
    assert rh.shed_fraction == rd.shed_fraction
    assert rh.per_priority == rd.per_priority


def test_engine_device_parity_across_shards_and_depths():
    """Residency composes with every execution axis: device at 2 shards
    and at pipeline depths 1 and 3 reproduces the host 1-shard depth-2
    fingerprint bit-for-bit (folds land in dispatch order on every
    path)."""
    from anomod.serve.engine import run_power_law
    eh, _ = run_power_law(state="host", **_small_serve_kw(seed=7))
    want = _fingerprint(eh)
    for kw in ({"shards": 2}, {"pipeline": 1}, {"pipeline": 3}):
        ed, rd = run_power_law(state="device", **kw,
                               **_small_serve_kw(seed=7))
        assert _fingerprint(ed) == want, kw
        assert rd.serve_state == "device"


def test_engine_default_is_device_and_unfused_uses_pool_too():
    """auto resolves to device on the bucket-runner plane (the pool is
    exact, not a tolerance trade), and the UNFUSED path's per-chunk
    dispatch serves through the pool seam with the same bytes as the
    host seam."""
    from anomod.serve.engine import run_power_law
    kw = _small_serve_kw(seed=3, duration=15)
    _, rep = run_power_law(**kw)
    assert rep.serve_state == "device"
    eh, _ = run_power_law(state="host", fuse=False, **kw)
    ed, _ = run_power_law(state="device", fuse=False, **kw)
    assert _fingerprint(eh) == _fingerprint(ed)


def test_engine_refuses_device_with_mesh_and_validates_knob():
    from anomod.serve.engine import ServeEngine
    from anomod.serve.queues import TenantSpec
    specs = [TenantSpec(tenant_id=0, name="t0", rate_spans_per_s=10.0)]
    with pytest.raises(ValueError, match="mesh plane manages its own"):
        ServeEngine(specs, ("a", "b"),
                    _cfg(S=2), mesh=object(), state="device")
    eng = ServeEngine(specs, ("a", "b"), _cfg(S=2), mesh=object(),
                      state="auto")
    assert eng.serve_state == "host"           # auto degrades under mesh
    with pytest.raises(ValueError, match="unknown serve state"):
        ServeEngine(specs, ("a", "b"), _cfg(S=2), state="gpu")


def test_serve_state_env_knob_validated(monkeypatch):
    """ANOMOD_SERVE_STATE joins the validated Config env contract."""
    from anomod.config import Config
    for raw, want in (("auto", "auto"), ("host", "host"),
                      ("device", "device"), (" DEVICE ", "device")):
        monkeypatch.setenv("ANOMOD_SERVE_STATE", raw)
        assert Config().serve_state == want
    monkeypatch.setenv("ANOMOD_SERVE_STATE", "vram")
    with pytest.raises(ValueError, match="ANOMOD_SERVE_STATE"):
        Config()
