"""Deploy topology + JaCoCo injection."""

import copy

import yaml

from anomod import topology
from anomod.synth import SN_SERVICES, TT_SERVICES


def test_sn_compose_shape():
    doc = topology.sn_compose()
    services = doc["services"]
    # all 12 SN services present, gcov instrumented except the gateway
    for svc in SN_SERVICES:
        assert svc in services
        if svc != "nginx-web-server":
            env = services[svc]["environment"]
            assert any(e.startswith("GCOV_PREFIX=") for e in env)
            assert "./coverage-reports:/coverage-reports" in services[svc]["volumes"]
            assert services[svc]["entrypoint"][0].startswith("/usr/local/bin/")
    # gateway on :8080, jaeger on :16686, prometheus :9090
    assert "8080:8080" in services["nginx-web-server"]["ports"]
    assert "16686:16686" in services["jaeger-agent"]["ports"]
    assert "9090:9090" in services["prometheus"]["ports"]
    # chaos-target redis stores exist
    for store in ("home-timeline-redis", "user-timeline-redis",
                  "social-graph-redis"):
        assert store in services
    # yaml roundtrip
    assert yaml.safe_load(yaml.safe_dump(doc)) == doc


def test_sn_container_name():
    assert topology.sn_container_name("user-service") == \
        "socialnetwork_user-service_1"


def test_tt_deployment_shape():
    doc = topology.tt_deployment("ts-order-service")
    assert doc["kind"] == "Deployment"
    spec = doc["spec"]["template"]["spec"]
    assert spec["initContainers"][0]["name"] == "agent-container"
    c = spec["containers"][0]
    env = {e["name"]: e.get("value") for e in c["env"]}
    assert env["JAVA_TOOL_OPTIONS"].startswith("-javaagent:/skywalking")
    assert c["readinessProbe"]["tcpSocket"]["port"] == c["ports"][0]["containerPort"]
    # ports are unique per service
    ports = {topology.tt_service_port(s) for s in TT_SERVICES}
    assert len(ports) == len(TT_SERVICES)


def test_inject_jacoco_appends_preserving_skywalking():
    docs = [topology.tt_deployment("ts-order-service")]
    out, changed = topology.inject_jacoco(docs)
    assert changed == 1
    spec = out[0]["spec"]["template"]["spec"]
    names = [v["name"] for v in spec["volumes"]]
    assert "jacoco-vol" in names and "coverage-vol" in names
    assert any(i["name"] == "init-jacoco" for i in spec["initContainers"])
    c = spec["containers"][0]
    jto = next(e["value"] for e in c["env"] if e["name"] == "JAVA_TOOL_OPTIONS")
    # skywalking agent first, jacoco appended after (reference :70-71 order)
    assert jto.startswith("-javaagent:/skywalking")
    assert "output=tcpserver,address=*,port=6300" in jto
    assert "includes=order.*" in jto
    assert "excludes=org.springframework.*" in jto
    mounts = [m["name"] for m in c["volumeMounts"]]
    assert "jacoco-vol" in mounts and "coverage-vol" in mounts
    # input not mutated
    orig = next(e["value"] for e in docs[0]["spec"]["template"]["spec"]
                ["containers"][0]["env"] if e["name"] == "JAVA_TOOL_OPTIONS")
    assert "jacoco" not in orig


def test_inject_jacoco_idempotent():
    docs = [topology.tt_deployment("ts-travel-service")]
    once, n1 = topology.inject_jacoco(docs)
    twice, n2 = topology.inject_jacoco(once)
    assert n1 == 1 and n2 == 0
    assert once == twice


def test_inject_jacoco_skips_non_workloads():
    svc = {"kind": "Service", "metadata": {"name": "ts-order-service"},
           "spec": {"ports": []}}
    before = copy.deepcopy(svc)
    out, changed = topology.inject_jacoco([svc])
    assert changed == 0 and out[0] == before


def test_inject_jacoco_file_mode_and_env_creation():
    # container without JAVA_TOOL_OPTIONS gets one created
    doc = topology.tt_deployment("ts-station-service", with_tracing=False)
    out, changed = topology.inject_jacoco([doc], mode="file")
    assert changed == 1
    c = out[0]["spec"]["template"]["spec"]["containers"][0]
    jto = next(e["value"] for e in c["env"] if e["name"] == "JAVA_TOOL_OPTIONS")
    assert jto.startswith("-javaagent:/jacoco")
    assert "output=file,destfile=/coverage/jacoco-$(HOSTNAME).exec" in jto


def test_package_prefix_inference():
    assert topology.service_package_prefix("ts-order-service") == "order.*"
    assert topology.service_package_prefix("ts-admin-basic-info-service") == \
        "adminbasicinfo.*"
    assert topology.infer_includes_from_packages(
        ["user.controller", "user.service", "com.helper"]) == "user.*"
    assert topology.infer_includes_from_packages([]) is None


def test_tt_manifests_full_stream_injection():
    docs = topology.tt_manifests()
    out, changed = topology.inject_jacoco(docs)
    assert changed == len(TT_SERVICES)
    txt = yaml.safe_dump_all(out)
    assert txt.count("init-jacoco") == len(TT_SERVICES)
