"""Exec-transport collectors against a scripted fake cluster.

Mirrors tests/test_live.py's stub-server design for the subprocess-driven
collection paths: a FakeCluster answers every kubectl/docker invocation
from canned data, and the assertions close the loop through the OFFLINE
loaders — collection is correct iff load_tt_log_dir / load_sn_log_dir /
load_tt_coverage_report consume the produced trees unmodified.
"""

import numpy as np
import pytest

from anomod.io.live_exec import (DockerLogCollector, ExecResult, ExecRunner,
                                 JacocoCoverageCollector, KubeLogCollector)

STAMP = "20260731_120000"


class FakeCluster:
    """Scripted answers for kubectl/docker command lines; records every
    invocation for behavioral asserts."""

    def __init__(self):
        self.calls = []
        self.pods = ["ts-order-service-86d6f7876-99bhf",
                     "ts-travel-service-5f7b8-x2k4p",
                     "nacos-0", "other-pod-1"]
        self.crashed = {"ts-order-service-86d6f7876-99bhf"}
        self.containers = {
            "compose-post-service": "c01",
            "post-storage-service": "c02",
        }
        self.jacoco_pods = {"ts-order-service-86d6f7876-99bhf",
                            "ts-travel-service-5f7b8-x2k4p"}

    def __call__(self, cmd):
        self.calls.append(cmd)
        joined = " ".join(cmd)
        if cmd[:3] == ["kubectl", "get", "pods"] and "-o" in cmd \
                and "json" in joined and "jsonpath" not in joined:
            import json
            return ExecResult(0, json.dumps({"items": [
                {"metadata": {"name": p}} for p in self.pods]}))
        if "jsonpath" in joined:
            return ExecResult(0, " ".join(self.pods))
        if cmd[:2] == ["kubectl", "logs"]:
            pod = cmd[2]
            if "--previous" in cmd:
                if pod in self.crashed:
                    return ExecResult(0, "ERROR crash before restart\n")
                return ExecResult(1, "", "no previous terminated container")
            return ExecResult(
                0, f"2026-07-31 12:00:00 INFO {pod} serving\n"
                   f"2026-07-31 12:00:01 WARN {pod} slow\n")
        if cmd[:2] == ["kubectl", "get"] and "events" in cmd:
            return ExecResult(0, '{"items": [{"reason": "Killing"}]}')
        if cmd[:2] == ["docker", "ps"]:
            rows = [f"{cid} socialnetwork_{svc}_1"
                    for svc, cid in self.containers.items()]
            return ExecResult(0, "\n".join(rows) + "\n")
        if cmd[:2] == ["docker", "logs"]:
            cid = cmd[-1]
            return ExecResult(
                0, "2026-07-31T12:00:00 INFO ready\n"
                   "2026-07-31T12:00:01 ERROR downstream failed\n")
        if "test -f /jacoco/jacococli.jar" in joined:
            pod = cmd[cmd.index("exec") + 1]
            return ExecResult(0 if pod in self.jacoco_pods else 1)
        if "jacococli.jar dump" in joined:
            return ExecResult(0)
        if "ls -1 /coverage/*.exec" in joined:
            pod = cmd[cmd.index("exec") + 1]
            return ExecResult(0, f"/coverage/jacoco-{pod}.exec\n")
        if cmd[:3] == ["kubectl", "-n", "default"] and cmd[3] == "cp":
            # "copy" the pod's dump: write a CoverageDump npz at the dst
            from pathlib import Path

            from anomod.io.coverage_report import CoverageDump, save_dump
            pod = cmd[4].split(":", 1)[0]
            dst = Path(cmd[5])
            covered = pod.startswith("ts-order")
            mask = np.zeros(10, bool)
            mask[:7 if covered else 3] = True
            save_dump(CoverageDump(service=pod,
                                   files={"src/Main.java": mask}), dst)
            # kubectl cp delivers bytes at EXACTLY the requested path;
            # numpy's savez appends .npz, so emulate the byte-copy
            if not dst.exists():
                dst.with_name(dst.name + ".npz").rename(dst)
            return ExecResult(0)
        return ExecResult(1, "", f"unscripted command: {joined}")


@pytest.fixture()
def cluster():
    return FakeCluster()


def _runner(cluster):
    return ExecRunner(run_fn=cluster)


def test_kube_log_collection_roundtrips_through_loader(tmp_path, cluster):
    col = KubeLogCollector(runner=_runner(cluster))
    rep = col.collect(tmp_path, stamp=STAMP)
    assert rep.kind == "kubectl_logs"
    # only ts-/nacos/rabbitmq pods collected; other-pod-1 filtered out
    assert not any("other-pod" in f for f in rep.files)
    # crashed pod got a _previous_ file; healthy one did not
    prev = [f for f in rep.files if "_previous_" in f]
    assert len(prev) == 1 and "ts-order-service" in prev[0]
    assert any("kubernetes_events_" in f for f in rep.files)
    from anomod.io.logs import load_tt_log_dir
    batch, summaries = load_tt_log_dir(tmp_path)
    assert batch is not None and batch.n_lines > 0
    # pod names collapse to service identity; _previous_ files excluded
    assert "ts-order-service" in batch.services
    assert "ts-travel-service" in batch.services


def test_docker_log_collection_writes_summary_contract(tmp_path, cluster):
    col = DockerLogCollector(runner=_runner(cluster))
    rep = col.collect(tmp_path, stamp=STAMP)
    assert rep.kind == "docker_logs"
    from anomod.io.logs import load_sn_log_dir
    batch, summaries = load_sn_log_dir(tmp_path)
    assert batch is not None and batch.n_lines > 0
    by_svc = {s.service: s for s in summaries}
    # the two running containers produced real files with counted errors;
    # crucially the loader-derived service identity is the bare display
    # name — the filename stamp must not leak into it
    assert "ComposePostService" in by_svc, sorted(by_svc)
    assert by_svc["ComposePostService"].n_error == 1
    assert by_svc["PostStorageService"].n_lines == 2
    # absent services carry the no-log-file row (the golden run's
    # stop-fault fingerprint), not a fabricated zero-count file
    text = (tmp_path / "summary.txt").read_text()
    assert "TextService: 未找到日志文件" in text
    assert not list(tmp_path.glob("TextService_*.log"))


def test_jacoco_collect_renders_loadable_report_tree(tmp_path, cluster):
    col = JacocoCoverageCollector(runner=_runner(cluster))
    rep = col.collect(tmp_path / "coverage_data", tmp_path / "report")
    assert rep.kind == "jacoco_coverage"
    assert rep.n_skipped == 0
    # exec files pulled with the pod__basename convention
    assert all("__jacoco-" in f for f in rep.files)
    from anomod.io.coverage import load_tt_coverage_report
    cb = load_tt_coverage_report(tmp_path / "report")
    assert cb is not None
    ratios = dict(zip(cb.services, cb.service_ratio()))
    assert ratios["ts-order-service"] == pytest.approx(0.7)
    assert ratios["ts-travel-service"] == pytest.approx(0.3)


def test_dump_failure_skips_pod_and_continues(tmp_path, cluster):
    cluster.jacoco_pods = {"ts-travel-service-5f7b8-x2k4p"}  # order has no jar
    col = JacocoCoverageCollector(runner=_runner(cluster))
    rep = col.collect(tmp_path / "coverage_data", tmp_path / "report")
    assert rep.n_skipped == 1
    assert len(rep.files) == 1 and "ts-travel" in rep.files[0]


def test_runner_timeout_degrades_not_raises(cluster):
    r = ExecRunner(timeout=0.001)
    res = r.run(["sleep", "5"])
    assert res.returncode != 0


def test_gcov_collection_roundtrips_through_loader(tmp_path):
    """SN gcov loop against a fake docker: SIGUSR1 flush per container,
    collect script per service writing into the mounted report tree, the
    host move, and load_sn_coverage_dir consuming the result."""
    from anomod.io.live_exec import ExecResult, GcovCoverageCollector

    mount = tmp_path / "coverage-reports"
    running = {"compose-post-service", "text-service"}
    flushes = []

    def fake(cmd):
        joined = " ".join(cmd)
        if cmd[:2] == ["docker", "ps"]:
            names = [f"socialnetwork_{s}_1" for s in sorted(running)]
            return ExecResult(0, "\n".join(names) + "\n")
        if "kill -USR1 1" in joined:
            flushes.append(cmd[2])
            return ExecResult(0)
        if "collect_coverage.sh" in joined:
            env = dict(kv.split("=", 1) for kv in cmd[3:-2:2])
            svc = env["SERVICE_NAME"]
            d = (mount / f"{env['EXPERIMENT_BASE_NAME']}_"
                         f"{env['TIMESTAMP']}" / svc)
            d.mkdir(parents=True, exist_ok=True)
            covered = 7 if svc == "text-service" else 3
            lines = [f"        -:    0:Source:src/{svc}.cpp"]
            for i in range(1, 11):
                mark = "5" if i <= covered else "#####"
                lines.append(f"        {mark}:{i:5d}:line {i};")
            (d / f"src#{svc}.cpp.gcov").write_text("\n".join(lines) + "\n")
            return ExecResult(0)
        return ExecResult(1, "", f"unscripted: {joined}")

    col = GcovCoverageCollector(runner=ExecRunner(run_fn=fake))
    out = tmp_path / "coverage_data" / "Exp_coverage_TS"
    rep = col.collect(mount, out, base="Exp", stamp="TS")
    assert rep.kind == "gcov_coverage"
    assert len(flushes) == 2                # one SIGUSR1 per container
    assert rep.n_records == 2               # one gcov file per service
    assert rep.n_skipped == len(col.services) - 2
    from anomod.io.coverage import load_sn_coverage_dir
    cb = load_sn_coverage_dir(out)
    assert cb is not None
    ratios = dict(zip(cb.services, cb.service_ratio()))
    assert ratios["text-service"] == pytest.approx(0.7)
    assert ratios["compose-post-service"] == pytest.approx(0.3)
    # a second run against the same (now existing) target must degrade
    # loudly — never nest the tree one level deep or crash
    rep2 = col.collect(mount, out, base="Exp", stamp="TS")
    assert rep2.n_records == 0
    assert any("target exists" in n for n in rep2.notes)
    assert load_sn_coverage_dir(out) is not None   # first run intact
