"""Policy-driven elastic serving: signal-fed autoscaler, live
rebalance, and deterministic scaling episodes (anomod.serve.policy,
ISSUE-13).

The central pin: a seeded sub-capacity run hit by a scripted load
surge (the chaos ``surge`` kind) under ``ANOMOD_SERVE_POLICY=auto``
produces at least one scale-up AND one scale-down episode, the SAME
migration schedule on rerun and on an ``anomod audit replay`` from the
flight header alone — and tenant states, alerts, SLO and shed stay
BYTE-identical to the static run of the same seed with the policy off
(equal canonical flight journals under ``anomod audit diff``
semantics): the autoscaler moves wall-clock capacity around, never a
scored byte.
"""

import dataclasses

import numpy as np
import pytest

from anomod.obs.flight import diff_journals
from anomod.serve.engine import (SHARD_VARIANT_REPORT_FIELDS,
                                 run_power_law)

#: the compact seeded scenario (the supervise-test idiom): sub-capacity
#: base load so a surge creates real pressure dynamics, long enough
#: that the up → down round trip completes inside the run
KW = dict(n_tenants=6, n_services=4, capacity_spans_per_s=1000,
          overload=0.6, duration_s=24, tick_s=1.0, seed=5,
          window_s=5.0, baseline_windows=4, fault_tenants=0,
          buckets=(64, 256), lane_buckets=(1, 2, 4), max_backlog=1500,
          n_windows=16, flight_digest_every=4)

#: a 6x surge for ticks 6..11: offered load jumps from 0.6x to 3.6x of
#: capacity, then drops back — the canonical episode forcer
SURGE = "surge@6:factor=6:ticks=6"

#: report fields that legitimately differ between a policy-on and a
#: policy-off run of the same seed: the executed decision counts and
#: the mode, plus n_checkpoints (every topology change takes an extra
#: baseline checkpoint so the recovery log never spans a scale
#: boundary); every OTHER canonical field must match byte-for-byte
POLICY_REPORT_FIELDS = ("policy", "n_scale_ups", "n_scale_downs",
                        "n_rebalances", "n_policy_migrations",
                        "brownout_ticks", "n_checkpoints")


def scaling_events(eng):
    return [ev for t in eng.flight_recorder.records()
            for ev in t.get("scaling", ())]


@pytest.fixture(scope="module")
def static():
    """The policy-off reference: same seed, same surge, fixed 1 shard."""
    eng, rep = run_power_law(shards=1, chaos=SURGE, **KW)
    return eng, rep, eng.flight_recorder.journal()


@pytest.fixture(scope="module")
def elastic():
    """The policy-on run: auto mode, 1→2 shard envelope, tight
    cooldown so the full up → down round trip fits in 24 ticks."""
    eng, rep = run_power_law(shards=1, chaos=SURGE, policy="auto",
                             min_shards=1, max_shards=2,
                             cooldown_ticks=3, **KW)
    return eng, rep


def assert_elastic_no_score_gap(static, eng, rep, extra_skip=()):
    """Byte-identical tenant states + alert streams, identical SLO /
    shed / canonical report fields, equal canonical flight journals —
    the elastic twin of the recovery no-score-gap contract."""
    ref_eng, ref_rep, ref_journal = static
    tids = sorted(ref_eng._tenant_det)
    assert tids == sorted(eng._tenant_det)
    for tid in tids:
        assert [dataclasses.asdict(a) for a in ref_eng.alerts_for(tid)] \
            == [dataclasses.asdict(a) for a in eng.alerts_for(tid)], \
            f"tenant {tid} alert stream diverges"
        s1 = ref_eng._tenant_replay[tid].state
        s2 = eng._tenant_replay[tid].state
        assert np.array_equal(np.asarray(s1.agg), np.asarray(s2.agg)), \
            f"tenant {tid} agg plane diverges"
        assert np.array_equal(np.asarray(s1.hist), np.asarray(s2.hist)), \
            f"tenant {tid} hist plane diverges"
    skip = set(SHARD_VARIANT_REPORT_FIELDS) | set(POLICY_REPORT_FIELDS) \
        | set(extra_skip)
    a = {k: v for k, v in ref_rep.to_dict().items() if k not in skip}
    b = {k: v for k, v in rep.to_dict().items() if k not in skip}
    assert a == b, sorted(k for k in a if a[k] != b[k])
    d = diff_journals(ref_journal, eng.flight_recorder.journal())
    assert d is None, d


def test_elastic_episode_fires_and_leaves_no_score_gap(static, elastic):
    """The acceptance-criteria pin, part 1: the surge forces a full
    scaling episode (up into the surge, down after it), tenants
    actually migrate, and every decision surface stays byte-identical
    to the static run — including the report's dispatch counts, which
    must cover the RETIRED shard's book (scale-down keeps it)."""
    eng, rep = elastic
    assert rep.policy == "auto"
    assert rep.n_scale_ups >= 1
    assert rep.n_scale_downs >= 1
    assert rep.n_policy_migrations >= 2      # delta up + drain down
    assert rep.peak_shards == 2 and rep.shards == 1
    events = scaling_events(eng)
    kinds = [ev["kind"] for ev in events]
    assert "scale_up" in kinds and "scale_down" in kinds
    assert kinds.index("scale_up") < kinds.index("scale_down")
    up = next(ev for ev in events if ev["kind"] == "scale_up")
    assert up["from"] == 1 and up["to"] == 2
    assert up["tenants"] == len(up["moved"])
    assert_elastic_no_score_gap(static, eng, elastic[1])


def test_elastic_schedule_identical_on_rerun_and_audit_replay(elastic):
    """The acceptance-criteria pin, part 2: the same seed produces the
    SAME migration schedule on a fresh rerun AND on a replay driven
    from the flight header alone (what `anomod audit replay` executes),
    with byte-identical canonical journals."""
    eng, _ = elastic
    events = scaling_events(eng)
    assert events                                 # episodes exist
    rerun, _ = run_power_law(shards=1, chaos=SURGE, policy="auto",
                             min_shards=1, max_shards=2,
                             cooldown_ticks=3, **KW)
    assert scaling_events(rerun) == events
    assert rerun.flight_recorder.canonical_bytes() \
        == eng.flight_recorder.canonical_bytes()
    # the header round trip: every policy knob rides the run dict
    # RESOLVED, so replay re-executes the same elastic invocation
    run = dict(eng.flight_recorder.header["run"])
    assert run["policy"] == "auto" and run["max_shards"] == 2
    run["buckets"] = tuple(run["buckets"])
    run["lane_buckets"] = tuple(run["lane_buckets"])
    replay, _ = run_power_law(**run)
    assert scaling_events(replay) == events
    assert replay.flight_recorder.canonical_bytes() \
        == eng.flight_recorder.canonical_bytes()


def test_rendezvous_minimal_disruption():
    """The property scale-up/down correctness rests on: growing the
    candidate set by one shard moves ONLY the tenants the NEW shard
    wins (everyone else keeps their owner), and shrinking it moves
    ONLY the removed shard's tenants — and the won set is a sane share
    of the fleet, not a degenerate clump (the raw-crc32 comparison
    failed this: its XOR-linear scores made whole runs of consecutive
    tenant ids prefer one shard, so a small fleet's first scale-up
    moved NOTHING)."""
    from anomod.serve.shard import rendezvous_shard
    tenants = range(400)
    for n in (1, 2, 3, 7):
        before = {t: rendezvous_shard(t, n) for t in tenants}
        after = {t: rendezvous_shard(t, n + 1) for t in tenants}
        delta = {t for t in tenants if after[t] == n}
        # only the new shard's winners changed owner
        for t in tenants:
            if t not in delta:
                assert after[t] == before[t], \
                    f"tenant {t} moved without its owner changing"
        # the won share is near 1/(n+1) — the balanced-growth property
        expect = len(list(tenants)) / (n + 1)
        assert 0.5 * expect <= len(delta) <= 1.7 * expect
        # shrinking is the exact inverse of growing: the removed
        # shard's tenants re-place, nobody else moves
        for t in tenants:
            if after[t] != n:
                assert rendezvous_shard(t, n) == after[t]
    # candidates subset (the dead-shard / scale-down form) agrees with
    # the full-range draw when the sets coincide
    assert rendezvous_shard(17, 4) == rendezvous_shard(
        17, 99, candidates=range(4))


def test_scripted_policy_executes_schedule():
    """`ANOMOD_SERVE_POLICY=script` replays a fixed scaling schedule:
    every action fires at its tick, envelope-clamped actions are
    journaled as skipped (never silent), and the run still carries no
    score gap vs static."""
    eng_s, rep_s = run_power_law(shards=1, **KW)
    eng, rep = run_power_law(
        shards=1, policy="script",
        policy_script="up@5;up@8;down@14;down@17", min_shards=1,
        max_shards=2, **KW)
    events = scaling_events(eng)
    assert [(ev["kind"], ev["tick"]) for ev in events] == \
        [("scale_up", 5), ("scale_up", 8), ("scale_down", 14),
         ("scale_down", 17)]
    assert events[1].get("skipped", "").startswith("at max_shards")
    assert events[3].get("skipped", "").startswith("at min_shards")
    assert rep.n_scale_ups == 1 and rep.n_scale_downs == 1
    assert_elastic_no_score_gap(
        (eng_s, rep_s, eng_s.flight_recorder.journal()), eng, rep)


def test_plan_rebalance_moves_hottest_and_respects_dead_shards():
    """The rebalance pass: hottest tenant moves from the most- to the
    least-loaded shard, a balanced fleet yields an empty plan, and a
    dead shard is never a destination."""
    from anomod.serve.policy import plan_rebalance
    from anomod.serve.queues import TenantSpec
    specs = [TenantSpec(t, f"t{t}", priority=1,
                        rate_spans_per_s=10.0) for t in range(6)]
    shard_of = {0: 0, 1: 0, 2: 0, 3: 0, 4: 1, 5: 1}
    rates = {0: 500.0, 1: 20.0, 2: 20.0, 3: 20.0, 4: 10.0, 5: 10.0}
    moves = plan_rebalance(shard_of, 2, specs, rates, 10_000.0, k=1)
    assert moves == [(0, 1)]                     # the head tenant moves
    # balanced fleet -> empty plan
    flat = {t: t % 2 for t in range(6)}
    even = {t: 10.0 for t in range(6)}
    assert plan_rebalance(flat, 2, specs, even, 10_000.0, k=2) == []
    # three shards, destination 1 dead: the move lands on 2, not 1
    shard3 = {0: 0, 1: 0, 2: 0, 3: 0, 4: 2, 5: 2}
    moves3 = plan_rebalance(shard3, 3, specs, rates, 10_000.0, k=1,
                            dead=(1,))
    assert moves3 and all(dst != 1 for _, dst in moves3)


def test_rebalance_in_engine_keeps_parity(static):
    """A scripted rebalance on a live 2-shard engine migrates tenants
    through the state seams with no score gap."""
    eng, rep = run_power_law(shards=1, chaos=SURGE, policy="script",
                             policy_script="up@4;rebalance@10:k=2;"
                                           "down@16",
                             min_shards=1, max_shards=2, **KW)
    ev = [e for e in scaling_events(eng) if e["kind"] == "rebalance"]
    assert len(ev) == 1
    assert_elastic_no_score_gap(static, eng, rep)


def test_brownout_ladder_tightens_and_relaxes_deterministically():
    """The degradation ladder: level 1 tightens the RCA budget, level
    2 coarsens the flight digest cadence 4x (visible as missing
    cadence digests), relaxing restores in reverse order — and the
    detector decision surface stays byte-identical to static (the
    ladder degrades auxiliary planes, never admission/scoring)."""
    eng_s, rep_s = run_power_law(shards=1, **KW)
    eng, rep = run_power_law(
        shards=1, policy="script",
        policy_script="brownout@4:level=1;brownout@8:level=2;"
                      "brownout@16:level=0",
        min_shards=1, max_shards=2, **KW)
    assert [(e["kind"], e["tick"], e["from"], e["to"])
            for e in scaling_events(eng)] == \
        [("brownout", 4, 0, 1), ("brownout", 8, 1, 2),
         ("brownout", 16, 2, 0)]
    assert rep.brownout_ticks == 12              # ticks 5..16 at >=1
    # level 2 coarsened the digest cadence: base 4 -> 16 over ticks
    # 8..15, so the tick-11 cadence digest is skipped (12 % 16 != 0)
    # while tick 15 still digests ((15+1) % 16 == 0, same crc as the
    # static run — coarsening drops anchors, it never changes them)
    digests = {t["tick"]: t["fold"]["state_digest"]
               for t in eng.flight_recorder.records()}
    base = {t["tick"]: t["fold"]["state_digest"]
            for t in eng_s.flight_recorder.records()}
    assert base[11] is not None and base[15] is not None
    assert digests[11] is None
    assert digests[15] == base[15]
    assert digests[19] == base[19]               # relaxed: cadence back
    # decisions untouched: states/alerts/SLO/shed byte-identical
    for tid in eng_s._tenant_det:
        assert [dataclasses.asdict(a) for a in eng_s.alerts_for(tid)] \
            == [dataclasses.asdict(a) for a in eng.alerts_for(tid)]
        assert np.array_equal(
            np.asarray(eng_s._tenant_replay[tid].state.agg),
            np.asarray(eng._tenant_replay[tid].state.agg))
    assert rep.latency == rep_s.latency
    assert rep.shed_fraction == rep_s.shed_fraction


def test_rca_evidence_migrates_with_tenants():
    """An elastic run with online RCA carries each tenant's evidence
    buffer to its new shard: the verdict stream is byte-identical to
    the static RCA run of the same seed."""
    kw = {**KW, "fault_tenants": 1, "window_s": 2.0}
    eng_s, _ = run_power_law(shards=1, rca=True, **kw)
    eng, rep = run_power_law(shards=1, rca=True, policy="script",
                             policy_script="up@5;down@15",
                             min_shards=1, max_shards=2, **kw)
    assert rep.n_scale_ups == 1 and rep.n_scale_downs == 1
    assert [v.to_dict() for v in eng.rca_verdicts] \
        == [v.to_dict() for v in eng_s.rca_verdicts]
    assert eng_s.rca_verdicts                    # the pin is live


def test_surge_chaos_amplifies_deterministically():
    """The chaos 'surge' kind multiplies offered arrivals for its
    window — deterministically (two runs agree span-for-span) and
    visibly (offered volume strictly above the no-surge run)."""
    _, rep_plain = run_power_law(shards=1, **KW)
    _, rep_a = run_power_law(shards=1, chaos=SURGE, **KW)
    _, rep_b = run_power_law(shards=1, chaos=SURGE, **KW)
    assert rep_a.offered_spans == rep_b.offered_spans
    assert rep_a.offered_spans > 2 * rep_plain.offered_spans
    assert rep_a.shed_spans > 0                  # the surge overloads


def test_policy_knob_validation(monkeypatch):
    """Every ANOMOD_SERVE_POLICY* knob is Config-validated (fail-loud),
    the script grammars refuse malformed shapes, and the engine
    refuses nonsense envelopes / unsupported planes."""
    from anomod.config import (Config, validate_chaos_script,
                               validate_policy_script)
    for var, bad in (("ANOMOD_SERVE_POLICY", "sometimes"),
                     ("ANOMOD_SERVE_POLICY_SCRIPT", "warp@5"),
                     ("ANOMOD_SERVE_POLICY_MIN_SHARDS", "0"),
                     ("ANOMOD_SERVE_POLICY_MAX_SHARDS", "-2"),
                     ("ANOMOD_SERVE_POLICY_TARGET_IMBALANCE", "0.5"),
                     ("ANOMOD_SERVE_POLICY_COOLDOWN_TICKS", "0")):
        monkeypatch.setenv(var, bad)
        with pytest.raises(ValueError):
            Config()
        monkeypatch.delenv(var)
    cfg = Config()
    assert cfg.serve_policy == "off"
    assert cfg.serve_policy_script == ""
    assert cfg.serve_policy_min_shards == 1
    assert cfg.serve_policy_max_shards == 8
    assert cfg.serve_policy_target_imbalance == 1.5
    assert cfg.serve_policy_cooldown_ticks == 8
    # policy script grammar
    good = validate_policy_script("up@3;rebalance@7:k=2;down@9;"
                                  "brownout@11:level=2")
    assert [a["action"] for a in good] == ["up", "rebalance", "down",
                                          "brownout"]
    for bad in ("up", "up@x", "up@-1", "up@5:k=2", "rebalance@5:k=0",
                "brownout@5:level=9", "sideways@5"):
        with pytest.raises(ValueError):
            validate_policy_script(bad)
    # surge grammar: score-path keys refused on a surge and vice versa
    got = validate_chaos_script("surge@5:factor=3:ticks=8")
    assert got[0]["factor"] == 3 and got[0]["ticks"] == 8
    for bad in ("surge@5:phase=score", "surge@5:shard=1",
                "surge@5:factor=1", "surge@5:ticks=0",
                "crash@5:factor=2"):
        with pytest.raises(ValueError):
            validate_chaos_script(bad)
    # engine refusals
    from anomod.replay import ReplayConfig
    from anomod.serve.engine import ServeEngine
    from anomod.serve.queues import TenantSpec
    specs = [TenantSpec(0, "t0", rate_spans_per_s=10.0)]
    cfg2 = ReplayConfig(n_services=2, n_windows=8, window_us=1_000_000,
                        chunk_size=64)
    with pytest.raises(ValueError, match="envelope"):
        ServeEngine(specs, ["a", "b"], cfg2, shards=1, policy="auto",
                    min_shards=2, max_shards=4)
    with pytest.raises(ValueError, match="non-empty"):
        ServeEngine(specs, ["a", "b"], cfg2, policy="script")
    with pytest.raises(ValueError, match="multimodal"):
        ServeEngine(specs, ["a", "b"], cfg2, policy="auto",
                    multimodal=True)
    # env default degrades to off on an unsupported plane
    monkeypatch.setenv("ANOMOD_SERVE_POLICY", "auto")
    from anomod.config import set_config
    set_config(Config())
    try:
        eng = ServeEngine(specs, ["a", "b"], cfg2, multimodal=True)
        assert eng.policy is None
        eng.close()
    finally:
        monkeypatch.delenv("ANOMOD_SERVE_POLICY")
        set_config(Config())


def test_supervisor_backoff_clock_injectable():
    """Satellite: the supervisor's respawn backoff sleeps through an
    injectable clock — a fake sleep records the schedule, no wall
    stall, and the D101 suppression is gone from supervise.py."""
    from pathlib import Path

    from anomod.serve.engine import ServeEngine
    from anomod.serve.queues import TenantSpec
    from anomod.replay import ReplayConfig
    from anomod.serve.supervise import ShardSupervisor
    slept = []
    specs = [TenantSpec(0, "t0", rate_spans_per_s=10.0)]
    cfg = ReplayConfig(n_services=2, n_windows=8, window_us=1_000_000,
                       chunk_size=64)
    eng = ServeEngine(specs, ["a", "b"], cfg, ckpt_every=0)
    sup = ShardSupervisor(eng, ckpt_every=4, retries=2,
                          backoff_s=0.5, max_respawns=1,
                          sleep_fn=slept.append)
    sup._checkpoint()
    # drive one recovery attempt: the backoff goes through the
    # injected clock (doubling), never time.sleep
    sup._fail_counts.clear()
    try:
        sup._recover_shard(0, RuntimeError("probe"))
    except Exception:
        pass
    assert slept and slept[0] == 0.5
    eng.close()
    src = (Path(__file__).parent.parent / "anomod" / "serve"
           / "supervise.py").read_text()
    assert "anomod-lint: disable=D101" not in src


@pytest.mark.slow
def test_elastic_with_crash_chaos_recovers_clean(static):
    """Composition: a surge-driven elastic run ALSO hit by a worker
    crash on the scaled-up shard recovers through supervision with the
    canonical journal still equal to the static fault-free run."""
    # tick 9: one tick after the scale-up, the new shard 1 serves a
    # slice (credit-quantized ticks like 10 can serve nothing — a
    # scripted fault on an empty slice would silently never fire)
    eng, rep = run_power_law(
        shards=1, chaos=SURGE + ";crash@9:shard=1:phase=dispatch",
        policy="auto", min_shards=1, max_shards=2, cooldown_ticks=3,
        ckpt_every=4, **KW)
    assert rep.n_scale_ups >= 1
    assert rep.n_shard_crashes >= 1 and rep.n_respawns >= 1
    assert_elastic_no_score_gap(
        static, eng, rep,
        extra_skip=("ckpt_every", "n_shard_crashes", "n_respawns",
                    "n_restored_ticks"))
