"""Driver-contract test for bench.py: forced onto the CPU fallback it must
still exit 0 and print exactly one JSON line with the metric fields the
driver records (the round-1 capture failed precisely because this path
wasn't hardened)."""

import json
import os
import subprocess
import sys
from pathlib import Path


def test_bench_cpu_fallback_contract(tmp_path):
    env = dict(os.environ)
    env["ANOMOD_BENCH_PLATFORM"] = "cpu"
    # an explicit pallas override off-TPU must be downgraded, not honored
    # into the never-finishing interpret path (advisor r2)
    env["ANOMOD_BENCH_KERNEL"] = "pallas"
    # keep the provenance record out of the repo's bench_runs/
    env["ANOMOD_BENCH_RUNS_DIR"] = str(tmp_path / "runs")
    # fresh ingest cache: the run must be cold-then-self-warming
    env["ANOMOD_CACHE_DIR"] = str(tmp_path / "cache")
    # small corpus keeps the fallback fast; the platform pin bypasses the
    # subprocess backend probe entirely
    r = subprocess.run(
        [sys.executable, str(Path(__file__).parent.parent / "bench.py"),
         "200"],
        capture_output=True, text=True, timeout=420, env=env)
    assert r.returncode == 0, r.stderr[-500:]
    lines = [l for l in r.stdout.strip().splitlines() if l.startswith("{")]
    assert len(lines) == 1, r.stdout
    out = json.loads(lines[0])
    assert out["metric"] == "tt_replay_throughput"
    assert out["unit"] == "spans/sec/chip"
    assert out["value"] > 0 and out["vs_baseline"] > 0
    assert out["kernel"] == "numpy"        # pallas never runs off-TPU; the
    assert "kernel_note" in out            # CPU engine takes over, explained
    assert "device_note" in out            # fallback is explained
    # median-of-N: the recorded wall is the median of >=3 raw repeats
    assert len(out["raw_wall_s"]) >= 3
    assert out["wall_s"] == sorted(out["raw_wall_s"])[len(out["raw_wall_s"]) // 2]
    # ingest split: a fresh cache dir means a cold first load, an honest
    # recorded parse_s, and a warm-vs-cold throughput metric in the line
    assert out["cache_hit"] is False
    assert out["parse_s"] > 0
    tp = out["tt_ingest_throughput"]
    assert tp["unit"] == "experiments/sec"
    assert tp["warm"] > 0 and tp["cold"] > 0
    assert tp["speedup"] > 1.0, \
        "warm columnar read must beat cold synth+concat"
    # provenance record: committed-capture schema with device + versions + SHA
    runs = list((tmp_path / "runs").glob("*.json"))
    assert len(runs) == 1
    rec = json.loads(runs[0].read_text())
    for field in ("metric", "value", "unit", "timestamp_utc", "git_sha",
                  "jax_version", "device", "kernel", "raw_wall_s"):
        assert field in rec, field
    assert rec["device"] == out["device"]


def test_bench_replicate_override_contract(tmp_path):
    """ANOMOD_BENCH_REPLICATE: a valid override is recorded in
    replicate_used (on non-CPU platforms it resizes the dispatch; the CPU
    fallback ignores it — device-sized replication would run for hours on
    a host core) and a malformed value is rejected with a note instead of
    burning the capture."""
    base = dict(os.environ)
    base["ANOMOD_BENCH_PLATFORM"] = "cpu"
    base["ANOMOD_BENCH_RUNS_DIR"] = str(tmp_path / "runs")
    base["ANOMOD_CACHE_DIR"] = str(tmp_path / "cache")

    env = dict(base, ANOMOD_BENCH_REPLICATE="7")
    r = subprocess.run(
        [sys.executable, str(Path(__file__).parent.parent / "bench.py"),
         "200"], capture_output=True, text=True, timeout=420, env=env)
    assert r.returncode == 0, r.stderr[-500:]
    out = json.loads([l for l in r.stdout.strip().splitlines()
                      if l.startswith("{")][0])
    assert out["replicate_used"] == 2      # CPU fallback keeps its sizing
    assert "replicate_note" not in out

    env = dict(base, ANOMOD_BENCH_REPLICATE="4k")
    r = subprocess.run(
        [sys.executable, str(Path(__file__).parent.parent / "bench.py"),
         "200"], capture_output=True, text=True, timeout=420, env=env)
    assert r.returncode == 0, r.stderr[-500:]
    out = json.loads([l for l in r.stdout.strip().splitlines()
                      if l.startswith("{")][0])
    assert out["value"] > 0                # capture survived the bad value


def test_bench_serve_mode_contract(tmp_path):
    """`bench.py --mode serve` on the CPU fallback: exit 0, one JSON line
    with sustained spans/sec, p99 admission->scored latency and the shed
    fraction under the seeded 2x overload, plus a provenance record."""
    env = dict(os.environ)
    env["ANOMOD_BENCH_PLATFORM"] = "cpu"
    env["ANOMOD_BENCH_RUNS_DIR"] = str(tmp_path / "runs")
    # tiny fleet keeps the tier-1 contract fast; the protocol (2x
    # overload, seeded) is what's under test, not the absolute number
    env["ANOMOD_SERVE_BENCH_CAPACITY"] = "1500"
    env["ANOMOD_SERVE_BENCH_DURATION"] = "45"
    env["ANOMOD_SERVE_BENCH_TENANTS"] = "12"
    # small registered-fleet sweep keeps the census probe fast; the
    # committed capture uses the 1e3/1e4/1e5 default
    env["ANOMOD_CENSUS_SWEEP"] = "400,1600,6400"
    r = subprocess.run(
        [sys.executable, str(Path(__file__).parent.parent / "bench.py"),
         "--mode", "serve"],
        capture_output=True, text=True, timeout=420, env=env)
    assert r.returncode == 0, r.stderr[-800:]
    lines = [l for l in r.stdout.strip().splitlines() if l.startswith("{")]
    assert len(lines) == 1, r.stdout
    out = json.loads(lines[0])
    assert out["metric"] == "serve_sustained_throughput"
    assert out["unit"] == "spans/sec"
    assert out["value"] > 0
    assert out["overload"] == 2.0
    # 2x overload against a bounded backlog MUST shed
    assert 0.2 < out["shed_fraction"] < 0.8
    assert out["p99_admission_to_scored_latency_s"] is not None
    assert out["served_spans"] > 0
    assert out["offered_spans"] > out["served_spans"]
    assert out["device"]
    # telemetry pair (observability PR): same seed off/on, overhead
    # fraction recorded, the enabled leg's registry snapshotted inline
    tel = out["telemetry"]
    assert tel["spans_per_sec_off"] > 0 and tel["spans_per_sec_on"] > 0
    assert 0.0 <= tel["overhead_fraction"] < 1.0
    assert tel["journal_samples"] > 0
    assert out["obs_snapshot"]["anomod_serve_served_spans_total"][
        "value"] == out["served_spans"]
    # shard-scaling legs (scale-out PR): 2/4 workers then a warm
    # 1-shard reference, all on the same seed; shedding and p99 are
    # shard-count-invariant by construction
    scaling = out["shard_scaling"]
    assert set(scaling) == {"1", "2", "4"}
    assert scaling["1"]["speedup_vs_1_shard"] == 1.0
    for leg in scaling.values():
        assert leg["spans_per_sec"] > 0
        assert leg["shed_fraction"] == out["shed_fraction"]
        assert leg["p99_latency_s"] == \
            out["p99_admission_to_scored_latency_s"]
        assert leg["shard_imbalance"] >= 1.0
    # jit-cache block present (disabled by default in this env)
    assert out["jit_cache"]["enabled"] in (True, False)
    runs = list((tmp_path / "runs").glob("*.json"))
    assert len(runs) == 1
    rec = json.loads(runs[0].read_text())
    assert rec["metric"] == "serve_sustained_throughput"
    assert rec["shed_fraction"] == out["shed_fraction"]
    # the committed self-scrape capture: TT-CSV sidecar next to the
    # record, loadable by the framework's own loader
    scrape = out["self_scrape"]
    csvs = list((tmp_path / "runs").glob("*_selfscrape.csv"))
    assert len(csvs) == 1
    assert scrape["samples"] > 0
    from anomod.io.metrics import load_tt_metric_csv
    batch = load_tt_metric_csv(csvs[0])
    assert batch is not None and batch.n_samples == scrape["samples"]
    # fused-vs-unfused on the same seed (PR-4): the tenant-fused
    # lane-stacked path is the headline, the unfused leg rides along
    fd = out["fused_dispatch"]
    assert fd["fused"] is True
    assert fd["spans_per_sec_fused"] == out["value"]
    assert fd["spans_per_sec_unfused"] > 0
    assert fd["speedup"] > 0
    assert fd["fused_dispatches"] > 0
    assert fd["lane_buckets"]
    assert 0.0 <= fd["lane_pad_waste"] < 1.0
    # staging decomposition (ISSUE-7, five-legged since ISSUE-8):
    # stage/dispatch/fold/score/other walls on the native AND
    # interpreter-staging legs of the same seed, plus the byte-parity
    # bits the native path is pinned to
    st = out["staging"]
    assert st["native_mode"] in ("auto", "on", "off")
    assert st["native_available"] in (True, False)
    for leg in ("wall_s_native", "wall_s_python"):
        walls = st[leg]
        assert set(walls) == {"stage", "dispatch", "fold", "score",
                              "other", "serve"}
        assert all(v >= 0 for v in walls.values())
        assert walls["stage"] + walls["dispatch"] + walls["fold"] \
            + walls["score"] <= walls["serve"] + 1e-6
    assert st["spans_per_sec_native"] > 0
    assert st["spans_per_sec_python"] > 0
    if st["native_available"] and st["native_mode"] != "off":
        assert st["native_staging_headline"] is True
        assert st["native_staged_dispatches"] > 0
    par = st["parity"]
    assert par["alerts_identical"] is True
    assert par["states_identical"] is True
    assert par["p99_identical"] is True
    assert par["shed_identical"] is True
    # tenant-state residency (ISSUE-8): the device-pool headline vs the
    # host-seam reference on the same seed, five-leg decompositions, the
    # fold+score+other share, and the pool's byte-parity bits
    ss = out["serve_state"]
    assert ss["headline"] == "device"
    assert ss["pool_engine"] in ("numpy", "jax")
    for leg in ("wall_s_device", "wall_s_host_seam"):
        assert set(ss[leg]) == {"stage", "dispatch", "fold", "score",
                                "other", "serve"}
    for share in ("fold_score_other_share_device",
                  "fold_score_other_share_host_seam"):
        assert 0.0 <= ss[share] <= 1.0
    assert ss["spans_per_sec_device"] > 0
    assert ss["spans_per_sec_host_seam"] > 0
    par = ss["parity"]
    assert par["alerts_identical"] is True
    assert par["states_identical"] is True
    assert par["p99_identical"] is True
    assert par["shed_identical"] is True
    # online-RCA block (ISSUE-6): alert→culprit numbers on the same
    # seed plus the determinism pins the capture must carry
    rca = out["rca"]
    assert rca["enabled"] is True
    assert rca["n_rca_runs"] > 0
    assert set(rca["topk_hits"]) == set(rca["topk_hit_rate"]) \
        == set(rca["topk_hit_rate_given_detected"]) == {"1", "3", "5"}
    assert rca["n_fault_tenants"] == 2
    assert 0 <= rca["eligible_fault_tenants"] <= rca["n_fault_tenants"]
    for k in ("1", "3", "5"):
        rate = rca["topk_hit_rate"][k]
        assert rate is not None and 0.0 <= rate <= 1.0
    # hit-rate is monotone in k by construction
    assert rca["topk_hit_rate"]["1"] <= rca["topk_hit_rate"]["3"] \
        <= rca["topk_hit_rate"]["5"]
    assert rca["alert_to_culprit_latency_s"]["p99_s"] is not None
    assert rca["queue_delay_virtual_s"]["p50_s"] is not None
    assert rca["rca_wall_s"] > 0
    assert rca["spans_per_sec_rca_on"] > 0
    par = rca["parity"]
    assert par["alerts_identical_to_rca_off"] is True
    assert par["states_identical_to_rca_off"] is True
    assert par["p99_identical_to_rca_off"] is True
    assert par["shed_identical_to_rca_off"] is True
    assert par["verdicts_identical_1_vs_2_shards"] is True
    # flight-recorder block (ISSUE-9): the always-on tick journal's
    # overhead leg, zero ring drops (no silent loss), and the read-side
    # byte-parity bits against the no-recorder leg
    fl = out["flight"]
    assert fl["enabled_headline"] is True
    assert fl["recorded_ticks"] > 0
    assert fl["dropped_ticks"] == 0
    assert fl["digest_every"] >= 1
    assert fl["spans_per_sec_on"] == out["value"]
    assert fl["spans_per_sec_off"] > 0
    assert 0.0 <= fl["overhead_fraction"] < 1.0
    par = fl["parity"]
    assert par["alerts_identical"] is True
    assert par["states_identical"] is True
    assert par["p99_identical"] is True
    assert par["shed_identical"] is True
    # recovery block (ISSUE-10): the checkpoint cadence priced in-run
    # on the headline (ckpt_wall / serve_wall — no A/B leg by design),
    # the chaos leg's crash/restore counts, and the no-score-gap
    # parity bits (byte-identical decisions + equal canonical flight
    # journals)
    rc = out["recovery"]
    assert rc["supervised_headline"] is True
    assert rc["ckpt_every"] >= 1
    assert rc["n_checkpoints"] >= 1
    assert rc["ckpt_wall_s"] >= 0
    assert 0.0 <= rc["ckpt_overhead_fraction"] < 1.0
    assert rc["chaos_script"]
    assert rc["n_shard_crashes"] == 3          # the scripted campaign
    assert rc["n_restored_ticks"] >= rc["n_shard_crashes"]
    assert rc["n_quarantined"] == 0            # repeat=1 faults recover
    assert rc["n_migrated_tenants"] == 0
    assert rc["mttr_ticks"] >= 1
    assert rc["recovery_wall_s"] >= 0
    par = rc["parity"]
    assert par["alerts_identical"] is True
    assert par["states_identical"] is True
    assert par["p99_identical"] is True
    assert par["shed_identical"] is True
    assert par["journal_canonical_identical"] is True
    # performance-observatory block (ISSUE-14): the dispatch-lifecycle
    # timeline's overlap-headroom bound, the measured fold WAIT, the
    # per-tick raw_wall_s samples `anomod perf diff` bootstraps over,
    # the on/off overhead fraction, and the read-side parity bits
    pf = out["perf"]
    assert pf["enabled_headline"] is False     # deep-dive opt-in, off
    assert pf["events_recorded"] > 0
    assert pf["events_dropped"] == 0
    assert pf["overlap_headroom_s"] >= 0.0
    assert pf["fold_wait_s"] >= 0.0
    assert pf["fold_wait_s"] <= pf["fold_wall_s"] + 1e-6
    # the headroom bound can never exceed the wait it would hide
    assert pf["overlap_headroom_s"] <= pf["fold_wait_s"] + 1e-6
    bf = pf["bubble_fractions"]
    assert set(bf) == {"stage", "dispatch", "score",
                       "fold_wait_of_fold", "fold_wait_of_serve",
                       "headroom_of_fold", "headroom_of_serve"}
    assert all(0.0 <= v <= 1.0 for v in bf.values())
    # one serve-wall sample per headline tick: the bootstrap's input
    assert len(pf["raw_wall_s"]) > 0
    assert all(t >= 0 for t in pf["raw_wall_s"])
    assert len(pf["perf_leg"]["raw_wall_s"]) > 0
    assert pf["noise_floor"] > 0
    assert pf["spans_per_sec_on"] > 0
    assert pf["spans_per_sec_off"] == out["value"]
    assert 0.0 <= pf["overhead_fraction"] < 1.0
    par = pf["parity"]
    assert par["alerts_identical"] is True
    assert par["states_identical"] is True
    assert par["p99_identical"] is True
    assert par["shed_identical"] is True
    # a self-diff of the finished capture must be clean: decisions
    # byte-exact by identity, walls trivially within the noise model
    from anomod.obs.perf import diff_captures
    self_diff = diff_captures(out, json.loads(json.dumps(out)))
    assert self_diff["status"] == "ok"
    assert self_diff["decisions"]["identical"] is True
    # fleet-census block (ISSUE-15): the deterministic resident-bytes
    # census, the hot-set/Zipf census, the registered-fleet sweep's
    # fitted O(registered) baseline slopes, one informational RSS
    # sample (never a pin), and the read-side parity bits
    cn = out["census"]
    assert cn["enabled_headline"] is False     # deep-dive opt-in, off
    assert cn["census_ticks"] >= 1
    rb = cn["resident_bytes"]
    assert rb["total"] > 0
    assert rb["pool_reconciled"] is True
    assert rb["by_plane"]["pool"] > 0
    assert rb["by_plane"]["admission"] > 0
    assert rb["total"] == sum(rb["by_plane"].values())
    hs = cn["hot_set"]
    assert hs["registered"] == out["n_tenants"]
    assert 0 < hs["ever_served"] <= hs["registered"]
    assert 0.0 < hs["occupancy_vs_registered"] <= 1.0
    assert hs["hot_by_decay"]
    assert hs["zipf_alpha"] is None or hs["zipf_alpha"] > 0
    assert len(hs["coldest"]) >= 1
    # informational cross-check only: present, never compared
    assert cn["process_resident_memory_bytes"] is None \
        or cn["process_resident_memory_bytes"] > 0
    sweep = cn["sweep"]
    assert sweep["sizes"] == [400, 1600, 6400]     # the env override
    assert len(sweep["rows"]) == 3
    bytes_by_size = [r["resident_bytes"] for r in sweep["rows"]]
    assert bytes_by_size == sorted(bytes_by_size)  # O(registered) grows
    assert all(r["pool_reconciled"] is True for r in sweep["rows"])
    assert sweep["bytes_slope_per_registered"] > 0
    assert "wall_slope_s_per_registered" in sweep
    assert cn["spans_per_sec_on"] > 0
    assert cn["spans_per_sec_off"] == out["value"]
    # the authoritative overhead price is measured IN-RUN (the
    # ckpt_wall idiom) — the A/B fraction is informational (box noise)
    assert cn["census_wall_s"] >= 0
    assert 0.0 <= cn["census_overhead_in_run"] < 0.05
    assert 0.0 <= cn["overhead_fraction"] < 1.0
    par = cn["parity"]
    assert par["alerts_identical"] is True
    assert par["states_identical"] is True
    assert par["p99_identical"] is True
    assert par["shed_identical"] is True
    assert par["journal_canonical_identical"] is True
    # live-feed block (ISSUE-18): the closed telemetry loop — the
    # self-scrape leg's throughput/poll counters, the feed-lag
    # histogram, and the five live-vs-replay parity bits (the
    # --from-live reproducibility pin the capture carries)
    lf = out["live_feed"]
    assert lf["spans_per_s"] > 0
    assert lf["served_spans"] > 0
    assert lf["n_polls"] >= 1
    assert lf["n_samples"] >= 1
    assert lf["gaps"] >= 0
    assert lf["journal_entries"] >= lf["n_polls"]
    assert set(lf["feed_lag"]) == {"p50", "p99"}
    # the scrape path observes the effective ingest lag per poll, so a
    # consuming leg always populates the histogram
    assert lf["feed_lag"]["p50"] is not None and lf["feed_lag"]["p50"] >= 0
    assert lf["feed_lag"]["p99"] is not None and lf["feed_lag"]["p99"] >= 0
    par = lf["parity"]
    assert par["alerts_identical"] is True
    assert par["states_identical"] is True
    assert par["p99_identical"] is True
    assert par["shed_identical"] is True
    assert par["journal_canonical_identical"] is True
    # a census self-diff of the finished capture must be clean (the
    # tiering before/after judge's identity case)
    from anomod.obs.census import diff_census
    cen_diff = diff_census(out, json.loads(json.dumps(out)))
    assert cen_diff["status"] == "ok"
    assert cen_diff["sweep_comparable"] is True
    # state-tiering block (ISSUE-19): the tiered registered-fleet
    # sweep (one extra 10x top point past the census sweep — the
    # committed capture's 1e6-registered / 1e3-hot mode), the
    # demote/spill/promote/miss counters and prefetch-hidden fraction
    # from the sub-capacity parity pair, and the parity bits — every
    # decision plane identical to the never-evicted twin, the journal
    # byte-equal across the same-config rerun
    tr = out["tiering"]
    assert tr["tier_hot"] > 0
    tsw = tr["sweep"]
    assert tsw["sizes"] == sweep["sizes"] + [10 * max(sweep["sizes"])]
    assert len(tsw["rows"]) == len(tsw["sizes"])
    assert all(r["pool_reconciled"] is True for r in tsw["rows"])
    assert tr["bytes_slope_per_registered"] \
        == tsw["bytes_slope_per_registered"]
    assert tr["bytes_slope_per_registered"] > 0
    assert tr["baseline_bytes_slope_per_registered"] \
        == sweep["bytes_slope_per_registered"]
    # tiering must never COST resident bytes per registered tenant
    assert tr["bytes_slope_per_registered"] \
        <= tr["baseline_bytes_slope_per_registered"]
    assert "wall_slope_s_per_registered" in tr
    ctr = tr["counters"]
    assert ctr["demotions_warm"] >= 1
    assert ctr["demotions_cold"] >= 1
    assert ctr["promotions"] >= 1
    assert ctr["tier_misses"] >= 1
    assert tr["prefetch_joins"] >= 1
    assert 0.0 <= tr["prefetch_hidden_fraction"] <= 1.0
    assert tr["tier_wall_s"] >= 0
    assert tr["tier_empty_at_end"] is True
    par = tr["parity"]
    assert par["alerts_identical"] is True
    assert par["states_identical"] is True
    assert par["p99_identical"] is True
    assert par["shed_identical"] is True
    assert par["served_identical"] is True
    assert par["journal_rerun_identical"] is True
    # elasticity block (ISSUE-13): the policy leg under the scripted
    # surge must complete a full scaling episode (>=1 up AND >=1 down)
    # and carry the elastic determinism parity bits — byte-identical
    # decisions and an equal canonical journal vs the static leg
    el = out["elasticity"]
    assert el["policy"] == "auto"
    assert el["chaos_script"].startswith("surge@")
    assert el["min_shards"] == 1 and el["max_shards"] == 2
    assert el["n_scale_ups"] >= 1
    assert el["n_scale_downs"] >= 1
    assert el["n_policy_migrations"] >= 1
    assert el["migrated_spans"] >= 0
    assert el["peak_shards"] == 2
    assert el["policy_wall_s"] >= 0
    assert el["shard_imbalance_static"] >= 1.0
    assert el["shard_imbalance_elastic"] >= 1.0
    kinds = [ev["kind"] for ev in el["episodes"]]
    assert "scale_up" in kinds and "scale_down" in kinds
    assert el["spans_per_sec_static"] > 0
    assert el["spans_per_sec_elastic"] > 0
    par = el["parity"]
    assert par["alerts_identical"] is True
    assert par["states_identical"] is True
    assert par["p99_identical"] is True
    assert par["shed_identical"] is True
    assert par["journal_canonical_identical"] is True
    # process-shard block (ISSUE-20): the GIL-free worker quartet —
    # thread-vs-process and N-vs-1-process parity bits, the sparse
    # barrier fold's payload bytes against the dense walk, and the
    # honesty bit that gates throughput-scaling claims on core count
    ps = out["proc_shard"]
    assert ps["worker_headline"] == "thread"
    assert ps["fold_headline"] in ("dense", "sparse")
    assert ps["n_cores"] >= 1
    assert ps["scaling_quotable"] is (ps["n_cores"] >= 4)
    if not ps["scaling_quotable"]:
        assert ps["speedup_process_vs_thread"] is None
    assert ps["spans_per_sec_thread_2shard"] > 0
    assert ps["spans_per_sec_process_2shard"] > 0
    assert ps["spans_per_sec_process_1shard"] > 0
    for leg in ("wall_s_thread", "wall_s_process"):
        walls = ps[leg]
        assert set(walls) == {"stage", "dispatch", "fold", "score",
                              "other", "serve"}
        assert all(v >= 0 for v in walls.values())
    # the sparse fold must shrink the barrier payload vs the dense walk
    assert ps["fold_payload_bytes_dense"] > 0
    assert 0 < ps["fold_payload_bytes_sparse"] \
        < ps["fold_payload_bytes_dense"]
    assert ps["fold_payload_ratio"] <= 0.5
    assert len(ps["thread_leg"]["raw_wall_s"]) > 0
    assert len(ps["process_leg"]["raw_wall_s"]) > 0
    par = ps["parity"]
    assert par["alerts_identical_thread_vs_process"] is True
    assert par["alerts_identical_2_vs_1_process"] is True
    assert par["p99_identical"] is True
    assert par["shed_identical"] is True
    assert par["served_identical"] is True
    assert par["journal_canonical_identical_thread_vs_process"] is True
    assert par["journal_canonical_identical_2_vs_1_process"] is True
    assert par["journal_canonical_identical_sparse_vs_dense"] is True


def test_pre_bench_exit_codes_named_and_unique():
    """The gate's exit-code table (accreted 3/4/5/6/7/8 across PRs 5–10)
    lives as named EXIT_* constants in ONE place; the constants are
    collected by prefix (a new one joins the pin automatically), every
    code is distinct, and the documented values are pinned so drivers
    parsing return codes never see a silent renumbering."""
    import sys as _sys
    _sys.path.insert(0, str(Path(__file__).parent.parent / "scripts"))
    try:
        import pre_bench_check as pbc
    finally:
        _sys.path.pop(0)
    codes = {name: getattr(pbc, name) for name in dir(pbc)
             if name.startswith("EXIT_")}
    assert len(set(codes.values())) == len(codes)
    assert codes == {
        "EXIT_READY": 0, "EXIT_COLD_CACHE": 1, "EXIT_CACHE_DISABLED": 2,
        "EXIT_SERVE_PRECONDITION": 3, "EXIT_ENV_CONTRACT": 4,
        "EXIT_NATIVE_UNUSABLE": 5, "EXIT_STATE_POOL_UNUSABLE": 6,
        "EXIT_FLIGHT_DIVERGENCE": 7, "EXIT_RECOVERY_DIVERGENCE": 8,
        "EXIT_LINT": 9, "EXIT_POLICY_DIVERGENCE": 10,
        "EXIT_PERF_DIVERGENCE": 11, "EXIT_CENSUS_DIVERGENCE": 12,
        "EXIT_ASYNC_DIVERGENCE": 13, "EXIT_FEED_DIVERGENCE": 14,
        "EXIT_TIERING_DIVERGENCE": 15,
        "EXIT_PROCSHARD_DIVERGENCE": 16,
    }
    # every literal return in the gate's source goes through a constant
    src = (Path(__file__).parent.parent / "scripts"
           / "pre_bench_check.py").read_text()
    import re
    assert not re.search(r"return [0-9]", src), \
        "pre_bench_check must return named EXIT_* constants, not literals"


# ---------------------------------------------------------------------------
# device-probe verdict cache (PR-4): CPU-only boxes stop paying the 60 s
# init-probe timeout on every run
# ---------------------------------------------------------------------------

def _fresh_config():
    from anomod.config import Config, set_config
    set_config(Config())


def test_probe_verdict_cache_roundtrip(tmp_path, monkeypatch):
    from anomod.config import get_config, set_config
    from anomod.utils import platform as plat
    old = get_config()
    try:
        monkeypatch.setenv("ANOMOD_CACHE_DIR", str(tmp_path / "cache"))
        _fresh_config()
        assert plat.read_probe_verdict() is None
        # the dead-tunnel timeout verdict IS cacheable — that's the
        # whole point (the box pays the deadline once per install)
        plat.write_probe_verdict("", "backend init probe timed out")
        assert plat.read_probe_verdict() == \
            ("", "backend init probe timed out")
        plat.write_probe_verdict("cpu", "probe ok")
        assert plat.read_probe_verdict() == ("cpu", "probe ok")
        # a corrupted verdict file reads as absent, never crashes
        plat._probe_verdict_path().write_text("{not json")
        assert plat.read_probe_verdict() is None
        # caching disabled: no path, writes are no-ops, reads absent
        monkeypatch.setenv("ANOMOD_CACHE_DIR", "off")
        _fresh_config()
        assert plat._probe_verdict_path() is None
        plat.write_probe_verdict("cpu", "x")
        assert plat.read_probe_verdict() is None
    finally:
        set_config(old)


def _load_bench_module():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", Path(__file__).parent.parent / "bench.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_resolve_platform_uses_cached_verdict(tmp_path, monkeypatch):
    """A cached verdict short-circuits the probe entirely;
    --probe-fresh re-probes and rewrites the cache with the new
    verdict."""
    from anomod.config import get_config, set_config
    from anomod.utils import platform as plat
    old = get_config()
    try:
        monkeypatch.delenv("ANOMOD_BENCH_PLATFORM", raising=False)
        monkeypatch.setenv("ANOMOD_CACHE_DIR", str(tmp_path / "cache"))
        _fresh_config()
        plat.write_probe_verdict("", "backend init probe timed out")
        bench = _load_bench_module()
        calls = []
        monkeypatch.setattr(
            plat, "probe_device_platform",
            lambda *a, **k: (calls.append(1), ("cpu", "probe ok"))[1])
        got, diag = bench._resolve_platform()
        assert got == "cpu"
        assert "cached verdict" in diag and not calls
        got, diag = bench._resolve_platform(fresh=True)
        assert got == "cpu" and calls
        assert "cached verdict" not in diag
        assert plat.read_probe_verdict() == ("cpu", "probe ok")
        # the refreshed verdict now serves from cache again
        calls.clear()
        got, diag = bench._resolve_platform()
        assert got == "cpu" and "cached verdict" in diag and not calls
        # a forced platform never touches probe OR cache
        monkeypatch.setenv("ANOMOD_BENCH_PLATFORM", "cpu")
        got, diag = bench._resolve_platform()
        assert got == "cpu" and "forced" in diag and not calls
        monkeypatch.delenv("ANOMOD_BENCH_PLATFORM")
        # a live-accelerator verdict is NEVER trusted from cache (a
        # tunnel that died since would hang the first backend touch
        # with no deadline) — the probe must re-run...
        plat.write_probe_verdict("tpu", "probe ok")
        calls.clear()
        got, diag = bench._resolve_platform()
        assert calls and "cached verdict" not in diag
        # ...and a live verdict is never WRITTEN either: the fresh
        # "cpu" probe result above replaced the stale entry
        assert plat.read_probe_verdict() == ("cpu", "probe ok")
        monkeypatch.setattr(plat, "probe_device_platform",
                            lambda *a, **k: ("tpu", "probe ok"))
        got, diag = bench._resolve_platform(fresh=True)
        assert got == "default"
        assert plat.read_probe_verdict() == ("cpu", "probe ok")  # unchanged
    finally:
        set_config(old)
