"""Driver-contract test for bench.py: forced onto the CPU fallback it must
still exit 0 and print exactly one JSON line with the metric fields the
driver records (the round-1 capture failed precisely because this path
wasn't hardened)."""

import json
import os
import subprocess
import sys
from pathlib import Path


def test_bench_cpu_fallback_contract():
    env = dict(os.environ)
    env["ANOMOD_BENCH_PLATFORM"] = "cpu"
    # hermetic: an inherited kernel override could force the pallas
    # interpret path off-TPU (never finishes at bench scale)
    env.pop("ANOMOD_BENCH_KERNEL", None)
    # small corpus keeps the fallback fast; the platform pin bypasses the
    # subprocess backend probe entirely
    r = subprocess.run(
        [sys.executable, str(Path(__file__).parent.parent / "bench.py"),
         "200"],
        capture_output=True, text=True, timeout=420, env=env)
    assert r.returncode == 0, r.stderr[-500:]
    lines = [l for l in r.stdout.strip().splitlines() if l.startswith("{")]
    assert len(lines) == 1, r.stdout
    out = json.loads(lines[0])
    assert out["metric"] == "tt_replay_throughput"
    assert out["unit"] == "spans/sec/chip"
    assert out["value"] > 0 and out["vs_baseline"] > 0
    assert out["kernel"] == "xla"          # pallas never runs off-TPU
    assert "device_note" in out            # fallback is explained
