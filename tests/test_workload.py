"""wrk2 content-model tests — the synthesized request distributions must
match the reference workload's parameters (mixed-workload.lua:33-125):
60/30/10 endpoint mix, 256-char base text, 1-6 mentions / 1-6 urls / 1-5
media (Lua's inclusive `for i = 0, n` runs n+1 times), 64-char urls,
18-digit media ids, user ids below 962."""

import re

import numpy as np
import pytest

from anomod.monitor import ActiveMonitor, capture_openapi_responses, \
    run_wrk2_workload
from anomod.scenario import SyntheticGateway
from anomod.workload import (WRK2_MAX_USER_INDEX, WRK2_MEDIA_RANGE,
                             WRK2_MENTION_RANGE, WRK2_TEXT_LEN,
                             WRK2_URL_LEN, WRK2_URL_RANGE,
                             compose_length_bounds, compose_post_body,
                             sample_compose_lengths, sample_wrk2_request,
                             timeline_query)

N = 400


def _bodies(seed=0, n=N):
    rng = np.random.default_rng(seed)
    return [compose_post_body(rng) for _ in range(n)]


def test_compose_body_field_layout():
    body = _bodies(n=1)[0]
    fields = dict(p.split("=", 1) for p in body.split("&"))
    assert set(fields) == {"username", "user_id", "text", "media_ids",
                           "media_types", "post_type"}
    assert fields["post_type"] == "0"
    assert fields["username"] == f"username_{fields['user_id']}"
    assert int(fields["user_id"]) < WRK2_MAX_USER_INDEX
    # media_types is the bracketed "png" list (mixed-workload.lua:61-66)
    assert re.fullmatch(r'\[("png",)*"png"\]', fields["media_types"])
    assert re.fullmatch(r'\[("\d{18}",)*"\d{18}"\]', fields["media_ids"])


def test_compose_content_distributions_match_lua_parameters():
    mentions, urls, media, sizes = [], [], [], []
    for body in _bodies():
        text = dict(p.split("=", 1) for p in body.split("&"))["text"]
        m = len(re.findall(r" @username_\d+", text))
        u = len(re.findall(r" http://[0-9A-Za-z]+", text))
        k = body.count('"png"')
        base = (len(text) - sum(len(s) for s in
                                re.findall(r" @username_\d+", text))
                - u * (8 + WRK2_URL_LEN))
        assert base == WRK2_TEXT_LEN
        assert WRK2_MENTION_RANGE[0] <= m <= WRK2_MENTION_RANGE[1]
        assert WRK2_URL_RANGE[0] <= u <= WRK2_URL_RANGE[1]
        assert WRK2_MEDIA_RANGE[0] <= k <= WRK2_MEDIA_RANGE[1]
        mentions.append(m)
        urls.append(u)
        media.append(k)
        sizes.append(len(body))
    # uniform-count means: mentions/urls 3.5, media 3
    assert np.mean(mentions) == pytest.approx(3.5, abs=0.3)
    assert np.mean(urls) == pytest.approx(3.5, abs=0.3)
    assert np.mean(media) == pytest.approx(3.0, abs=0.3)
    lo, hi = compose_length_bounds()
    assert min(sizes) >= lo and max(sizes) <= hi


def test_vectorized_lengths_match_string_model():
    sizes = np.array([len(b) for b in _bodies(seed=1, n=600)])
    fast = sample_compose_lengths(np.random.default_rng(2), 600)
    lo, hi = compose_length_bounds()
    assert fast.min() >= lo and fast.max() <= hi
    # same distribution: means within a couple of url-lengths
    assert abs(float(sizes.mean()) - float(fast.mean())) < 30
    assert abs(float(sizes.std()) - float(fast.std())) < 30


def test_request_mix_and_timeline_args():
    rng = np.random.default_rng(3)
    reqs = [sample_wrk2_request(rng) for _ in range(2000)]
    frac = {t: sum(r.template == t for r in reqs) / len(reqs)
            for t in {r.template for r in reqs}}
    assert frac["/wrk2-api/home-timeline/read"] == pytest.approx(0.60, abs=0.05)
    assert frac["/wrk2-api/user-timeline/read"] == pytest.approx(0.30, abs=0.05)
    assert frac["/wrk2-api/post/compose"] == pytest.approx(0.10, abs=0.03)
    for r in reqs:
        if r.method == "GET":
            assert r.body is None and r.content_length == 0
            q = dict(p.split("=") for p in r.path.split("?")[1].split("&"))
            assert int(q["stop"]) == int(q["start"]) + 10
            assert int(q["user_id"]) < WRK2_MAX_USER_INDEX
        else:
            assert r.content_length == len(r.body)
    assert timeline_query(np.random.default_rng(0)) == \
        timeline_query(np.random.default_rng(0))


def test_gateway_records_wrk2_content_lengths():
    gw = SyntheticGateway(seed=0)
    run_wrk2_workload(gw, 300, seed=4)
    batch = gw.to_api_batch()
    eps = list(batch.endpoints)
    compose_idx = eps.index("POST /wrk2-api/post/compose")
    mask = (batch.endpoint == compose_idx) & (batch.status == 200)
    assert mask.sum() > 10
    lo, hi = compose_length_bounds()
    clen = batch.content_length[mask]
    assert clen.min() >= lo and clen.max() <= hi
    # GET reads keep the synthetic response-size draw (< 2048 bytes)
    get_mask = (batch.endpoint != compose_idx) & (batch.status == 200)
    assert batch.content_length[get_mask].max() < 2048


def test_capture_interleaves_wrk2_traffic(tmp_path):
    from anomod.workload import compose_length_bounds
    report = capture_openapi_responses(out_dir=tmp_path, cycles=2,
                                      wrk2_requests=50)
    # 50 workload requests + 12 pre-check + 2*12 monitor probes
    batch = report.batch
    assert batch.n_records == 50 + 12 + 2 * 12
    assert (tmp_path / "openapi_responses.jsonl").exists()
    # genuinely interleaved: wrk2 compose records (compose endpoint with a
    # full-body content length — the monitor's own compose probe bodies are
    # ~100 bytes, far below the wrk2 band) must appear both before and
    # after the first monitor cycle, not as one initial burst
    lo, _ = compose_length_bounds()
    compose_idx = list(batch.endpoints).index("POST /wrk2-api/post/compose")
    wrk2_pos = np.flatnonzero((batch.endpoint == compose_idx)
                              & (batch.content_length >= lo))
    assert wrk2_pos.size > 0
    first_block_end = 12 + 25 + 12   # pre-check + chunk 1 + cycle 1
    assert wrk2_pos.min() < first_block_end < wrk2_pos.max()


def test_monitor_post_probes_carry_encoded_bodies():
    report = ActiveMonitor(seed=0).run(cycles=1)
    batch = report.batch
    eps = list(batch.endpoints)
    reg = eps.index("POST /wrk2-api/user/register")
    mask = (batch.endpoint == reg) & (batch.status == 200)
    if mask.any():
        # register body ~ "first_name=Test&...": deterministic small length
        assert 60 < batch.content_length[mask].max() < 140


def test_synth_api_compose_lengths():
    from anomod.labels import labels_for_testbed
    from anomod.synth import generate_api
    label = labels_for_testbed("SN")[0]
    batch = generate_api(label, n_records=800)
    compose = [i for i, e in enumerate(batch.endpoints)
               if "post/compose" in e]
    assert len(compose) == 1
    mask = batch.endpoint == compose[0]
    lo, hi = compose_length_bounds()
    clen = batch.content_length[mask]
    assert clen.min() >= lo and clen.max() <= hi
