"""Self-scraping telemetry plane (anomod.obs) + hardened tracer.

The acceptance-critical pin is the full dogfood round trip:
registry → TT-CSV export → ``load_tt_metric_csv`` → ``OnlineDetector``
flags an injected serve-plane stall on the ``serve`` subsystem.  The
rest covers registry semantics (thread safety, kind clash, disabled
nulls), both exporters, the engine's registry wiring, the env-contract
gate, and the tracer's new contracts (thread-local stacks, tags/events,
Jaeger round trip with parents+durations, atomic dump).
"""

import json
import subprocess
import sys
import threading
from pathlib import Path

import numpy as np
import pytest

from anomod.obs import export as obs_export
from anomod.obs.registry import NULL, Registry, set_registry
from anomod.obs.selfscrape import score_self_scrape, spans_from_metrics
from anomod.utils.tracing import Tracer

SCRIPTS = Path(__file__).parent.parent / "scripts"


@pytest.fixture
def registry():
    """A fresh force-enabled registry installed as the process default
    (instrumented call sites record into it), restored afterwards."""
    reg = Registry(enabled=True, max_samples=200_000)
    prev = set_registry(reg)
    yield reg
    set_registry(prev)


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------

def test_counter_gauge_histogram_semantics(registry):
    c = registry.counter("anomod_test_events_total")
    c.inc()
    c.inc(4)
    assert c.value == 5
    with pytest.raises(ValueError):
        c.inc(-1)                       # counters are monotone
    g = registry.gauge("anomod_test_depth")
    g.set(7)
    g.inc(2)
    g.dec()
    assert g.value == 8
    h = registry.histogram("anomod_test_wall_seconds")
    rng = np.random.default_rng(0)
    vals = rng.uniform(1.0, 2.0, 1000)
    for v in vals:
        h.observe(float(v))
    assert h.count == 1000
    assert h.sum == pytest.approx(vals.sum(), rel=1e-5)
    assert h.quantile(0.5) == pytest.approx(np.median(vals), rel=0.05)
    assert h.quantile(0.99) == pytest.approx(
        np.quantile(vals, 0.99), rel=0.05)
    # handles are memoized; a kind clash fails loudly
    assert registry.counter("anomod_test_events_total") is c
    with pytest.raises(ValueError, match="already registered"):
        registry.gauge("anomod_test_events_total")


def test_disabled_registry_is_noop():
    reg = Registry(enabled=False, max_samples=100)
    assert reg.counter("anomod_x_total") is NULL
    reg.counter("anomod_x_total").inc()      # all no-ops, never raise
    reg.histogram("anomod_x_seconds").observe(1.0)
    assert reg.scrape(now_s=0.0) == 0
    assert reg.snapshot() == {}
    assert reg.n_samples == 0


def test_counter_thread_safety(registry):
    c = registry.counter("anomod_test_threads_total")

    def work():
        for _ in range(5_000):
            c.inc()

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 40_000


def test_scrape_vs_record_hammer(registry):
    """Scrape-vs-record race hammer (the sharded serving plane's
    regime: worker threads record while the coordinator scrapes).

    Every histogram observation is exactly 1.0, so any scraped
    ``_count`` that disagrees with its ``_sum`` is a TORN read — the
    pre-fix ``Histogram.samples`` read count and sum outside the lock
    and could journal a count from after an observe with the sum from
    before it.  Counters/gauges ride along to shake the registry's
    handle table and journal under the same concurrency."""
    h = registry.histogram("anomod_test_hammer_seconds")
    c = registry.counter("anomod_test_hammer_total")
    g = registry.gauge("anomod_test_hammer_depth")
    N_THREADS, N_OBS = 4, 20_000
    # aggressive GIL churn: make the torn-read window (count read,
    # switch, observe, switch, sum read) actually reachable
    prev_switch = sys.getswitchinterval()
    sys.setswitchinterval(1e-5)

    def record():
        for k in range(N_OBS):
            h.observe(1.0)
            c.inc()
            g.set(float(k))

    threads = [threading.Thread(target=record)
               for _ in range(N_THREADS)]
    try:
        for t in threads:
            t.start()
        scrapes = 0
        while any(t.is_alive() for t in threads):
            registry.scrape(now_s=float(scrapes))
            scrapes += 1
        for t in threads:
            t.join()
    finally:
        sys.setswitchinterval(prev_switch)
    registry.scrape(now_s=float(scrapes))
    # final totals exact
    assert h.count == N_THREADS * N_OBS
    assert h.sum == pytest.approx(float(N_THREADS * N_OBS))
    assert c.value == N_THREADS * N_OBS
    # every scraped (count, sum) pair is internally consistent
    rows = {}
    for t_s, name, _, val in registry.journal():
        rows.setdefault(t_s, {})[name] = val
    checked = 0
    for t_s, r in rows.items():
        if "anomod_test_hammer_seconds_count" in r:
            assert r["anomod_test_hammer_seconds_count"] == pytest.approx(
                r["anomod_test_hammer_seconds_sum"]), \
                f"torn histogram snapshot at scrape t={t_s}"
            checked += 1
    assert checked >= 2          # the hammer actually overlapped scrapes


def test_registry_fold_from_shard_registries(registry):
    """The sharded engine's merge seam: counters fold as deltas
    (summable fleet totals across repeated folds), gauges land on
    shard-labeled twins, histograms merge once at final through
    merge_digest."""
    shard = Registry(enabled=True, max_samples=1000)
    state = {}
    c = shard.counter("anomod_serve_fused_dispatches_total")
    g = shard.gauge("anomod_serve_lane_pad_waste_fraction")
    h = shard.histogram("anomod_serve_fused_lanes")
    c.inc(3)
    g.set(0.25)
    for v in (1.0, 2.0, 4.0):
        h.observe(v)
    registry.fold_from(shard, state, shard="0")
    assert registry.counter(
        "anomod_serve_fused_dispatches_total").value == 3
    c.inc(2)
    registry.fold_from(shard, state, shard="0")   # delta, not re-total
    assert registry.counter(
        "anomod_serve_fused_dispatches_total").value == 5
    assert registry.gauge("anomod_serve_lane_pad_waste_fraction",
                          shard="0").value == 0.25
    # histograms only at final=True, and they DRAIN: a second final
    # fold (engine run() twice) adds only the new observations
    assert registry.histogram("anomod_serve_fused_lanes").count == 0
    registry.fold_from(shard, state, shard="0", final=True)
    assert registry.histogram("anomod_serve_fused_lanes").count == 3
    assert registry.histogram("anomod_serve_fused_lanes").sum == \
        pytest.approx(7.0)
    registry.fold_from(shard, state, shard="0", final=True)   # drained
    assert registry.histogram("anomod_serve_fused_lanes").count == 3
    h.observe(8.0)
    registry.fold_from(shard, state, shard="0", final=True)
    assert registry.histogram("anomod_serve_fused_lanes").count == 4
    assert registry.histogram("anomod_serve_fused_lanes").sum == \
        pytest.approx(15.0)
    # disabled either side: no-op
    registry.fold_from(Registry(enabled=False, max_samples=10), {},
                       shard="1", final=True)


def test_histogram_merge_digest(registry):
    """The serve plane's fold path: a pre-built t-digest joins the
    histogram weight-preserving, with count/sum bookkeeping."""
    from anomod.ops.tdigest import tdigest_build
    h = registry.histogram("anomod_test_fold_seconds")
    vals = np.linspace(1.0, 3.0, 512).astype(np.float32)
    h.merge_digest(tdigest_build(vals, k=32))
    assert h.count == 512
    assert h.sum == pytest.approx(float(vals.sum()), rel=1e-4)
    assert h.quantile(0.5) == pytest.approx(2.0, rel=0.05)


def test_scrape_journal_bound_and_batch(registry):
    g = registry.gauge("anomod_serve_backlog_spans")
    for t in range(10):
        g.set(t)
        registry.scrape(now_s=float(t))
    assert registry.n_samples == 10
    batch = obs_export.to_metric_batch(registry)
    assert batch.n_samples == 10
    assert batch.metric_names == ("anomod_serve_backlog_spans",)
    assert batch.services == ("serve",)
    # series carry service="<subsystem>" for direct multimodal pushes
    assert 'service="serve"' in batch.series_keys[0]
    assert int(batch.series_service[0]) == 0
    small = Registry(enabled=True, max_samples=5)
    c = small.counter("anomod_x_total")
    for t in range(20):
        c.inc()
        small.scrape(now_s=float(t))
    assert small.n_samples == 5              # bounded journal drops oldest


def test_prometheus_text_format(registry):
    registry.counter("anomod_ingest_cache_hits_total").inc(3)
    h = registry.histogram("anomod_serve_tick_seconds")
    for v in np.linspace(0.01, 0.02, 300):
        h.observe(float(v))
    text = obs_export.to_prometheus_text(registry)
    assert "# HELP anomod_ingest_cache_hits_total " in text
    assert "# TYPE anomod_ingest_cache_hits_total counter" in text
    assert "anomod_ingest_cache_hits_total 3" in text
    assert "# HELP anomod_serve_tick_seconds " in text
    assert "# TYPE anomod_serve_tick_seconds summary" in text
    assert 'anomod_serve_tick_seconds{quantile="0.99"}' in text
    assert "anomod_serve_tick_seconds_count 300" in text


def _parse_prom(text):
    """A tiny exposition-format parser (unescaping label values per the
    grammar) — what the adversarial-label pin re-reads the export with."""
    samples = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        head, value = line.rsplit(" ", 1)
        labels = {}
        if "{" in head:
            name, rest = head.split("{", 1)
            body = rest[:rest.rindex("}")]
            i = 0
            while i < len(body):
                eq = body.index("=", i)
                key = body[i:eq]
                assert body[eq + 1] == '"'
                j = eq + 2
                val = []
                while body[j] != '"':
                    if body[j] == "\\":
                        val.append({"\\": "\\", '"': '"',
                                    "n": "\n"}[body[j + 1]])
                        j += 2
                    else:
                        val.append(body[j])
                        j += 1
                labels[key] = "".join(val)
                i = j + 1
                if i < len(body) and body[i] == ",":
                    i += 1
            head = name
        samples[(head, tuple(sorted(labels.items())))] = float(value)
    return samples


def test_prometheus_escaping_adversarial_labels(registry):
    """Exposition-format hardening: backslash, double-quote and newline
    in label values must escape per the grammar and round-trip through a
    parser; HELP lines appear exactly once per metric family even with
    label variants (the shard-labeled gauge shape)."""
    evil = 'C:\\temp\n"quoted",comma'
    registry.gauge("anomod_test_evil", path=evil).set(7)
    registry.gauge("anomod_test_evil", path="plain").set(8)
    registry.counter("anomod_test_total", reason="a\\b").inc(2)
    text = obs_export.to_prometheus_text(registry)
    # raw control characters never leak into the wire format
    for line in text.splitlines():
        assert "\r" not in line
    assert '\\n' in text and '\\"' in text and "\\\\" in text
    samples = _parse_prom(text)
    assert samples[("anomod_test_evil",
                    (("path", evil),))] == 7
    assert samples[("anomod_test_evil",
                    (("path", "plain"),))] == 8
    assert samples[("anomod_test_total",
                    (("reason", "a\\b"),))] == 2
    # one HELP + one TYPE per family, label variants notwithstanding
    assert text.count("# HELP anomod_test_evil ") == 1
    assert text.count("# TYPE anomod_test_evil ") == 1
    # every family has a HELP line
    names = {line.split(" ", 3)[2] for line in text.splitlines()
             if line.startswith("# TYPE ")}
    helped = {line.split(" ", 3)[2] for line in text.splitlines()
              if line.startswith("# HELP ")}
    assert names == helped


# ---------------------------------------------------------------------------
# instrumented layers record into the registry
# ---------------------------------------------------------------------------

def test_cache_instrumentation_mirrors_stats(tmp_path, registry):
    import dataclasses

    from anomod.config import Config
    from anomod.io import cache
    cfg = dataclasses.replace(Config(), cache_dir=tmp_path / "cache")
    calls = []

    def compute():
        calls.append(1)
        return np.arange(4)

    # miss + store, then a hit — wrong-kind arg keeps the helper honest
    from anomod.schemas import ApiBatch
    value = ApiBatch(endpoint=np.zeros(2, np.int32),
                     t_s=np.array([1.0, 2.0]),
                     status=np.array([200, 200], np.int16),
                     latency_ms=np.array([1.0, 2.0]),
                     content_length=np.zeros(2, np.int64),
                     endpoints=("/a",))
    cache.cached("api", {"k": 1}, lambda: value, cfg=cfg)
    cache.cached("api", {"k": 1}, lambda: value, cfg=cfg)
    assert registry.counter("anomod_ingest_cache_misses_total").value >= 1
    assert registry.counter("anomod_ingest_cache_hits_total").value >= 1
    assert registry.counter("anomod_ingest_cache_stores_total").value >= 1
    assert registry.counter(
        "anomod_ingest_cache_written_bytes_total").value > 0
    assert registry.counter(
        "anomod_ingest_cache_read_bytes_total").value > 0


def test_prefetch_instrumentation(registry):
    from anomod.io.prefetch import Pipeline
    pipe = Pipeline(range(10), lambda x: x * 2, depth=2)
    assert list(pipe) == [2 * i for i in range(10)]
    h = registry.histogram("anomod_prefetch_stage_seconds")
    assert h.count == 10


def test_serve_engine_registry_wiring(registry):
    """A small seeded serve run populates every serve-plane metric and
    scrapes on the virtual clock (deterministic timeline)."""
    from anomod.serve.engine import run_power_law
    eng, rep = run_power_law(
        n_tenants=6, n_services=4, capacity_spans_per_s=1200,
        overload=1.5, duration_s=12, tick_s=1.0, seed=5,
        window_s=4.0, baseline_windows=2, fault_tenants=0)
    assert rep.served_spans > 0
    served = registry.counter("anomod_serve_served_spans_total").value
    assert served == rep.served_spans
    offered = registry.counter("anomod_serve_offered_spans_total").value
    assert offered == rep.offered_spans
    assert registry.counter("anomod_serve_ticks_total").value == rep.ticks
    lat = registry.histogram("anomod_serve_admit_to_scored_seconds")
    lat_total = sum(s.n_samples for s in eng._slo.values())
    eng_report_fold = lat.count            # report() folded every tenant
    assert eng_report_fold == lat_total
    # bucket-pad waste is derivable and bounded
    staged = registry.counter("anomod_serve_staged_rows_total").value
    live = registry.counter("anomod_serve_live_rows_total").value
    assert live == rep.served_spans and staged >= live
    assert 0.0 <= registry.gauge(
        "anomod_serve_pad_waste_fraction").value < 1.0
    # one scrape per virtual second, on the virtual clock
    ts = {t for t, _, _, _ in registry.journal()}
    assert ts and max(ts) <= 12.0 + 1.0
    # tracer on by default (gated on the enabled registry)
    assert eng.tracer is not None and eng.tracer.n_spans > 0


# ---------------------------------------------------------------------------
# the acceptance round trip: injected serve-plane stall
# ---------------------------------------------------------------------------

def _simulated_stalled_run(stall_after_s: float = 140.0,
                           end_s: float = 200.0) -> Registry:
    """A hand-driven registry timeline: healthy serve telemetry for the
    baseline phase, then a stall (tick walls and queue depth jump 30x)."""
    reg = Registry(enabled=True, max_samples=100_000)
    tick = reg.histogram("anomod_serve_tick_seconds")
    lat = reg.histogram("anomod_serve_admit_to_scored_seconds")
    backlog = reg.gauge("anomod_serve_backlog_spans")
    served = reg.counter("anomod_serve_served_spans_total")
    rng = np.random.default_rng(7)
    for t in range(int(end_s)):
        stalled = t >= stall_after_s
        scale = 30.0 if stalled else 1.0
        tick.observe(float(rng.uniform(0.009, 0.011) * scale))
        lat.observe(float(rng.uniform(0.4, 0.6) * scale))
        backlog.set(float(rng.uniform(900, 1100) * scale))
        served.inc(0 if stalled else 500)
        reg.scrape(now_s=float(t))
    return reg


def test_self_scrape_flags_injected_serve_stall(tmp_path):
    """registry → TT-CSV → load_tt_metric_csv → OnlineDetector: the
    stall localizes to the `serve` subsystem, after its onset."""
    from anomod.io.metrics import load_tt_metric_csv
    reg = _simulated_stalled_run()
    csv_path = tmp_path / "selfscrape.csv"
    n = obs_export.export_tt_csv(reg, csv_path)
    assert n == reg.n_samples
    assert load_tt_metric_csv(csv_path).n_samples == n   # loader contract
    report = score_self_scrape(csv_path, window_s=10.0,
                               baseline_windows=4, z_threshold=4.0)
    assert "serve" in report["subsystems"]
    assert report["n_alerts"] > 0
    assert report["alerted_subsystems"] == ["serve"]
    onset_window = int(140.0 // 10.0)
    assert all(a["window"] >= onset_window for a in report["alerts"])
    assert report["ranked_subsystems"][0] == "serve"


def test_self_scrape_healthy_run_stays_quiet(tmp_path):
    reg = _simulated_stalled_run(stall_after_s=1e9)     # never stalls
    csv_path = tmp_path / "healthy.csv"
    obs_export.export_tt_csv(reg, csv_path)
    report = score_self_scrape(csv_path, window_s=10.0,
                               baseline_windows=4, z_threshold=4.0)
    assert report["n_alerts"] == 0


def test_spans_from_metrics_counter_differencing():
    """Cumulative *_total streams must contribute rates, not their
    monotone raw values (which would fake a latency trend)."""
    reg = Registry(enabled=True, max_samples=10_000)
    c = reg.counter("anomod_serve_served_spans_total")
    for t in range(50):
        c.inc(100)                       # perfectly steady rate
        reg.scrape(now_s=float(t))
    spans = spans_from_metrics(obs_export.to_metric_batch(reg))
    # first sample has no predecessor and is dropped; the rest are the
    # constant per-scrape delta (normalized to the series' own scale,
    # so steady rate -> the 1e6 anchor), never the growing cumulative
    assert spans.n_spans == 49
    assert set(spans.duration_us.tolist()) == {1_000_000}


# ---------------------------------------------------------------------------
# env contract gate
# ---------------------------------------------------------------------------

def test_obs_env_contract(monkeypatch):
    from anomod.config import Config
    monkeypatch.setenv("ANOMOD_OBS_ENABLED", "0")
    assert Config().obs_enabled is False
    monkeypatch.setenv("ANOMOD_OBS_ENABLED", "1")
    assert Config().obs_enabled is True
    monkeypatch.setenv("ANOMOD_OBS_MAX_SAMPLES", "nope")
    with pytest.raises(ValueError, match="ANOMOD_OBS_MAX_SAMPLES"):
        Config()
    monkeypatch.setenv("ANOMOD_OBS_MAX_SAMPLES", "0")
    with pytest.raises(ValueError, match="ANOMOD_OBS_MAX_SAMPLES"):
        Config()


def test_env_contract_script_passes_on_repo():
    r = subprocess.run(
        [sys.executable, str(SCRIPTS / "check_env_contract.py")],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stdout + r.stderr
    out = json.loads(r.stdout)
    assert out["status"] == "ok"
    # the PINNED inventory size: a new ANOMOD_* knob must land here and
    # in docs/CONFIGURATION.md in the same PR (ISSUE-20 took it to 79
    # with ANOMOD_SERVE_WORKER / _WORKER_START_TIMEOUT_S / _FOLD)
    assert out["n_vars"] == 79


def test_env_contract_script_catches_rogue_var(tmp_path):
    """A fixture tree with an undocumented ANOMOD_* read must fail."""
    (tmp_path / "anomod").mkdir()
    (tmp_path / "scripts").mkdir()
    (tmp_path / "docs").mkdir()
    (tmp_path / "anomod" / "config.py").write_text(
        'X = _env("ANOMOD_KNOWN_KNOB", "1")\n')
    (tmp_path / "anomod" / "rogue.py").write_text(
        'import os\nY = os.environ.get("ANOMOD_ROGUE_KNOB")\n')
    (tmp_path / "README.md").write_text("no knobs documented here\n")
    r = subprocess.run(
        [sys.executable, str(SCRIPTS / "check_env_contract.py"),
         "--root", str(tmp_path)],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 1
    out = json.loads(r.stdout)
    assert "ANOMOD_ROGUE_KNOB" in out["missing"]
    assert "ANOMOD_KNOWN_KNOB" not in out.get("missing", {})


# ---------------------------------------------------------------------------
# tracer: thread safety, tags/events, round trip, atomic dump
# ---------------------------------------------------------------------------

def test_tracer_thread_local_stacks():
    """Spans opened from worker threads must not corrupt the main
    thread's parent links (the old shared-stack bug)."""
    tr = Tracer("anomod-test")
    stop = threading.Event()

    def worker():
        while not stop.is_set():
            with tr.span("worker.stage"):
                pass

    threads = [threading.Thread(target=worker) for _ in range(4)]
    with tr.span("main.pipeline"):
        for t in threads:
            t.start()
        for _ in range(200):
            with tr.span("main.step"):
                pass
        stop.set()
        for t in threads:
            t.join()
    doc = tr.to_jaeger()["data"][0]
    by_id = {s["spanID"]: s for s in doc["spans"]}
    for s in doc["spans"]:
        if s["operationName"] == "main.step":
            # every main.step's parent is main.pipeline, never a worker
            assert len(s["references"]) == 1
            parent = by_id[s["references"][0]["spanID"]]
            assert parent["operationName"] == "main.pipeline"
        elif s["operationName"] == "worker.stage":
            assert s["references"] == []       # thread roots, not children


def test_tracer_jaeger_roundtrip_parents_and_durations(tmp_path):
    """Docstring-promised round trip: to_jaeger() parses through
    spans_from_jaeger with parent references and durations intact."""
    import time

    from anomod.io.sn_traces import spans_from_jaeger
    tr = Tracer("anomod-test")
    with tr.span("pipeline", phase="bench"):
        with tr.span("load"):
            time.sleep(0.01)
        with tr.span("detect") as sp:
            sp.event("windows-scored", n=7)
    batch = spans_from_jaeger(tr.to_jaeger())
    assert batch.n_spans == 3
    assert batch.services == ("anomod-test",)
    names = [batch.endpoints[int(e)] for e in batch.endpoint]
    root = names.index("pipeline")
    assert (batch.parent == -1).sum() == 1
    assert int(batch.parent[names.index("load")]) == root
    assert int(batch.parent[names.index("detect")]) == root
    assert int(batch.duration_us[names.index("load")]) >= 10_000
    # tags + events survive in the Jaeger shape
    doc = tr.to_jaeger()["data"][0]["spans"]
    root_span = next(s for s in doc if s["operationName"] == "pipeline")
    assert {"key": "phase", "value": "bench"} in root_span["tags"]
    detect_span = next(s for s in doc if s["operationName"] == "detect")
    assert detect_span["logs"] and detect_span["logs"][0]["fields"]


def test_tracer_chrome_roundtrip(tmp_path):
    """Chrome trace-event exporter: the event array loads as plain JSON
    (the chrome://tracing / Perfetto shape — complete "X" events on the
    microsecond clock) and round-trips through spans_from_chrome with
    names, parents, durations and tags intact."""
    import time

    from anomod.utils.tracing import spans_from_chrome
    tr = Tracer("anomod-test")
    with tr.span("pipeline", phase="bench"):
        with tr.span("load"):
            time.sleep(0.01)
        with tr.span("detect"):
            pass
    events = tr.to_chrome()
    assert all(e["ph"] == "X" for e in events)
    assert all(isinstance(e["ts"], int) and isinstance(e["dur"], int)
               for e in events)
    # foreign events (another producer's metadata rows) are skipped, and
    # a Perfetto-style re-sort by timestamp still parses losslessly
    shuffled = sorted(events, key=lambda e: e["ts"], reverse=True)
    spans = spans_from_chrome(
        [{"ph": "M", "name": "process_name"}] + shuffled)
    assert [s["name"] for s in spans] == ["pipeline", "load", "detect"]
    root = spans[0]
    assert root["parent"] is None
    assert spans[1]["parent"] == 0 and spans[2]["parent"] == 0
    assert spans[1]["dur"] >= 0.01
    assert root["tags"] == {"phase": "bench"}
    # atomic publish, same contract as the jaeger dump
    path = tmp_path / "trace_chrome.json"
    path.write_text("[]")
    tr.dump_chrome(path)
    assert list(tmp_path.glob("*.tmp")) == []
    assert json.loads(path.read_text()) == events


def test_obs_export_chrome_cli(tmp_path):
    """`anomod obs export --format chrome`: the self-exercise engine's
    own trace lands as a loadable trace-event array."""
    from anomod.cli import main
    from anomod.utils.tracing import spans_from_chrome
    out = tmp_path / "serve_trace.json"
    rc = main(["obs", "export", "--format", "chrome", "--out", str(out),
               "--serve-seconds", "4", "--tenants", "4",
               "--capacity", "1000"])
    assert rc == 0
    events = json.loads(out.read_text())
    spans = spans_from_chrome(events)
    names = {s["name"] for s in spans}
    assert "serve.run" in names and "serve.admit" in names


def test_tracer_dump_atomic(tmp_path):
    tr = Tracer("anomod-test")
    with tr.span("only"):
        pass
    path = tmp_path / "trace.json"
    path.write_text("{\"stale\": true}")     # replace, never append/truncate
    tr.dump(path)
    doc = json.loads(path.read_text())
    assert doc["data"][0]["spans"][0]["operationName"] == "only"
    # no tmp litter left beside the published file
    assert list(tmp_path.glob("*.tmp")) == []
