"""Recovery subsystem: readiness convergence, force-delete/stuck policies,
Prometheus OOM guard, trap-equivalent teardown."""

import pytest

from anomod.chaos import ChaosController
from anomod.recovery import (
    GuardedRun, Phase, Pod, PrometheusState, ReadinessController,
    SyntheticCluster, cluster_for_testbed, guard_prometheus,
    run_with_recovery,
)


def test_healthy_cluster_converges_fast():
    cluster = cluster_for_testbed("SN", n_slow=0, n_crashloop=0, n_stuck=0)
    report = ReadinessController().wait_for_pods_ready(cluster)
    assert report.ready
    assert report.waited_s <= 30.0
    assert not report.force_deleted and not report.restarted_stuck


def test_crashlooper_is_force_deleted_then_recovers():
    pods = [Pod(name="ok-1", service="ok"),
            Pod(name="bad-1", service="bad", crashloop=True,
                crashes_before_ok=2)]
    cluster = SyntheticCluster(pods)
    report = ReadinessController().wait_for_pods_ready(cluster)
    assert report.ready
    # deleted exactly as many times as the script demands before clean start
    assert report.force_deleted.count("bad-1") == 2
    assert cluster.pods["bad-1"].deletions == 2


def test_stuck_running_not_ready_restarted_after_deadline():
    pods = [Pod(name="stuck-1", service="s", stuck_unready=True)]
    cluster = SyntheticCluster(pods)
    ctl = ReadinessController(stuck_deadline_s=180.0, timeout_s=600.0)
    report = ctl.wait_for_pods_ready(cluster)
    assert report.ready
    assert report.restarted_stuck == ["stuck-1"]
    # not restarted before the 180 s deadline elapsed
    assert report.waited_s >= 180.0


def test_timeout_reports_unready_pods():
    # a pod that can never become ready within the timeout
    pods = [Pod(name="never-1", service="n", startup_s=10_000.0)]
    cluster = SyntheticCluster(pods)
    report = ReadinessController(timeout_s=120.0).wait_for_pods_ready(cluster)
    assert not report.ready
    assert report.unready_at_timeout == ["never-1"]
    assert report.waited_s >= 120.0


def test_seeded_tt_cluster_with_all_archetypes_converges():
    cluster = cluster_for_testbed("TT", seed=3)
    report = ReadinessController().wait_for_pods_ready(cluster)
    assert report.ready
    assert report.force_deleted            # the crash-looper
    assert report.restarted_stuck          # the stuck pod
    # deterministic: same seed reproduces the same recovery trace
    again = ReadinessController().wait_for_pods_ready(
        cluster_for_testbed("TT", seed=3))
    assert again.force_deleted == report.force_deleted
    assert again.restarted_stuck == report.restarted_stuck


def test_prometheus_oom_guard_restarts_and_waits():
    cluster = SyntheticCluster([])
    prom = PrometheusState(oom_killed=True, ready=False)
    assert guard_prometheus(prom, cluster)
    assert prom.restart_count == 1
    assert prom.ready
    # healthy prometheus is left alone
    assert guard_prometheus(prom, cluster)
    assert prom.restart_count == 1


def test_guarded_run_sweeps_on_entry_and_teardown_on_exception():
    ctl = ChaosController()
    leftover = ctl.create("Lv_P_CPU_preserve")     # crashed previous run
    assert ctl.status()
    with pytest.raises(RuntimeError):
        with GuardedRun(ctl) as guard:
            assert guard.swept_on_entry == 1       # pre-run sweep
            assert not ctl.status()
            ctl.create("Lv_S_KILLPOD_preserve")
            raise RuntimeError("body failed")      # ERR trap path
    assert not ctl.status()                        # trap destroyed chaos
    assert not ctl.destroy(leftover.uid)


def test_run_with_recovery_full_envelope():
    cluster = cluster_for_testbed("TT", seed=1)
    ctl = ChaosController()
    prom = PrometheusState(oom_killed=True, ready=False)
    calls = []

    def body():
        # fault is live exactly while the body runs
        lat, err = ctl.active_effects("ts-preserve-service")
        calls.append((lat, err))
        return "collected"

    result, report = run_with_recovery(
        cluster, ctl, "Lv_P_CPU_preserve", body, prometheus=prom)
    assert result == "collected"
    assert report.ready
    assert prom.restart_count == 1
    assert calls and calls[0][0] > 1.0             # latency effect was active
    assert not ctl.status()                        # torn down after


def test_phase_script_shapes():
    p = Pod(name="x", service="s", crashloop=True, crashes_before_ok=1)
    assert p.phase_at(2.0)[0] is Phase.PENDING
    assert p.phase_at(10.0)[0] is Phase.CRASHLOOP
    cluster = SyntheticCluster([p])
    cluster.advance(10.0)
    cluster.delete_pod("x")
    phase, ready = p.phase_at(cluster.now + 25.0)
    assert phase is Phase.RUNNING and ready


def test_stuck_deadline_counts_running_time_only():
    # long Pending phase must not pre-charge the stuck deadline
    pods = [Pod(name="late-stuck", service="s", startup_s=200.0,
                stuck_unready=True)]
    cluster = SyntheticCluster(pods)
    ctl = ReadinessController(stuck_deadline_s=180.0, timeout_s=900.0)
    report = ctl.wait_for_pods_ready(cluster)
    assert report.ready
    # restart happens only after 180 s of Running-not-Ready, i.e. >= 380 s in
    assert report.waited_s >= 380.0


def test_cluster_for_testbed_rejects_oversubscription():
    with pytest.raises(ValueError):
        cluster_for_testbed("SN", n_crashloop=40)
