"""Must-pass fixture for S301: the same work through the seam."""


def drain(replays):
    return [r.get_state() for r in replays]
