"""Must-trip fixture for the E2xx env-contract family: an uncovered
constant read, plus the dynamic f-string/concat reads the old grep
gate could not see (its documented false negative)."""
import os
from os import environ

name = "SHARDS"
a = os.environ.get("ANOMOD_ROGUE_KNOB")     # E201: uncovered
b = environ[f"ANOMOD_{name}"]               # E202: dynamic (f-string)
c = os.getenv("ANOMOD_" + name)             # E202: dynamic (concat)
env_alias = os.environ
d = env_alias["ANOMOD_ALIASED_ROGUE"]       # E201: via alias
