"""Must-trip fixture for S301 (linted under a pretend NON-seam path):
pool internals reached around the get_state/set_state/gather seam."""


def drain(replays, runner):
    slots = [r._slot for r in replays]          # S301
    runner._slots.clear()                       # S301
    return replays[0]._runner, slots            # S301
