"""Must-pass fixture for the E2xx env-contract family: covered ANOMOD_*
reads (the test hands the linter a corpus naming ANOMOD_KNOWN_KNOB) and
non-ANOMOD reads, which are out of contract."""
import os

a = os.environ.get("ANOMOD_KNOWN_KNOB", "")
b = os.environ.get("PATH", "")
c = os.getenv("HOME")
