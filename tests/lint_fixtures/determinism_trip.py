"""Must-trip fixture for the D1xx determinism family (linted under a
pretend canonical path, e.g. anomod/serve/fixture.py)."""
import random
import time

import numpy as np
import numpy.random


def dotted_import_is_not_an_alias_hole():
    # `import numpy.random` binds the root name `numpy`: the unseeded
    # call below must still resolve to numpy.random.default_rng
    return numpy.random.default_rng()       # D103: unseeded


def decide(tenants):
    stamp = time.time()                     # D101: wall clock
    rng = np.random.default_rng()           # D103: unseeded
    jitter = random.random()                # D103: process-global RNG
    legacy = np.random.rand(3)              # D103: legacy global API
    keyed = {id(t): t for t in tenants}     # D104: address-keyed
    order = list(set(tenants))              # D105: set order
    deadline = time.perf_counter() + 5.0    # D102: not wall-leg form
    for t in set(tenants):                  # D105: set iteration
        stamp += t
    return stamp, rng, jitter, legacy, keyed, order, deadline
