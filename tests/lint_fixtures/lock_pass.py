"""Must-pass fixture for L501: mutations under the lock, reads free,
and the *_locked caller-holds-lock idiom."""
import threading


class Reg:
    def __init__(self):
        self._lock = threading.Lock()
        self._rows = []
        self.count = 0

    def record(self, v):
        with self._lock:
            self._rows.append(v)
            self.count += 1
            self._fold_locked()

    def _fold_locked(self):
        self._rows.clear()

    def peek(self):
        return self.count
