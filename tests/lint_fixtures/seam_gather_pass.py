"""Must-pass fixture for S302: gathers that honor always-copy."""
import numpy as np


class Pool:
    def gather(self, slot):
        return self.agg[slot].copy()

    def gather_rows(self, slots):
        return np.asarray(self.hist[slots])
