"""Must-pass fixture for C601: the commit barrier lands before the
first read of scoring-committed state (the async-tail ordering), and
reads with no deferred work in flight are untouched."""


class Engine:
    def tick(self, served):
        # reads BEFORE any deferred issue are free
        n_before = len(self._tenant_det)
        self._commit_deferred()                      # prior tick's barrier
        pending = self._stage_pending(served)
        self._dispatch_rounds(pending, defer=True)   # window opens
        self._deferred = {"pending": pending}
        if self.checkpoint_due():
            self._commit_deferred()                  # barrier closes it
        return n_before, len(self._tenant_det)       # post-barrier read

    def closure_is_not_a_window_read(self, served, pending):
        self._deferred = {"pending": pending}

        def _later():
            # executes at the barrier, on the worker — not a window read
            return self.alerts_for(0)

        self._commit_deferred()
        return _later()
