"""Must-trip fixture for S302 (linted under a pretend SEAM path, e.g.
anomod/replay.py): a gather returning an aliased pool-plane row."""


class Pool:
    def gather(self, slot):
        return self.agg[slot]                   # S302: aliased row

    def gather_rows(self, slots):
        return self.hist[slots]                 # S302: aliased rows
