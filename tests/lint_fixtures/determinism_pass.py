"""Must-pass fixture for the D1xx determinism family: seeded RNG,
wall-leg perf_counter form, sorted set iteration."""
import time

import numpy as np


def decide(seed, tenant, window, tenants):
    t0 = time.perf_counter()
    rng = np.random.default_rng((seed, tenant, window))
    order = sorted(set(tenants))
    draws = rng.random(len(order))
    t_wall = time.perf_counter()
    wall_s = time.perf_counter() - t0
    return draws, order, wall_s, time.perf_counter() - t_wall
