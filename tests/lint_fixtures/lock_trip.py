"""Must-trip fixture for L501 (linted under a pretend lock-owning path,
e.g. anomod/obs/registry.py): shared-state mutation outside the lock."""
import threading


class Reg:
    def __init__(self):
        self._lock = threading.Lock()
        self._rows = []
        self.count = 0

    def record(self, v):
        self._rows.append(v)        # L501: unlocked append
        self.count += 1             # L501: unlocked increment

    def install(self, key, v):
        self._rows[0] = (key, v)    # L501: unlocked subscript store

    def reset(self):
        self._rows, self.count = [], 0   # L501: unlocked tuple unpack
