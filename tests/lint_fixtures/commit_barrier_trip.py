"""Must-trip fixture for C601 (linted under a pretend serve path, e.g.
anomod/serve/fixture.py): reads of scoring-committed state while a
deferred dispatch is still in flight — before the commit barrier."""


class Engine:
    def tick_defer_call(self, served):
        pending = self._stage_pending(served)
        self._dispatch_rounds(pending, defer=True)   # window opens
        alerts = self.alerts_for(0)                  # C601: pre-commit read
        n = len(self._tenant_det)                    # C601: pre-commit read
        self._flight_tick(0.0, served, 0.0)          # C601: pre-commit publish
        self._commit_deferred()
        return alerts, n

    def tick_armed_deferred(self, served, pending):
        self._deferred = {"pending": pending}        # window opens
        doc = self._perf_drain()                     # C601: pre-commit drain
        self._commit_deferred()
        return doc
