"""Worker process for the 2-process multi-host mesh test.

Launched by tests/test_multihost.py as
``python tests/multihost_worker.py <process_id> <num_processes> <port>``.
Each worker pins 4 virtual CPU devices, joins the jax.distributed
coordinator, builds the hybrid (dcn, data) mesh, and runs three
cross-process exercises:

- a psum over both mesh axes (the gradient/sketch-state reduction shape),
- an HLL register pmax-merge where each process observes a disjoint item
  range (the distinct-count plane of the replay pipeline, merged over DCN),
- a full GCN training step with the batch dp-sharded over (dcn, data) and
  replicated params: each process stages only ITS half of the batch, XLA
  derives the cross-process gradient psum from the shardings — the
  multi-host analog of the reference's per-worker collection + merge, for
  training.

Prints one ``MHRESULT {json}`` line; the parent asserts both processes
produce identical, correct values.
"""

import json
import os
import sys


def main() -> int:
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    local_devices = 4
    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={local_devices}")
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")

    from anomod.parallel.multihost import (dcn_data_parallel_spec,
                                           initialize_distributed,
                                           make_hybrid_mesh,
                                           process_local_array,
                                           replicated_value)
    initialize_distributed(f"127.0.0.1:{port}", nproc, pid)

    import numpy as np
    from anomod.parallel.mesh import shard_map_compat as shard_map
    from jax.sharding import PartitionSpec as P

    from anomod.ops.hll import hll_add, hll_estimate, hll_init
    from anomod.parallel.collectives import pmax_merge_hll

    mesh = make_hybrid_mesh()
    spec = dcn_data_parallel_spec(mesh)
    n_global = nproc * local_devices

    # --- psum across the process boundary -------------------------------
    fn = jax.jit(shard_map(
        lambda x: jax.lax.psum(x, tuple(mesh.axis_names)),
        mesh=mesh, in_specs=spec, out_specs=P()))
    local = np.arange(pid * local_devices, (pid + 1) * local_devices,
                      dtype=np.float32)
    psum = float(replicated_value(
        fn(process_local_array(mesh, spec, local))).ravel()[0])

    # --- HLL sketch-state merge over DCN --------------------------------
    p = 10
    # each global shard observes a disjoint 500-item range
    per_shard = np.stack([
        hll_add(hll_init(p=p), np.arange(d * 500, (d + 1) * 500,
                                         dtype=np.uint64), p=p)
        for d in range(pid * local_devices, (pid + 1) * local_devices)])
    merge = jax.jit(shard_map(
        lambda r: pmax_merge_hll(r[0], tuple(mesh.axis_names)),
        mesh=mesh, in_specs=spec, out_specs=P()))
    merged = replicated_value(merge(
        process_local_array(mesh, spec, per_shard)))
    est = float(hll_estimate(merged))

    # --- dp training step across the process boundary -------------------
    # THE shared distributed step (anomod.parallel.train), on the hybrid
    # mesh with process-local staging: each process passes only its rows.
    from anomod.parallel.train import make_distributed_train_step
    from anomod.rca import _stack, build_dataset

    samples, _ = build_dataset("TT", seeds=[0], n_traces=8, n_windows=4)
    n_batch = 2 * n_global                      # dp axis | global devices
    stacked = _stack((samples * ((n_batch // len(samples)) + 1))[:n_batch])
    params, opt_state, train_step, put_batch = make_distributed_train_step(
        "gcn", stacked, mesh, stage="process-local")
    rows = slice(pid * (n_batch // nproc), (pid + 1) * (n_batch // nproc))
    batch = put_batch({k: v[rows] for k, v in stacked.items()})
    params, opt_state, loss = train_step(params, opt_state, batch)
    loss = float(replicated_value(loss))
    leaf0 = sorted(jax.tree_util.tree_leaves_with_path(params),
                   key=lambda kv: str(kv[0]))[0][1]
    param_sum = float(np.sum(replicated_value(leaf0)))

    print("MHRESULT " + json.dumps({
        "pid": pid,
        "process_count": jax.process_count(),
        "global_devices": jax.device_count(),
        "psum": psum,
        "expected_psum": float(sum(range(n_global))),
        "hll_estimate": est,
        "true_distinct": n_global * 500,
        "train_loss": loss,
        "param_sum": param_sum,
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
