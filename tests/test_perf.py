"""Performance observatory (anomod.obs.perf) + `anomod perf`.

The acceptance-critical pins: the dispatch-lifecycle recorder is a pure
READ-SIDE consumer (states/alerts/SLO/shed and the canonical flight
journal byte-identical with recording on or off); the event timeline
RECONCILES with the five-leg ServeReport walls (the hooks reuse the
wall-leg clock reads, so agreement is float-rounding-exact for the
dispatch and fold legs); the overlap-headroom analyzer implements its
documented model exactly (synthetic-event unit pins); `anomod perf
diff` passes two same-seed captures and flags a doctored 2× wall
slowdown by name; and the Chrome export rides the one Tracer pipeline
with shard/slot tags that survive the ``spans_from_chrome`` round trip.
"""

import copy
import json

import numpy as np
import pytest

from anomod.obs.perf import (EVENT_FIELDS, PerfRecorder, analyze_events,
                             bootstrap_ratio_ci, capture_history,
                             collect_decisions, collect_wall_samples,
                             diff_captures, fold_perf_records, perf_tracer)
from anomod.serve.engine import SHARD_VARIANT_REPORT_FIELDS, run_power_law

#: the shared tiny seeded run (the test_flight idiom): long enough for
#: multiple fused dispatch rounds per tick so the pipeline actually
#: carries in-flight work the timeline can see
RUN_KW = dict(n_tenants=6, n_services=4, capacity_spans_per_s=1000,
              overload=2.0, duration_s=20, tick_s=1.0, seed=5,
              window_s=5.0, baseline_windows=4, fault_tenants=1,
              buckets=(64, 256), lane_buckets=(1, 2, 4), max_backlog=1500,
              n_windows=16, shards=1, pipeline=2)


def _run(**overrides):
    return run_power_law(**{**RUN_KW, **overrides})


@pytest.fixture(scope="module")
def perf_pair():
    """One perf-off / perf-on run pair on the same seed."""
    eng_off, rep_off = _run()
    eng_on, rep_on = _run(perf=True)
    return eng_off, rep_off, eng_on, rep_on


# ---------------------------------------------------------------------------
# the read-side contract (the PR-9 pin technique)
# ---------------------------------------------------------------------------

def test_perf_on_off_decisions_byte_identical(perf_pair):
    eng_off, rep_off, eng_on, rep_on = perf_pair
    # every tenant's alert stream and replay state, bitwise
    assert set(eng_off._tenant_det) == set(eng_on._tenant_det)
    for tid in eng_off._tenant_det:
        assert eng_off.alerts_for(tid) == eng_on.alerts_for(tid)
        s1 = eng_off._tenant_replay[tid].state
        s2 = eng_on._tenant_replay[tid].state
        assert np.array_equal(np.asarray(s1.agg), np.asarray(s2.agg))
        assert np.array_equal(np.asarray(s1.hist), np.asarray(s2.hist))
    # SLO / shed / admission byte-identical
    assert rep_off.latency == rep_on.latency
    assert rep_off.shed_fraction == rep_on.shed_fraction
    assert rep_off.per_priority == rep_on.per_priority
    # report-field equality outside the declared variant surface and
    # the perf plane's own config bit
    skip = set(SHARD_VARIANT_REPORT_FIELDS) | {"perf_enabled"}
    a = {k: v for k, v in rep_off.to_dict().items() if k not in skip}
    b = {k: v for k, v in rep_on.to_dict().items() if k not in skip}
    assert a == b
    # canonical flight journals equal — the recorder never touched a
    # canonical plane (events ride the `perf` VARIANT key only)
    assert eng_off.flight_recorder.canonical_bytes() \
        == eng_on.flight_recorder.canonical_bytes()


def test_perf_plane_live_and_variant_declared(perf_pair):
    _, _, eng_on, rep_on = perf_pair
    assert rep_on.perf_enabled is True
    assert rep_on.perf_events_recorded > 0
    assert rep_on.fold_wait_s > 0.0
    assert 0.0 <= rep_on.overlap_headroom_s <= rep_on.fold_wait_s + 1e-9
    assert eng_on.perf_events_dropped == 0
    # the new report fields are consciously variant (the P401 route)
    for f in ("perf_events_recorded", "overlap_headroom_s",
              "fold_wait_s", "bubble_fractions"):
        assert f in SHARD_VARIANT_REPORT_FIELDS
    from anomod.obs.flight import FLIGHT_VARIANT_KEYS
    assert "perf" in FLIGHT_VARIANT_KEYS
    # every journal record carries the perf tier (the self-describing
    # shape contract), and the events landed in the VARIANT tier
    recs = eng_on.flight_recorder.records()
    assert all("perf" in r for r in recs)
    assert sum(len(r["perf"]["events"]) for r in recs) \
        == rep_on.perf_events_recorded
    # a perf-OFF engine journals the tier EMPTY, never absent
    eng_off = perf_pair[0]
    assert all(r["perf"] == {"events": [], "headroom_s": 0.0,
                             "wait_s": 0.0}
               for r in eng_off.flight_recorder.records())


# ---------------------------------------------------------------------------
# timeline ↔ five-leg wall reconciliation
# ---------------------------------------------------------------------------

def test_timeline_reconciles_with_report_walls(perf_pair):
    """The events reuse the wall-leg clock reads: the summed dispatch
    and fold event durations equal the report walls to rounding, stage
    events are a subset of the stage wall (stage_plan time is not a
    dispatch event), and the measured WAIT fits inside the fold leg."""
    _, _, eng_on, rep_on = perf_pair
    evs = eng_on.perf_events
    assert evs and all(set(EVENT_FIELDS) == set(e) for e in evs)
    disp = sum(e["submitted"] - e["submitted_t0"] for e in evs)
    fold = sum(e["folded"] - e["retire_t0"] for e in evs)
    stage = sum(e["staged"] - e["staged_t0"] for e in evs)
    wait = sum(e["materialized"] - e["retire_t0"] for e in evs)
    # report walls round to 4 digits; per-leg slack is rounding only
    assert abs(disp - rep_on.dispatch_wall_s) <= 1e-3 + 0.01 * disp
    assert abs(fold - rep_on.fold_wall_s) <= 1e-3 + 0.01 * fold
    assert 0.0 < stage <= rep_on.stage_wall_s + 1e-3
    assert 0.0 < wait <= fold + 1e-9
    assert abs(wait - rep_on.fold_wait_s) < 1e-6
    # per-tick: each journal record's perf events sum to that record's
    # variant fold-leg wall delta (the journal carries both surfaces)
    for rec in eng_on.flight_recorder.records():
        evs_t = rec["perf"]["events"]
        if not evs_t:
            continue
        fold_t = sum(e["folded"] - e["retire_t0"] for e in evs_t)
        assert abs(fold_t - rec["walls"]["fold_s"]) <= 2e-3
    # lifecycle ordering holds per event
    for e in evs:
        assert e["staged_t0"] <= e["staged"] <= e["submitted_t0"] \
            <= e["submitted"] <= e["retire_t0"] <= e["materialized"] \
            <= e["folded"]


def test_slot_refill_stamps_previous_dispatch(perf_pair):
    """A reused scratch slot stamps the PREVIOUS dispatch on that slot
    with the refill time — strictly after that dispatch materialized
    (the PR-5 scratch-reuse contract, now visible in the timeline)."""
    _, _, eng_on, _ = perf_pair
    refilled = [e for e in eng_on.perf_events
                if e["refill"] is not None]
    assert refilled, "a multi-round run must reuse scratch slots"
    for e in refilled:
        assert e["refill"] >= e["materialized"]


# ---------------------------------------------------------------------------
# the overlap-headroom model (synthetic-event unit pins)
# ---------------------------------------------------------------------------

def _ev(seq, slot, wait, stage, tick=0, shard=0, t0=0.0):
    """A synthetic lifecycle event: ``stage`` seconds of scratch pack,
    ``wait`` seconds blocked at retire."""
    staged = t0 + stage
    return {"seq": seq, "tick": tick, "shard": shard, "width": 64,
            "lanes": 2, "slot": slot, "staged_t0": t0, "staged": staged,
            "submitted_t0": staged, "submitted": staged + 0.001,
            "retire_t0": staged + 0.002,
            "materialized": staged + 0.002 + wait,
            "folded": staged + 0.003 + wait, "refill": None}


def test_headroom_claims_later_other_slot_staging():
    # dispatch 0 waits 10 ms; dispatch 1 (other slot) stages 4 ms after
    # it — all 4 ms are legally hideable under the wait
    evs = [_ev(0, slot=0, wait=0.010, stage=0.001),
           _ev(1, slot=1, wait=0.0, stage=0.004, t0=1.0)]
    got = analyze_events(evs, pipeline=2)
    assert got["n_events"] == 2
    assert abs(got["wait_s"] - 0.010) < 1e-12
    assert abs(got["headroom_s"] - 0.004) < 1e-12


def test_headroom_capped_by_wait_and_blocked_by_same_slot():
    # same-slot follower: its staging needs THIS slot, the barrier
    # protects exactly that — zero headroom
    evs = [_ev(0, slot=0, wait=0.010, stage=0.001),
           _ev(1, slot=0, wait=0.0, stage=0.004, t0=1.0)]
    assert analyze_events(evs, pipeline=2)["headroom_s"] == 0.0
    # headroom never exceeds the wait it hides under
    evs = [_ev(0, slot=0, wait=0.002, stage=0.001),
           _ev(1, slot=1, wait=0.0, stage=0.050, t0=1.0)]
    got = analyze_events(evs, pipeline=2)
    assert abs(got["headroom_s"] - 0.002) < 1e-12


def test_headroom_depth_window_and_single_claim():
    # pipeline=1: only the NEXT other-slot dispatch's staging is legal
    evs = [_ev(0, slot=0, wait=0.010, stage=0.001),
           _ev(1, slot=1, wait=0.0, stage=0.003, t0=1.0),
           _ev(2, slot=2, wait=0.0, stage=0.004, t0=2.0)]
    got = analyze_events(evs, pipeline=1)
    assert abs(got["headroom_s"] - 0.003) < 1e-12
    # pipeline=2 reaches both
    got = analyze_events(evs, pipeline=2)
    assert abs(got["headroom_s"] - 0.007) < 1e-12
    # a stage wall claims once: the earliest wait takes both followers'
    # staging (1 + 3 ms); the second wait finds nothing left — the
    # total is 4 ms, NOT 4 + 3 (double-counting ev2 under both waits)
    evs = [_ev(0, slot=0, wait=0.010, stage=0.001),
           _ev(1, slot=1, wait=0.010, stage=0.001, t0=1.0),
           _ev(2, slot=2, wait=0.0, stage=0.003, t0=2.0)]
    got = analyze_events(evs, pipeline=4)
    assert abs(got["headroom_s"] - 0.004) < 1e-12
    # groups never span (tick, shard) boundaries
    evs = [_ev(0, slot=0, wait=0.010, stage=0.001, tick=0),
           _ev(1, slot=1, wait=0.0, stage=0.004, t0=1.0, tick=1)]
    assert analyze_events(evs, pipeline=2)["headroom_s"] == 0.0


def test_fold_perf_records_order_and_recorder_abort():
    a = [_ev(0, slot=0, wait=0, stage=0.001, shard=1)]
    b = [_ev(0, slot=0, wait=0, stage=0.001, shard=0),
         _ev(1, slot=1, wait=0, stage=0.001, shard=0)]
    folded = fold_perf_records([a, b])
    assert [(e["shard"], e["seq"]) for e in folded] == \
        [(0, 0), (0, 1), (1, 0)]
    # an aborted dispatch drops its open record, counted
    rec = PerfRecorder(0)
    rec.note_staged((64, 2, 0), 0.0, 0.001)
    rec.note_aborted((64, 2, 0))
    assert rec.drain() == [] and rec.n_aborted == 1


# ---------------------------------------------------------------------------
# noise-aware capture diffing
# ---------------------------------------------------------------------------

def _capture(walls, shed=0.4, p99=23.0):
    return {"metric": "serve_sustained_throughput", "value": 1e5,
            "shed_fraction": shed,
            "p99_admission_to_scored_latency_s": p99,
            "staging": {"parity": {"alerts_identical": True}},
            "perf": {"raw_wall_s": list(walls),
                     "overlap_headroom_s": 0.01}}


def test_diff_same_capture_clean_and_doctored_flagged():
    rng = np.random.default_rng(0)
    walls = (0.05 + 0.01 * rng.random(40)).tolist()
    a = _capture(walls)
    doc = diff_captures(a, copy.deepcopy(a), noise_floor=0.35)
    assert doc["status"] == "ok"
    assert doc["decisions"]["identical"] is True
    assert doc["regressions"] == []
    assert doc["noise_model"]["floor_fraction"] == 0.35
    # a 2x wall slowdown clears any reasonable noise floor and is
    # named by path — the mechanized answer to "is this PR slower"
    slow = _capture([2.0 * w for w in walls])
    doc = diff_captures(a, slow, noise_floor=0.35)
    assert doc["status"] == "wall-regression"
    assert doc["regressions"][0]["path"] == "perf.raw_wall_s"
    assert doc["regressions"][0]["ci95"][0] > 1.35
    # ...and the mirror direction reads as improvement, not regression
    doc = diff_captures(slow, a, noise_floor=0.35)
    assert doc["status"] == "ok"
    assert doc["walls"][0]["verdict"] == "improvement"
    # noise-sized wobble stays within the floor
    wobble = _capture([1.1 * w for w in walls])
    assert diff_captures(a, wobble, noise_floor=0.35)["status"] == "ok"


def test_diff_decision_drift_is_never_noise():
    a = _capture([0.05] * 10)
    b = _capture([0.05] * 10, shed=0.41)
    doc = diff_captures(a, b, noise_floor=0.35)
    assert doc["status"] == "decision-drift"
    assert doc["decision_mismatches"][0]["path"] == "shed_fraction"
    # parity bits are decisions too
    b = _capture([0.05] * 10)
    b["staging"]["parity"]["alerts_identical"] = False
    doc = diff_captures(a, b, noise_floor=0.35)
    assert any(m["path"] == "staging.parity.alerts_identical"
               for m in doc["decision_mismatches"])


def test_diff_decision_coverage_gap_is_not_ok():
    """A diff that never actually compared the decision surface must
    not report ok: a truncated/foreign capture sharing NO decision
    keys reads as a coverage gap (identical=None), while PARTIAL
    overlap stays legitimate — block schemas grow across PRs."""
    a = _capture([0.05] * 10)
    b = {"metric": "x", "perf": {"raw_wall_s": [0.05] * 10}}
    doc = diff_captures(a, b, noise_floor=0.35)
    assert doc["status"] == "decision-coverage-gap"
    assert doc["decisions"]["identical"] is None
    assert doc["decisions"]["compared"] == 0
    # partial overlap (B grew a block A lacks) is still ok
    c = copy.deepcopy(a)
    c["new_block"] = {"shed_fraction": 0.7}
    doc = diff_captures(a, c, noise_floor=0.35)
    assert doc["status"] == "ok"
    assert doc["decisions"]["only_in_b"] == ["new_block.shed_fraction"]
    # two decision-free docs compare nothing and that IS ok
    assert diff_captures({"x": 1}, {"x": 2})["status"] == "ok"


def test_collectors_and_bootstrap_determinism():
    a = _capture([0.05] * 5)
    assert "perf.raw_wall_s" in collect_wall_samples(a)
    dec = collect_decisions(a)
    assert "shed_fraction" in dec
    assert "staging.parity.alerts_identical" in dec
    assert "value" not in dec                  # throughput is a wall
    # seeded bootstrap: the same inputs always give the same CI
    x = [1.0, 1.1, 0.9, 1.05]
    y = [2.0, 2.2, 1.8, 2.1]
    assert bootstrap_ratio_ci(x, y) == bootstrap_ratio_ci(x, y)
    ratio, lo, hi = bootstrap_ratio_ci(x, y)
    assert lo <= ratio <= hi and lo > 1.5


def test_capture_history_indexes_runs(tmp_path):
    (tmp_path / "b.json").write_text(json.dumps(
        {"metric": "m", "value": 2.0, "unit": "u",
         "timestamp_utc": "2026-08-04T01:00:00Z",
         "shed_fraction": 0.4,
         "perf": {"overlap_headroom_s": 0.5,
                  "raw_wall_s": [0.1, 0.2]}}))
    (tmp_path / "a.json").write_text(json.dumps(
        {"metric": "m", "value": 1.0, "unit": "u",
         "timestamp_utc": "2026-08-03T01:00:00Z"}))
    (tmp_path / "junk.json").write_text("not json")
    (tmp_path / "other.json").write_text(json.dumps({"no": "metric"}))
    rows = capture_history(tmp_path)
    assert [r["value"] for r in rows] == [1.0, 2.0]   # timestamp order
    assert rows[1]["overlap_headroom_s"] == 0.5
    assert rows[1]["n_wall_sample_legs"] == 1
    assert rows[0]["overlap_headroom_s"] is None


# ---------------------------------------------------------------------------
# Chrome/Perfetto export through the one Tracer pipeline
# ---------------------------------------------------------------------------

def test_perf_chrome_export_roundtrip(perf_pair):
    from anomod.utils.tracing import spans_from_chrome
    _, _, eng_on, _ = perf_pair
    tr = perf_tracer(eng_on.perf_events)
    events = tr.to_chrome()
    assert events and all(e["ph"] == "X" for e in events)
    # shard + pipeline-slot tags ride args (the Perfetto grouping key)
    assert all("shard" in e["args"] and "slot" in e["args"]
               for e in events)
    spans = spans_from_chrome(events)
    names = {s["name"] for s in spans}
    assert {"lane.stage", "lane.dispatch", "lane.inflight",
            "lane.wait", "lane.fold"} <= names
    # round trip: tags and lanes survive a Perfetto-style re-sort
    resorted = spans_from_chrome(
        sorted(events, key=lambda e: e["ts"], reverse=True))
    assert resorted == spans
    for s in spans:
        assert s["tags"]["shard"] == "0"
        assert "slot" in s["tags"] and "width" in s["tags"]
    # distinct scratch slots land on distinct lanes (tids)
    by_slot = {}
    for e in events:
        by_slot.setdefault((e["args"]["width"], e["args"]["lanes"],
                            e["args"]["slot"]), set()).add(e["tid"])
    assert all(len(tids) == 1 for tids in by_slot.values())
    if len(by_slot) > 1:
        all_tids = [next(iter(t)) for t in by_slot.values()]
        assert len(set(all_tids)) == len(all_tids)


def test_tracer_worker_thread_lanes_and_tags():
    """Satellite pin: worker-thread spans export on their OWN chrome
    lane (tid) with shard tags in args, and spans_from_chrome carries
    the lane through the round trip."""
    import threading

    from anomod.utils.tracing import Tracer, spans_from_chrome
    tr = Tracer("anomod-test")
    with tr.span("coordinator"):
        pass
    # both workers alive at once (a finished thread's ident is
    # reusable — the engine's ShardWorkers are persistent, which is
    # what the lane-per-thread contract rides on)
    barrier = threading.Barrier(2)

    def worker(shard):
        with tr.span("serve.score_shard", shard=shard, pipeline=2):
            barrier.wait(timeout=10)

    ts = [threading.Thread(target=worker, args=(s,)) for s in (0, 1)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    events = tr.to_chrome()
    shard_spans = [e for e in events
                   if e["name"] == "serve.score_shard"]
    assert {e["args"]["shard"] for e in shard_spans} == {"0", "1"}
    # worker lanes are distinct from the coordinator's lane 0
    assert all(e["tid"] != 0 for e in shard_spans)
    assert len({e["tid"] for e in shard_spans}) == 2
    spans = spans_from_chrome(events)
    got = [s for s in spans if s["name"] == "serve.score_shard"]
    assert {s["tags"]["shard"] for s in got} == {"0", "1"}
    assert all(s["tid"] != 0 for s in got)


def test_sharded_engine_trace_carries_shard_tags():
    """The engine's worker-thread score spans carry the shard tag into
    the chrome export — a 2-shard trace's lanes group by shard."""
    from anomod.utils.tracing import Tracer
    tracer = Tracer("anomod-serve")
    _run(shards=2, tracer=tracer)
    events = tracer.to_chrome()
    shard_spans = [e for e in events
                   if e["name"] == "serve.score_shard"]
    assert {e["args"]["shard"] for e in shard_spans} == {"0", "1"}
    assert len({e["tid"] for e in shard_spans}) == 2


# ---------------------------------------------------------------------------
# knobs + CLI
# ---------------------------------------------------------------------------

def test_perf_knobs_validated(monkeypatch):
    from anomod.config import Config
    monkeypatch.setenv("ANOMOD_PERF", "1")
    monkeypatch.setenv("ANOMOD_PERF_MAX_EVENTS", "1024")
    monkeypatch.setenv("ANOMOD_PERF_NOISE_FLOOR", "0.2")
    cfg = Config()
    assert cfg.perf is True
    assert cfg.perf_max_events == 1024
    assert cfg.perf_noise_floor == 0.2
    for var, bad in (("ANOMOD_PERF_MAX_EVENTS", "zero"),
                     ("ANOMOD_PERF_MAX_EVENTS", "0"),
                     ("ANOMOD_PERF_NOISE_FLOOR", "lots"),
                     ("ANOMOD_PERF_NOISE_FLOOR", "-1")):
        monkeypatch.setenv(var, bad)
        with pytest.raises(ValueError):
            Config()
        monkeypatch.setenv("ANOMOD_PERF_MAX_EVENTS", "1024")
        monkeypatch.setenv("ANOMOD_PERF_NOISE_FLOOR", "0.2")


def test_perf_cli_record_and_diff(tmp_path, capsys):
    from anomod.cli import main
    out = tmp_path / "timeline.json"
    chrome = tmp_path / "timeline_chrome.json"
    rc = main(["perf", "record", "--out", str(out),
               "--chrome", str(chrome), "--tenants", "4",
               "--duration", "8", "--tick", "1.0",
               "--capacity", "1000", "--seed", "3"])
    assert rc == 0
    doc = json.loads(out.read_text())
    assert doc["perf_format"] == 1
    assert doc["report"]["perf_events_recorded"] == len(doc["events"])
    assert len(doc["raw_wall_s"]) == 8
    from anomod.utils.tracing import spans_from_chrome
    spans = spans_from_chrome(json.loads(chrome.read_text()))
    assert any(s["name"] == "lane.stage" for s in spans)
    capsys.readouterr()
    # diff: a capture against itself exits 0; a doctored 2x exits 1
    # naming the wall; a decision drift exits 2
    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    cap = _capture([0.05, 0.06, 0.055, 0.052, 0.058] * 4)
    a.write_text(json.dumps(cap))
    b.write_text(json.dumps(cap))
    assert main(["perf", "diff", str(a), str(b)]) == 0
    capsys.readouterr()
    slow = copy.deepcopy(cap)
    slow["perf"]["raw_wall_s"] = [2 * w for w in
                                  slow["perf"]["raw_wall_s"]]
    b.write_text(json.dumps(slow))
    assert main(["perf", "diff", str(a), str(b)]) == 1
    got = json.loads(capsys.readouterr().out)
    assert got["regressions"][0]["path"] == "perf.raw_wall_s"
    drift = copy.deepcopy(cap)
    drift["shed_fraction"] = 0.99
    b.write_text(json.dumps(drift))
    assert main(["perf", "diff", str(a), str(b)]) == 2
    capsys.readouterr()
    # a coverage-gap diff exits 2 like drift: nothing was compared
    b.write_text(json.dumps({"metric": "x",
                             "perf": {"raw_wall_s": [0.05] * 10}}))
    assert main(["perf", "diff", str(a), str(b)]) == 2
    capsys.readouterr()
    # history over the two files
    assert main(["perf", "history", str(tmp_path)]) == 0
    hist = json.loads(capsys.readouterr().out)
    assert hist["n_captures"] >= 2
    # mode-mismatched flags fail loud, never silently ignored
    with pytest.raises(SystemExit):
        main(["perf", "history", str(tmp_path), "--out", "x.json"])
    with pytest.raises(SystemExit):
        main(["perf", "history", str(tmp_path), "--noise-floor", "0.2"])
    capsys.readouterr()


def test_perf_retention_bound_counts_drops(monkeypatch):
    """The retained-event ring is bounded and every eviction is
    counted — loss visible, never silent (the flight-ring pin)."""
    monkeypatch.setenv("ANOMOD_PERF_MAX_EVENTS", "8")
    from anomod.config import Config, get_config, set_config
    old = get_config()
    try:
        set_config(Config())
        # flight OFF: the perf plane still accumulates and retains
        # (the journal doc alone is skipped — nothing consumes it)
        eng, rep = _run(perf=True, duration_s=10, flight=False)
        assert rep.perf_events_recorded > 8
        assert rep.fold_wait_s > 0.0
        assert len(eng.perf_events) == 8
        assert eng.perf_events_dropped == rep.perf_events_recorded - 8
        # the retained tail is the newest events
        assert eng.perf_events[-1]["tick"] >= eng.perf_events[0]["tick"]
    finally:
        set_config(old)
