"""OpenAPI-spec ingestion: fixture spec -> endpoint catalog -> generated
suite -> executed against the synthetic gateway (the reference's
``--bbSwaggerUrl`` regeneration flow, run_experiment.sh:500-555, made
deterministic and JVM-free)."""

import json
from pathlib import Path

import numpy as np
import pytest

from anomod.openapi import (SpecEndpoint, endpoint_pool_from_spec,
                            instantiate, load_spec, parse_spec)

FIXTURE = Path(__file__).parent / "fixtures" / "tt_openapi_small.json"


@pytest.fixture(scope="module")
def spec():
    return load_spec(FIXTURE)


def test_parse_spec_flattens_operations(spec):
    eps = parse_spec(spec)
    assert len(eps) == 7          # 5 single-op paths + get/delete on order
    by_key = {(e.method, e.template): e for e in eps}
    # $ref body schema resolved through #/definitions
    login = by_key[("POST", "/api/v1/users/login")]
    assert login.body_schema["properties"]["username"]["type"] == "string"
    # path-LEVEL shared parameter reaches both operations
    for m in ("GET", "DELETE"):
        e = by_key[(m, "/api/v1/orderservice/order/{orderId}")]
        assert e.path_params == (("orderId", "string"),)
    # op-level typed path param
    train = by_key[("GET", "/api/v1/trainservice/trains/{trainId}")]
    assert train.path_params == (("trainId", "integer"),)


def test_instantiate_is_deterministic_and_complete(spec):
    pool1 = endpoint_pool_from_spec(spec, seed=4)
    pool2 = endpoint_pool_from_spec(spec, seed=4)
    assert [(s.method, s.path, s.body) for s in pool1] == \
        [(s.method, s.path, s.body) for s in pool2]
    for s in pool1:
        assert "{" not in s.path and "}" not in s.path
        if s.body is not None:
            body = json.loads(s.body)
            assert isinstance(body, dict) and body
    # schema types drive the synthesized values
    preserve = next(s for s in pool1
                    if s.template == "/api/v1/preserveservice/preserve")
    body = json.loads(preserve.body)
    assert isinstance(body["seatType"], int)
    assert isinstance(body["isWithin"], bool)
    assert body["date"] == "2025-01-01"
    assert len(body["accountId"]) == 36          # uuid format
    # integer path param instantiated as an int literal
    train = next(s for s in pool1
                 if s.template == "/api/v1/trainservice/trains/{trainId}")
    assert train.path.rsplit("/", 1)[-1].isdigit()


def test_spec_pool_routes_to_owning_services(spec):
    pool = endpoint_pool_from_spec(spec, seed=0)
    svc = {s.template: s.service for s in pool}
    assert svc["/api/v1/users/login"] == "ts-user-service"
    assert svc["/api/v1/travelservice/trips/left"] == "ts-travel-service"
    assert svc["/api/v1/orderservice/order/{orderId}"] == "ts-order-service"
    assert svc["/api/v1/stationservice/stations"] == "ts-station-service"


def test_openapi3_request_body_and_servers():
    doc = {
        "openapi": "3.0.1",
        "paths": {
            "/api/v1/foodservice/foods/{date}": {
                "get": {
                    "parameters": [
                        {"name": "date", "in": "path", "required": True,
                         "schema": {"type": "string", "format": "date"}}
                    ]
                },
                "post": {
                    "requestBody": {"content": {"application/json": {
                        "schema": {"$ref": "#/components/schemas/FoodOrder"}
                    }}}
                }
            }
        },
        "components": {"schemas": {"FoodOrder": {
            "type": "object",
            "properties": {"orderId": {"type": "string"},
                           "price": {"type": "number"}}
        }}},
    }
    eps = {(e.method): e for e in parse_spec(doc)}
    assert eps["GET"].path_params == (("date", "string"),)
    assert eps["POST"].body_schema["properties"]["price"]["type"] == "number"
    rng = np.random.default_rng(0)
    spec_req = instantiate(doc, eps["POST"], rng)
    assert isinstance(json.loads(spec_req.body)["price"], float)


def test_load_spec_rejects_lfs_stub(tmp_path):
    stub = tmp_path / "spec.json"
    stub.write_text("version https://git-lfs.github.com/spec/v1\n"
                    "oid sha256:abcd\nsize 42\n")
    with pytest.raises(ValueError, match="LFS pointer"):
        load_spec(stub)


def test_suite_from_spec_runs_against_gateway(spec):
    """The full round trip: spec -> suite (budget calibration intact) ->
    run_suite -> api records + caused traces, run-id join working."""
    from anomod.suite import generate_suite, run_suite, traces_for_run

    suite = generate_suite("TT", n_tests=21, seed=2, spec=spec)
    assert suite.n_tests == 21
    # round-robin covers the whole spec surface before sampling
    ops = {(t.spec.method, t.spec.template) for t in suite.tests[:7]}
    assert len(ops) == 7
    run = run_suite(suite, iterations=2, seed=0)
    assert run.api.n_records == 42
    assert run.pass_rate > 0.8              # healthy SUT, no chaos
    assert run.spans.n_spans > run.api.n_records        # caused traces
    joined = traces_for_run(run.spans, suite.run_id)
    assert len(joined) == 42                # every request's trace joins
    # spec-derived entry services appear in the caused spans
    names = set(np.array(run.spans.services)[
        np.unique(run.spans.service)].tolist())
    assert "ts-order-service" in names or "ts-travel-service" in names


def test_suite_spec_budget_calibration(spec):
    """budget -> n_tests stays on the reference calibration line with a
    spec-derived pool (600 s -> 256 TT tests)."""
    from anomod.suite import generate_suite

    suite = generate_suite("TT", budget_s=600.0, spec=spec)
    assert suite.n_tests == 256
    assert suite.covered_targets == 825
