"""De-saturated quality benchmark: effect-size sweeps + degradation curves.

The full-strength synthetic faults (6-20x latency, 0.5-0.7 error rates —
synth._fault_effects) are trivially detectable: every model and the z-score
baseline hit top-1 = 1.0, so the benchmark can neither rank the model zoo nor
catch regressions.  This harness evaluates along three difficulty axes
(synth.HardMode):

  - severity: fault effects interpolated toward baseline (0.05 ≈ 1.25x
    latency / 2.5% errors — the regime where detectors genuinely differ);
  - noise: wider baseline distributions (lower SNR);
  - confounders: decoy services that also degrade, which the ranking must
    not confuse with the labeled culprit.

Models train ONCE on a mixed-severity corpus (full + mid + low) and are then
evaluated at each sweep point on held-out seeds — degradation curves measure
robustness, not per-point refitting.  The z-score detector (anomod.detect)
runs as the training-free baseline.  No reference counterpart: the reference
ships fixed-intensity chaos (chaos-experiments/*.yaml); the sweep fills the
taxonomy's intensity axis for evaluation purposes.
"""

from __future__ import annotations

import dataclasses
import sys
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from anomod import detect, synth
from anomod.utils import platform
from anomod.rca import (_apply_model, _stack, build_dataset,
                        experiment_stream, init_params, make_model, rca_loss,
                        standardize_features, topk_eval)

#: The default sweep grid: full-strength down to the hard regime.
SEVERITIES = (1.0, 0.4, 0.2, 0.1, 0.05)

#: Diagnostic breadcrumb: set to a one-line note when the most recent sweep
#: lost its device backend mid-run and completed on the CPU failover path
#: (utils.platform.with_cpu_failover); the CLI copies it into the
#: provenance record so a mixed-backend table is labeled as such.
LAST_FAILOVER: Optional[str] = None

#: The de-saturated operating point used by the regression floor test and
#: docs/BENCHMARKS.md "hard regime" table: mild effects + decoys + noise.
HARD_POINT = dict(severity=0.12, noise=0.5, n_confounders=2)


#: Named distribution shifts for the train-shift/eval-shift table: models
#: train on the default effect model ("in-dist") and are evaluated under
#: each shifted generator (synth.HardMode's effect_shape / fault_profile /
#: fault_locus axes).
SHIFTS: Dict[str, Dict[str, str]] = {
    "in-dist": {},
    "additive": {"effect_shape": "add"},
    "tail-only": {"effect_shape": "tail"},
    "bursty": {"fault_profile": "bursty"},
    "partial-window": {"fault_profile": "partial"},
    "edge-locus": {"fault_locus": "edge"},
}


@dataclasses.dataclass
class QualityPoint:
    model: str
    severity: float
    noise: float
    n_confounders: int
    top1: float
    top3: float
    detection_auc: float
    n_eval: int
    shift: str = "in-dist"


def _repad_edges(stacked: Dict[str, np.ndarray], e_max: int) -> None:
    cur = stacked["edge_src"].shape[1]
    if cur < e_max:
        pad = ((0, 0), (0, e_max - cur))
        for k in ("edge_src", "edge_dst"):
            stacked[k] = np.pad(stacked[k], pad)
        stacked["edge_mask"] = np.pad(stacked["edge_mask"], pad)
        if "edge_x" in stacked:
            stacked["edge_x"] = np.pad(
                stacked["edge_x"], pad + ((0, 0), (0, 0)))


def _train_model(model_name: str, train: Dict[str, np.ndarray],
                 epochs: int = 150, lr: float = 3e-3):
    import jax
    import jax.numpy as jnp
    import optax

    model = make_model(model_name)
    rng = jax.random.PRNGKey(0)
    sample0 = {k: v[0] for k, v in train.items()}
    params = init_params(model_name, model, sample0, rng)
    tx = optax.adamw(lr, weight_decay=1e-4)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(
            lambda p, b: rca_loss(_apply_model(model_name, model, p, b), b)
        )(params, batch)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    batch = {k: jnp.asarray(v) for k, v in train.items()}
    for _ in range(epochs):
        params, opt_state, _ = step(params, opt_state, batch)
    return model, params


def _zscore_eval(testbed: str, seeds: Sequence[int],
                 hard: "synth.HardMode", n_confounders: int,
                 n_traces: int) -> Tuple[float, float, float, int]:
    """Training-free z-score detector over hard corpora (per-seed corpus
    evaluation via detect.evaluate_corpus, averaged).

    The experiments come from rca.experiment_stream — the SAME builder,
    arguments, and seeds the learned-model eval consumes through
    build_dataset — so every quality-table cell scores identical bundles
    (regenerating is cheap: generation is ~1% of sweep wall time, which
    training dominates).  The detection statistic is a rank-based AUC over
    experiment scores, same definition as rca.topk_eval, so the column is
    comparable across zscore and learned models.
    """
    top1s, top3s, aucs, n = [], [], [], 0
    for seed in seeds:
        exps = [exp for _, exp in experiment_stream(
            testbed, seed, n_traces=n_traces, hard=hard,
            n_confounders=n_confounders)]
        s = detect.evaluate_corpus(exps)
        top1s.append(s.top1)
        top3s.append(s.top3)
        pos = np.array([r.score for r in s.results if r.is_anomaly_true])
        neg = np.array([r.score for r in s.results if not r.is_anomaly_true])
        aucs.append(float((pos[:, None] > neg[None, :]).mean())
                    if len(pos) and len(neg) else 1.0)
        n += s.n_rca_cases
    return (float(np.mean(top1s)), float(np.mean(top3s)),
            float(np.mean(aucs)), n)


def _stream_eval(testbed: str, seeds: Sequence[int],
                 hard: "synth.HardMode", n_confounders: int,
                 n_traces: int) -> Tuple[float, float, float, int]:
    """Training-free multimodal STREAMING detector over the same corpora.

    Same contract as :func:`_zscore_eval` (identical bundles via
    rca.experiment_stream, rank-based AUC over per-experiment detection
    scores) so `stream` sits in the quality table cell-for-cell with the
    offline rows.  Note the sweep's corpora are much sparser than live
    traffic (n_traces=60 vs the streaming benchmark's 400) — this row
    measures the detector under the OFFLINE sweep's density, its hardest
    setting.
    """
    from anomod.stream import stream_experiment_multimodal
    top1s, top3s, aucs, n = [], [], [], 0
    for seed in seeds:
        hits1 = hits3 = cases = 0
        pos, neg = [], []
        for label, exp in experiment_stream(
                testbed, seed, n_traces=n_traces, hard=hard,
                n_confounders=n_confounders):
            det = stream_experiment_multimodal(exp)
            score = max((a.score for a in det.alerts), default=0.0)
            (pos if label.is_anomaly else neg).append(score)
            if label.is_anomaly and label.target_service:
                ranked = det.ranked_services()
                hits1 += bool(ranked) and ranked[0] == label.target_service
                hits3 += label.target_service in ranked[:3]
                cases += 1
        top1s.append(hits1 / cases if cases else 0.0)
        top3s.append(hits3 / cases if cases else 0.0)
        p, q = np.asarray(pos), np.asarray(neg)
        aucs.append(float((p[:, None] > q[None, :]).mean())
                    if len(p) and len(q) else 1.0)
        n += cases
    return (float(np.mean(top1s)), float(np.mean(top3s)),
            float(np.mean(aucs)), n)


def severity_sweep(testbed: str = "TT",
                   model_names: Sequence[str] = ("zscore", "gcn", "gat",
                                                 "sage", "temporal", "lru",
                                                 "transformer", "moe"),
                   severities: Sequence[float] = SEVERITIES,
                   train_seeds: Sequence[int] = range(6),
                   eval_seeds: Sequence[int] = range(100, 103),
                   n_traces: int = 60, epochs: int = 120,
                   noise: float = 0.5, n_confounders: int = 2,
                   verbose: bool = False) -> List[QualityPoint]:
    """Degradation curves: train once on mixed severity, eval per point.

    Every eval point uses noise + confounders (the hard axes are on by
    default); severity is the swept axis.  Returns one QualityPoint per
    (model, severity).
    """
    eval_modes = {sev: synth.HardMode(severity=sev, noise=noise)
                  for sev in severities}
    cells = _eval_grid(testbed, model_names, eval_modes, train_seeds,
                       eval_seeds, n_traces, epochs, noise, n_confounders,
                       verbose)
    return [QualityPoint(name, sev, noise, n_confounders, *cell)
            for (name, sev), cell in cells.items()]


def shift_sweep(testbed: str = "TT",
                model_names: Sequence[str] = ("zscore", "gcn", "gat",
                                              "sage", "temporal", "lru",
                                              "transformer", "moe"),
                shifts: Sequence[str] = tuple(SHIFTS),
                severity: float = 0.3,
                train_seeds: Sequence[int] = range(6),
                eval_seeds: Sequence[int] = range(100, 103),
                n_traces: int = 60, epochs: int = 120,
                noise: float = 0.5, n_confounders: int = 2,
                verbose: bool = False,
                edge_aware: bool = False) -> List[QualityPoint]:
    """Train-shift/eval-shift table (round-2 weak #4): models train ONCE on
    the default effect model (the same mixed-severity corpus as
    severity_sweep) and are evaluated under each shifted generator in
    :data:`SHIFTS` at one fixed severity.  A ranking that only holds
    in-distribution is a statement about the generator; this sweep shows
    which model ordering survives effect-shape, fault-timing, and
    fault-locus shift.

    ``edge_aware``: opt-in variant — out-edge feature blocks plus a
    node+edge mixed-locus training corpus, the supervised counterpart of
    the streaming out-edge plane.  The canonical table keeps node
    features and node-locus training (the honest shift premise); this
    variant answers "CAN the models attribute link faults when given the
    evidence channel and training exposure"."""
    eval_modes = {name: synth.HardMode(severity=severity, noise=noise,
                                       **SHIFTS[name])
                  for name in shifts}
    cells = _eval_grid(testbed, model_names, eval_modes, train_seeds,
                       eval_seeds, n_traces, epochs, noise, n_confounders,
                       verbose, edge_features=edge_aware,
                       train_loci=("node", "edge") if edge_aware
                       else ("node",))
    return [QualityPoint(name, severity, noise, n_confounders, *cell,
                         shift=shift)
            for (name, shift), cell in cells.items()]


def _eval_grid(testbed, model_names, eval_modes: Dict[object, "synth.HardMode"],
               train_seeds, eval_seeds, n_traces, epochs, noise,
               n_confounders, verbose=False, edge_features=False,
               train_loci=("node",)):
    """Shared sweep engine: one unshifted mixed-severity training pass,
    then every model evaluated on every eval-mode corpus.  Returns
    {(model, mode_key): (top1, top3, auc, n_eval)}; corpora per cell are
    identical across models (rca.experiment_stream via build_dataset).

    ``edge_features`` / ``train_loci`` configure the EDGE-AWARE variant:
    out-edge feature blocks plus a training mixture that includes
    edge-locus corpora — without both, link-fault attribution is
    architecturally outside the models' evidence (training on node
    faults alone leaves the out-edge channel with nothing to learn
    from).  The canonical tables keep the defaults."""
    # zscore and stream are training-free rows — only the learned models
    # need the mixed-severity training corpus and eval batches
    needs_training = any(name not in ("zscore", "stream")
                         for name in model_names)
    train = None
    if needs_training:
        # mixed-severity training corpus: full + mid + low thirds of the seeds
        thirds = np.array_split(np.asarray(list(train_seeds)), 3)
        train_parts = []
        for sev, part in zip((1.0, 0.4, 0.15), thirds):
            if len(part) == 0:
                continue
            for locus in train_loci:
                samples, services = build_dataset(
                    testbed, [int(s) for s in part], n_traces=n_traces,
                    hard=synth.HardMode(severity=sev, noise=noise,
                                        fault_locus=locus),
                    n_confounders=n_confounders,
                    edge_features=edge_features)
                train_parts.append(_stack(samples))
        e_max = max(p["edge_src"].shape[1] for p in train_parts)
        for p in train_parts:
            _repad_edges(p, e_max)
        train = {k: np.concatenate([p[k] for p in train_parts])
                 for k in train_parts[0]}

        # eval batches per mode (held-out seeds; the zscore path regenerates
        # the identical corpora via experiment_stream, so nothing here is
        # needed for a zscore-only sweep)
        eval_batches: Dict[object, Dict[str, np.ndarray]] = {}
        for key, mode in eval_modes.items():
            samples, _ = build_dataset(testbed, eval_seeds, n_traces=n_traces,
                                       hard=mode, n_confounders=n_confounders,
                                       edge_features=edge_features)
            ev = _stack(samples)
            e_max = max(e_max, ev["edge_src"].shape[1])
            eval_batches[key] = ev
        _repad_edges(train, e_max)
        for ev in eval_batches.values():
            _repad_edges(ev, e_max)
        standardize_features(train, list(eval_batches.values()))

    def _train_and_eval(name):
        """One model's train + full eval row (host-input → host-output, so a
        backend failover can redo it wholesale: corpora and finished cells
        live in numpy, only params/compiled fns die with the device)."""
        import jax.numpy as jnp
        row = {}
        model, params = _train_model(name, train, epochs=epochs)
        for key in eval_modes:
            ev = eval_batches[key]
            scores = np.asarray(_apply_model(
                name, model, params,
                {k: jnp.asarray(v) for k, v in ev.items()}))
            row[(name, key)] = topk_eval(scores, ev)
        return row

    global LAST_FAILOVER
    LAST_FAILOVER = None

    def _note_failover(exc, _model=None):
        global LAST_FAILOVER
        LAST_FAILOVER = (f"device backend lost mid-sweep at model "
                         f"{_model!r} ({type(exc).__name__}); remaining "
                         f"rows completed on the CPU failover backend")
        print(f"[anomod.quality] {LAST_FAILOVER}", file=sys.stderr)

    cells: Dict[Tuple[str, object], Tuple[float, float, float, int]] = {}
    for name in model_names:
        if name in ("zscore", "stream"):
            ev_fn = _zscore_eval if name == "zscore" else _stream_eval
            for key, mode in eval_modes.items():
                cells[(name, key)] = ev_fn(
                    testbed, eval_seeds, mode, n_confounders, n_traces)
                if verbose:
                    print(f"{name} {key}: top1={cells[(name, key)][0]:.2f}")
            continue
        row = platform.with_cpu_failover(
            lambda: _train_and_eval(name),
            on_failover=lambda e, _m=name: _note_failover(e, _m))
        cells.update(row)
        if verbose:
            for (n, key), cell in row.items():
                print(f"{n} {key}: top1={cell[0]:.2f}")
    return cells


def render_shift_markdown(points: Sequence[QualityPoint]) -> str:
    """Train-shift/eval-shift table: one row per model, one top1 column per
    shifted generator (training is always in-distribution)."""
    shifts = list(dict.fromkeys(p.shift for p in points))
    models: Dict[str, Dict[str, QualityPoint]] = {}
    for p in points:
        models.setdefault(p.model, {})[p.shift] = p
    head = "| model | " + " | ".join(f"top1 {s}" for s in shifts) + " |"
    rows = [head, "|" + "---|" * (1 + len(shifts))]
    for name, by_shift in models.items():
        cells = " | ".join(f"{by_shift[s].top1:.2f}" if s in by_shift else "-"
                           for s in shifts)
        rows.append(f"| {name} | {cells} |")
    return "\n".join(rows)


def render_markdown(points: Sequence[QualityPoint]) -> str:
    """Degradation-curve table: one row per model, one column per severity."""
    severities = sorted({p.severity for p in points}, reverse=True)
    models: Dict[str, Dict[float, QualityPoint]] = {}
    for p in points:
        models.setdefault(p.model, {})[p.severity] = p
    head = "| model | " + " | ".join(f"top1@{s:g}" for s in severities) + \
        " | " + " | ".join(f"top3@{s:g}" for s in severities) + " |"
    sep = "|" + "---|" * (1 + 2 * len(severities))
    rows = [head, sep]
    for name, by_sev in models.items():
        t1 = " | ".join(f"{by_sev[s].top1:.2f}" if s in by_sev else "-"
                        for s in severities)
        t3 = " | ".join(f"{by_sev[s].top3:.2f}" if s in by_sev else "-"
                        for s in severities)
        rows.append(f"| {name} | {t1} | {t3} |")
    return "\n".join(rows)
