"""Workload-suite helpers — analogs of the EvoMaster test utilities and the
wrk2 mixed-workload request mix.

- ``resolve_location``: merge a ``Location`` response header against a URI
  template, the behavior of the reference's generated-suite helper
  (BlackBox_tests/Final_version_2m/em_test_utils.py:4-26) re-implemented
  fresh on urllib.
- ``is_valid_uri_or_empty``: permissive URI syntax check
  (em_test_utils.py:27-46 uses rfc3986; this uses urllib splitting).
- ``SN_REQUEST_MIX``: the wrk2 workload distribution
  (mixed-workload.lua:113-115 — 60% home-timeline read, 30% user-timeline
  read, 10% compose), used by the synthetic generator's SN template
  weighting.
"""

from __future__ import annotations

from urllib.parse import urlparse, urlunparse

# mixed-workload.lua:113-115
SN_REQUEST_MIX = {
    "home-timeline-service": 0.60,
    "user-timeline-service": 0.30,
    "compose-post-service": 0.10,
}


def resolve_location(location_header: str, expected_template: str) -> str:
    """Resolve a Location header against the URI template of the followed-up
    endpoint: absolute locations win; relative ones adopt the template's
    scheme/authority; an empty location falls back to the template."""
    if not location_header:
        return expected_template
    loc = urlparse(location_header)
    if loc.scheme and loc.netloc:
        return location_header
    tpl = urlparse(expected_template)
    path = location_header if location_header.startswith("/") else \
        "/" + location_header
    return urlunparse((tpl.scheme, tpl.netloc, path, "", loc.query, ""))


def is_valid_uri_or_empty(uri: str) -> bool:
    """True for "" or a syntactically plausible absolute/relative URI."""
    if uri == "":
        return True
    try:
        parsed = urlparse(uri)
    except ValueError:
        return False
    if parsed.scheme and not parsed.netloc and not parsed.path:
        return False
    # reject whitespace and control characters anywhere
    return not any(c.isspace() or ord(c) < 32 for c in uri)
