"""Workload-suite helpers — analogs of the EvoMaster test utilities and the
wrk2 mixed-workload content model.

- ``resolve_location``: merge a ``Location`` response header against a URI
  template, the behavior of the reference's generated-suite helper
  (BlackBox_tests/Final_version_2m/em_test_utils.py:4-26) re-implemented
  fresh on urllib.
- ``is_valid_uri_or_empty``: permissive URI syntax check
  (em_test_utils.py:27-46 uses rfc3986; this uses urllib splitting).
- ``SN_REQUEST_MIX``: the wrk2 workload distribution
  (mixed-workload.lua:113-115 — 60% home-timeline read, 30% user-timeline
  read, 10% compose), used by the synthetic generator's SN template
  weighting.
- wrk2 *content model* (``compose_post_body``, ``timeline_query``,
  ``sample_wrk2_request``): the reference's request-body synthesis
  (mixed-workload.lua:33-108) as deterministic numpy-seeded draws, so
  generated ``api_responses.jsonl`` artifacts carry the same
  method/content-length distributions as real wrk2 traffic.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np
from urllib.parse import urlparse, urlunparse

# mixed-workload.lua:113-115
SN_REQUEST_MIX = {
    "home-timeline-service": 0.60,
    "user-timeline-service": 0.30,
    "compose-post-service": 0.10,
}

# ---------------------------------------------------------------------------
# wrk2 content-model parameters (mixed-workload.lua).  Lua's `for i = 0, n`
# loop body runs n+1 times, so the drawn `math.random(0, 5)` mention/url
# counts yield 1..6 appended items (and media 1..5) — the model reproduces
# that off-by-one because it is what the real workload sends.
# ---------------------------------------------------------------------------
WRK2_CHARSET = ("qwertyuiopasdfghjklzxcvbnm"
                "QWERTYUIOPASDFGHJKLZXCVBNM1234567890")   # :7-10
WRK2_MAX_USER_INDEX = 962       # :15 (env default)
WRK2_TEXT_LEN = 256             # :37 stringRandom(256)
WRK2_MENTION_RANGE = (1, 6)     # :38 math.random(0,5), loop 0..n
WRK2_URL_RANGE = (1, 6)         # :39
WRK2_MEDIA_RANGE = (1, 5)       # :40 math.random(0,4), loop 0..n
WRK2_URL_LEN = 64               # :56 " http://" .. stringRandom(64)
WRK2_MEDIA_ID_LEN = 18          # :60 decRandom(18)
WRK2_TIMELINE_STOP_OFFSET = 10  # :86-88 stop = start + 10
WRK2_TIMELINE_START_MAX = 100   # :85 math.random(0, 100)

_MENTION_PREFIX = " @username_"  # :52
_URL_PREFIX = " http://"         # :56

# Byte-length decomposition shared by the analytic bounds and the vectorized
# sampler (kept in one place so they can't drift from compose_post_body).
_FORM_OVERHEAD = len("username=username_&user_id=&text=&media_ids="
                     "&media_types=&post_type=0")
_PNG_LEN = len('"png"')


def _media_lists_len(k):
    """len(media_ids) + len(media_types) for ``k`` media entries: each is
    '[' + k quoted items + (k-1) commas + ']'.  Works elementwise on numpy
    arrays."""
    return (2 + k * (WRK2_MEDIA_ID_LEN + 2) + (k - 1)) \
        + (2 + k * _PNG_LEN + (k - 1))


def _text_len(m, mention_digits, u):
    """len(text): base + mentions + urls; elementwise-safe."""
    return (WRK2_TEXT_LEN
            + m * len(_MENTION_PREFIX) + mention_digits
            + u * (len(_URL_PREFIX) + WRK2_URL_LEN))


def _rand_string(rng: np.random.Generator, length: int,
                 charset: str = WRK2_CHARSET) -> str:
    return "".join(charset[i] for i in
                   rng.integers(0, len(charset), length))


def compose_post_body(rng: np.random.Generator) -> str:
    """One compose-post form body with the reference's exact content model
    (mixed-workload.lua:33-83): 256-char base text, 1-6 ``@username_<id>``
    mentions (never self), 1-6 64-char urls, 1-5 18-digit media ids typed
    ``png``, form-urlencoded field layout with the JSON-ish bracket lists."""
    user_index = int(rng.integers(0, WRK2_MAX_USER_INDEX))
    text = _rand_string(rng, WRK2_TEXT_LEN)
    n_mentions = int(rng.integers(WRK2_MENTION_RANGE[0],
                                  WRK2_MENTION_RANGE[1] + 1))
    n_urls = int(rng.integers(WRK2_URL_RANGE[0], WRK2_URL_RANGE[1] + 1))
    n_media = int(rng.integers(WRK2_MEDIA_RANGE[0], WRK2_MEDIA_RANGE[1] + 1))
    for _ in range(n_mentions):
        while True:
            mention = int(rng.integers(0, WRK2_MAX_USER_INDEX))
            if mention != user_index:
                break
        text += f"{_MENTION_PREFIX}{mention}"
    for _ in range(n_urls):
        text += _URL_PREFIX + _rand_string(rng, WRK2_URL_LEN)
    media_ids = "[" + ",".join(
        '"' + _rand_string(rng, WRK2_MEDIA_ID_LEN, "1234567890") + '"'
        for _ in range(n_media)) + "]"
    media_types = "[" + ",".join('"png"' for _ in range(n_media)) + "]"
    return (f"username=username_{user_index}&user_id={user_index}"
            f"&text={text}&media_ids={media_ids}"
            f"&media_types={media_types}&post_type=0")


def timeline_query(rng: np.random.Generator) -> str:
    """Timeline-read query args (mixed-workload.lua:84-108):
    ``user_id`` uniform over the seeded graph, ``stop = start + 10``."""
    user_id = int(rng.integers(0, WRK2_MAX_USER_INDEX))
    start = int(rng.integers(0, WRK2_TIMELINE_START_MAX + 1))
    return f"user_id={user_id}&start={start}&stop={start + WRK2_TIMELINE_STOP_OFFSET}"


@dataclasses.dataclass(frozen=True)
class WorkloadRequest:
    """One synthesized wrk2 request (wrk.format analog)."""
    method: str
    path: str        # path + query, gateway-relative
    template: str    # canonical endpoint path
    body: Optional[str] = None

    @property
    def content_length(self) -> int:
        return len(self.body) if self.body is not None else 0


def sample_wrk2_request(rng: np.random.Generator) -> WorkloadRequest:
    """Draw one request from the 60/30/10 mix with full content synthesis
    (mixed-workload.lua:111-125)."""
    coin = float(rng.random())
    if coin < SN_REQUEST_MIX["home-timeline-service"]:
        tpl = "/wrk2-api/home-timeline/read"
        return WorkloadRequest("GET", f"{tpl}?{timeline_query(rng)}", tpl)
    if coin < (SN_REQUEST_MIX["home-timeline-service"]
               + SN_REQUEST_MIX["user-timeline-service"]):
        tpl = "/wrk2-api/user-timeline/read"
        return WorkloadRequest("GET", f"{tpl}?{timeline_query(rng)}", tpl)
    tpl = "/wrk2-api/post/compose"
    return WorkloadRequest("POST", tpl, tpl, body=compose_post_body(rng))


def compose_length_bounds() -> Tuple[int, int]:
    """Analytic (min, max) compose-body length implied by the lua
    parameters — used by tests and the synthetic generator to validate
    sampled content-length histograms."""
    def total(idx_d: int, m: int, mention_d: int, u: int, k: int) -> int:
        return (_FORM_OVERHEAD + 2 * idx_d
                + _text_len(m, m * mention_d, u) + _media_lists_len(k))

    lo = total(1, WRK2_MENTION_RANGE[0], 1, WRK2_URL_RANGE[0],
               WRK2_MEDIA_RANGE[0])
    hi = total(3, WRK2_MENTION_RANGE[1], 3, WRK2_URL_RANGE[1],
               WRK2_MEDIA_RANGE[1])
    return lo, hi


def sample_compose_lengths(rng: np.random.Generator, n: int) -> np.ndarray:
    """Vectorized draw of ``n`` compose content-lengths from the analytic
    length decomposition (same distribution as ``len(compose_post_body)``
    without string materialization — used for bulk synthesis)."""
    idx = rng.integers(0, WRK2_MAX_USER_INDEX, n)
    idx_d = np.char.str_len(idx.astype(str))
    m = rng.integers(WRK2_MENTION_RANGE[0], WRK2_MENTION_RANGE[1] + 1, n)
    # per-mention id digit counts: draw all at max fan-out and mask
    mention_ids = rng.integers(0, WRK2_MAX_USER_INDEX,
                               (n, WRK2_MENTION_RANGE[1]))
    mention_d = np.char.str_len(mention_ids.astype(str))
    mask = np.arange(WRK2_MENTION_RANGE[1])[None, :] < m[:, None]
    mention_digits = (mention_d * mask).sum(axis=1)
    u = rng.integers(WRK2_URL_RANGE[0], WRK2_URL_RANGE[1] + 1, n)
    k = rng.integers(WRK2_MEDIA_RANGE[0], WRK2_MEDIA_RANGE[1] + 1, n)
    return (_FORM_OVERHEAD + 2 * idx_d
            + _text_len(m, mention_digits, u)
            + _media_lists_len(k)).astype(np.int32)


def resolve_location(location_header: str, expected_template: str) -> str:
    """Resolve a Location header against the URI template of the followed-up
    endpoint: absolute locations win; relative ones adopt the template's
    scheme/authority; an empty location falls back to the template."""
    if not location_header:
        return expected_template
    loc = urlparse(location_header)
    if loc.scheme and loc.netloc:
        return location_header
    tpl = urlparse(expected_template)
    path = location_header if location_header.startswith("/") else \
        "/" + location_header
    return urlunparse((tpl.scheme, tpl.netloc, path, "", loc.query, ""))


def is_valid_uri_or_empty(uri: str) -> bool:
    """True for "" or a syntactically plausible absolute/relative URI."""
    if uri == "":
        return True
    try:
        parsed = urlparse(uri)
    except ValueError:
        return False
    if parsed.scheme and not parsed.netloc and not parsed.path:
        return False
    # reject whitespace and control characters anywhere
    return not any(c.isspace() or ord(c) < 32 for c in uri)
