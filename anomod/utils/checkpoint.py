"""Checkpoint / resume for RCA training and replay state (orbax).

The reference has no checkpointing — each experiment is run-to-completion and
the archive folder is the only persisted state (SURVEY.md §5).  Training a
GNN RCA model is iterative, so this framework adds real checkpoint/resume:
params + opt_state + step counter via orbax-checkpoint, with a numpy
fallback writer for environments without orbax.
"""

from __future__ import annotations

import json
import pickle
from pathlib import Path
from typing import Any, Optional, Tuple


def _try_orbax():
    try:
        import orbax.checkpoint as ocp
        return ocp
    except ImportError:
        return None


def save_train_state(path: Path, params: Any, opt_state: Any,
                     step: int, meta: Optional[dict] = None) -> str:
    """Persist a training state; returns the backend used ("orbax"/"pickle")."""
    import jax
    path = Path(path)
    path.mkdir(parents=True, exist_ok=True)
    host = jax.tree_util.tree_map(lambda x: jax.device_get(x), (params, opt_state))
    ocp = _try_orbax()
    (path / "meta.json").write_text(json.dumps(
        {"step": step, **(meta or {})}))
    if ocp is not None:
        ckptr = ocp.PyTreeCheckpointer()
        target = (path / "state.orbax").resolve()
        if target.exists():
            import shutil
            shutil.rmtree(target)
        ckptr.save(target, host)
        return "orbax"
    with open(path / "state.pkl", "wb") as f:
        pickle.dump(host, f)
    return "pickle"


def restore_train_state(path: Path) -> Tuple[Any, Any, int, dict]:
    """Restore (params, opt_state, step, meta)."""
    path = Path(path)
    meta = json.loads((path / "meta.json").read_text())
    step = int(meta.pop("step", 0))
    ocp = _try_orbax()
    orbax_dir = path / "state.orbax"
    if ocp is not None and orbax_dir.exists():
        ckptr = ocp.PyTreeCheckpointer()
        params, opt_state = ckptr.restore(orbax_dir.resolve())
        return params, opt_state, step, meta
    with open(path / "state.pkl", "rb") as f:
        params, opt_state = pickle.load(f)
    return params, opt_state, step, meta
