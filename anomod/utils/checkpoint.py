"""Checkpoint / resume for RCA training and replay state (orbax).

The reference has no checkpointing — each experiment is run-to-completion and
the archive folder is the only persisted state (SURVEY.md §5).  Training a
GNN RCA model is iterative, so this framework adds real checkpoint/resume:
params + opt_state + step counter via orbax-checkpoint (arrays) with the
pytree structure pickled alongside (optax states are namedtuples, which a
bare orbax restore would flatten into lists/dicts), plus a pure-pickle
fallback for environments without orbax.

Crash-safety contract: each save writes the full state into a fresh
``v<step>`` version directory FIRST, then atomically publishes it by
``os.replace``-ing ``meta.json`` (whose ``version`` field names the live
directory), then garbage-collects older versions.  A kill at any point
leaves ``meta.json`` referencing a complete state — the previous one if the
new version wasn't published yet — so a checkpointed run is always
resumable.  Restore also accepts the legacy flat layout (state files next
to meta.json) for checkpoints written before versioning.
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
from pathlib import Path
from typing import Any, Optional, Tuple


def _try_orbax():
    try:
        import orbax.checkpoint as ocp
        return ocp
    except ImportError:
        return None


def _write_state(state_dir: Path, host) -> str:
    """Write (params, opt_state) into state_dir; returns backend name."""
    import jax
    state_dir.mkdir(parents=True, exist_ok=True)
    ocp = _try_orbax()
    if ocp is not None:
        leaves, treedef = jax.tree_util.tree_flatten(host)
        target = (state_dir / "state.orbax").resolve()
        if target.exists():        # same-step re-save of an unpublished dir
            shutil.rmtree(target)
        ocp.PyTreeCheckpointer().save(target, leaves)
        with open(state_dir / "treedef.pkl", "wb") as f:
            pickle.dump(treedef, f)
        return "orbax"
    with open(state_dir / "state.pkl", "wb") as f:
        pickle.dump(host, f)
    return "pickle"


def save_train_state(path: Path, params: Any, opt_state: Any,
                     step: int, meta: Optional[dict] = None) -> str:
    """Persist a training state; returns the backend used ("orbax"/"pickle").

    Writes ``path/v<step>/`` first, publishes it by atomically replacing
    ``path/meta.json``, then removes superseded version dirs — see the
    module docstring's crash-safety contract."""
    import jax
    path = Path(path)
    path.mkdir(parents=True, exist_ok=True)
    host = jax.tree_util.tree_map(jax.device_get, (params, opt_state))
    version = f"v{step}"
    backend = _write_state(path / version, host)
    # publish: meta written to a temp file then atomically moved into place;
    # caller meta must not clobber the step/version keys
    tmp = path / "meta.json.tmp"
    tmp.write_text(json.dumps({**(meta or {}),
                               "step": step, "version": version}))
    os.replace(tmp, path / "meta.json")
    # GC superseded versions (and any legacy flat state files)
    for old in path.glob("v*"):
        if old.name != version and old.is_dir():
            shutil.rmtree(old, ignore_errors=True)
    for legacy in ("state.orbax", "state.pkl", "treedef.pkl"):
        p = path / legacy
        if p.is_dir():
            shutil.rmtree(p, ignore_errors=True)
        elif p.exists():
            p.unlink()
    return backend


def restore_train_state(path: Path) -> Tuple[Any, Any, int, dict]:
    """Restore (params, opt_state, step, meta) with original pytree structure."""
    import jax
    path = Path(path)
    meta = json.loads((path / "meta.json").read_text())
    step = int(meta.pop("step", 0))
    state_dir = path / meta.pop("version") if "version" in meta else path
    orbax_dir = state_dir / "state.orbax"
    if orbax_dir.exists():
        ocp = _try_orbax()
        if ocp is None:
            raise RuntimeError(
                f"{path} was written with orbax-checkpoint, which is not "
                "importable here — install orbax-checkpoint or restore on a "
                "machine that has it.")
        leaves = ocp.PyTreeCheckpointer().restore(orbax_dir.resolve())
        with open(state_dir / "treedef.pkl", "rb") as f:
            treedef = pickle.load(f)
        params, opt_state = jax.tree_util.tree_unflatten(treedef, leaves)
        return params, opt_state, step, meta
    with open(state_dir / "state.pkl", "rb") as f:
        params, opt_state = pickle.load(f)
    return params, opt_state, step, meta


def checkpoint_mtime(path) -> Optional[float]:
    """Publish time (meta.json mtime) of the live checkpoint, or None.

    meta.json is atomically replaced as the LAST step of every save, so its
    mtime is the moment the checkpoint became live — callers use it to tell
    "saved by this run" from "stale leftover of an earlier run" (the CLI's
    failover retry must not resume a pre-existing checkpoint)."""
    path = Path(path)
    if not has_checkpoint(path):
        return None
    try:
        return (path / "meta.json").stat().st_mtime
    except OSError:
        return None


def has_checkpoint(path) -> bool:
    """True when a published AND restorable checkpoint exists at ``path``.

    A meta.json alone is not enough: a legacy (pre-versioning) save killed
    between its meta and state writes leaves a torn checkpoint, and an
    always-pass-resume job must start fresh on it rather than crash in
    restore."""
    path = Path(path)
    meta_file = path / "meta.json"
    if not meta_file.exists():
        return False
    try:
        meta = json.loads(meta_file.read_text())
    except (OSError, ValueError):
        return False
    state_dir = path / meta["version"] if "version" in meta else path
    # an orbax state needs its treedef companion to be restorable
    return ((state_dir / "state.orbax").exists()
            and (state_dir / "treedef.pkl").exists()) \
        or (state_dir / "state.pkl").exists()
