"""Checkpoint / resume for RCA training and replay state (orbax).

The reference has no checkpointing — each experiment is run-to-completion and
the archive folder is the only persisted state (SURVEY.md §5).  Training a
GNN RCA model is iterative, so this framework adds real checkpoint/resume:
params + opt_state + step counter via orbax-checkpoint (arrays) with the
pytree structure pickled alongside (optax states are namedtuples, which a
bare orbax restore would flatten into lists/dicts), plus a pure-pickle
fallback for environments without orbax.
"""

from __future__ import annotations

import json
import pickle
from pathlib import Path
from typing import Any, Optional, Tuple


def _try_orbax():
    try:
        import orbax.checkpoint as ocp
        return ocp
    except ImportError:
        return None


def save_train_state(path: Path, params: Any, opt_state: Any,
                     step: int, meta: Optional[dict] = None) -> str:
    """Persist a training state; returns the backend used ("orbax"/"pickle")."""
    import jax
    path = Path(path)
    path.mkdir(parents=True, exist_ok=True)
    host = jax.tree_util.tree_map(jax.device_get, (params, opt_state))
    # caller meta must not clobber the step counter
    (path / "meta.json").write_text(json.dumps({**(meta or {}), "step": step}))
    ocp = _try_orbax()
    if ocp is not None:
        leaves, treedef = jax.tree_util.tree_flatten(host)
        target = (path / "state.orbax").resolve()
        if target.exists():
            import shutil
            shutil.rmtree(target)
        ocp.PyTreeCheckpointer().save(target, leaves)
        with open(path / "treedef.pkl", "wb") as f:
            pickle.dump(treedef, f)
        return "orbax"
    with open(path / "state.pkl", "wb") as f:
        pickle.dump(host, f)
    return "pickle"


def restore_train_state(path: Path) -> Tuple[Any, Any, int, dict]:
    """Restore (params, opt_state, step, meta) with original pytree structure."""
    import jax
    path = Path(path)
    meta = json.loads((path / "meta.json").read_text())
    step = int(meta.pop("step", 0))
    orbax_dir = path / "state.orbax"
    if orbax_dir.exists():
        ocp = _try_orbax()
        if ocp is None:
            raise RuntimeError(
                f"{path} was written with orbax-checkpoint, which is not "
                "importable here — install orbax-checkpoint or restore on a "
                "machine that has it.")
        leaves = ocp.PyTreeCheckpointer().restore(orbax_dir.resolve())
        with open(path / "treedef.pkl", "rb") as f:
            treedef = pickle.load(f)
        params, opt_state = jax.tree_util.tree_unflatten(treedef, leaves)
        return params, opt_state, step, meta
    with open(path / "state.pkl", "rb") as f:
        params, opt_state = pickle.load(f)
    return params, opt_state, step, meta
