"""Utilities: checkpointing, tracing/profiling, run reports."""

from anomod.utils.checkpoint import restore_train_state, save_train_state
from anomod.utils.tracing import Tracer, profile_to

__all__ = ["save_train_state", "restore_train_state", "Tracer", "profile_to"]
