"""Single home for the "pin JAX to virtual CPU devices" workaround.

The container's axon sitecustomize force-registers the TPU platform at
interpreter start, so ``JAX_PLATFORMS=cpu`` in the environment alone does not
stick — and with a dead tunnel the first backend-touching call (anything via
``jax.devices()``) hangs forever.  The reliable recipe, used by the test
suite, the driver dry run, and the benchmark fallback alike:

  1. put ``--xla_force_host_platform_device_count=N`` into ``XLA_FLAGS``
     (covers subprocesses that initialize on import),
  2. pin ``jax_platforms=cpu`` + ``jax_num_cpu_devices`` via ``jax.config``
     *before* backend init in this process,
  3. ``clear_backends()`` first so a previously-initialized process can be
     repointed (no-op when nothing is initialized yet).
"""

from __future__ import annotations

import os


def probe_device_platform(attempts=None):
    """Out-of-process device-backend probe with a hard deadline.

    Returns ``(platform, diagnostic)`` where ``platform`` is the backend's
    ``jax.devices()[0].platform`` string ("tpu", "cpu", ...) or "" when no
    backend initializes within the deadline.  Probing in a subprocess is
    mandatory here: with a dead axon tunnel the first in-process
    device-touching call hangs forever, so the caller (bench.py, the TPU
    test suite's collection gate) must learn the backend state without
    touching it.
    """
    import subprocess
    import sys

    attempts = attempts or (75.0, 30.0)
    last = ""
    for t in attempts:
        try:
            r = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(jax.devices()[0].platform)"],
                timeout=t, capture_output=True)
            if r.returncode == 0:
                return r.stdout.decode(errors="replace").strip(), "probe ok"
            last = (r.stderr or b"").decode(errors="replace").strip()[-300:]
        except subprocess.TimeoutExpired:
            last = f"backend init probe timed out after {t:.0f}s"
    return "", last or "unknown"


def pin_cpu(n_devices: int = 1) -> None:
    """Pin this process's JAX to ``n_devices`` virtual CPU devices.

    Safe to call before or after backend init; must be called before any
    device-touching call to avoid the dead-tunnel hang.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax
    from jax.extend.backend import clear_backends
    clear_backends()
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", n_devices)
