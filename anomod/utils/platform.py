"""Single home for the "pin JAX to virtual CPU devices" workaround.

The container's axon sitecustomize force-registers the TPU platform at
interpreter start, so ``JAX_PLATFORMS=cpu`` in the environment alone does not
stick — and with a dead tunnel the first backend-touching call (anything via
``jax.devices()``) hangs forever.  The reliable recipe, used by the test
suite, the driver dry run, and the benchmark fallback alike:

  1. put ``--xla_force_host_platform_device_count=N`` into ``XLA_FLAGS``
     (covers subprocesses that initialize on import),
  2. pin ``jax_platforms=cpu`` + ``jax_num_cpu_devices`` via ``jax.config``
     *before* backend init in this process,
  3. ``clear_backends()`` first so a previously-initialized process can be
     repointed (no-op when nothing is initialized yet).
"""

from __future__ import annotations

import os


def probe_device_platform(attempts=None):
    """Out-of-process device-backend probe with a hard deadline.

    Returns ``(platform, diagnostic)`` where ``platform`` is the backend's
    ``jax.devices()[0].platform`` string ("tpu", "cpu", ...) or "" when no
    backend initializes within the deadline.  Probing in a subprocess is
    mandatory here: with a dead axon tunnel the first in-process
    device-touching call hangs forever, so the caller (bench.py, the TPU
    test suite's collection gate) must learn the backend state without
    touching it.
    """
    import subprocess
    import sys

    attempts = attempts or (75.0, 30.0)
    last = ""
    for t in attempts:
        try:
            r = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(jax.devices()[0].platform)"],
                timeout=t, capture_output=True)
            if r.returncode == 0:
                return r.stdout.decode(errors="replace").strip(), "probe ok"
            last = (r.stderr or b"").decode(errors="replace").strip()[-300:]
        except subprocess.TimeoutExpired:
            last = f"backend init probe timed out after {t:.0f}s"
    return "", last or "unknown"


def _probe_verdict_path():
    """Cache file for the device-probe verdict, or None when caching is
    off.  Keyed by jax/jaxlib version + OS platform (importing jax does
    NOT initialize the backend — versions are safe to read even with a
    dead tunnel); lives under ``ANOMOD_CACHE_DIR`` next to the ingest
    cache, so the one cache knob governs both."""
    try:
        from anomod.io.cache import cache_root
        root = cache_root()
    except Exception:
        return None
    if root is None:
        return None
    import sys

    import jax
    try:
        import jaxlib
        jaxlib_v = getattr(jaxlib, "__version__", "unknown")
    except Exception:
        jaxlib_v = "unknown"
    key = f"{jax.__version__}_{jaxlib_v}_{sys.platform}".replace("/", "_")
    return root / "probe" / f"verdict_{key}.json"


def read_probe_verdict():
    """The cached device-probe verdict as ``(platform, diagnostic)``, or
    None when absent/unreadable/disabled.  A cached empty platform means
    the probe timed out on this jax/jaxlib install — the caller's CPU
    fallback applies without re-paying the probe deadline (the whole
    point: CPU-only boxes stop burning ~60 s per bench run).  A revived
    device tunnel needs a fresh probe (bench.py ``--probe-fresh``).

    Callers must only WRITE (and trust) CPU/timeout verdicts: a cached
    live-accelerator verdict would bypass the liveness probe on a
    tunnel that has since died, and the first backend touch would hang
    with no deadline (bench.py enforces this on both sides)."""
    import json

    path = _probe_verdict_path()
    if path is None:
        return None
    try:
        d = json.loads(path.read_text())
        return str(d["platform"]), str(d["diag"])
    except (OSError, ValueError, KeyError, TypeError):
        return None


def write_probe_verdict(platform: str, diag: str) -> None:
    """Publish the probe verdict atomically (tmp + os.replace, the
    ingest cache's publish idiom); best-effort — an unwritable cache dir
    must never fail the capture."""
    import json

    path = _probe_verdict_path()
    if path is None:
        return
    try:
        path.parent.mkdir(parents=True, exist_ok=True)
        tmp = path.with_suffix(".tmp")
        tmp.write_text(json.dumps({"platform": platform, "diag": diag}))
        os.replace(tmp, path)
    except OSError:
        pass


def ensure_live_backend(n_cpu_fallback: int = 1, attempts=None) -> str:
    """Probe the device backend out-of-process; pin CPU when it is dead.

    The long-running CLI subcommands (quality / rca / replay) would
    otherwise hang forever at first backend touch when the axon tunnel is
    down (same failure mode bench.py hardens against).  Returns a one-line
    note: "probe ok: tpu" or "device backend unavailable (...); pinned
    cpu".  ``attempts=None`` keeps probe_device_platform's bounded-retry
    default (75 s + 30 s — sized for a slow-but-alive cold init, which must
    NOT be misread as dead); ``ANOMOD_PROBE_DEADLINE=<secs>`` overrides it,
    ``ANOMOD_SKIP_PROBE=1`` bypasses the probe entirely (saves the
    ~10-20 s subprocess init when the caller knows the backend is healthy).
    """
    if os.environ.get("ANOMOD_SKIP_PROBE", "").strip() == "1":
        return "probe skipped via ANOMOD_SKIP_PROBE"
    if attempts is None:
        deadline = env_number("ANOMOD_PROBE_DEADLINE", None, cast=float)
        if deadline is not None:
            attempts = (deadline,)
    plat, diag = probe_device_platform(attempts)
    if plat:
        return f"probe ok: {plat}"
    pin_cpu(n_cpu_fallback)
    return f"device backend unavailable ({diag}); pinned cpu"


def enable_jit_cache():
    """Point jax's persistent compilation cache at
    ``<ANOMOD_CACHE_DIR>/jit`` when the validated ``ANOMOD_JIT_CACHE``
    knob is on (anomod.config).

    Returns the cache directory as a string, or None when disabled (knob
    off, or ingest caching disabled entirely).  Idempotent and
    best-effort: an unwritable cache dir must never fail a serve or a
    capture — the process just compiles as it always did.  The cache is
    keyed by HLO hash, so the serving plane's per-shard runners (whose
    jitted grids lower to identical HLO) compile once per shape per
    *install*, not once per shape per shard per process — the same
    mechanism that lets a warm bench restart skip the
    (width x lane-bucket) compile wall entirely.
    """
    from anomod.config import get_config
    cfg = get_config()
    if not cfg.jit_cache or cfg.cache_dir is None:
        return None
    try:
        d = cfg.cache_dir / "jit"
        d.mkdir(parents=True, exist_ok=True)
        import jax
        jax.config.update("jax_compilation_cache_dir", str(d))
        # serve-grid entries are individually tiny and fast to compile;
        # without flooring these thresholds the cache would skip exactly
        # the many-small-shapes workload it exists for here
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        return str(d)
    except (OSError, AttributeError):
        return None


def env_number(name: str, default, cast=int):
    """Parse a numeric env var, warning and falling back on garbage.

    Single home for the "numeric knob from the environment" pattern
    (ANOMOD_CPU_DEVICES, ANOMOD_PROBE_DEADLINE): empty/unset → default,
    non-numeric → stderr warning + default.
    """
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return cast(raw)
    except ValueError:
        import sys
        print(f"[anomod] ignoring non-numeric {name}={raw!r}",
              file=sys.stderr)
        return default


def _current_platform() -> str:
    """Best-effort platform of the (possibly already-initialized) backend."""
    try:
        import jax
        return jax.devices()[0].platform
    except Exception:
        return "unknown"


#: Substrings that mark a RuntimeError as loss of the device *backend*
#: (tunnel/transport death) rather than a deterministic device-side error.
#: A TPU OOM (RESOURCE_EXHAUSTED) or a compile error must NOT fail over —
#: retrying those on CPU buries the real bug under a mislabeled
#: "backend lost" note.
_BACKEND_LOSS_MARKERS = (
    "UNAVAILABLE", "DEADLINE_EXCEEDED", "Connection", "connection",
    "transport", "remote_compile", "Socket closed", "failed to connect",
)


def is_backend_loss(exc: BaseException) -> bool:
    """True when the exception text reads as a dead device backend."""
    msg = str(exc)
    return any(m in msg for m in _BACKEND_LOSS_MARKERS)


def with_cpu_failover(fn, n_cpu=None, on_failover=None, _platform=None):
    """Run ``fn()``; if it dies because the device *backend* was lost while
    active, repoint the process to CPU and run it once more.

    This is the mid-run analog of the pre-run probe: a device tunnel that
    dies *during* a long sweep poisons every subsequent jax call in the
    process, but the host-side state (numpy corpora, completed result
    cells) is intact — repointing via :func:`pin_cpu` and redoing only the
    in-flight unit of work salvages the run.  The retry is single-shot and
    gated twice: the current platform must not already be ``cpu`` and the
    error text must read as backend loss (:func:`is_backend_loss`) —
    deterministic device errors (OOM, compile failures) propagate so they
    surface as what they are.  ``n_cpu=None`` sizes the fallback mesh from
    ``ANOMOD_CPU_DEVICES``; ``on_failover`` is called with the original
    exception before the retry (log/record hook); ``_platform`` injects
    the platform getter for tests.
    """
    get = _platform or _current_platform
    try:
        return fn()
    except RuntimeError as e:
        # marker check FIRST: it never touches the backend, so ordinary
        # RuntimeErrors (bugs) propagate without a jax.devices() call that
        # could itself hang on a dead, never-initialized tunnel; the
        # platform gate then only runs for plausible backend-loss errors
        if not is_backend_loss(e) or get() == "cpu":
            raise
        pin_cpu(n_cpu if n_cpu is not None
                else env_number("ANOMOD_CPU_DEVICES", 1))
        if on_failover is not None:
            on_failover(e)
        return fn()


#: process-local record that pin_cpu ran — the ONLY trustworthy "we are
#: on CPU" signal (the JAX_PLATFORMS env var is not binding here: the
#: container sitecustomize force-registers the TPU platform regardless,
#: see the module docstring)
_PINNED = False


def is_pinned() -> bool:
    """True when pin_cpu already pinned THIS process to the CPU backend
    (probing for a live device backend is pointless then)."""
    return _PINNED


def pin_cpu(n_devices: int = 1) -> None:
    """Pin this process's JAX to ``n_devices`` virtual CPU devices.

    Safe to call before or after backend init; must be called before any
    device-touching call to avoid the dead-tunnel hang.
    """
    global _PINNED
    import re
    flags = os.environ.get("XLA_FLAGS", "")
    want = f"--xla_force_host_platform_device_count={n_devices}"
    if "xla_force_host_platform_device_count" in flags:
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+",
                       want, flags)
        os.environ["XLA_FLAGS"] = flags
    else:
        os.environ["XLA_FLAGS"] = (flags + " " + want).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax
    from jax.extend.backend import clear_backends
    clear_backends()
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", n_devices)
    except AttributeError:
        # older jax: no such option — the XLA_FLAGS host-platform count
        # (set above, read at the post-clear_backends re-init) is the knob
        pass
    _PINNED = True
