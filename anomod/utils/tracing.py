"""Tracing / profiling: the framework's own observability.

The reference instruments its SUTs with Jaeger/SkyWalking (SURVEY.md §5);
the analog for a TPU framework is (a) wall-clock span timing of pipeline
stages emitted in a Jaeger-compatible JSON shape — so this framework's own
trace can be loaded back through anomod.io.sn_traces — and (b) XLA device
profiling via jax.profiler for kernel-level inspection.

Thread-safety contract: spans may open from any thread (the prefetch
Pipeline's staging worker, ingest pool callbacks) — each thread keeps its
OWN span stack (thread-local), so parent links never cross threads and a
worker's span can never corrupt the main thread's nesting; the span list
itself is lock-protected.  A span opened on a fresh thread is a root of
the same trace (no cross-thread parent inference — wrong more often than
right, and the Jaeger shape has no way to say "maybe").

Durability contract: :meth:`Tracer.dump` publishes atomically
(same-directory tmp + ``os.replace``, the anomod.io.cache idiom), so a
run killed mid-write never leaves a truncated JSON behind a valid path.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from pathlib import Path
from typing import List, Optional


class Span:
    """Handle yielded by :meth:`Tracer.span` — tag/event mutation only."""

    __slots__ = ("_rec",)

    def __init__(self, rec: dict):
        self._rec = rec

    def set_tag(self, key: str, value) -> None:
        self._rec["tags"][str(key)] = value

    def event(self, message: str, **fields) -> None:
        """Append a timestamped span log (Jaeger ``logs`` entry)."""
        self._rec["events"].append(
            {"t": time.time(), "message": str(message), **fields})


class Tracer:
    """Lightweight span tracer; dumps Jaeger-API-shaped JSON."""

    def __init__(self, service: str = "anomod"):
        self.service = service
        self._spans: List[dict] = []
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._trace_id = f"anomod-{int(time.time() * 1e6):x}"
        # thread ident -> small stable lane id, in first-span order: the
        # chrome exporter's ``tid`` — worker-thread spans (shard workers,
        # the prefetch pipeline) land on their OWN Perfetto lane instead
        # of all collapsing onto lane 0, so a sharded run's concurrency
        # structure is visually inspectable
        self._tids: dict = {}

    def _tid(self) -> int:
        ident = threading.get_ident()
        with self._lock:
            got = self._tids.get(ident)
            if got is None:
                got = self._tids[ident] = len(self._tids)
            return got

    def _stack(self) -> List[int]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    @property
    def n_spans(self) -> int:
        with self._lock:
            return len(self._spans)

    @contextlib.contextmanager
    def span(self, name: str, **tags):
        stack = self._stack()
        parent = stack[-1] if stack else None
        start = time.time()
        rec = {"name": name, "start": start, "dur": 0.0, "parent": parent,
               "tid": self._tid(),
               "tags": {str(k): v for k, v in tags.items()}, "events": []}
        with self._lock:
            idx = len(self._spans)
            self._spans.append(rec)
        stack.append(idx)
        try:
            yield Span(rec)
        finally:
            stack.pop()
            rec["dur"] = time.time() - start

    def add_span(self, name: str, start_s: float, dur_s: float,
                 parent: Optional[int] = None, tid: int = 0,
                 **tags) -> int:
        """Append a PRE-TIMED span record (explicit start/duration/lane)
        — the injection seam for timelines measured elsewhere, e.g. the
        performance observatory's dispatch-lifecycle events
        (anomod.obs.perf.perf_tracer), which export through the one
        chrome/jaeger pipeline instead of growing a second exporter.
        Never touches the thread-local span stack.  Returns the span's
        index (usable as a later ``parent``)."""
        rec = {"name": name, "start": float(start_s),
               "dur": float(dur_s), "parent": parent, "tid": int(tid),
               "tags": {str(k): v for k, v in tags.items()},
               "events": []}
        with self._lock:
            idx = len(self._spans)
            self._spans.append(rec)
        return idx

    def event(self, message: str, **fields) -> None:
        """Attach an event to the CURRENT thread's innermost open span
        (no-op outside any span — callers never need to guard)."""
        stack = self._stack()
        if not stack:
            return
        with self._lock:
            rec = self._spans[stack[-1]]
        Span(rec).event(message, **fields)

    def to_jaeger(self) -> dict:
        """Jaeger API JSON (loadable by anomod.io.sn_traces)."""
        with self._lock:
            # copy the mutable containers too: a worker thread may still
            # be set_tag()/event()-ing an open span while we serialize
            # (each event dict is write-once at append, so list() is
            # deep enough)
            recs = [{**s, "tags": dict(s["tags"]),
                     "events": list(s["events"])} for s in self._spans]
        spans = []
        for i, s in enumerate(recs):
            refs = ([{"refType": "CHILD_OF", "traceID": self._trace_id,
                      "spanID": f"s{s['parent']:08x}"}]
                    if s["parent"] is not None else [])
            tags = [{"key": "span.kind", "value": "internal"}]
            tags.extend({"key": k, "value": str(v)}
                        for k, v in sorted(s["tags"].items()))
            logs = [{"timestamp": int(e["t"] * 1e6),
                     "fields": [{"key": k, "value": str(v)}
                                for k, v in e.items() if k != "t"]}
                    for e in s["events"]]
            spans.append({
                "traceID": self._trace_id, "spanID": f"s{i:08x}",
                "processID": "p0", "operationName": s["name"],
                "startTime": int(s["start"] * 1e6),
                "duration": int(s["dur"] * 1e6),
                "references": refs,
                "tags": tags,
                "logs": logs,
            })
        return {"data": [{"traceID": self._trace_id,
                          "processes": {"p0": {"serviceName": self.service}},
                          "spans": spans}]}

    def to_chrome(self) -> List[dict]:
        """The span list as Chrome trace-event JSON (the array form
        ``chrome://tracing`` / Perfetto load directly): one complete
        event (``"ph": "X"``) per span on the microsecond clock domain.

        The trace-event format has no parent references — nesting is
        inferred from timestamp containment per ``(pid, tid)`` lane — so
        the EXPLICIT parent index and span id ride in ``args`` alongside
        the span's tags, which is what lets :func:`spans_from_chrome`
        round-trip the exact parent links instead of re-guessing them
        from timestamps (guessing breaks on zero-duration spans)."""
        with self._lock:
            recs = [{**s, "tags": dict(s["tags"])} for s in self._spans]
        events = []
        for i, s in enumerate(recs):
            events.append({
                "name": s["name"], "ph": "X", "cat": self.service,
                "ts": int(s["start"] * 1e6),
                "dur": int(s["dur"] * 1e6),
                # one lane per recording thread (or per explicit
                # add_span lane): Perfetto groups worker-thread spans —
                # shard workers, the dispatch timeline's scratch slots —
                # instead of collapsing every span onto lane 0; the
                # shard/slot TAGS ride in args (below) so lanes group
                # by shard in the UI and survive the round trip
                "pid": 0, "tid": s.get("tid", 0),
                "args": {**{str(k): str(v)
                            for k, v in sorted(s["tags"].items())},
                         "span_id": i,
                         "parent": -1 if s["parent"] is None
                         else s["parent"]},
            })
        return events

    def _dump_json(self, path: Path, doc) -> None:
        """The one atomic-publish body behind both dump shapes (tmp +
        ``os.replace``, the anomod.io.cache idiom)."""
        path = Path(path)
        if path.parent and not path.parent.exists():
            path.parent.mkdir(parents=True, exist_ok=True)
        tmp = path.with_name(f"{path.name}.{os.getpid()}.tmp")
        try:
            tmp.write_text(json.dumps(doc))
            os.replace(tmp, path)
        finally:
            if tmp.exists():
                try:
                    tmp.unlink()
                except OSError:
                    pass

    def dump_chrome(self, path: Path) -> None:
        """Atomic publish of :meth:`to_chrome` (same contract as
        :meth:`dump`)."""
        self._dump_json(path, self.to_chrome())

    def dump(self, path: Path) -> None:
        """Atomic publish (tmp + ``os.replace``): a killed run never
        leaves a truncated trace behind a valid path."""
        self._dump_json(path, self.to_jaeger())


def spans_from_chrome(events: List[dict]) -> List[dict]:
    """Parse a Chrome trace-event array back into span records
    (``{"name", "start", "dur", "parent", "tags"}`` — seconds, parent
    by span index, ``None`` for roots): the round-trip contract of
    :meth:`Tracer.to_chrome`, the chrome twin of
    ``anomod.io.sn_traces.spans_from_jaeger``.  Only complete events
    (``"ph": "X"``) are spans; anything else (metadata, counters some
    other producer appended) is skipped.  Events are keyed back into
    index order by the ``args.span_id`` the exporter planted, so a
    reordered (e.g. Perfetto-sorted) file still parses losslessly."""
    spans = [e for e in events if e.get("ph") == "X"]
    spans.sort(key=lambda e: e.get("args", {}).get("span_id", 0))
    out = []
    for e in spans:
        args = dict(e.get("args", {}))
        parent = args.pop("parent", -1)
        args.pop("span_id", None)
        out.append({"name": e.get("name", ""),
                    "start": e.get("ts", 0) / 1e6,
                    "dur": e.get("dur", 0) / 1e6,
                    "parent": None if parent in (-1, None) else int(parent),
                    "tid": int(e.get("tid", 0)),
                    "tags": args})
    return out


@contextlib.contextmanager
def profile_to(log_dir: Optional[str]):
    """XLA device profiling (TensorBoard trace) when a dir is given."""
    if not log_dir:
        yield
        return
    import jax
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
