"""Tracing / profiling: the framework's own observability.

The reference instruments its SUTs with Jaeger/SkyWalking (SURVEY.md §5);
the analog for a TPU framework is (a) wall-clock span timing of pipeline
stages emitted in a Jaeger-compatible JSON shape — so this framework's own
trace can be loaded back through anomod.io.sn_traces — and (b) XLA device
profiling via jax.profiler for kernel-level inspection.
"""

from __future__ import annotations

import contextlib
import json
import time
from pathlib import Path
from typing import List, Optional


class Tracer:
    """Lightweight span tracer; dumps Jaeger-API-shaped JSON."""

    def __init__(self, service: str = "anomod"):
        self.service = service
        self._spans: List[dict] = []
        self._stack: List[int] = []
        self._trace_id = f"anomod-{int(time.time() * 1e6):x}"

    @contextlib.contextmanager
    def span(self, name: str):
        idx = len(self._spans)
        parent = self._stack[-1] if self._stack else None
        start = time.time()
        self._spans.append({"name": name, "start": start, "dur": 0.0,
                            "parent": parent})
        self._stack.append(idx)
        try:
            yield
        finally:
            self._stack.pop()
            self._spans[idx]["dur"] = time.time() - start

    def to_jaeger(self) -> dict:
        """Jaeger API JSON (loadable by anomod.io.sn_traces)."""
        spans = []
        for i, s in enumerate(self._spans):
            refs = ([{"refType": "CHILD_OF", "traceID": self._trace_id,
                      "spanID": f"s{s['parent']:08x}"}]
                    if s["parent"] is not None else [])
            spans.append({
                "traceID": self._trace_id, "spanID": f"s{i:08x}",
                "processID": "p0", "operationName": s["name"],
                "startTime": int(s["start"] * 1e6),
                "duration": int(s["dur"] * 1e6),
                "references": refs,
                "tags": [{"key": "span.kind", "value": "internal"}],
                "logs": [],
            })
        return {"data": [{"traceID": self._trace_id,
                          "processes": {"p0": {"serviceName": self.service}},
                          "spans": spans}]}

    def dump(self, path: Path) -> None:
        Path(path).write_text(json.dumps(self.to_jaeger()))


@contextlib.contextmanager
def profile_to(log_dir: Optional[str]):
    """XLA device profiling (TensorBoard trace) when a dir is given."""
    if not log_dir:
        yield
        return
    import jax
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
