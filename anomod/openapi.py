"""OpenAPI/Swagger spec ingestion → endpoint catalog → generated suites.

The reference regenerates its EvoMaster suites from a Swagger document
(run_experiment.sh:500-555 passes ``--bbSwaggerUrl file://$EVOMASTER_SPEC``
over ``specs/.../combined-all-v3.5.json``; Evomaster/README.md:74-90).  The
shipped spec is an LFS pointer stub, so ingestion is built against the
standard document shapes and tested on a committed fixture: this module
parses Swagger 2.0 and OpenAPI 3.x JSON into :class:`SpecEndpoint` entries,
instantiates path parameters and JSON bodies deterministically from their
schemas, and hands ``anomod.suite.generate_suite`` a spec-derived endpoint
pool — completing the spec → suite → gateway flow without a JVM in the
loop.

Fresh design notes: EvoMaster explores the spec stochastically for a time
budget; here the budget→test-count calibration (anomod.suite._CALIBRATION)
carries the same knob deterministically, and "exploration" is seeded
round-robin + random sampling over the parsed endpoint pool — the property
campaigns need (coverage of the spec surface, reproducible by seed) without
the genetic search.
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path
from typing import Dict, List, Optional, Tuple

import numpy as np

from anomod.scenario import RequestSpec

_METHODS = ("get", "post", "put", "delete", "patch", "head", "options")


@dataclasses.dataclass(frozen=True)
class SpecEndpoint:
    """One (method, path-template) operation parsed from a spec."""
    method: str                      # upper-case HTTP verb
    template: str                    # path template incl. basePath, {param}s
    path_params: Tuple[Tuple[str, str], ...] = ()   # (name, type)
    body_schema: Optional[dict] = None               # JSON request schema
    operation_id: str = ""


def load_spec(path) -> dict:
    """Read a spec JSON file; an LFS pointer stub is a clear error (the
    caller decides whether to fall back to the internal catalog)."""
    from anomod.io.lfs import is_lfs_pointer
    path = Path(path)
    if is_lfs_pointer(path):
        raise ValueError(f"{path} is a git-LFS pointer stub, not a spec")
    with open(path) as f:
        return json.load(f)


def _resolve_ref(doc: dict, node):
    """Follow one local ``$ref`` (``#/a/b/c``); non-local refs pass through
    unresolved (the synthesizer falls back to a generic value)."""
    while isinstance(node, dict) and isinstance(node.get("$ref"), str) \
            and node["$ref"].startswith("#/"):
        cur = doc
        for part in node["$ref"][2:].split("/"):
            if not isinstance(cur, dict) or part not in cur:
                return node
            cur = cur[part]
        node = cur
    return node


def _param_type(doc: dict, param: dict) -> str:
    # v2 keeps `type` on the parameter; v3 nests it in `schema`
    if "type" in param:
        return str(param["type"])
    schema = _resolve_ref(doc, param.get("schema") or {})
    return str(schema.get("type", "string"))


def _body_schema(doc: dict, op: dict, shared_params: List[dict]) -> Optional[dict]:
    # v3: requestBody.content.application/json.schema
    body = _resolve_ref(doc, op.get("requestBody") or {})
    content = body.get("content") or {}
    for mime, media in content.items():
        if "json" in mime:
            return _resolve_ref(doc, media.get("schema") or {})
    # v2: parameters with in: body
    for p in list(op.get("parameters") or []) + shared_params:
        p = _resolve_ref(doc, p)
        if p.get("in") == "body":
            return _resolve_ref(doc, p.get("schema") or {})
    return None


def parse_spec(doc: dict) -> List[SpecEndpoint]:
    """Flatten a Swagger 2.0 / OpenAPI 3.x document into endpoint entries.

    ``basePath`` (v2) prefixes every path; v3 ``servers`` URLs are treated
    as host-level and ignored (the gateway owns the host).  Path-level
    shared parameters merge into each operation's."""
    base = str(doc.get("basePath", "")).rstrip("/")
    out: List[SpecEndpoint] = []
    for path, item in (doc.get("paths") or {}).items():
        item = _resolve_ref(doc, item)
        shared = [_resolve_ref(doc, p) for p in (item.get("parameters") or [])]
        for method in _METHODS:
            if method not in item:
                continue
            op = _resolve_ref(doc, item[method])
            params = [_resolve_ref(doc, p)
                      for p in (op.get("parameters") or [])] + shared
            path_params = tuple(
                (str(p.get("name", "")), _param_type(doc, p))
                for p in params if p.get("in") == "path")
            out.append(SpecEndpoint(
                method=method.upper(),
                template=f"{base}{path}",
                path_params=path_params,
                body_schema=_body_schema(doc, op, shared),
                operation_id=str(op.get("operationId", "")),
            ))
    return out


# ---------------------------------------------------------------------------
# Deterministic instantiation (the generated-suite request values)
# ---------------------------------------------------------------------------

def _value_for(doc: dict, schema, rng, depth: int = 0):
    schema = _resolve_ref(doc, schema if isinstance(schema, dict) else {})
    if "enum" in schema and schema["enum"]:
        return schema["enum"][int(rng.integers(len(schema["enum"])))]
    t = schema.get("type", "object" if schema.get("properties") else "string")
    if t == "integer":
        return int(rng.integers(1, 100))
    if t == "number":
        return round(float(rng.uniform(0, 100)), 2)
    if t == "boolean":
        return bool(rng.integers(2))
    if t == "array":
        if depth >= 3:
            return []
        return [_value_for(doc, schema.get("items") or {}, rng, depth + 1)]
    if t == "object":
        if depth >= 3:
            return {}
        props = schema.get("properties") or {}
        return {k: _value_for(doc, v, rng, depth + 1)
                for k, v in props.items()}
    # string (formats: keep it simple and deterministic)
    fmt = schema.get("format", "")
    if fmt == "date-time":
        return "2025-01-01T00:00:00Z"
    if fmt == "date":
        return "2025-01-01"
    if fmt == "uuid":
        return f"00000000-0000-0000-0000-{int(rng.integers(1 << 47)):012x}"
    return f"s{int(rng.integers(1 << 30)):x}"


def instantiate(doc: dict, ep: SpecEndpoint, rng) -> RequestSpec:
    """One concrete request for a spec endpoint: path params substituted,
    JSON body synthesized from its schema."""
    path = ep.template
    for name, t in ep.path_params:
        val = _value_for(doc, {"type": t}, rng)
        path = path.replace("{" + name + "}", str(val))
    body = None
    if ep.body_schema is not None:
        body = json.dumps(_value_for(doc, ep.body_schema, rng))
    return RequestSpec(ep.method, path, ep.template, flow="openapi",
                       body=body)


def endpoint_pool_from_spec(doc: dict, seed: int = 0) -> List[RequestSpec]:
    """The suite-generation pool: one instantiated RequestSpec per spec
    operation, ordered by (template, method) for determinism."""
    rng = np.random.default_rng(seed)
    eps = sorted(parse_spec(doc), key=lambda e: (e.template, e.method))
    if not eps:
        raise ValueError("spec has no paths/operations")
    return [instantiate(doc, e, rng) for e in eps]
