"""Machine-readable benchmark capture provenance.

Every successful benchmark capture (driver `bench.py` run, quality sweep,
kernel micro-bench) is written as one JSON file under ``bench_runs/`` so the
headline numbers in ``docs/BENCHMARKS.md`` cite committed, re-checkable
artifacts instead of prose: each record carries the measured value, the
kernel, the *device string* (so an on-chip claim is distinguishable from a
CPU fallback), jax/jaxlib versions, a UTC timestamp, and the git SHA of the
tree that produced it.

This answers the round-2 verdict's evidence gap: the builder-measured
3.0e8 spans/sec/chip existed only as a markdown table; with the device
tunnel dead at round end nothing was re-verifiable.  The protocol now is
"capture -> write record -> commit" the moment a device is live.

Writes are best-effort: a benchmark must never fail because the repo is
read-only or git is absent, so all failures degrade to returning ``None``.
"""

from __future__ import annotations

import json
import os
import subprocess
import time
from typing import Optional

DEFAULT_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)),
                           "bench_runs")


def git_sha(cwd: Optional[str] = None) -> str:
    """Best-effort HEAD SHA of the benchmarked tree ('' if unavailable),
    suffixed ``-dirty`` when the working tree has uncommitted changes — a
    record citing a clean SHA must actually be reproducible from it."""
    cwd = cwd or os.path.dirname(DEFAULT_DIR)
    try:
        r = subprocess.run(["git", "rev-parse", "HEAD"], cwd=cwd,
                           capture_output=True, timeout=10)
        if r.returncode != 0:
            return ""
        sha = r.stdout.decode().strip()
        # -uno: a capture record being written is itself untracked, so
        # counting untracked files would mark every capture dirty by
        # construction; only modified TRACKED files make the measured code
        # state unreproducible
        s = subprocess.run(["git", "status", "--porcelain", "-uno"], cwd=cwd,
                           capture_output=True, timeout=10)
        if s.returncode == 0 and s.stdout.strip():
            sha += "-dirty"
        return sha
    except Exception:
        return ""


def capture_record(metric: str, value: float, unit: str, **extra) -> dict:
    """Build a full provenance record for one measurement.

    ``extra`` carries measurement-specific fields (kernel, device, raw
    per-repeat wall times, workload shape...).  Environment fields are
    stamped here so every record is self-describing.
    """
    import jax
    rec = {
        "metric": metric,
        "value": value,
        "unit": unit,
        "timestamp_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "git_sha": git_sha(),
        "jax_version": jax.__version__,
    }
    try:
        import jaxlib
        rec["jaxlib_version"] = jaxlib.__version__
    except Exception:
        pass
    rec.update(extra)
    return rec


def write_capture(record: dict, outdir: Optional[str] = None) -> Optional[str]:
    """Write one capture record to ``bench_runs/``; return its path.

    Filename encodes timestamp + metric + device class so a directory
    listing reads as a capture log.  Returns None (never raises) when the
    filesystem refuses — provenance must not break the measurement.
    """
    outdir = outdir or os.environ.get("ANOMOD_BENCH_RUNS_DIR", DEFAULT_DIR)
    try:
        os.makedirs(outdir, exist_ok=True)
        device = str(record.get("device", "unknown"))
        devclass = "tpu" if "TPU" in device.upper() else \
            ("cpu" if "CPU" in device.upper() else "dev")
        ts = record.get("timestamp_utc", "").replace(":", "").replace("-", "")
        stem = f"{ts}_{record.get('metric', 'capture')}_{devclass}"
        # O_EXCL + counter suffix: two captures of the same metric within
        # one second must not clobber each other — the log's whole job is
        # to preserve every capture.
        for i in range(1000):
            path = os.path.join(
                outdir, f"{stem}.json" if i == 0 else f"{stem}_{i}.json")
            try:
                fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o644)
            except FileExistsError:
                continue
            with os.fdopen(fd, "w") as f:
                json.dump(record, f, indent=1, sort_keys=True)
                f.write("\n")
            return path
        return None
    except Exception:
        return None
