"""Typed schemas: the columnar span IR and per-modality record contracts.

The two reference trace schemas are unified behind one columnar ``SpanBatch``:

  - SN / Jaeger spans: flat rows with ``trace_id, span_id, parent_span_id,
    service, operation, start_time, duration_us, http_status_code, http_method,
    http_url, component, tags, logs``
    (SN_collection-scripts/Dataset/trace_data/jaeger_to_csv.py:76-90).
  - TT / SkyWalking spans: ``node_id="segment:span"``, parent via same-segment
    ``parent_span_id`` or cross-segment ``refs``; fields ``service_code,
    endpoint_name, start/end ms, type(Entry|Exit|Local), is_error, ...``
    (TT_collection-scripts/T-Dataset/trace_collector.py:86-123, 401-481).

Design is TPU-first: everything hot is a fixed-dtype numpy array (host) that
can be staged to HBM unchanged; strings are interned into side tables.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

# Span kinds (TT "type" field; SN spans are all RPC ~ Entry/Exit mix).
KIND_ENTRY = 0
KIND_EXIT = 1
KIND_LOCAL = 2
KIND_NAMES = ("Entry", "Exit", "Local")


class SpanBatch(NamedTuple):
    """Columnar batch of spans — the unified span IR.

    All arrays share length ``n_spans``.  ``parent`` holds the *global row
    index* of the parent span within the same batch (-1 for roots) — parent
    resolution from the two reference conventions happens at load time
    (anomod.graph.resolve_parents).
    """

    trace: np.ndarray      # int32  — index into `trace_ids` table
    parent: np.ndarray     # int32  — global row index of parent, -1 = root
    service: np.ndarray    # int32  — index into `services`
    endpoint: np.ndarray   # int32  — index into `endpoints`
    start_us: np.ndarray   # int64  — epoch microseconds
    duration_us: np.ndarray  # int64
    is_error: np.ndarray   # bool_
    status: np.ndarray     # int16  — HTTP status code, 0 if absent
    kind: np.ndarray       # int8   — KIND_ENTRY/EXIT/LOCAL

    # Side tables (python tuples -> not traced by JAX)
    services: Tuple[str, ...]
    endpoints: Tuple[str, ...]
    trace_ids: Tuple[str, ...]

    @property
    def n_spans(self) -> int:
        return int(self.trace.shape[0])

    @property
    def n_traces(self) -> int:
        return len(self.trace_ids)

    @property
    def n_services(self) -> int:
        return len(self.services)

    def validate(self) -> "SpanBatch":
        n = self.n_spans
        for name in ("trace", "parent", "service", "endpoint", "start_us",
                     "duration_us", "is_error", "status", "kind"):
            arr = getattr(self, name)
            if arr.shape != (n,):
                raise ValueError(f"SpanBatch.{name}: shape {arr.shape} != ({n},)")
        if n:
            if self.parent.max(initial=-1) >= n:
                raise ValueError("SpanBatch.parent out of range")
            if self.service.max(initial=0) >= len(self.services):
                raise ValueError("SpanBatch.service id out of range")
            if self.trace.max(initial=0) >= len(self.trace_ids):
                raise ValueError("SpanBatch.trace id out of range")
        return self


def empty_span_batch() -> SpanBatch:
    z = lambda dt: np.zeros((0,), dtype=dt)  # noqa: E731
    return SpanBatch(
        trace=z(np.int32), parent=z(np.int32), service=z(np.int32),
        endpoint=z(np.int32), start_us=z(np.int64), duration_us=z(np.int64),
        is_error=z(np.bool_), status=z(np.int16), kind=z(np.int8),
        services=(), endpoints=(), trace_ids=(),
    )


def concat_span_batches(batches: Sequence[SpanBatch]) -> SpanBatch:
    """Concatenate batches, re-interning the string tables."""
    batches = [b for b in batches if b.n_spans]
    if not batches:
        return empty_span_batch()
    services: Dict[str, int] = {}
    endpoints: Dict[str, int] = {}
    trace_ids: Dict[str, int] = {}
    cols = {k: [] for k in ("trace", "parent", "service", "endpoint",
                            "start_us", "duration_us", "is_error", "status", "kind")}
    offset = 0
    for b in batches:
        svc_map = np.array([services.setdefault(s, len(services)) for s in b.services]
                           or [0], dtype=np.int32)
        ep_map = np.array([endpoints.setdefault(e, len(endpoints)) for e in b.endpoints]
                          or [0], dtype=np.int32)
        tr_map = np.array([trace_ids.setdefault(t, len(trace_ids)) for t in b.trace_ids]
                          or [0], dtype=np.int32)
        cols["service"].append(svc_map[b.service])
        cols["endpoint"].append(ep_map[b.endpoint])
        cols["trace"].append(tr_map[b.trace])
        par = b.parent.copy()
        par[par >= 0] += offset
        cols["parent"].append(par)
        for k in ("start_us", "duration_us", "is_error", "status", "kind"):
            cols[k].append(getattr(b, k))
        offset += b.n_spans
    return SpanBatch(
        trace=np.concatenate(cols["trace"]),
        parent=np.concatenate(cols["parent"]),
        service=np.concatenate(cols["service"]),
        endpoint=np.concatenate(cols["endpoint"]),
        start_us=np.concatenate(cols["start_us"]),
        duration_us=np.concatenate(cols["duration_us"]),
        is_error=np.concatenate(cols["is_error"]),
        status=np.concatenate(cols["status"]),
        kind=np.concatenate(cols["kind"]),
        services=tuple(services), endpoints=tuple(endpoints),
        trace_ids=tuple(trace_ids),
    ).validate()


def take_spans(batch: SpanBatch, idx: np.ndarray) -> SpanBatch:
    """Row-subset of a SpanBatch (boolean mask or index array).

    Side tables are kept whole so service/endpoint/trace ids stay valid;
    ``parent`` is NOT remapped — rows whose parent falls outside the subset
    keep their original global index, so callers that need parent edges
    must subset by whole traces.  Used by the streaming layer to slice a
    corpus into arrival-ordered micro-batches (time slices keep traces
    intact only incidentally; the replay plane never reads ``parent``).
    """
    return batch._replace(
        trace=batch.trace[idx], parent=batch.parent[idx],
        service=batch.service[idx], endpoint=batch.endpoint[idx],
        start_us=batch.start_us[idx], duration_us=batch.duration_us[idx],
        is_error=batch.is_error[idx], status=batch.status[idx],
        kind=batch.kind[idx])


# ---------------------------------------------------------------------------
# Metric IR — long-format samples, matching both reference CSV shapes:
#   SN per-query CSVs:  timestamp,value,metric,<label cols>
#     (fetch_prometheus_metrics.py:57-66)
#   TT single long CSV: metric_name,timestamp,datetime,value,<label cols>
#     (metric_collector.py:431-443)
# ---------------------------------------------------------------------------

class MetricBatch(NamedTuple):
    metric: np.ndarray      # int32 — index into `metric_names`
    series: np.ndarray      # int32 — index into `series_keys` (label-set id)
    t_s: np.ndarray         # float64 — epoch seconds
    value: np.ndarray       # float64 (NaN allowed)
    metric_names: Tuple[str, ...]
    series_keys: Tuple[str, ...]   # rendered label strings k="v",...
    series_service: np.ndarray     # int32 per series — service id or -1
    services: Tuple[str, ...]

    @property
    def n_samples(self) -> int:
        return int(self.t_s.shape[0])


# ---------------------------------------------------------------------------
# Log IR — per (service, window) line/error/warn counts, matching the
# reference summaries (collect_log.sh:101-137; log_collector.py report).
# Raw lines stay on host; only counts go to device.
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class LogSummary:
    service: str
    n_lines: int
    n_error: int
    n_warn: int
    n_info: int = 0
    size_bytes: int = 0


class LogBatch(NamedTuple):
    service: np.ndarray    # int32
    t_s: np.ndarray        # float64 — line timestamp (bucketed ok)
    level: np.ndarray      # int8: 0=info 1=warn 2=error 3=other
    services: Tuple[str, ...]

    @property
    def n_lines(self) -> int:
        return int(self.t_s.shape[0])


LOG_INFO, LOG_WARN, LOG_ERROR, LOG_OTHER = 0, 1, 2, 3


# ---------------------------------------------------------------------------
# API-response IR — one record per probed request, matching the JSONL contract
# (enhanced_openapi_monitor.py:155-169: timestamp, endpoint, method,
#  status_code, latency_ms, content_length, ...).
# ---------------------------------------------------------------------------

class ApiBatch(NamedTuple):
    endpoint: np.ndarray     # int32
    t_s: np.ndarray          # float64
    status: np.ndarray       # int16
    latency_ms: np.ndarray   # float32
    content_length: np.ndarray  # int32
    endpoints: Tuple[str, ...]

    @property
    def n_records(self) -> int:
        return int(self.t_s.shape[0])


# ---------------------------------------------------------------------------
# Coverage IR — per (service, file) line-coverage counters, unifying
# gcov text (SN) and JaCoCo XML LINE counters (TT, coverage_summary.py:97-125).
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class FileCoverage:
    service: str
    path: str
    lines_total: int
    lines_covered: int

    @property
    def ratio(self) -> float:
        return self.lines_covered / self.lines_total if self.lines_total else 0.0


class CoverageBatch(NamedTuple):
    service: np.ndarray       # int32, per file
    lines_total: np.ndarray   # int32
    lines_covered: np.ndarray  # int32
    services: Tuple[str, ...]
    paths: Tuple[str, ...]

    def service_ratio(self) -> np.ndarray:
        """Per-service covered/total line ratio."""
        n = len(self.services)
        tot = np.zeros(n, np.int64)
        cov = np.zeros(n, np.int64)
        np.add.at(tot, self.service, self.lines_total)
        np.add.at(cov, self.service, self.lines_covered)
        with np.errstate(invalid="ignore", divide="ignore"):
            return np.where(tot > 0, cov / np.maximum(tot, 1), 0.0)


def coverage_batch_from_files(files: Sequence[FileCoverage]) -> CoverageBatch:
    services: Dict[str, int] = {}
    svc_idx = np.array([services.setdefault(f.service, len(services)) for f in files],
                       dtype=np.int32) if files else np.zeros((0,), np.int32)
    return CoverageBatch(
        service=svc_idx,
        lines_total=np.array([f.lines_total for f in files], np.int32),
        lines_covered=np.array([f.lines_covered for f in files], np.int32),
        services=tuple(services),
        paths=tuple(f.path for f in files),
    )


# ---------------------------------------------------------------------------
# Experiment bundle — the five synchronized modalities for one experiment,
# joined by the shared experiment name key (T-Dataset/README.md:19).
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Experiment:
    name: str                       # e.g. "Lv_P_CPU_preserve_20251103T140939Z_em"
    testbed: str                    # "SN" | "TT"
    spans: Optional[SpanBatch] = None
    metrics: Optional[MetricBatch] = None
    logs: Optional[LogBatch] = None
    log_summaries: Optional[List[LogSummary]] = None
    api: Optional[ApiBatch] = None
    coverage: Optional[CoverageBatch] = None
    synthetic: bool = False
