"""t-digest with fixed-capacity centroids — TPU-native design.

Classic t-digests grow/shrink centroid lists dynamically; that's hostile to
XLA (dynamic shapes).  This implementation keeps a **fixed K-centroid array**
and rebuilds by sort + quantile-bucketing + segment reduction, which vmaps
cleanly over (service, edge, metric) lanes and runs on the MXU/VPU:

  build:  sort values → normalized rank q → centroid bucket via the t-digest
          scale function k(q) = K·(asin(2q−1)/π + ½) → segment mean/weight.
  merge:  concatenate centroid sets, weighted re-bucket by the same rule
          (associative up to sketch error; shard states merge over ICI via
          all_gather + rebuild).
  query:  interpolated inverse of the cumulative-weight curve.

The numpy path is the oracle; the jax path is identical math under jit/vmap.
No reference counterpart exists (the reference computes exact percentiles in
Python, enhanced_openapi_monitor.py:321-332) — this is the streaming-scale
replacement.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import numpy as np


class TDigest(NamedTuple):
    mean: "object"    # [..., K] float32 — centroid means (sorted)
    weight: "object"  # [..., K] float32 — centroid weights (0 = empty slot)

    @property
    def capacity(self) -> int:
        return self.mean.shape[-1]


def _scale_bucket(q, k: int, xp):
    """t-digest k1 scale function mapped to integer buckets [0, k)."""
    z = xp.clip(2.0 * q - 1.0, -1.0, 1.0)
    s = (xp.arcsin(z) / np.pi + 0.5) * k
    return xp.clip(s.astype(np.int32) if xp is np else s.astype("int32"), 0, k - 1)


def _segment_mean(bucket, values, weights, k: int, xp):
    """Weighted per-bucket mean/weight via one-hot reductions (works for both
    numpy and jax.numpy; jax lowers the one-hot matmul onto the MXU)."""
    onehot = (bucket[..., None] == xp.arange(k)[None, :]).astype(values.dtype)
    w = xp.sum(onehot * weights[..., None], axis=-2)
    m = xp.sum(onehot * (weights * values)[..., None], axis=-2)
    return xp.where(w > 0, m / xp.where(w > 0, w, 1.0), 0.0), w


def tdigest_build(values, k: int = 64, weights=None, xp=np) -> TDigest:
    """Build a K-centroid digest from a value batch (last axis reduced)."""
    values = xp.asarray(values, dtype="float32" if xp is not np else np.float32)
    n = values.shape[-1]
    if weights is None:
        weights = xp.ones_like(values)
    order = xp.argsort(values, axis=-1)
    v = xp.take_along_axis(values, order, axis=-1)
    w = xp.take_along_axis(weights, order, axis=-1)
    cum = xp.cumsum(w, axis=-1)
    total = cum[..., -1:]
    q = (cum - 0.5 * w) / xp.where(total > 0, total, 1.0)
    bucket = _scale_bucket(q, k, xp)
    mean, weight = _segment_mean(bucket, v, w, k, xp)
    return TDigest(mean=mean, weight=weight)


def tdigest_merge(a: TDigest, b: TDigest, xp=np) -> TDigest:
    """Merge two digests (same capacity) by weighted rebuild."""
    k = a.capacity
    values = xp.concatenate([a.mean, b.mean], axis=-1)
    weights = xp.concatenate([a.weight, b.weight], axis=-1)
    return tdigest_build(values, k=k, weights=weights, xp=xp)


def tdigest_merge_many(digests, xp=np) -> TDigest:
    """Merge a leading axis of digests (e.g. all-gathered shard states)."""
    mean = xp.concatenate([d.mean for d in digests], axis=-1)
    weight = xp.concatenate([d.weight for d in digests], axis=-1)
    return tdigest_build(mean, k=digests[0].capacity, weights=weight, xp=xp)


def _fill_empty_means(mean, weight, xp):
    """Replace empty centroids' placeholder mean (0, from _segment_mean)
    with the nearest populated centroid's mean so CDF interpolation can
    never land on a bogus 0.  The k1 scale leaves empty buckets interleaved
    with populated ones whenever n < k or the distribution is peaked —
    without this fill, a quantile whose bracketing index hits an empty
    bucket interpolates toward 0 (observed: per-segment p99 below p50).
    Populated means are non-decreasing (buckets of a sorted stream), so a
    running max forward-fills and a reverse running min backfills."""
    if xp is np:
        cummax = lambda a: np.maximum.accumulate(a, axis=-1)
        cummin = lambda a: np.minimum.accumulate(a, axis=-1)
    else:
        import jax
        cummax = lambda a: jax.lax.cummax(a, axis=a.ndim - 1)
        cummin = lambda a: jax.lax.cummin(a, axis=a.ndim - 1)
    pop = weight > 0
    ffill = cummax(xp.where(pop, mean, -xp.inf))
    bfill = cummin(xp.where(pop, mean, xp.inf)[..., ::-1])[..., ::-1]
    filled = xp.where(xp.isfinite(ffill), ffill, bfill)
    # all-empty rows: keep the 0 placeholder
    return xp.where(xp.isfinite(filled), filled, 0.0)


def tdigest_quantile(d: TDigest, q, xp=np):
    """Approximate quantile(s) by interpolating the centroid CDF."""
    w = d.weight
    mean = _fill_empty_means(d.mean, w, xp)
    total = xp.sum(w, axis=-1, keepdims=True)
    cum = xp.cumsum(w, axis=-1) - 0.5 * w
    qq = xp.asarray(q, dtype=d.mean.dtype)
    target = qq * xp.squeeze(total, -1)
    # index of first centroid with cum >= target
    idx = xp.sum((cum < target[..., None]).astype("int32"), axis=-1)
    idx = xp.clip(idx, 0, d.mean.shape[-1] - 1)
    idx0 = xp.clip(idx - 1, 0, d.mean.shape[-1] - 1)
    c0 = xp.take_along_axis(cum, idx0[..., None], axis=-1)[..., 0]
    c1 = xp.take_along_axis(cum, idx[..., None], axis=-1)[..., 0]
    m0 = xp.take_along_axis(mean, idx0[..., None], axis=-1)[..., 0]
    m1 = xp.take_along_axis(mean, idx[..., None], axis=-1)[..., 0]
    t = xp.where(c1 > c0, (target - c0) / xp.where(c1 > c0, c1 - c0, 1.0), 0.0)
    t = xp.clip(t, 0.0, 1.0)
    return m0 + t * (m1 - m0)


def segment_pad(values, segment_ids, n_segments: int, xp=np, pad_to: int = 1):
    """Scatter a flat value stream into padded per-segment lanes.

    Sorts once by segment, scatters each segment's run into a
    [n_segments, L_max] matrix (weight 0 = padding) — the shared staging
    for every per-segment digest build (host/XLA and the Mosaic kernel).
    ``pad_to`` rounds L_max up (the kernel path uses 128 so the lane dim
    lands on a TPU lane-aligned layout and recompiles less often).
    Returns ``(padded_values, weights)``.
    """
    values = xp.asarray(values, dtype="float32")
    segment_ids = xp.asarray(segment_ids)
    n = values.shape[0]
    if n == 0:
        z = xp.zeros((n_segments, pad_to), dtype="float32")
        return z, xp.zeros_like(z)
    order = xp.argsort(segment_ids * xp.asarray(1, segment_ids.dtype), stable=True) \
        if xp is not np else np.argsort(segment_ids, kind="stable")
    seg_s = segment_ids[order]
    val_s = values[order]
    # position of each row within its segment
    idx = xp.arange(n)
    starts = xp.searchsorted(seg_s, xp.arange(n_segments))
    pos = idx - starts[seg_s]
    counts = xp.bincount(seg_s, length=n_segments) if xp is not np else \
        np.bincount(seg_s, minlength=n_segments)
    l_max = int(counts.max()) if xp is np else int(np.asarray(counts).max())
    l_max = max(l_max, 1)
    l_max += (-l_max) % pad_to
    padded = xp.zeros((n_segments, l_max), dtype="float32")
    weights = xp.zeros((n_segments, l_max), dtype="float32")
    if xp is np:
        padded[seg_s, pos] = val_s
        weights[seg_s, pos] = 1.0
    else:
        padded = padded.at[seg_s, pos].set(val_s)
        weights = weights.at[seg_s, pos].set(1.0)
    return padded, weights


def tdigest_by_segment(values, segment_ids, n_segments: int, k: int = 64,
                       xp=np) -> TDigest:
    """Per-segment t-digests from a flat value stream — the vmapped
    featurization path (BASELINE.json: per-service latency digests).

    One :func:`segment_pad` staging pass, then all digests in one
    vmapped/broadcast tdigest_build.  On TPU the Mosaic-kernel variant of
    the same contract is anomod.ops.pallas_tdigest.tdigest_by_segment_pallas.
    """
    padded, weights = segment_pad(values, segment_ids, n_segments, xp=xp)
    return tdigest_build(padded, k=k, weights=weights, xp=xp)
