"""Version shims for the moving pallas API surface (ops-side analog of
anomod.parallel.mesh's shard_map/pvary shims)."""

from __future__ import annotations


def tpu_compiler_params(**kwargs):
    """pltpu compiler params across the rename (``CompilerParams`` in newer
    jax, ``TPUCompilerParams`` before)."""
    from jax.experimental.pallas import tpu as pltpu
    cls = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams")
    return cls(**kwargs)
