"""Pallas TPU kernel for HyperLogLog register updates.

Computes murmur-style hashing, bucket/rank extraction, and the register max
entirely in VMEM across grid steps: per block, a [B, m] one-hot of bucket ids
carries each item's rank, a VPU max-reduce collapses it to [m], and the
register vector accumulates with ``jnp.maximum`` (revisited output block).

Used for single-sketch (global) cardinalities; the per-lane variant stays on
the XLA scatter-max path (anomod.ops.hll / anomod.replay hll plane).
"""

from __future__ import annotations


from anomod.ops.compat import tpu_compiler_params as _compiler_params



def make_pallas_hll_fn(p: int = 10, block: int = 2048, interpret: bool = False):
    """Returns fn(items int32 [N]) -> registers int32 [2^p]; N % block == 0."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m = 1 << p

    def kernel(items_ref, out_ref):
        step = pl.program_id(0)

        @pl.when(step == 0)
        def _init():
            out_ref[:] = jnp.zeros_like(out_ref)

        x = items_ref[:].astype(jnp.uint32)
        # murmur3 fmix32 avalanche (matches anomod.ops.hll._avalanche32)
        def fmix(v):
            v = v ^ (v >> jnp.uint32(16))
            v = v * jnp.uint32(0x85EBCA6B)
            v = v ^ (v >> jnp.uint32(13))
            v = v * jnp.uint32(0xC2B2AE35)
            return v ^ (v >> jnp.uint32(16))

        h = fmix(x)
        bucket = (h >> jnp.uint32(32 - p)).astype(jnp.int32)      # [B]
        h2 = fmix(h ^ jnp.uint32(0x9E3779B9))
        # branchless clz via bit shifts (Mosaic has no uint32->float cast)
        v = h2
        hi = jnp.zeros_like(bucket)                               # msb index
        for s in (16, 8, 4, 2, 1):
            t = v >> jnp.uint32(s)
            nz = t != jnp.uint32(0)
            hi = jnp.where(nz, hi + s, hi)
            v = jnp.where(nz, t, v)
        clz = jnp.where(h2 != jnp.uint32(0), 31 - hi, jnp.int32(32))
        rank = jnp.minimum(clz + 1, jnp.int32(32))                # [B]
        # [B, m] one-hot carrying ranks, VPU max-reduce over B
        m_iota = jax.lax.broadcasted_iota(jnp.int32, (block, m), 1)
        cand = jnp.where(m_iota == bucket[:, None], rank[:, None], 0)
        out_ref[:] = jnp.maximum(out_ref[:], jnp.max(cand, axis=0))

    @jax.jit
    def run(items):
        n = items.shape[0]
        assert n % block == 0, f"item count {n} must be a multiple of {block}"
        return pl.pallas_call(
            kernel,
            grid=(n // block,),
            in_specs=[pl.BlockSpec((block,), lambda i: (i,))],
            out_specs=pl.BlockSpec((m,), lambda i: (0,)),
            out_shape=jax.ShapeDtypeStruct((m,), jnp.int32),
            compiler_params=_compiler_params(
                dimension_semantics=("arbitrary",)),
            interpret=interpret,
        )(items)

    return run
