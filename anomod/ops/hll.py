"""HyperLogLog with int32-native hashing — TPU-friendly cardinality sketch.

2^p registers of max leading-zero rank; updates are a segment-max, merges are
elementwise max (so shard states combine with ``lax.pmax`` over ICI — exactly
associative, unlike the t-digest).  Hashing sticks to uint32 ops (TPU has no
fast 64-bit int path): two rounds of a murmur3-style avalanche.

Used for distinct-count featurization over span/metric streams (distinct
trace ids per service, distinct endpoints per edge, ...) — capability-new vs
the reference, which counts exact sets in Python (collect_trace.sh:54-58 jq
dedup; trace_collector.py:358-360 set()).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

_ALPHA = {16: 0.673, 32: 0.697, 64: 0.709}


def _alpha(m: int) -> float:
    return _ALPHA.get(m, 0.7213 / (1.0 + 1.079 / m))


def _avalanche32(x, xp):
    """murmur3 fmix32 — uint32 in/out."""
    x = x.astype("uint32")
    x = x ^ (x >> np.uint32(16))
    x = (x * np.uint32(0x85EBCA6B)).astype("uint32")
    x = x ^ (x >> np.uint32(13))
    x = (x * np.uint32(0xC2B2AE35)).astype("uint32")
    x = x ^ (x >> np.uint32(16))
    return x


def _clz32(x, xp):
    """Count leading zeros of uint32 via float trick (no native clz on VPU)."""
    # log2 via float conversion is exact enough for rank (x>0)
    xf = x.astype("float64") if xp is np else x.astype("float32")
    fl = xp.floor(xp.log2(xp.where(xf > 0, xf, 1.0)))
    return xp.where(x > 0, 31 - fl.astype("int32"), np.int32(32))


def hll_init(p: int = 12, lanes: Optional[int] = None, xp=np):
    """Zeroed registers: [m] or [lanes, m] with m = 2^p."""
    m = 1 << p
    shape = (m,) if lanes is None else (lanes, m)
    return xp.zeros(shape, dtype="int32")


def hll_add(registers, items, p: int = 12, lane=None, xp=np):
    """Add an int32 item batch. ``lane`` (optional, same shape as items)
    scatters items into per-lane registers (e.g. per-service sketches)."""
    items = xp.asarray(items).astype("uint32")
    h = _avalanche32(items, xp)
    bucket = (h >> np.uint32(32 - p)).astype("int32")
    h2 = _avalanche32(h ^ np.uint32(0x9E3779B9), xp)
    # rank: leading zeros (of the remaining bits) + 1, capped
    rank = xp.minimum(_clz32(h2, xp) + 1, np.int32(32)).astype("int32")
    m = 1 << p
    if lane is None:
        if xp is np:
            out = registers.copy()
            np.maximum.at(out, bucket, rank)
            return out
        return registers.at[bucket].max(rank)
    flat = lane.astype("int64") * m + bucket.astype("int64") if xp is np else \
        lane.astype("int32") * m + bucket
    L = registers.shape[0]
    if xp is np:
        out = registers.copy().reshape(-1)
        np.maximum.at(out, flat, rank)
        return out.reshape(L, m)
    # jax: scatter-max
    out = registers.reshape(-1)
    out = out.at[flat].max(rank)
    return out.reshape(L, m)


def hll_merge(a, b, xp=np):
    return xp.maximum(a, b)


def hll_estimate(registers, xp=np):
    """Cardinality estimate with small-range (linear counting) correction."""
    m = registers.shape[-1]
    regs = registers.astype("float64" if xp is np else "float32")
    inv = xp.sum(xp.power(2.0, -regs), axis=-1)
    raw = _alpha(m) * m * m / inv
    zeros = xp.sum((registers == 0).astype("int32"), axis=-1)
    # linear counting when estimate is small and empty registers exist
    lc = m * xp.log(m / xp.maximum(zeros, 1).astype(raw.dtype))
    use_lc = (raw <= 2.5 * m) & (zeros > 0)
    return xp.where(use_lc, lc, raw)
