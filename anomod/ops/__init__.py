"""TPU-friendly streaming sketches and segment ops (numpy oracle + JAX/Pallas)."""

from anomod.ops.tdigest import (TDigest, tdigest_build, tdigest_merge,
                                tdigest_quantile)
from anomod.ops.hll import (hll_add, hll_estimate, hll_merge, hll_init)

__all__ = ["TDigest", "tdigest_build", "tdigest_merge", "tdigest_quantile",
           "hll_add", "hll_estimate", "hll_merge", "hll_init"]
