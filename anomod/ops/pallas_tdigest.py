"""Pallas TPU kernel for fixed-K t-digest build/merge (BASELINE.json mandate).

Work split (TPU-first): the t-digest *scale pass* — sort by value, cumulative
weight, k1 scale function k(q) = K·(asin(2q−1)/π + ½) — is cheap elementwise/
sort work that XLA fuses well (and Mosaic lacks asin), so it stays in jax;
the *reduction pass* — bucketed segment mean/weight over every (service,
edge, metric) lane — is the bandwidth-heavy part and runs here as one fused
kernel: per lane, a [K, L] one-hot built in VMEM contracts against the
[L, 2] (weight, weight·value) plane on the MXU, producing the [K, 2]
centroid state without materializing the one-hot in HBM (the jax path's
[R, L, K] broadcast is the thing this kernel deletes).

Merge = concatenate centroid sets and rebuild with the same kernel (the
classic weighted-rebuild merge of anomod.ops.tdigest.tdigest_merge).

Numerics match anomod.ops.tdigest.tdigest_build exactly (same bucket rule,
same mean = Σwv/Σw), so the numpy oracle is the parity reference; interpret
mode covers CPU test runs.
"""

from __future__ import annotations


from anomod.ops.compat import tpu_compiler_params as _compiler_params


import functools
from typing import Tuple

import numpy as np


@functools.lru_cache(maxsize=64)
def make_pallas_tdigest_fn(n_centroids: int, length: int,
                           interpret: bool = False):
    """Returns fn(bucket[R, L] int32, w[R, L] f32, wv[R, L] f32)
    -> (mean[R, K] f32, weight[R, K] f32).

    ``bucket`` holds precomputed scale-function buckets in [0, K); rows are
    independent digest lanes (vmap is the grid, not program logic).  Padding
    slots carry w == 0 and any in-range bucket.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    K = n_centroids
    L = length
    # Mosaic requires the sublane (second-to-last) block dim to be a
    # multiple of 8 or the full array dim; one digest lane per block
    # violates that (caught by the compiled-parity TPU suite — interpret
    # mode accepts any block shape), so each block carries SUB=8 lanes and
    # the kernel unrolls the per-lane MXU contraction across sublanes.
    SUB = 8

    def kernel(bucket_ref, w_ref, wv_ref, mean_ref, weight_ref):
        # [L, K] centroid iota shared by every sublane's one-hot
        iota = jax.lax.broadcasted_iota(jnp.int32, (L, K), 1)
        for r in range(SUB):
            bucket = bucket_ref[r]                  # [L] int32
            w = w_ref[r]                            # [L]
            wv = wv_ref[r]                          # [L]
            # one-hot in VMEM; contract on the MXU: [K, L] @ [L, 2]
            onehot = (iota == bucket[:, None]).astype(jnp.float32)
            rhs = jnp.stack([w, wv], axis=1)        # [L, 2]
            acc = jax.lax.dot_general(
                onehot, rhs, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST)  # [K, 2]
            wk = acc[:, 0]
            weight_ref[r] = wk
            mean_ref[r] = jnp.where(
                wk > 0, acc[:, 1] / jnp.where(wk > 0, wk, 1.0), 0.0)

    @jax.jit
    def run(bucket, w, wv):
        R = bucket.shape[0]
        assert bucket.shape == w.shape == wv.shape == (R, L)
        pad = (-R) % SUB
        if pad:  # padding lanes carry w == 0 -> zero weight, zero mean
            bucket = jnp.pad(bucket, ((0, pad), (0, 0)))
            w = jnp.pad(w, ((0, pad), (0, 0)))
            wv = jnp.pad(wv, ((0, pad), (0, 0)))
        Rp = R + pad
        out_shape = (jax.ShapeDtypeStruct((Rp, K), jnp.float32),
                     jax.ShapeDtypeStruct((Rp, K), jnp.float32))
        mean, weight = pl.pallas_call(
            kernel,
            grid=(Rp // SUB,),
            in_specs=[pl.BlockSpec((SUB, L), lambda i: (i, 0))] * 3,
            out_specs=[pl.BlockSpec((SUB, K), lambda i: (i, 0))] * 2,
            out_shape=out_shape,
            compiler_params=_compiler_params(
                dimension_semantics=("parallel",)),
            interpret=interpret,
        )(bucket.astype(jnp.int32), w.astype(jnp.float32),
          wv.astype(jnp.float32))
        return mean[:R], weight[:R]

    return run


def _scale_pass(values, weights, k: int):
    """jax prolog: sort by value, cumulative weight, k1 scale buckets."""
    import jax.numpy as jnp

    order = jnp.argsort(values, axis=-1)
    v = jnp.take_along_axis(values, order, axis=-1)
    w = jnp.take_along_axis(weights, order, axis=-1)
    cum = jnp.cumsum(w, axis=-1)
    total = cum[..., -1:]
    q = (cum - 0.5 * w) / jnp.where(total > 0, total, 1.0)
    z = jnp.clip(2.0 * q - 1.0, -1.0, 1.0)
    s = (jnp.arcsin(z) / np.pi + 0.5) * k
    bucket = jnp.clip(s.astype(jnp.int32), 0, k - 1)
    return bucket, w, w * v


def tdigest_build_pallas(values, k: int = 64, weights=None,
                         interpret: bool = False):
    """Drop-in Pallas variant of tdigest.tdigest_build (leading dims = lanes).

    Returns a TDigest NamedTuple with [..., K] mean/weight arrays.
    """
    import jax.numpy as jnp

    from anomod.ops.tdigest import TDigest

    values = jnp.asarray(values, jnp.float32)
    if weights is None:
        weights = jnp.ones_like(values)
    lead = values.shape[:-1]
    L = values.shape[-1]
    bucket, w, wv = _scale_pass(values, jnp.asarray(weights, jnp.float32), k)
    R = int(np.prod(lead)) if lead else 1
    fn = make_pallas_tdigest_fn(k, L, interpret=interpret)
    mean, weight = fn(bucket.reshape(R, L), w.reshape(R, L), wv.reshape(R, L))
    return TDigest(mean=mean.reshape(*lead, k), weight=weight.reshape(*lead, k))


def tdigest_by_segment_pallas(values, segment_ids, n_segments: int,
                              k: int = 64, interpret=None):
    """Per-segment digests through the Mosaic kernel — the TPU featurization
    fast path with the same contract as tdigest.tdigest_by_segment.

    Host :func:`anomod.ops.tdigest.segment_pad` staging (lane dim rounded to
    128 for TPU layout + compile-cache stability), then ONE fused build over
    all segment lanes.  ``interpret=None`` auto-selects: compiled on a TPU
    backend, interpret mode elsewhere (so the same call works on the CPU
    test mesh).
    """
    import numpy as _np

    from anomod.ops.tdigest import segment_pad

    if interpret is None:
        import jax
        interpret = jax.default_backend() != "tpu"
    padded, weights = segment_pad(_np.asarray(values, _np.float32),
                                  _np.asarray(segment_ids), n_segments,
                                  pad_to=128)
    return tdigest_build_pallas(padded, k=k, weights=weights,
                                interpret=interpret)


def tdigest_merge_pallas(a, b, interpret: bool = False):
    """Merge two digest lanes by weighted rebuild through the kernel."""
    import jax.numpy as jnp

    k = a.mean.shape[-1]
    values = jnp.concatenate([a.mean, b.mean], axis=-1)
    weights = jnp.concatenate([a.weight, b.weight], axis=-1)
    return tdigest_build_pallas(values, k=k, weights=weights,
                                interpret=interpret)
