"""Pallas TPU kernel for the replay aggregation hot loop.

Fuses the whole per-chunk pipeline of anomod.replay.make_replay_fn — bf16
hi/lo moment split, one-hot construction, histogram bucketing, and the MXU
matmul — into one kernel whose [F+H, SW+1] accumulator stays VMEM-resident
across the entire grid (state never round-trips to HBM between blocks).

Measured on v5e (30.4M-span replicated TT corpus, block sweep 1024-8192 all
within 3%): **3.0e8 spans/sec/chip vs 2.5e8 for the XLA scan path** — the
hand-written kernel is the fast path and the bench default on TPU
(``ANOMOD_BENCH_KERNEL`` overrides).

Three structural fixes over the round-1 kernel (which measured 6.0e7
spans/sec vs 1.1e8 for the XLA scan path):

1. **Transposed formulation.**  out[F+H, SW+1] = rhsᵀ[F+H, B] @ onehot
   [B, SW+1] puts the narrow 25-row feature axis on *sublanes* (25→32
   padding, 1.3x) instead of lanes (25→128, 5x), and every operand is
   built in its natural layout — the old kernel's in-kernel ``feats.T``
   relayout is gone.
2. **bf16 one-hot + hi/lo moments, single MXU pass.**  The old kernel ran
   one f32 ``Precision.HIGHEST`` matmul (~6 bf16 MXU passes).  This kernel
   uses the same split as the XLA path (replay.py chunk_step): 0/1 planes
   exact in bf16, latency moments as a two-way bf16 hi/lo split, all in ONE
   bf16 matmul with f32 accumulation.
3. **VMEM-sized tiles.**  The old [8192, SW+1] f32 one-hot tile was ~46 MB
   — ~3x core VMEM (~16 MB), so Mosaic spilled it to HBM.  The default
   block of 4096 keeps the bf16 tile under 12 MB.

``inner_repeats`` replays the staged corpus on-device via an outer grid
dimension (same measurement trick as the XLA path's fori_loop).

Falls back to interpret mode off-TPU (used by the CPU-mesh tests).
"""

from __future__ import annotations

import numpy as np

# staged-column order fed to the kernel (matches anomod.replay plane order:
# the three exact 0/1 planes, then the three latency-moment planes)
PLANES = ("valid", "err", "s5", "dur_raw", "dur", "dur2")
N_PLANES = len(PLANES)


def make_pallas_replay_fn(n_segments: int, n_hist: int = 16,
                          block: int = 4096, interpret: bool = False,
                          inner_repeats: int = 1):
    """Returns fn(sid[N], planes[6, N]) -> agg[SW, 6+H].

    ``sid`` may contain n_segments (== dead/padding lane, dropped).
    ``planes`` rows follow :data:`PLANES`; the histogram bucket is computed
    in-kernel from the log-latency row (``clip(int(dur), 0, H-1)``), and the
    histogram occupies the trailing H columns of the output.

    When invoked inside ``shard_map``, the enclosing shard_map must pass
    ``check_vma=False``: the kernel's internal constants don't carry mesh
    varying-axes metadata, and the static checker rejects the mix whether
    or not the output declares a vma (see make_sharded_replay_fn).
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    SW1 = n_segments + 1          # + dead lane
    ROWS = 3 + 6 + n_hist         # exact + (hi, lo) moments + histogram

    def kernel(sid_ref, planes_ref, out_ref):
        @pl.when((pl.program_id(0) == 0) & (pl.program_id(1) == 0))
        def _init():
            out_ref[:] = jnp.zeros_like(out_ref)

        sid = sid_ref[:]                          # [B] int32
        planes = planes_ref[:]                    # [6, B] f32, natural layout
        exact = planes[0:3].astype(jnp.bfloat16)  # valid / err / 5xx
        moments = planes[3:6]                     # dur_raw / dur / dur^2
        hi = moments.astype(jnp.bfloat16)
        lo = (moments - hi.astype(jnp.float32)).astype(jnp.bfloat16)
        valid = planes[0]
        bucket = jnp.clip(planes[4].astype(jnp.int32), 0, n_hist - 1)
        h_iota = jax.lax.broadcasted_iota(jnp.int32, (n_hist, block), 0)
        bucket_oh = jnp.where(h_iota == bucket[None, :], valid[None, :],
                              0.0).astype(jnp.bfloat16)       # [H, B]
        rhs_t = jnp.concatenate([exact, hi, lo, bucket_oh], axis=0)
        seg_iota = jax.lax.broadcasted_iota(jnp.int32, (block, SW1), 1)
        onehot = (seg_iota == sid[:, None]).astype(jnp.bfloat16)
        out_ref[:] += jax.lax.dot_general(
            rhs_t, onehot, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @jax.jit
    def run(sid, planes):
        n = sid.shape[0]
        assert planes.shape == (N_PLANES, n), \
            "planes must be feature-major [6, N]"
        assert n % block == 0, f"span count {n} must be a multiple of {block}"
        grid = (inner_repeats, n // block)
        acc = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec((block,), lambda r, i: (i,)),
                pl.BlockSpec((N_PLANES, block), lambda r, i: (0, i)),
            ],
            out_specs=pl.BlockSpec((ROWS, SW1), lambda r, i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((ROWS, SW1), jnp.float32),
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("arbitrary", "arbitrary")),
            interpret=interpret,
        )(sid, planes)
        # recombine hi+lo moments, drop the dead lane, back to [SW, F+H]
        agg_t = jnp.concatenate(
            [acc[0:3], acc[3:6] + acc[6:9], acc[9:]], axis=0)
        return agg_t.T[:n_segments]

    return run


def pallas_replay_numpy(sid, planes, n_segments, n_hist):
    """Oracle for the fused kernel (planes feature-major [6, N])."""
    out = np.zeros((n_segments + 1, N_PLANES + n_hist), np.float32)
    np.add.at(out[:, :N_PLANES], sid, planes.T)
    valid = planes[0]
    bucket = np.clip(planes[4].astype(np.int32), 0, n_hist - 1)
    np.add.at(out, (sid, N_PLANES + bucket), valid)
    return out[:n_segments]
