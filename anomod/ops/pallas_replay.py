"""Pallas TPU kernel for the replay aggregation hot loop.

Fuses one-hot construction + the two MXU matmuls of anomod.replay (windowed
per-service feature aggregation and log-latency histogram) into a single
kernel with VMEM-resident accumulator state across grid steps: the [SW, F+H]
state never round-trips to HBM between chunks, and the one-hot tile lives
only in VMEM.

Grid: one step per span block (BLOCK rows).  Outputs use a constant index
map so the same VMEM block accumulates across the whole grid (standard
revisiting-output pattern); step 0 zero-initializes.

Falls back to interpret mode off-TPU (used by the CPU-mesh tests).

Status: measured 6.0e7 spans/sec/chip on v5e (30M-span corpus, block=8192) vs
1.1e8 for the XLA scan path in anomod.replay — the [SW, F+H] output tile is
too narrow to fill the MXU from inside one kernel, so the XLA path stays the
bench default.  Kept as the tuning base for a double-buffered variant.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np


def make_pallas_replay_fn(n_segments: int, n_feats: int, n_hist: int,
                          block: int = 4096, interpret: bool = False):
    """Returns fn(sid[N], feats[F,N], bucket[N]) -> agg[SW, F+H].

    ``sid`` may contain n_segments (== dead/padding lane, dropped).
    The histogram occupies the trailing H lanes of the output.
    ``feats`` is feature-major [F, N]: a span-major [N, F] layout would be
    lane-padded F->128 by XLA (21x HBM blowup at replay scale).
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    SW1 = n_segments + 1          # + dead lane
    FH = n_feats + n_hist

    def kernel(sid_ref, feats_ref, bucket_ref, out_ref):
        step = pl.program_id(0)

        @pl.when(step == 0)
        def _init():
            out_ref[:] = jnp.zeros_like(out_ref)

        sid = sid_ref[:]                       # [B] int32
        feats = feats_ref[:].T                 # [F, B] block -> [B, F]
        bucket = bucket_ref[:]                 # [B] int32
        # one-hot over segments, [B, SW1] — VMEM-resident tile
        seg_iota = jax.lax.broadcasted_iota(jnp.int32, (block, SW1), 1)
        onehot = (seg_iota == sid[:, None]).astype(jnp.float32)
        # histogram one-hot over buckets, [B, H]; valid = feats[:, 0]
        h_iota = jax.lax.broadcasted_iota(jnp.int32, (block, n_hist), 1)
        bucket_oh = (h_iota == bucket[:, None]).astype(jnp.float32)
        bucket_oh = bucket_oh * feats[:, 0][:, None]
        rhs = jnp.concatenate([feats, bucket_oh], axis=1)  # [B, F+H]
        out_ref[:] += jax.lax.dot_general(
            onehot, rhs, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST)

    @jax.jit
    def run(sid, feats, bucket):
        n = sid.shape[0]
        assert feats.shape == (n_feats, n), "feats must be feature-major [F, N]"
        assert n % block == 0, f"span count {n} must be a multiple of {block}"
        grid = (n // block,)
        out = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec((block,), lambda i: (i,)),
                pl.BlockSpec((n_feats, block), lambda i: (0, i)),
                pl.BlockSpec((block,), lambda i: (i,)),
            ],
            out_specs=pl.BlockSpec((SW1, FH), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((SW1, FH), jnp.float32),
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("arbitrary",)),
            interpret=interpret,
        )(sid, feats, bucket)
        return out[:n_segments]  # drop the dead padding lane

    return run


def pallas_replay_numpy(sid, feats, bucket, n_segments, n_feats, n_hist):
    """Oracle for the fused kernel (feats feature-major [F, N])."""
    FH = n_feats + n_hist
    out = np.zeros((n_segments + 1, FH), np.float32)
    np.add.at(out[:, :n_feats], sid, feats.T)
    valid = feats[0]
    np.add.at(out, (sid, n_feats + np.clip(bucket, 0, n_hist - 1)), valid)
    return out[:n_segments]
