"""Pallas TPU kernel for the replay aggregation hot loop.

Fuses the whole per-chunk pipeline of anomod.replay.make_replay_fn — bf16
hi/lo moment split, one-hot construction, histogram bucketing, and the MXU
matmul — into one kernel whose [F+H, SW+1] accumulator stays VMEM-resident
across the entire grid (state never round-trips to HBM between blocks).

Measured on v5e (30.4M-span replicated TT corpus, block sweep 1024-8192 all
within 3%): **3.0e8 spans/sec/chip vs 2.5e8 for the XLA scan path** — the
hand-written kernel is the fast path and the bench default on TPU
(``ANOMOD_BENCH_KERNEL`` overrides).

Three structural fixes over the round-1 kernel (which measured 6.0e7
spans/sec vs 1.1e8 for the XLA scan path):

1. **Transposed formulation.**  out[F+H, SW+1] = rhsᵀ[F+H, B] @ onehot
   [B, SW+1] puts the narrow 25-row feature axis on *sublanes* (25→32
   padding, 1.3x) instead of lanes (25→128, 5x), and every operand is
   built in its natural layout — the old kernel's in-kernel ``feats.T``
   relayout is gone.
2. **bf16 one-hot + hi/lo moments, single MXU pass.**  The old kernel ran
   one f32 ``Precision.HIGHEST`` matmul (~6 bf16 MXU passes).  This kernel
   uses the same split as the XLA path (replay.py chunk_step): 0/1 planes
   exact in bf16, latency moments as a two-way bf16 hi/lo split, all in ONE
   bf16 matmul with f32 accumulation.
3. **VMEM-sized tiles.**  The old [8192, SW+1] f32 one-hot tile was ~46 MB
   — ~3x core VMEM (~16 MB), so Mosaic spilled it to HBM.  The default
   block of 4096 keeps the bf16 tile under 12 MB.

``inner_repeats`` replays the staged corpus on-device via an outer grid
dimension (same measurement trick as the XLA path's fori_loop).

Falls back to interpret mode off-TPU (used by the CPU-mesh tests).
"""

from __future__ import annotations


from anomod.ops.compat import tpu_compiler_params as _compiler_params


import numpy as np

# staged-column order fed to the kernel (matches anomod.replay plane order:
# the three exact 0/1 planes, then the three latency-moment planes)
PLANES = ("valid", "err", "s5", "dur_raw", "dur", "dur2")
N_PLANES = len(PLANES)


def _build_rhs_t(planes, block, n_hist):
    """Shared kernel-body stage for both replay kernels: the [3+6+H, B]
    bf16 right-hand side — exact 0/1 planes, two-way hi/lo split of the
    latency moments, and the in-kernel histogram bucket one-hot.  Traced
    inside a pallas kernel (plain jnp ops only)."""
    import jax
    import jax.numpy as jnp

    exact = planes[0:3].astype(jnp.bfloat16)  # valid / err / 5xx
    moments = planes[3:6]                     # dur_raw / dur / dur^2
    hi = moments.astype(jnp.bfloat16)
    lo = (moments - hi.astype(jnp.float32)).astype(jnp.bfloat16)
    valid = planes[0]
    bucket = jnp.clip(planes[4].astype(jnp.int32), 0, n_hist - 1)
    h_iota = jax.lax.broadcasted_iota(jnp.int32, (n_hist, block), 0)
    bucket_oh = jnp.where(h_iota == bucket[None, :], valid[None, :],
                          0.0).astype(jnp.bfloat16)       # [H, B]
    return jnp.concatenate([exact, hi, lo, bucket_oh], axis=0)


def _recombine_moments(acc, n_segments):
    """Shared epilogue: recombine hi+lo moment rows, drop the dead-pad
    segment, transpose the row axis back behind the segment axis —
    ``[ROWS, SW+1] -> [SW, F+H]``, or batched ``[L, ROWS, SW+1] ->
    [L, SW, F+H]`` for the lane-stacked kernel.  The bf16 hi/lo split
    layout (3 exact + 3 hi + 3 lo + H histogram rows) is encoded HERE
    and in the kernels' rhs staging only."""
    import jax.numpy as jnp

    agg_t = jnp.concatenate(
        [acc[..., 0:3, :], acc[..., 3:6, :] + acc[..., 6:9, :],
         acc[..., 9:, :]], axis=-2)
    return jnp.swapaxes(agg_t, -1, -2)[..., :n_segments, :]


def make_pallas_replay_fn(n_segments: int, n_hist: int = 16,
                          block: int = 4096, interpret: bool = False,
                          inner_repeats: int = 1):
    """Returns fn(sid[N], planes[6, N]) -> agg[SW, 6+H].

    ``sid`` may contain n_segments (== dead/padding lane, dropped).
    ``planes`` rows follow :data:`PLANES`; the histogram bucket is computed
    in-kernel from the log-latency row (``clip(int(dur), 0, H-1)``), and the
    histogram occupies the trailing H columns of the output.

    When invoked inside ``shard_map``, the enclosing shard_map must pass
    ``check_vma=False``: the kernel's internal constants don't carry mesh
    varying-axes metadata, and the static checker rejects the mix whether
    or not the output declares a vma (see make_sharded_replay_fn).
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    SW1 = n_segments + 1          # + dead lane
    ROWS = 3 + 6 + n_hist         # exact + (hi, lo) moments + histogram

    def kernel(sid_ref, planes_ref, out_ref):
        @pl.when((pl.program_id(0) == 0) & (pl.program_id(1) == 0))
        def _init():
            out_ref[:] = jnp.zeros_like(out_ref)

        sid = sid_ref[:]                          # [B] int32
        # [6, B] f32 natural layout -> shared bf16 rhs build
        rhs_t = _build_rhs_t(planes_ref[:], block, n_hist)
        seg_iota = jax.lax.broadcasted_iota(jnp.int32, (block, SW1), 1)
        onehot = (seg_iota == sid[:, None]).astype(jnp.bfloat16)
        out_ref[:] += jax.lax.dot_general(
            rhs_t, onehot, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @jax.jit
    def run(sid, planes):
        n = sid.shape[0]
        assert planes.shape == (N_PLANES, n), \
            "planes must be feature-major [6, N]"
        assert n % block == 0, f"span count {n} must be a multiple of {block}"
        if n == 0:
            # zero-block grid would skip the init step and return garbage
            return jnp.zeros((n_segments, N_PLANES + n_hist), jnp.float32)
        grid = (inner_repeats, n // block)
        acc = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec((block,), lambda r, i: (i,)),
                pl.BlockSpec((N_PLANES, block), lambda r, i: (0, i)),
            ],
            out_specs=pl.BlockSpec((ROWS, SW1), lambda r, i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((ROWS, SW1), jnp.float32),
            compiler_params=_compiler_params(
                dimension_semantics=("arbitrary", "arbitrary")),
            interpret=interpret,
        )(sid, planes)
        return _recombine_moments(acc, n_segments)

    return run


def make_pallas_lane_delta_fn(n_segments: int, n_hist: int = 16,
                              block: int = 0, interpret: bool = False):
    """The serving plane's fused LANE-STACKED score kernel:
    ``fn(sid[L, W] int32, planes[L, 6, W] f32) -> [L, SW, 6+H]`` per-lane
    aggregation deltas — anomod.replay.make_lane_delta's TPU formulation
    as ONE Mosaic kernel instead of a vmap of the one-hot chunk step.

    Each grid step processes one ``block``-wide slice of one lane through
    the same fused pipeline as :func:`make_pallas_replay_fn` (bf16 hi/lo
    moment split, in-kernel histogram bucketing, single bf16 MXU matmul
    with f32 accumulation), accumulating into that lane's VMEM-resident
    ``[ROWS, SW+1]`` block — the per-lane roll/split/edge/score chain the
    interpreter used to drive as separate dispatches runs as one kernel
    launch per fused (lanes, width) shape.  Dead pad lanes carry all-pad
    rows (sid = SW, valid = 0) and produce exact-zero deltas, exactly as
    the scatter twin's dead segments.  ``block=0`` picks ``min(W, 4096)``
    (the VMEM-tuned replay default); W must be a block multiple — serve
    widths are powers of two, so the default always divides.

    Parity contract: identical 0/1 and histogram planes to the scatter/
    matmul engines (exact bf16 values, f32 accumulation); latency moments
    within the bf16 hi/lo split's error envelope — the same tolerance
    the compiled replay-kernel pins use.  Interpret mode keeps the
    kernel exercised in tier-1 on CPU (tests/test_replay.py); the
    Mosaic-compiled pin lives in tpu_tests/test_mosaic_parity.py.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    SW1 = n_segments + 1          # + dead lane
    ROWS = 3 + 6 + n_hist         # exact + (hi, lo) moments + histogram

    def run(sid, planes):
        L, W = sid.shape
        assert planes.shape == (L, N_PLANES, W), \
            "planes must be lane-major [L, 6, W]"
        blk = block or min(W, 4096)
        assert W % blk == 0, f"width {W} must be a multiple of {blk}"

        def kernel(sid_ref, planes_ref, out_ref):
            @pl.when(pl.program_id(1) == 0)
            def _init():
                out_ref[:] = jnp.zeros_like(out_ref)

            s = sid_ref[0]                        # [B] int32, this lane
            rhs_t = _build_rhs_t(planes_ref[0], blk, n_hist)
            seg_iota = jax.lax.broadcasted_iota(jnp.int32, (blk, SW1), 1)
            onehot = (seg_iota == s[:, None]).astype(jnp.bfloat16)
            out_ref[0] += jax.lax.dot_general(
                rhs_t, onehot, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        acc = pl.pallas_call(
            kernel,
            grid=(L, W // blk),
            in_specs=[
                pl.BlockSpec((1, blk), lambda l, i: (l, i)),
                pl.BlockSpec((1, N_PLANES, blk), lambda l, i: (l, 0, i)),
            ],
            out_specs=pl.BlockSpec((1, ROWS, SW1), lambda l, i: (l, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((L, ROWS, SW1), jnp.float32),
            compiler_params=_compiler_params(
                dimension_semantics=("arbitrary", "arbitrary")),
            interpret=interpret,
        )(sid, planes)
        return _recombine_moments(acc, n_segments)

    return run


def stage_sorted_planes(sid, planes, n_segments, k: int = 128,
                        block: int = 4096):
    """Host-side re-staging for the sorted-window kernel: sort spans by
    segment id, bucket them into aligned windows of ``k`` segments
    (window w owns segments [w*k, (w+1)*k)), and pad each window's span run
    to a ``block`` multiple so every kernel block touches exactly one
    window.

    Returns ``(sid_local[T], planes[6, T], wids[T // block])`` where
    ``sid_local = sid - wid*k`` ∈ [0, k) and padding rows carry
    ``sid_local = 0`` with all-zero planes (they contribute nothing to any
    output plane — including the count — because every aggregated value is
    a plane-weighted sum).  One-time cost, O(N log N) on the host: replay
    measurement loops never re-stage.
    """
    sid = np.asarray(sid, np.int32)
    planes = np.asarray(planes, np.float32)
    n = sid.shape[0]
    nw = (n_segments + 1 + k - 1) // k      # + dead lane
    order = np.argsort(sid, kind="stable")
    sid_s = sid[order]
    wid_s = sid_s // k
    counts = np.bincount(wid_s, minlength=nw)
    padded = -(-counts // block) * block    # per-window ceil to block
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    pad_starts = np.concatenate([[0], np.cumsum(padded)[:-1]])
    total = int(padded.sum())
    dst = (pad_starts[wid_s] + (np.arange(n) - starts[wid_s])).astype(np.int64)
    sid_local = np.zeros(total, np.int32)
    sid_local[dst] = sid_s - wid_s * k
    planes_out = np.zeros((planes.shape[0], total), np.float32)
    planes_out[:, dst] = planes[:, order]
    wids = np.repeat(np.arange(nw, dtype=np.int32), padded // block)
    return sid_local, planes_out, wids


def make_pallas_replay_sorted_fn(n_segments: int, n_hist: int = 16,
                                 k: int = 128, block: int = 4096,
                                 interpret: bool = False,
                                 inner_repeats: int = 1,
                                 bf16_onehot: bool = False):
    """Sorted-window variant of :func:`make_pallas_replay_fn`:
    ``fn(sid_local[T], planes[6, T], wids[T // block]) -> agg[SW, 6+H]``
    over arrays staged by :func:`stage_sorted_planes`.

    Same fused pipeline (bf16 hi/lo split, in-kernel bucketing, resident
    VMEM accumulator), but the one-hot and the MXU matmul are ``k`` lanes
    wide instead of ``n_segments + 1``: each block's spans all live in one
    aligned k-segment window (host staging guarantees it), so the block's
    [ROWS, k] partial accumulates into a dynamic k-wide slice of the
    accumulator at the window's column offset (``wids`` rides scalar
    prefetch into the index-map/kernel).  For the TT bench corpus
    (SW+1 = 1441, k = 128) that is ~11x less one-hot construction and MXU
    work per span for ~5% padding — aligned windows keep global segment s
    at column s, so the epilogue is unchanged."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nw = (n_segments + 1 + k - 1) // k
    NWK = nw * k
    ROWS = 3 + 6 + n_hist         # exact + (hi, lo) moments + histogram

    def kernel(wids_ref, sid_ref, planes_ref, out_ref):
        @pl.when((pl.program_id(0) == 0) & (pl.program_id(1) == 0))
        def _init():
            out_ref[:] = jnp.zeros_like(out_ref)

        sid = sid_ref[:]                          # [B] int32, window-local
        # [6, B] f32 -> shared bf16 rhs build (same split as the unsorted
        # kernel, so the two paths cannot diverge numerically)
        rhs_t = _build_rhs_t(planes_ref[:], block, n_hist)
        if bf16_onehot:
            # the one-hot construction is the kernel's VPU bottleneck
            # (scripts/bench_kernel_roofline.py ablations); window-local
            # ids are < k <= 128, exactly representable in bf16, and
            # 16-bit lanes compare at 2x packing — the [B, k] compare
            # halves its cycle count where the int32 iota cannot
            seg_iota = jax.lax.broadcasted_iota(jnp.bfloat16, (block, k), 1)
            onehot = (seg_iota == sid.astype(jnp.bfloat16)[:, None]
                      ).astype(jnp.bfloat16)                      # [B, k]
        else:
            seg_iota = jax.lax.broadcasted_iota(jnp.int32, (block, k), 1)
            onehot = (seg_iota == sid[:, None]).astype(jnp.bfloat16)
        partial = jax.lax.dot_general(
            rhs_t, onehot, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # [ROWS, k]
        col = wids_ref[pl.program_id(1)] * k
        out_ref[:, pl.ds(col, k)] += partial

    @jax.jit
    def run(sid_local, planes, wids):
        t = sid_local.shape[0]
        assert planes.shape == (N_PLANES, t), \
            "planes must be feature-major [6, T]"
        assert t % block == 0, f"span count {t} must be a multiple of {block}"
        assert wids.shape == (t // block,)
        if t == 0:
            # zero-block grid would skip the init step and return garbage
            return jnp.zeros((n_segments, N_PLANES + n_hist), jnp.float32)
        grid = (inner_repeats, t // block)
        acc = pl.pallas_call(
            kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=grid,
                in_specs=[
                    pl.BlockSpec((block,), lambda r, i, w: (i,)),
                    pl.BlockSpec((N_PLANES, block), lambda r, i, w: (0, i)),
                ],
                out_specs=pl.BlockSpec((ROWS, NWK), lambda r, i, w: (0, 0)),
            ),
            out_shape=jax.ShapeDtypeStruct((ROWS, NWK), jnp.float32),
            compiler_params=_compiler_params(
                dimension_semantics=("arbitrary", "arbitrary")),
            interpret=interpret,
        )(wids, sid_local, planes)
        return _recombine_moments(acc, n_segments)

    return run


def pallas_replay_numpy(sid, planes, n_segments, n_hist):
    """Oracle for the fused kernel (planes feature-major [6, N])."""
    out = np.zeros((n_segments + 1, N_PLANES + n_hist), np.float32)
    np.add.at(out[:, :N_PLANES], sid, planes.T)
    valid = planes[0]
    bucket = np.clip(planes[4].astype(np.int32), 0, n_hist - 1)
    np.add.at(out, (sid, N_PLANES + bucket), valid)
    return out[:n_segments]


def make_pallas_window_gather_fn(n_services: int, n_windows: int,
                                 n_feats: int, interpret: bool = False):
    """The device state pool's batched-scoring gather as ONE Mosaic
    kernel: ``fn(pool[P, S*W, F], slots[T], cols[T]) -> [T, S, F]`` —
    tenant ``t``'s scored window column ``pool[slots[t]].reshape(
    S, W, F)[:, cols[t]]``, one grid step per tenant, slot/column
    indices scalar-prefetched so the block index maps can address the
    pool rows directly (the same PrefetchScalarGridSpec pattern as the
    sorted-window replay kernel above).

    This is the SCORE half of the serve plane's pallas opt-in
    (``ANOMOD_SERVE_LANE_ENGINE=pallas`` routes the pool's gather here;
    anomod.replay.TenantStatePool).  A pure copy, so the gathered
    columns are bit-identical to the XLA take_along_axis gather on
    every backend — interpret mode keeps it exercised in tier-1 on CPU.

    The FOLD half deliberately stays on XLA's scatter-add: it already
    runs as one fused dispatch, and a Mosaic scatter must revisit
    aliased output blocks when lanes share a slot (dead pad lanes all
    target slot 0), a write-back ordering hazard interpret mode cannot
    pin — fused-gather + XLA-scatter is the whole win without the
    unverifiable half.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    S, W, F = n_services, n_windows, n_feats

    def kernel(slots_ref, cols_ref, pool_ref, out_ref):
        del slots_ref                  # consumed by the index map
        row = pool_ref[0].reshape(S, W, F)
        c = cols_ref[pl.program_id(0)]
        out_ref[0] = jax.lax.dynamic_slice_in_dim(row, c, 1, axis=1)[:, 0]

    @jax.jit
    def run(pool, slots, cols):
        T = slots.shape[0]
        assert pool.shape[1:] == (S * W, F), "pool must be [P, S*W, F]"
        assert cols.shape == (T,)
        return pl.pallas_call(
            kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(T,),
                in_specs=[
                    pl.BlockSpec((1, S * W, F),
                                 lambda t, s, c: (s[t], 0, 0)),
                ],
                out_specs=pl.BlockSpec((1, S, F), lambda t, s, c: (t, 0, 0)),
            ),
            out_shape=jax.ShapeDtypeStruct((T, S, F), jnp.float32),
            compiler_params=_compiler_params(
                dimension_semantics=("arbitrary",)),
            interpret=interpret,
        )(slots, cols, pool)

    return run
