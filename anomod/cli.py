"""Non-interactive CLI — the counterpart of the reference's numbered menus
(automated_multimodal_collection.sh:845-888, run_all_experiments.sh:601-638)
as flags instead of prompts.

Subcommands grow with the framework; `list` and `synth` are available from
day one so every experiment the reference menus offer is addressable by name.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="anomod",
        description="TPU-native anomaly-detection & RCA framework (AnoMod capabilities)")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_list = sub.add_parser("list", help="list experiments + fault labels")
    p_list.add_argument("--testbed", choices=["SN", "TT"], default=None)

    p_synth = sub.add_parser("synth", help="generate a synthetic experiment summary")
    p_synth.add_argument("experiment")
    p_synth.add_argument("--traces", type=int, default=100)

    args = parser.parse_args(argv)

    if args.cmd == "list":
        from anomod import labels
        rows = labels.ALL_LABELS if args.testbed is None else \
            labels.labels_for_testbed(args.testbed)
        for l in rows:
            print(f"{l.testbed}  {l.experiment:40s} {l.anomaly_level:12s} "
                  f"{l.anomaly_type:28s} {l.target_service}")
        return 0

    if args.cmd == "synth":
        from anomod import synth
        exp = synth.generate_experiment(args.experiment, n_traces=args.traces)
        print(json.dumps({
            "experiment": exp.name, "testbed": exp.testbed,
            "spans": exp.spans.n_spans, "traces": exp.spans.n_traces,
            "services": exp.spans.n_services,
            "metric_samples": exp.metrics.n_samples,
            "log_lines": exp.logs.n_lines,
            "api_records": exp.api.n_records,
        }))
        return 0

    return 1


if __name__ == "__main__":
    sys.exit(main())
